"""Run the test suite with per-file process isolation.

One pytest process per tests/test_*.py file.  Motivation (round 4): a
single-process run of all 22 files segfaulted inside a pjit dispatch
around test ~145 (jaxlib CPU client, after hundreds of compiled
executables accumulated in one interpreter) while every file passes in
isolation.  No pytest-xdist/pytest-forked in this image, so this script
is the isolation layer: a crash in one file is contained, attributed,
and reported as that file's failure instead of killing the whole run.

Env handling: tests/conftest.py already forces the 8-device virtual CPU
mesh; this script only scrubs PALLAS_AXON_POOL_IPS so a dead axon TPU
tunnel cannot hang interpreter startup (sitecustomize dials it when the
var is set).

Usage: python scripts/run_suite.py [--timeout-per-file S] [--fast]
         [--artifacts-dir DIR] [pattern]
Exit 0 iff every file's pytest exited 0.  `--artifacts-dir DIR` copies
the run's telemetry/bench artifacts (bench_results/*.json, any
*flight_record*.jsonl the tests left behind) into DIR afterwards,
prints the inventory, runs the obs analyzers (swim_tpu/obs/analyze)
over every captured .jsonl — an error-severity health finding in any
artifact fails the run, so CI gates on protocol health, not just on
assertions — and finally runs the bench trend gate (swim_tpu/obs/trend
--check): a >10% periods/sec drop vs the last-good bench round in the
captured artifacts also fails the run.

`--fast` swaps the default pattern for FAST_FILES, a curated
sub-5-minute smoke tier (host-side protocol logic, harness registries,
roofline math, observability, bridge conformance, profiler contracts,
memory-wall accounting + streaming-study parity) for pre-push
iteration; the full per-file suite stays the CI tier.

The SCENARIO gate (round 8): after the suite, FAST_SCENARIOS runs the
library's sub-minute adversarial fault scenarios through `swim-tpu
scenario run <name> --check` — each must produce a passing verdict
(observatory error gate + the spec's expectations).  On by default
whenever --artifacts-dir is given; force with --scenarios on/off.
Scenario outputs land in <artifacts-dir>/scenarios, deliberately
OUTSIDE the raw top-level telemetry sweep: ungated contrast arms dump
error findings on purpose, and the verdict is their gate-aware judge.

The AUDIT gate (round 14): alongside the scenario gate, `swim-tpu
audit --check` verifies the static compiled-program contracts
(analysis/audit.py — retrace budget, donation coverage, wire payloads,
ICI tally completeness, barrier survival, hot-path hygiene) at
smoke-sized arms; an unwaived contract failure fails the run by name.
"""
from __future__ import annotations

import argparse
import glob
import os
import shutil
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# --fast tier: one representative file per subsystem, chosen for wall
# time (no multi-engine equivalence sweeps, no 64k-node compiles) while
# still crossing every layer — host protocol units, bench harness
# registries, roofline model math, obs analyzers/health/trend, the
# profiler contracts, and the bridge conformance server.  Budget: the
# whole tier (one pytest process per file) must stay under 5 minutes.
FAST_FILES = (
    "tests/test_core_units.py",
    "tests/test_bench_harness.py",
    "tests/test_roofline.py",
    "tests/test_observatory.py",
    "tests/test_profiler.py",
    "tests/test_memwall.py",
    "tests/test_bridge.py",
    "tests/test_graft_entry.py",
    "tests/test_sampling.py",
    "tests/test_audit.py",
    "tests/test_serve.py",
    "tests/test_servetrace.py",
)

# Scenario gate: the library's sub-minute adversarial scenarios, run via
# `swim-tpu scenario run <name> --check` after the suite (one process
# per scenario, same isolation rationale as the per-file loop).  Each
# must produce a PASSING verdict artifact — the observatory error gate
# plus the spec's own expectations.  baseline_config3 (n=100k, 4 arms)
# is library-only, far too heavy for CI.
FAST_SCENARIOS = (
    # (label, scenario name, extra CLI flags).  flap runs twice: once
    # through the serial arm loop and once through the vmapped
    # program-batch path (--batch) — the batched run must produce the
    # same passing verdict (identical artifact bytes modulo nothing:
    # same out_dir), so a batching regression fails CI by name.
    ("rack_outage", "rack_outage", ()),
    ("flap", "flap", ()),
    ("flap@batch", "flap", ("--batch",)),
    ("flap_boundary", "flap_boundary", ()),
    ("gray_10pct", "gray_10pct", ()),
    ("replay_storm", "replay_storm", ()),
    ("lean_fidelity", "lean_fidelity", ()),
)


def run_scenarios(out_dir: str, timeout: float, env: dict) -> list[str]:
    """Run the FAST_SCENARIOS gate; return failure labels ([] = green).

    Verdict artifacts + flight dumps land in `out_dir` so the analyzer
    sweep that follows also replays the scenario telemetry."""
    failures: list[str] = []
    os.makedirs(out_dir, exist_ok=True)
    for label, name, flags in FAST_SCENARIOS:
        t0 = time.time()
        p = subprocess.Popen(
            [sys.executable, "-m", "swim_tpu.cli", "scenario", "run",
             name, "--check", "--out-dir", out_dir, *flags],
            cwd=REPO, env=env, text=True, start_new_session=True,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        try:
            out, _ = p.communicate(timeout=timeout)
            rc = p.returncode
        except subprocess.TimeoutExpired:
            try:
                os.killpg(p.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            out, rc = f"TIMEOUT after {timeout:.0f}s", None
        dt = time.time() - t0
        mark = "PASS" if rc == 0 else "FAIL"
        print(f"{mark} scenario:{label:32s} {dt:7.1f}s", flush=True)
        if rc != 0:
            for line in (out or "").strip().splitlines()[-10:]:
                print(f"  {line}", flush=True)
            failures.append(f"scenario:{label}")
    return failures


def analyze_artifacts(dest: str) -> list[str]:
    """Run the obs analyzers over every .jsonl artifact in `dest`.

    Returns formatted error-severity findings (empty = healthy).  Prints
    one summary line per artifact.  jax-free: swim_tpu.obs.analyze
    imports only json+numpy, so this adds no JAX startup to the runner.
    """
    from swim_tpu.obs import analyze

    errors: list[str] = []
    for path in sorted(glob.glob(os.path.join(dest, "*.jsonl"))):
        name = os.path.basename(path)
        try:
            report = analyze.analyze(path)
        except (ValueError, OSError, KeyError) as e:
            # Unanalyzable telemetry a test left behind is a real defect
            # in the capture pipeline, not noise to skip past.
            errors.append(f"{name}: unanalyzable ({e})")
            print(f"  ANALYZE FAIL {name}: {e}", flush=True)
            continue
        worst = (report.get("health") or {}).get("worst", "ok")
        kind = report.get("kind", "?")
        print(f"  analyzed {name:40s} kind={kind} health={worst}",
              flush=True)
        for f in analyze.error_findings(report):
            errors.append(f"{name}: [{f['severity']}] {f['rule']}: "
                          f"{f['message']}")
    return errors


def collect_artifacts(dest: str) -> list[str]:
    """Copy bench/telemetry artifacts into `dest`; return rel paths."""
    patterns = (os.path.join(REPO, "bench_results", "*.json"),
                os.path.join(REPO, "*flight_record*.jsonl"),
                os.path.join(REPO, "bench_results", "*.jsonl"))
    os.makedirs(dest, exist_ok=True)
    copied: list[str] = []
    for pat in patterns:
        for src in sorted(glob.glob(pat)):
            shutil.copy2(src, os.path.join(dest, os.path.basename(src)))
            copied.append(os.path.relpath(src, REPO))
    return copied


def run_audit_gate(timeout: float, env: dict) -> list[str]:
    """Run `swim-tpu audit --check`; return failure labels ([] = green).

    The static contract gate (analysis/audit.py): retrace budget,
    donation coverage, wire payloads, tally completeness, barrier
    survival, hygiene — deviceless, so it runs anywhere the suite runs.
    Smoke-sized arms (the seeded-violation tests in test_audit.py cover
    the detection logic; this gate proves the COMMITTED TREE satisfies
    every contract end to end).  Report writing is skipped — the
    committed bench_results/audit_report.json stays byte-stable, owned
    by explicit `swim-tpu audit` runs."""
    t0 = time.time()
    p = subprocess.Popen(
        [sys.executable, "-m", "swim_tpu.cli", "audit", "--check",
         "--out", "", "--wire-n", "256", "--retrace-n", "128"],
        cwd=REPO, env=env, text=True, start_new_session=True,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        out, _ = p.communicate(timeout=timeout)
        rc = p.returncode
    except subprocess.TimeoutExpired:
        try:
            os.killpg(p.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        out, rc = f"TIMEOUT after {timeout:.0f}s", None
    dt = time.time() - t0
    mark = "PASS" if rc == 0 else "FAIL"
    print(f"{mark} audit:contracts                     {dt:7.1f}s",
          flush=True)
    if rc != 0:
        for line in (out or "").strip().splitlines()[-10:]:
            print(f"  {line}", flush=True)
        return ["audit:contracts"]
    return []


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("pattern", nargs="?", default="tests/test_*.py")
    ap.add_argument("--timeout-per-file", type=float, default=2400.0)
    ap.add_argument("--artifacts-dir", default=None,
                    help="copy bench_results JSON + telemetry JSONL "
                         "artifacts here after the run")
    ap.add_argument("--fast", action="store_true",
                    help="run the curated <5-minute smoke tier "
                         "(FAST_FILES) instead of the full suite")
    ap.add_argument("--scenarios", choices=("auto", "on", "off"),
                    default="auto",
                    help="run the FAST_SCENARIOS adversarial gate "
                         "(swim-tpu scenario run --check) after the "
                         "suite; 'auto' = on when --artifacts-dir is "
                         "given (the gated CI path)")
    args = ap.parse_args()

    if args.fast and args.pattern == "tests/test_*.py":
        files = [os.path.join(REPO, rel) for rel in FAST_FILES
                 if os.path.exists(os.path.join(REPO, rel))]
    else:
        files = sorted(glob.glob(os.path.join(REPO, args.pattern)))
    if not files:
        print(f"no test files match {args.pattern}", file=sys.stderr)
        return 2

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)

    failures: list[str] = []
    t_all = time.time()
    for path in files:
        rel = os.path.relpath(path, REPO)
        t0 = time.time()
        stdout = stderr = ""
        rc: int | None = None  # None = timeout sentinel (never a real rc)
        # New session so a timeout can kill the whole process GROUP —
        # test-spawned grandchildren (e.g. bridge_client subprocesses)
        # included, not just the direct pytest child.
        p = subprocess.Popen(
            [sys.executable, "-m", "pytest", rel, "-q", "--no-header"],
            cwd=REPO, env=env, text=True, start_new_session=True,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        try:
            stdout, stderr = p.communicate(timeout=args.timeout_per_file)
            rc = p.returncode
            tail = (stdout or "").strip().splitlines()
            summary = tail[-1] if tail else "(no output)"
        except subprocess.TimeoutExpired:
            summary = f"TIMEOUT after {args.timeout_per_file:.0f}s"
            try:
                os.killpg(p.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            try:
                stdout, stderr = p.communicate(timeout=30)
            except subprocess.TimeoutExpired:
                # A grandchild re-setsid'd out of the group and holds the
                # pipes; abandon the read rather than wedge the runner.
                p.kill()
                stdout, stderr = "", "(pipes wedged after group kill)"
        dt = time.time() - t0
        if rc == 0:
            print(f"PASS {rel:40s} {dt:7.1f}s  {summary}", flush=True)
        else:
            # Negative rc = killed by signal (e.g. -11 segfault): name it.
            sig = ""
            if rc is not None and rc < 0:
                try:
                    sig = f" ({signal.strsignal(-rc) or 'unknown signal'})"
                except ValueError:
                    sig = " (unknown signal)"
            print(f"FAIL {rel:40s} {dt:7.1f}s  rc={rc}{sig}  {summary}",
                  flush=True)
            for label, text in (("stdout", stdout), ("stderr", stderr)):
                chunk = text.strip().splitlines()[-15:]
                if chunk:
                    print(f"  --- {rel} {label} tail ---", flush=True)
                    for line in chunk:
                        print(f"  {line}", flush=True)
            failures.append(rel)
    print(f"\n{len(files) - len(failures)}/{len(files)} files green "
          f"in {time.time() - t_all:.0f}s"
          + (f"; FAILED: {', '.join(failures)}" if failures else ""))
    if args.scenarios == "on" or (args.scenarios == "auto"
                                  and args.artifacts_dir):
        # Scenario outputs go to a SUBDIRECTORY of the artifacts dir:
        # ungated contrast arms (flap storm, gray vanilla) dump
        # telemetry whose error findings are the scenario's point —
        # the verdict is the gate-aware judge for those, so they must
        # stay out of analyze_artifacts' raw top-level *.jsonl sweep.
        scen_dir = os.path.join(
            args.artifacts_dir or os.path.join(REPO, "suite_scenarios"),
            "scenarios")
        failures += run_scenarios(scen_dir, args.timeout_per_file, env)
        failures += run_audit_gate(args.timeout_per_file, env)
    if args.artifacts_dir:
        copied = collect_artifacts(args.artifacts_dir)
        print(f"artifacts -> {args.artifacts_dir} ({len(copied)}):")
        for rel in copied:
            print(f"  {rel}")
        errors = analyze_artifacts(args.artifacts_dir)
        if errors:
            print(f"ERROR-severity health findings in {len(errors)} "
                  "artifact(s):", file=sys.stderr)
            for line in errors:
                print(f"  {line}", file=sys.stderr)
            return 1
        # Bench trend gate (jax-free): a >10% periods/sec drop vs the
        # last-good bench round is a regression the assertions can't
        # see — fail the gated run, same as an error-severity finding.
        from swim_tpu.obs import trend

        checks = trend.check(trend.series(trend.collect(REPO)))
        for c in checks:
            print(f"  trend [{'ok' if c['ok'] else 'FAIL'}] "
                  f"{c['tier']}@{c['nodes']}/{c['platform']}: "
                  f"r{c['latest_round']} {c['latest_pps']} vs last-good "
                  f"r{c['last_good_round']} {c['last_good_pps']} "
                  f"(drop {c['drop_pct']}%)", flush=True)
        if any(not c["ok"] for c in checks):
            print("bench trend gate FAILED (>10% drop vs last-good)",
                  file=sys.stderr)
            return 1
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
