"""Render the SWIM-paper fidelity figures into docs/figures/.

Two artifacts (VERDICT r1 item 5 asked for committed plots, not only the
CI-enforced bounds in tests/test_fidelity.py):

  1. detection_cdf.png — empirical first-detection CDF (rumor engine,
     uniform probing, zero loss) against the analytic Geometric(p) law
     with p = 1 - (1 - 1/(N-1))^L; the paper's e/(e-1) expectation.
  2. fp_suppression.png — false-DEAD view-periods vs loss for vanilla
     SWIM and Lifeguard at N=512: zero FPs in the subcritical regime,
     the dissemination-capacity transition near 10% loss, and
     Lifeguard's reduction beyond it (docs/RESULTS.md section 3).

Chart style follows the dataviz reference palette (categorical slots 1-2,
thin marks, recessive grid, text in ink tokens, legend for two series).

Usage: python scripts/make_figures.py   (CPU, a few minutes; bitwise-
deterministic seeds, so the committed PNGs are reproducible)
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import matplotlib
matplotlib.use("Agg")
import matplotlib.pyplot as plt
import numpy as np

SURFACE = "#fcfcfb"
INK = "#0b0b0b"
INK2 = "#52514e"
GRID = "#e8e7e4"
S1 = "#2a78d6"   # categorical slot 1 (blue)
S2 = "#eb6834"   # categorical slot 2 (orange)

OUT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "docs", "figures")


def style_axes(ax):
    ax.set_facecolor(SURFACE)
    for s in ("top", "right"):
        ax.spines[s].set_visible(False)
    for s in ("left", "bottom"):
        ax.spines[s].set_color(GRID)
    ax.tick_params(colors=INK2, labelsize=9)
    ax.grid(True, color=GRID, linewidth=0.6)
    ax.set_axisbelow(True)


def fig_detection_cdf():
    from tests.test_fidelity import detection_latencies, geometric_cdf

    n, n_crash, crash_at, periods = 2048, 48, 2, 40
    samples = np.concatenate([
        detection_latencies(n, n_crash, crash_at, periods, seed)
        for seed in (0, 1, 2)])
    live = n - n_crash
    p = 1.0 - (1.0 - 1.0 / (n - 1)) ** live
    ks = np.arange(0, int(samples.max()) + 2)
    emp = np.searchsorted(np.sort(samples), ks, side="right") / len(samples)

    fig, ax = plt.subplots(figsize=(6.4, 4.0), dpi=160)
    fig.patch.set_facecolor(SURFACE)
    style_axes(ax)
    ax.step(ks, emp, where="post", color=S1, linewidth=1.8,
            label=f"empirical ({len(samples)} crashes, N={n})")
    ax.step(ks, geometric_cdf(ks, p), where="post", color=S2,
            linewidth=1.8, linestyle="--", label="Geometric(p), analytic")
    mean = samples.mean()
    ax.axvline(mean, color=INK2, linewidth=0.8, linestyle=":")
    ax.annotate(f"mean {mean:.2f} periods\n(analytic {1/p:.2f})",
                xy=(mean, 0.08), xytext=(mean + 0.6, 0.06),
                fontsize=8.5, color=INK2)
    ax.set_xlim(0, min(10, ks.max()))
    ax.set_ylim(0, 1.02)
    ax.set_xlabel("protocol periods until first detection", color=INK)
    ax.set_ylabel("P(T ≤ k)", color=INK)
    ax.set_title("First-detection latency matches the SWIM paper's law",
                 color=INK, fontsize=11, loc="left")
    ax.legend(frameon=False, fontsize=8.5, labelcolor=INK2,
              loc="lower right")
    fig.tight_layout()
    path = os.path.join(OUT, "detection_cdf.png")
    fig.savefig(path, facecolor=SURFACE)
    print("wrote", path, f"(mean {mean:.3f}, analytic {1/p:.3f})")


def fp_viewperiods(loss: float, lifeguard: bool) -> int:
    from tests.test_fidelity import fp_study

    res = fp_study(loss, lifeguard)
    return int(np.asarray(res.series.false_dead_views).sum())


def fig_fp_suppression():
    losses = [0.02, 0.05, 0.08, 0.10, 0.12, 0.15]
    vanilla = [fp_viewperiods(l, False) for l in losses]
    lg = [fp_viewperiods(l, True) for l in losses]

    fig, ax = plt.subplots(figsize=(6.4, 4.0), dpi=160)
    fig.patch.set_facecolor(SURFACE)
    style_axes(ax)
    x = [100 * l for l in losses]
    ax.plot(x, vanilla, color=S1, linewidth=1.8, marker="o",
            markersize=4.5, label="vanilla SWIM")
    ax.plot(x, lg, color=S2, linewidth=1.8, marker="o",
            markersize=4.5, label="Lifeguard (LHA)")
    ax.set_yscale("symlog", linthresh=10)
    ax.set_xlabel("packet loss (%)", color=INK)
    ax.set_ylabel("false-DEAD view-periods (70 periods, N=512)",
                  color=INK)
    ax.set_title("Suspicion suppresses FPs until piggyback capacity "
                 "saturates (8–10% loss)", color=INK, fontsize=11,
                 loc="left")
    ax.legend(frameon=False, fontsize=8.5, labelcolor=INK2,
              loc="upper left")
    fig.tight_layout()
    path = os.path.join(OUT, "fp_suppression.png")
    fig.savefig(path, facecolor=SURFACE)
    print("wrote", path)
    for l, v, g in zip(losses, vanilla, lg):
        print(f"  loss {l:.2f}: vanilla {v}, lifeguard {g}")


def fig_suspicion_tradeoff():
    """λ-sweep trade-off (BASELINE config 4) from the committed 1M-node
    sweep artifact: false-DEAD views vs dead-declaration latency, one
    curve per loss rate, λ annotated per point. Reads the newest
    mults×losses grid JSON in bench_results/ (CPU fallback or TPU
    capture); silently skips if none exists yet."""
    import glob
    import json

    cands = sorted(glob.glob(os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "bench_results",
        "study_suspicion_1m*.json")), key=os.path.getmtime)
    grid = None
    for path in reversed(cands):
        with open(path) as f:
            doc = json.load(f)
        doc = doc.get("result", doc) or {}
        pts = doc.get("points", [])
        if len({p.get("loss") for p in pts}) >= 2:
            grid, src = doc, path
            break
    if grid is None:
        print("no mults x losses grid artifact yet; skipping tradeoff fig")
        return
    fig, ax = plt.subplots(figsize=(6.4, 4.0), dpi=160)
    fig.patch.set_facecolor(SURFACE)
    style_axes(ax)
    palette = (S1, S2, "#3d9970", "#8e6bc1", "#b0672f")
    for i, loss in enumerate(sorted({p["loss"] for p in grid["points"]})):
        color = palette[i % len(palette)]
        pts = [p for p in grid["points"] if p["loss"] == loss]
        pts.sort(key=lambda p: p["suspicion_mult"])
        # x = measured first-SUSPECT latency (dead-view latency saturates
        # at the run horizon in the 1M overload regime, see RESULTS §5).
        # A point with no latency key means NO detection was recorded —
        # that is the WORST latency, not 0; plot only measured points
        # and name the suppressed ones in the legend entry
        meas = [p for p in pts if "suspect_latency_mean" in p]
        never = [p["suspicion_mult"] for p in pts
                 if "suspect_latency_mean" not in p]
        x = [p["suspect_latency_mean"] for p in meas]
        y = [p["false_dead_views_final"] for p in meas]
        label = f"loss {100 * loss:.0f}%"
        if never:
            label += f" (λ={','.join(f'{m:g}' for m in never)}: never)"
        ax.plot(x, y, color=color, linewidth=1.8, marker="o",
                markersize=4.5, label=label)
        for p, xi, yi in zip(meas, x, y):
            ax.annotate(f"λ={p['suspicion_mult']:g}", (xi, yi),
                        textcoords="offset points", xytext=(5, 4),
                        fontsize=7.5, color=INK2)
    ax.set_yscale("symlog", linthresh=10)
    ax.set_xlabel("mean first-suspicion latency (periods)", color=INK)
    ax.set_ylabel(f"false-DEAD views at end (N={grid['n']:,})", color=INK)
    ax.set_title("At 1M nodes the λ trade-off is origination-budget "
                 "dominated, not timeout-dominated", color=INK,
                 fontsize=11, loc="left")
    ax.legend(frameon=False, fontsize=8.5, labelcolor=INK2,
              loc="upper right")
    fig.tight_layout()
    path = os.path.join(OUT, "suspicion_tradeoff.png")
    fig.savefig(path, facecolor=SURFACE)
    print(f"wrote {path} (from {os.path.basename(src)})")


def fig_perf_sequence():
    """Rounds 2–4 optimization sequence: measured protocol-periods/sec
    at 1M nodes on ONE TPU v5 lite chip after each profile-driven step
    (docs/RESULTS.md §1; artifacts: bench_all_r2_cache_artifact.json,
    flagship_tpu_r3.json, last_good_tpu.json, bench_all.json).  Single
    series — magnitude over ordered stages — so: bars, one hue, direct
    value labels, no legend; the dotted line is the fused HBM roofline
    for the final geometry (cold-kernel accounting), the honest
    single-chip ceiling."""
    # The stage values are the HISTORICAL record — each number is tied
    # to a specific commit and preserved in bench_results/; they are
    # deliberately frozen here (a recapture updates the artifacts and
    # future-round tables, not this sequence).
    stages = [
        ("round-2\nbaseline", 2.83),
        ("gathers\n→ rolls", 5.87),
        ("strided-tile\nwalk fixes", 22.8),
        ("+ period-scope\nselection (R5)", 48.2),
        ("+ hierarchical\ntop-k (r3)", 52.2),
        ("+ sort-free\ncompaction", 53.6),
        ("+ Pallas\ncold kernel", 81.0),
        ("+ selb kernel,\nprobes, RNG", 96.6),
    ]
    ceiling = 226.0          # fused roofline, cold-kernel accounting @1M
    fig, ax = plt.subplots(figsize=(7.6, 3.9), dpi=160)
    fig.patch.set_facecolor(SURFACE)
    style_axes(ax)
    xs = np.arange(len(stages))
    vals = [v for _, v in stages]
    ax.bar(xs, vals, width=0.62, color=S1, zorder=3)
    for x, v in zip(xs, vals):
        ax.annotate(f"{v:g}", (x, v), textcoords="offset points",
                    xytext=(0, 3), ha="center", fontsize=9, color=INK2)
    ax.axhline(ceiling, color=INK2, linewidth=0.9, linestyle=":")
    ax.annotate("fused HBM roofline (period-scope geometry): "
                f"{ceiling:g} p/s", (0.0, ceiling),
                textcoords="offset points", xytext=(2, 4), ha="left",
                fontsize=8.5, color=INK2)
    ax.set_xticks(xs, [s for s, _ in stages], fontsize=7.8)
    ax.set_ylim(0, ceiling * 1.12)
    ax.set_ylabel("protocol-periods/sec @ 1M nodes", color=INK)
    ax.set_title("Ring engine, one TPU v5 lite chip: 34× across rounds 2–4",
                 color=INK, fontsize=11, loc="left")
    fig.tight_layout()
    path = os.path.join(OUT, "perf_sequence.png")
    fig.savefig(path, facecolor=SURFACE)
    print("wrote", path)


if __name__ == "__main__":
    os.makedirs(OUT, exist_ok=True)
    if "--tradeoff-only" in sys.argv:
        fig_suspicion_tradeoff()
    elif "--perf-only" in sys.argv:
        fig_perf_sequence()
    else:
        fig_detection_cdf()
        fig_fp_suppression()
        fig_perf_sequence()
        fig_suspicion_tradeoff()
