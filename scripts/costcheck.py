"""Compile-time cost proxy for the ring step (no TPU required).

Prints, for one jitted ring period at the given N (CPU backend):
  * XLA cost-analysis bytes accessed (the HBM-traffic proxy that drove
    the round-3 strided-tile-walk discovery: 119 -> 9.7 GB/period),
  * optimized-HLO kernel counts (fusion/convert/etc. — a launch-overhead
    proxy: the measured TPU tail at 1M is dominated by many small
    [N]-vector kernels, so fewer kernels is directionally better),
  * wall-clock per period on this host (weak proxy, reported for trend).

Usage: python scripts/costcheck.py [N] [--sel-scope period] [--probe rotor]
       [--periods 3] [--unroll 1]
"""
from __future__ import annotations

import argparse
import collections
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("n", type=int, nargs="?", default=262_144)
    ap.add_argument("--sel-scope", default="period",
                    choices=("wave", "period"))
    ap.add_argument("--probe", default="rotor", choices=("rotor", "pull"))
    ap.add_argument("--periods", type=int, default=3)
    ap.add_argument("--unroll", type=int, default=1)
    ap.add_argument("--no-run", action="store_true",
                    help="analysis only (skip the timed execution)")
    args = ap.parse_args()

    from swim_tpu.utils.platform import force_cpu
    force_cpu(1)

    import jax
    import jax.numpy as jnp

    from swim_tpu import SwimConfig
    from swim_tpu.models import ring
    from swim_tpu.sim import faults

    cfg = SwimConfig(n_nodes=args.n, ring_sel_scope=args.sel_scope,
                     ring_probe=args.probe)
    state = ring.init_state(cfg)
    plan = faults.with_random_crashes(
        faults.none(args.n), jax.random.key(1), 0.001, 0, args.periods)
    key = jax.random.key(0)

    def one(st, seed):
        def body(s, _):
            rnd = ring.draw_period_ring(
                jax.random.fold_in(key, seed), s.step, cfg)
            return ring.step(cfg, s, plan, rnd), None
        s, _ = jax.lax.scan(body, st, None, length=args.periods,
                            unroll=args.unroll)
        return s

    t0 = time.perf_counter()
    lowered = jax.jit(one).lower(state, jnp.int32(0))
    compiled = lowered.compile()
    t_compile = time.perf_counter() - t0

    ca = compiled.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0]
    bytes_total = float(ca.get("bytes accessed", 0.0))
    per_period = bytes_total / args.periods
    print(f"N={args.n} scope={args.sel_scope} probe={args.probe} "
          f"periods={args.periods} unroll={args.unroll}")
    print(f"compile: {t_compile:.1f}s")
    print(f"cost-analysis bytes: {bytes_total/1e9:.3f} GB total, "
          f"{per_period/1e9:.3f} GB/period")
    # flops for completeness (the step is bandwidth-bound; flops tiny)
    print(f"cost-analysis flops: {float(ca.get('flops', 0.0))/1e9:.3f} G")

    hlo = compiled.as_text()
    kinds = collections.Counter()
    for m in re.finditer(r"^\s*(?:ROOT\s+)?%?[\w.-]+\s*=\s*[\w\[\]{},<> ]*?"
                         r"\b(fusion|custom-call|while|sort|scatter|gather|"
                         r"reduce|convolution|dot)\b", hlo, re.M):
        kinds[m.group(1)] += 1
    print("optimized-HLO op counts:", dict(kinds))

    if not args.no_run:
        out = compiled(state, jnp.int32(0))
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        out = compiled(state, jnp.int32(1))
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        print(f"cpu wall: {dt/args.periods*1e3:.1f} ms/period "
              f"({args.periods/dt:.2f} periods/sec)")
        assert int(out.step) == args.periods
    return 0


if __name__ == "__main__":
    sys.exit(main())
