"""Profile one ring-engine period on the current backend.

Usage: python scripts/profile_ring.py [N] [--periods P] [--trace DIR]
                                      [--probe rotor|pull] [--top K]
                                      [--sel-scope wave|period]

Times a jitted multi-period run, then (with --trace) writes a
jax.profiler trace and prints the top-K XLA ops by self time parsed
straight out of the .trace.json.gz — no TensorBoard needed (the parser
lives in swim_tpu.obs.prof.top_ops_from_trace, shared with `swim-tpu
profile`, which adds phase-level attribution on top of this script's
whole-step view).
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

args = sys.argv[1:]


def opt(name, default=None):
    if name in args:
        i = args.index(name)
        v = args[i + 1]
        del args[i:i + 2]
        return v
    return default


trace_dir = opt("--trace")
periods = int(opt("--periods", "5"))
probe = opt("--probe", "rotor")
sel_scope = opt("--sel-scope", "wave")
top_k = int(opt("--top", "25"))
n = int(args[0]) if args else 1_000_000

from swim_tpu import SwimConfig
from swim_tpu.models import ring
from swim_tpu.sim import faults

cfg = SwimConfig(n_nodes=n, ring_probe=probe, ring_sel_scope=sel_scope)
plan = faults.with_random_crashes(
    faults.none(n), jax.random.key(1), 0.001, 0, periods)
state = ring.init_state(cfg)
key = jax.random.key(0)

run = jax.jit(lambda st: ring.run(cfg, st, plan, key, periods))
t0 = time.perf_counter()
compiled = run.lower(state).compile()
print(f"compile: {time.perf_counter() - t0:.2f}s "
      f"(platform={jax.devices()[0].platform})")
out = jax.block_until_ready(compiled(state))
t0 = time.perf_counter()
out = jax.block_until_ready(compiled(state))
dt = time.perf_counter() - t0
print(f"{periods} periods: {dt:.3f}s -> {dt / periods * 1e3:.1f} ms/period, "
      f"{periods / dt:.2f} periods/sec @ N={n} probe={probe}")

# roofline cross-check: the analytic traffic model vs XLA's own
# bytes-accessed estimate for the whole compiled run (when exposed)
from swim_tpu.utils import roofline as rl

tr_model = rl.ring_traffic(cfg)
xla_bytes = rl.hlo_bytes_accessed(compiled)
print(f"roofline model: {tr_model['fused'] / 1e9:.2f}-"
      f"{tr_model['unfused'] / 1e9:.2f} GB/period"
      + (f"; XLA cost-analysis: {xla_bytes / periods / 1e9:.2f} GB/period"
         if xla_bytes else "; XLA cost-analysis: n/a on this backend"))

if not trace_dir:
    sys.exit(0)

with jax.profiler.trace(trace_dir):
    jax.block_until_ready(run(state))

# ---- parse the trace: top ops by device self-time -------------------------
from swim_tpu.obs.prof import top_ops_from_trace

try:
    top = top_ops_from_trace(trace_dir, top_k=top_k)
except FileNotFoundError as e:
    sys.exit(str(e))

print(f"\ntrace: {top['trace']}")
print(f"device events total: {top['total_us'] / 1e6:.3f}s "
      f"(over {periods} profiled periods)")
print(f"{'self us':>12} {'calls':>7}  {'phase':<12} op")
for op in top["ops"]:
    print(f"{op['self_us']:12.0f} {op['calls']:7d}  "
          f"{(op['phase_guess'] or '-'):<12} {op['op'][:96]}")
