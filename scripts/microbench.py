"""Microbenchmarks for the candidate ring-engine primitives on TPU.

Measures the per-op cost of the memory patterns the fast engine would use,
so the design is chosen from data, not guesses:

  1. row-gather of packed window words by a permutation (wave delivery)
  2. column take + column scatter of a few u32 words (window access)
  3. elementwise .at[dst, sel].max boolean scatter (the CURRENT engine's
     wave delivery — suspected dominant cost)
  4. feistel permutation evaluation (compute-only target selection)
  5. per-period uniform generation (loss draws)
  6. full packed-knows popcount reduction (knower counts / retirement)
  7. two-level per-subject view gather (opinion_of replacement)

Usage: python scripts/microbench.py [N]
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

N = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
RW = 64          # packed words per node (R = 2048 rumors)
WW = 3           # window words
K = 3
REPS = 20


def timeit(name, fn, *args):
    fn_j = jax.jit(fn)
    out = jax.block_until_ready(fn_j(*args))
    t0 = time.perf_counter()
    for _ in range(REPS):
        out = jax.block_until_ready(fn_j(*args))
    dt = (time.perf_counter() - t0) / REPS
    print(f"{name:55s} {dt * 1e3:8.3f} ms", flush=True)
    return dt


def main():
    key = jax.random.key(0)
    print(f"N={N}, RW={RW} words ({RW * 32} rumors), platform="
          f"{jax.devices()[0].platform}")

    knows = jax.random.randint(key, (N, RW), 0, 2**31).astype(jnp.uint32)
    win = knows[:, :WW]
    perm = jax.random.permutation(key, N).astype(jnp.int32)
    dst = jax.random.randint(key, (N,), 0, N).astype(jnp.int32)
    sel = jax.random.randint(key, (N, 6), 0, 64).astype(jnp.int32)
    upd = jnp.ones((N, 6), jnp.bool_)
    kbool = jnp.zeros((N, 64), jnp.bool_)
    widx = jnp.asarray([17, 18, 19], jnp.int32)
    subj_slots = jax.random.randint(key, (N, 4), 0, RW * 32).astype(jnp.int32)

    # 1. wave delivery as row gather by permutation + OR
    timeit("row-gather win[perm] | win  (u32[N,3])",
           lambda w, p: w[p] | w, win, perm)
    # 1b. row gather with RANDOM (non-perm) indices
    timeit("row-gather win[dst] | win   (u32[N,3])",
           lambda w, d: w[d] | w, win, dst)
    # 2. column take + column scatter
    timeit("col-take knows[:, widx]      (u32[N,3] of [N,64])",
           lambda kn, w: jnp.take(kn, w, axis=1), knows, widx)
    timeit("col-scatter knows.at[:, widx].set",
           lambda kn, w, v: kn.at[:, w].set(v), knows, widx, win)
    timeit("col-dynslice + dynupdate     (u32[N,3] @ word 17)",
           lambda kn, v: jax.lax.dynamic_update_slice(
               kn, v | jax.lax.dynamic_slice(kn, (0, 17), (N, 3)),
               (0, 17)), knows, win)
    # 4. feistel eval
    from swim_tpu.ops import sampling
    ids = jnp.arange(N, dtype=jnp.uint32)
    timeit("feistel perm eval            (u32[N])",
           lambda i: sampling.feistel(i, N, jnp.uint32(123),
                                      jnp.uint32(456)), ids)
    # 5. uniforms
    timeit("uniform [N, 14] f32",
           lambda k: jax.random.uniform(k, (N, 14)), key)
    timeit("random_bits [N, 4] u32",
           lambda k: jax.random.bits(k, (N, 4), jnp.uint32), key)
    # 6. popcount reduce
    timeit("popcount-sum over knows      (u32[N,64] -> [64])",
           lambda kn: jax.lax.population_count(kn).sum(axis=0), knows)
    # per-rumor knower count (unpack reduce)
    def knower_counts(kn):
        bits = jnp.right_shift(kn[:, :, None],
                               jnp.arange(32, dtype=jnp.uint32)) & 1
        return bits.sum(axis=0).reshape(-1)
    timeit("per-rumor knower counts      ([N,64]->[2048])",
           knower_counts, knows)
    # 7. two-level view gather: word = slot>>5, bit = slot&31
    def view_gather(kn, ss):
        w = ss >> 5
        b = ss & 31
        words = jnp.take_along_axis(kn, w, axis=1)
        return (jnp.right_shift(words, b.astype(jnp.uint32)) & 1) > 0
    timeit("view gather knows[i,slot[i,c]] ([N,4])",
           view_gather, knows, subj_slots)
    # 8. full-array elementwise pass for reference
    timeit("elementwise pass knows|1     (u32[N,64])",
           lambda kn: kn | jnp.uint32(1), knows)
    timeit("elementwise pass win|1       (u32[N,3])",
           lambda w: w | jnp.uint32(1), win)
    # LAST (suspected pathological): the current engine's delivery scatter
    timeit("bool scatter .at[dst,sel].max  ([N,6] into [N,64])",
           lambda kb, d, s, u: kb.at[d[:, None], s].max(u),
           kbool, dst, sel, upd)


if __name__ == "__main__":
    main()
