"""Demonstrate the SWIM e/(e-1) first-detection law at 131,072 nodes.

VERDICT r2 "Missing #6": the flagship sharded engine is rotor-only (its
scatter-free design is the point — arbitrary-row gathers would
reintroduce the all-to-all it exists to avoid), so the paper's
geometric first-detection law is reproduced on the SINGLE-PROGRAM pull
engine at the largest N one chip comfortably fits.  This script runs
that demonstration (pull-mode ring engine, burst crash, zero loss),
KS-tests the latency distribution against Geometric(p) with
p = 1 - (1 - 1/(N-1))^live, and writes the artifact JSON.

Usage: python scripts/pull_law_131k.py [N] [--crashes C] [--periods P]
       [--seeds S] [--out PATH]
"""
from __future__ import annotations

import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

from swim_tpu import SwimConfig
from swim_tpu.models import ring
from swim_tpu.sim import faults, runner

args = sys.argv[1:]


def opt(name, default):
    if name in args:
        i = args.index(name)
        v = args[i + 1]
        del args[i:i + 2]
        return v
    return default


# defaults reproduce bench_results/pull_law_131k.json exactly (a burst
# must stay under the OB=64 origination budget — see the guard below)
n_crash = int(opt("--crashes", "48"))
periods = int(opt("--periods", "30"))
n_seeds = int(opt("--seeds", "5"))
out_path = opt("--out", os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "bench_results", "pull_law_131k.json"))
n = int(args[0]) if args else 131_072
crash_at = 2

cfg = SwimConfig(n_nodes=n, ring_probe="pull")
ob = 32 * cfg.ring_orig_words
if n_crash > ob - 8:
    sys.exit(f"--crashes {n_crash} would saturate the per-period "
             f"origination budget (OB={ob}): budget-dropped suspicions "
             f"record late and bias the latency law — use fewer "
             f"simultaneous crashes and more --seeds")
victims = np.linspace(0, n - 1, n_crash).astype(np.int32)
lats = []
t0 = time.perf_counter()
for seed in range(n_seeds):
    plan = faults.with_crashes(faults.none(n), victims, crash_at)
    res = runner.run_study_ring(cfg, ring.init_state(cfg), plan,
                                jax.random.key(seed), periods)
    first = np.asarray(res.track.first_suspect)[victims]
    detected = first != int(runner.NEVER)
    lat = first[detected] - crash_at + 1
    lats.append(lat)
    print(f"seed {seed}: {detected.sum()}/{n_crash} detected, "
          f"mean latency {lat.mean():.3f}", flush=True)
lats = np.concatenate(lats)
elapsed = time.perf_counter() - t0

live = n - n_crash
p = 1.0 - (1.0 - 1.0 / (n - 1)) ** live
expect = 1.0 / p

# discrete-support KS against Geometric(p)
hi = int(lats.max())
ks_k = np.arange(0, hi + 1)
emp = np.searchsorted(np.sort(lats), ks_k, side="right") / len(lats)
geo = 1.0 - (1.0 - p) ** ks_k
d = float(np.abs(emp - geo).max())
crit = 1.628 / math.sqrt(len(lats))            # alpha = 0.01

result = {
    "study": "pull_detection_law", "n": n, "crashes_per_seed": n_crash,
    "seeds": n_seeds, "periods": periods, "engine": "ring",
    "ring_probe": "pull", "platform": jax.devices()[0].platform,
    "samples": int(len(lats)),
    "latency_mean": float(lats.mean()),
    "expected_mean": expect,
    "e_over_e_minus_1": math.e / (math.e - 1.0),
    "ks_distance": d, "ks_critical_alpha01": crit,
    "ks_pass": d < crit,
    "wall_seconds": round(elapsed, 1),
}
os.makedirs(os.path.dirname(out_path), exist_ok=True)
with open(out_path, "w") as f:
    json.dump(result, f, indent=1)
print(json.dumps(result))
