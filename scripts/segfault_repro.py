"""Minimal standalone repro for the jaxlib-CPU many-compiles segfault.

Why this file exists (VERDICT r6 #8): a single pytest process running
all of tests/ segfaults inside a pjit dispatch around test ~145 — after
hundreds of distinct compiled executables have accumulated in one
interpreter — while every test file passes in isolation.  That crash is
the entire reason scripts/run_suite.py runs one pytest process per
file.  This script is the smallest self-contained program that walks
the same cliff, so the failure can be demonstrated, bisected against
jaxlib versions, and reported upstream without dragging the test suite
along.

Mechanism: compile and dispatch MANY DISTINCT jitted programs (each
iteration pads a different static shape, so nothing is served from
cache) in one process.  Each program is trivial; the crash is a
function of accumulated executables, not of any one program's size.

Usage:
    JAX_PLATFORMS=cpu python scripts/segfault_repro.py [N] [--verbose]

N defaults to 600 distinct compiles (comfortably past the observed
~145-test cliff; each test file compiles several programs).  Exit 0
with "survived" means this jaxlib build took N compiles without
crashing — raise N before concluding the bug is gone.  A segfault
(rc -11 from the shell) is the repro.  Progress prints every 25
compiles so the crash point is attributable.
"""
from __future__ import annotations

import sys

import jax
import jax.numpy as jnp


def distinct_program(i: int):
    """Return a freshly-jitted program no previous iteration compiled.

    The static pad width makes every signature unique, so XLA compiles
    and retains a new executable each call — the accumulation pattern
    that precedes the crash.  The body mixes the ops the suite's
    engines lean on (reduction, gather, where) to stay representative.
    """
    pad = i % 97 + 1

    @jax.jit
    def prog(x):
        y = jnp.pad(x, (0, pad))
        idx = jnp.argsort(y)[: x.shape[0]]
        return jnp.where(y[idx] > 0, y[idx], -y[idx]).sum()

    return prog


def main() -> int:
    n = 600
    verbose = "--verbose" in sys.argv[1:]
    for a in sys.argv[1:]:
        if a.isdigit():
            n = int(a)
    print(f"jax {jax.__version__} on {jax.devices()[0].platform}; "
          f"compiling {n} distinct programs in one process", flush=True)
    x = jnp.arange(1024, dtype=jnp.float32)
    for i in range(n):
        out = float(distinct_program(i)(x))
        if verbose or i % 25 == 0:
            print(f"  compile {i:4d} ok (out={out:.0f})", flush=True)
    print(f"survived {n} distinct compiles — no segfault on this build",
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
