"""Wait for the axon TPU tunnel to recover, then capture the round's
TPU artifacts: full bench (all tiers) and the 1M-node studies.

Results land in bench_results/ as JSON; each capture is atomic and the
script exits after one successful full capture (or after --max-hours).

Usage: python scripts/tpu_watch.py [--max-hours H]
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "bench_results")

# When the axon tunnel is unhealthy, /root/.axon_site/sitecustomize.py hangs
# EVERY python interpreter at startup (its register() dials the tunnel,
# gated on PALLAS_AXON_POOL_IPS).  To keep the watcher itself immune, launch
# it as:
#   AXON_POOL_IPS_BACKUP="$PALLAS_AXON_POOL_IPS" \
#   env -u PALLAS_AXON_POOL_IPS python scripts/tpu_watch.py
# The watcher then restores the variable for its CHILDREN only, so probe and
# capture subprocesses still see the TPU (and a hung child is just a timeout).
CHILD_ENV = dict(os.environ)
_backup = os.environ.get("AXON_POOL_IPS_BACKUP")
if _backup and not CHILD_ENV.get("PALLAS_AXON_POOL_IPS"):
    CHILD_ENV["PALLAS_AXON_POOL_IPS"] = _backup


def probe(timeout: float = 120.0) -> bool:
    code = "import jax; d=jax.devices(); print(d[0].platform, len(d))"
    try:
        r = subprocess.run([sys.executable, "-c", code], timeout=timeout,
                           capture_output=True, text=True, env=CHILD_ENV)
        # The axon plugin may report its platform as "tpu" or "axon"; either
        # means the tunnel answered and real hardware is reachable.
        return r.returncode == 0 and any(
            p in r.stdout for p in ("tpu", "axon"))
    except subprocess.SubprocessError:
        return False


def _attach_analysis(payload) -> dict | None:
    """Best-effort obs analyzer summary for a capture's flight record.

    A detection-study payload that dumped telemetry carries the dump
    path under "flight_record"; replay it through swim_tpu.obs.analyze
    (jax-free, so cheap in the watcher) and return a compact summary so
    the captured artifact is self-describing about protocol health.
    Never fails the capture: the analysis rides along or it doesn't.
    """
    if not isinstance(payload, dict):
        return None
    path = payload.get("flight_record")
    if not isinstance(path, str):
        return None
    if not os.path.isabs(path):
        path = os.path.join(REPO, path)
    try:
        from swim_tpu.obs import analyze

        report = analyze.analyze(path)
        return {
            "health": report.get("health"),
            "detection": report.get("detection"),
            "detection_law": report.get("detection_law"),
            "dissemination": report.get("dissemination"),
            "piggyback": report.get("piggyback"),
        }
    except Exception as e:  # noqa: BLE001 — attachment is best-effort
        return {"error": f"{type(e).__name__}: {e}"}


def run_save(name: str, cmd: list[str], timeout: float,
             check=None) -> bool | None:
    print(f"[tpu_watch] running {name}: {' '.join(cmd)}", flush=True)
    try:
        r = subprocess.run(cmd, timeout=timeout, capture_output=True,
                           text=True, cwd=REPO, env=CHILD_ENV)
    except subprocess.SubprocessError as e:
        print(f"[tpu_watch] {name} failed: {e}", flush=True)
        return False
    os.makedirs(OUT, exist_ok=True)
    payload = None
    for line in reversed((r.stdout or "").strip().splitlines()):
        try:
            payload = json.loads(line)
            break
        except json.JSONDecodeError:
            continue
    final = os.path.join(OUT, f"{name}.json")
    tmp = final + ".tmp"
    record = {"cmd": cmd, "rc": r.returncode, "result": payload,
              "stdout_tail": (r.stdout or "")[-6000:],
              "stderr_tail": (r.stderr or "")[-2000:],
              "captured_at": time.strftime("%Y-%m-%d %H:%M:%S")}
    analysis = _attach_analysis(payload)
    if analysis is not None:
        record["analysis"] = analysis
    with open(tmp, "w") as f:
        json.dump(record, f, indent=1)
    os.replace(tmp, final)
    ok = r.returncode == 0 and payload is not None
    if ok and check is not None and not check(payload):
        # e.g. bench.py ALWAYS exits 0 with a JSON line — a CPU-fallback
        # or all-tiers-failed run must not be recorded as a successful
        # TPU capture.  Split the failure by WHAT the payload shows:
        # a CPU-fallback payload is definitionally a tunnel flap
        # (bench's own probe timed out) → None = retry at the next
        # recovery, uncounted; a TPU-platform payload that still fails
        # its check (value 0, bad arms) is a deterministic failure →
        # False, which main() marks done for best-effort captures.
        flap = _is_cpu_fallback(payload)
        print(f"[tpu_watch] {name}: rc={r.returncode} parsed=yes "
              f"ok={'retry (cpu fallback)' if flap else 'bad payload'}",
              flush=True)
        return None if flap else False
    print(f"[tpu_watch] {name}: rc={r.returncode} "
          f"parsed={'yes' if payload else 'no'} ok={ok}", flush=True)
    return ok


def _is_cpu_fallback(p: dict) -> bool:
    """The payload shows the run fell back to the CPU mesh — i.e. the
    tunnel flapped between the watcher's probe and the capture's own."""
    if p.get("platform") == "cpu":
        return True
    arms = p.get("arms") or []
    return any(a.get("platform") == "cpu" for a in arms)


def _bench_on_tpu(p: dict) -> bool:
    """bench.py payload really ran on the accelerator and measured."""
    return (p.get("platform") not in (None, "cpu")
            and float(p.get("value", 0) or 0) > 0)


def _ablation_on_tpu(p: dict) -> bool:
    arms = p.get("arms") or []
    return bool(arms) and all(a.get("platform") != "cpu" for a in arms)


CAPTURES: list = [
    # (name, cmd tail, timeout, required-for-completion, payload check)
    ("bench_all", ["bench.py", "--tier", "all"], 3600, True,
     _bench_on_tpu),
    # Throughput-geometry ablation (default / period-scope / lean arms
    # at 1M nodes — the measured evidence for RESULTS.md's
    # geometry-vs-ceiling analysis).
    # (capture name differs from the script's own output file
    # bench_results/geometry_ablation.json so run_save's wrapper does
    # not clobber the full 3-arm artifact)
    ("geometry_ablation_run",
     ["scripts/geometry_ablation.py", "1000000", "50"], 2400, False,
     _ablation_on_tpu),
    # Beyond-1M scale probes: 4M (9.4 GB state+transients headroom) and
    # 10M (5.9 GB state — near the single-chip HBM edge; validated at
    # 4M on the CPU host, 10M is allowed to fail OOM and record it).
    ("scale_4m",
     ["bench.py", "--tier", "ringp", "--nodes", "4000000",
      "--periods", "20", "--tier-timeout", "1500"], 1800, False,
     _bench_on_tpu),
    # 10M may legitimately OOM — record whatever happened, done on any
    # non-CPU attempt (value 0 + a TPU platform is an honest OOM record)
    ("scale_10m",
     ["bench.py", "--tier", "ringp", "--nodes", "10000000",
      "--periods", "10", "--tier-timeout", "1500"], 1800, False,
     lambda p: p.get("platform") not in (None, "cpu")),
    # 16M: the measured single-chip HBM edge after the init-inside-jit
    # harness fix (state ~10.4 GB single-copy); honest-failure rules as
    # the 10M row.
    ("scale_16m",
     ["bench.py", "--tier", "ringp", "--nodes", "16000000",
      "--periods", "8", "--tier-timeout", "1500"], 1800, False,
     lambda p: p.get("platform") not in (None, "cpu")),
    # Multi-chip throughput wire at 1M: compact sel + packed scalar
    # bundles (ring_ici_wire="compact" + ring_scalar_wire="packed") —
    # the real-pod measurement behind the shard-anchor ICI projection's
    # compact+packed arm.
    ("ringshardc_1m",
     ["bench.py", "--tier", "ringshardc", "--nodes", "1000000",
      "--periods", "50", "--tier-timeout", "1500"], 1800, False,
     _bench_on_tpu),
    # Batched scenario fleet on the real chip: the CPU host measures
    # ~1x wall-clock for the vmapped fleet (XLA-CPU gather/scatter does
    # not amortize across the batch axis — bench_results/
    # scenariobatch_fleet.json is the honest stand-in), so the
    # hardware wall-clock ratio is captured here.  Parity gates inside
    # the tier: a run whose batched lanes diverge from serial reports
    # ok=false and value 0 and is not recorded as a capture.
    ("scenariobatch",
     ["bench.py", "--tier", "scenariobatch", "--tier-timeout", "1500"],
     1800, False, _bench_on_tpu),
    # Detection law beyond the XLA-CPU envelope (which aborts at 8M):
    # pull-probe ring engine at 10M on real hardware.  The flight-record
    # dump lets _attach_analysis enrich the capture with the offline
    # analyzer report (detection law, health, piggyback pressure).
    ("study_detection_10m",
     ["-m", "swim_tpu.cli", "study", "detection", "--nodes", "10000000",
      "--engine", "ring", "--periods", "12",
      "--crash-fraction", "0.00001", "--telemetry", "--flight-record",
      "bench_results/detection_10m_flight.jsonl"], 3600, False, None),
    # Behind the one-chip memory wall (PR 13): 16M detection on a single
    # chip via the streaming O(crashes) study driver + donated chunks.
    # The deviceless-AOT verdict says this fits at 98.4% of HBM
    # (bench_results/memwall_report.json); this row is the execution
    # proof.  Checkpoint/resume is ON so a preempted capture resumes
    # instead of restarting (snapshots are per-shard .npz under
    # bench_results/ckpt_16m).
    ("study_detection_16m",
     ["-m", "swim_tpu.cli", "study", "detection", "--nodes", "16000000",
      "--engine", "ring", "--periods", "12",
      "--crash-fraction", "0.00001", "--stream", "on",
      "--checkpoint-dir", "bench_results/ckpt_16m",
      "--checkpoint-every", "4"], 7200, False, None),
    # The 64M flagship: 4 chips of state on the v5e-8 mesh via the
    # sharded ring engine (per-chip ~5.5G by the memwall ringshard row),
    # streaming + per-shard checkpoints — the multi-chip headline run
    # ROADMAP item 2 points at.
    ("flagship_64m",
     ["-m", "swim_tpu.cli", "study", "detection", "--nodes", "64000000",
      "--engine", "ringshard", "--periods", "12",
      "--crash-fraction", "0.00001", "--stream", "on",
      "--checkpoint-dir", "bench_results/ckpt_64m",
      "--checkpoint-every", "4"], 14400, False, None),
    # Contract audit (analysis/audit.py): deviceless verification of
    # the trace/donation/wire/tally/barrier/hygiene invariants at the
    # default shapes.  The audit compiles AOT on the host CPU (the
    # contracts are about program structure, not wall-clock), so the
    # payload check gates on the contract verdict rather than the
    # platform: every check must pass or be formally waived.
    ("audit",
     ["bench.py", "--tier", "audit", "--tier-timeout", "900"], 1200,
     False, lambda p: bool(p.get("ok_parity"))),
    # Serving hub load harness: 1000 concurrent sessions against a
    # 1M-node ring engine, clean arm vs replay/duplication storm.  The
    # payload check gates on ok_parity — the storm arm must leave the
    # engine state bitwise identical and both arms must admit every
    # session; the RTT/admission numbers ride along as serve_* trend
    # keys.  The harness is host-side (UDP loopback + the free-running
    # engine thread), so this row measures the chip's step cadence under
    # mirroring load rather than kernel throughput.
    ("serve_1m",
     ["bench.py", "--tier", "serve", "--tier-timeout", "1500"], 1800,
     False, lambda p: bool(p.get("ok_parity"))),
    # Profile trace: top-op attribution for the optimized ring step.
    ("profile_ring_1m",
     ["scripts/profile_ring.py", "1000000", "--periods", "3",
      "--trace", "/tmp/tr_r3"], 1800, False, None),
    # Phase-level attribution (obs/prof.py): prefix-differenced phase
    # timings + roofline byte accounting at 1M; --out auto persists
    # bench_results/profile_phases.json for the bridge's swim_prof_*
    # gauges, --trace attaches the top-op table for RESULTS.md §10.
    ("profile_phases_1m",
     ["-m", "swim_tpu.cli", "profile", "--nodes", "1000000",
      "--trace", "/tmp/tr_phases", "--json", "--out", "auto"], 1800,
     False, None),
    # Profiler overhead contract on the real chip (the committed
    # artifact is the 65k lean-anchor CPU measurement; this records the
    # accelerator's number alongside it).
    ("profiler_overhead_1m",
     ["bench.py", "--tier", "profiler", "--nodes", "1000000",
      "--periods", "20"], 1800, False, None),
    # Real λ sweep (BASELINE config 4): 5 multipliers × 2 loss rates = 10
    # full 1M-node 100-period runs — budget accordingly.
    ("study_suspicion_1m",
     ["-m", "swim_tpu.cli", "study", "suspicion_sweep", "--nodes",
      "1000000", "--engine", "ring", "--periods", "100",
      "--mults", "1.0", "2.0", "3.0", "4.0", "6.0",
      "--losses", "0.02", "0.05"], 10800, True, None),
    # 4 arms (vanilla/lifeguard × OB 64/256): budget-vs-LHA attribution
    ("study_lifeguard_1m",
     ["-m", "swim_tpu.cli", "study", "lifeguard", "--nodes", "1000000",
      "--engine", "ring", "--periods", "100", "--budget-arms"], 7200,
     True, None),
]


def _write_trend() -> None:
    """Refresh bench_results/trend.json after a capture pass.

    Best-effort and jax-free (swim_tpu.obs.trend reads JSON only): the
    summary folds the fresh captures into the per-tier periods/sec
    trajectories and runs the regression gate, so the watcher's output
    directory always carries an up-to-date trend verdict next to the
    raw capture records.  A broken artifact must not kill the watch
    loop, hence the broad containment.
    """
    try:
        from swim_tpu.obs import trend

        summary = trend.summarize(REPO)
        os.makedirs(OUT, exist_ok=True)
        tmp = os.path.join(OUT, "trend.json.tmp")
        with open(tmp, "w") as f:
            json.dump(summary, f, indent=1)
        os.replace(tmp, os.path.join(OUT, "trend.json"))
        gate = "PASS" if summary.get("ok", True) else "FAIL"
        print(f"[tpu_watch] trend refreshed (gate: {gate})", flush=True)
    except Exception as e:  # noqa: BLE001 — watcher must outlive this
        print(f"[tpu_watch] trend refresh failed: {e}", flush=True)


def main() -> int:
    max_hours = 12.0
    if "--max-hours" in sys.argv:
        max_hours = float(sys.argv[sys.argv.index("--max-hours") + 1])
    deadline = time.time() + max_hours * 3600
    done: set[str] = set()
    while time.time() < deadline:
        if probe():
            print("[tpu_watch] TPU healthy — capturing", flush=True)
            for name, tail, tmo, required, check in CAPTURES:
                if name in done:
                    continue
                res = run_save(name, [sys.executable] + tail, tmo, check)
                if res:
                    done.add(name)
                elif not probe():
                    # Tunnel died mid-pass (ANY capture, required or
                    # not): don't burn hours running the remaining long
                    # captures against a dead backend, and leave the
                    # failed capture un-done so it retries at the next
                    # recovery.
                    print("[tpu_watch] tunnel lost mid-capture; waiting",
                          flush=True)
                    break
                elif res is False and not required:
                    # Deterministic failure of a best-effort capture
                    # (crash, or a TPU-platform payload failing its
                    # check): record it done so it cannot retry-loop
                    # forever ahead of the required studies.  res=None
                    # (CPU-fallback payload = tunnel flap) stays un-done
                    # and retries at the next recovery.
                    done.add(name)
            _write_trend()
            if {c[0] for c in CAPTURES if c[3]} <= done:
                print("[tpu_watch] capture complete", flush=True)
                return 0
            print("[tpu_watch] capture incomplete; will retry the "
                  "missing pieces", flush=True)
        time.sleep(240)
    print("[tpu_watch] gave up (deadline)", flush=True)
    return 1


if __name__ == "__main__":
    sys.exit(main())
