"""Profile one rumor-engine period on the current backend.

Usage: python scripts/profile_rumor.py [N] [R] [--trace DIR]
Prints per-period wall time; with --trace, writes a jax.profiler trace.
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

args = sys.argv[1:]
trace_dir = None
if "--trace" in args:
    i = args.index("--trace")
    if i + 1 >= len(args):
        sys.exit("--trace needs a directory argument")
    trace_dir = args[i + 1]
    del args[i:i + 2]
n = int(args[0]) if len(args) > 0 else 262_144
r = int(args[1]) if len(args) > 1 else 256

from swim_tpu import SwimConfig
from swim_tpu.models import rumor
from swim_tpu.sim import faults

cfg = SwimConfig(n_nodes=n, rumor_capacity=r)
plan = faults.with_random_crashes(
    faults.none(n), jax.random.key(1), 0.001, 0, 10)
state = rumor.init_state(cfg)
key = jax.random.key(0)

step = jax.jit(lambda st: rumor.run(cfg, st, plan, key, 5))
t0 = time.perf_counter()
out = jax.block_until_ready(step(state))
print(f"compile+first: {time.perf_counter() - t0:.2f}s")
t0 = time.perf_counter()
out = jax.block_until_ready(step(state))
dt = time.perf_counter() - t0
print(f"5 periods: {dt:.3f}s -> {dt / 5 * 1e3:.1f} ms/period, "
      f"{5 / dt:.1f} periods/sec @ N={n} R={r}")

if trace_dir:
    with jax.profiler.trace(trace_dir):
        jax.block_until_ready(step(state))
    print("trace written to", trace_dir)
