"""Throughput-geometry ablation at 1M nodes: how far do the protocol
knobs take one chip toward the 10k periods/sec north star?

Each arm is a legitimate SWIM operating point (every knob is a config
field a user sets; nothing here changes engine semantics), measured
with the same defended harness as bench.py (distinct seed per dispatch,
host-fetch barrier, step-advance proof).  Arms:

  default   — the bench flagship geometry (lambda=5, k=3, WW=12,
              RW=128, C=3, wave-scope selection)
  period    — + ring_sel_scope="period" (deviation R5)
  lean      — + lambda=2 (the 1M sweep's own finding: past lambda=2
              the timeout is not the binding constraint at low loss —
              docs/RESULTS.md 5a), retransmit_mult=2, k=1, window 3
              periods, C=2: WW=6, RW=56 words at 1M (geometry() sizes
              the ring from the slowest-resolving timer) — shorter
              gossip window, weaker indirect probing, smaller rumor
              ring (overflow is counted, never silent)

Timing reuses bench.py's defended harness (_time_run: distinct seed
per dispatch, host-fetch barrier, step-advance proof) plus the same
3x-roofline plausibility guard.  The LAST stdout line is the full
summary JSON (so tpu_watch's wrapper artifact is self-contained); the
same summary is written to bench_results/geometry_ablation.json.

Usage: python scripts/geometry_ablation.py [N] [periods]
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

N = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
PERIODS = int(sys.argv[2]) if len(sys.argv) > 2 else 50

ARMS = {
    "default": {},
    "period": dict(ring_sel_scope="period"),
    "lean": dict(ring_sel_scope="period", suspicion_mult=2.0,
                 retransmit_mult=2.0, k_indirect=1,
                 ring_window_periods=3, ring_view_c=2),
}


def measure(name: str, kw: dict) -> dict:
    from bench import _time_run
    from swim_tpu import SwimConfig
    from swim_tpu.models import ring
    from swim_tpu.sim import faults
    from swim_tpu.utils import roofline as rl

    cfg = SwimConfig(n_nodes=N, **kw)
    g = ring.geometry(cfg)
    plan = faults.with_random_crashes(
        faults.none(N), jax.random.key(1), 0.001, 0, PERIODS)
    state = ring.init_state(cfg)
    key = jax.random.key(0)
    run = jax.jit(lambda st, seed: ring.run(
        cfg, st, plan, jax.random.fold_in(key, seed), PERIODS))

    t0 = time.perf_counter()
    out0 = run(state, jnp.int32(99))
    jax.block_until_ready(out0)
    compile_s = time.perf_counter() - t0
    # bench.py's defended harness: distinct seed per dispatch,
    # host-fetch barrier, step-advance execution proof
    pps = _time_run(run, state, warmup=1, periods=PERIODS)
    ceil = rl.ceiling_periods_per_sec(cfg)
    limit = 3.0 * ceil["ceiling_fused"]
    if pps > limit:
        raise RuntimeError(
            f"{name}: measured {pps:.0f} p/s exceeds 3x the roofline "
            f"ceiling ({limit:.0f}) — timing artifact")
    res = {
        "arm": name, "n": N, "periods": PERIODS,
        "periods_per_sec": round(pps, 2),
        "overflow": int(out0.overflow),
        "geometry": {"ww": g.ww, "rw": g.rw, "c": g.c,
                     "k": cfg.k_indirect,
                     "sel_scope": cfg.ring_sel_scope,
                     "suspicion_mult": cfg.suspicion_mult},
        "ceiling_fused_pps": round(ceil["ceiling_fused"], 1),
        "roofline_fraction": round(pps / ceil["ceiling_fused"], 4),
        "compile_s": round(compile_s, 1),
        "platform": jax.devices()[0].platform,
    }
    print(json.dumps(res), flush=True)
    return res


def main():
    out = [measure(name, kw) for name, kw in ARMS.items()]
    summary = {"n": N, "periods": PERIODS, "arms": out}
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench_results",
        "geometry_ablation.json")
    with open(path, "w") as f:
        json.dump(summary, f, indent=1)
    print(f"wrote {path}", file=sys.stderr)
    # LAST stdout line = the full summary, so tpu_watch's last-JSON-line
    # wrapper artifact is self-contained (all three arms, not just lean)
    print(json.dumps(summary), flush=True)


if __name__ == "__main__":
    main()
