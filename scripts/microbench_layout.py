"""Window-layout microbench: node-major [N, WW] vs word-major [WW, N].

The ring engine keeps `win` node-major while `cold` is word-major (the
round-2 transpose that made cold's flush/census passes contiguous).  A
TPU tiles the MINOR dimension into 128 lanes; WW=12 < 128 means every
node-major win pass wastes ~90% of each lane tile.  This script times
the engine's three hot window patterns in both layouts at the 1M-node
default geometry, so the layout decision is made from measured numbers.

Patterns (per models/ring.py):
  select  — the engine's `_select_first_b` (imported, not copied), with
            the eligibility mask pre-applied
  wave    — roll along the node axis + OR-update into win (one wave)
  colsel  — per-row window-column select (`_col_select_multi`, one query)

TUNNEL HAZARDS (docs/RESULTS.md §1b): every rep perturbs one input (so
the axon tunnel's identical-dispatch result cache cannot serve a
repeat) and the timing barrier is a host fetch of an output element
(bare `block_until_ready` returns at enqueue for some executables).
Even so, single-op rows remain dominated by the ~66 ms fixed dispatch
latency — only the relative composite rows are meaningful over the
tunnel; absolute per-op numbers need a local backend.

Usage: python scripts/microbench_layout.py [N] [reps]
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from swim_tpu.models.ring import _select_first_b

N = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
REPS = int(sys.argv[2]) if len(sys.argv) > 2 else 10
WW, B = 12, 6


def timeit(name, fn, *args):
    """Time REPS dispatches; arg 0 is XORed with the rep index so no
    two dispatches are identical (tunnel cache defense), and the
    barrier is a host fetch of one output element (enqueue-return
    defense)."""
    fn_j = jax.jit(lambda salt, *a: fn(a[0] ^ salt, *a[1:]))

    def once(i):
        out = fn_j(jnp.uint32(i), *args)
        leaf = jax.tree.leaves(out)[0]
        # single-ELEMENT fetch: slice on device first, so the barrier
        # transfers 4 bytes, not the whole array
        np.asarray(leaf.ravel()[0])
        return out

    once(0)
    t0 = time.perf_counter()
    for i in range(1, REPS + 1):
        out = once(i)
    dt = (time.perf_counter() - t0) / REPS
    print(f"{name:48s} {dt * 1e3:8.3f} ms", flush=True)
    return out


def select_nm(win, elig):                    # node-major [N, WW]
    # impl="lax" pins the XLA extract loop: this script A/Bs LAYOUTS,
    # and "auto" would silently measure the Pallas selb kernel on TPU
    return _select_first_b(win & elig[None, :], B, impl="lax")


def select_wm(win, elig):                    # word-major [WW, N]
    # word-major twin of ring._select_first_b (the engine has no
    # word-major selector to import; keep in sync with it)
    budget = jnp.full((N,), B, jnp.int32)
    taken = [None] * WW
    for w in range(WW - 1, -1, -1):
        m = win[w] & elig[w]
        acc = jnp.zeros_like(m)
        for _ in range(B):
            low = m & (jnp.uint32(0) - m)
            bitm = jnp.where(budget > 0, low, jnp.uint32(0))
            acc = acc | bitm
            m = m ^ bitm
            budget = budget - (bitm != 0).astype(jnp.int32)
        taken[w] = acc
    return jnp.stack(taken, axis=0)


def wave_nm(win, sel, ok, s):
    return win | jnp.where(ok[:, None], jnp.roll(sel, s, axis=0),
                           jnp.uint32(0))


def wave_wm(win, sel, ok, s):
    return win | jnp.where(ok[None, :], jnp.roll(sel, s, axis=1),
                           jnp.uint32(0))


def colsel_nm(win, wcol):
    out = jnp.zeros((N,), jnp.uint32)
    for w in range(WW):
        out = jnp.where(wcol == w, win[:, w], out)
    return out


def colsel_wm(win, wcol):
    out = jnp.zeros((N,), jnp.uint32)
    for w in range(WW):
        out = jnp.where(wcol == w, win[w], out)
    return out


def main():
    key = jax.random.key(0)
    print(f"N={N}, WW={WW}, B={B}, reps={REPS}, "
          f"platform={jax.devices()[0].platform}")
    win_nm = jax.random.bits(key, (N, WW), jnp.uint32)
    win_wm = jnp.asarray(win_nm.T)
    elig = jax.random.bits(key, (WW,), jnp.uint32)
    ok = jax.random.bernoulli(key, 0.7, (N,))
    wcol = jax.random.randint(key, (N,), 0, WW).astype(jnp.int32)
    s = 12345

    sel_nm = timeit("select node-major [N,WW]", select_nm, win_nm, elig)
    sel_wm = timeit("select word-major [WW,N]", select_wm, win_wm, elig)
    timeit("wave roll+OR node-major", wave_nm, win_nm, sel_nm, ok, s)
    timeit("wave roll+OR word-major", wave_wm, win_wm, sel_wm, ok, s)
    timeit("column-select node-major", colsel_nm, win_nm, wcol)
    timeit("column-select word-major", colsel_wm, win_wm, wcol)
    # 14-wave composite: the full per-period wave traffic in each layout
    def waves14_nm(win, sel):
        for i in range(14):
            win = wave_nm(win, sel, ok, 1000 + i)
        return win

    def waves14_wm(win, sel):
        for i in range(14):
            win = wave_wm(win, sel, ok, 1000 + i)
        return win

    timeit("14-wave composite node-major", waves14_nm, win_nm, sel_nm)
    timeit("14-wave composite word-major", waves14_wm, win_wm, sel_wm)


if __name__ == "__main__":
    main()
