"""Lint: every `self.stats[...]` key in core/node.py must be declared.

The typed registry (swim_tpu/obs/registry.py NODE_COUNTERS) superseded
the flat stats dict; `MetricsRegistry.stats_view()` keeps the old
`self.stats["probes"] += 1` call sites working but raises KeyError on an
undeclared key — at runtime, on whichever code path first touches it.
This script moves that failure to build time: it AST-walks core/node.py,
collects every string literal used to subscript `self.stats`, and exits
non-zero if any is missing from NODE_COUNTERS (or if a subscript key is
not a plain string literal, which the view cannot type).

Run directly (`python scripts/check_metrics_registry.py`) or via the
fast tier-1 test that shells out to it (tests/test_telemetry.py).
"""

from __future__ import annotations

import ast
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

NODE_PY = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "swim_tpu", "core", "node.py")


def stats_keys(path: str = NODE_PY) -> tuple[set[str], list[str]]:
    """(string keys subscripting self.stats, non-literal subscript reprs)."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    keys: set[str] = set()
    dynamic: list[str] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Subscript):
            continue
        v = node.value
        if not (isinstance(v, ast.Attribute) and v.attr == "stats"
                and isinstance(v.value, ast.Name) and v.value.id == "self"):
            continue
        s = node.slice
        if isinstance(s, ast.Constant) and isinstance(s.value, str):
            keys.add(s.value)
        else:
            dynamic.append(f"line {node.lineno}: {ast.unparse(s)}")
    return keys, dynamic


def main() -> int:
    from swim_tpu.obs.registry import NODE_COUNTERS

    keys, dynamic = stats_keys()
    missing = sorted(keys - set(NODE_COUNTERS))
    ok = True
    if missing:
        ok = False
        print(f"UNDECLARED stats keys in core/node.py: {missing} — "
              "declare them in swim_tpu.obs.registry.NODE_COUNTERS "
              "(name -> help text)", file=sys.stderr)
    if dynamic:
        ok = False
        print("non-literal self.stats subscripts (the typed view needs "
              f"string-literal keys): {dynamic}", file=sys.stderr)
    unused = sorted(set(NODE_COUNTERS) - keys)
    if unused:
        # declared-but-never-incremented is informational, not fatal:
        # counters may be bumped outside node.py (tests, future callers)
        print(f"note: declared counters not incremented in node.py: "
              f"{unused}", file=sys.stderr)
    print(f"checked {len(keys)} stats keys against "
          f"{len(NODE_COUNTERS)} declared counters: "
          f"{'OK' if ok else 'FAIL'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
