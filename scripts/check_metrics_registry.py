"""Lint: every `self.stats[...]` key in core/node.py must be declared.

The typed registry (swim_tpu/obs/registry.py NODE_COUNTERS) superseded
the flat stats dict; `MetricsRegistry.stats_view()` keeps the old
`self.stats["probes"] += 1` call sites working but raises KeyError on an
undeclared key — at runtime, on whichever code path first touches it.
This script moves that failure to build time: it AST-walks core/node.py,
collects every string literal used to subscript `self.stats`, and exits
non-zero if any is missing from NODE_COUNTERS (or if a subscript key is
not a plain string literal, which the view cannot type).

Also lints the health-gauge surface: every rule in
swim_tpu/obs/health.py HEALTH_RULES must be a legal Prometheus metric
name suffix with a known severity, and `render_health` must emit exactly
{swim_health_<rule>} ∪ {swim_health_status} — so the gauge names on the
bridge's /metrics never drift from the rule table docs/dashboards key on.

And the profiler-gauge surface: every `swim_prof_*` string literal in
obs/expo.py `render_profile` must be declared in obs/prof.py
PROF_GAUGES and vice versa (AST source scan, mirroring the stats-key
lint — render_profile's own runtime assert only fires when a profile
artifact actually renders, which CI without an artifact never does).

And the scenario-rule surface: the two fault-schedule-aware health
rules the scenario compiler feeds (gray_undetected, flap_false_dead)
must exist in HEALTH_RULES, and every rule a library scenario names —
`allow_rules` waivers, `rule_fired` expectations — must be a declared
rule, so a rule rename can never silently void a waiver or doom an
expectation.

Run directly (`python scripts/check_metrics_registry.py`) or via the
fast tier-1 test that shells out to it (tests/test_telemetry.py).
"""

from __future__ import annotations

import ast
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

NODE_PY = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "swim_tpu", "core", "node.py")


def stats_keys(path: str = NODE_PY) -> tuple[set[str], list[str]]:
    """(string keys subscripting self.stats, non-literal subscript reprs)."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    keys: set[str] = set()
    dynamic: list[str] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Subscript):
            continue
        v = node.value
        if not (isinstance(v, ast.Attribute) and v.attr == "stats"
                and isinstance(v.value, ast.Name) and v.value.id == "self"):
            continue
        s = node.slice
        if isinstance(s, ast.Constant) and isinstance(s.value, str):
            keys.add(s.value)
        else:
            dynamic.append(f"line {node.lineno}: {ast.unparse(s)}")
    return keys, dynamic


def check_health_gauges() -> list[str]:
    """Problems with the swim_health_* gauge surface ([] = clean)."""
    import re

    from swim_tpu.obs.expo import render_health
    from swim_tpu.obs.health import HEALTH_RULES, SEVERITIES

    problems: list[str] = []
    # metric-name charset minus a leading digit; the full name is
    # swim_health_<rule>, so the rule itself must match [a-z0-9_]+
    name_re = re.compile(r"^[a-z][a-z0-9_]*$")
    for rule, (severity, _help) in HEALTH_RULES.items():
        if not name_re.match(rule):
            problems.append(f"rule {rule!r} is not a legal Prometheus "
                            "metric-name suffix")
        if severity not in SEVERITIES:
            problems.append(f"rule {rule!r} has unknown severity "
                            f"{severity!r} (expected one of {SEVERITIES})")
    expected = {f"swim_health_{r}" for r in HEALTH_RULES}
    expected.add("swim_health_status")
    emitted = {line.split("{")[0].split(" ")[0]
               for line in render_health([]).splitlines()
               if line and not line.startswith("#")}
    if emitted != expected:
        problems.append(
            f"render_health emits {sorted(emitted)} but the rule table "
            f"implies {sorted(expected)} — keep HEALTH_RULES and "
            "render_health in lockstep")
    return problems


def check_prof_gauges() -> list[str]:
    """Problems with the swim_prof_* gauge surface ([] = clean).

    Source-level cross-check: the `swim_prof_*` names render_profile
    writes (string literals in obs/expo.py) must be exactly
    prof.PROF_GAUGES, and each must be a legal Prometheus metric name.
    """
    import re

    from swim_tpu.obs.prof import PROF_GAUGES

    expo_py = os.path.join(os.path.dirname(NODE_PY), os.pardir,
                           "obs", "expo.py")
    with open(expo_py) as f:
        tree = ast.parse(f.read(), filename=expo_py)
    emitted: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            for m in re.findall(r"swim_prof_[a-z0-9_]+", node.value):
                emitted.add(m)
        elif isinstance(node, ast.JoinedStr):
            for part in node.values:
                if isinstance(part, ast.Constant) \
                        and isinstance(part.value, str):
                    for m in re.findall(r"swim_prof_[a-z0-9_]+",
                                        part.value):
                        emitted.add(m)
    problems: list[str] = []
    name_re = re.compile(r"^[a-z][a-z0-9_]*$")
    for name in PROF_GAUGES:
        if not name_re.match(name):
            problems.append(f"PROF_GAUGES entry {name!r} is not a legal "
                            "Prometheus metric name")
    if emitted != set(PROF_GAUGES):
        problems.append(
            f"obs/expo.py mentions {sorted(emitted)} but prof.PROF_GAUGES "
            f"declares {sorted(PROF_GAUGES)} — keep render_profile and "
            "the phase table in lockstep")
    return problems


def check_mem_gauges() -> list[str]:
    """Problems with the swim_mem_* gauge surface ([] = clean).

    Two-sided, mirroring the prof/health lints: (a) the literal
    `swim_mem_*` keys in memwall.gauge_values (AST source scan — a key
    typo there would silently publish a zero) must be exactly
    memwall.MEM_GAUGES; (b) render_memwall over a synthetic report must
    emit exactly the MEM_GAUGES series (runtime render, the
    check_health_gauges pattern — CI has no memwall artifact to render
    otherwise).  Every name must be a legal Prometheus metric name.
    """
    import re

    from swim_tpu.obs.expo import render_memwall
    from swim_tpu.obs.memwall import MEM_GAUGES

    problems: list[str] = []
    name_re = re.compile(r"^[a-z][a-z0-9_]*$")
    for name in MEM_GAUGES:
        if not name_re.match(name):
            problems.append(f"MEM_GAUGES entry {name!r} is not a legal "
                            "Prometheus metric name")
    mw_py = os.path.join(os.path.dirname(NODE_PY), os.pardir,
                         "obs", "memwall.py")
    with open(mw_py) as f:
        tree = ast.parse(f.read(), filename=mw_py)
    fn = next((n for n in ast.walk(tree)
               if isinstance(n, ast.FunctionDef)
               and n.name == "gauge_values"), None)
    if fn is None:
        problems.append("obs/memwall.py has no gauge_values()")
    else:
        written = {n.value for n in ast.walk(fn)
                   if isinstance(n, ast.Constant)
                   and isinstance(n.value, str)
                   and n.value.startswith("swim_mem_")}
        if written != set(MEM_GAUGES):
            problems.append(
                f"memwall.gauge_values writes {sorted(written)} but "
                f"MEM_GAUGES declares {sorted(MEM_GAUGES)} — keep the "
                "two in lockstep")
    fake = {"n": 1, "state_bytes": 0, "hbm_budget_bytes": 1}
    emitted = {line.split("{")[0].split(" ")[0]
               for line in render_memwall(fake).splitlines()
               if line and not line.startswith("#")}
    if emitted != set(MEM_GAUGES):
        problems.append(
            f"render_memwall emits {sorted(emitted)} but MEM_GAUGES "
            f"declares {sorted(MEM_GAUGES)} — keep the renderer and the "
            "gauge table in lockstep")
    return problems


def check_audit_gauges() -> list[str]:
    """Problems with the swim_audit_* gauge surface ([] = clean).

    Mirrors check_mem_gauges: (a) the literal `swim_audit_*` keys in
    analysis/audit.py gauge_values (AST source scan) must be exactly
    audit.AUDIT_GAUGES; (b) render_audit over a synthetic report must
    emit exactly the AUDIT_GAUGES series; (c) every name must be a
    legal Prometheus metric name.  Plus the contract-table pairing:
    each CONTRACTS family and each WAIVERS entry must reference a
    declared contract, so a renamed contract can never orphan a waiver.
    """
    import re

    from swim_tpu.analysis.audit import AUDIT_GAUGES, CONTRACTS, WAIVERS
    from swim_tpu.obs.expo import render_audit

    problems: list[str] = []
    name_re = re.compile(r"^[a-z][a-z0-9_]*$")
    for name in AUDIT_GAUGES:
        if not name_re.match(name):
            problems.append(f"AUDIT_GAUGES entry {name!r} is not a legal "
                            "Prometheus metric name")
    audit_py = os.path.join(os.path.dirname(NODE_PY), os.pardir,
                            "analysis", "audit.py")
    with open(audit_py) as f:
        tree = ast.parse(f.read(), filename=audit_py)
    fn = next((n for n in ast.walk(tree)
               if isinstance(n, ast.FunctionDef)
               and n.name == "gauge_values"), None)
    if fn is None:
        problems.append("analysis/audit.py has no gauge_values()")
    else:
        written = {n.value for n in ast.walk(fn)
                   if isinstance(n, ast.Constant)
                   and isinstance(n.value, str)
                   and n.value.startswith("swim_audit_")}
        if written != set(AUDIT_GAUGES):
            problems.append(
                f"audit.gauge_values writes {sorted(written)} but "
                f"AUDIT_GAUGES declares {sorted(AUDIT_GAUGES)} — keep "
                "the two in lockstep")
    fake = {"wire_n": 1, "retrace_n": 1, "platform": "cpu",
            "totals": {"checks_total": 0, "failures": 0, "waived": 0,
                       "retraces_extra": 0,
                       "unattributed_collective_bytes": 0,
                       "undonated_bytes": 0,
                       "barrier_chains_missing": 0}}
    emitted = {line.split("{")[0].split(" ")[0]
               for line in render_audit(fake).splitlines()
               if line and not line.startswith("#")}
    if emitted != set(AUDIT_GAUGES):
        problems.append(
            f"render_audit emits {sorted(emitted)} but AUDIT_GAUGES "
            f"declares {sorted(AUDIT_GAUGES)} — keep the renderer and "
            "the gauge table in lockstep")
    for waiver in WAIVERS:
        if waiver.get("contract") not in CONTRACTS:
            problems.append(
                f"audit waiver names unknown contract "
                f"{waiver.get('contract')!r} — waivers must reference "
                "CONTRACTS entries")
        if not waiver.get("pointer"):
            problems.append(
                f"audit waiver for {waiver.get('contract')!r}/"
                f"{waiver.get('arm')!r} has no tracking pointer — a "
                "waiver is a debt, not a hole")
    return problems


def check_session_gauges() -> list[str]:
    """Problems with the swim_session_* gauge surface ([] = clean).

    Mirrors check_mem_gauges/check_audit_gauges for the serving hub:
    (a) the literal `swim_session_*` keys in serve/hub.py gauge_values
    (AST source scan) must be exactly hub.SESSION_GAUGES; (b)
    render_sessions over a synthetic report — including a per-session
    table, since clock lag renders one labeled series per session —
    must emit exactly the SESSION_GAUGES names; (c) every name must be
    a legal Prometheus metric name.
    """
    import re

    from swim_tpu.obs.expo import render_sessions
    from swim_tpu.serve.hub import SESSION_GAUGES

    problems: list[str] = []
    name_re = re.compile(r"^[a-z][a-z0-9_]*$")
    for name in SESSION_GAUGES:
        if not name_re.match(name):
            problems.append(f"SESSION_GAUGES entry {name!r} is not a "
                            "legal Prometheus metric name")
    hub_py = os.path.join(os.path.dirname(NODE_PY), os.pardir,
                          "serve", "hub.py")
    with open(hub_py) as f:
        tree = ast.parse(f.read(), filename=hub_py)
    fn = next((n for n in ast.walk(tree)
               if isinstance(n, ast.FunctionDef)
               and n.name == "gauge_values"), None)
    if fn is None:
        problems.append("serve/hub.py has no gauge_values()")
    else:
        written = {n.value for n in ast.walk(fn)
                   if isinstance(n, ast.Constant)
                   and isinstance(n.value, str)
                   and n.value.startswith("swim_session_")}
        if written != set(SESSION_GAUGES):
            problems.append(
                f"hub.gauge_values writes {sorted(written)} but "
                f"SESSION_GAUGES declares {sorted(SESSION_GAUGES)} — "
                "keep the two in lockstep")
    fake = {"nodes": 8, "admitted": 2, "evicted": 1, "active": 1,
            "mirror_bytes_per_period": 16,
            "sessions": [{"row": 3, "clock_lag_periods": 0},
                         {"row": 5, "clock_lag_periods": 2}]}
    emitted = {line.split("{")[0].split(" ")[0]
               for line in render_sessions(fake).splitlines()
               if line and not line.startswith("#")}
    if emitted != set(SESSION_GAUGES):
        problems.append(
            f"render_sessions emits {sorted(emitted)} but "
            f"SESSION_GAUGES declares {sorted(SESSION_GAUGES)} — keep "
            "the renderer and the gauge table in lockstep")
    return problems


def check_serve_trace_gauges() -> list[str]:
    """Problems with the swim_serve_* trace gauge surface ([] = clean).

    Mirrors check_session_gauges for obs/servetrace.py: (a) the literal
    `swim_serve_*` keys in servetrace.gauge_values (AST source scan)
    must be exactly SERVE_TRACE_GAUGES; (b) render_serve_trace over a
    synthetic phase summary — including per-phase rows, since the three
    phase gauges render one labeled series per phase — must emit
    exactly the SERVE_TRACE_GAUGES names; (c) every name must be a
    legal Prometheus metric name; (d) the hub's `ext_mirror_overflow`
    warn rule must be declared in HEALTH_RULES so its Findings render
    through the health gauge surface.
    """
    import re

    from swim_tpu.obs.expo import render_serve_trace
    from swim_tpu.obs.health import HEALTH_RULES
    from swim_tpu.obs.servetrace import PHASES, SERVE_TRACE_GAUGES

    problems: list[str] = []
    name_re = re.compile(r"^[a-z][a-z0-9_]*$")
    for name in SERVE_TRACE_GAUGES:
        if not name_re.match(name):
            problems.append(f"SERVE_TRACE_GAUGES entry {name!r} is not "
                            "a legal Prometheus metric name")
    st_py = os.path.join(os.path.dirname(NODE_PY), os.pardir,
                         "obs", "servetrace.py")
    with open(st_py) as f:
        tree = ast.parse(f.read(), filename=st_py)
    fn = next((n for n in ast.walk(tree)
               if isinstance(n, ast.FunctionDef)
               and n.name == "gauge_values"), None)
    if fn is None:
        problems.append("obs/servetrace.py has no gauge_values()")
    else:
        written = {n.value for n in ast.walk(fn)
                   if isinstance(n, ast.Constant)
                   and isinstance(n.value, str)
                   and n.value.startswith("swim_serve_")}
        if written != set(SERVE_TRACE_GAUGES):
            problems.append(
                f"servetrace.gauge_values writes {sorted(written)} but "
                f"SERVE_TRACE_GAUGES declares "
                f"{sorted(SERVE_TRACE_GAUGES)} — keep the two in "
                "lockstep")
    fake = {"nodes": 4096, "periods": 3,
            "phases": {name: {"mean_ms": 1.0, "p99_ms": 2.0,
                              "fraction": 0.2} for name in PHASES},
            "period_ms": {"mean": 5.0, "total": 15.0},
            "unattributed_ms": 0.1}
    emitted = {line.split("{")[0].split(" ")[0]
               for line in render_serve_trace(fake).splitlines()
               if line and not line.startswith("#")}
    if emitted != set(SERVE_TRACE_GAUGES):
        problems.append(
            f"render_serve_trace emits {sorted(emitted)} but "
            f"SERVE_TRACE_GAUGES declares {sorted(SERVE_TRACE_GAUGES)} "
            "— keep the renderer and the gauge table in lockstep")
    if "ext_mirror_overflow" not in HEALTH_RULES:
        problems.append(
            "serve/hub.py fires `ext_mirror_overflow` Findings but "
            "HEALTH_RULES does not declare the rule — undeclared rules "
            "never reach the swim_health_findings gauge surface")
    return problems


def check_ici_terms() -> list[str]:
    """Problems with the auditor's ICI tally vocabulary ([] = clean).

    The tally-completeness contract attributes traced collective bytes
    to the named terms in audit.ICI_TERM_FAMILIES; a term the tally no
    longer emits (rename, removal) would silently leave its family's
    budget over-claimed.  Terms are declared where the bytes move: the
    psum/gather terms as literal keys in obs/ici.py, the roll_* terms
    as `label=` literals at the models/ring.py (and sharded-ops) call
    sites.  Require every auditor term to appear as a QUOTED literal in
    at least one of those sources — the reverse direction (no breakdown
    key outside the auditor's vocabulary) is checked at trace time by
    the contract itself.
    """
    from swim_tpu.analysis.audit import ICI_TERMS

    pkg = os.path.dirname(os.path.dirname(NODE_PY))
    sources = ""
    for rel in (("obs", "ici.py"), ("models", "ring.py"),
                ("parallel", "ring_shard.py")):
        with open(os.path.join(pkg, *rel)) as f:
            sources += f.read()
    problems: list[str] = []
    for term in ICI_TERMS:
        if f'"{term}"' not in sources and f"'{term}'" not in sources:
            problems.append(
                f"auditor tally term {term!r} is not a declared key in "
                "obs/ici.py or a roll label in models/ring.py — update "
                "audit.ICI_TERM_FAMILIES to match the tally vocabulary")
    return problems


def check_scenario_rules() -> list[str]:
    """Problems with the scenario/health-rule surface ([] = clean).

    The scenario compiler leans on two fault-schedule-aware health
    rules (gray_undetected, flap_false_dead) and lets library specs
    name rules in `allow_rules` waivers and `rule_fired` expectations —
    a renamed or deleted rule would silently turn a waiver into a no-op
    and a rule_fired check into a guaranteed failure, so pin the whole
    rule vocabulary here at build time.
    """
    from swim_tpu.obs.health import HEALTH_RULES

    problems: list[str] = []
    for rule in ("gray_undetected", "flap_false_dead"):
        if rule not in HEALTH_RULES:
            problems.append(
                f"scenario rule {rule!r} missing from HEALTH_RULES — "
                "the scenario gauges (sim/scenario.py fault_gauges) "
                "feed it")
    from swim_tpu.sim import scenario

    for name, spec in scenario.LIBRARY.items():
        unknown = sorted(set(spec.allow_rules) - set(HEALTH_RULES))
        if unknown:
            problems.append(
                f"library scenario {name!r} waives unknown rule(s) "
                f"{unknown} — waivers must name HEALTH_RULES entries")
        for chk in spec.expect:
            if chk.get("check") == "rule_fired" \
                    and chk.get("rule") not in HEALTH_RULES:
                problems.append(
                    f"library scenario {name!r} expects unknown rule "
                    f"{chk.get('rule')!r} to fire")
    return problems


def check_scenario_metrics() -> list[str]:
    """Problems with the scenario expect-metric surface ([] = clean).

    Engine-arm checks (`metric_zero` / `metric_max` / `metric_nonzero`
    / `fewer`) look their metric up in the arm digest dict
    (sim/scenario.py `_arm_digest`); a typo'd or renamed metric reads
    as None, which `metric_zero` treats as failing but `fewer` would
    compare as None-vs-None.  Pin every library metric name to the
    digest vocabulary, derived from the same sources the digest is
    built from (PeriodSeries fields x series_digest suffixes, the
    detection-summary milestone keys, and _arm_digest's explicit
    scalars) so a telemetry-field rename surfaces at build time.
    """
    from swim_tpu.sim import scenario
    from swim_tpu.sim.runner import PeriodSeries

    vocab = {f"{f}_{s}" for f in PeriodSeries._fields
             for s in ("final", "peak", "sum", "mean")}
    for m in ("suspect", "dead_view", "disseminated"):
        vocab |= {f"{m}_detected", f"{m}_latency_mean",
                  f"{m}_latency_p50", f"{m}_latency_p99"}
    vocab |= {"crashed", "overflow", "max_incarnation",
              "false_dead_views_final", "false_dead_views_peak"}
    metric_checks = ("metric_zero", "metric_max", "metric_nonzero",
                     "fewer")
    problems: list[str] = []
    for name, spec in scenario.LIBRARY.items():
        if spec.engine == "real":
            continue   # real arms digest counters, not engine series
        for chk in spec.expect:
            if chk.get("check") not in metric_checks:
                continue
            metric = chk.get("metric")
            if metric is not None and metric not in vocab:
                problems.append(
                    f"library scenario {name!r} checks unknown metric "
                    f"{metric!r} — not in the engine-arm digest "
                    "vocabulary")
    return problems


def check_trend_tier_keys() -> list[str]:
    """Problems with the bench->trend key surface ([] = clean).

    The trend engine (obs/trend.py) auto-registers a tier series only
    when a bench payload carries `<tier>_nodes` alongside a metric key —
    `<tier>_periods_per_sec` (throughput family) or `<tier>_peak_bytes`
    (memory family, gate direction inverted); a tier that emits one
    without the other silently never trends.  For the special-cased
    artifact tiers (which bypass the generic `{tier}_{key}` loop in
    bench.py main()), scan bench.py source for explicitly written key
    literals and require the pairing.
    """
    import re

    bench_py = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench.py")
    with open(bench_py) as f:
        src = f.read()
    pps = set(re.findall(r'"([a-z0-9]+)_periods_per_sec"', src))
    peak = set(re.findall(r'"([a-z0-9]+)_peak_bytes"', src))
    sessions = set(re.findall(r'"([a-z0-9]+)_sessions"', src))
    p99 = set(re.findall(r'"([a-z0-9]+)_p99_ms"', src))
    unattr = set(re.findall(r'"([a-z0-9]+)_unattributed_ms"', src))
    nodes = set(re.findall(r'"([a-z0-9]+)_nodes"', src))
    problems: list[str] = []
    for suffix, tiers in (("periods_per_sec", pps), ("peak_bytes", peak),
                          ("sessions", sessions), ("p99_ms", p99),
                          ("unattributed_ms", unattr)):
        for tier in sorted(tiers - nodes):
            problems.append(
                f"bench.py writes \"{tier}_{suffix}\" but never "
                f"\"{tier}_nodes\" — the trend engine needs both to "
                "register the series")
    for tier in sorted(nodes - (pps | peak | sessions | p99 | unattr)):
        problems.append(
            f"bench.py writes \"{tier}_nodes\" but no metric key "
            f"(\"{tier}_periods_per_sec\", \"{tier}_peak_bytes\", "
            f"\"{tier}_sessions\", \"{tier}_p99_ms\" or "
            f"\"{tier}_unattributed_ms\") — the trend engine needs the "
            "pair to register the series")
    return problems


def main() -> int:
    from swim_tpu.obs.registry import NODE_COUNTERS

    keys, dynamic = stats_keys()
    missing = sorted(keys - set(NODE_COUNTERS))
    ok = True
    if missing:
        ok = False
        print(f"UNDECLARED stats keys in core/node.py: {missing} — "
              "declare them in swim_tpu.obs.registry.NODE_COUNTERS "
              "(name -> help text)", file=sys.stderr)
    if dynamic:
        ok = False
        print("non-literal self.stats subscripts (the typed view needs "
              f"string-literal keys): {dynamic}", file=sys.stderr)
    unused = sorted(set(NODE_COUNTERS) - keys)
    if unused:
        # declared-but-never-incremented is informational, not fatal:
        # counters may be bumped outside node.py (tests, future callers)
        print(f"note: declared counters not incremented in node.py: "
              f"{unused}", file=sys.stderr)
    health_problems = check_health_gauges()
    for problem in health_problems:
        ok = False
        print(f"health-gauge lint: {problem}", file=sys.stderr)
    prof_problems = check_prof_gauges()
    for problem in prof_problems:
        ok = False
        print(f"prof-gauge lint: {problem}", file=sys.stderr)
    for problem in check_mem_gauges():
        ok = False
        print(f"mem-gauge lint: {problem}", file=sys.stderr)
    for problem in check_audit_gauges():
        ok = False
        print(f"audit-gauge lint: {problem}", file=sys.stderr)
    for problem in check_session_gauges():
        ok = False
        print(f"session-gauge lint: {problem}", file=sys.stderr)
    for problem in check_serve_trace_gauges():
        ok = False
        print(f"serve-trace-gauge lint: {problem}", file=sys.stderr)
    for problem in check_ici_terms():
        ok = False
        print(f"ici-term lint: {problem}", file=sys.stderr)
    scenario_problems = check_scenario_rules()
    for problem in scenario_problems:
        ok = False
        print(f"scenario-rule lint: {problem}", file=sys.stderr)
    for problem in check_scenario_metrics():
        ok = False
        print(f"scenario-metric lint: {problem}", file=sys.stderr)
    for problem in check_trend_tier_keys():
        ok = False
        print(f"trend-key lint: {problem}", file=sys.stderr)
    from swim_tpu.analysis.audit import AUDIT_GAUGES, ICI_TERMS
    from swim_tpu.obs.health import HEALTH_RULES
    from swim_tpu.obs.memwall import MEM_GAUGES
    from swim_tpu.obs.prof import PROF_GAUGES
    from swim_tpu.obs.servetrace import SERVE_TRACE_GAUGES
    from swim_tpu.serve.hub import SESSION_GAUGES
    from swim_tpu.sim.scenario import LIBRARY

    print(f"checked {len(keys)} stats keys against "
          f"{len(NODE_COUNTERS)} declared counters, "
          f"{len(HEALTH_RULES)} health gauges, "
          f"{len(PROF_GAUGES)} profiler gauges, "
          f"{len(MEM_GAUGES)} memory gauges, "
          f"{len(AUDIT_GAUGES)} audit gauges, "
          f"{len(SESSION_GAUGES)} session gauges, "
          f"{len(SERVE_TRACE_GAUGES)} serve-trace gauges, "
          f"{len(ICI_TERMS)} tally terms and "
          f"{len(LIBRARY)} library scenarios: "
          f"{'OK' if ok else 'FAIL'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
