import json
from swim_tpu.sim import experiments

# Geometry-scaled twin of study_suspicion_4m_cpu.json: OB=128 (>= the
# ~106 originations/period demand at 4M).  OW=8 OOM'd the CPU host's
# study summary; OB=128 is the smallest power-of-two budget above
# demand and halves the ring footprint.
out = experiments.suspicion_sweep(
    n=4_000_000, mults=(2.0,), losses=(0.02,), crash_fraction=0.0002,
    periods=60, seed=0, engine="ringshard", ring_orig_words=4)
print(json.dumps(out))
