"""Anchor the v5e-8 @1M projection with a real shard-sized measurement.

VERDICT r4 Missing #1 / Next #2: the 8-chip 10k-p/s claim rested on a
model with zero measured anchor points.  This script converts it into a
projection where EVERY term is measured or trace-derived:

  * **Per-chip HBM term (MEASURED).**  A v5e-8 1M-node run gives each
    chip N/8 = 131,072 node rows (win/cold shards, node vectors) plus
    the REPLICATED rumor table of the 1M geometry.  That workload is
    reproduced on the one real chip as a single-program run at
    N=131,072 with the timer multipliers re-tuned so `ring.geometry()`
    yields the EXACT 1M ring (same WW/RW/spread/life — geometry scales
    with log10 N, so the multipliers must compensate; the solver below
    matches all four).  Timing uses bench.py's defended harness
    (distinct seed per dispatch, host-fetch barrier, step-advance
    proof).
  * **ICI term (TRACE-DERIVED), per wire format.**  A CountingOps shim
    tallies, during one abstract trace of `ring.step` at the FULL 1M
    size, exactly the bytes the sharded twin (parallel/ring_shard.py
    ShardOps) would move per chip per period, for all four
    (sel wire, scalar wire) combos: `cfg.ring_ici_wire` "window" (2
    dense u32[S, WW] neighbor blocks per wave roll) vs "compact" (the
    first-B piggyback packed as slot indices, ops/wavepack.py — one
    [S, B] narrow-int block per wave plus one shared boundary fetch
    per period), crossed with `cfg.ring_scalar_wire` "wide" (each
    per-wave scalar vector rolls at its storage dtype) vs "packed"
    (ok chains ride 1 bit/node and buddy payloads as byte codes,
    fused into one ppermute bundle per wave).  Plus psum payloads for
    reductions/replicated gathers and the [D, kl] candidate
    all_gather.

**ICI time model (deliberate serial-link lower bound).**  Every tally
is the per-chip RECEIVED payload bytes per period (a window roll
receives 2 neighbor blocks; sends mirror receives by ring symmetry and
travel the opposite direction of the full-duplex links, so they are
not double-counted).  t_ici divides that received total by ONE link's
per-direction bandwidth (45 GB/s) — as if every inbound block
serialized through a single port.  That is intentionally conservative:
it claims no credit for spreading receives across the chip's several
ICI links, and the slack stands in for what the byte count omits
(multi-hop forwarding of k>1 switch branches, packet/ppermute launch
overheads).  An achieved-bandwidth calibration on a real pod can only
move the ceiling UP from this floor.

Projection brackets: perfect HBM/ICI overlap (1/max) vs fully serial
(1/sum); `ici_ceiling_pps` (1e3/t_ici) is the chip-independent bound
the wire format alone imposes.  Dispatch cost is EXCLUDED from the
projection — the ~66 ms observed here is the axon tunnel's tax
(docs/RESULTS.md §1b #3); an on-pod dispatch is local.  Residual
approximations, recorded in the artifact: the [N]-candidate
compactions run at shard size plus a small all_gather merge (counted
in ICI, its local top_k not re-measured), and replicated Phase-D table
logic is identical per chip by construction.

Usage: python scripts/shard_anchor.py [--cpu-smoke]
  --cpu-smoke: trace-only tier-1 regression — full-size ICI tallies
  for both wires on CPU in seconds (no chip measurement, no artifact
  write); last stdout line is the same JSON shape with
  chip_measured/projections null.
Artifact: bench_results/shard_anchor_v5e8.json (last stdout line = JSON).
"""
from __future__ import annotations

import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_FULL = 1_000_000
D = 8
N_SHARD = N_FULL // D
PERIODS = 100

ICI_GBPS = 45.0          # v5e ICI, per link per direction (public figure)
NORTH_STAR_PPS = 10_000.0

ARMS = {
    "ringp": dict(ring_sel_scope="period"),
    "lean": dict(ring_sel_scope="period", suspicion_mult=2.0,
                 retransmit_mult=2.0, k_indirect=1,
                 ring_window_periods=3, ring_view_c=2),
}

# (sel wire, scalar wire) combos traced per arm.  The bare keys keep
# the pre-packed-scalar artifact/test vocabulary ("window", "compact"
# == wide scalar wire); "+packed" adds ring_scalar_wire="packed".
WIRES = {
    "window": ("window", "wide"),
    "compact": ("compact", "wide"),
    "window+packed": ("window", "packed"),
    "compact+packed": ("compact", "packed"),
}

# Combined scalar roll bytes (every roll_* term except the sel-window
# waves) per chip per period BEFORE this PR's scalar-wire work — the
# committed pre-PR artifact's roll[1000000,{int32,uint32,bool}] sums
# (int32 pid + view-slot rolls, u32 gone/top-key rolls, bool flag
# rolls).  The denominator for scalar_roll_reduction_vs_pre_pr.
PRE_PR_SCALAR_ROLL_BYTES = {"ringp": 24_750_000, "lean": 12_750_000}


def scalar_roll_bytes(breakdown: dict) -> int:
    """Combined scalar-roll bytes in a trace breakdown: the named
    roll_* terms minus the sel-window wave payloads (which belong to
    ring_ici_wire, not the scalar wire)."""
    return sum(v for k, v in breakdown.items()
               if k.startswith("roll") and k != "roll_sel_waves")


def _match_mult(base: float, want: "dict[float, int]") -> float:
    """Smallest multiplier m >= candidates near `base` such that every
    ceil(m * key) == value in `want` (keys are log-N-scaled factors)."""
    for i in range(0, 400):
        m = round(base + i * 0.005, 4)
        if all(math.ceil(m * k) == v for k, v in want.items()):
            return m
    raise RuntimeError(f"no multiplier matches {want} near {base}")


def matched_cfg(kw: dict):
    """SwimConfig at N_SHARD whose ring geometry & timers equal the
    N_FULL config's (per-chip slice of the 1M run carries the 1M ring)."""
    from swim_tpu import SwimConfig
    from swim_tpu.models import ring

    full = SwimConfig(n_nodes=N_FULL, **kw)
    ln = SwimConfig(n_nodes=N_SHARD, **kw).log_n
    rm = _match_mult(full.retransmit_mult,
                     {ln: full.retransmit_limit})
    sm = _match_mult(full.suspicion_mult,
                     {ln: full.suspicion_periods,
                      ln * full.suspicion_max_mult:
                          full.suspicion_max_periods})
    cfg = SwimConfig(n_nodes=N_SHARD,
                     **{**kw, "retransmit_mult": rm, "suspicion_mult": sm})
    gf, gs = ring.geometry(full), ring.geometry(cfg)
    if gf != gs:
        raise RuntimeError(f"geometry mismatch: full={gf} shard={gs}")
    assert cfg.suspicion_periods == full.suspicion_periods
    assert cfg.gossip_window == full.gossip_window
    return cfg, full


def trace_ici_bytes(full_cfg) -> dict:
    """Per-chip ICI bytes/period the ShardOps layout would move at
    N_FULL over D chips.  The CountingOps tally now lives in the
    runtime telemetry layer (swim_tpu/obs/ici.py — the flight recorder
    embeds the same dict in its dump header); this wrapper pins the
    anchor script's D and ICI_GBPS constants."""
    from swim_tpu.obs.ici import trace_ici_bytes as _trace

    return _trace(full_cfg, D, ici_gbps=ICI_GBPS)


def measure_chip(cfg) -> dict:
    """Measured per-chip HBM term: the shard-sized workload on the real
    chip (bench.py defended harness)."""
    import jax
    import jax.numpy as jnp

    from bench import _time_run
    from swim_tpu.models import ring
    from swim_tpu.sim import faults
    from swim_tpu.utils import roofline as rl

    n = cfg.n_nodes
    plan = faults.with_random_crashes(
        faults.none(n), jax.random.key(1), 0.001, 0, PERIODS)
    state = ring.init_state(cfg)
    key = jax.random.key(0)
    run = jax.jit(lambda st, seed: ring.run(
        cfg, st, plan, jax.random.fold_in(key, seed), PERIODS))
    t0 = time.perf_counter()
    jax.block_until_ready(run(state, jnp.int32(99)))
    compile_s = time.perf_counter() - t0
    pps = _time_run(run, state, warmup=1, periods=PERIODS)
    ceil = rl.ceiling_periods_per_sec(cfg)
    if pps > 3.0 * ceil["ceiling_fused"]:
        raise RuntimeError(f"{pps:.0f} p/s exceeds 3x roofline — timing "
                           "artifact")
    return {"n": n, "periods": PERIODS, "periods_per_sec": round(pps, 2),
            "t_chip_ms": round(1e3 / pps, 3),
            "compile_s": round(compile_s, 1),
            "ceiling_fused_pps": round(ceil["ceiling_fused"], 1),
            "platform": jax.devices()[0].platform}


def main() -> int:
    import jax

    from swim_tpu.models import ring

    smoke = "--cpu-smoke" in sys.argv
    if smoke:
        from swim_tpu.utils.platform import force_cpu

        force_cpu(1)
    arms = {}
    for name, kw in ARMS.items():
        cfg, full = matched_cfg(kw)
        g = ring.geometry(cfg)
        # the chip term is wire-independent (the wire only changes what
        # crosses ICI); in --cpu-smoke the whole arm is trace-only so
        # the tier-1 regression runs in seconds
        chip = None if smoke else measure_chip(cfg)
        wires = {}
        for label, (wire, scalar) in WIRES.items():
            ici = trace_ici_bytes(full.replace(ring_ici_wire=wire,
                                               ring_scalar_wire=scalar))
            w = {"ici_traced": ici,
                 "scalar_roll_bytes": scalar_roll_bytes(ici["breakdown"])}
            if chip is not None:
                t_chip, t_ici = chip["t_chip_ms"], ici["t_ici_ms"]
                w["projected_v5e8_pps_overlap"] = round(
                    1e3 / max(t_chip, t_ici), 1)
                w["projected_v5e8_pps_serial"] = round(
                    1e3 / (t_chip + t_ici), 1)
            wires[label] = w
        red = (wires["window"]["ici_traced"]["breakdown"]
               ["roll_sel_waves"]
               / wires["compact"]["ici_traced"]["breakdown"]
               ["roll_sel_waves"])
        sred = (PRE_PR_SCALAR_ROLL_BYTES[name]
                / wires["compact+packed"]["scalar_roll_bytes"])
        arms[name] = {
            "geometry": {"ww": g.ww, "rw": g.rw, "c": g.c,
                         "k": cfg.k_indirect,
                         "suspicion_mult_matched": cfg.suspicion_mult,
                         "retransmit_mult_matched": cfg.retransmit_mult},
            "chip_measured": chip,
            "wires": wires,
            "roll_sel_waves_reduction": round(red, 2),
            "scalar_roll_bytes_pre_pr": PRE_PR_SCALAR_ROLL_BYTES[name],
            "scalar_roll_reduction_vs_pre_pr": round(sred, 2),
        }
        print(json.dumps({name: arms[name]}), flush=True)
    out = {
        "study": "shard_anchor_v5e8",
        "n_full": N_FULL, "devices": D, "n_shard": N_SHARD,
        "ici_gbps_per_link": ICI_GBPS,
        "north_star_pps": NORTH_STAR_PPS,
        "platform": jax.devices()[0].platform,
        "arms": arms,
        "notes": [
            "per-chip term MEASURED on one chip at N=n_shard with timer "
            "multipliers matched so ring.geometry equals the 1M "
            "config's (per-chip slice of a v5e-8 1M run); wire-"
            "independent, so measured once per arm; null in --cpu-smoke",
            "ICI term trace-derived from the ops seam per wire format: "
            "window = 2 dense neighbor blocks per wave roll, compact = "
            "1 packed [S,B] slot-index block per wave + 1 boundary "
            "block per period (ops/wavepack.py); psum/all_gather "
            "payloads counted at result size",
            "ICI time = per-chip RECEIVED bytes / one link's "
            "per-direction 45 GB/s — a deliberate serial-link lower "
            "bound (sends ride the opposite duplex direction and are "
            "not double-counted; no credit for multi-link spread, "
            "which covers un-modeled multi-hop forwarding)",
            "dispatch excluded: the ~66 ms/dispatch here is the axon "
            "tunnel tax; on-pod dispatch is local",
            "north-star verdict = projected lean arm on the "
            "compact+packed wire vs 10,000 p/s; ici_ceiling verdict is "
            "chip-independent (wire bytes only)",
            "scalar wire (ring_scalar_wire): '+packed' combos fuse each "
            "wave's scalars into one bit/byte-packed ppermute bundle "
            "(ok chains 1 bit/node, buddy cols/vals byte codes — "
            "ops/wavepack.py pack_bundle); scalar_roll_bytes sums every "
            "roll_* term except the sel-window waves, and "
            "scalar_roll_reduction_vs_pre_pr divides the pre-PR "
            "artifact's combined scalar roll bytes by the packed arm's "
            "(the upstream u8 partition ids and the deferred-verdict "
            "view query shrink the wide wire too)",
        ],
    }
    ns = arms.get("lean", arms.get("ringp"))
    ns_wire = (ns or {}).get("wires", {}).get("compact+packed", {})
    ovl = ns_wire.get("projected_v5e8_pps_overlap")
    out["north_star_within_overlap_projection"] = (
        None if ovl is None else bool(ovl >= NORTH_STAR_PPS))
    out["north_star_within_ici_ceiling"] = bool(
        ns_wire.get("ici_traced", {}).get("ici_ceiling_pps", 0.0)
        >= NORTH_STAR_PPS)
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench_results",
        "shard_anchor_v5e8.json")
    if not smoke:
        with open(path, "w") as f:
            json.dump(out, f, indent=1)
        print(f"wrote {path}", file=sys.stderr)
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
