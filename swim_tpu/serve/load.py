"""Load harness: 10^3..10^4 concurrent bridge clients against one hub.

`run_load` is the engine behind `swim-tpu serve bench` / `bench.py
--tier serve`.  It stands up a `ServeHub` over a >=1M-node ring engine
(LEAN-anchor geometry, the telemetry-tier shape) and drives SESSIONS
concurrent clients at it from this host, multiplexed over a handful of
shared UDP sockets — 10^4 sessions never means 10^4 fds; the hub keys
sessions by reserved row, not by socket.  Defended metrics:

  sessions/sec   admission rate: HELLO burst start -> last WELCOME
                 (with datagram retry, so a dropped reply costs latency
                 rather than a lost session)
  p50/p99 ms     round-trip latency of OP_ECHO probes answered straight
                 from the hub's frontend drain, sampled WHILE the
                 engine steps and every session ACKs its mirrored pings

Two arms, same seed and geometry, run back to back (the
tests/test_ring_shard.py tri-run spirit applied to the serving seam):

  clean   admission burst + echo sampling + per-period mirrored-ping
          ACKs from every session
  storm   identical, plus the sim/scenario.py replay_storm adversary
          applied to every session datagram (`duplicate`/`replay`
          knobs, the real-node SimNetwork vocabulary): a fraction of
          client->hub datagrams is sent twice, a fraction re-sends a
          stale earlier payload

`ok_parity` asserts the two arms leave the engine state BITWISE
identical (sha256 over every state field) and that both admitted the
full session count: adversarial datapath traffic — duplicated acks,
replayed probes, echo floods — must never perturb the tensor verdict.

Two trace-layer companions (obs/servetrace.py): `run_trace` re-runs
the clean arm untraced-then-traced and decomposes the echo-RTT p99
tail into named `_period` phases (bench_results/serve_trace.json, the
`swim-tpu serve trace` engine), and `trace_overhead` is the
socket-free best-of-reps measurement behind `bench.py --tier
servetrace`'s <=5% overhead contract.
"""

from __future__ import annotations

import hashlib
import socket
import threading
import time

import numpy as np

from swim_tpu.config import SwimConfig
from swim_tpu.core import codec
from swim_tpu.obs import servetrace
from swim_tpu.serve import hub as hub_mod
from swim_tpu.serve.hub import (HDR, OP_BYE, OP_DELIVER, OP_DGRAM, OP_ECHO,
                                OP_ECHO_REPLY, OP_HELLO, OP_REJECT,
                                OP_WELCOME, ServeHub, pack, unpack)
from swim_tpu.types import MsgKind

# The 1M-capable geometry the telemetry tier anchors on (bench.py
# LEAN_ANCHOR): small window, period-scoped selection — the shape that
# fits a million-node ring state on the CPU host.
SERVE_ANCHOR = {"ring_sel_scope": "period", "suspicion_mult": 2.0,
                "retransmit_mult": 2.0, "k_indirect": 1,
                "ring_window_periods": 3, "ring_view_c": 2}

DEFAULT_STORM = {"duplicate": 0.3, "replay": 0.3}


def state_digest(state) -> str:
    """sha256 over every ring-state field (bitwise arm comparator)."""
    h = hashlib.sha256()
    for name, arr in zip(state._fields, state):
        h.update(name.encode())
        h.update(np.asarray(arr).tobytes())
    return h.hexdigest()


class _ClientArm:
    """SESSIONS concurrent clients over `n_sockets` shared UDP sockets.

    Each socket owns sessions round-robin and runs one receiver thread:
    WELCOME completes an admission, DELIVERed mirrored pings are ACKed
    back through the session seam, ECHO_REPLY closes an RTT sample.
    The storm knobs wrap every session datagram (DGRAM/ECHO) — never
    HELLO/BYE, mirroring replay_storm's scope: adversarial *session
    traffic*, not adversarial membership."""

    def __init__(self, hub_addr, sessions: int, n_sockets: int = 16,
                 duplicate: float = 0.0, replay: float = 0.0,
                 seed: int = 0):
        self.hub_addr = hub_addr
        self.sessions = sessions
        self.duplicate = duplicate
        self.replay = replay
        self._rng = np.random.default_rng(seed * 6151 + 13)
        self._socks = []
        for _ in range(min(n_sockets, sessions)):
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            s.bind(("127.0.0.1", 0))
            s.settimeout(0.25)
            self._socks.append(s)
        self._lock = threading.Lock()
        self.row_of: dict[int, int] = {}       # nonce -> assigned row
        self.rejected: dict[int, int] = {}     # nonce -> reason
        self.last_welcome = 0.0
        self._echo_sent: dict[int, float] = {}
        self.rtts_ms: list[float] = []
        # client-side [t_send, t_recv] stamps per echo (time.monotonic
        # — the SAME clock obs/servetrace.py frames use, so
        # analyze.summarize_serve can overlap them for attribution)
        self.echo_windows: list[tuple[float, float]] = []
        self.acks_sent = 0
        self._history: list[tuple[socket.socket, bytes]] = []
        self._closing = False
        self._threads = [threading.Thread(target=self._recv_loop,
                                          args=(s,), daemon=True)
                         for s in self._socks]
        for t in self._threads:
            t.start()

    # ------------------------------------------------------------- sends

    def _send(self, sock: socket.socket, data: bytes) -> None:
        """One session datagram, through the adversary: maybe
        duplicated, maybe followed by a stale replay from history."""
        sock.sendto(data, self.hub_addr)
        if self.duplicate > 0.0 and self._rng.random() < self.duplicate:
            sock.sendto(data, self.hub_addr)
        if self.replay > 0.0:
            with self._lock:
                self._history.append((sock, data))
                if len(self._history) > 4096:
                    del self._history[:2048]
                stale = (self._history[
                    int(self._rng.integers(len(self._history)))]
                    if self._rng.random() < self.replay else None)
            if stale is not None:
                stale[0].sendto(stale[1], self.hub_addr)

    # ---------------------------------------------------------- admission

    def admit_all(self, timeout: float = 60.0) -> dict:
        """HELLO every session (nonce = session index) and wait for the
        WELCOMEs; unanswered nonces are re-sent every 200ms.  Returns
        the admission metrics."""
        start = time.monotonic()
        deadline = start + timeout
        while time.monotonic() < deadline:
            with self._lock:
                missing = [i for i in range(self.sessions)
                           if i not in self.row_of
                           and i not in self.rejected]
            if not missing:
                break
            for i in missing:
                sock = self._socks[i % len(self._socks)]
                sock.sendto(pack(OP_HELLO, i, 0), self.hub_addr)
            time.sleep(0.2)
        with self._lock:
            admitted = len(self.row_of)
            end = self.last_welcome or time.monotonic()
        seconds = max(end - start, 1e-9)
        return {"sessions": admitted,
                "rejected": len(self.rejected),
                "seconds": round(seconds, 4),
                "sessions_per_sec": round(admitted / seconds, 1)}

    def leave_all(self) -> None:
        with self._lock:
            rows = list(self.row_of.items())
        for nonce, row in rows:
            sock = self._socks[nonce % len(self._socks)]
            sock.sendto(pack(OP_BYE, row, 0), self.hub_addr)

    # -------------------------------------------------------------- echo

    def sample_echoes(self, samples: int, spacing_s: float = 0.001,
                      settle_s: float = 1.0) -> None:
        for seq in range(samples):
            sock = self._socks[seq % len(self._socks)]
            with self._lock:
                self._echo_sent[seq] = time.monotonic()
            self._send(sock, pack(OP_ECHO, seq, 0))
            time.sleep(spacing_s)
        deadline = time.monotonic() + settle_s
        while time.monotonic() < deadline:
            with self._lock:
                if not self._echo_sent:
                    return
            time.sleep(0.02)

    # ------------------------------------------------------------ receive

    def _recv_loop(self, sock: socket.socket) -> None:
        while not self._closing:
            try:
                data, _ = sock.recvfrom(65535)
            except socket.timeout:
                continue
            except OSError:
                return
            if len(data) < HDR.size:
                continue
            op, a, b, payload = unpack(data)
            if op == OP_WELCOME:
                with self._lock:
                    if b not in self.row_of:
                        self.row_of[b] = a
                        self.last_welcome = time.monotonic()
            elif op == OP_REJECT:
                with self._lock:
                    # queue-full rejects retry (transient back-pressure);
                    # pool-full rejects are terminal for the nonce
                    if a == hub_mod.REJ_FULL:
                        self.rejected[b] = a
            elif op == OP_ECHO_REPLY:
                now = time.monotonic()
                with self._lock:
                    sent = self._echo_sent.pop(a, None)
                    if sent is not None:
                        self.rtts_ms.append((now - sent) * 1e3)
                        self.echo_windows.append((sent, now))
            elif op == OP_DELIVER:
                # a mirrored rotor ping for row b: ACK it back through
                # the session seam (the hub's liveness credit)
                try:
                    if codec.peek_kind(payload) != MsgKind.PING:
                        continue
                    msg = codec.decode(payload)
                except codec.DecodeError:
                    continue
                ack = codec.encode(codec.Message(
                    kind=MsgKind.ACK, sender=b, probe_seq=msg.probe_seq))
                self._send(sock, pack(OP_DGRAM, b, a, ack))
                with self._lock:
                    self.acks_sent += 1

    def close(self) -> None:
        self._closing = True
        for s in self._socks:
            try:
                s.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=5.0)


# Log-bucketed RTT histogram edges, ms: 0.125 .. ~16s doubling — wide
# enough that a loopback p50 (~2 ms) and a GIL-stalled tail (~100 ms)
# both land mid-range with headroom for a pathological run.
RTT_HIST_EDGES_MS = tuple(0.125 * 2 ** k for k in range(18))


def _percentile(vals: list[float], q: float) -> float:
    """Linear interpolation between closest ranks (the numpy default,
    hand-rolled so the tail arithmetic is explicit): rank = (n-1)*q/100,
    value = v[floor] + frac*(v[ceil]-v[floor]).  Nearest-rank on a
    small sample set overstates the tail — at 50 samples nearest-rank
    p99 IS the max; interpolation keeps it between the top two."""
    if not vals:
        return 0.0
    s = sorted(float(v) for v in vals)
    rank = (len(s) - 1) * (q / 100.0)
    lo = int(rank)
    hi = min(lo + 1, len(s) - 1)
    return s[lo] + (s[hi] - s[lo]) * (rank - lo)


def _rtt_hist(vals: list[float]) -> dict:
    """Log-bucketed RTT histogram: counts[i] holds samples in
    [edges[i], edges[i+1]); the first bucket absorbs anything below
    edges[0], the last anything above edges[-1]."""
    counts = [0] * len(RTT_HIST_EDGES_MS)
    for v in vals:
        i = 0
        while (i + 1 < len(RTT_HIST_EDGES_MS)
               and v >= RTT_HIST_EDGES_MS[i + 1]):
            i += 1
        counts[i] += 1
    return {"edges_ms": list(RTT_HIST_EDGES_MS), "counts": counts}


def _run_arm(cfg: SwimConfig, sessions: int, periods: int, seed: int,
             n_sockets: int, echo_samples: int, frontend: str,
             duplicate: float, replay: float,
             trace: bool = False) -> dict:
    tracer = servetrace.ServeTrace() if trace else None
    hub = ServeHub(cfg, reserved_rows=list(range(sessions)), seed=seed,
                   ext_capacity=hub_mod.EXT_CAPACITY,
                   # no evictions during the measured run: every arm
                   # must leave the plan untouched for bitwise parity
                   ack_grace=periods + 2,
                   queue_capacity=max(1024, sessions + 128),
                   frontend=frontend, trace=tracer)
    arm = _ClientArm(hub.address, sessions, n_sockets=n_sockets,
                     duplicate=duplicate, replay=replay, seed=seed)
    try:
        admission = arm.admit_all()
        echo_thread = threading.Thread(
            target=arm.sample_echoes, args=(echo_samples,), daemon=True)
        step_s = time.monotonic()
        echo_thread.start()
        hub.step_periods(periods)
        step_seconds = time.monotonic() - step_s
        echo_thread.join(timeout=120.0)
        time.sleep(0.3)              # let in-flight ACKs drain
        digest = state_digest(hub.state)
        report = hub.report()
        out = {"admission": admission,
               "rtt_ms": {"p50": round(_percentile(arm.rtts_ms, 50), 3),
                          "p99": round(_percentile(arm.rtts_ms, 99), 3),
                          "p999":
                              round(_percentile(arm.rtts_ms, 99.9), 3),
                          "hist": _rtt_hist(arm.rtts_ms),
                          "samples": len(arm.rtts_ms)},
               "acks_sent": arm.acks_sent,
               "step_seconds": round(step_seconds, 3),
               "digest": digest,
               "report": report}
        if tracer is not None:
            out["trace"] = {"summary": tracer.summary(),
                            "frames": tracer.frames(),
                            "echo_windows":
                                [list(w) for w in arm.echo_windows],
                            "spans": len(tracer.span_dicts())}
        return out
    finally:
        arm.close()
        hub.close()


def run_load(n_nodes: int = 1_000_000, sessions: int = 1000,
             periods: int = 3, seed: int = 0, n_sockets: int = 16,
             echo_samples: int = 2000, frontend: str = "auto",
             storm: dict | None = None) -> dict:
    """The full serve-tier measurement: clean arm, storm arm, parity.

    Returns the bench_results/serve_load.json payload (bench.py stamps
    captured_at/commit).  `ok_parity` is the defended invariant: the
    adversarial arm's duplicated/replayed session traffic leaves engine
    state bitwise identical AND both arms admit every session."""
    storm = dict(DEFAULT_STORM if storm is None else storm)
    cfg = SwimConfig(n_nodes=n_nodes, **SERVE_ANCHOR)
    clean = _run_arm(cfg, sessions, periods, seed, n_sockets,
                     echo_samples, frontend, 0.0, 0.0)
    stormed = _run_arm(cfg, sessions, periods, seed, n_sockets,
                       echo_samples, frontend,
                       float(storm.get("duplicate", 0.0)),
                       float(storm.get("replay", 0.0)))
    ok = (clean["digest"] == stormed["digest"]
          and clean["admission"]["sessions"] == sessions
          and stormed["admission"]["sessions"] == sessions)
    return {"nodes": n_nodes,
            "sessions": sessions,
            "periods": periods,
            "frontend": clean["report"]["frontend"],
            "anchor_cfg": dict(SERVE_ANCHOR),
            "admission_sessions_per_sec":
                clean["admission"]["sessions_per_sec"],
            "p50_rtt_ms": clean["rtt_ms"]["p50"],
            "p99_rtt_ms": clean["rtt_ms"]["p99"],
            "clean": clean,
            "storm": {"knobs": storm, **stormed},
            "ok_parity": ok}


def run_trace(n_nodes: int = 1_000_000, sessions: int = 1000,
              periods: int = 3, seed: int = 0, n_sockets: int = 16,
              echo_samples: int = 2000, frontend: str = "auto") -> dict:
    """Tail-latency attribution at the serve-tier shape: the
    bench_results/serve_trace.json payload (`swim-tpu serve trace`).

    Two clean arms, same seed and geometry: UNTRACED (the parity
    baseline) then TRACED (`ServeHub(trace=...)` on).  The traced
    arm's period frames + the clients' echo windows feed
    analyze.summarize_serve, which decomposes the echo-RTT p99 tail
    into per-phase milliseconds by interval overlap.  `ok_parity`
    defends both contracts at once: the arms' engine states are
    sha256-bitwise identical (tracing reads clocks, never inputs) AND
    >= the contract fraction of the tail is attributed to named
    phases."""
    from swim_tpu.obs import analyze

    cfg = SwimConfig(n_nodes=n_nodes, **SERVE_ANCHOR)
    off = _run_arm(cfg, sessions, periods, seed, n_sockets,
                   echo_samples, frontend, 0.0, 0.0)
    on = _run_arm(cfg, sessions, periods, seed, n_sockets,
                  echo_samples, frontend, 0.0, 0.0, trace=True)
    att = analyze.summarize_serve(on["trace"]["frames"],
                                  on["trace"]["echo_windows"],
                                  phase_summary=on["trace"]["summary"])
    att["nodes"] = n_nodes       # the expo renderer's shape label
    digests_match = off["digest"] == on["digest"]
    t_off, t_on = off["step_seconds"], on["step_seconds"]
    return {"kind": "serve_trace",
            "nodes": n_nodes,
            "sessions": sessions,
            "periods": periods,
            "frontend": on["report"]["frontend"],
            "anchor_cfg": dict(SERVE_ANCHOR),
            "attribution": att,
            "phase_summary": on["trace"]["summary"],
            "rtt_ms": on["rtt_ms"],
            "digest_untraced": off["digest"],
            "digest_traced": on["digest"],
            "digests_match": digests_match,
            "step_seconds_untraced": t_off,
            "step_seconds_traced": t_on,
            "serve_unattributed_ms": att.get("unattributed_ms", 0.0),
            "coverage_pct": att.get("coverage_pct", 0.0),
            "ok_parity": digests_match and bool(att.get("attributed"))}


def trace_overhead(n_nodes: int = 65_536, sessions: int = 256,
                   periods: int = 6, seed: int = 0,
                   reps: int = 3) -> dict:
    """Tracing-overhead contract measurement (`bench.py --tier
    servetrace` -> bench_results/servetrace_overhead.json).

    Deterministic and socket-free so the number is the tracer's, not
    the network's: in-process sessions, per-period ACK datagrams
    (identical in both arms — they exercise the span path but touch
    host counters only), best-of-`reps` periods/sec untraced vs
    traced.  The telemetry layer's precedent is 1.45%; the contract
    here is the same 5%.  `ok_parity` pins the arms' engine-state
    digests bitwise equal.  The traced arm's per-period wall not
    covered by a named phase rides along as `serve_unattributed_ms`
    (the obs/trend.py inverted family)."""
    cfg = SwimConfig(n_nodes=n_nodes, **SERVE_ANCHOR)
    ack = codec.encode(codec.Message(kind=MsgKind.ACK, sender=0,
                                     probe_seq=1))

    def arm(traced: bool) -> tuple[float, str, float]:
        best, digest, unattr = None, "", 0.0
        for _ in range(reps):
            hub = ServeHub(cfg, reserved_rows=list(range(sessions)),
                           seed=seed, ack_grace=2 * periods + 4,
                           frontend="socket", trace=traced)
            try:
                for _ in range(sessions):
                    hub.attach()
                hub.step_periods(1)      # compile + warm, untimed
                t0 = time.monotonic()
                for _ in range(periods):
                    for row in range(min(32, sessions)):
                        hub._on_session_datagram(
                            None, row, (row + 1) % n_nodes, ack)
                    hub.step_periods(1)
                dt = time.monotonic() - t0
                digest = state_digest(hub.state)
                rep_unattr = (hub.trace.summary()["unattributed_ms"]
                              if traced else 0.0)
            finally:
                hub.close()
            if best is None or dt < best:
                best, unattr = dt, rep_unattr
        return float(best), digest, unattr

    t_off, d_off, _ = arm(False)
    t_on, d_on, unattr_ms = arm(True)
    pps_off, pps_on = periods / t_off, periods / t_on
    overhead = (pps_off - pps_on) / pps_off * 100.0
    return {"nodes": n_nodes,
            "sessions": sessions,
            "periods": periods,
            "reps": reps,
            "pps_off": round(pps_off, 3),
            "pps_on": round(pps_on, 3),
            "overhead_pct": round(overhead, 2),
            "contract_pct": 5.0,
            "within_contract": overhead <= 5.0,
            "digest_off": d_off,
            "digest_on": d_on,
            "serve_unattributed_ms": round(unattr_ms, 4),
            "anchor_cfg": dict(SERVE_ANCHOR),
            "ok_parity": d_off == d_on}
