"""Serving hub: async session admission over a free-running ring engine.

`ServeHub` admits thousands of concurrent external cores onto ONE
tensor-cluster simulation — ROADMAP item 3's "serve heavy traffic"
half.  Three structural differences from `bridge/engine_server.py`
(which remains the full-fidelity lockstep seam for a handful of
sessions):

  NO BARRIER.  The engine steps whenever the driver says so
    (`step_periods`); no session clock gates it.  A session proves
    liveness by ACKing its mirrored rotor pings; one that stops
    (disconnect, stall, wedge) is EVICTED — its reserved row is
    crash-gated and the cluster detects the death organically — instead
    of freezing everyone else's time.
  BOUNDED WORK QUEUE.  Admission (HELLO), clean departure (BYE) and
    eviction are items on a bounded `queue.Queue` drained by a
    dedicated worker thread, so the device step NEVER blocks on socket
    I/O and a join storm degrades to rejections, not latency.
  BATCHED ROW MIRRORING.  All reserved-row writes for a device step —
    every session's gossip turned `ring.ExtOriginations` entries — are
    coalesced into ONE placed update (a single `jax.device_put` of the
    whole batch) instead of one host->device round-trip per session.
    The placement is priced as the `ext_mirror_rows` term in
    obs/ici.py (16 bytes per slot: 4 i32/u32 lanes), and the auditor's
    `ici_tally_completeness` contract extends over it
    (analysis/audit.py `placed` family) — which is why `EXT_CAPACITY`
    lives here as a module constant the auditor imports.

Wire protocol (datagram; native/udppump.cpp epoll frontend when the
toolchain is present, plain Python UDP otherwise — `frontend="auto"`):
a fixed `!BII` header (op, a, b) + optional payload.  Sessions are
keyed by their assigned reserved ROW, not by socket: many sessions
share one client socket, which is how 10^4 sessions fit under a ~1024
fd ulimit (serve/load.py multiplexes ~16 sockets).

  HELLO  (c->h)  a=client nonce          -> WELCOME a=row b=nonce
                                          | REJECT a=reason b=nonce
  BYE    (c->h)  a=row                   clean leave: row returns to
                                         the free pool, NO plan
                                         mutation (churn-neutral)
  DGRAM  (c->h)  a=src row b=dst node    payload = core/codec.py bytes
                                         (gossip -> injections; ACK ->
                                         liveness credit; PING -> D3
                                         synthesized ack)
  DELIVER(h->c)  a=sender b=dst row      payload = codec bytes
                                         (mirrored rotor pings, acks)
  ECHO   (c->h)  a,b opaque              -> ECHO_REPLY a,b — answered
                                         straight from the frontend
                                         drain, the RTT probe the load
                                         harness p50/p99 is built on
Deviations D2/D3 are inherited from engine_server.py where the shared
seam applies; hub-synthesized acks carry EMPTY gossip unless
`mirror_gossip=True` (the full resolved-row diff per session per
period is the lockstep bridge's fidelity trade, not the hub's).
"""

from __future__ import annotations

import collections
import functools
import queue
import socket
import struct
import threading

import numpy as np

from swim_tpu.config import SwimConfig
from swim_tpu.core import codec
from swim_tpu.obs import servetrace
from swim_tpu.obs.health import Finding
from swim_tpu.types import MsgKind, Status, key_incarnation, key_status, \
    opinion_key

WORD = 32

# Static capacity of the coalesced per-step ExtOriginations placement.
# analysis/audit.py imports this to price the hub's mirroring bytes
# (ici_tally_completeness / serve_ext_mirror: exactly 16 bytes per slot).
EXT_CAPACITY = 64

# ------------------------------------------------------------ wire format

HDR = struct.Struct("!BII")

OP_HELLO = 1
OP_BYE = 2
OP_DGRAM = 3
OP_WELCOME = 4
OP_DELIVER = 5
OP_ECHO = 6
OP_ECHO_REPLY = 7
OP_REJECT = 8

REJ_FULL = 1        # no free reserved row
REJ_QUEUE = 2       # admission queue full (join storm back-pressure)


def pack(op: int, a: int = 0, b: int = 0, payload: bytes = b"") -> bytes:
    return HDR.pack(op, a & 0xFFFFFFFF, b & 0xFFFFFFFF) + payload


def unpack(data: bytes) -> tuple[int, int, int, bytes]:
    op, a, b = HDR.unpack_from(data, 0)
    return op, a, b, data[HDR.size:]


# --------------------------------------------------------- gauge surface

SESSION_GAUGES: dict[str, str] = {
    "swim_session_admitted":
        "Sessions admitted onto reserved rows since hub start",
    "swim_session_evicted":
        "Sessions evicted (stall/disconnect; their rows were "
        "crash-gated and die organically)",
    "swim_session_active":
        "Sessions currently attached to reserved rows",
    "swim_session_clock_lag_periods":
        "Periods since a session's last liveness credit (per-session "
        "series when the report carries a session table)",
    "swim_session_mirror_bytes_per_period":
        "Bytes of the coalesced per-step ExtOriginations placement "
        "(the obs/ici.py ext_mirror_rows term: 16 per slot)",
    "swim_session_mirror_spill_slots":
        "Queued gossip slots that missed their period's fixed-capacity "
        "ExtOriginations batch (EXT_CAPACITY spill — injected late, "
        "never dropped; persistent spill fires ext_mirror_overflow)",
}


def gauge_values(report: dict) -> dict[str, float]:
    """SESSION_GAUGES values from one `ServeHub.report()` dict (the
    expo.render_sessions scalar fallback; clock lag collapses to the
    WORST attached session)."""
    sessions = report.get("sessions") or []
    worst = max((float(s.get("clock_lag_periods", 0)) for s in sessions),
                default=0.0)
    return {
        "swim_session_admitted": float(report.get("admitted", 0)),
        "swim_session_evicted": float(report.get("evicted", 0)),
        "swim_session_active": float(report.get("active", 0)),
        "swim_session_clock_lag_periods": worst,
        "swim_session_mirror_bytes_per_period":
            float(report.get("mirror_bytes_per_period", 0)),
        "swim_session_mirror_spill_slots":
            float(report.get("mirror_spill_slots", 0)),
    }


# ------------------------------------------------------------- frontends


class _SocketFrontend:
    """Plain Python UDP frontend (the no-toolchain fallback): one
    socket, one drain thread, same callback contract as the pump."""

    kind = "socket"

    def __init__(self, host: str, port: int, on_datagram):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind((host, port))
        self._sock.settimeout(0.25)
        self.local_address = self._sock.getsockname()
        self._on = on_datagram
        self._closing = False
        self._thread = threading.Thread(target=self._drain, daemon=True)
        self._thread.start()

    def _drain(self) -> None:
        while not self._closing:
            try:
                data, addr = self._sock.recvfrom(65535)
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                self._on(addr, data)
            except Exception:  # noqa: BLE001 — a broken handler must not
                pass           # kill the drain loop (pump contract)

    def send(self, to, payload: bytes) -> None:
        try:
            self._sock.sendto(payload, to)
        except OSError:
            pass               # datagram loss is legal on this seam

    def stats(self) -> dict[str, int]:
        return {}

    def close(self) -> None:
        self._closing = True
        try:
            self._sock.close()
        except OSError:
            pass
        self._thread.join(timeout=5.0)


class _PumpFrontend:
    """The udppump epoll datapath as hub frontend: sends enqueue into
    the pump's outbox, inbound datagrams arrive in batches on the
    drainer thread — one GIL crossing per batch, which is what makes
    10^3 concurrent clients cheap (native/udppump.cpp)."""

    kind = "udppump"

    def __init__(self, host: str, port: int, on_datagram):
        from swim_tpu.native.transport import NativeUDPTransport

        self._t = NativeUDPTransport(host, port)
        self._t.set_receiver(on_datagram)
        self.local_address = self._t.local_address

    def send(self, to, payload: bytes) -> None:
        self._t.send(to, payload)

    def stats(self) -> dict[str, int]:
        return self._t.stats()

    def close(self) -> None:
        self._t.close()


def make_frontend(host: str, port: int, on_datagram, prefer: str = "auto"):
    """The hub datapath: `"udppump"` (native epoll, raises without the
    toolchain), `"socket"` (pure Python), or `"auto"` (pump when
    available — the promoted default)."""
    if prefer not in ("auto", "udppump", "socket"):
        raise ValueError(f"bad frontend {prefer!r}")
    if prefer in ("auto", "udppump"):
        from swim_tpu.native import transport as native_transport

        if native_transport.is_available():
            return _PumpFrontend(host, port, on_datagram)
        if prefer == "udppump":
            raise RuntimeError("native udppump unavailable (no toolchain)")
    return _SocketFrontend(host, port, on_datagram)


# ------------------------------------------------------------------- hub


class _Client:
    """One admitted session: a reserved row plus its return address."""

    __slots__ = ("row", "addr", "joined_t", "last_ack_t", "pings_sent",
                 "pings_acked")

    def __init__(self, row: int, addr, t: int):
        self.row = row
        self.addr = addr            # None: in-process attach (no sends)
        self.joined_t = t
        self.last_ack_t = t
        self.pings_sent = 0
        self.pings_acked = 0


class ServeHub:
    """Async-admission serving hub over one ring-engine simulation.

    `reserved_rows` are the engine node ids sessions may attach to;
    admission assigns a free one without retracing (the jitted step is
    shape-stable: the plan and the fixed-capacity ExtOriginations batch
    are the only inputs that change).  Drive the engine with
    `step_periods(k)` (deterministic — tests and the load harness) or
    `start(auto_period=s)` (free-running).  `attach()`/`detach()` are
    the in-process admission path (same worker-queue internals, no
    sockets) used by the churn-neutrality test.
    """

    def __init__(self, cfg: SwimConfig, reserved_rows: list[int],
                 seed: int = 0, host: str = "127.0.0.1", port: int = 0,
                 ext_capacity: int = EXT_CAPACITY, ack_grace: int = 3,
                 queue_capacity: int = 1024, frontend: str = "auto",
                 mirror_gossip: bool = False,
                 trace: "servetrace.ServeTrace | bool | None" = None):
        import jax

        from swim_tpu.models import ring

        if cfg.ring_probe != "rotor":
            raise ValueError("ServeHub requires the rotor probe (the "
                             "mirrored-ping seam is rotor-shaped)")
        self.cfg = cfg
        self.n = cfg.n_nodes
        rows = list(reserved_rows)
        if len(set(rows)) != len(rows):
            raise ValueError("duplicate reserved rows")
        for r in rows:
            if not 0 <= r < self.n:
                raise ValueError("reserved rows must be node ids")
        self.reserved_rows = rows
        self.ext_capacity = int(ext_capacity)
        self.ack_grace = int(ack_grace)
        self.mirror_gossip = bool(mirror_gossip)
        self._jax = jax
        self._ring = ring
        self._key = jax.random.key(seed)
        self.state = ring.init_state(cfg)
        self.t = 0
        self._step = jax.jit(functools.partial(ring.step, cfg))
        self._ext_empty = ring.ext_none(self.ext_capacity)  # device-resident
        # host-side fault mirrors (device plan rebuilt on change; the
        # engine_server.py generation-checked pattern)
        self._crash = np.full((self.n,), np.iinfo(np.int32).max // 2,
                              np.int32)
        self._join = np.zeros((self.n,), np.int32)
        self._plan = None
        self._plan_dirty = True
        self._plan_gen = 0
        self._inject: list[tuple[int, int, int, int]] = []
        self._lock = threading.Lock()
        # bounded work queue: the ONLY path from socket I/O to hub
        # membership state; the device step never waits on it
        self._work: queue.Queue = queue.Queue(maxsize=queue_capacity)
        self._free: collections.deque[int] = collections.deque(rows)
        self._clients: dict[int, _Client] = {}
        self._findings: list[Finding] = []
        self._stats = {"admitted": 0, "evicted": 0, "left": 0,
                       "rejected_full": 0, "queue_drops": 0,
                       "mirror_updates": 0, "mirror_bytes": 0,
                       "mirror_spill_slots": 0, "mirror_spill_periods": 0,
                       "datagrams": 0, "echoes": 0}
        self._spill_streak = 0
        # serve-path tracing (obs/servetrace.py): default OFF — a None
        # check on every hot path, zero allocation untraced.  Tracing
        # only reads clocks and appends to host buffers, so engine
        # state stays bitwise identical traced-vs-untraced.
        self.trace = servetrace.coerce(trace)
        if self.mirror_gossip:
            self._subject = np.asarray(self.state.subject)
            self._rkey = np.asarray(self.state.rkey)
            self._prev_rows: dict[int, np.ndarray] = {}
        self._closing = False
        self.frontend = make_frontend(host, port, self._on_datagram,
                                      frontend)
        self.address = self.frontend.local_address
        self._worker = threading.Thread(target=self._admission_worker,
                                        daemon=True)
        self._worker.start()
        self._engine_thread: threading.Thread | None = None

    # ---------------------------------------------------------- lifecycle

    def start(self, auto_period: float = 0.05) -> None:
        """Free-running mode: step one period every `auto_period`
        seconds until close().  Admission/datapath threads run either
        way; tests and the harness prefer step_periods()."""
        def loop() -> None:
            import time

            while not self._closing:
                self._period()
                time.sleep(auto_period)

        self._engine_thread = threading.Thread(target=loop, daemon=True)
        self._engine_thread.start()

    def close(self) -> None:
        self._closing = True
        try:
            self._work.put_nowait(None)
        except queue.Full:
            pass
        if self._engine_thread is not None:
            self._engine_thread.join(timeout=10)
        self._worker.join(timeout=10)
        self.frontend.close()

    # ------------------------------------------------------ admission path

    def _on_datagram(self, addr, data: bytes) -> None:
        """Frontend drain callback — pump or socket thread.  Never
        touches device state and never blocks: membership changes go
        through the bounded queue, everything else reads host mirrors."""
        if len(data) < HDR.size:
            return
        tr = self.trace
        t_in = tr.now() if tr is not None else 0.0
        op, a, b, payload = unpack(data)
        if op == OP_ECHO:
            # answered straight from the drain: the load harness's RTT
            # probe measures the datapath, not the engine
            with self._lock:
                self._stats["echoes"] += 1
            self.frontend.send(addr, pack(OP_ECHO_REPLY, a, b))
            if tr is not None:
                s = tr.datagram_span(t_in, op)
                t = tr.now()
                s.event(t, "send")
                tr.emit(s.finish(t, "echo_reply"))
        elif op == OP_HELLO:
            span = tr.datagram_span(t_in, op) if tr is not None else None
            try:
                if span is None:
                    self._work.put_nowait(("admit", addr, a))
                else:
                    span.event(tr.now(), "queued")
                    self._work.put_nowait(("admit", addr, a, span))
            except queue.Full:
                with self._lock:
                    self._stats["queue_drops"] += 1
                self.frontend.send(addr, pack(OP_REJECT, REJ_QUEUE, a))
                if span is not None:
                    t = tr.now()
                    span.event(t, "send")
                    tr.emit(span.finish(t, "rejected_queue"))
        elif op == OP_BYE:
            span = tr.datagram_span(t_in, op, row=a) \
                if tr is not None else None
            try:
                if span is None:
                    self._work.put_nowait(("leave", a, addr))
                else:
                    span.event(tr.now(), "queued")
                    self._work.put_nowait(("leave", a, addr, span))
            except queue.Full:
                with self._lock:     # client may re-send; worst case the
                    self._stats["queue_drops"] += 1   # row stalls out
        elif op == OP_DGRAM:
            self._on_session_datagram(addr, a, b, payload, t_in=t_in)

    def _admission_worker(self) -> None:
        """Drains the bounded work queue: admissions, clean leaves,
        evictions.  A dedicated thread, so admission latency is set by
        queue depth — not by the device step."""
        while True:
            item = self._work.get()
            if item is None:
                return
            try:
                kind = item[0]
                # optional 4th element: a "serve" trace span minted at
                # frontend receipt — "handled" marks worker dequeue, so
                # handled-minus-queued is the work-queue wait
                span = item[3] if len(item) > 3 else None
                tr = self.trace
                if span is not None and tr is not None:
                    span.event(tr.now(), "handled")
                if kind == "admit":
                    self._do_admit(item[1], item[2])
                elif kind == "leave":
                    self._do_leave(item[1], item[2])
                elif kind == "evict":
                    self._do_evict(item[1], item[2])
                if span is not None and tr is not None:
                    tr.emit(span.finish(tr.now(), kind))
            except Exception:  # noqa: BLE001 — one bad item must not
                pass           # kill the admission plane

    def _do_admit(self, addr, nonce: int) -> None:
        with self._lock:
            row = self._free.popleft() if self._free else None
            if row is not None:
                self._clients[row] = _Client(row, addr, self.t)
                self._stats["admitted"] += 1
            else:
                self._stats["rejected_full"] += 1
        if addr is None:
            return
        if row is None:
            self.frontend.send(addr, pack(OP_REJECT, REJ_FULL, nonce))
        else:
            self.frontend.send(addr, pack(OP_WELCOME, row, nonce))

    def _do_leave(self, row: int, addr) -> None:
        """Clean departure: the row returns to the free pool with NO
        plan mutation — tensor state is untouched, which is what makes
        silent join/leave churn bitwise-neutral (tests/test_serve.py)."""
        with self._lock:
            c = self._clients.get(row)
            if c is None or (addr is not None and c.addr != addr):
                return
            del self._clients[row]
            self._free.append(row)
            self._stats["left"] += 1

    def _do_evict(self, row: int, reason: str) -> None:
        with self._lock:
            c = self._clients.pop(row, None)
            if c is None:
                return
            self._stats["evicted"] += 1
            lag = self.t - c.last_ack_t
            self._findings.append(Finding(
                rule="session_evicted", severity="warn", period=self.t,
                value=float(lag), threshold=float(self.ack_grace),
                message=f"session row {row} evicted ({reason}): "
                        f"{lag} periods without liveness credit"))
        # row is NOT returned to the pool: it is crash-gated and the
        # cluster detects the death organically (kill takes _lock)
        self.kill(row)

    # in-process admission (no sockets): the churn test's deterministic
    # path through the SAME worker internals

    def attach(self) -> int | None:
        """Synchronously admit an in-process session; returns its row
        (None when the pool is exhausted)."""
        with self._lock:
            before = set(self._clients)
        self._do_admit(None, 0)
        with self._lock:
            new = set(self._clients) - before
        return new.pop() if new else None

    def detach(self, row: int) -> None:
        """Synchronously leave (clean): the in-process BYE."""
        self._do_leave(row, None)

    def evict(self, row: int, reason: str = "test") -> None:
        """Synchronously evict: crash-gate the row + health finding."""
        self._do_evict(row, reason)

    # ------------------------------------------------------- fault wiring

    def kill(self, node_id: int) -> None:
        with self._lock:
            if 0 <= node_id < self.n and self._crash[node_id] > self.t:
                self._crash[node_id] = self.t
                self._plan_dirty = True
                self._plan_gen += 1

    def _alive(self, node_id: int) -> bool:
        return (0 <= node_id < self.n and self._crash[node_id] > self.t
                and self._join[node_id] <= self.t)

    def _device_plan(self):
        with self._lock:
            rebuild = self._plan_dirty or self._plan is None
            gen = self._plan_gen
            if rebuild:
                crash = self._crash.copy()
                join = self._join.copy()
        if rebuild:
            import jax.numpy as jnp

            from swim_tpu.sim.faults import FaultPlan

            self._plan = FaultPlan(
                crash_step=jnp.asarray(crash),
                loss=jnp.float32(0.0),
                partition_id=jnp.zeros((self.n,), jnp.uint8),
                partition_start=jnp.int32(1 << 30),
                partition_end=jnp.int32(1 << 30),
                join_step=jnp.asarray(join))
            with self._lock:
                if self._plan_gen == gen:
                    self._plan_dirty = False
        return self._plan

    # ------------------------------------------------------- session seam

    def _on_session_datagram(self, addr, src: int, dst: int,
                             payload: bytes, t_in: float = 0.0) -> None:
        """One DGRAM from session row `src` toward engine node `dst`
        (codec bytes).  Runs on the frontend thread; reads host mirrors
        only — the engine may be mid-step on another thread."""
        with self._lock:
            c = self._clients.get(src)
            if c is None or (c.addr is not None and c.addr != addr):
                return
            self._stats["datagrams"] += 1
        tr = self.trace
        if tr is not None and not t_in:
            t_in = tr.now()          # in-process callers skip the drain
        try:
            kind = codec.peek_kind(payload)
        except codec.DecodeError:
            return
        if kind == MsgKind.ACK:
            with self._lock:
                c.pings_acked = c.pings_sent
                c.last_ack_t = self.t
            if tr is not None:
                tr.emit(tr.datagram_span(t_in, OP_DGRAM, row=src)
                        .finish(tr.now(), "ack"))
            return
        try:
            msg = codec.decode(payload)
        except codec.DecodeError:
            return
        span = tr.datagram_span(t_in, OP_DGRAM, row=src) \
            if tr is not None else None
        self._queue_injections(dst if self._alive(dst) else src,
                               msg.gossip, span=span)
        if kind == MsgKind.PING and self._alive(dst):
            # D3: answer from host state at datagram time (empty gossip
            # unless mirror_gossip — the hub trades the lockstep
            # bridge's piggyback fidelity for datapath throughput)
            ack = codec.Message(kind=MsgKind.ACK, sender=dst,
                                probe_seq=msg.probe_seq,
                                on_behalf=msg.on_behalf)
            self._deliver(src, dst, ack)
            if span is not None and span.end is None and not msg.gossip:
                # pure ping (no gossip riding the mirror): the span
                # closes at the synthesized-ack send; gossip-carrying
                # datagrams close at their flush period instead
                t = tr.now()
                span.event(t, "send")
                tr.emit(span.finish(t, "deliver"))

    def _queue_injections(self, hearer: int,
                          gossip: tuple[codec.WireUpdate, ...],
                          span=None) -> None:
        first = True
        for u in gossip:
            if not 0 <= u.member < self.n:
                continue
            key = opinion_key(int(u.status), u.incarnation)
            if self.mirror_gossip and key <= self._best_key(u.member):
                continue             # stale vs table mirror (D2)
            org = u.origin if 0 <= u.origin < self.n else hearer
            if span is not None and first:
                span.event(self.trace.now(), "queued")
            with self._lock:
                if span is not None and first:
                    # the span rides the datagram's FIRST queued slot:
                    # its flush period stamps the coalesce-batching
                    # delay (spilled slots flush a period late — the
                    # span measures exactly that)
                    self._inject.append((u.member, key, org, hearer,
                                         span))
                    first = False
                else:
                    self._inject.append((u.member, key, org, hearer))

    def _deliver(self, row: int, sender: int, msg: codec.Message) -> None:
        with self._lock:
            c = self._clients.get(row)
            addr = c.addr if c is not None else None
        if addr is not None:
            self.frontend.send(addr, pack(OP_DELIVER, sender, row,
                                          codec.encode(msg)))

    # ------------------------------------------------------------- engine

    def step_periods(self, k: int) -> None:
        for _ in range(k):
            self._period()

    def _period(self) -> None:
        import jax

        ring = self._ring
        tr = self.trace
        if tr is not None:
            tr.begin(self.t)
        # 1. eviction scan — a session that missed its last ack_grace
        # mirrored pings is enqueued for eviction (never evicted inline:
        # membership changes stay on the worker thread)
        with self._lock:
            stale = [c.row for c in self._clients.values()
                     if c.pings_sent - c.pings_acked > self.ack_grace]
        for row in stale:
            try:
                self._work.put_nowait(("evict", row, "stall"))
            except queue.Full:
                break                # retry next period
        if tr is not None:
            tr.lap("evict_scan")
        # 2. the batched row mirror: coalesce every queued reserved-row
        # write into ONE placed ExtOriginations (a single device_put of
        # the whole fixed-capacity batch — the ext_mirror_rows bytes).
        # Slots past ext_capacity SPILL to the next period: injected
        # late, never dropped — counted, gauged, and health-ruled
        # (ext_mirror_overflow) when the backlog persists.
        with self._lock:
            batch = self._inject[:self.ext_capacity]
            self._inject = self._inject[self.ext_capacity:]
            spill = len(self._inject)
            if spill > 0:
                self._stats["mirror_spill_slots"] += spill
                self._stats["mirror_spill_periods"] += 1
                self._spill_streak += 1
                if self._spill_streak >= 2:
                    self._findings.append(Finding(
                        rule="ext_mirror_overflow", severity="warn",
                        period=self.t, value=float(spill),
                        threshold=float(self.ext_capacity),
                        message=f"ext mirror overflow: {spill} gossip "
                                f"slots spilled past the "
                                f"{self.ext_capacity}-slot batch for "
                                f"{self._spill_streak} consecutive "
                                f"periods"))
            else:
                self._spill_streak = 0
        if batch:
            cap = self.ext_capacity
            subject = np.full((cap,), -1, np.int32)
            key = np.zeros((cap,), np.uint32)
            origin = np.zeros((cap,), np.int32)
            hearer = np.zeros((cap,), np.int32)
            for i, item in enumerate(batch):
                s, k, o, h = item[:4]
                subject[i], key[i], origin[i], hearer[i] = s, k, o, h
            ext = jax.device_put(ring.ExtOriginations(
                subject=subject, key=key, origin=origin, hearer=hearer))
            with self._lock:
                self._stats["mirror_updates"] += 1
                self._stats["mirror_bytes"] += 16 * cap
            if tr is not None:
                # gossip spans riding this batch close at their flush:
                # end-minus-"queued" is the coalesce-batching delay
                t_flush = tr.now()
                for item in batch:
                    if len(item) > 4 and item[4].end is None:
                        item[4].event(t_flush, "flush")
                        tr.emit(item[4].finish(t_flush, "gossip_flushed"))
        else:
            ext = self._ext_empty    # cached device-resident empty batch
        if tr is not None:
            tr.lap("inject_coalesce")
        # 3. one engine period (shape-stable: no retrace on churn)
        rnd = ring.draw_period_ring(self._key, self.t, self.cfg)
        self.state = self._step(self.state, self._device_plan(), rnd,
                                ext=ext)
        if tr is not None:
            # device-synced phase edge (the obs/prof.py timing rule):
            # without it the async dispatch returns instantly and the
            # step's wall time would masquerade as s_off_get
            jax.block_until_ready(self.state)
            tr.lap("engine_step")
        s_off = int(jax.device_get(rnd.s_off))
        if tr is not None:
            tr.lap("s_off_get")
        self.t += 1
        # 4. mirror the rotor probe of every attached session
        if self.mirror_gossip:
            self._subject = np.asarray(self.state.subject)
            self._rkey = np.asarray(self.state.rkey)
        with self._lock:
            attached = list(self._clients.values())
        for c in attached:
            prober = (c.row - s_off) % self.n
            if not self._alive(prober):
                continue             # no probe of this row this period
            gossip: tuple = ()
            if self.mirror_gossip:
                gossip = self._fresh_updates(c.row, prober)
            with self._lock:
                c.pings_sent += 1
            self._deliver(c.row, prober, codec.Message(
                kind=MsgKind.PING, sender=prober, probe_seq=self.t,
                gossip=gossip))
        if tr is not None:
            tr.lap("mirror_fanout")
            tr.end()

    # ------------------------------------------------- state decoding
    # (host mirrors; the engine_server.py shapes, used only with
    # mirror_gossip=True)

    def _best_key(self, member: int) -> int:
        mask = self._subject == member
        return int(self._rkey[mask].max()) if mask.any() else 0

    def _resolved_row(self, x: int) -> np.ndarray:
        g = self._ring.geometry(self.cfg)
        win_x = np.asarray(self.state.win[x])
        cold_x = np.asarray(self.state.cold[:, x])
        t = int(self.state.step)
        first_gw = t * g.ow - g.ww
        win_ring0 = first_gw % g.rw
        words = cold_x.copy()
        for w in range(g.ww):
            words[(win_ring0 + w) % g.rw] = win_x[w]
        return np.unpackbits(words.astype("<u4").view(np.uint8),
                             bitorder="little").astype(bool)

    def _fresh_updates(self, row: int,
                       origin: int) -> tuple[codec.WireUpdate, ...]:
        cur = self._resolved_row(row)
        prev = self._prev_rows.get(row)
        self._prev_rows[row] = cur
        fresh = cur if prev is None else (cur & ~prev)
        out = []
        for sl in np.nonzero(fresh)[0].tolist()[:255]:
            subj = int(self._subject[sl])
            if subj < 0:
                continue
            k = int(self._rkey[sl])
            out.append(codec.WireUpdate(
                member=subj, status=Status(key_status(k)),
                incarnation=key_incarnation(k), addr=("sim", subj),
                origin=origin))
        return tuple(out)

    # ------------------------------------------------------------ reports

    def findings(self) -> list[Finding]:
        with self._lock:
            return list(self._findings)

    def report(self) -> dict:
        """Point-in-time session stats — the expo.render_sessions /
        SESSION_GAUGES input."""
        with self._lock:
            sessions = [{"row": c.row,
                         "clock_lag_periods": self.t - c.last_ack_t}
                        for c in self._clients.values()]
            return {"nodes": self.n,
                    "periods": self.t,
                    "frontend": self.frontend.kind,
                    "admitted": self._stats["admitted"],
                    "evicted": self._stats["evicted"],
                    "left": self._stats["left"],
                    "active": len(self._clients),
                    "rejected_full": self._stats["rejected_full"],
                    "queue_drops": self._stats["queue_drops"],
                    "mirror_updates": self._stats["mirror_updates"],
                    "mirror_bytes": self._stats["mirror_bytes"],
                    "mirror_bytes_per_period": 16 * self.ext_capacity,
                    "mirror_spill_slots":
                        self._stats["mirror_spill_slots"],
                    "mirror_spill_periods":
                        self._stats["mirror_spill_periods"],
                    "datagrams": self._stats["datagrams"],
                    "echoes": self._stats["echoes"],
                    "sessions": sessions}
