"""Serving hub: thousands of concurrent external cores on one cluster.

`swim_tpu.serve` is the scale-out sibling of `bridge/engine_server.py`:
where the bridge locksteps a handful of TCP sessions behind a
min-over-clocks barrier (one slow client stalls the world), the hub
(serve/hub.py) runs the ring engine FREE of any client barrier and
admits/evicts sessions asynchronously over a datagram frontend — the
udppump epoll datapath when the native toolchain is present, a plain
Python UDP socket otherwise.  serve/load.py is the 10^3..10^4-client
load harness behind `swim-tpu serve bench` / `bench.py --tier serve`.
"""

from swim_tpu.serve.hub import EXT_CAPACITY, SESSION_GAUGES, ServeHub

__all__ = ["EXT_CAPACITY", "SESSION_GAUGES", "ServeHub"]
