"""Scalar (numpy) twin of the ring engine — the bitwise gold standard.

Implements swim_tpu/models/ring.py's documented semantics — rotor waves,
word recycling, dissemination floor, top-C views, sentinel expiry, fresh-
lane allocation — in deliberately plain numpy, phase by phase, consuming
the SAME RingRandomness tensors, so tests/test_ring.py can require
bitwise-equal RingState trajectories in every regime (crash, loss,
partition, join, Lifeguard).  Deliberately unoptimized: clarity is the
point; it runs at N ≤ a few hundred.

The one structural liberty: per-node heard-bits are a bool matrix
`knows[N, R]` over ring slots instead of packed win/cold words; the
engine's (win, cold) pair is reconstructed for comparison by
`packed_state()`.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from swim_tpu.config import SwimConfig
from swim_tpu.models.ring import (WORD, RingGeometry, RingRandomness,
                                  geometry)
from swim_tpu.models.rumor import dynamic_timeout_py
from swim_tpu.sim.faults import FaultPlan, to_numpy
from swim_tpu.types import Status, key_incarnation, key_status, opinion_key


def _is_suspect(key: int) -> bool:
    return key_status(key) == Status.SUSPECT


def _is_dead(key: int) -> bool:
    return key_status(key) == Status.DEAD


@dataclasses.dataclass
class OracleRingState:
    knows: np.ndarray      # bool[N, R] heard-bits by ring slot
    inc_self: np.ndarray   # u32[N]
    lha: np.ndarray        # i32[N]
    gone_key: np.ndarray   # u32[N]
    subject: np.ndarray    # i32[R]
    rkey: np.ndarray       # u32[R]
    birth0: np.ndarray     # i32[R]
    sent_node: np.ndarray  # i32[R, S]
    sent_time: np.ndarray  # i32[R, S]
    confirmed: np.ndarray  # bool[R]
    overflow: int
    index_overflow: int
    step: int


class RingOracle:
    def __init__(self, cfg: SwimConfig, plan: FaultPlan):
        self.cfg = cfg
        self.g: RingGeometry = geometry(cfg)
        self.plan = to_numpy(plan)
        n, r, s = cfg.n_nodes, self.g.rw * WORD, cfg.sentinels
        self.state = OracleRingState(
            knows=np.zeros((n, r), bool),
            inc_self=np.zeros(n, np.uint32),
            lha=np.zeros(n, np.int32),
            gone_key=np.zeros(n, np.uint32),
            subject=np.full(r, -1, np.int32),
            rkey=np.zeros(r, np.uint32),
            birth0=np.zeros(r, np.int32),
            sent_node=np.full((r, s), -1, np.int32),
            sent_time=np.zeros((r, s), np.int32),
            confirmed=np.zeros(r, bool),
            overflow=0, index_overflow=0, step=0,
        )

    # ------------------------------------------------------------- helpers

    def _ring_col(self, gword: int) -> int:
        return int(np.mod(gword, self.g.rw))

    def _lane_slots(self, gword0: int) -> list[int]:
        """Ring slots of OB consecutive lanes starting at global word 0."""
        out = []
        for la in range(self.g.ow * WORD):
            out.append(self._ring_col(gword0 + la // WORD) * WORD
                       + la % WORD)
        return out

    # ---------------------------------------------------------------- step

    def step(self, rnd: RingRandomness) -> OracleRingState:
        cfg, g, st, plan = self.cfg, self.g, self.state, self.plan
        n, k = cfg.n_nodes, cfg.k_indirect
        r_tot, s_cap = g.rw * WORD, cfg.sentinels
        ob = g.ow * WORD
        t = st.step
        crashed = t >= plan.crash_step
        joined = t >= plan.join_step
        active = ~crashed & joined
        part_on = bool(plan.partition_start <= t < plan.partition_end)
        live_total = int(active.sum())
        loss = float(plan.loss)
        pid = plan.partition_id

        s_off = int(np.asarray(rnd.s_off))
        q_off = [int(x) for x in np.asarray(rnd.q_off)]
        u = {name: np.asarray(getattr(rnd, name))
             for name in ("loss_w1", "loss_w2", "loss_w3", "loss_w4",
                          "loss_w5", "loss_w6", "lha_u")}

        entry_gw0 = t * g.ow - g.ww
        fresh_gw0 = t * g.ow

        # --- Phase 0a: judge outgoing lanes --------------------------------
        # All decisions are made against the ENTRY-state table (the engine
        # evaluates glob_refuted/dissemination vectorized over the
        # unmodified state), so snapshot before applying any frees.
        out_slots = self._lane_slots(entry_gw0)
        entry_subject = st.subject.copy()
        entry_rkey = st.rkey.copy()
        entry_gone = st.gone_key.copy()
        carry = np.zeros(ob, bool)
        for la, sl in enumerate(out_slots):
            if entry_subject[sl] < 0:
                continue
            knowers = int((st.knows[:, sl] & active).sum())
            dissem = knowers >= live_total
            in_budget = (t - int(st.birth0[sl])) < g.spread
            key = int(entry_rkey[sl])
            sub = int(entry_subject[sl])
            refuted = bool(
                ((entry_subject == sub) & (entry_subject >= 0)
                 & (entry_rkey > key)).any()) or int(entry_gone[sub]) > key
            pending = (_is_suspect(key) and not st.confirmed[sl]
                       and not refuted)
            if not dissem and in_budget:
                carry[la] = True
            elif pending:
                pass                              # keep at the cold slot
            else:
                if dissem:
                    st.gone_key[sub] = max(st.gone_key[sub],
                                           np.uint32(key))
                elif _is_dead(key):
                    st.overflow += 1              # lost death certificate
                st.subject[sl] = -1

        # --- Phase 0b: invalidate previous generation of fresh lanes -------
        fresh_slots = self._lane_slots(fresh_gw0)
        for sl in fresh_slots:
            if st.subject[sl] < 0:
                continue
            knowers = int((st.knows[:, sl] & active).sum())
            sub = int(st.subject[sl])
            if knowers >= live_total:
                st.gone_key[sub] = max(st.gone_key[sub], st.rkey[sl])
            st.subject[sl] = -1

        # --- Phase 0c: move carried lanes ----------------------------------
        for la in range(ob):
            if not carry[la]:
                continue
            src, dst = out_slots[la], fresh_slots[la]
            st.subject[dst] = st.subject[src]
            st.rkey[dst] = st.rkey[src]
            st.birth0[dst] = st.birth0[src]
            st.confirmed[dst] = st.confirmed[src]
            st.sent_node[dst] = st.sent_node[src]
            st.sent_time[dst] = st.sent_time[src]
            st.knows[:, dst] = st.knows[:, src]
            st.subject[src] = -1
            # the old column's bits stay (the engine's flush writes the
            # full outgoing column to cold; freed slots' bits are stale
            # by contract and never consulted)
        for la in range(ob):                      # fresh non-carried: clean
            if not carry[la]:
                sl = fresh_slots[la]
                st.sent_node[sl] = -1
                st.sent_time[sl] = 0
                st.confirmed[sl] = False
                st.knows[:, sl] = False

        # --- per-subject top-C index (R3) ----------------------------------
        used = st.subject >= 0
        top = {s: [] for s in range(n)}           # subject -> [(key, slot)]
        for sl in np.nonzero(used)[0]:
            top[int(st.subject[sl])].append((int(st.rkey[sl]), int(sl)))
        top_c = {}
        sus_best = {}
        for s, entries in top.items():
            if not entries:
                continue
            entries.sort(key=lambda e: (-e[0], -e[1]))
            top_c[s] = entries[:g.c]
            if len(entries) > g.c:
                st.index_overflow += 1
            sus = [(kk, sl) for kk, sl in entries if _is_suspect(kk)]
            if sus:
                sus_best[s] = max(sus, key=lambda e: (e[0], e[1]))

        def knows_bit(node: int, slot: int) -> bool:
            return slot >= 0 and bool(st.knows[node, slot])

        def view_of(node: int, subj: int) -> int:
            best = max(opinion_key(Status.ALIVE, 0), int(st.gone_key[subj]))
            for kk, sl in top_c.get(subj, []):
                if knows_bit(node, sl):
                    best = max(best, kk)
            return best

        # --- Phases A+B: rotor waves ---------------------------------------
        window_slots = []
        first_gw = entry_gw0 + g.ow
        for w in range(g.ww):
            col = self._ring_col(first_gw + w)
            for b in range(WORD):
                window_slots.append(col * WORD + b)

        # Deviation R5 (docs/PROTOCOL.md): in "period" selection scope the
        # piggyback selection AND buddy knowledge are evaluated against a
        # start-of-period snapshot of the heard-bits; deliveries still
        # write st.knows live.  In "wave" scope sel_knows aliases st.knows,
        # so every wave's selection sees earlier waves' deliveries (exact
        # SWIM semantics).
        sel_knows = (st.knows.copy() if cfg.ring_sel_scope == "period"
                     else st.knows)

        def select_b(node: int) -> list[int]:
            """First-B transmissible window slots known to node, newest
            word first, LSB first within a word."""
            picked = []
            for w in range(g.ww - 1, -1, -1):
                for b in range(WORD):
                    sl = window_slots[w * WORD + b]
                    if (st.subject[sl] >= 0 and sel_knows[node, sl]):
                        picked.append(sl)
                        if len(picked) >= min(cfg.max_piggyback,
                                              g.ww * WORD):
                            return picked
            return picked

        def buddy(node: int, subj: int) -> list[int]:
            if not (cfg.lifeguard and cfg.buddy):
                return []
            e = sus_best.get(subj)
            if (e and e[1] >= 0 and bool(sel_knows[node, e[1]])
                    and e[1] in window_slots):
                return [e[1]]
            return []

        # integer loss threshold, mirroring the engine exactly: the
        # loss_w*/lha_u tensors carry raw u16 draws and delivery is
        # bits >= ceil(loss*65536) (see ring.RingRandomness)
        loss_thr = int(np.ceil(np.float32(loss) * np.float32(65536.0)))

        def delivered(src: int, dst: int, uu: int) -> bool:
            if not (active[src] and active[dst]):
                return False
            if part_on and pid[src] != pid[dst]:
                return False
            return uu >= loss_thr

        lha = st.lha.copy()
        if cfg.ring_probe == "rotor":
            # W1 + W2 (selection state mutates between waves, so evaluate
            # all of a wave's selections BEFORE any of its deliveries)
            tgt = [(i + s_off) % n for i in range(n)]
            # a not-yet-joined target: in nobody's membership list
            prober_mask = active & joined[np.asarray(tgt)]
            w1_payload = {}
            for i in range(n):
                if prober_mask[i]:
                    w1_payload[i] = select_b(i) + buddy(i, tgt[i])
            ok1 = np.zeros(n, bool)               # indexed by receiver j
            for j in range(n):
                i = (j - s_off) % n
                if i in w1_payload and delivered(i, j,
                                                 int(u["loss_w1"][j])):
                    ok1[j] = True
            for j in np.nonzero(ok1)[0]:
                for sl in w1_payload[(j - s_off) % n]:
                    st.knows[j, sl] = True

            w2_payload = {}
            for j in np.nonzero(ok1)[0]:
                w2_payload[int(j)] = select_b(int(j))
            ok2 = np.zeros(n, bool)               # indexed by receiver i
            for i in range(n):
                j = (i + s_off) % n
                if j in w2_payload and delivered(j, i,
                                                 int(u["loss_w2"][i])):
                    ok2[i] = True
            for i in np.nonzero(ok2)[0]:
                for sl in w2_payload[(i + s_off) % n]:
                    st.knows[i, sl] = True
            acked = ok2 & prober_mask

            need = prober_mask & ~acked
            relayed = np.zeros(n, bool)
            for a in range(k):
                q = q_off[a]
                d4 = s_off - q
                # W3
                p3 = {i: select_b(i) for i in range(n) if need[i]}
                ok3 = np.zeros(n, bool)           # by receiver p
                for p in range(n):
                    i = (p - q) % n
                    if i in p3 and delivered(i, p,
                                             int(u["loss_w3"][p, a])):
                        ok3[p] = True
                for p in np.nonzero(ok3)[0]:
                    for sl in p3[(p - q) % n]:
                        st.knows[p, sl] = True
                # W4
                p4 = {}
                for p in np.nonzero(ok3)[0]:
                    jj = (p + d4) % n
                    p4[int(p)] = select_b(int(p)) + buddy(int(p), jj)
                ok4 = np.zeros(n, bool)           # by receiver j
                for j in range(n):
                    p = (j - d4) % n
                    if p in p4 and delivered(p, j,
                                             int(u["loss_w4"][j, a])):
                        ok4[j] = True
                for j in np.nonzero(ok4)[0]:
                    for sl in p4[(j - d4) % n]:
                        st.knows[j, sl] = True
                # W5
                p5 = {int(j): select_b(int(j))
                      for j in np.nonzero(ok4)[0]}
                ok5 = np.zeros(n, bool)           # by receiver p
                for p in range(n):
                    j = (p + d4) % n
                    if j in p5 and delivered(j, p,
                                             int(u["loss_w5"][p, a])):
                        ok5[p] = True
                for p in np.nonzero(ok5)[0]:
                    for sl in p5[(p + d4) % n]:
                        st.knows[p, sl] = True
                # W6
                p6 = {int(p): select_b(int(p))
                      for p in np.nonzero(ok5)[0]}
                ok6 = np.zeros(n, bool)           # by receiver i
                for i in range(n):
                    p = (i + q) % n
                    if p in p6 and delivered(p, i,
                                             int(u["loss_w6"][i, a])):
                        ok6[i] = True
                for i in np.nonzero(ok6)[0]:
                    for sl in p6[(i + q) % n]:
                        st.knows[i, sl] = True
                relayed |= ok6 & need

            probe_ok = acked | relayed
            failed = prober_mask & ~probe_ok
            s_probe = st.lha.copy()
            if cfg.lifeguard:
                for i in range(n):
                    if prober_mask[i]:    # idle periods leave LHA alone
                        lha[i] = min(max(lha[i] + (1 if failed[i] else -1),
                                         0), cfg.lha_max)
                for i in range(n):
                    if failed[i] and not (int(u["lha_u"][i])
                                          * (1 + int(s_probe[i])) < 65536):
                        failed[i] = False
            susp_sub = list(tgt)
            susp_org = list(range(n))
            view_rows = list(range(n))
        else:
            # pull-uniform mode: mirror of ring.py's pull branch
            # (deviations P1-P4 there), same operation order: all
            # selections precomputed, all deliveries applied, THEN views.
            from swim_tpu.models.ring import PULL_SRC_ATTEMPTS, py_pow_f32

            pr = rnd.pull
            m_u = np.asarray(pr.m_u)
            src_u = np.asarray(pr.src_u)
            d_fwd = np.asarray(pr.d_fwd)
            d_back = np.asarray(pr.d_back)
            px_u = np.asarray(pr.px_u)
            px_fwd = np.asarray(pr.px_fwd)
            px_back = np.asarray(pr.px_back)
            ack_u = np.asarray(pr.ack_u)
            ack_leg = np.asarray(pr.ack_leg)
            members_i = int(joined.sum())
            denom = np.float32(max(members_i - 1, 1))
            base0 = float(np.float32(np.float32(1.0)
                                     - np.float32(1.0) / denom))
            lf = np.float32(loss)
            thr2 = np.float32(1.0) - (np.float32(1.0) - lf) * (
                np.float32(1.0) - lf)
            sel_cache = {i: select_b(i) for i in range(n)}
            live_total_i = int(active.sum())

            def draw_id(j: int, uu) -> int:
                idx = int(np.float32(uu) * np.float32(n - 1))
                idx = min(idx, n - 2)
                return idx + (1 if idx >= j else 0)

            def cut(a_id: int, b_id: int) -> bool:
                return part_on and pid[a_id] != pid[b_id]

            failed = np.zeros(n, bool)
            src_arr = np.zeros(n, np.int32)
            deliveries: list[tuple[int, int]] = []   # (dst, sender)
            for j in range(n):
                ljj = live_total_i - (1 if active[j] else 0)
                if members_i >= 2:
                    p0j = np.float32(py_pow_f32(base0, max(ljj, 0)))
                else:
                    p0j = np.float32(1.0)
                probed = (np.float32(m_u[j]) >= p0j) and joined[j]
                src = draw_id(j, src_u[j, 0])
                src_ok = bool(active[src])
                for a in range(1, PULL_SRC_ATTEMPTS):
                    nxt = draw_id(j, src_u[j, a])
                    if not src_ok:
                        src = nxt
                    src_ok = src_ok or bool(active[nxt])
                src_arr[j] = src
                probe_live = probed and src_ok
                d_ok = (probe_live and active[j] and not cut(src, j)
                        and np.float32(d_fwd[j]) >= lf)
                if d_ok:
                    deliveries.append((j, src))
                acked_lane = d_ok and np.float32(d_back[j]) >= lf
                need = probe_live and not acked_lane
                relayed_lane = False
                px_deliver = False
                px_src = 0
                for b in range(k):
                    p_b = draw_id(j, px_u[j, b])
                    path_up = (need and active[p_b] and not cut(src, p_b)
                               and not cut(p_b, j))
                    w4_ok = (path_up and active[j]
                             and np.float32(px_fwd[j, b]) >= thr2)
                    if w4_ok and not px_deliver:
                        px_src = p_b
                        px_deliver = True
                    if w4_ok and np.float32(px_back[j, b]) >= thr2:
                        relayed_lane = True
                if px_deliver:
                    deliveries.append((j, px_src))
                aq = draw_id(j, ack_u[j])
                if (active[j] and active[aq] and not cut(j, aq)
                        and np.float32(ack_leg[j]) >= thr2):
                    deliveries.append((j, aq))
                failed[j] = probe_live and not (acked_lane or relayed_lane)
            for dst, sender in deliveries:
                for sl in sel_cache[sender]:
                    st.knows[dst, sl] = True
            susp_sub = list(range(n))
            susp_org = [int(x) for x in src_arr]
            view_rows = [int(x) for x in src_arr]

        # --- Phase C: suspicion verdicts (views read post-delivery) ---------
        mk_suspect = np.zeros(n, bool)
        re_suspect = np.zeros(n, bool)
        susp_key = np.zeros(n, np.uint32)
        for i in range(n):
            if not failed[i]:
                continue
            vk = view_of(view_rows[i], susp_sub[i])
            stt = key_status(vk)
            if stt == Status.ALIVE:
                mk_suspect[i] = True
            elif stt == Status.SUSPECT:
                re_suspect[i] = True
            susp_key[i] = opinion_key(Status.SUSPECT, key_incarnation(vk))

        refute = np.zeros(n, bool)
        new_inc = st.inc_self.copy()
        for i in range(n):
            if not active[i]:
                continue
            e = sus_best.get(i)
            if e and knows_bit(i, e[1]) \
                    and e[0] > opinion_key(Status.ALIVE,
                                           int(st.inc_self[i])):
                refute[i] = True
                new_inc[i] = np.uint32(key_incarnation(e[0]) + 1)
                if cfg.lifeguard:
                    lha[i] = min(lha[i] + 1, cfg.lha_max)

        # sentinel expiry
        confirm = np.zeros(r_tot, bool)
        conf_node = np.zeros(r_tot, np.int32)
        for sl in np.nonzero(st.subject >= 0)[0]:
            key = int(st.rkey[sl])
            if not _is_suspect(key) or st.confirmed[sl]:
                continue
            sub = int(st.subject[sl])
            dead_key = opinion_key(Status.DEAD, key_incarnation(key))
            if dead_key <= int(st.gone_key[sub]):
                continue
            filled = int((st.sent_node[sl] >= 0).sum())
            if cfg.lifeguard and cfg.dynamic_suspicion:
                tout = dynamic_timeout_py(cfg, filled)
            else:
                tout = cfg.suspicion_periods
            for si in range(s_cap):
                nd = int(st.sent_node[sl, si])
                if nd < 0 or plan.crash_step[nd] <= t:
                    continue
                if t < int(st.sent_time[sl, si]) + tout:
                    continue
                hk = int(st.gone_key[sub]) > key
                for kk, osl in top_c.get(sub, []):
                    if kk > key and knows_bit(nd, osl):
                        hk = True
                        break
                if not hk:
                    confirm[sl] = True
                    conf_node[sl] = nd
                    break

        # --- Phase D: new originations -------------------------------------
        cands = []                                # (subj, key, orig, srcslot,
        #                                            is_susp)
        for sl in np.nonzero(confirm)[0]:
            cands.append((int(st.subject[sl]),
                          opinion_key(Status.DEAD,
                                      key_incarnation(int(st.rkey[sl]))),
                          int(conf_node[sl]), int(sl), False))
        for i in range(n):
            if refute[i]:
                cands.append((i, opinion_key(Status.ALIVE, int(new_inc[i])),
                              i, -1, False))
        for i in range(n):
            if mk_suspect[i] or re_suspect[i]:
                cands.append((susp_sub[i], int(susp_key[i]),
                              susp_org[i], -1, True))
        total = len(cands)
        cands = cands[:ob]
        self.state.overflow = st.overflow + max(total - ob, 0)
        st.overflow = self.state.overflow

        free_lanes = [la for la in range(ob) if not carry[la]]
        seen = {}
        alloc_i = 0
        placements = []                           # (cand, slot, fresh?)
        for cand in cands:
            subj, key, orig, srcslot, is_susp = cand
            if (subj, key) in seen:
                placements.append((cand, seen[(subj, key)], False))
                continue
            existing = np.nonzero((st.subject == subj)
                                  & (st.rkey == np.uint32(key)))[0]
            if existing.size:
                sl = int(existing[0])
                seen[(subj, key)] = sl
                placements.append((cand, sl, False))
                continue
            if alloc_i < len(free_lanes):
                sl = fresh_slots[free_lanes[alloc_i]]
                alloc_i += 1
                seen[(subj, key)] = sl
                placements.append((cand, sl, True))
            else:
                st.overflow += 1

        for (subj, key, orig, srcslot, is_susp), sl, fresh in placements:
            if fresh:
                st.subject[sl] = subj
                st.rkey[sl] = np.uint32(key)
                st.birth0[sl] = t
                st.confirmed[sl] = False
                st.sent_node[sl] = -1
                st.sent_time[sl] = 0
                st.knows[:, sl] = False
                st.knows[orig, sl] = True
            if is_susp and st.subject[sl] >= 0:
                row = st.sent_node[sl]
                if orig not in row[row >= 0]:
                    fill = int((row >= 0).sum())
                    if fill < s_cap:
                        st.sent_node[sl, fill] = orig
                        st.sent_time[sl, fill] = t
            if (not is_susp) and srcslot >= 0:
                st.confirmed[srcslot] = True

        for i in range(n):
            if active[i]:
                st.inc_self[i] = new_inc[i]
                st.lha[i] = lha[i]
        st.step = t + 1
        return st

    # ------------------------------------------------------- comparison

    def packed_state(self):
        """(win, cold) u32 arrays equivalent to the engine's packing."""
        st, g = self.state, self.g
        n = self.cfg.n_nodes
        t = st.step
        first_gw = t * g.ow - g.ww
        win = np.zeros((n, g.ww), np.uint32)
        win_cols = set()
        for w in range(g.ww):
            col = self._ring_col(first_gw + w)
            win_cols.add(col)
            for b in range(WORD):
                sl = col * WORD + b
                win[:, w] |= (st.knows[:, sl].astype(np.uint32) << b)
        cold = np.zeros((n, g.rw), np.uint32)
        for col in range(g.rw):
            for b in range(WORD):
                sl = col * WORD + b
                cold[:, col] |= (st.knows[:, sl].astype(np.uint32) << b)
        return win, cold, sorted(win_cols)
