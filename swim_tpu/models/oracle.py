"""Scalar SWIM oracle — the readable, testable gold standard.

Implements exactly the period-synchronous protocol of docs/PROTOCOL.md in
plain Python + NumPy, one message at a time. The vectorized dense engine
(swim_tpu/models/dense.py) must produce *bitwise identical* state given the
same `PeriodRandomness` tensors; tests/test_dense_vs_oracle.py enforces it.

Deliberately unoptimized: clarity over speed (usable to a few hundred nodes).
Views are stored as packed lattice keys (swim_tpu/types.opinion_key) so state
comparison with the engines is a plain array equality.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from swim_tpu.config import SwimConfig
from swim_tpu.sim.faults import FaultPlan
from swim_tpu.types import Status, key_incarnation, key_status, opinion_key
from swim_tpu.utils.prng import PeriodRandomness

NO_DEADLINE = np.int32(2**31 - 1)


@dataclasses.dataclass
class OracleState:
    """Full simulator state after some number of periods."""

    key: np.ndarray         # u32[N, N] — key[i, j]: i's opinion of j
    retransmit: np.ndarray  # i32[N, N] — gossip sends of i's update about j
    deadline: np.ndarray    # i32[N, N] — suspicion expiry period (NO_DEADLINE)
    lha: np.ndarray         # i32[N]    — Lifeguard local health score
    step: int               # periods completed


def init_state(cfg: SwimConfig) -> OracleState:
    n = cfg.n_nodes
    return OracleState(
        key=np.full((n, n), opinion_key(Status.ALIVE, 0), np.uint32),
        # Counters start at the limit: the initial full-alive view is common
        # knowledge and is not gossiped (matches a converged cluster).
        retransmit=np.full((n, n), cfg.retransmit_limit, np.int32),
        deadline=np.full((n, n), NO_DEADLINE, np.int32),
        lha=np.zeros((n,), np.int32),
        step=0,
    )


def _select_uniform(u: np.float32, candidates: list[int]) -> int:
    """Pick candidates[floor(u * c)] — float32 math to match the engine."""
    c = len(candidates)
    idx = int(np.float32(u) * np.float32(c))
    return candidates[min(idx, c - 1)]


class Oracle:
    """Drives OracleState one protocol period at a time."""

    def __init__(self, cfg: SwimConfig, plan: FaultPlan):
        from swim_tpu.sim import faults as _faults

        self.cfg = cfg
        self.plan = _faults.to_numpy(plan)
        self.state = init_state(cfg)

    # -- fault model -------------------------------------------------------

    def crashed(self, i: int, t: int) -> bool:
        return t >= int(self.plan.crash_step[i])

    def joined(self, i: int, t: int) -> bool:
        return t >= int(self.plan.join_step[i])

    def active(self, i: int, t: int) -> bool:
        return self.joined(i, t) and not self.crashed(i, t)

    def delivered(self, src: int, dst: int, t: int, u_loss: float) -> bool:
        if not (self.active(src, t) and self.active(dst, t)):
            return False
        p = self.plan
        if (int(p.partition_start) <= t < int(p.partition_end)
                and int(p.partition_id[src]) != int(p.partition_id[dst])):
            return False
        return np.float32(u_loss) >= np.float32(p.loss)

    # -- gossip ------------------------------------------------------------

    def piggyback_selection(self, sender: int, forced: int = -1) -> list[int]:
        """Subjects piggybacked on each of `sender`'s messages this wave.

        Eligible updates (retransmit counter below the limit), fewest
        retransmissions first, ties by subject id; at most B. Lifeguard's
        buddy system can force one subject in ahead of the ranking.
        """
        st, cfg = self.state, self.cfg
        eligible = [j for j in range(cfg.n_nodes)
                    if st.retransmit[sender, j] < cfg.retransmit_limit]
        eligible.sort(key=lambda j: (int(st.retransmit[sender, j]), j))
        sel = eligible[:cfg.max_piggyback]
        if forced >= 0 and forced not in sel:
            sel = [forced] + sel[:cfg.max_piggyback - 1]
        return sel

    def _merge_update(self, dst: int, subject: int, new_key: int, t: int):
        """Lattice-join one received update into dst's view."""
        st, cfg = self.state, self.cfg
        old = int(st.key[dst, subject])
        if int(new_key) <= old:
            return
        st.key[dst, subject] = np.uint32(new_key)
        st.retransmit[dst, subject] = 0  # new information → re-gossip it
        new_status = key_status(int(new_key))
        if new_status == Status.SUSPECT:
            # Everyone who learns of a suspicion starts (or restarts, for a
            # higher incarnation) a suspicion timer — whoever expires first
            # gossips the death.
            st.deadline[dst, subject] = t + self._suspicion_periods(dst)
        else:
            st.deadline[dst, subject] = NO_DEADLINE

    def _suspicion_periods(self, node: int) -> int:
        # Vanilla timeout. Lifeguard's dynamic-suspicion shortening (by
        # independent confirmations) lands with the Lifeguard milestone and
        # must stay in lockstep with the dense engine.
        return self.cfg.suspicion_periods

    # -- one protocol period ----------------------------------------------

    def step(self, rnd: PeriodRandomness) -> None:
        from swim_tpu.utils import prng as _prng

        st, cfg = self.state, self.cfg
        n, k, t = cfg.n_nodes, cfg.k_indirect, st.step
        rnd = _prng.to_numpy(rnd)
        up = [i for i in range(n) if self.active(i, t)]

        # ---- Phase A: all random choices (docs/PROTOCOL.md §4) ----
        from swim_tpu.ops.sampling import py_round_robin_target

        rr = cfg.target_selection == "round_robin"
        epoch, pos = divmod(t, n - 1)
        target = {}
        proxies = {}
        for i in up:
            # not-yet-joined nodes are in nobody's membership list
            cands = [j for j in range(n)
                     if j != i and key_status(int(st.key[i, j])) != Status.DEAD
                     and self.joined(j, t)]
            if rr:
                # §4.3 round-robin walks the node's per-epoch Feistel
                # shuffle; believed-dead targets probed, fail fast; a
                # not-yet-joined target means an idle period
                ti = py_round_robin_target(i, epoch, pos, n)
                if not self.joined(ti, t):
                    continue
            else:
                if not cands:
                    continue
                ti = _select_uniform(rnd.target_u[i], cands)
            target[i] = ti
            cands2 = [j for j in cands if j != ti]
            if cands2:
                proxies[i] = [_select_uniform(rnd.proxy_u[i, s], cands2)
                              for s in range(k)]
            else:
                proxies[i] = []

        # ---- Waves. Each wave: selections from wave-start state, all
        # deliveries merged at wave end (the lattice join commutes). ----

        def run_wave(messages):
            """messages: list of (src, dst, u_loss, forced_subject)."""
            # selections & counter increments from wave-start state
            sends = []
            for src, dst, u_loss, forced in messages:
                sel = self.piggyback_selection(src, forced)
                payload = [(j, int(st.key[src, j])) for j in sel]
                ok = self.delivered(src, dst, t, u_loss)
                sends.append((src, dst, ok, payload, sel))
            # counters advance for every *sent* message (delivered or not)
            for src, dst, ok, payload, sel in sends:
                for j in sel:
                    st.retransmit[src, j] += 1
            # deliveries merge at wave end
            for src, dst, ok, payload, sel in sends:
                if ok:
                    for j, kj in payload:
                        self._merge_update(dst, j, kj, t)
            return sends

        def buddy_subject(src: int, dst: int) -> int:
            """Force-include dst's suspect update when pinging it (Lifeguard)."""
            if (cfg.lifeguard and cfg.buddy
                    and key_status(int(st.key[src, dst])) == Status.SUSPECT):
                return dst
            return -1

        # W1: direct pings i → T(i)
        w1 = run_wave([(i, target[i], rnd.loss_w1[i], buddy_subject(i, target[i]))
                       for i in sorted(target)])
        got_ping = {}
        for src, dst, ok, *_ in w1:
            if ok:
                got_ping.setdefault(dst, []).append(src)

        # W2: acks T(i) → i for every ping that arrived
        w2 = run_wave([(dst, src, rnd.loss_w2[src], -1)
                       for dst in sorted(got_ping) for src in got_ping[dst]])
        acked = {src for _, src, ok, *_ in w2 if ok}

        # W3: ping-req fan-out from probers whose direct ack did not arrive
        need_indirect = [i for i in sorted(target)
                         if i not in acked and proxies[i]]
        w3_msgs, w3_tag = [], []
        for i in need_indirect:
            for s in range(k):
                w3_msgs.append((i, proxies[i][s], rnd.loss_w3[i, s], -1))
                w3_tag.append((i, s))
        w3 = run_wave(w3_msgs)
        w3_ok = {tag: m[2] for tag, m in zip(w3_tag, w3)}

        # W4: proxies probe the target on the requester's behalf
        w4_msgs, w4_tag = [], []
        for i in need_indirect:
            for s in range(k):
                if w3_ok[(i, s)]:
                    w4_msgs.append((proxies[i][s], target[i],
                                    rnd.loss_w4[i, s],
                                    buddy_subject(proxies[i][s], target[i])))
                    w4_tag.append((i, s))
        w4 = run_wave(w4_msgs)
        w4_ok = {tag: m[2] for tag, m in zip(w4_tag, w4)}

        # W5: target acks each proxy whose ping arrived
        w5_msgs, w5_tag = [], []
        for (i, s), ok in w4_ok.items():
            if ok:
                w5_msgs.append((target[i], proxies[i][s], rnd.loss_w5[i, s], -1))
                w5_tag.append((i, s))
        w5 = run_wave(w5_msgs)
        w5_ok = {tag: m[2] for tag, m in zip(w5_tag, w5)}

        # W6: proxies relay the ack back to the requester
        w6_msgs, w6_tag = [], []
        for (i, s), ok in w5_ok.items():
            if ok:
                w6_msgs.append((proxies[i][s], i, rnd.loss_w6[i, s], -1))
                w6_tag.append((i, s))
        w6 = run_wave(w6_msgs)
        relayed = {i for (i, s), m in zip(w6_tag, w6) if m[2]}

        # ---- End of period bookkeeping (docs/PROTOCOL.md §3) ----

        # 1. probe verdicts (health S read at probe time, updated after)
        for i in sorted(target):
            ok = (i in acked) or (i in relayed)
            s_probe = int(st.lha[i])
            if cfg.lifeguard:
                # LHA score: failed round raises S, clean round lowers it.
                s_new = s_probe + (1 if not ok else -1)
                st.lha[i] = np.int32(min(max(s_new, 0), cfg.lha_max))
            if ok:
                continue
            if cfg.lifeguard:
                # LHA probe thinning: unhealthy nodes are proportionally less
                # likely to raise a suspicion this period (PROTOCOL.md §7).
                if not (np.float32(rnd.lha_u[i])
                        < np.float32(1.0) / np.float32(1 + s_probe)):
                    continue
            tgt = target[i]
            cur = int(st.key[i, tgt])
            if key_status(cur) == Status.ALIVE:
                v = key_incarnation(cur)
                self._merge_update(i, tgt, opinion_key(Status.SUSPECT, v), t)

        # 2. refutation: a live node that sees itself suspected bumps its
        #    incarnation and gossips the new ALIVE.
        for j in up:
            cur = int(st.key[j, j])
            if key_status(cur) == Status.SUSPECT:
                v = key_incarnation(cur)
                st.key[j, j] = np.uint32(opinion_key(Status.ALIVE, v + 1))
                st.retransmit[j, j] = 0
                st.deadline[j, j] = NO_DEADLINE
                if cfg.lifeguard:
                    st.lha[j] = np.int32(min(int(st.lha[j]) + 1, cfg.lha_max))

        # 3. suspicion expiry → declare dead, gossip the confirm
        for i in up:
            for j in range(n):
                if (key_status(int(st.key[i, j])) == Status.SUSPECT
                        and int(st.deadline[i, j]) <= t):
                    v = key_incarnation(int(st.key[i, j]))
                    st.key[i, j] = np.uint32(opinion_key(Status.DEAD, v))
                    st.retransmit[i, j] = 0
                    st.deadline[i, j] = NO_DEADLINE

        st.step = t + 1

    def run(self, key, periods: int) -> OracleState:
        from swim_tpu.utils import prng

        for _ in range(periods):
            self.step(prng.to_numpy(
                prng.draw_period(key, self.state.step, self.cfg)))
        return self.state
