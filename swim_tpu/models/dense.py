"""Dense vectorized SWIM engine — exact O(N²)-state simulation in one jit step.

The whole cluster's protocol period — target sampling, six message waves,
suspicion, refutation, expiry (docs/PROTOCOL.md) — executes as one traced
JAX program over [N, N] tensors:

  * view keys `u32[N, N]` merge by scatter-max (the lattice join commutes,
    so a wave's deliveries need no ordering),
  * piggyback selection is a per-row top-B over (retransmit_count, subject),
  * message delivery is gather (payload from sender rows) + scatter (into
    receiver rows), with crash/partition/loss as multiplicative masks.

No data-dependent control flow: every wave always "runs" with boolean sent
masks, which is what lets XLA compile a single static program and fuse the
elementwise fault masks into the scatters.

Contract: bitwise-identical state evolution to the scalar oracle
(swim_tpu/models/oracle.py) given the same PeriodRandomness tensors —
enforced by tests/test_dense_vs_oracle.py. Exactness makes this the gold
reference for the scalable rumor engine, and the engine of choice up to
~10k nodes (memory is 9·N² bytes + transients).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from swim_tpu.config import SwimConfig
from swim_tpu.ops import lattice, sampling
from swim_tpu.sim import faults
from swim_tpu.sim.faults import FaultPlan
from swim_tpu.utils.prng import PeriodRandomness, draw_period

NO_DEADLINE = jnp.int32(2**31 - 1)
_RANK_INF = jnp.int32(2**30)


class DenseState(NamedTuple):
    """Mirrors oracle.OracleState field-for-field (bitwise comparable)."""

    key: jax.Array         # u32[N, N]  view: key[i, j] = i's opinion of j
    retransmit: jax.Array  # i32[N, N]  gossip send counts
    deadline: jax.Array    # i32[N, N]  suspicion expiry period
    lha: jax.Array         # i32[N]     Lifeguard local health score
    step: jax.Array        # i32 scalar periods completed


def init_state(cfg: SwimConfig) -> DenseState:
    n = cfg.n_nodes
    return DenseState(
        key=jnp.full((n, n), lattice.alive_key(jnp.uint32(0)), jnp.uint32),
        retransmit=jnp.full((n, n), cfg.retransmit_limit, jnp.int32),
        deadline=jnp.full((n, n), NO_DEADLINE, jnp.int32),
        lha=jnp.zeros((n,), jnp.int32),
        step=jnp.int32(0),
    )


def _masked_pick(mask: jax.Array, u: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Uniform pick over each row's True positions (oracle's float32 math).

    mask: bool[..., N]; u: f32[...] → (index[...], valid[...]).
    Picks the (floor(u·c)+1)-th set bit; valid iff the row has any.
    """
    c = jnp.sum(mask, axis=-1).astype(jnp.int32)
    idx = (u * c.astype(jnp.float32)).astype(jnp.int32)
    idx = jnp.minimum(idx, jnp.maximum(c - 1, 0))
    cum = jnp.cumsum(mask.astype(jnp.int32), axis=-1)
    pick = jnp.argmax(cum > idx[..., None], axis=-1).astype(jnp.int32)
    return pick, c > 0


def _piggyback(cfg: SwimConfig, retransmit: jax.Array):
    """Per-sender top-B selection: fewest retransmissions first, ties by id.

    Returns (sel_idx i32[N, B], sel_valid bool[N, B]).
    """
    # Width min(B, N) is exact: a sender can never piggyback more than N
    # distinct subjects, and when a buddy-forced subject is absent from the
    # selection at most N-1 of these slots can be valid.
    n, b = cfg.n_nodes, min(cfg.max_piggyback, cfg.n_nodes)
    j_ids = jnp.arange(n, dtype=jnp.int32)
    rank = retransmit * jnp.int32(n + 1) + j_ids[None, :]
    rank = jnp.where(retransmit < cfg.retransmit_limit, rank, _RANK_INF)
    neg_vals, sel_idx = jax.lax.top_k(-rank, b)
    return sel_idx.astype(jnp.int32), neg_vals > -_RANK_INF


def _apply_forced(cfg: SwimConfig, sel_idx, sel_valid, forced):
    """Lifeguard buddy: prepend `forced` subject (-1 = none) if absent."""
    present = jnp.any(sel_valid & (sel_idx == forced[..., None]), axis=-1)
    need = (forced >= 0) & ~present
    f_idx = jnp.concatenate(
        [jnp.maximum(forced, 0)[..., None], sel_idx[..., :-1]], axis=-1)
    f_valid = jnp.concatenate(
        [jnp.ones_like(forced[..., None], dtype=bool), sel_valid[..., :-1]],
        axis=-1)
    sel_idx = jnp.where(need[..., None], f_idx, sel_idx)
    sel_valid = jnp.where(need[..., None], f_valid, sel_valid)
    return sel_idx, sel_valid


def step(cfg: SwimConfig, state: DenseState, plan: FaultPlan,
         rnd: PeriodRandomness, tap: dict | None = None,
         prof=None) -> DenseState:
    """One protocol period for all N nodes (pure; jit with cfg static).

    `tap` (optional, static presence) receives per-period telemetry
    scalars (swim_tpu/obs/engine.py EngineFrame keys).  The tap never
    feeds back into state; with tap=None the traced program is
    unchanged, so telemetry-on state is bitwise identical to
    telemetry-off.

    `prof` (optional, static presence) is an obs/prof.py PhaseProbe.
    The dense engine reports the coarse phase subset (select / merge /
    commit / telemetry_tap): its per-wave piggyback selection and
    delivery interleave, so the wave chain is one "merge" phase.  Like
    tap, prof=None leaves the traced program unchanged.
    """
    n, k = cfg.n_nodes, cfg.k_indirect
    plan, prog = faults.split_program(plan)
    t = state.step
    key, retransmit, deadline, lha = (state.key, state.retransmit,
                                      state.deadline, state.lha)
    ids = jnp.arange(n, dtype=jnp.int32)
    crashed = t >= plan.crash_step                     # bool[N]
    joined = t >= plan.join_step
    # active membership: joined and not crashed — not-yet-joined nodes
    # neither act nor receive and are in nobody's membership list yet
    up = ~crashed & joined
    part_on = ((t >= plan.partition_start) & (t < plan.partition_end))

    if prog is not None:
        # u16 lane thresholds -> exact f32 probabilities (the scale is
        # a power of two, so thr * 2^-16 is exact); composed with the
        # global loss by saturating addition, matching the ring
        # engine's integer composition
        send_thr, recv_thr, reply_thr = faults.link_lanes(prog, t)
        scale = jnp.float32(1.0 / 65536.0)
        send_f = send_thr.astype(jnp.float32) * scale
        recv_f = recv_thr.astype(jnp.float32) * scale
        reply_f = reply_thr.astype(jnp.float32) * scale

    def delivered(src, dst, u, reply=False):
        """Fault mask for a batch of directed messages (docs/PROTOCOL.md §3)."""
        cut = part_on & (plan.partition_id[src] != plan.partition_id[dst])
        thr = plan.loss.astype(jnp.float32)
        if prog is not None:
            thr = thr + send_f[src] + recv_f[dst]
            if reply:
                thr = thr + reply_f[src]
        return up[src] & up[dst] & ~cut & (u >= thr)

    # ---- Phase A: all random choices --------------------------------------
    not_dead = ~lattice.is_dead(key)
    cand = (not_dead & (ids[None, :] != ids[:, None])
            & joined[None, :])                         # bool[N, N]
    if cfg.target_selection == "round_robin":
        # SWIM §4.3 randomized round-robin: each node walks its own
        # per-epoch Feistel shuffle of the id space; believed-dead targets
        # are probed and fail fast (docs/PROTOCOL.md §4). A not-yet-joined
        # target means an idle period (no probe: not a member yet).
        epoch = jnp.broadcast_to(t // jnp.int32(n - 1), (n,))
        pos = jnp.broadcast_to(t % jnp.int32(n - 1), (n,))
        target = sampling.round_robin_target(ids, epoch, pos, n)
        prober = up & joined[target]
    else:
        target, has_cand = _masked_pick(cand, rnd.target_u)
        prober = up & has_cand                         # i sends a W1 ping
    cand2 = cand & (ids[None, :] != target[:, None])
    # proxies: k independent picks over cand2 (same row mask per slot)
    c2 = jnp.sum(cand2, axis=-1).astype(jnp.int32)
    idx2 = (rnd.proxy_u * c2[:, None].astype(jnp.float32)).astype(jnp.int32)
    idx2 = jnp.minimum(idx2, jnp.maximum(c2 - 1, 0)[:, None])
    cum2 = jnp.cumsum(cand2.astype(jnp.int32), axis=-1)
    proxies = jnp.argmax(cum2[:, None, :] > idx2[:, :, None],
                         axis=-1).astype(jnp.int32)    # i32[N, k]
    has_proxy = c2 > 0

    if prof is not None and prof.cut("select", target, target=target,
                                     proxies=proxies, prober=prober):
        return prof.captured

    def buddy(cur_key, src, dst):
        """forced subject per message: dst if src believes dst SUSPECT.

        Evaluated against the *current* view at wave-build time (the oracle
        reads live state when constructing each wave's message list).
        """
        if not (cfg.lifeguard and cfg.buddy):
            return jnp.full(src.shape, -1, jnp.int32)
        return jnp.where(lattice.is_suspect(cur_key[src, dst]), dst,
                         jnp.int32(-1))

    def wave(carry, src, dst, sent, u_loss, forced, reply=False):
        """Run one message wave; returns updated carry and delivered mask.

        carry = (key, retransmit, deadline). src/dst/sent/u_loss/forced are
        flat message arrays of equal length M (static).  `reply` marks
        ack legs (W2/W5/W6) for the FaultProgram gray lane.
        """
        key, retransmit, deadline = carry
        sel_idx, sel_valid = _piggyback(cfg, retransmit)   # wave-start state
        msel = sel_idx[src]                                # [M, B]
        mval = sel_valid[src]
        msel, mval = _apply_forced(cfg, msel, mval, forced)
        mval = mval & sent[:, None]
        payload = key[src[:, None], msel]                  # [M, B] u32
        # counters advance for every sent message, delivered or not
        retransmit = retransmit.at[src[:, None], msel].add(
            mval.astype(jnp.int32))
        ok = sent & delivered(src, dst, u_loss, reply)     # [M]
        dval = mval & ok[:, None]
        new_key = key.at[dst[:, None], msel].max(
            jnp.where(dval, payload, jnp.uint32(0)))
        changed = new_key > key
        retransmit = jnp.where(changed, 0, retransmit)
        deadline = jnp.where(
            changed,
            jnp.where(lattice.is_suspect(new_key),
                      t + jnp.int32(cfg.suspicion_periods), NO_DEADLINE),
            deadline)
        return (new_key, retransmit, deadline), ok

    carry = (key, retransmit, deadline)

    # W1: pings i → T(i)
    carry, w1_ok = wave(carry, ids, target, prober, rnd.loss_w1,
                        buddy(carry[0], ids, target))
    # W2: acks T(i) → i (one per delivered ping, indexed by pinger i)
    no_force = jnp.full((n,), -1, jnp.int32)
    carry, w2_ok = wave(carry, target, ids, w1_ok, rnd.loss_w2, no_force,
                        reply=True)
    acked = w2_ok
    # W3: ping-req i → proxies, for probers with no direct ack
    need = prober & ~acked & has_proxy
    src3 = jnp.repeat(ids, k)
    dst3 = proxies.reshape(-1)
    sent3 = jnp.repeat(need, k)
    carry, w3_ok = wave(carry, src3, dst3, sent3, rnd.loss_w3.reshape(-1),
                        jnp.full((n * k,), -1, jnp.int32))
    # W4: proxy pings p → T(i)
    tgt4 = jnp.repeat(target, k)
    carry, w4_ok = wave(carry, dst3, tgt4, w3_ok, rnd.loss_w4.reshape(-1),
                        buddy(carry[0], dst3, tgt4))
    # W5: target acks T(i) → p
    carry, w5_ok = wave(carry, tgt4, dst3, w4_ok, rnd.loss_w5.reshape(-1),
                        jnp.full((n * k,), -1, jnp.int32), reply=True)
    # W6: relay acks p → i
    carry, w6_ok = wave(carry, dst3, src3, w5_ok, rnd.loss_w6.reshape(-1),
                        jnp.full((n * k,), -1, jnp.int32), reply=True)
    key, retransmit, deadline = carry
    relayed = jnp.any(w6_ok.reshape(n, k), axis=-1)

    if prof is not None and prof.cut("merge", key, key=key,
                                     retransmit=retransmit,
                                     deadline=deadline, acked=acked,
                                     relayed=relayed):
        return prof.captured

    # ---- End of period (docs/PROTOCOL.md §3) ------------------------------

    # 1. probe verdicts (health read at probe time, updated after)
    probe_ok = acked | relayed
    failed = prober & ~probe_ok
    s_probe = lha
    if cfg.lifeguard:
        lha = jnp.where(prober,
                        jnp.clip(lha + jnp.where(failed, 1, -1), 0,
                                 cfg.lha_max), lha)
        thin = rnd.lha_u < (jnp.float32(1.0) /
                            (1 + s_probe).astype(jnp.float32))
        failed = failed & thin
    cur_tk = key[ids, target]
    mk_suspect = failed & (lattice.status_of(cur_tk) == 0)  # currently ALIVE
    susp = lattice.suspect_key(lattice.incarnation_of(cur_tk))
    new_tk = jnp.where(mk_suspect, jnp.maximum(cur_tk, susp), cur_tk)
    ch = new_tk > cur_tk
    key = key.at[ids, target].set(new_tk)
    retransmit = retransmit.at[ids, target].set(
        jnp.where(ch, 0, retransmit[ids, target]))
    deadline = deadline.at[ids, target].set(
        jnp.where(ch, t + jnp.int32(cfg.suspicion_periods),
                  deadline[ids, target]))

    # 2. refutation: live node that sees itself suspected bumps incarnation
    self_k = key[ids, ids]
    refute = up & lattice.is_suspect(self_k)
    new_self = jnp.where(
        refute, lattice.alive_key(lattice.incarnation_of(self_k) + 1), self_k)
    key = key.at[ids, ids].set(new_self)
    retransmit = retransmit.at[ids, ids].set(
        jnp.where(refute, 0, retransmit[ids, ids]))
    deadline = deadline.at[ids, ids].set(
        jnp.where(refute, NO_DEADLINE, deadline[ids, ids]))
    if cfg.lifeguard:
        lha = jnp.where(refute, jnp.clip(lha + 1, 0, cfg.lha_max), lha)

    # 3. suspicion expiry → DEAD, gossip the confirm
    expire = (lattice.is_suspect(key) & (deadline <= t) & up[:, None])
    key = jnp.where(expire, lattice.dead_key(lattice.incarnation_of(key)),
                    key)
    retransmit = jnp.where(expire, 0, retransmit)
    deadline = jnp.where(expire, NO_DEADLINE, deadline)

    # inactive (crashed or not-yet-joined) nodes are frozen: restore rows
    frozen = (~up)[:, None]
    key = jnp.where(frozen, state.key, key)
    retransmit = jnp.where(frozen, state.retransmit, retransmit)
    deadline = jnp.where(frozen, state.deadline, deadline)
    lha = jnp.where(~up, state.lha, lha)

    if prof is not None and prof.cut("commit", key, key=key,
                                     retransmit=retransmit,
                                     deadline=deadline, lha=lha):
        return prof.captured

    if tap is not None:
        # ---- telemetry tap (swim_tpu/obs/engine.py EngineFrame) ----------
        # Selection stats measure the start-of-period piggyback pass;
        # occupancy counts still-transmissible (sender, subject) entries.
        b = min(cfg.max_piggyback, n)
        _, val0 = _piggyback(cfg, state.retransmit)
        row_bits = jnp.sum(val0.astype(jnp.int32), axis=-1)        # [N]
        tap["sel_slots_selected"] = jnp.sum(row_bits)
        tap["sel_rows_saturated"] = jnp.sum(
            ((row_bits >= b) & up).astype(jnp.int32))
        tap["sel_slots_max"] = jnp.max(row_bits)
        tap["win_occupancy"] = jnp.sum(
            (state.retransmit < cfg.retransmit_limit).astype(jnp.int32))
        tap["waves_delivered"] = (
            jnp.sum(w1_ok) + jnp.sum(w2_ok) + jnp.sum(w3_ok)
            + jnp.sum(w4_ok) + jnp.sum(w5_ok)
            + jnp.sum(w6_ok)).astype(jnp.int32)
        tap["probes_failed"] = jnp.sum(failed).astype(jnp.int32)
        if prof is not None:
            prof.cut("telemetry_tap", tap["sel_slots_selected"])

    return DenseState(key, retransmit, deadline, lha, t + 1)


@functools.partial(jax.jit, static_argnums=(0, 4))
def run(cfg: SwimConfig, state: DenseState, plan: FaultPlan,
        root_key: jax.Array, periods: int) -> DenseState:
    """Run `periods` protocol periods under one fused lax.scan."""

    def body(st, _):
        rnd = draw_period(root_key, st.step, cfg)
        return step(cfg, st, plan, rnd), None

    state, _ = jax.lax.scan(body, state, None, length=periods)
    return state


class DenseEngine:
    """Convenience wrapper holding (cfg, plan, state) with a jitted step."""

    def __init__(self, cfg: SwimConfig, plan: FaultPlan,
                 root_key: jax.Array | None = None):
        self.cfg = cfg
        self.plan = plan
        self.root_key = (root_key if root_key is not None
                         else jax.random.key(0))
        self.state = init_state(cfg)
        self._step = jax.jit(functools.partial(step, cfg))

    def run(self, periods: int) -> DenseState:
        self.state = run(self.cfg, self.state, self.plan, self.root_key,
                         periods)
        return self.state

    def step_once(self, rnd: PeriodRandomness | None = None) -> DenseState:
        if rnd is None:
            rnd = draw_period(self.root_key, self.state.step, self.cfg)
        self.state = self._step(self.state, self.plan, rnd)
        return self.state
