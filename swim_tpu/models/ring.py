"""Ring engine — the TPU-throughput SWIM simulation (scatter-free).

Why a third engine
------------------
The dense engine is exact but O(N²); the rumor engine is O(R·N) but its
message waves deliver with elementwise scatters over random destination
indices, which serialize on TPU (measured round 2: 1.56 s/period at
N=1M — scatter dispatch, not HBM bandwidth, dominates).  This engine is
designed backwards from the TPU memory system so one protocol period is a
handful of fused streaming passes over ~50 MB of hot state at N=1M:

  * **All-roll message waves.**  Probe targets follow the *rotor*
    round-robin variant of SWIM §4.3: one shared pseudo-random offset
    `s_t` per period, target(i) = (i + s_t) mod N, with s_t walking a
    keyed Feistel shuffle of [1, N) per epoch, so every node probes
    every other exactly once per epoch of N−1 periods (§4.3's
    worst-case-detection bound, strengthened: every node is also probed
    exactly once per period).  The k proxies use k more shared offsets.
    Every wave's delivery is then `jnp.roll` by a traced scalar — no
    gather, no scatter.  (GSPMD alone does NOT see a traced-shift roll
    as a neighbor exchange — it all-gathers; the sharded execution path
    is swim_tpu/parallel/ring_shard.py, which runs this same step under
    shard_map with the rolls as collective-permute pairs on ICI — the
    TPU-native analog of the reference's socket fan-out, SURVEY.md §5
    "Distributed comm backend".)
  * **Bit-packed heard-sets.**  Which-node-has-heard-which-rumor lives
    in u32 words (32 rumors/word): 8× less HBM traffic than the rumor
    engine's bool[N, R], and the first-B piggyback selection runs as a
    fused lowest-set-bit loop directly on the packed words (no top_k).
  * **Ring table with word recycling.**  Rumors are allocated into OW
    fresh 32-slot words per period; only the youngest WW words
    (`win u32[N, WW]`, a static slice) are transmissible.  When a word
    leaves the window, lanes whose rumor is still *spreading* (not yet
    heard by every live node, spread budget left) are carried — bits,
    metadata, suspicion timers — into the SAME lane of the
    corresponding fresh word (`fresh[w] = outgoing[w] & carry_mask[w]`,
    one fused op per word); finished lanes retire (tombstoning dead
    rumors) and become free lanes for new originations.  Gossip thus
    proceeds in window-length bursts for as long as SWIM's retransmit
    budget would keep a rumor alive — fixing the rumor engine's global
    age window, which stalled death dissemination at scale (measured:
    7/8 deaths at N=4096 never completed) — while retirement costs one
    [N, OW]-word pass per period instead of an O(R·N) scan.
  * **Per-subject top-C index.**  View queries (probe verdicts,
    refutation, buddy, sentinel refutation) never touch [N, R] masks:
    a tiny [R]-table pass rebuilds top-C (key, slot) per subject each
    period, and each query is C two-level word gathers, O(N·C).

Protocol semantics are the rumor engine's (docs/PROTOCOL.md §3–§7 and
its documented deviations) with these additional documented deviations:

  R1. **Rotor probing** (default, `cfg.ring_probe == "rotor"`).
      Shared-offset round-robin instead of per-node shuffled lists: the
      §4.3 bounded-detection regime, not uniform sampling — a crash is
      detected in ≤ ~2 periods.  Proxy offsets may coincide with each
      other / the target / self with probability O(k/N); such a proxy
      slot is wasted (exact SWIM samples proxies without replacement).
      `cfg.ring_probe == "pull"` instead samples each node's IN-probe
      lane (deviations P1–P4 at the pull branch below), preserving
      uniform probing's geometric e/(e−1) first-detection law exactly,
      still scatter-free (delivery by row gathers); vanilla protocol
      only.
  R2. **Burst transmissibility.**  A rumor gossips while its word is in
      the window (WW/OW periods per burst), recycling while it spreads,
      up to `2 * gossip_window` periods total; eviction of a
      still-pending suspicion or a still-spreading rumor at budget end
      is counted in `overflow`.
  R3. **Top-C subject views.**  A viewer's opinion joins only the C
      highest-keyed live rumors per subject; more than C concurrent
      distinct assertions about one subject increments
      `index_overflow`.  The join is a lower bound of the true view, so
      degradation is toward slower detection, never wrong state.
  R4. **Recycling-first allocation.**  Carried lanes always win over
      new originations; a period whose new originations exceed the free
      lanes drops the excess (priority confirm > refute > suspect) into
      `overflow` — a dropped suspicion is re-detected by the next
      failed probe, so overload degrades into latency, never wrong
      state (same philosophy as the rumor engine's deviation 4).
  R5. **Period-scope piggyback selection** (opt-in,
      `cfg.ring_sel_scope == "period"`; default "wave" is exact).
      Selection and buddy knowledge are evaluated once per period
      against the start-of-period window instead of before every wave:
      a rumor learned mid-period relays from the NEXT period on (one
      extra period of dissemination latency per hop worst-case, no
      state divergence otherwise).  Removes 2+4k−1 of the 2+4k
      full-window selection passes — the dominant HBM term at 1M nodes
      (utils/roofline.py).

Join/churn: nodes with `FaultPlan.join_step > 0` are inert (no probing,
no receiving, excluded from dissemination totals) until their join
period — crash, join, and rejoin-under-a-fresh-id schedules compose.

Reference parity note: jpfuentes2/swim (Haskell; tree unavailable at
survey time, SURVEY.md §0) has no simulator — this engine is the
TPU-native scaling capability the north star adds; its per-node protocol
semantics follow docs/PROTOCOL.md like the other engines, validated
bitwise against the scalar twin in swim_tpu/models/ring_oracle.py.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from swim_tpu.config import SwimConfig
from swim_tpu.ops import coldsel, lattice, sampling, selb, wavemerge, wavepack
from swim_tpu.sim import faults
from swim_tpu.sim.faults import FaultPlan

WORD = 32

# Sentinel-expiry probe compaction cap (Phase C): rumors whose sentinel
# deadlines expire in one period track the origination budget (~OB), so
# 512 covers steady state with ~8x headroom; a burst beyond it takes the
# exact full-batch branch of the lax.cond.  Module-level so tests can
# force either branch (tests/test_ring.py pins them bitwise-equal).
_SENTINEL_QUERY_CAP = 512


class RingGeometry(NamedTuple):
    """Static geometry derived from SwimConfig (plain Python ints)."""

    ow: int       # words originated per period (lane budget OB = 32*ow)
    ww: int       # window words (transmissible candidates = 32*ww)
    rw: int       # cold ring words (total slots R = 32*rw)
    c: int        # per-subject view index depth
    spread: int   # total spread budget in periods (recycle cutoff)
    life: int     # ring turnover in periods (rw = ow * life)


def geometry(cfg: SwimConfig) -> RingGeometry:
    ow = cfg.ring_orig_words
    wp = cfg.ring_window_periods
    spread = 2 * cfg.gossip_window
    life = max(cfg.suspicion_max_periods + 4, spread + 2, wp + 2)
    return RingGeometry(ow=ow, ww=ow * wp, rw=ow * life, c=cfg.ring_view_c,
                        spread=spread, life=life)


class RingState(NamedTuple):
    """Node-axis tensors shard over the mesh; table tensors replicate.

    `cold` is WORD-major ([RW, N], node axis LAST — see SHARD_AXES): the
    per-period flush writes one word-row for all nodes, and in node-major
    layout a single-column write rewrites every (8, 128) tile of the
    512 MB array (measured on TPU: 2.3 ms per 4 MB column); word-major
    makes both the flush and the word-row reads contiguous.

    SHARD_AXES (consumed generically by parallel.mesh.shard_state /
    state_shardings) records the node-axis position of fields where it
    is not leading.  It is a plain class attribute, not a field."""

    # --- per node (axis N sharded; cold's node axis is axis 1) ---
    win: jax.Array       # u32[N, WW]  heard-bits, youngest WW words
    cold: jax.Array      # u32[RW, N]  heard-bits, cold ring (word-major)
    inc_self: jax.Array  # u32[N]
    lha: jax.Array       # i32[N]
    gone_key: jax.Array  # u32[N]   DEAD tombstone floor per subject
    # --- rumor table (axis R = 32*RW ring slots, replicated) ---
    subject: jax.Array    # i32[R]   -1 = free
    rkey: jax.Array       # u32[R]
    birth0: jax.Array     # i32[R]   first-generation birth (spread budget)
    sent_node: jax.Array  # i32[R, S]
    sent_time: jax.Array  # i32[R, S]
    confirmed: jax.Array  # bool[R]
    # --- scalars ---
    overflow: jax.Array        # i32  dropped originations / evictions
    index_overflow: jax.Array  # i32  deviation-R3 occurrences
    step: jax.Array            # i32

    SHARD_AXES = {"cold": 1}   # class attr (un-annotated => not a field)


def init_state(cfg: SwimConfig) -> RingState:
    g = geometry(cfg)
    n, r, s = cfg.n_nodes, g.rw * WORD, cfg.sentinels
    return RingState(
        win=jnp.zeros((n, g.ww), jnp.uint32),
        cold=jnp.zeros((g.rw, n), jnp.uint32),
        inc_self=jnp.zeros((n,), jnp.uint32),
        lha=jnp.zeros((n,), jnp.int32),
        gone_key=jnp.zeros((n,), jnp.uint32),
        subject=jnp.full((r,), -1, jnp.int32),
        rkey=jnp.zeros((r,), jnp.uint32),
        birth0=jnp.zeros((r,), jnp.int32),
        sent_node=jnp.full((r, s), -1, jnp.int32),
        sent_time=jnp.zeros((r, s), jnp.int32),
        confirmed=jnp.zeros((r,), jnp.bool_),
        overflow=jnp.int32(0),
        index_overflow=jnp.int32(0),
        step=jnp.int32(0),
    )


# ---------------------------------------------------------------------------
# Slot arithmetic.
#
# Global word G is the G-th 32-slot word ever allocated; period t allocates
# (into the window's youngest columns) global words [t*OW, (t+1)*OW).
# ON ENTRY to step(t), win column w holds global word  t*OW − WW + w  (the
# window as period t−1 left it).  The Phase-0 shift drops columns [0, OW)
# and appends the OW fresh (zero) columns, after which win column w holds
# global word  (t+1)*OW − WW + w.  A global word lives in cold column
# (G mod RW) from the moment it leaves the window until the ring reuses
# that column.  Ring slot of (G, bit b) = (G mod RW)*32 + b, and the ring
# slot axis has R = 32*RW entries.
#
# Negative global words (early periods) denote never-allocated space; mod
# arithmetic maps them onto empty columns, which is harmless.
# ---------------------------------------------------------------------------


PULL_SRC_ATTEMPTS = 3


class ExtOriginations(NamedTuple):
    """External rumor originations injected into Phase D (host bridge).

    The TPUSimTransport seam (swim_tpu/bridge/engine_server.py): claims
    arriving from a foreign core over the TCP bridge become first-class
    rumors in tensor state.  All arrays are replicated, fixed-size [E]:

      subject: i32[E]  member the claim is about (-1 = empty entry)
      key:     u32[E]  packed opinion key (ops/lattice.py layout)
      origin:  i32[E]  the claim's ORIGINATOR (wire `origin`: the
                       suspecting/declaring node — sentinel bookkeeping
                       tracks its liveness, exactly like internal
                       suspicions)
      hearer:  i32[E]  the engine node that RECEIVED the datagram — it
                       gets the heard-bit, so dissemination radiates
                       from the true delivery point

    Injected candidates join the Phase-D merge at the LOWEST priority
    (confirms > refutes > internal suspicions > external): an external
    claim never displaces an internal origination from the lane budget.
    Entries whose rumor already exists in the table dedup onto the
    existing slot (the hearer's bit is then NOT set — it will hear
    through normal waves; documented deviation of the seam).
    """

    subject: jax.Array
    key: jax.Array
    origin: jax.Array
    hearer: jax.Array


def ext_none(capacity: int) -> ExtOriginations:
    """An all-empty injection batch of the given static capacity."""
    return ExtOriginations(
        subject=jnp.full((capacity,), -1, jnp.int32),
        key=jnp.zeros((capacity,), jnp.uint32),
        origin=jnp.zeros((capacity,), jnp.int32),
        hearer=jnp.zeros((capacity,), jnp.int32),
    )


def pow_f32(base, expo):
    """base**expo for f32 base and non-negative i32 expo, by 31 rounds of
    square-and-multiply in a FIXED operation order.  IEEE-754 f32
    multiply is correctly rounded on every backend, so evaluating the
    identical operation sequence in jnp (engine) and numpy (oracle)
    yields bit-identical results — which is what lets the pull-mode
    probed decision stay part of the bitwise contract.  (The base itself
    must be divide-free on device: see the reciprocal table at the p0
    computation — XLA:TPU f32 divide is not guaranteed correctly
    rounded.)"""
    one = jnp.float32(1.0)
    result = jnp.broadcast_to(one, jnp.shape(expo)).astype(jnp.float32)
    cur = jnp.broadcast_to(jnp.asarray(base, jnp.float32),
                           jnp.shape(expo)).astype(jnp.float32)
    e = jnp.asarray(expo, jnp.int32)
    for bit in range(31):
        result = jnp.where((e >> bit) & 1 == 1, result * cur, result)
        cur = cur * cur
    return result


def py_pow_f32(base: float, expo: int) -> float:
    """Scalar numpy twin of pow_f32 (same operation order, f32 ops)."""
    import numpy as np

    result = np.float32(1.0)
    cur = np.float32(base)
    e = int(expo)
    for bit in range(31):
        if (e >> bit) & 1:
            result = np.float32(result * cur)
        cur = np.float32(cur * cur)
    return float(result)


class PullRandomness(NamedTuple):
    """Per-period uniforms for the pull-uniform probe mode (one pulled
    prober lane per node — see `step`'s pull branch for semantics)."""

    m_u: jax.Array      # f32[N]  in-probe count draw (vs exact P(m=0))
    src_u: jax.Array    # f32[N, A]  prober-id draws (first-alive wins)
    d_fwd: jax.Array    # f32[N]  direct ping leg
    d_back: jax.Array   # f32[N]  direct ack leg
    px_u: jax.Array     # f32[N, k]  proxy-id draws
    px_fwd: jax.Array   # f32[N, k]  ping-req + proxy-ping legs (composed)
    px_back: jax.Array  # f32[N, k]  proxy-ack + relay legs (composed)
    ack_u: jax.Array    # f32[N]  ack-gossip contact draw (P3')
    ack_leg: jax.Array  # f32[N]  its composed ping+ack legs


class RingRandomness(NamedTuple):
    s_off: jax.Array    # i32 scalar: probe offset in [1, N)   (rotor)
    q_off: jax.Array    # i32[k]:  proxy offsets in [1, N)     (rotor)
    # The six loss legs and the LHA draw are raw u16 values carried in
    # u32 (0..65535), threshold-compared by consumers IN INTEGERS:
    # `bits >= ceil(loss*65536)` == the former `bits/65536 >= loss`
    # exactly (bits/65536 is exact in f32; 65536*loss is an exponent
    # shift; ceil is exact), and `bits*(1+s) < 65536` == the former
    # `bits/65536 < fl(1/(1+s))` — verified exhaustively over every
    # (bits, s) pair, s in [0,256].  Carrying bits instead of f32
    # uniforms removes the four [N,k] convert-multiply materializations
    # the round-4 TPU profile measured at 0.36 ms/period @ 1M.
    loss_w1: jax.Array  # u32[N]   u16 draw                    (rotor)
    loss_w2: jax.Array  # u32[N]   u16 draw                    (rotor)
    loss_w3: jax.Array  # u32[N, k] u16 draw                   (rotor)
    loss_w4: jax.Array  # u32[N, k] u16 draw                   (rotor)
    loss_w5: jax.Array  # u32[N, k] u16 draw                   (rotor)
    loss_w6: jax.Array  # u32[N, k] u16 draw                   (rotor)
    lha_u: jax.Array    # u32[N]   u16 draw, probe thinning    (rotor)
    pull: PullRandomness | None = None          # pull mode only


def draw_period_ring(key: jax.Array, step, cfg: SwimConfig) -> RingRandomness:
    n, k = cfg.n_nodes, cfg.k_indirect
    t = jnp.asarray(step, jnp.int32)
    # rotor offset: position (t mod N−1) of an epoch-keyed shuffle of [0,N−1)
    epoch = (t // jnp.int32(n - 1)).astype(jnp.uint32)
    pos = jnp.mod(t, jnp.int32(n - 1)).astype(jnp.uint32)
    ka = sampling._mix32(epoch * jnp.uint32(0x9E3779B9) + jnp.uint32(0xABCD))
    kb = sampling._mix32(epoch ^ jnp.uint32(0x7F4A7C15))
    s_off = sampling.feistel(pos, n - 1, ka, kb) + 1            # [1, N)
    # proxy offsets: k positions of a per-period shuffle (mutually
    # distinct; may equal s_off or wrap onto self/target with prob
    # O(k/N) — deviation R1)
    tk = jnp.asarray(step, jnp.uint32)
    pka = sampling._mix32(tk * jnp.uint32(0x85EBCA6B) + jnp.uint32(0x51ED))
    pkb = sampling._mix32(tk ^ jnp.uint32(0xC2B2AE35))
    q_off = sampling.feistel(jnp.arange(k, dtype=jnp.uint32), n - 1,
                             pka, pkb) + 1
    kk = jax.random.fold_in(key, step)
    if cfg.ring_probe == "pull":
        ks = jax.random.split(kk, 9)
        zero = jnp.zeros((0,), jnp.uint32)
        return RingRandomness(
            s_off=s_off.astype(jnp.int32), q_off=q_off.astype(jnp.int32),
            loss_w1=zero, loss_w2=zero, loss_w3=zero, loss_w4=zero,
            loss_w5=zero, loss_w6=zero, lha_u=zero,
            pull=PullRandomness(
                m_u=jax.random.uniform(ks[0], (n,)),
                src_u=jax.random.uniform(ks[1], (n, PULL_SRC_ATTEMPTS)),
                d_fwd=jax.random.uniform(ks[2], (n,)),
                d_back=jax.random.uniform(ks[3], (n,)),
                px_u=jax.random.uniform(ks[4], (n, k)),
                px_fwd=jax.random.uniform(ks[5], (n, k)),
                px_back=jax.random.uniform(ks[6], (n, k)),
                ack_u=jax.random.uniform(ks[7], (n,)),
                ack_leg=jax.random.uniform(ks[8], (n,)),
            ))
    # The seven rotor uniforms exist only to be threshold-compared
    # (Bernoulli loss legs, LHA probe thinning), so 16-bit resolution
    # is ample (quantizes each probability by <= 1/65536).  Packing
    # two u16 halves per u32 threefry output halves the generated
    # bits: 4 [N] + 2 [N, k] raw draws instead of 3 [N] + 4 [N, k]
    # f32 uniforms (the period RNG measured 0.67 ms at the 1M
    # flagship — the generation, not the use, is the cost).  The
    # halves stay RAW u16 integers (see RingRandomness): consumers
    # compare in the integer domain, a proven-exact rewrite of the
    # former f32 compares, so no [N,k]-sized float conversion is ever
    # materialized.  The oracle consumes these same tensors
    # (ring_oracle.py), so the bitwise engine<->oracle contract is
    # unaffected by HOW they are drawn.
    ks = jax.random.split(kk, 4)

    def halves(bits):
        return (bits & jnp.uint32(0xFFFF), bits >> 16)

    w12 = jax.random.bits(ks[0], (n,), jnp.uint32)
    w34 = jax.random.bits(ks[1], (n, k), jnp.uint32)
    w56 = jax.random.bits(ks[2], (n, k), jnp.uint32)
    lha_b = jax.random.bits(ks[3], (n,), jnp.uint32)
    loss_w1, loss_w2 = halves(w12)
    loss_w3, loss_w4 = halves(w34)
    loss_w5, loss_w6 = halves(w56)
    return RingRandomness(
        s_off=s_off.astype(jnp.int32),
        q_off=q_off.astype(jnp.int32),
        loss_w1=loss_w1, loss_w2=loss_w2,
        loss_w3=loss_w3, loss_w4=loss_w4,
        loss_w5=loss_w5, loss_w6=loss_w6,
        lha_u=halves(lha_b)[0],
    )


def _select_first_b(win_masked, b: int, impl: str = "auto"):
    """u32[N, WW]: mask of the first `b` set bits of each row's window,
    newest word first, LSB-first within a word.  Lowering lives in
    ops/selb.py (Pallas one-pass kernel on TPU, budgeted extract loop
    elsewhere; bitwise-pinned by
    tests/test_core_units.py::TestSelectFirstB)."""
    return selb.select_first_b(win_masked, b, impl=impl)


def _col_select_multi(mat: jax.Array, cols: list[jax.Array]) -> list[jax.Array]:
    """[mat[i, c[i]] for c in cols], as one-hot masked reduces over `mat`.

    `mat[rows, col]` with per-row dynamic columns lowers to XLA's generic
    gather, which TPU executes near-serially (measured: 13–21 ms per
    1M-row gather — the round-2 profile's entire hot set).  A Python
    loop of per-column slices is no better: XLA decomposes it into
    dozens of strided slice fusions that each touch 1/lanes of every
    tile of `mat` (the round-3 profile's entire hot set — ~119 GB of
    effective traffic per period at 1M nodes).  A single max-reduce of
    the one-hot-masked matrix instead reads `mat` exactly once, in its
    native tiling, per query.  Out-of-range c yields 0 (same as the
    pre-clamped contract).

    Contract: `mat` must hold UNSIGNED / NON-NEGATIVE values (u32
    heard-words here) — the reduce is a max against a 0 fill, so a
    negative selected value would be silently replaced by 0 (ADVICE
    r3: the old OR-accumulate had the same restriction, made explicit
    here)."""
    w_ids = jnp.arange(mat.shape[1], dtype=jnp.int32)
    zero = jnp.zeros((), mat.dtype)
    # ONE variadic reduce over W with Q accumulators: the loop body
    # evaluates all Q masked contributions per (n, w) element, so `mat`
    # is loaded once for every query (the previous stacked [Q, N, W]
    # max-reduce cost ~Q reads of `mat` plus a materialized hit mask —
    # measured 9x the traffic on the cost-analysis proxy).
    ops_in = [jnp.where(jnp.asarray(c)[:, None] == w_ids[None, :],
                        mat, zero) for c in cols]
    outs = jax.lax.reduce(ops_in, [zero] * len(cols),
                          lambda a, b: tuple(
                              jnp.maximum(x, y) for x, y in zip(a, b)),
                          (1,))
    return list(outs)


def _top_k_vals(x: jax.Array, k: int) -> jax.Array:
    """Top-k VALUES of a 1-D array, descending — exact, values-only.

    Hierarchical: block-wise top_k then a merge top_k over the block
    winners.  XLA lowers a single lax.top_k on a very long axis to a
    full sort (measured: 1.26 ms per [1M] top_k at k=64 on v5 lite);
    the block form sorts 4096-element rows instead.  Returns exactly
    lax.top_k's values (ties are indistinguishable by value; callers
    must not need indices)."""
    n, block = x.shape[0], 4096
    if n <= 4 * block or k > block:
        return jax.lax.top_k(x, min(k, n))[0]
    nb = -(-n // block)
    fill = jnp.asarray(jnp.iinfo(x.dtype).min
                       if jnp.issubdtype(x.dtype, jnp.integer)
                       else -jnp.inf, x.dtype)
    xp = jnp.concatenate(
        [x, jnp.full((nb * block - n,), fill, x.dtype)])
    vb = jax.lax.top_k(xp.reshape(nb, block), k)[0]              # [nb, k]
    return jax.lax.top_k(vb.reshape(-1), k)[0]


def _first_true_idx(valid: jax.Array, k: int) -> jax.Array:
    """i32[k]: ascending indices of the first k True entries of a 1-D
    bool vector; missing entries fill with n = valid.shape[0].

    Sort-free hierarchical compaction (round 4): the previous
    implementation keyed a full _top_k_vals, whose block stage still
    sorts every 4096-lane row — measured ~1.25 ms per [1M] call on v5
    lite, x2 calls per period.  Counting is exact and streams `valid`
    once: per-block true counts -> exclusive offsets -> for each output
    rank j, locate its block (searchsorted over the tiny offset vector),
    gather that one block row, and pick the rank-within-block element
    via a block-local cumsum.  All post-pass work is O(k * block).
    """
    n = valid.shape[0]
    kk = min(k, n)
    block = 1024
    nb = -(-n // block)
    vp = jnp.concatenate(
        [valid, jnp.zeros((nb * block - n,), valid.dtype)])
    v = vp.reshape(nb, block).astype(jnp.int32)
    bc = jnp.sum(v, axis=1)                       # [nb] per-block counts
    coff = jnp.cumsum(bc) - bc                    # exclusive offsets
    total = coff[-1] + bc[-1]
    j = jnp.arange(kk, dtype=jnp.int32)
    # last block whose offset <= j: the block holding global rank j
    # (trailing empty blocks share the next block's offset, and the
    # rightmost match is the non-empty one)
    b_j = jnp.searchsorted(coff, j, side="right").astype(jnp.int32) - 1
    b_j = jnp.clip(b_j, 0, nb - 1)
    r_j = j - coff[b_j]                           # rank within block
    rows = v[b_j]                                 # [kk, block] gather
    rcs = jnp.cumsum(rows, axis=1)
    hit = (rows > 0) & (rcs == (r_j + 1)[:, None])
    pos = jnp.sum(jnp.where(hit, jnp.arange(block, dtype=jnp.int32)[None],
                            0), axis=1)
    idx = jnp.where(j < total, b_j * block + pos, n).astype(jnp.int32)
    if k > n:
        idx = jnp.concatenate([idx, jnp.full((k - n,), n, jnp.int32)])
    return idx


def _lane_counts(words: jax.Array, active: jax.Array) -> jax.Array:
    """i32[OW*32]: per-lane active-knower counts of OW packed words.

    `words` is u32[OW, N] (word-major rows); lane la = w*32 + b counts
    active nodes with bit b of word w set.  One fused reduce instead of
    a Python loop of OB per-lane reductions (which XLA lowers to OB
    separate strided passes)."""
    ow = words.shape[0]
    bit_ids = jnp.arange(WORD, dtype=jnp.uint32)[None, :, None]
    bits = (words[:, None, :] >> bit_ids) & jnp.uint32(1)    # [OW, 32, N]
    masked = jnp.where(active[None, None, :], bits,
                       jnp.uint32(0)).astype(jnp.int32)
    return jnp.sum(masked, axis=2).reshape(ow * WORD)


def _window_overlay(g: RingGeometry, step) -> tuple[jax.Array, jax.Array]:
    """(in_win bool[RW], wcol i32[RW]): which ring words are currently
    window-resident after `step` completed periods, and which win column
    holds each — THE single home of the win/cold overlay invariant
    (consumed by resolved_words and live_knower_counts; the slot-
    arithmetic comment block above is the derivation)."""
    first_gw = step * g.ow - g.ww          # win col 0 after the last step
    win_ring0 = jnp.mod(first_gw, g.rw)
    word_off = jnp.mod(jnp.arange(g.rw, dtype=jnp.int32) - win_ring0,
                       g.rw)
    return word_off < g.ww, jnp.clip(word_off, 0, g.ww - 1)


def live_knower_counts(cfg: SwimConfig, state: RingState,
                       up: jax.Array,
                       chunk_words: int | None = None,
                       pair_budget: int = 1 << 23) -> jax.Array:
    """i32[R]: per-ring-slot count of live ("up") nodes holding the bit.

    The study runner's census.  Computed split by storage (win vs cold)
    in CHUNKS of word rows so the expanded [chunk, 32, N] intermediate
    stays ~2 GiB however large N·RW grows: the previous formulation
    expanded resolved_words to [N, RW, 32] in one piece, which CPU XLA
    MATERIALIZES — 115 GB at 4M nodes / OW=4, and a 245 GB
    RESOURCE_EXHAUSTED at OW=8 (TPU fuses it, but the chunked form is
    layout-native there too: cold row chunks are contiguous in the
    word-major [RW, N] layout).  Integer sums — bitwise-identical to
    the unchunked census in any chunk order.
    """
    g = geometry(cfg)
    n = cfg.n_nodes

    # Ordering token: the latest partial-count vector.  Every chunk's
    # SOURCE matrix is rethreaded through an optimization_barrier against
    # it before slicing, so chunk c+1's slice cannot be staged until
    # chunk c's partial sum is done.  Without this chain the XLA:TPU
    # latency-hiding scheduler hoists EVERY chunk slice ahead of the
    # reductions — ~330 live u32[1, 2^23] buffers at 16M nodes, 5.4 GB
    # of the 5.46 GB HLO temp that kept the 16M study 591 MB over one
    # chip AFTER streaming milestones (memwall full-allocation capture;
    # the committed study_detection_16m_oom.json shows the same site).
    # The barrier is an identity: values, chunk boundaries and addition
    # order are unchanged, so the census stays bitwise-identical.
    tok = [None]

    def chained(x):
        if tok[0] is not None:
            x, _ = jax.lax.optimization_barrier((x, tok[0]))
        return x

    # 2^23 word-node pairs x (4 B u32 bits + 4 B i32 masked) x 32 bits
    # ~= 2 GiB of expanded intermediates per chunk
    cw = chunk_words or max(1, pair_budget // max(n, 1))

    def matrix_counts(words, nrows):            # [nrows, N] word-major
        # _lane_counts IS this census kernel; reuse it per chunk.
        # Beyond ~8.4M nodes even ONE word row exceeds the 2 GiB
        # budget (the 16M study OOM'd by 620 MB on exactly this), so
        # the node axis splits too — integer partial sums, bitwise-
        # identical in any split.
        out = []
        for r0 in range(0, nrows, cw):
            rc = min(cw, nrows - r0)
            if rc * n <= pair_budget:
                tot = _lane_counts(chained(words)[r0:r0 + rc], up)
            else:
                seg = max(1, pair_budget // rc)
                tot = None
                for c0 in range(0, n, seg):
                    part = _lane_counts(
                        chained(words)[r0:r0 + rc, c0:c0 + seg],
                        up[c0:c0 + seg])
                    tot = part if tot is None else tot + part
                    tok[0] = tot
            tok[0] = tot
            out.append(tot.reshape(-1, WORD))
        return jnp.concatenate(out)

    counts_cold = matrix_counts(state.cold, g.rw)
    counts_win = matrix_counts(state.win.T, g.ww)
    # overlay: window-resident ring words read their win column (cold's
    # copy of a window column is one generation stale by design)
    in_win, wcol = _window_overlay(g, state.step)
    counts = jnp.where(in_win[:, None], counts_win[wcol], counts_cold)
    return counts.reshape(g.rw * WORD)


def resolved_words(cfg: SwimConfig, state: RingState) -> jax.Array:
    """u32[N, RW]: the CURRENT heard-bits of every ring word.

    Resolves the win/cold split (window words live in `win`; cold's copy
    of a window column is one generation stale by design) using the slot
    arithmetic this module owns — external consumers (study runner,
    metrics) must use this instead of re-deriving the layout.
    """
    g = geometry(cfg)
    in_win, wcol = _window_overlay(g, state.step)
    return jnp.where(in_win[None, :], state.win[:, wcol], state.cold.T)


class GlobalOps:
    """Cross-node operations, single-program flavor: the whole node axis
    is local, so every method is ordinary array code.

    `step` routes ALL cross-node data movement through this object:
    node-axis rolls, global reductions, scatter/gather by global node id,
    heard-bit lookups for arbitrary node rows, and first-k-true index
    compaction.  swim_tpu/parallel/ring_shard.py supplies the shard_map
    twin (ShardOps) whose methods compute the same VALUES from a node
    shard plus XLA collectives (collective-permute rolls, psum
    reductions, masked local scatters) — one step body, two execution
    layouts, bitwise-equal results.
    """

    supports_random_gather = True   # pull mode's arbitrary row gathers

    def __init__(self, cfg: SwimConfig):
        self.n = cfg.n_nodes

    # -- node identity ----------------------------------------------------
    def ids(self):
        """i32: global ids of the locally-held node rows."""
        return jnp.arange(self.n, dtype=jnp.int32)

    def zeros_nodes(self, dtype, cols: int | None = None):
        shape = (self.n,) if cols is None else (self.n, cols)
        return jnp.zeros(shape, dtype)

    def full_nodes(self, val, dtype):
        return jnp.full((self.n,), val, dtype)

    # -- reductions -------------------------------------------------------
    def gsum(self, partial):
        """Global sum given this shard's partial (scalar or small vec)."""
        return partial

    def gmax(self, partial):
        """Global max given this shard's partial (telemetry reductions)."""
        return partial

    # -- communication ----------------------------------------------------
    def roll_from(self, x, d, label=None):
        """Value of x at node (i + d) mod n, for every local row i.

        `label` names the roll for the per-collective ICI byte tally
        (obs/ici.py CountingOps) — stable keys like "roll_ok_waves"
        instead of shape/dtype-derived ones; inert here."""
        del label
        return jnp.roll(x, -d, axis=0)

    def roll_bundle(self, parts, d, labels=None):
        """roll_from over several same-offset node vectors at once —
        the packed scalar wire's fusion seam (ring_scalar_wire): the
        sharded twin ships ONE bit/byte-packed ppermute payload per
        call (ops/wavepack.py pack_bundle); here the node axis is one
        address space, so each part just rolls."""
        del labels
        return tuple(jnp.roll(x, -d, axis=0) for x in parts)

    # -- node-axis scatter/gather by GLOBAL node id -----------------------
    def scatter_max(self, dst, idx, val):
        """dst[idx] <- max(dst[idx], val); idx outside [0, n) drops."""
        return dst.at[idx].max(val, mode="drop")

    def scatter_add(self, dst, idx, val):
        return dst.at[idx].add(val, mode="drop")

    def scatter_or_word(self, win, rows, cols, bits):
        """win[rows, cols] |= bits via add (caller guarantees the added
        bits are disjoint from existing ones); rows outside [0, n) drop."""
        return win.at[rows, cols].add(bits, mode="drop")

    def gather(self, arr, idx):
        """arr[idx] for node-axis arr; idx replicated, in [0, n)."""
        return arr[idx]

    # -- nodewise exchanges (pull mode: per-node queries of random peers;
    #    the sharded twin routes these through a D-step ppermute ring
    #    pass — see ring_shard.ShardOps) ---------------------------------
    def gather_nodewise(self, arr, idx):
        """arr[idx] for node-axis arr and node-axis global ids."""
        return arr[idx]

    def gather_rows(self, mat, idx):
        """mat[idx] for a node-axis [N, C] matrix; idx node-axis ids —
        the pull branch's selection-row exchange."""
        return mat[idx]

    def knows_nodewise(self, win, cold, slot_pos, rows, slot):
        """Heard-bit for node-axis (rows, slot) query vectors."""
        return self.knows_words(win, cold, slot_pos, rows, slot)

    def knows_self(self, win, cold, slot_pos, slot):
        """Heard-bit of each row's OWN node for ring slots `slot`."""
        return self.knows_words(win, cold, slot_pos, self.ids(), slot)

    def knows_words(self, win, cold, slot_pos, rows, slot):
        """Heard-bit of GLOBAL node ids `rows` (any shape) for ring
        slots `slot` (same shape): the generic two-level word lookup
        (cold is word-major: [RW, N])."""
        ok, wcol, word_r, bit = slot_pos(slot)
        word = jnp.where(ok, win[rows, wcol], cold[word_r, rows])
        return (slot >= 0) & (((word >> bit) & 1) > 0)

    def first_true_nodes(self, valid, k):
        """Ascending global ids of the first k True entries of a
        node-axis bool vector; missing entries fill with n."""
        return _first_true_idx(valid, k)

    def merge_waves(self, win, sel, oks, offs, bcols, bvals, impl):
        """Fused period-scope delivery: OR the rolled start-of-period
        selection payload into `win` under each wave's receiver mask,
        plus the compact buddy forced bits, in one pass.

        oks/offs are per-wave lists ([N] bool / traced scalar d, with
        receiver i hearing sel row (i + d) mod n); bcols/bvals are
        receiver-aligned compact forced-bit lists (val 0 = inert).
        This layout routes to ops/wavemerge.py (Pallas kernel on the
        TPU backend: the lane-misaligned rolled ORs become contiguous
        DMAs — the largest profiled term of the 1M period, 2.33 ms,
        docs/RESULTS.md §1); the sharded twin keeps per-wave ppermute
        rolls (same values, same ICI traffic either way)."""
        if bcols:
            bcol = jnp.stack(bcols)
            bval = jnp.stack(bvals)
        else:
            bcol = jnp.zeros((0, self.n), jnp.int32)
            bval = jnp.zeros((0, self.n), jnp.uint32)
        return wavemerge.merge_waves(
            win, sel, jnp.stack(oks),
            jnp.stack([jnp.asarray(d, jnp.int32) for d in offs]),
            bcol, bval, impl=impl)


def step(cfg: SwimConfig, state: RingState, plan: FaultPlan,
         rnd: RingRandomness, ops: GlobalOps | None = None,
         ext: ExtOriginations | None = None,
         tap: dict | None = None, prof=None) -> RingState:
    """One protocol period for all N nodes (pure; jit with cfg static).

    With the default `ops`, every array spans the full node axis; under
    swim_tpu/parallel/ring_shard.py the same body runs inside shard_map
    with node-axis tensors sharded and `ops` supplying the collectives.

    `ext` (optional, static presence) injects externally-originated
    rumors into Phase D — the host-bridge seam (see ExtOriginations).
    With ext=None the traced program is unchanged.

    `tap` (optional, static presence) receives per-period telemetry
    scalars (swim_tpu/obs/engine.py EngineFrame keys), reduced through
    the ops seam so both execution layouts report identical frames.
    The tap never feeds back into state; with tap=None the traced
    program is unchanged — telemetry-on protocol state is bitwise
    identical to telemetry-off by construction.

    `prof` (optional, static presence) is a swim_tpu/obs/prof.py
    PhaseProbe marking the step's phase boundaries (select / pack /
    ppermute / merge / commit / telemetry_tap).  In marker mode each
    cut folds one already-live array into a replicated i32 signature;
    in prefix mode the step RETURNS EARLY at the probe's named boundary
    with the phase's live arrays (`prof.captured`) so the profiler can
    difference device-synced prefix timings.  Like tap/ext, prof=None
    leaves the traced program unchanged — the profiling-on bitwise
    parity is structural.
    """
    if ops is None:
        ops = GlobalOps(cfg)
    # FaultProgram plans split into (base FaultPlan, program-or-None);
    # prog is None for plain plans AND zero-segment programs, so the
    # empty scenario traces the exact graph a FaultPlan does (the
    # bitwise-parity contract pinned by tests/test_scenario.py).
    plan, prog = faults.split_program(plan)
    g = geometry(cfg)
    n, k = cfg.n_nodes, cfg.k_indirect
    r_tot, s_cap = g.rw * WORD, cfg.sentinels
    ob = g.ow * WORD
    t = state.step
    ids = ops.ids()
    rr = jnp.arange(r_tot, dtype=jnp.int32)
    lanes = jnp.arange(ob, dtype=jnp.int32)
    crashed = t >= plan.crash_step
    joined = t >= plan.join_step
    active = ~crashed & joined
    part_on = (t >= plan.partition_start) & (t < plan.partition_end)
    live_total = ops.gsum(jnp.sum(active).astype(jnp.int32))

    subject, rkey, birth0 = state.subject, state.rkey, state.birth0
    snode, stime = state.sent_node, state.sent_time
    confirmed = state.confirmed
    gone_key = state.gone_key
    overflow = state.overflow
    cold = state.cold

    entry_gw0 = t * g.ow - g.ww        # entry win col 0's global word
    fresh_gw0 = t * g.ow               # this period's first fresh word

    # ---- Phase 0a: judge the outgoing words (entry win cols [0, OW)) ------
    out_cols = state.win[:, :g.ow]                             # u32[N, OW]
    out_knowers = ops.gsum(_lane_counts(out_cols.T, active))   # i32[OB]
    out_rcol = jnp.mod(entry_gw0 + lanes // WORD, g.rw)
    out_slots = out_rcol * WORD + lanes % WORD                 # i32[OB]
    out_sub = subject[out_slots]
    out_key = rkey[out_slots]
    out_used = out_sub >= 0
    out_dissem = out_knowers >= live_total
    in_budget = (t - birth0[out_slots]) < g.spread
    # three classes: carry (still spreading -> recycle into the same lane
    # of the fresh word), keep (pending suspicion: timer still running —
    # stays at its now-cold slot, stops transmitting), retire (done).
    # A suspicion outranked by any live same-subject rumor or by the
    # dissemination floor is refuted — it retires instead of being kept.
    glob_refuted = (jnp.any(
        (subject[None, :] == out_sub[:, None]) & (subject >= 0)[None, :]
        & (rkey[None, :] > out_key[:, None]), axis=-1)
        | (ops.gather(gone_key, jnp.maximum(out_sub, 0)) > out_key))
    pending = (out_used & lattice.is_suspect(out_key)
               & ~confirmed[out_slots] & ~glob_refuted)
    carry = out_used & ~out_dissem & in_budget
    keep = out_used & ~carry & pending
    retire = out_used & ~carry & ~keep
    out_dead = out_used & lattice.is_dead(out_key)
    # ANY fully-disseminated retiring key floors the subject's views and
    # permanently refutes lower-keyed suspicions (`gone_key` is the
    # dissemination floor; its DEAD restriction is the death tombstone) —
    # without this, a refutation that disseminates and retires would
    # become invisible to later sentinel-expiry checks.
    tomb = retire & out_dissem
    gone_key = ops.scatter_max(gone_key, jnp.where(tomb, out_sub, n),
                               out_key)
    # a death evicted before full dissemination is a lost certificate
    overflow = overflow + jnp.sum(retire & out_dead & ~out_dissem
                                  ).astype(jnp.int32)

    # ---- Phase 0b: invalidate the previous generation of the fresh cols ---
    fresh_rcol = jnp.mod(fresh_gw0 + lanes // WORD, g.rw)
    fresh_slots = fresh_rcol * WORD + lanes % WORD             # i32[OB]
    inv_sub = subject[fresh_slots]
    inv_used = inv_sub >= 0
    inv_key = rkey[fresh_slots]
    # The OW query rows here are SHARED by every node (static mod
    # offsets of the traced period), so a contiguous dynamic row slice
    # reads OW rows (~4 MB each at 1M) instead of streaming the whole
    # 512 MB cold matrix through a per-node select pass.  (The C+1
    # view queries below stay one-hot passes — their rows are per-node.
    # Round-3's strided-walk hazard was WIN column slices and cold row
    # WRITES; a word-major cold ROW READ is contiguous.)
    fresh_rows = [
        jax.lax.dynamic_slice_in_dim(
            cold, jnp.mod(fresh_gw0 + w, g.rw), 1, axis=0)[0]
        for w in range(g.ow)]                          # OW x u32[N]
    inv_knowers = ops.gsum(_lane_counts(jnp.stack(fresh_rows), active))
    inv_tomb = inv_used & (inv_knowers >= live_total)
    gone_key = ops.scatter_max(gone_key, jnp.where(inv_tomb, inv_sub, n),
                               inv_key)
    # kept (pending-suspicion) slots reaped here had life >= timeout + 4
    # periods — their timers have provably resolved, so reaping is silent
    subject = subject.at[jnp.where(inv_used, fresh_slots, r_tot)].set(
        -1, mode="drop")

    # ---- Phase 0c: move carried lanes old slot -> same lane of fresh word -
    mv_src = jnp.where(carry, out_slots, r_tot)    # gather rows (drop-safe)
    mv_dst = jnp.where(carry, fresh_slots, r_tot)
    subject = subject.at[mv_dst].set(
        jnp.where(carry, out_sub, -1), mode="drop")
    rkey = rkey.at[mv_dst].set(out_key, mode="drop")
    birth0 = birth0.at[mv_dst].set(birth0[jnp.minimum(mv_src, r_tot - 1)],
                                   mode="drop")
    confirmed = confirmed.at[mv_dst].set(
        confirmed[jnp.minimum(mv_src, r_tot - 1)], mode="drop")
    snode = snode.at[mv_dst].set(snode[jnp.minimum(mv_src, r_tot - 1)],
                                 mode="drop")
    stime = stime.at[mv_dst].set(stime[jnp.minimum(mv_src, r_tot - 1)],
                                 mode="drop")
    # carried and retired outgoing slots free now; kept slots stay used.
    # (A dst can never equal a src: out and fresh ring columns are
    # distinct because 0 < WW < RW.)
    subject = subject.at[jnp.where(carry | retire, out_slots, r_tot)].set(
        -1, mode="drop")

    carry_mask = jnp.stack(
        [jnp.sum(jnp.where(carry[w * WORD:(w + 1) * WORD],
                           jnp.uint32(1) << jnp.arange(
                               WORD, dtype=jnp.uint32), jnp.uint32(0)))
         for w in range(g.ow)]).astype(jnp.uint32)             # u32[OW]

    # ---- Phase 0d: flush out cols to cold, shift window, carry bits -------
    # One fused full-matrix select instead of OW dynamic row updates: a
    # single-row update of the [RW, N] matrix is a strided read-modify-
    # write of every tile (measured ~7 ms each at 1M), while the fused
    # where-pass streams cold once at HBM bandwidth.
    # In rotor mode the flush is DEFERRED into the Phase-C fused pass
    # (ops/coldsel.py — the single home of the flush+select lowering):
    # nothing between here and the view queries reads cold, and fusing
    # flush + Q-query select into one blocked Pallas kernel reads and
    # writes cold exactly once per period on the TPU backend (it also
    # removes the {0,1}/{1,0} layout copies XLA otherwise inserts
    # around the loop carry — round-4 TPU HLO attribution).  The pull
    # branch reads cold through gather-style knows_* lookups before
    # Phase C, so it flushes here, immediately.
    flush_rows = jnp.stack(
        [jnp.mod(entry_gw0 + w, g.rw) for w in range(g.ow)]
    ).astype(jnp.int32)                                        # i32[OW]
    defer_flush = cfg.ring_probe == "rotor"
    if defer_flush:
        flush_vals = state.win[:, :g.ow].T                     # u32[OW, N]
    else:
        row_ids = jnp.arange(g.rw, dtype=jnp.int32)[:, None]   # [RW, 1]
        for w in range(g.ow):
            cold = jnp.where(row_ids == flush_rows[w],
                             state.win[:, w][None, :], cold)
    fresh_cols = out_cols & carry_mask[None, :]                # u32[N, OW]
    win = jnp.concatenate([state.win[:, g.ow:], fresh_cols], axis=1)
    first_gw = entry_gw0 + g.ow        # win col 0's global word, post-shift
    win_ring0 = jnp.mod(first_gw, g.rw)

    # ---- per-subject top-C index (R3) -------------------------------------
    used = subject >= 0
    sub_or_n = jnp.where(used, subject, n)
    subj_cl = jnp.maximum(subject, 0)
    top_key, top_slot = [], []
    remaining = used
    for _ in range(g.c):
        bk = ops.scatter_max(ops.zeros_nodes(jnp.uint32),
                             jnp.where(remaining, subject, n), rkey)
        bk_at_r = ops.gather(bk, subj_cl)
        hit = remaining & (rkey == bk_at_r) & (bk_at_r > 0)
        bs = ops.scatter_max(ops.full_nodes(-1, jnp.int32),
                             jnp.where(hit, subject, n), rr)
        top_key.append(bk)
        top_slot.append(bs)
        remaining = remaining & ~(rr == ops.gather(bs, subj_cl))
    n_per_subj = ops.scatter_add(ops.zeros_nodes(jnp.int32), sub_or_n,
                                 jnp.int32(1))
    index_overflow = state.index_overflow + ops.gsum(jnp.sum(
        n_per_subj > g.c).astype(jnp.int32))
    sus_hit = used & lattice.is_suspect(rkey)
    sus_bk = ops.scatter_max(ops.zeros_nodes(jnp.uint32),
                             jnp.where(sus_hit, subject, n), rkey)
    sus_slot = ops.scatter_max(
        ops.full_nodes(-1, jnp.int32),
        jnp.where(sus_hit & (rkey == ops.gather(sus_bk, subj_cl)),
                  subject, n), rr)

    def slot_pos(slot):
        """(in_win, win_col, ring_word, bit) for ring slot array `slot`."""
        sl = jnp.maximum(slot, 0)
        word_r = sl // WORD
        bit = (sl % WORD).astype(jnp.uint32)
        off = jnp.mod(word_r - win_ring0, g.rw)
        return ((slot >= 0) & (off < g.ww),
                jnp.minimum(off, g.ww - 1), word_r, bit)

    def knows_bit(rows, slot):
        """bool[shape]: does node rows[...] (GLOBAL ids) know slot[...]?"""
        return ops.knows_words(win, cold, slot_pos, rows, slot)

    # ---- Phases A+B+probe-verdicts, per probe pattern ---------------------
    pid = plan.partition_id
    loss_f = plan.loss.astype(jnp.float32)
    # integer loss threshold: bits >= ceil(loss*65536) == u >= loss
    # exactly (see RingRandomness); 65536*loss is an exact exponent
    # shift in f32 and ceil is exact, so no boundary sample can flip
    loss_thr = jnp.ceil(loss_f * jnp.float32(65536.0)).astype(jnp.uint32)
    if prog is not None:
        # per-node u16 lanes at period t, same integer geometry as
        # loss_thr: a leg delivers iff u >= loss_thr + send lane (rolled
        # from the sender) + local recv lane.  Reply legs (acks) use the
        # saturated send+reply lane — gray nodes gossip fine but their
        # acks get lost (Lifeguard's gray-failure workload).
        send_thr, recv_thr, reply_thr = faults.link_lanes(prog, t)
        send_thr16 = send_thr.astype(jnp.uint16)
        resp_thr16 = jnp.minimum(
            send_thr + reply_thr,
            jnp.uint32(faults.LANE_MAX)).astype(jnp.uint16)
    b_pig = min(cfg.max_piggyback, g.ww * WORD)
    win_slots_lin = jnp.mod(win_ring0 * WORD
                            + jnp.arange(g.ww * WORD, dtype=jnp.int32),
                            r_tot)
    elig = used[win_slots_lin].reshape(g.ww, WORD)
    elig_mask = jnp.sum(jnp.where(
        elig, jnp.uint32(1) << jnp.arange(WORD, dtype=jnp.uint32)[None, :],
        jnp.uint32(0)), axis=1)                                # u32[WW]

    # Piggyback-selection freshness (deviation R5): in "wave" scope the
    # selection pass re-runs against the LIVE window before every wave
    # (exact SWIM: an ack can relay a rumor its sender learned earlier in
    # the same period).  In "period" scope both the first-B selection and
    # the buddy/forced-bit knowledge are evaluated ONCE against the
    # start-of-period window (`sel_src` binds `win` before any wave
    # delivery) and reused by all 2+4k waves — deliveries still
    # accumulate into `win` per wave, so end-of-period state sees
    # everything; only the RELAY of mid-period knowledge waits for the
    # next period.  This removes 2+4k−1 full `_select_first_b` window
    # passes from the hot path (utils/roofline.py "waves" term).
    period_scope = cfg.ring_sel_scope == "period"
    sel_src = win                      # start-of-period window snapshot
    if period_scope:
        sel_base = _select_first_b(sel_src & elig_mask[None, :], b_pig,
                                   impl=cfg.ring_selb_kernel)

    def sel_now(forced):
        if period_scope:
            return sel_base | forced
        return _select_first_b(win & elig_mask[None, :], b_pig,
                               impl=cfg.ring_selb_kernel) | forced

    def sel_win():
        """The window senders consult for piggyback/buddy knowledge."""
        return sel_src if period_scope else win

    no_force = ops.zeros_nodes(jnp.uint32, g.ww)
    lha = state.lha
    delivered_ct = jnp.int32(0)        # telemetry: gossip waves delivered

    if prof is not None:
        # end of "select": window shifted, top-C index built, first-B
        # selection done (period scope).  Probe = win: already consumed
        # by every wave, so the marker adds no fusion-breaking reads
        # (sel_base must stay single-consumer — see the tap note below).
        sel_parts = dict(win=win, elig_mask=elig_mask, gone_key=gone_key,
                         overflow=overflow, index_overflow=index_overflow,
                         sus_slot=sus_slot, sus_bk=sus_bk,
                         top_key=jnp.stack(top_key),
                         top_slot=jnp.stack(top_slot))
        if period_scope:
            sel_parts["sel_base"] = sel_base
        if prof.cut("select", win, ops=ops, **sel_parts):
            return prof.captured

    if cfg.ring_probe == "rotor":
        # Rotor: target(i) = i + s_t; every wave is a roll (deviation R1).
        s_off = rnd.s_off
        target = jnp.mod(ids + s_off, n)
        roll_from = ops.roll_from

        # a not-yet-joined target is in nobody's membership list: idle.
        # (joined[target] is a rotation — roll, never gather: see
        # _col_select_multi's docstring for the measured cost gap.)
        prober = active & roll_from(joined, s_off, label="roll_probe_gate")

        # Scalar wave wire (ring_scalar_wire): "packed" narrows every
        # per-wave scalar payload to its information content — ok chains
        # ride as 1 bit/node, buddy cols/vals as byte codes — and fuses
        # each wave's scalars into ONE ops.roll_bundle call (a single
        # ppermute payload on the sharded twin).  Validation pins packed
        # to the fused period-scope rotor path, so the unfused branch
        # below only ever sees "wide".
        scalar_packed = cfg.ring_scalar_wire == "packed"

        def buddy_cv(d):
            """Compact (col, val) per sender i: forced window bit of the
            suspect witness about subject (i + d) mod n, when sender i
            knows it and it is in the window (val 0 = inert).
            Subject-table lookups are rolls; the sender's own word is a
            streamed window column-select (window-only: val is masked by
            in_win, so cold never matters).  Wide wire: (i32 col, u32
            val).  Packed wire: (narrow col, u8 code = bit + 1, 0 =
            inert) — the receiver rebuilds val as 1 << (code - 1), so
            only ~2 bytes/node travel instead of 8."""
            if not (cfg.lifeguard and cfg.buddy):
                return None
            if scalar_packed:
                sdt = wavepack.code_dtype(r_tot)
                slot = (roll_from((sus_slot + 1).astype(sdt), d,
                                  label="roll_buddy_slots"
                                  ).astype(jnp.int32) - 1)
            else:
                slot = roll_from(sus_slot, d, label="roll_buddy_slots")
            in_win, wcol, _, bit = slot_pos(slot)
            (wword,) = _col_select_multi(sel_win(), [wcol])
            kn = (slot >= 0) & (((wword >> bit) & 1) > 0)
            usebit = kn & in_win
            if scalar_packed:
                code = jnp.where(usebit, bit + 1,
                                 jnp.uint32(0)).astype(jnp.uint8)
                return wcol.astype(wavepack.code_dtype(g.ww - 1)), code
            return wcol, jnp.where(usebit, jnp.uint32(1) << bit,
                                   jnp.uint32(0))

        def force_mat(cv):
            """[N, WW] one-hot expansion of a compact forced bit — the
            per-wave (unfused) delivery path's sel contribution."""
            if cv is None:
                return no_force
            col, val = cv
            onehot = (jnp.arange(g.ww, dtype=jnp.int32)[None, :]
                      == col[:, None])
            return jnp.where(onehot, val[:, None], jnp.uint32(0))

        def wave_ok(send_flag_at_sender, d, u, cv=None, reply=False):
            """(ok bool[N], cv') per receiver i: the message from (i+d)
            arrived.  The ok chain needs the sender's flag and partition
            id at the receiver; on the packed wire those — plus the
            wave's link lane (u16, program plans only) and buddy
            (col, code), when given — fuse into ONE roll_bundle payload,
            so cv' comes back receiver-aligned.  On the wide wire each
            vector rolls separately and cv passes through sender-aligned
            (the fused staging rolls it).  `reply` marks ack legs (W2/
            W5/W6): those roll the saturated send+reply lane instead of
            the plain send lane."""
            lane = None
            if prog is not None:
                lane = resp_thr16 if reply else send_thr16
            if scalar_packed:
                parts = [send_flag_at_sender, pid]
                labels = ["roll_ok_waves", "roll_pid_waves"]
                if lane is not None:
                    parts.append(lane)
                    labels.append("roll_link_thr")
                if cv is not None:
                    parts.extend(cv)
                    labels.extend(["roll_buddy_cols", "roll_buddy_vals"])
                rolled = ops.roll_bundle(tuple(parts), d,
                                         labels=tuple(labels))
                flag_r, pid_r = rolled[0], rolled[1]
                nxt = 2
                if lane is not None:
                    lane_r = rolled[nxt]
                    nxt += 1
                cvr = tuple(rolled[nxt:]) if cv is not None else None
            else:
                flag_r = roll_from(send_flag_at_sender, d,
                                   label="roll_ok_waves")
                pid_r = roll_from(pid, d, label="roll_pid_waves")
                if lane is not None:
                    lane_r = roll_from(lane, d, label="roll_link_thr")
                cvr = cv
            if lane is None:
                thr = loss_thr
            else:
                # u <= 65535, so a composed threshold >= 65536 is
                # "never deliver"; all-u32 arithmetic, no overflow
                thr = loss_thr + lane_r.astype(jnp.uint32) + recv_thr
            ok = (flag_r & active & ~(part_on & (pid_r != pid))
                  & (u >= thr))
            return ok, cvr

        # Period scope: every wave ORs the SAME start-of-period selection
        # (sel_base | forced) into the window, and the ok chain never
        # reads the window — so the 2+4k delivery ORs commute and fuse
        # into ONE merge pass (ops/wavemerge.py; ≤32 waves per its u32
        # ok-pack).  Wave scope re-selects from the live window before
        # every wave, so deliveries must stay in-line.  The single
        # merge_waves call is also the sharded twin's ICI wire seam:
        # with cfg.ring_ici_wire="compact", ShardOps ships sel_base as
        # packed B-slot indices per wave instead of the dense window
        # (SWIM's bounded piggyback on the wire — ops/wavepack.py);
        # inert here, where the whole node axis is one address space.
        fused = period_scope and (2 + 4 * k) <= 32
        waves = []              # (ok, off, compact buddy cv | None)

        def deliver(ok, d, cv=None):
            """One wave: receiver i ORs sel row (i + d) mod n under ok."""
            nonlocal win, delivered_ct
            if tap is not None:
                delivered_ct = delivered_ct + jnp.sum(ok).astype(jnp.int32)
            if fused:
                waves.append((ok, d, cv))
            else:
                sel_w = sel_now(force_mat(cv))
                win = win | jnp.where(ok[:, None],
                                      roll_from(sel_w, d,
                                                label="roll_sel_waves"),
                                      jnp.uint32(0))

        # W1: ping i -> i+s.  Receiver j hears from sender j−s.  The
        # buddy payload shares W1's offset, so it rides W1's bundle on
        # the packed wire.
        ok1, cv1 = wave_ok(prober & active, -s_off, rnd.loss_w1,
                           buddy_cv(s_off))                  # per recv j
        deliver(ok1, -s_off, cv1)
        # W2: ack j=i+s -> i (acks iff the ping arrived; ok1 is indexed
        # by j already).  Receiver i hears from i+s.
        ok2, _ = wave_ok(ok1, s_off, rnd.loss_w2,
                         reply=True)                         # per recv i
        deliver(ok2, s_off)
        acked = ok2 & prober

        need = prober & ~acked
        relayed = ops.zeros_nodes(jnp.bool_)
        for a in range(k):
            q = rnd.q_off[a]
            d4 = s_off - q
            # W3: ping-req i -> i+q.  Receiver p hears from p−q.
            ok3, _ = wave_ok(need, -q, rnd.loss_w3[:, a])    # per recv p
            deliver(ok3, -q)
            # W4: proxy ping p -> p+d4 (the original target j=i+s).
            # Receiver j hears from j−d4 = p.
            ok4, cv4 = wave_ok(ok3, -d4, rnd.loss_w4[:, a],
                               buddy_cv(d4))                 # per recv j
            deliver(ok4, -d4, cv4)
            # W5: target ack j -> j−d4 (back to proxy p).  Receiver p
            # hears from p+d4.
            ok5, _ = wave_ok(ok4, d4, rnd.loss_w5[:, a],
                             reply=True)                     # per recv p
            deliver(ok5, d4)
            # W6: relay ack p -> p−q (back to prober i).  Receiver i
            # hears from i+q.
            ok6, _ = wave_ok(ok5, q, rnd.loss_w6[:, a],
                             reply=True)                     # per recv i
            deliver(ok6, q)
            relayed = relayed | (ok6 & need)

        if fused and prof is not None:
            # end of "ppermute": the full ok chain (per-wave delivery
            # flags and their node-vector rolls) is decided; nothing has
            # touched the window yet.  NOTE the fused path stages its
            # payloads AFTER the ok chain, so the cut order here is
            # ppermute -> pack (obs/prof.py phases_for documents it).
            oks_now = jnp.stack([w[0] for w in waves])
            if prof.cut("ppermute", oks_now, ops=ops, win=win):
                return prof.captured
        if fused:
            # Buddy forced bits ride as receiver-aligned compact rows:
            # mask val by the wave's delivery (roll of sel|forced ==
            # roll(sel) | roll(forced), bit-OR exact).  Wide wire: roll
            # the sender-side (col, val) here by the wave's offset.
            # Packed wire: (col, code) already arrived receiver-aligned
            # inside the wave's bundle — decode val = 1 << (code - 1)
            # locally (where(ok, roll(val)) == where(ok & rolled-use,
            # rolled 1<<bit), so the decode is bitwise-equal to the
            # wide path).
            bcols, bvals = [], []
            for ok, d, cv in waves:
                if cv is None:
                    continue
                if scalar_packed:
                    col_r, code_r = cv
                    has = ok & (code_r > 0)
                    shift = jnp.where(code_r > 0, code_r - 1,
                                      0).astype(jnp.uint32)
                    bcols.append(col_r.astype(jnp.int32))
                    bvals.append(jnp.where(has, jnp.uint32(1) << shift,
                                           jnp.uint32(0)))
                else:
                    col, val = cv
                    bcols.append(roll_from(col, d,
                                           label="roll_buddy_cols"))
                    bvals.append(jnp.where(
                        ok, roll_from(val, d, label="roll_buddy_vals"),
                        jnp.uint32(0)))
            if prof is not None:
                # end of "pack": wave payload staging (buddy compact
                # rows rolled+masked; the sharded compact wire's B-slot
                # packing rides inside merge_waves and lands in
                # "merge" here)
                pk_parts = dict(win=win,
                                oks=jnp.stack([w[0] for w in waves]))
                if bvals:
                    pk_parts["bcol"] = jnp.stack(bcols)
                    pk_parts["bval"] = jnp.stack(bvals)
                if prof.cut("pack", pk_parts.get("bval", win), ops=ops,
                            **pk_parts):
                    return prof.captured
            win = ops.merge_waves(
                win, sel_base, [w[0] for w in waves],
                [w[1] for w in waves], bcols, bvals,
                impl=cfg.ring_wave_kernel)

        if prof is not None and prof.cut("merge", win, ops=ops, win=win,
                                         acked=acked, relayed=relayed):
            # end of "merge": every wave's selection is OR-delivered
            # into the window (one fused merge_waves pass, or the
            # in-line per-wave ORs on the wave-scope path)
            return prof.captured

        probe_ok = acked | relayed
        failed = prober & ~probe_ok
        s_probe = lha
        if cfg.lifeguard:
            lha = jnp.where(prober,
                            jnp.clip(lha + jnp.where(failed, 1, -1), 0,
                                     cfg.lha_max), lha)
            # bits*(1+s) < 65536 == bits/65536 < fl(1/(1+s)) for every
            # (bits, s), s <= 256 — checked exhaustively (RingRandomness)
            assert cfg.lha_max <= 256, "integer thin compare verified to 256"
            thin = (rnd.lha_u * (1 + s_probe).astype(jnp.uint32)
                    < jnp.uint32(65536))
            failed = failed & thin
        # view_of(ids, target) + Phase C's self-suspicion word, fused:
        # subject tables roll (target is a rotation of ids), and all C+1
        # heard-word queries share ONE streamed pass over win and cold.
        if scalar_packed:
            # slot + 1 in the narrowest dtype holding [0, r_tot]
            # (0 = "no slot" stands in for -1), decoded after the roll.
            sdt = wavepack.code_dtype(r_tot)
            q_slots = [roll_from((top_slot[lvl] + 1).astype(sdt), s_off,
                                 label="roll_view_slots"
                                 ).astype(jnp.int32) - 1
                       for lvl in range(g.c)]
        else:
            q_slots = [roll_from(top_slot[lvl], s_off,
                                 label="roll_view_slots")
                       for lvl in range(g.c)]
        q_slots.append(sus_slot)               # self query: subj == ids
        q_pos = [slot_pos(s) for s in q_slots]
        q_win = _col_select_multi(win, [p[1] for p in q_pos])
        # Fused deferred-flush + select: cold becomes post-flush here,
        # exactly as an immediate Phase-0d where-pass would have left
        # it (bitwise contract: tests/test_coldsel.py pins the pallas
        # and jnp lowerings equal element-for-element).
        cold, q_cold_arr = coldsel.cold_update_select(
            cold, flush_rows, flush_vals,
            jnp.stack([p[2] for p in q_pos]),
            impl=cfg.ring_cold_kernel)
        q_cold = [q_cold_arr[i] for i in range(len(q_pos))]
        q_kn = []
        for (ok, _, _, bit), wv, cv, s in zip(q_pos, q_win, q_cold,
                                              q_slots):
            word = jnp.where(ok, wv, cv)
            q_kn.append((s >= 0) & (((word >> bit) & 1) > 0))
        # Verdict deferral (both wires): instead of rolling gone_key and
        # all C top keys to the viewer (C+1 u32 vectors), ship the C
        # known-bits BACK to the subject (bool, 1 bit each on the packed
        # wire), fold the key max at the subject, and roll the ONE u32
        # verdict forward.  Rolls commute with elementwise max/where, so
        # viewed_tk is bitwise-identical to the direct form.
        kn_back = (ops.roll_bundle(tuple(q_kn[:g.c]), -s_off,
                                   labels=("roll_view_known",) * g.c)
                   if g.c else ())
        tk_subj = jnp.maximum(lattice.alive_key(jnp.uint32(0)), gone_key)
        for lvl in range(g.c):
            tk_subj = jnp.maximum(
                tk_subj, jnp.where(kn_back[lvl], top_key[lvl],
                                   jnp.uint32(0)))
        viewed_tk = roll_from(tk_subj, s_off, label="roll_view_verdict")
        self_key = jnp.where(q_kn[g.c], sus_bk, jnp.uint32(0))
        susp_subject = target
        susp_orig = ids
    else:
        # Pull-uniform (cfg.ring_probe == "pull"): each node j samples its
        # own IN-probe lane from the environment side, preserving uniform
        # probing's first-detection law with gather-only delivery.
        # Documented deviations (vs exact uniform SWIM):
        #   P1. One prober lane per node per period, fired with the EXACT
        #       no-probe probability P(m_j=0) = (1 − 1/(M−1))^{L_j} of the
        #       push model (M = joined members, L_j = live members other
        #       than j) — so the geometric first-detection law holds
        #       exactly, join churn included; periods where several nodes
        #       probed j are folded into one prober.
        #   P2. The prober id is the first live draw of A=3 uniforms over
        #       the other ids (all-dead draws: lane idles — pessimistic);
        #       a proxy may coincide with the prober/target.
        #   P3. Gossip flows only TOWARD a node (the direct ping plus the
        #       first successful proxy ping deliver piggyback); the
        #       ack-direction gossip of exact SWIM (each prober hears its
        #       target's piggyback) is modeled by one "ack-pull" contact
        #       per node from an INDEPENDENT uniform draw at the composed
        #       ping+ack delivery probability — same marginal flow, but
        #       the draw is decoupled from the node's simulated out-probe.
        #   P4. Each two-hop message path composes its two loss legs into
        #       one draw against (1−loss)²  (same marginal probability).
        if not ops.supports_random_gather:
            raise NotImplementedError(
                "pull-uniform probing needs arbitrary-row exchanges; "
                "this ops layout does not provide them")
        if prog is not None:
            # pull mode draws each contact at an env-side COMPOSED
            # probability (deviations P3/P4) and never sees individual
            # legs, so per-node lane programs have no sound insertion
            # point — scenario specs with link/gray segments must use
            # the rotor probe.
            raise NotImplementedError(
                "FaultProgram link/gray segments are not supported by "
                "pull-uniform probing; use ring_probe='rotor'")
        pr = rnd.pull
        sel_all = sel_now(no_force)
        # P(m_j = 0) = (1 − 1/(M−1))^{L_j}: a live prober picks uniformly
        # among the M−1 OTHER JOINED members (membership-list semantics,
        # join-churn aware), and there are L_j live probers besides j.
        members = ops.gsum(jnp.sum(joined).astype(jnp.int32))
        lj = live_total - active.astype(jnp.int32)
        # 1/(M−1) via a HOST-computed f32 reciprocal table rather than a
        # device divide: IEEE-754 guarantees correctly-rounded f32 mul
        # (pow_f32's only op), but XLA:TPU may lower f32 divide to a
        # reciprocal approximation — a 1-ulp base difference would break
        # the bitwise engine↔oracle contract on the flagship backend.
        # numpy's host divide is correctly rounded, identical to the
        # oracle's np.float32 divide by construction.
        import numpy as _np

        # Exact integer ramp rounded ONCE to f32 — float-dtype arange is
        # inexact above 2^24 and would silently diverge from the oracle's
        # np.float32(int) rounding at that scale; int64→f32 cast matches
        # it for every i.
        _ramp = _np.arange(n, dtype=_np.int64).astype(_np.float32)
        recip = jnp.asarray(
            _np.float32(1.0) / _np.maximum(_ramp, _np.float32(1.0)))
        di = jnp.clip(members - 1, 1, n - 1)
        base = jnp.float32(1.0) - recip[di]
        p0 = jnp.where(members >= 2, pow_f32(base, jnp.maximum(lj, 0)),
                       jnp.float32(1.0))
        probed = (pr.m_u >= p0) & joined          # only members are probed

        def draw_id(u):
            idx = (u * jnp.float32(n - 1)).astype(jnp.int32)
            idx = jnp.minimum(idx, n - 2)
            return idx + (idx >= ids).astype(jnp.int32)

        # All cross-node reads below go through ops.gather/gather_rows:
        # on the single-program layout these are plain indexing; on the
        # sharded layout (round 4 — this closes VERDICT r3 item 7's
        # "build it" arm) each becomes a psum of owned entries, i.e.
        # the per-period all-to-all of selection rows the scatter-free
        # rotor path exists to avoid. The values are bitwise-identical
        # across layouts (exactly one shard owns each element).
        src = draw_id(pr.src_u[:, 0])
        src_ok = ops.gather_nodewise(active, src)
        for a in range(1, PULL_SRC_ATTEMPTS):
            # Attempts are sequential by meaning (attempt a only matters
            # when a-1 missed a live peer), but nothing in the dataflow
            # says so, and XLA's scheduler issues every draw_id/gather
            # up front — PULL_SRC_ATTEMPTS concurrent [N] temps in the
            # 16M memwall capture.  Threading src_ok through the next
            # draw's uniforms (identity barrier, bitwise-neutral) keeps
            # one attempt in flight at a time.
            u_a, _ = jax.lax.optimization_barrier(
                (pr.src_u[:, a], src_ok))
            nxt = draw_id(u_a)
            src = jnp.where(src_ok, src, nxt)
            src_ok = src_ok | ops.gather_nodewise(active, nxt)
        probe_live = probed & src_ok

        def pid_of(idx):
            return ops.gather_nodewise(pid, idx)

        thr2 = 1.0 - (1.0 - loss_f) * (1.0 - loss_f)
        # hoisted: pid_of(src) is loop-invariant, and on the sharded
        # layout every pid_of call is a full D-hop ring-pass exchange
        pid_src = pid_of(src)
        # direct ping src -> j and its ack
        d_fwd_ok = (probe_live & active
                    & ~(part_on & (pid_src != pid))
                    & (pr.d_fwd >= loss_f))
        win = win | jnp.where(d_fwd_ok[:, None],
                              ops.gather_rows(sel_all, src),
                              jnp.uint32(0))
        acked_lane = d_fwd_ok & (pr.d_back >= loss_f)
        # indirect: k proxies, two-hop paths with composed legs (P4)
        need = probe_live & ~acked_lane
        relayed_lane = ops.zeros_nodes(jnp.bool_)
        px_deliver = ops.zeros_nodes(jnp.bool_)
        px_src = ops.zeros_nodes(jnp.int32)
        for b in range(k):
            p_b = draw_id(pr.px_u[:, b])
            pid_pb = pid_of(p_b)
            path_up = (need & ops.gather_nodewise(active, p_b)
                       & ~(part_on & (pid_src != pid_pb))
                       & ~(part_on & (pid_pb != pid)))
            w4_ok = path_up & active & (pr.px_fwd[:, b] >= thr2)
            first = w4_ok & ~px_deliver
            px_src = jnp.where(first, p_b, px_src)
            px_deliver = px_deliver | w4_ok
            relayed_lane = relayed_lane | (
                w4_ok & (pr.px_back[:, b] >= thr2))
        # The three [N, WW] selection-row gathers below (direct, proxy,
        # ack-pull) each produce a ~1GB result at 16M nodes that the
        # following OR consumes immediately — but gather k+1 has no data
        # dependence on OR k, so the latency-hiding scheduler issues all
        # three up front and holds ~3GB of gather results live at peak
        # (the dominant HLO-temp terms of the 16M one-chip capture).
        # Threading the accumulated `win` through the next gather's index
        # via an optimization_barrier (identity op, bitwise-neutral)
        # serializes them: peak holds ONE gather result at a time.
        px_src, win = jax.lax.optimization_barrier((px_src, win))
        win = win | jnp.where(px_deliver[:, None],
                              ops.gather_rows(sel_all, px_src),
                              jnp.uint32(0))
        # ack-direction gossip (P3'): one contact from an independent
        # uniform draw, delivered iff a ping+ack round trip would be —
        # both legs composed into one draw against thr2 = 1-(1-loss)^2,
        # the same marginal probability as exact SWIM's ack piggyback
        aq = draw_id(pr.ack_u)
        ack_gossip_ok = (active & ops.gather_nodewise(active, aq)
                         & ~(part_on & (pid != pid_of(aq)))
                         & (pr.ack_leg >= thr2))
        aq_g, win = jax.lax.optimization_barrier((aq, win))
        win = win | jnp.where(ack_gossip_ok[:, None],
                              ops.gather_rows(sel_all, aq_g),
                              jnp.uint32(0))
        if prof is not None and prof.cut(
                "merge", win, ops=ops, win=win, acked=acked_lane,
                relayed=relayed_lane):
            # end of "merge" (pull): direct + proxy + ack-pull gossip
            # all gathered and OR-delivered
            return prof.captured
        failed = probe_live & ~(acked_lane | relayed_lane)
        if tap is not None:
            delivered_ct = (jnp.sum(d_fwd_ok) + jnp.sum(px_deliver)
                            + jnp.sum(ack_gossip_ok)).astype(jnp.int32)
        # src's view of j: the subject is the viewer's OWN row, so the
        # per-subject tables index locally; only the heard-bit lookup
        # crosses shards (ops.knows_words)
        viewed_tk = jnp.maximum(lattice.alive_key(jnp.uint32(0)), gone_key)
        for lvl in range(g.c):
            kn = ops.knows_nodewise(win, cold, slot_pos, src,
                                    top_slot[lvl])
            viewed_tk = jnp.maximum(
                viewed_tk, jnp.where(kn, top_key[lvl], jnp.uint32(0)))
        # Phase C self query: sus_slot/sus_bk indexed by ids is identity
        self_key = jnp.where(
            ops.knows_self(win, cold, slot_pos, sus_slot), sus_bk,
            jnp.uint32(0))
        susp_subject = ids
        susp_orig = src

    v_status = lattice.status_of(viewed_tk)
    mk_suspect = failed & (v_status == 0)
    re_suspect = failed & (v_status == 1)
    susp_key = lattice.suspect_key(lattice.incarnation_of(viewed_tk))

    # ---- Phase C: refutation + sentinel expiry ----------------------------
    # refutation: i knows a suspect rumor about i outranking its aliveness
    # (self_key computed per probe branch above, on the fused query pass)
    refute = active & lattice.is_suspect(self_key) & (
        self_key > lattice.alive_key(state.inc_self))
    new_inc = jnp.where(refute, lattice.incarnation_of(self_key) + 1,
                        state.inc_self).astype(jnp.uint32)
    inc_self = new_inc
    if cfg.lifeguard:
        lha = jnp.where(refute, jnp.clip(lha + 1, 0, cfg.lha_max), lha)

    # sentinel expiry ([R]-sized)
    filled = jnp.sum(snode >= 0, axis=-1).astype(jnp.int32)
    if cfg.lifeguard and cfg.dynamic_suspicion:
        from swim_tpu.models.rumor import dynamic_timeout_table
        timeout = dynamic_timeout_table(cfg)[jnp.clip(filled, 0, s_cap)]
    else:
        timeout = jnp.full((r_tot,), cfg.suspicion_periods, jnp.int32)
    sent_alive = ((snode >= 0)
                  & (ops.gather(plan.crash_step,
                                jnp.maximum(snode, 0)) > t))
    deadline_hit = sent_alive & (t >= stime + timeout[:, None])
    is_susp_r = lattice.is_suspect(rkey)
    subj_r = jnp.maximum(subject, 0)
    gone_at_r = ops.gather(gone_key, subj_r)
    higher_known = jnp.broadcast_to((gone_at_r > rkey)[:, None],
                                    snode.shape)
    # All C levels' heard-bit probes ride ONE knows_bit call: per-level
    # calls cost two 16k-element generic gathers EACH (win row + cold
    # column), and TPU executes those near-serially — the round-4
    # profile measured the 6 separate gathers at ~1.5 ms/period @ 1M.
    # Batched [R, S*C] they are two gathers total, same element count.
    snode_cl = jnp.maximum(snode, 0)
    oslots, cands = [], []
    for lvl in range(g.c):
        oslot = ops.gather(top_slot[lvl], subj_r)              # [R]
        okey = ops.gather(top_key[lvl], subj_r)
        cands.append(((okey > rkey) & (oslot >= 0))[:, None])
        oslots.append(jnp.broadcast_to(oslot[:, None], snode.shape))
    s_lanes = snode.shape[1]
    rows_b = jnp.concatenate([snode_cl] * g.c, axis=1)      # [R, S*C]
    slots_b = jnp.concatenate(oslots, axis=1)

    # The probe results are consumed ONLY where a sentinel deadline
    # expired this period (can_confirm = deadline_hit & ~higher_known),
    # and expiries per period track the origination budget (~OB), not
    # the table size R — so in steady state the [R, S*C] batch gathers
    # ~48k elements to use a few hundred.  Exact two-tier evaluation:
    # compact the expiring rumor rows (first_true on the REPLICATED
    # [R] hit vector — plain _first_true_idx, not the node-axis
    # ops.first_true_nodes) and probe only those; if a burst overflows
    # the cap, fall back to the full batch inside lax.cond (both
    # branches exact; TPU gather cost is per-element, so the small
    # branch is the ~0.9 ms/period saving measured at 1M).  Works
    # under BOTH ops: the predicate is computed from replicated data,
    # so every shard takes the same cond branch, and ShardOps'
    # knows_words psum shrinks with the compacted query
    # (tests/test_ring_shard.py pins sharded == single-program
    # bitwise; test_sentinel_query_cap_branches_bitwise_equal pins the
    # branches against each other).
    # Only rows whose probe could still flip a confirm THIS period are
    # worth probing.  The deadline test is `>=`, so a row keeps
    # "hitting" every period until it is recycled — stale rows
    # (already confirmed, no longer suspect, out-ranked by the
    # subject's known death, or an unused lane) accumulate until
    # sum(hit_r) overflows any fixed cap: the round-4 TPU profile
    # measured the full-batch cond branch firing 34/50 periods at 1M
    # for this reason alone.  Their kn values are dead code — `confirm`
    # repeats exactly these conjuncts — and every gate input is
    # replicated under ShardOps (rkey/subject tables are replicated;
    # gone_at_r is already gathered for higher_known), so the cond
    # predicate stays shard-uniform and both branches stay exact.
    dead_key_r = lattice.dead_key(lattice.incarnation_of(rkey))
    actionable = (used & is_susp_r & ~confirmed
                  & (dead_key_r > gone_at_r) & ~(gone_at_r > rkey))
    hit_r = jnp.any(deadline_hit, axis=-1) & actionable     # [R]
    cap = min(_SENTINEL_QUERY_CAP, r_tot)
    if getattr(ops, "supports_random_gather", False) and cap < r_tot:
        rid = _first_true_idx(hit_r, cap)                   # [cap]
        rid_cl = jnp.minimum(rid, r_tot - 1)

        def _compacted(_):
            rows_c = rows_b[rid_cl]                         # [cap, S*C]
            slots_c = slots_b[rid_cl]
            kn_c = knows_bit(rows_c, slots_c)
            return (jnp.zeros(rows_b.shape, jnp.bool_)
                    .at[rid].set(kn_c, mode="drop"))

        def _full(_):
            return knows_bit(rows_b, slots_b)

        kn_b = jax.lax.cond(
            jnp.sum(hit_r.astype(jnp.int32)) <= cap,
            _compacted, _full, None)
    else:
        kn_b = knows_bit(rows_b, slots_b)
    for lvl in range(g.c):
        kn = kn_b[:, lvl * s_lanes:(lvl + 1) * s_lanes]
        higher_known = higher_known | (cands[lvl] & kn)
    can_confirm = deadline_hit & ~higher_known
    confirm = (used & is_susp_r & ~confirmed
               & (dead_key_r > gone_at_r)
               & jnp.any(can_confirm, axis=-1))
    conf_s = jnp.argmax(can_confirm, axis=-1)
    conf_node = jnp.take_along_axis(snode, conf_s[:, None], axis=-1)[:, 0]

    # ---- Phase D: new originations into the free fresh lanes --------------
    # Channels, priority order: confirms > refutes > new/independent
    # suspicions (carried lanes were already placed in Phase 0).  The
    # global candidate list is indexed [0,R) = confirms (replicated),
    # [R, R+N) = refutes, [R+N, R+2N) = suspicions (node-axis); its
    # first OB true entries ascending — exactly the priority order — are
    # found per channel and merged.  top_k, never nonzero: nonzero's
    # compaction lowers to a full-length scatter, which TPU serializes
    # (measured 17.5 ms at ~2M candidates); and per-channel compaction
    # is what lets the sharded ops find its node-axis candidates with
    # one small all-gather instead of a global scatter.
    suspect = mk_suspect | re_suspect
    n_ext = 0 if ext is None else ext.subject.shape[0]
    m_cand = r_tot + 2 * n + n_ext
    total = (jnp.sum(confirm).astype(jnp.int32)
             + ops.gsum(jnp.sum(refute).astype(jnp.int32))
             + ops.gsum(jnp.sum(suspect).astype(jnp.int32)))
    kk1, _ = jax.lax.top_k(jnp.where(confirm, r_tot - rr, 0), ob)
    ci1 = jnp.where(kk1 > 0, r_tot - kk1, m_cand)
    ci2 = ops.first_true_nodes(refute, ob)
    ci2 = jnp.where(ci2 < n, r_tot + ci2, m_cand)
    ci3 = ops.first_true_nodes(suspect, ob)
    ci3 = jnp.where(ci3 < n, r_tot + n + ci3, m_cand)
    chans = [ci1, ci2, ci3]
    if ext is not None:
        # external channel (host bridge): replicated [E] entries, lowest
        # priority — an external claim never displaces an internal one
        ext_valid = ext.subject >= 0
        total = total + jnp.sum(ext_valid).astype(jnp.int32)
        chans.append(jnp.where(
            ext_valid,
            r_tot + 2 * n + jnp.arange(n_ext, dtype=jnp.int32), m_cand))
    cand = jnp.concatenate(chans)
    mk_, _ = jax.lax.top_k(jnp.where(cand < m_cand, m_cand - cand, 0), ob)
    ci = jnp.where(mk_ > 0, m_cand - mk_, m_cand)
    got = ci < m_cand
    # channel decode + candidate fields (all replicated [OB]; node-axis
    # values arrive through ops.gather by global id)
    is1 = ci < r_tot
    i1 = jnp.clip(ci, 0, r_tot - 1)
    is2 = got & ~is1 & (ci < r_tot + n)
    j2 = jnp.clip(ci - r_tot, 0, n - 1)
    is3 = got & ~is1 & ~is2 & (ci < r_tot + 2 * n)
    j3 = jnp.clip(ci - r_tot - n, 0, n - 1)
    if ext is not None:
        is4 = got & ~is1 & ~is2 & ~is3
        j4 = jnp.clip(ci - r_tot - 2 * n, 0, n_ext - 1)
        sub3 = jnp.where(is3, ops.gather(susp_subject, j3),
                         ext.subject[j4])
        key3 = jnp.where(is3, ops.gather(susp_key, j3), ext.key[j4])
        org3 = jnp.where(is3, ops.gather(susp_orig, j3), ext.origin[j4])
        hear3 = jnp.where(is3, org3, ext.hearer[j4])
    else:
        sub3 = ops.gather(susp_subject, j3)
        key3 = ops.gather(susp_key, j3)
        org3 = ops.gather(susp_orig, j3)
        hear3 = org3
    subj_c = jnp.where(
        got, jnp.where(is1, subject[i1],
                       jnp.where(is2, j2, sub3)),
        -1)
    key_c = jnp.where(
        got, jnp.where(
            is1, dead_key_r[i1],
            jnp.where(is2,
                      lattice.alive_key(ops.gather(new_inc, j2)),
                      key3)), 0)
    orig_c = jnp.where(
        got, jnp.where(is1, jnp.maximum(conf_node[i1], 0),
                       jnp.where(is2, j2, org3)), 0)
    if ext is not None:
        # who gets the heard-bit: the datagram's receiving node for
        # external entries, the originator itself everywhere else
        hear_c = jnp.where(
            got, jnp.where(is1, jnp.maximum(conf_node[i1], 0),
                           jnp.where(is2, j2, hear3)), 0)
        susp_c = is3 | (is4 & lattice.is_suspect(key_c))
    else:
        hear_c = orig_c
        susp_c = is3
    srcslot_c = jnp.where(got & is1, i1, -1)
    overflow = overflow + jnp.maximum(total - ob, 0)

    # dedup within candidates (earlier wins) and vs the live table
    eq = ((subj_c[:, None] == subj_c[None, :])
          & (key_c[:, None] == key_c[None, :]))
    earlier = jnp.tril(jnp.ones((ob, ob), jnp.bool_), k=-1)
    dup_mask = eq & earlier & got[None, :] & got[:, None]
    dup_prev = jnp.any(dup_mask, axis=-1)
    win_idx = jnp.argmax(dup_mask, axis=-1)
    ex = (used[None, :] & (subj_c[:, None] == subject[None, :])
          & (key_c[:, None] == rkey[None, :]))
    ex_match = jnp.any(ex, axis=-1)
    ex_slot = jnp.argmax(ex, axis=-1).astype(jnp.int32)

    # free fresh lanes: those not carried in Phase 0
    (free_lane,) = jnp.nonzero(~carry, size=ob, fill_value=ob)
    n_free = jnp.sum(~carry).astype(jnp.int32)
    place = got & ~dup_prev & ~ex_match
    apos = jnp.cumsum(place.astype(jnp.int32)) - 1
    alloc_ok = place & (apos < n_free)
    lane_c = jnp.where(alloc_ok,
                       free_lane[jnp.clip(apos, 0, ob - 1)], ob)
    slot_new = jnp.where(alloc_ok,
                         fresh_slots[jnp.clip(lane_c, 0, ob - 1)], -1)
    overflow = overflow + jnp.sum(place & ~alloc_ok).astype(jnp.int32)
    slot_f0 = jnp.where(ex_match, ex_slot, slot_new)
    slot_f = jnp.where(dup_prev, slot_f0[win_idx], slot_f0).astype(jnp.int32)
    placed = got & (slot_f >= 0)

    wslot = jnp.where(alloc_ok, slot_f, r_tot)
    subject = subject.at[wslot].set(subj_c, mode="drop")
    rkey = rkey.at[wslot].set(key_c, mode="drop")
    birth0 = birth0.at[wslot].set(t, mode="drop")
    confirmed = confirmed.at[wslot].set(False, mode="drop")
    snode = snode.at[wslot].set(-1, mode="drop")
    stime = stime.at[wslot].set(0, mode="drop")

    # originators hear their rumor: tiny scatter into the fresh win cols.
    # scatter-ADD is scatter-OR here: the added one-hots live in freshly
    # allocated free lanes, which are bit-disjoint from every bit already
    # set in the word (carried lanes) and from each other (each lane is
    # allocated once) — while scatter-max would REPLACE smaller existing
    # bit patterns with the one-hot.
    fw = jnp.clip(lane_c // WORD, 0, g.ow - 1)
    fbit = (jnp.clip(lane_c, 0, ob - 1) % WORD).astype(jnp.uint32)
    orig_rows = jnp.where(alloc_ok, hear_c, n)
    win = ops.scatter_or_word(
        win, orig_rows, g.ww - g.ow + fw,
        jnp.where(alloc_ok, jnp.uint32(1) << fbit, jnp.uint32(0)))

    # sentinel joins (same scheme as the rumor engine)
    joiner = placed & susp_c
    tgt_r = jnp.where(joiner, slot_f, r_tot)
    already = jnp.any(snode[jnp.clip(tgt_r, 0, r_tot - 1)]
                      == orig_c[:, None], axis=-1) & joiner
    joiner = joiner & ~already
    tgt_r = jnp.where(joiner, slot_f, r_tot)
    same_r = (tgt_r[:, None] == tgt_r[None, :])
    grp_rank = jnp.sum(same_r & earlier & joiner[None, :],
                       axis=-1).astype(jnp.int32)
    fill_now = jnp.sum(snode[jnp.clip(tgt_r, 0, r_tot - 1)] >= 0,
                       axis=-1).astype(jnp.int32)
    spos = fill_now + grp_rank
    j_ok = joiner & (spos < s_cap)
    wr = jnp.where(j_ok, tgt_r, r_tot)
    ws = jnp.clip(spos, 0, s_cap - 1)
    snode = snode.at[wr, ws].set(orig_c, mode="drop")
    stime = stime.at[wr, ws].set(t, mode="drop")

    conf_slot = jnp.where(placed & (srcslot_c >= 0), srcslot_c, r_tot)
    confirmed = confirmed.at[conf_slot].set(True, mode="drop")

    # inactive nodes are frozen
    inc_self = jnp.where(active, inc_self, state.inc_self)
    lha = jnp.where(active, lha, state.lha)

    if prof is not None and prof.cut(
            "commit", subject, ops=ops, win=win, cold=cold,
            inc_self=inc_self, lha=lha, gone_key=gone_key, rkey=rkey,
            birth0=birth0, snode=snode, stime=stime, confirmed=confirmed,
            overflow=overflow, index_overflow=index_overflow):
        # end of "commit": verdicts, query pass, Phase C+D, full state
        # assembled — this prefix is the whole step minus the tap
        return prof.captured

    if tap is not None:
        # ---- telemetry tap (swim_tpu/obs/engine.py EngineFrame) ----------
        # Every value is reduced through the ops seam, so single-program
        # and sharded layouts publish identical replicated i32 scalars.
        # Selection stats validate the compact-wire packing headroom
        # (PR-1): how full the B piggyback budget runs vs the eligible
        # start-of-period window.  Derived from the selection INPUT, not
        # from sel_base: `_select_first_b` keeps the first B set bits per
        # row, so selected == min(popcount(masked window), B) exactly —
        # and reading sel_base here would add a second consumer that
        # breaks the fused wave merge (measured +10% per period at 65k
        # on CPU vs ~2% for this form; the 5% overhead contract).
        occ_bits = jnp.sum(jax.lax.population_count(
            sel_src & elig_mask[None, :]), axis=-1).astype(jnp.int32)
        row_bits = jnp.minimum(occ_bits, b_pig)                  # [N]
        tap["sel_slots_selected"] = ops.gsum(jnp.sum(row_bits))
        tap["sel_rows_saturated"] = ops.gsum(jnp.sum(
            ((row_bits >= b_pig) & active).astype(jnp.int32)))
        tap["sel_slots_max"] = ops.gmax(jnp.max(row_bits))
        tap["win_occupancy"] = ops.gsum(jnp.sum(occ_bits))
        tap["waves_delivered"] = ops.gsum(delivered_ct)
        tap["probes_failed"] = ops.gsum(jnp.sum(failed).astype(jnp.int32))
        tap["overflow"] = overflow
        tap["index_overflow"] = index_overflow
        if prof is not None:
            # tap values are already replicated reductions — no ops
            prof.cut("telemetry_tap", tap["sel_slots_selected"])

    return RingState(
        win=win, cold=cold, inc_self=inc_self, lha=lha, gone_key=gone_key,
        subject=subject, rkey=rkey, birth0=birth0,
        sent_node=snode, sent_time=stime, confirmed=confirmed,
        overflow=overflow, index_overflow=index_overflow, step=t + 1,
    )


@functools.partial(jax.jit, static_argnums=(0, 4))
def run(cfg: SwimConfig, state: RingState, plan: FaultPlan,
        root_key: jax.Array, periods: int) -> RingState:
    """Run `periods` protocol periods under one fused lax.scan."""

    def body(stt, _):
        rnd = draw_period_ring(root_key, stt.step, cfg)
        return step(cfg, stt, plan, rnd), None

    state, _ = jax.lax.scan(body, state, None, length=periods)
    return state


class RingEngine:
    """Convenience wrapper holding (cfg, plan, state) with a jitted step."""

    def __init__(self, cfg: SwimConfig, plan: FaultPlan,
                 root_key: jax.Array | None = None):
        self.cfg = cfg
        self.plan = plan
        self.root_key = (root_key if root_key is not None
                         else jax.random.key(0))
        self.state = init_state(cfg)
        self._step = jax.jit(functools.partial(step, cfg))

    def run(self, periods: int) -> RingState:
        self.state = run(self.cfg, self.state, self.plan, self.root_key,
                         periods)
        return self.state

    def step_once(self, rnd: RingRandomness | None = None) -> RingState:
        if rnd is None:
            rnd = draw_period_ring(self.root_key, self.state.step, self.cfg)
        self.state = self._step(self.state, self.plan, rnd)
        return self.state
