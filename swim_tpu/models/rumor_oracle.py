"""Scalar rumor-table oracle — the readable gold standard for the rumor engine.

The dense oracle (swim_tpu/models/oracle.py) validates the dense engine, but
the rumor engine's full lifecycle — sentinel-based suspicion expiry,
Lifeguard dynamic timeouts, rumor retirement, tombstones, the origination
budget — deviates from the dense protocol by design (rumor.py docstring,
deviations 1–4), so round 1 could only validate it bitwise *pre-expiry*.
This module closes that gap: it implements the rumor engine's documented
semantics one message at a time in plain Python + NumPy, and
tests/test_rumor_vs_scalar.py enforces **bitwise identical** RumorState
evolution under the same RumorRandomness, through every phase, with
Lifeguard dynamic suspicion on or off.

Mirror discipline: every ordering rule the vectorized engine inherits from
its primitives is spelled out here as an explicit scalar rule —

  * candidate order  = (age, slot) ascending over eligible rumors, then
    ineligible slots by index (lax.top_k is stable on ties);
  * per-sender piggyback = first B known candidates in candidate order;
  * argmax witnesses (buddy, refutation) = FIRST index attaining the max;
  * origination order = table confirms by slot, refutes by node id,
    suspicions by node id; first `budget` valid candidates win;
  * slot allocation  = free slots in slot order;
  * sentinel joins   = candidate order within a rumor, first-free positions.

Deliberately unoptimized (clarity over speed; fine to a few hundred nodes).
Reference parity note: the reference (jpfuentes2/swim, Haskell — tree
unavailable at survey time, SURVEY.md §0) has no simulator; this oracle
specifies the TPU simulator's semantics, not the reference's code.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from swim_tpu.config import SwimConfig
from swim_tpu.models.rumor import (RESAMPLE_ATTEMPTS, RumorRandomness,
                                   _budget, _pig_window, dynamic_timeout_py)
from swim_tpu.sim.faults import FaultPlan
from swim_tpu.types import (Status, key_incarnation, key_status,
                            opinion_key)


def _alive_key(inc: int) -> int:
    return opinion_key(Status.ALIVE, inc)


def _suspect_key(inc: int) -> int:
    return opinion_key(Status.SUSPECT, inc)


def _dead_key(inc: int) -> int:
    return opinion_key(Status.DEAD, inc)


def _is_suspect(key: int) -> bool:
    return key_status(key) == Status.SUSPECT


def _is_dead(key: int) -> bool:
    return key_status(key) == Status.DEAD


@dataclasses.dataclass
class RumorOracleState:
    """Field-for-field scalar mirror of rumor.RumorState."""

    knows: np.ndarray      # bool[N, R]
    inc_self: np.ndarray   # u32[N]
    lha: np.ndarray        # i32[N]
    gone_key: np.ndarray   # u32[N]
    subject: np.ndarray    # i32[R]
    rkey: np.ndarray       # u32[R]
    birth: np.ndarray      # i32[R]
    sent_node: np.ndarray  # i32[R, S]
    sent_time: np.ndarray  # i32[R, S]
    confirmed: np.ndarray  # bool[R]
    overflow: int
    step: int


def init_state(cfg: SwimConfig) -> RumorOracleState:
    n, r, s = cfg.n_nodes, cfg.rumor_slots, cfg.sentinels
    return RumorOracleState(
        knows=np.zeros((n, r), bool),
        inc_self=np.zeros((n,), np.uint32),
        lha=np.zeros((n,), np.int32),
        gone_key=np.zeros((n,), np.uint32),
        subject=np.full((r,), -1, np.int32),
        rkey=np.zeros((r,), np.uint32),
        birth=np.zeros((r,), np.int32),
        sent_node=np.full((r, s), -1, np.int32),
        sent_time=np.zeros((r, s), np.int32),
        confirmed=np.zeros((r,), bool),
        overflow=0,
        step=0,
    )


class RumorOracle:
    """Drives RumorOracleState one protocol period at a time."""

    def __init__(self, cfg: SwimConfig, plan: FaultPlan):
        from swim_tpu.sim import faults as _faults

        self.cfg = cfg
        self.plan = _faults.to_numpy(plan)
        self.state = init_state(cfg)

    # -- fault model -------------------------------------------------------

    def crashed(self, i: int, t: int) -> bool:
        return t >= int(self.plan.crash_step[i])

    def joined(self, i: int, t: int) -> bool:
        return t >= int(self.plan.join_step[i])

    def active(self, i: int, t: int) -> bool:
        return self.joined(i, t) and not self.crashed(i, t)

    def delivered(self, src: int, dst: int, t: int, u_loss) -> bool:
        if not (self.active(src, t) and self.active(dst, t)):
            return False
        p = self.plan
        if (int(p.partition_start) <= t < int(p.partition_end)
                and int(p.partition_id[src]) != int(p.partition_id[dst])):
            return False
        return np.float32(u_loss) >= np.float32(p.loss)

    # -- views (derived) ---------------------------------------------------

    def _opinion(self, i: int, subj: int) -> tuple[int, int]:
        """(key, witness rumor index or -1): i's view of subj via the
        heard-rumor join, floored at max(ALIVE(0), tombstone)."""
        st = self.state
        best, arg = 0, 0
        for r in range(self.cfg.rumor_slots):
            if (st.subject[r] == subj and st.subject[r] >= 0
                    and st.knows[i, r] and int(st.rkey[r]) > best):
                best, arg = int(st.rkey[r]), r
        floor = max(_alive_key(0), int(st.gone_key[subj]))
        if best > floor:
            return best, arg
        return floor, -1

    def _believes_dead(self, i: int, subj: int) -> bool:
        st = self.state
        if _is_dead(int(st.gone_key[subj])):
            return True
        for r in range(self.cfg.rumor_slots):
            if (st.subject[r] == subj and st.subject[r] >= 0
                    and st.knows[i, r] and _is_dead(int(st.rkey[r]))):
                return True
        return False

    # -- one protocol period ----------------------------------------------

    def step(self, rnd: RumorRandomness) -> None:
        from swim_tpu.utils import prng as _prng

        cfg, st = self.cfg, self.state
        n, k, r_cap, s_cap = (cfg.n_nodes, cfg.k_indirect, cfg.rumor_slots,
                              cfg.sentinels)
        t = st.step
        base = _prng.to_numpy(rnd.base)
        resample_u = np.asarray(rnd.resample_u)
        up = [i for i in range(n) if self.active(i, t)]
        up_set = set(up)

        # ---- Phase 0: retirement (rumor.py deviation 1 + tombstones) ----
        used0 = st.subject >= 0
        age = t - st.birth
        window = cfg.gossip_window
        pend_horizon = (cfg.suspicion_max_periods
                        if cfg.lifeguard and cfg.dynamic_suspicion
                        else cfg.suspicion_periods) + 2
        is_susp_r = np.array([_is_suspect(int(kk)) for kk in st.rkey])
        is_dead_r = np.array([_is_dead(int(kk)) for kk in st.rkey])
        # same-subject matrix from the PRE-retirement table (the engine
        # computes it in Phase 0 and reuses it for expiry refutation)
        same_subj = (st.subject[:, None] == st.subject[None, :])
        live_total = len(up)
        knowers = np.array([int(sum(st.knows[i, r] for i in up))
                            for r in range(r_cap)])
        disseminated = knowers >= live_total
        for r in range(r_cap):
            if not used0[r]:
                continue
            gone_at = int(st.gone_key[st.subject[r]])
            glob_refuted = (gone_at > int(st.rkey[r])) or any(
                used0[r2] and same_subj[r, r2]
                and int(st.rkey[r2]) > int(st.rkey[r])
                for r2 in range(r_cap))
            pending = (is_susp_r[r] and not st.confirmed[r]
                       and not glob_refuted and age[r] < pend_horizon)
            if is_dead_r[r]:
                if disseminated[r]:
                    # retire into the tombstone floor
                    subj = int(st.subject[r])
                    st.gone_key[subj] = max(int(st.gone_key[subj]),
                                            int(st.rkey[r]))
                    st.subject[r] = -1
            elif not (age[r] < window or pending):
                st.subject[r] = -1
        used = st.subject >= 0

        # ---- Phase A: probe targets & proxies (deviation 3) --------------
        def draw_tgt(i: int, u) -> int:
            idx = int(np.float32(u) * np.float32(n - 1))
            idx = min(idx, n - 2)
            return idx + (1 if idx >= i else 0)

        target: dict[int, int] = {}
        prober: set[int] = set()
        if cfg.target_selection == "round_robin":
            from swim_tpu.ops.sampling import py_round_robin_target

            epoch, pos = divmod(t, n - 1)
            for i in range(n):
                target[i] = py_round_robin_target(i, epoch, pos, n)
            prober = {i for i in up if self.joined(target[i], t)}
        else:
            def bad_tgt(i, ti):
                return self._believes_dead(i, ti) or not self.joined(ti, t)

            for i in range(n):
                ti = draw_tgt(i, base.target_u[i])
                bad = bad_tgt(i, ti)
                for a in range(RESAMPLE_ATTEMPTS):
                    nxt = draw_tgt(i, resample_u[i, a])
                    if bad:
                        ti = nxt
                        bad = bad_tgt(i, ti)
                target[i] = ti
                if i in up_set and not bad and n >= 2:
                    prober.add(i)

        proxies: dict[int, list[int]] = {}
        for i in range(n):
            lo, hi = min(i, target[i]), max(i, target[i])
            row = []
            for s in range(k):
                idx2 = int(np.float32(base.proxy_u[i, s])
                           * np.float32(max(n - 2, 1)))
                idx2 = min(idx2, max(n - 3, 0))
                p = idx2 + (1 if idx2 >= lo else 0)
                p = p + (1 if p >= hi else 0)
                row.append(p)
            proxies[i] = row
        has_proxy = n > 2

        # ---- Phase B: the period's piggyback candidate order -------------
        b_pig = min(cfg.max_piggyback, r_cap)
        w_pig = _pig_window(cfg)
        eligible = [r for r in range(r_cap)
                    if used[r] and 0 <= age[r] < window]
        cand = sorted(eligible, key=lambda r: (int(age[r]), r))
        cand += [r for r in range(r_cap) if r not in set(cand)]
        cand = cand[:w_pig]
        cand_valid = [used[r] and 0 <= age[r] < window for r in cand]

        def select(i: int) -> list[int]:
            """First-B known candidates (rumor ids) in candidate order."""
            out = []
            for pos, r in enumerate(cand):
                if cand_valid[pos] and st.knows[i, r]:
                    out.append(r)
                    if len(out) == b_pig:
                        break
            return out

        def buddy(src: int, dst: int) -> int:
            """First max-key suspect rumor about dst known to src, or -1."""
            if not (cfg.lifeguard and cfg.buddy):
                return -1
            best, arg = 0, 0
            for r in range(r_cap):
                if (used[r] and st.subject[r] == dst and st.knows[src, r]
                        and int(st.rkey[r]) > best):
                    best, arg = int(st.rkey[r]), r
            return arg if _is_suspect(best) else -1

        def run_wave(messages):
            """messages: (src, dst, sent, u_loss, forced rumor id).
            Selections read wave-start state; merges land at wave end.
            Returns the per-message delivered flags."""
            sends, oks = [], []
            for src, dst, sent, u_loss, forced in messages:
                sel = select(src) if sent else []
                ok = sent and self.delivered(src, dst, t, u_loss)
                sends.append((dst, sel, forced, ok))
                oks.append(ok)
            for dst, sel, forced, ok in sends:
                if ok:
                    for r in sel:
                        st.knows[dst, r] = True
                    if forced >= 0:
                        st.knows[dst, forced] = True
            return oks

        # W1 PING i→T(i)
        w1_msgs = [(i, target[i], i in prober, base.loss_w1[i],
                    buddy(i, target[i]) if i in prober else -1)
                   for i in range(n)]
        w1_ok = run_wave(w1_msgs)
        # W2 ACK T(i)→i (loss draw indexed by the pinger i)
        w2_msgs = [(target[i], i, w1_ok[i], base.loss_w2[i], -1)
                   for i in range(n)]
        w2_ok = run_wave(w2_msgs)
        acked = {i for i in range(n) if w2_ok[i]}
        # W3 PING-REQ i→p
        need = [i for i in range(n)
                if i in prober and i not in acked and has_proxy]
        need_set = set(need)
        w3_msgs = [(i, proxies[i][s], i in need_set, base.loss_w3[i, s], -1)
                   for i in range(n) for s in range(k)]
        w3_ok = run_wave(w3_msgs)
        # W4 proxy PING p→T(i)
        w4_msgs = []
        for m, (i, s) in enumerate(((i, s) for i in range(n)
                                    for s in range(k))):
            p = proxies[i][s]
            w4_msgs.append((p, target[i], w3_ok[m], base.loss_w4[i, s],
                            buddy(p, target[i]) if w3_ok[m] else -1))
        w4_ok = run_wave(w4_msgs)
        # W5 target ACK T(i)→p
        w5_msgs = []
        for m, (i, s) in enumerate(((i, s) for i in range(n)
                                    for s in range(k))):
            w5_msgs.append((target[i], proxies[i][s], w4_ok[m],
                            base.loss_w5[i, s], -1))
        w5_ok = run_wave(w5_msgs)
        # W6 relay ACK p→i
        w6_msgs = []
        for m, (i, s) in enumerate(((i, s) for i in range(n)
                                    for s in range(k))):
            w6_msgs.append((proxies[i][s], i, w5_ok[m],
                            base.loss_w6[i, s], -1))
        w6_ok = run_wave(w6_msgs)
        relayed = {i for i in range(n)
                   if any(w6_ok[i * k + s] for s in range(k))}

        # ---- Phase C: verdicts / refutation / expiry ---------------------
        failed = {i for i in prober if i not in acked and i not in relayed}
        s_probe = st.lha.copy()
        if cfg.lifeguard:
            for i in prober:
                delta = 1 if i in failed else -1
                st.lha[i] = np.int32(
                    min(max(int(st.lha[i]) + delta, 0), cfg.lha_max))
            failed = {i for i in failed
                      if np.float32(base.lha_u[i])
                      < np.float32(1.0) / np.float32(1 + int(s_probe[i]))}
        mk_suspect, re_suspect, susp_key = set(), set(), {}
        for i in range(n):
            vk, _ = self._opinion(i, target[i])
            susp_key[i] = _suspect_key(key_incarnation(vk))
            if i in failed:
                stat = key_status(vk)
                if stat == Status.ALIVE:
                    mk_suspect.add(i)
                elif stat == Status.SUSPECT:
                    re_suspect.add(i)

        refute, new_inc = set(), {}
        for i in range(n):
            best = _alive_key(int(st.inc_self[i]))
            for r in range(r_cap):
                if (used[r] and st.subject[r] == i and st.knows[i, r]
                        and int(st.rkey[r]) > best):
                    best = int(st.rkey[r])
            if i in up_set and _is_suspect(best):
                refute.add(i)
                new_inc[i] = key_incarnation(best) + 1
                st.inc_self[i] = np.uint32(new_inc[i])
                if cfg.lifeguard:
                    st.lha[i] = np.int32(min(int(st.lha[i]) + 1,
                                             cfg.lha_max))
            else:
                new_inc[i] = int(st.inc_self[i])

        # suspicion expiry via sentinels (deviation 2)
        confirm, conf_node = set(), {}
        for r in range(r_cap):
            if not (used[r] and is_susp_r[r] and not st.confirmed[r]):
                continue
            filled = int(np.sum(st.sent_node[r] >= 0))
            if cfg.lifeguard and cfg.dynamic_suspicion:
                timeout = dynamic_timeout_py(cfg, min(filled, s_cap))
            else:
                timeout = cfg.suspicion_periods
            dead_k = _dead_key(key_incarnation(int(st.rkey[r])))
            if not dead_k > int(st.gone_key[st.subject[r]]):
                continue
            for s in range(s_cap):
                node = int(st.sent_node[r, s])
                # a sentinel only fires while its node is still up
                if node < 0 or int(self.plan.crash_step[node]) <= t:
                    continue
                if t < int(st.sent_time[r, s]) + timeout:
                    continue
                refuted = any(
                    used[r2] and same_subj[r, r2]
                    and int(st.rkey[r2]) > int(st.rkey[r])
                    and st.knows[node, r2]
                    for r2 in range(r_cap))
                if not refuted:
                    confirm.add(r)
                    conf_node[r] = node
                    break

        # ---- Phase D: originations (deviation 4) -------------------------
        cb = _budget(cfg)
        cands = []  # (subj, key, orig, src_rumor, is_suspect_class)
        for r in range(r_cap):
            if r in confirm:
                cands.append((int(st.subject[r]),
                              _dead_key(key_incarnation(int(st.rkey[r]))),
                              conf_node[r], r, False))
        for i in range(n):
            if i in refute:
                cands.append((i, _alive_key(new_inc[i]), i, -1, False))
        for i in range(n):
            if i in mk_suspect or i in re_suspect:
                cands.append((target[i], susp_key[i], i, -1, True))
        self.state.overflow = int(self.state.overflow
                                  + max(len(cands) - cb, 0))
        cands = cands[:cb]

        # allocation: dedup within candidates (earlier wins), dedup vs the
        # post-retirement table, then free slots in slot order
        free_slots = [r for r in range(r_cap) if not used[r]]
        slot_of: dict[int, int] = {}   # candidate index → slot (-1 = none)
        seen: dict[tuple[int, int], int] = {}
        alloc_writes = []               # (slot, subj, key)
        n_alloc = 0
        for ci, (subj, keyv, orig, srcr, is_s) in enumerate(cands):
            if (subj, keyv) in seen:
                slot_of[ci] = slot_of[seen[(subj, keyv)]]
                continue
            seen[(subj, keyv)] = ci
            ex = next((r for r in range(r_cap)
                       if used[r] and int(st.subject[r]) == subj
                       and int(st.rkey[r]) == keyv), None)
            if ex is not None:
                slot_of[ci] = ex
                continue
            if n_alloc < len(free_slots) and n_alloc < cb:
                slot = free_slots[n_alloc]
                n_alloc += 1
                slot_of[ci] = slot
                alloc_writes.append((slot, subj, keyv))
            else:
                slot_of[ci] = -1
                self.state.overflow = int(self.state.overflow + 1)

        for slot, subj, keyv in alloc_writes:
            st.subject[slot] = np.int32(subj)
            st.rkey[slot] = np.uint32(keyv)
            st.birth[slot] = np.int32(t)
            st.confirmed[slot] = False
            st.sent_node[slot] = -1
            st.sent_time[slot] = 0
            st.knows[:, slot] = False   # clear heard bits of the reused slot

        for ci, (subj, keyv, orig, srcr, is_s) in enumerate(cands):
            slot = slot_of[ci]
            if slot >= 0:
                st.knows[orig, slot] = True   # originator hears its rumor

        # sentinel joins: placed suspect-class candidates, candidate order
        for ci, (subj, keyv, orig, srcr, is_s) in enumerate(cands):
            slot = slot_of[ci]
            if slot < 0 or not is_s:
                continue
            if any(int(st.sent_node[slot, s]) == orig for s in range(s_cap)):
                continue
            for s in range(s_cap):
                if int(st.sent_node[slot, s]) < 0:
                    st.sent_node[slot, s] = np.int32(orig)
                    st.sent_time[slot, s] = np.int32(t)
                    break

        # mark confirmed suspicions whose DEAD rumor landed
        for ci, (subj, keyv, orig, srcr, is_s) in enumerate(cands):
            if srcr >= 0 and slot_of[ci] >= 0:
                st.confirmed[srcr] = True

        st.step = t + 1

    def run(self, key, periods: int) -> RumorOracleState:
        from swim_tpu.models import rumor as rumor_mod

        for _ in range(periods):
            self.step(rumor_mod.draw_period_rumor(key, self.state.step,
                                                  self.cfg))
        return self.state
