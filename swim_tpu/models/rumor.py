"""Scalable rumor engine — O(R·N) SWIM simulation for 100k–1M nodes.

The dense engine (swim_tpu/models/dense.py) stores every pairwise opinion:
9·N² bytes is ~9 TB at 1M nodes. This engine exploits what SWIM actually
disseminates: a small working set of *rumors*. A rumor is one membership
assertion `(subject, lattice key)` — SUSPECT(v)/ALIVE(v)/DEAD(v) about one
node — and a node's view of subject j is exactly

    view(i, j) = join( ALIVE(0), own-ALIVE if j == i,
                       { rumor.key : rumor about j that i has heard } )

because the opinion lattice join (swim_tpu/ops/lattice.py) is associative
and commutative. So the full simulation state is a bounded rumor table
(capacity R = cfg.rumor_slots) plus a heard-bitmask `knows[N, R]` — memory
O(R·N + N) instead of O(N²), with the node axis sharded across the TPU mesh
exactly like the dense engine.

Documented deviations from the exact protocol (docs/PROTOCOL.md §6), chosen
so that each is either statistically neutral or strictly pessimistic:

1. **Piggyback ordering**: exact SWIM prefers least-retransmitted updates
   per (sender, subject). Per-pair counters are O(N²), so eligibility is by
   rumor *age* — a rumor is transmissible while `t - birth < gossip_window`
   (the same Θ(retransmit_limit) budget the counters enforce: a node makes
   Θ(1) sends per period) — and selection prefers the *youngest* eligible
   rumors, which is what low-retransmit-count ordering converges to.
2. **Suspicion expiry via sentinels**: exact SWIM lets every suspector
   time out independently; all produce the identical DEAD(v) key, so only
   the earliest matters for the projected view. The rumor tracks up to
   `cfg.sentinels` earliest *independent suspectors* (the originator plus
   later nodes whose own probe of the subject also failed); expiry fires
   when any live, un-refuted sentinel passes its deadline. Non-sentinel
   suspectors never confirm — visible only if every sentinel crashes
   (≥ S simultaneous failures) and as ≤1 period of extra dissemination
   skew (gossip hop instead of local expiry).
3. **Believed-dead probe targets are resampled ≤ 4 times**, then the node
   idles for the period (exact: one draw from the masked candidate CDF).
   Proxies are not dead-checked at all (a dead proxy just fails).
4. **Origination budget**: at most `origination_budget` new rumors per
   period enter the table (confirm > refute > suspect priority); the rest
   are dropped and counted in `state.overflow`. A dropped suspicion is
   re-detected by the next failed probe, a dropped confirm by re-suspicion,
   so overload degrades into detection latency, never into wrong state.

In the exact regime — piggyback bound ≥ active rumors, gossip window ≥ run
length, no confirmed deaths — the projected views are bitwise-identical to
the dense engine under the same PeriodRandomness (tests/test_rumor_vs_dense
.py); elsewhere agreement is statistical.

Reference parity note: the reference (jpfuentes2/swim, Haskell — tree
unavailable at survey time, SURVEY.md §0) has no simulator at all; this
engine is the TPU-native capability the north star adds on top of the
reference's per-node protocol semantics (docs/PROTOCOL.md §3–§7).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from swim_tpu.config import SwimConfig
from swim_tpu.ops import lattice, sampling
from swim_tpu.sim import faults
from swim_tpu.sim.faults import FaultPlan
from swim_tpu.utils.prng import PeriodRandomness, draw_period

RESAMPLE_ATTEMPTS = 4
_BIG = jnp.int32(2**30)


class RumorState(NamedTuple):
    """Sharded node-axis tensors first, then the replicated rumor table."""

    # --- per node (leading axis N, sharded across the mesh) ---
    knows: jax.Array      # bool[N, R]  node i has heard rumor r
    inc_self: jax.Array   # u32[N]      own incarnation
    lha: jax.Array        # i32[N]      Lifeguard local health score
    gone_key: jax.Array   # u32[N]      tombstone floor, indexed by SUBJECT:
    #                       a DEAD rumor retires here only once every live
    #                       node has heard it, after which it floors every
    #                       node's view of that subject (see `step` Phase 0)
    # --- rumor table (leading axis R, replicated) ---
    subject: jax.Array    # i32[R]      subject node id; -1 = free slot
    rkey: jax.Array       # u32[R]      asserted lattice key
    birth: jax.Array      # i32[R]      period originated
    sent_node: jax.Array  # i32[R, S]   independent suspectors; -1 = empty
    sent_time: jax.Array  # i32[R, S]   period each sentinel began suspecting
    confirmed: jax.Array  # bool[R]     suspicion already produced its DEAD
    # --- scalars ---
    overflow: jax.Array   # i32         originations dropped (budget/table)
    step: jax.Array       # i32         periods completed


class RumorRandomness(NamedTuple):
    base: PeriodRandomness
    resample_u: jax.Array  # f32[N, RESAMPLE_ATTEMPTS] believed-dead redraws


def draw_period_rumor(key: jax.Array, step, cfg: SwimConfig) -> RumorRandomness:
    base = draw_period(key, step, cfg)
    rk = jax.random.fold_in(jax.random.fold_in(key, step), 0x5e71)
    return RumorRandomness(
        base=base,
        resample_u=jax.random.uniform(rk, (cfg.n_nodes, RESAMPLE_ATTEMPTS)),
    )


def _budget(cfg: SwimConfig) -> int:
    """Max originations per period (candidate compaction width)."""
    return min(cfg.rumor_slots, 256)


def dynamic_timeout_py(cfg: SwimConfig, filled: int) -> int:
    """Lifeguard dynamic suspicion timeout for `filled` sentinels (plain
    Python ints — the single definition shared by the engines' trace-time
    table and the scalar rumor oracle)."""
    import math

    base_to = float(cfg.suspicion_periods)
    max_to = float(cfg.suspicion_max_periods)
    c_tot = float(cfg.k_indirect + 1)
    frac = math.log(max(float(filled), 1.0)) / math.log(c_tot + 1.0)
    return int(math.ceil(max(base_to, max_to - (max_to - base_to) * frac)))


def dynamic_timeout_table(cfg: SwimConfig) -> jax.Array:
    """i32[S+1]: timeout per filled-sentinel count, built at trace time."""
    return jnp.asarray([dynamic_timeout_py(cfg, f)
                        for f in range(cfg.sentinels + 1)], jnp.int32)


def _pig_window(cfg: SwimConfig) -> int:
    """Global candidate width W for piggyback selection (≥ B)."""
    b = min(cfg.max_piggyback, cfg.rumor_slots)
    return min(cfg.rumor_slots, max(8 * b, 64))


def init_state(cfg: SwimConfig) -> RumorState:
    n, r, s = cfg.n_nodes, cfg.rumor_slots, cfg.sentinels
    return RumorState(
        knows=jnp.zeros((n, r), jnp.bool_),
        inc_self=jnp.zeros((n,), jnp.uint32),
        lha=jnp.zeros((n,), jnp.int32),
        gone_key=jnp.zeros((n,), jnp.uint32),
        subject=jnp.full((r,), -1, jnp.int32),
        rkey=jnp.zeros((r,), jnp.uint32),
        birth=jnp.zeros((r,), jnp.int32),
        sent_node=jnp.full((r, s), -1, jnp.int32),
        sent_time=jnp.zeros((r, s), jnp.int32),
        confirmed=jnp.zeros((r,), jnp.bool_),
        overflow=jnp.int32(0),
        step=jnp.int32(0),
    )


# ---------------------------------------------------------------------------
# Views (derived, never stored)
# ---------------------------------------------------------------------------

def _about(subject: jax.Array, used: jax.Array, subj: jax.Array) -> jax.Array:
    """bool[..., R]: rumor r is about subj[...] (broadcast compare)."""
    return used[None, :] & (subject[None, :] == subj[..., None])


def opinion_of(state: RumorState, subj: jax.Array) -> tuple[jax.Array,
                                                            jax.Array]:
    """Per-node opinion of one subject each: (key u32[N], argmax rumor i32[N]).

    view(i, subj[i]) over the heard-rumor join, floored at ALIVE(0). The
    returned rumor index is the join's witness (used by the buddy force);
    -1 when the floor wins.
    """
    used = state.subject >= 0
    mk = _about(state.subject, used, subj) & state.knows      # [N, R]
    vals = jnp.where(mk, state.rkey, jnp.uint32(0))
    best = jnp.max(vals, axis=-1)
    arg = jnp.argmax(vals, axis=-1).astype(jnp.int32)
    floor = jnp.maximum(lattice.alive_key(jnp.uint32(0)),
                        state.gone_key[subj])
    return jnp.maximum(best, floor), jnp.where(best > floor, arg, -1)


def _believes_dead(state: RumorState, subj: jax.Array) -> jax.Array:
    used = state.subject >= 0
    mk = _about(state.subject, used, subj) & state.knows
    return (jnp.any(mk & lattice.is_dead(state.rkey)[None, :], axis=-1)
            | lattice.is_dead(state.gone_key[subj]))


def view_matrix(cfg: SwimConfig, state: RumorState) -> jax.Array:
    """u32[N, N] projected pairwise views — tests/metrics only (small N)."""
    n = cfg.n_nodes
    used = state.subject >= 0
    base = jnp.maximum(lattice.alive_key(jnp.uint32(0)),
                       state.gone_key)[None, :]
    base = jnp.broadcast_to(base, (n, n))
    ids = jnp.arange(n)
    base = base.at[ids, ids].max(lattice.alive_key(state.inc_self))
    vals = jnp.where(state.knows & used[None, :], state.rkey[None, :],
                     jnp.uint32(0))                            # [N, R]
    col = jnp.where(used, state.subject, n)                    # n → dropped
    return base.at[:, col].max(vals, mode="drop")


# ---------------------------------------------------------------------------
# One protocol period
# ---------------------------------------------------------------------------

def step(cfg: SwimConfig, state: RumorState, plan: FaultPlan,
         rnd: RumorRandomness, tap: dict | None = None,
         prof=None) -> RumorState:
    """One protocol period for all N nodes (pure; jit with cfg static).

    `tap` (optional, static presence) receives per-period telemetry
    scalars (swim_tpu/obs/engine.py EngineFrame keys).  The tap never
    feeds back into state; with tap=None the traced program is
    unchanged, so telemetry-on state is bitwise identical to
    telemetry-off.

    `prof` (optional, static presence) is an obs/prof.py PhaseProbe.
    Like the dense engine, the rumor engine reports the coarse phase
    subset (select / merge / commit / telemetry_tap): per-wave
    selection and delivery interleave inside `wave`.  prof=None leaves
    the traced program unchanged.
    """
    n, k, r_cap = cfg.n_nodes, cfg.k_indirect, cfg.rumor_slots
    s_cap = cfg.sentinels
    plan, prog = faults.split_program(plan)
    t = state.step
    base = rnd.base
    ids = jnp.arange(n, dtype=jnp.int32)
    rr = jnp.arange(r_cap, dtype=jnp.int32)
    crashed = t >= plan.crash_step
    joined = t >= plan.join_step
    # `up` is full membership activity: joined and not crashed. Nodes with
    # a future join_step neither act nor receive, are skipped as probe
    # targets (not in anyone's membership list yet), and count toward
    # dissemination totals only once joined.
    up = ~crashed & joined
    part_on = (t >= plan.partition_start) & (t < plan.partition_end)

    # ---- Phase 0: retire stale rumors (docstring deviation 1/4) -----------
    # Non-DEAD rumors age out after the gossip window (suspicions hang on
    # until their own timer resolves). DEAD rumors are different: forgetting
    # a death would make the cluster re-detect it forever, so a DEAD rumor
    # stays until EVERY live node has heard it, and only then retires into
    # the persistent `gone_key` tombstone floor — which also means a death
    # confirmed inside a partition never leaks across it.
    used = state.subject >= 0
    age = t - state.birth
    transmissible_for = jnp.int32(cfg.gossip_window)
    # a suspicion must outlive its own (possibly Lifeguard-extended) timer
    pend_horizon = jnp.int32(
        (cfg.suspicion_max_periods if cfg.lifeguard and cfg.dynamic_suspicion
         else cfg.suspicion_periods) + 2)
    is_susp_r = lattice.is_suspect(state.rkey)
    is_dead_r = lattice.is_dead(state.rkey)
    gone_at_subj = state.gone_key[jnp.maximum(state.subject, 0)]   # u32[R]
    same_subj = (state.subject[:, None] == state.subject[None, :])
    glob_refuted = (jnp.any(
        same_subj & used[None, :]
        & (state.rkey[None, :] > state.rkey[:, None]), axis=-1)
        | (gone_at_subj > state.rkey))
    pending = (is_susp_r & ~state.confirmed & ~glob_refuted
               & (age < pend_horizon))
    live_total = jnp.sum(up).astype(jnp.int32)
    knowers = jnp.sum(state.knows & up[:, None], axis=0).astype(jnp.int32)
    disseminated = knowers >= live_total
    retire_dead = used & is_dead_r & disseminated
    gone_key = state.gone_key.at[
        jnp.where(retire_dead, state.subject, n)].max(state.rkey, mode="drop")
    keep = used & jnp.where(is_dead_r, ~disseminated,
                            (age < transmissible_for) | pending)
    subject = jnp.where(keep, state.subject, -1)
    used = subject >= 0
    st = state._replace(subject=subject, gone_key=gone_key)

    # ---- Phase A: probe-target selection (deviation 3) --------------------
    def skip_self(idx):
        return idx + (idx >= ids).astype(jnp.int32)

    def draw_tgt(u):
        idx = (u * jnp.float32(n - 1)).astype(jnp.int32)
        return skip_self(jnp.minimum(idx, n - 2))

    if cfg.target_selection == "round_robin":
        # §4.3 Feistel round-robin (same schedule as the dense engine);
        # believed-dead targets are probed and fail fast — no resampling.
        # A not-yet-joined target is no probe at all (idle period): it is
        # in nobody's membership list.
        epoch = jnp.broadcast_to(t // jnp.int32(n - 1), (n,))
        pos = jnp.broadcast_to(t % jnp.int32(n - 1), (n,))
        target = sampling.round_robin_target(ids, epoch, pos, n)
        prober = up & joined[target]
    else:
        target = draw_tgt(base.target_u)
        bad = _believes_dead(st, target) | ~joined[target]
        for a in range(RESAMPLE_ATTEMPTS):
            nxt = draw_tgt(rnd.resample_u[:, a])
            target = jnp.where(bad, nxt, target)
            bad = bad & (_believes_dead(st, target) | ~joined[target])
        prober = up & ~bad & (n >= 2)

    # proxies: uniform over j ∉ {i, T(i)} — the dense masked-CDF mapping
    lo = jnp.minimum(ids, target)
    hi = jnp.maximum(ids, target)
    idx2 = (base.proxy_u * jnp.float32(max(n - 2, 1))).astype(jnp.int32)
    idx2 = jnp.minimum(idx2, max(n - 3, 0))
    prox = idx2 + (idx2 >= lo[:, None]).astype(jnp.int32)
    prox = prox + (prox >= hi[:, None]).astype(jnp.int32)   # i32[N, k]
    has_proxy = n > 2

    if prog is not None:
        # u16 lane thresholds -> exact f32 probabilities (power-of-two
        # scale), composed with the global loss like the dense engine
        send_thr, recv_thr, reply_thr = faults.link_lanes(prog, t)
        scale = jnp.float32(1.0 / 65536.0)
        send_f = send_thr.astype(jnp.float32) * scale
        recv_f = recv_thr.astype(jnp.float32) * scale
        reply_f = reply_thr.astype(jnp.float32) * scale

    def delivered(src, dst, u, reply=False):
        cut = part_on & (plan.partition_id[src] != plan.partition_id[dst])
        thr = plan.loss.astype(jnp.float32)
        if prog is not None:
            thr = thr + send_f[src] + recv_f[dst]
            if reply:
                thr = thr + reply_f[src]
        return up[src] & up[dst] & ~cut & (u >= thr)

    # ---- Phase B: global piggyback candidates (deviation 1) ---------------
    b_pig = min(cfg.max_piggyback, r_cap)
    w_pig = _pig_window(cfg)
    eligible = used & (age >= 0) & (age < transmissible_for)
    # youngest first, ties by slot: ages are bounded by the gossip window
    score = jnp.where(eligible, age * jnp.int32(r_cap) + rr, _BIG)
    _, cand_idx = jax.lax.top_k(-score, w_pig)
    cand_idx = cand_idx.astype(jnp.int32)
    cand_valid = eligible[cand_idx]                          # bool[W]

    if prof is not None and prof.cut(
            "select", target, target=target, prox=prox, prober=prober,
            cand_idx=cand_idx, cand_valid=cand_valid, subject=subject,
            gone_key=gone_key):
        return prof.captured

    knows = st.knows

    def select_first_b(kn):
        """First-B-set-bits per row of the priority-ordered candidate mask.

        Candidate columns are already globally priority-sorted, so per-row
        selection is positional, not a sort. Two lowerings: B argmax
        passes for small B (lax.top_k is pathologically slow per row —
        measured 672 ms for one [65536, 64] top_k on CPU vs ~5 ms for six
        argmax passes), top_k for the large-B exact regime.
        """
        if b_pig <= 16:
            # pack rows to u8 words, then B rounds of lowest-set-bit
            # extract-and-clear (m & -m isolates it, popcount(low-1) names
            # it, m & (m-1) clears it) — pure elementwise [N] ops
            packed = jnp.packbits(kn, axis=-1, bitorder="little")
            words = [packed[:, w] for w in range(packed.shape[-1])]
            one = jnp.uint8(1)
            ws, oks = [], []
            for _ in range(b_pig):
                idx = jnp.zeros(kn.shape[:1], jnp.int32)
                found = jnp.zeros(kn.shape[:1], jnp.bool_)
                nxt = []
                for w, m in enumerate(words):
                    nz = m != 0
                    low = m & (jnp.uint8(0) - m)
                    bit = jax.lax.population_count(low - one)
                    take = nz & ~found
                    idx = jnp.where(take, 8 * w + bit.astype(jnp.int32),
                                    idx)
                    nxt.append(jnp.where(take, m & (m - one), m))
                    found = found | nz
                words = nxt
                ws.append(idx)
                oks.append(found)
            wpos = jnp.stack(ws, axis=-1)                     # [N, B]
            val = jnp.stack(oks, axis=-1)
        else:
            pos = jnp.cumsum(kn.astype(jnp.int32), axis=-1)
            prio = jnp.where(
                kn & (pos <= b_pig),
                jnp.int32(w_pig) - jnp.arange(w_pig, dtype=jnp.int32), 0)
            vals, wpos = jax.lax.top_k(prio, b_pig)
            val = vals > 0
        return jnp.take(cand_idx, wpos), val

    def wave(knows, src, dst, sent, u_loss, forced, reply=False):
        """One message wave: per-sender top-B selection + scatter-OR merge.

        src/dst/sent/u_loss/forced are flat [M] message arrays; forced is a
        rumor index (-1 = none) force-included by the Lifeguard buddy rule
        (added alongside the B selected — exact SWIM displaces the last
        slot; deviation noted in the module docstring).  `reply` marks
        ack legs (W2/W5/W6) for the FaultProgram gray lane.
        """
        kn = knows[:, cand_idx] & cand_valid[None, :]         # [N, W]
        sel, val = select_first_b(kn)
        ok = sent & delivered(src, dst, u_loss, reply)        # [M]
        upd = val[src] & ok[:, None]                          # [M, B]
        knows = knows.at[dst[:, None], sel[src]].max(upd)
        fok = ok & (forced >= 0)
        knows = knows.at[dst, jnp.maximum(forced, 0)].max(fok)
        return knows, ok

    def buddy(knows_now, src, dst):
        """Rumor index of src's SUSPECT witness about dst, -1 if none."""
        if not (cfg.lifeguard and cfg.buddy):
            return jnp.full(src.shape, -1, jnp.int32)
        mk = _about(st.subject, used, dst) & knows_now[src]
        vals = jnp.where(mk, st.rkey, jnp.uint32(0))
        best = jnp.max(vals, axis=-1)
        arg = jnp.argmax(vals, axis=-1).astype(jnp.int32)
        return jnp.where(lattice.is_suspect(best), arg, -1)

    no_force = jnp.full((n,), -1, jnp.int32)
    src3 = jnp.repeat(ids, k)
    dst3 = prox.reshape(-1)
    tgt4 = jnp.repeat(target, k)
    no_force_k = jnp.full((n * k,), -1, jnp.int32)

    # W1 PING i→T(i)
    knows, w1_ok = wave(knows, ids, target, prober, base.loss_w1,
                        buddy(knows, ids, target))
    # W2 ACK T(i)→i
    knows, w2_ok = wave(knows, target, ids, w1_ok, base.loss_w2, no_force,
                        reply=True)
    acked = w2_ok
    # W3 PING-REQ i→p
    need = prober & ~acked & has_proxy
    sent3 = jnp.repeat(need, k)
    knows, w3_ok = wave(knows, src3, dst3, sent3, base.loss_w3.reshape(-1),
                        no_force_k)
    # W4 proxy PING p→T(i)
    knows, w4_ok = wave(knows, dst3, tgt4, w3_ok, base.loss_w4.reshape(-1),
                        buddy(knows, dst3, tgt4))
    # W5 target ACK T(i)→p
    knows, w5_ok = wave(knows, tgt4, dst3, w4_ok, base.loss_w5.reshape(-1),
                        no_force_k, reply=True)
    # W6 relay ACK p→i
    knows, w6_ok = wave(knows, dst3, src3, w5_ok, base.loss_w6.reshape(-1),
                        no_force_k, reply=True)
    relayed = jnp.any(w6_ok.reshape(n, k), axis=-1)
    st = st._replace(knows=knows)

    if prof is not None and prof.cut("merge", knows, knows=knows,
                                     acked=acked, relayed=relayed):
        return prof.captured

    # ---- Phase C: end-of-period verdicts (docs/PROTOCOL.md §3) ------------

    # 1. probe verdicts
    probe_ok = acked | relayed
    failed = prober & ~probe_ok
    lha = st.lha
    s_probe = lha
    if cfg.lifeguard:
        lha = jnp.where(prober,
                        jnp.clip(lha + jnp.where(failed, 1, -1), 0,
                                 cfg.lha_max), lha)
        thin = base.lha_u < (jnp.float32(1.0)
                             / (1 + s_probe).astype(jnp.float32))
        failed = failed & thin
    viewed_tk, _ = opinion_of(st, target)
    v_status = lattice.status_of(viewed_tk)
    mk_suspect = failed & (v_status == 0)            # new suspicion
    re_suspect = failed & (v_status == 1)            # independent suspector
    susp_key = lattice.suspect_key(lattice.incarnation_of(viewed_tk))

    # 2. refutation (own view of self is SUSPECT → bump incarnation)
    self_mk = _about(st.subject, used, ids) & st.knows
    self_vals = jnp.where(self_mk, st.rkey, jnp.uint32(0))
    self_best = jnp.maximum(jnp.max(self_vals, axis=-1),
                            lattice.alive_key(st.inc_self))
    refute = up & lattice.is_suspect(self_best)
    new_inc = jnp.where(refute, lattice.incarnation_of(self_best) + 1,
                        st.inc_self.astype(jnp.uint32)).astype(jnp.uint32)
    inc_self = jnp.where(refute, new_inc, st.inc_self)
    if cfg.lifeguard:
        lha = jnp.where(refute, jnp.clip(lha + 1, 0, cfg.lha_max), lha)

    # 3. suspicion expiry via sentinels (deviation 2)
    filled = jnp.sum(st.sent_node >= 0, axis=-1).astype(jnp.int32)  # [R]
    if cfg.lifeguard and cfg.dynamic_suspicion:
        # Lifeguard timeout as a trace-time table over the filled-sentinel
        # count (≤ S+1 entries): exact integers with no on-device float
        # math, so the scalar oracle reproduces it bitwise.
        timeout = dynamic_timeout_table(cfg)[jnp.clip(filled, 0, s_cap)]
    else:
        timeout = jnp.full((r_cap,), cfg.suspicion_periods, jnp.int32)
    snode = st.sent_node
    sact = (snode >= 0) & (plan.crash_step[jnp.maximum(snode, 0)] > t)
    deadline_hit = sact & (t >= st.sent_time + timeout[:, None])    # [R, S]
    higher = (same_subj & used[None, :]
              & (st.rkey[None, :] > st.rkey[:, None]))              # [R, R]
    refuted_s = []
    for s_i in range(s_cap):
        kn_s = st.knows[jnp.maximum(snode[:, s_i], 0)]              # [R, R']
        refuted_s.append(jnp.any(higher & kn_s, axis=-1))
    refuted = jnp.stack(refuted_s, axis=-1)                         # [R, S]
    can_confirm = deadline_hit & ~refuted
    dead_key_r = lattice.dead_key(lattice.incarnation_of(st.rkey))
    confirm = (used & is_susp_r & ~st.confirmed
               & (dead_key_r > gone_key[jnp.maximum(st.subject, 0)])
               & jnp.any(can_confirm, axis=-1))
    conf_s = jnp.argmax(can_confirm, axis=-1)
    conf_node = jnp.take_along_axis(snode, conf_s[:, None], axis=-1)[:, 0]

    # ---- Phase D: originations (deviation 4) ------------------------------
    # candidate order encodes priority: confirms, then refutes, then suspects
    cb = _budget(cfg)
    c_subj = jnp.concatenate([st.subject, ids, target])
    c_key = jnp.concatenate([dead_key_r,
                             lattice.alive_key(new_inc),
                             susp_key])
    c_orig = jnp.concatenate([jnp.maximum(conf_node, 0), ids, ids])
    c_valid = jnp.concatenate([confirm, refute, mk_suspect | re_suspect])
    c_src = jnp.concatenate([rr, jnp.full((2 * n,), -1, jnp.int32)])
    c_susp = jnp.concatenate([jnp.zeros((r_cap + n,), jnp.bool_),
                              jnp.ones((n,), jnp.bool_)])
    total = jnp.sum(c_valid).astype(jnp.int32)
    m = c_valid.shape[0]
    (ci,) = jnp.nonzero(c_valid, size=cb, fill_value=m)
    got = ci < m
    ci = jnp.minimum(ci, m - 1)
    subj_c = jnp.where(got, c_subj[ci], -1)
    key_c = jnp.where(got, c_key[ci], 0)
    orig_c = jnp.where(got, c_orig[ci], 0)
    src_c = jnp.where(got, c_src[ci], -1)
    susp_c = got & c_susp[ci]
    overflow = st.overflow + jnp.maximum(total - cb, 0)

    # dedup within candidates (earlier wins)
    eq = (subj_c[:, None] == subj_c[None, :]) & (key_c[:, None] ==
                                                 key_c[None, :])
    earlier = jnp.tril(jnp.ones((cb, cb), jnp.bool_), k=-1)
    dup_mask = eq & earlier & got[None, :] & got[:, None]
    dup_prev = jnp.any(dup_mask, axis=-1)
    win_idx = jnp.argmax(dup_mask, axis=-1)          # first match

    # dedup vs table
    ex = (used[None, :] & (subj_c[:, None] == subject[None, :])
          & (key_c[:, None] == st.rkey[None, :]))
    ex_match = jnp.any(ex, axis=-1)
    ex_slot = jnp.argmax(ex, axis=-1).astype(jnp.int32)

    needs_slot = got & ~dup_prev & ~ex_match
    (free_slots,) = jnp.nonzero(~used, size=cb, fill_value=r_cap)
    n_free = jnp.sum(~used).astype(jnp.int32)
    apos = jnp.cumsum(needs_slot.astype(jnp.int32)) - 1
    alloc_ok = needs_slot & (apos < jnp.minimum(n_free, cb))
    slot_new = jnp.where(alloc_ok,
                         free_slots[jnp.clip(apos, 0, cb - 1)], -1)
    overflow = overflow + jnp.sum(needs_slot & ~alloc_ok)

    slot_f0 = jnp.where(ex_match, ex_slot, slot_new)
    slot_f = jnp.where(dup_prev, slot_f0[win_idx], slot_f0).astype(jnp.int32)
    placed = got & (slot_f >= 0)

    # write allocated slots (out-of-range indices drop)
    wslot = jnp.where(alloc_ok, slot_f, r_cap)
    subject = subject.at[wslot].set(subj_c, mode="drop")
    rkey = st.rkey.at[wslot].set(key_c, mode="drop")
    birth = st.birth.at[wslot].set(t, mode="drop")
    confirmed = st.confirmed.at[wslot].set(False, mode="drop")
    snode = snode.at[wslot].set(-1, mode="drop")
    stime = st.sent_time.at[wslot].set(0, mode="drop")
    # clear heard-bits of reused slots, then originators hear their rumor
    newly = jnp.zeros((r_cap,), jnp.bool_).at[wslot].set(True, mode="drop")
    knows = jnp.where(newly[None, :], False, st.knows)
    knows = knows.at[jnp.where(placed, orig_c, n),
                     jnp.maximum(slot_f, 0)].max(placed, mode="drop")

    # sentinel joins: every placed suspect-class candidate is an independent
    # suspector; give it a sentinel slot if one is free and it is new there
    joiner = placed & susp_c
    tgt_r = jnp.where(joiner, slot_f, r_cap)
    already = jnp.any(snode[jnp.clip(tgt_r, 0, r_cap - 1)]
                      == orig_c[:, None], axis=-1) & joiner
    joiner = joiner & ~already
    tgt_r = jnp.where(joiner, slot_f, r_cap)
    same_r = (tgt_r[:, None] == tgt_r[None, :])
    grp_rank = jnp.sum(same_r & earlier & joiner[None, :],
                       axis=-1).astype(jnp.int32)
    fill_now = jnp.sum(snode[jnp.clip(tgt_r, 0, r_cap - 1)] >= 0,
                       axis=-1).astype(jnp.int32)
    spos = fill_now + grp_rank
    j_ok = joiner & (spos < s_cap)
    wr = jnp.where(j_ok, tgt_r, r_cap)
    ws = jnp.clip(spos, 0, s_cap - 1)
    snode = snode.at[wr, ws].set(orig_c, mode="drop")
    stime = stime.at[wr, ws].set(t, mode="drop")

    # mark confirmed suspicions whose DEAD rumor actually landed
    conf_ok_slot = jnp.where(placed & (src_c >= 0), src_c, r_cap)
    confirmed = confirmed.at[conf_ok_slot].set(True, mode="drop")

    # Inactive (crashed or not-yet-joined) nodes are frozen by
    # construction: delivered() blocks receipt, and every origination path
    # (prober/refute/sentinel) requires activity. Their heard-bits for
    # *reused* slots are still cleared above — a frozen row only stays
    # meaningful for rumors that are still in the table.
    inc_self = jnp.where(~up, state.inc_self, inc_self)
    lha = jnp.where(~up, state.lha, lha)

    if prof is not None and prof.cut(
            "commit", rkey, knows=knows, inc_self=inc_self, lha=lha,
            gone_key=gone_key, subject=subject, rkey=rkey, birth=birth,
            snode=snode, stime=stime, confirmed=confirmed,
            overflow=overflow):
        return prof.captured

    if tap is not None:
        # ---- telemetry tap (swim_tpu/obs/engine.py EngineFrame) ----------
        # Selection stats measure the start-of-period piggyback pass (the
        # window the first wave consults); occupancy counts (node,
        # eligible-rumor) heard pairs at period start.
        kn0 = state.knows[:, cand_idx] & cand_valid[None, :]
        _, val0 = select_first_b(kn0)
        row_bits = jnp.sum(val0.astype(jnp.int32), axis=-1)        # [N]
        tap["sel_slots_selected"] = jnp.sum(row_bits)
        tap["sel_rows_saturated"] = jnp.sum(
            ((row_bits >= b_pig) & up).astype(jnp.int32))
        tap["sel_slots_max"] = jnp.max(row_bits)
        tap["win_occupancy"] = jnp.sum(
            (state.knows & eligible[None, :]).astype(jnp.int32))
        tap["waves_delivered"] = (
            jnp.sum(w1_ok) + jnp.sum(w2_ok) + jnp.sum(w3_ok)
            + jnp.sum(w4_ok) + jnp.sum(w5_ok)
            + jnp.sum(w6_ok)).astype(jnp.int32)
        tap["probes_failed"] = jnp.sum(failed).astype(jnp.int32)
        tap["overflow"] = overflow
        if prof is not None:
            prof.cut("telemetry_tap", tap["sel_slots_selected"])

    return RumorState(
        knows=knows, inc_self=inc_self, lha=lha, gone_key=gone_key,
        subject=subject, rkey=rkey, birth=birth,
        sent_node=snode, sent_time=stime, confirmed=confirmed,
        overflow=overflow, step=t + 1,
    )


@functools.partial(jax.jit, static_argnums=(0, 4))
def run(cfg: SwimConfig, state: RumorState, plan: FaultPlan,
        root_key: jax.Array, periods: int) -> RumorState:
    """Run `periods` protocol periods under one fused lax.scan."""

    def body(stt, _):
        rnd = draw_period_rumor(root_key, stt.step, cfg)
        return step(cfg, stt, plan, rnd), None

    state, _ = jax.lax.scan(body, state, None, length=periods)
    return state


class RumorEngine:
    """Convenience wrapper holding (cfg, plan, state) with a jitted step."""

    def __init__(self, cfg: SwimConfig, plan: FaultPlan,
                 root_key: jax.Array | None = None):
        self.cfg = cfg
        self.plan = plan
        self.root_key = (root_key if root_key is not None
                         else jax.random.key(0))
        self.state = init_state(cfg)
        self._step = jax.jit(functools.partial(step, cfg))

    def run(self, periods: int) -> RumorState:
        self.state = run(self.cfg, self.state, self.plan, self.root_key,
                         periods)
        return self.state

    def step_once(self, rnd: RumorRandomness | None = None) -> RumorState:
        if rnd is None:
            rnd = draw_period_rumor(self.root_key, self.state.step, self.cfg)
        self.state = self._step(self.state, self.plan, rnd)
        return self.state
