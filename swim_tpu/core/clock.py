"""Clock abstraction: one protocol implementation, two notions of time.

The reference runs nodes against real time; its 32-node in-process demo
(BASELINE.json configs[0]) shows the same code must also run many nodes in
one process. swim_tpu splits that seam explicitly:

  * `SimClock` — a deterministic discrete-event scheduler. Tests and the
    demo advance virtual time manually, so multi-node runs are exactly
    reproducible on one host (the reference's in-process cluster pattern,
    SURVEY.md §4).
  * `AsyncioClock` — wraps a running asyncio loop for real deployments
    (UDP transport).

Timers are the only way the Node observes time, so the protocol logic is
identical under both.
"""

from __future__ import annotations

import abc
import heapq
import itertools
from typing import Callable


class TimerHandle:
    __slots__ = ("cancelled", "_cancel_fn")

    def __init__(self, cancel_fn: Callable[[], None] | None = None):
        self.cancelled = False
        self._cancel_fn = cancel_fn

    def cancel(self) -> None:
        if not self.cancelled:
            self.cancelled = True
            if self._cancel_fn is not None:
                self._cancel_fn()


class Clock(abc.ABC):
    @abc.abstractmethod
    def now(self) -> float:
        """Current time in seconds."""

    @abc.abstractmethod
    def call_later(self, delay: float, fn: Callable[[], None]) -> TimerHandle:
        """Schedule `fn` after `delay` seconds; returns a cancellable handle."""


class SimClock(Clock):
    """Deterministic virtual time. Ties break by schedule order."""

    def __init__(self, start: float = 0.0):
        self._now = start
        self._heap: list[tuple[float, int, TimerHandle,
                               Callable[[], None]]] = []
        self._seq = itertools.count()

    def now(self) -> float:
        return self._now

    def call_later(self, delay: float, fn: Callable[[], None]) -> TimerHandle:
        h = TimerHandle()
        heapq.heappush(self._heap,
                       (self._now + max(delay, 0.0), next(self._seq), h, fn))
        return h

    # -- driving ------------------------------------------------------------

    def advance(self, dt: float) -> int:
        """Run all timers due within the next `dt` seconds; returns count."""
        return self.advance_to(self._now + dt)

    def advance_to(self, deadline: float) -> int:
        fired = 0
        while self._heap and self._heap[0][0] <= deadline:
            when, _, h, fn = heapq.heappop(self._heap)
            self._now = when
            if not h.cancelled:
                fired += 1
                fn()
        self._now = deadline
        return fired

    def pending(self) -> int:
        return sum(1 for _, _, h, _ in self._heap if not h.cancelled)


class AsyncioClock(Clock):
    """Real time via an asyncio event loop."""

    def __init__(self, loop=None):
        import asyncio

        self._loop = loop or asyncio.get_event_loop()

    def now(self) -> float:
        return self._loop.time()

    def call_later(self, delay: float, fn: Callable[[], None]) -> TimerHandle:
        timer = self._loop.call_later(delay, fn)
        return TimerHandle(timer.cancel)
