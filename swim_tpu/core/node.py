"""SWIM node runtime: the event-driven protocol driver.

The real-node counterpart of the reference's per-node tick (SURVEY.md §3
call stacks): a periodic probe loop (direct ping → k indirect ping-reqs →
suspect), the receive path (ping/ping-req/ack/nack/join handlers with
piggyback merge), the suspicion subprotocol with incarnation refutation,
and Lifeguard extensions (local health aware timeouts, nacks, buddy
priority) behind cfg flags.

Time and wire are injected (Clock + Transport), so the same Node runs:
  * many-per-process over SimNetwork/SimClock — deterministic tests & demo
    (the reference's 32-node in-process cluster),
  * one-per-host over UDPTransport/AsyncioClock — a real cluster.
"""

from __future__ import annotations

import dataclasses
import itertools
import math
import random
from typing import Callable

from swim_tpu.config import SwimConfig, log_n_of
from swim_tpu.core.clock import Clock, TimerHandle
from swim_tpu.core.codec import (Address, DecodeError, Message, WireUpdate,
                                 decode, encode)
from swim_tpu.core.gossip import PiggybackQueue
from swim_tpu.core.membership import MembershipTable
from swim_tpu.obs.registry import MetricsRegistry
from swim_tpu.obs.trace import Span, TraceSink
from swim_tpu.types import MsgKind, Opinion, Status


class _Probe:
    __slots__ = ("target", "acked", "nacked", "timers", "started", "span")

    def __init__(self, target: int):
        self.target = target
        self.acked = False
        self.nacked = False
        self.timers: list[TimerHandle] = []
        self.started = 0.0
        self.span: Span | None = None


class _Suspicion:
    __slots__ = ("incarnation", "timer", "confirmers", "started", "span")

    def __init__(self, incarnation: int, timer: TimerHandle, started: float):
        self.incarnation = incarnation
        self.timer = timer
        self.confirmers: set[int] = set()
        self.started = started
        self.span: Span | None = None


class Node:
    def __init__(self, cfg: SwimConfig, node_id: int, transport, clock: Clock,
                 seed: int | None = None,
                 on_event: Callable[[int, Opinion | None, Opinion], None]
                 | None = None,
                 trace: TraceSink | None = None):
        self.cfg = cfg
        self.id = node_id
        self.transport = transport
        self.clock = clock
        self.rng = random.Random(seed if seed is not None else node_id)
        self.members = MembershipTable(node_id, transport.local_address,
                                       self.rng)
        if on_event is not None:
            self.members.listeners.append(on_event)
        self.gossip = PiggybackQueue(cfg.max_piggyback)
        self.lha = 0  # Lifeguard local health score
        self._probes: dict[int, _Probe] = {}
        self._relays: dict[int, tuple[Address, int, int]] = {}
        self._suspicions: dict[int, _Suspicion] = {}
        self._seq = itertools.count(1)
        self._tick_timer: TimerHandle | None = None
        self._running = False
        # observability (swim_tpu/obs/): typed counter/histogram registry;
        # `stats` is a dict-compatible view over its counters (aggregation
        # in utils/metrics, exposition in obs/expo — undeclared keys raise,
        # scripts/check_metrics_registry.py enforces the declaration).
        # `trace` receives probe/suspicion lifecycle spans; None = off.
        self.registry = MetricsRegistry.node_default()
        self.stats = self.registry.stats_view()
        self.trace = trace

    # ------------------------------------------------------------------ API

    def start(self, seeds: list[Address] = ()) -> None:
        self.transport.set_receiver(self._on_datagram)
        self._running = True
        for s in seeds:
            if s != self.transport.local_address:
                self._send_to_addr(s, Message(kind=MsgKind.JOIN,
                                              sender=self.id))
        # desynchronize first ticks across nodes
        delay = self.rng.uniform(0, self.cfg.protocol_period)
        self._tick_timer = self.clock.call_later(delay, self._tick)

    def stop(self) -> None:
        self._running = False
        if self._tick_timer:
            self._tick_timer.cancel()
        for p in self._probes.values():
            for t in p.timers:
                t.cancel()
        for s in self._suspicions.values():
            s.timer.cancel()
        self._probes.clear()
        self._suspicions.clear()
        self._relays.clear()

    def bootstrap(self, members: list[tuple[int, Address]]) -> None:
        """Statically seed the membership table (demo/test convenience)."""
        for mid, addr in members:
            self.members.note_member(mid, addr)

    # ---------------------------------------------------------- protocol tick

    def _tick(self) -> None:
        if not self._running:
            return
        self._tick_timer = self.clock.call_later(self.cfg.protocol_period,
                                                 self._tick)
        self.gossip.gc(self._retransmit_limit())
        target = self.members.next_probe_target()
        if target is None:
            return
        self.stats["probes"] += 1
        seq = next(self._seq)
        probe = _Probe(target)
        probe.started = self.clock.now()
        if self.trace is not None:
            probe.span = Span("probe", self.id, target, probe.started)
            probe.span.event(probe.started, "ping")
        self._probes[seq] = probe
        self._send(target, Message(kind=MsgKind.PING, sender=self.id,
                                   probe_seq=seq),
                   forced=self._buddy(target))
        probe.timers.append(self.clock.call_later(
            self._probe_timeout(), lambda: self._on_probe_timeout(seq)))
        probe.timers.append(self.clock.call_later(
            self.cfg.protocol_period * 0.95,
            lambda: self._on_probe_period_end(seq)))

    def _probe_timeout(self) -> float:
        frac = 0.3 * self.cfg.protocol_period
        if self.cfg.lifeguard:
            # LHA: an unhealthy node waits longer before fanning out
            frac *= 1.0 + self.lha / max(self.cfg.lha_max, 1)
        return min(frac, 0.9 * self.cfg.protocol_period)

    def _on_probe_timeout(self, seq: int) -> None:
        probe = self._probes.get(seq)
        if probe is None or probe.acked:
            return
        target_addr = self.members.addr(probe.target)
        if target_addr is None:
            return
        for proxy in self.members.random_members(
                self.cfg.k_indirect, {self.id, probe.target}):
            if probe.span is not None:
                probe.span.event(self.clock.now(), "ping-req")
            self._send(proxy, Message(kind=MsgKind.PING_REQ, sender=self.id,
                                      probe_seq=seq, target=probe.target,
                                      target_addr=target_addr))

    def _on_probe_period_end(self, seq: int) -> None:
        probe = self._probes.pop(seq, None)
        if probe is None:
            return
        ok = probe.acked
        if self.cfg.lifeguard:
            # Lifeguard LHA: clean round -1; failed round with zero feedback
            # +1; failed round where nacks proved our network path works: 0.
            delta = -1 if ok else (0 if probe.nacked else 1)
            self.lha = min(max(self.lha + delta, 0), self.cfg.lha_max)
        if probe.span is not None and self.trace is not None:
            self.trace.emit(probe.span.finish(self.clock.now(),
                                              "ack" if ok else "fail"))
        if ok:
            return
        self.stats["probe_failures"] += 1
        self._suspect(probe.target)

    # ----------------------------------------------------------- suspicion

    def _suspect(self, member: int) -> None:
        op = self.members.opinion(member)
        if op is None or op.status != Status.ALIVE:
            return
        new = Opinion(Status.SUSPECT, op.incarnation)
        self._apply_and_gossip(member, new)

    def _start_suspicion_timer(self, member: int, incarnation: int,
                               origin: int | None = None) -> None:
        old = self._suspicions.pop(member, None)
        if old is not None:
            old.timer.cancel()
            self._finish_suspicion(member, old, "superseded")
        timeout = self._suspicion_timeout(0)
        timer = self.clock.call_later(
            timeout, lambda: self._on_suspicion_expired(member))
        s = _Suspicion(incarnation, timer, self.clock.now())
        if origin is not None:
            s.confirmers.add(origin)
        if self.trace is not None:
            s.span = Span("suspicion", self.id, member, s.started)
        self._suspicions[member] = s
        self.stats["suspicions"] += 1

    def _finish_suspicion(self, member: int, s: _Suspicion,
                          outcome: str) -> None:
        """Record a suspicion's resolution (histogram + span)."""
        self.registry.observe("suspicion_duration_seconds",
                              self.clock.now() - s.started)
        if s.span is not None and self.trace is not None:
            self.trace.emit(s.span.finish(self.clock.now(), outcome))

    def _cancel_suspicion(self, member: int) -> None:
        """Drop a suspicion refuted/overridden by fresher gossip."""
        s = self._suspicions.pop(member, None)
        if s is None:
            return
        s.timer.cancel()
        self._finish_suspicion(member, s, "refuted")

    def _suspicion_timeout(self, confirmations: int) -> float:
        n = max(self.members.alive_count(), 2)
        base = self.cfg.suspicion_mult * log_n_of(n) * self.cfg.protocol_period
        if not (self.cfg.lifeguard and self.cfg.dynamic_suspicion):
            return base
        # Lifeguard: start high (benefit of the doubt), shrink toward the
        # vanilla floor as independent suspectors corroborate.
        max_t = base * self.cfg.suspicion_max_mult
        c_max = self.cfg.k_indirect + 1
        frac = math.log(confirmations + 1) / math.log(c_max + 1)
        return max(base, max_t - (max_t - base) * frac)

    def _confirm_suspicion(self, member: int, from_node: int,
                           incarnation: int) -> None:
        """Independent suspector seen → shrink the timer (Lifeguard).

        A claim about an older incarnation is refuted information and must
        not accelerate the current suspicion."""
        s = self._suspicions.get(member)
        if s is None or incarnation < s.incarnation \
                or from_node in s.confirmers:
            return
        s.confirmers.add(from_node)
        if s.span is not None:
            s.span.event(self.clock.now(), "confirm")
        if not (self.cfg.lifeguard and self.cfg.dynamic_suspicion):
            return
        elapsed = self.clock.now() - s.started
        # c = extra suspectors beyond the originator (docs/PROTOCOL.md §7:
        # a lone suspector waits the full max; matches rumor.py's filled-1)
        remain = self._suspicion_timeout(len(s.confirmers) - 1) - elapsed
        s.timer.cancel()
        s.timer = self.clock.call_later(
            max(remain, 0.0), lambda: self._on_suspicion_expired(member))

    def _on_suspicion_expired(self, member: int) -> None:
        s = self._suspicions.pop(member, None)
        if s is None:
            return
        op = self.members.opinion(member)
        if op is None or op.status != Status.SUSPECT:
            self._finish_suspicion(member, s, "superseded")
            return
        self.stats["deaths_declared"] += 1
        self._finish_suspicion(member, s, "confirmed")
        self._apply_and_gossip(member, Opinion(Status.DEAD, op.incarnation))

    # ------------------------------------------------------------- receive

    def _on_datagram(self, src: Address, payload: bytes) -> None:
        if not self._running:
            return
        self.stats["messages_in"] += 1
        try:
            msg = decode(payload)
        except DecodeError:
            self.stats["decode_errors"] += 1
            return
        self._merge_gossip(msg, src)
        handler = {
            MsgKind.PING: self._on_ping,
            MsgKind.PING_REQ: self._on_ping_req,
            MsgKind.ACK: self._on_ack,
            MsgKind.NACK: self._on_nack,
            MsgKind.JOIN: self._on_join,
            MsgKind.JOIN_REPLY: lambda m, a: None,  # gossip merge did it all
        }[msg.kind]
        handler(msg, src)

    def _note_and_gossip(self, member: int, addr: Address) -> None:
        """Register a directly-observed member; gossip the discovery if new
        so joins disseminate infection-style (O(log N) periods), not by
        O(N) direct contact."""
        if self.members.note_member(member, addr):
            self.gossip.enqueue(WireUpdate(member, Status.ALIVE, 0, addr,
                                           origin=self.id))

    def _on_ping(self, msg: Message, src: Address) -> None:
        self._note_and_gossip(msg.sender, src)
        self._send_to_addr(src, self._with_gossip(Message(
            kind=MsgKind.ACK, sender=self.id, probe_seq=msg.probe_seq,
            on_behalf=msg.on_behalf)))

    def _on_ping_req(self, msg: Message, src: Address) -> None:
        """Probe `msg.target` on the requester's behalf and relay the result."""
        self._note_and_gossip(msg.sender, src)
        sub_seq = next(self._seq)
        self._relays[sub_seq] = (src, msg.probe_seq, msg.target)
        self._send_to_addr(msg.target_addr, self._with_gossip(
            Message(kind=MsgKind.PING, sender=self.id, probe_seq=sub_seq,
                    on_behalf=msg.sender),
            forced=self._buddy(msg.target)))

        # reap the relay entry whether or not the sub-probe succeeds; under
        # Lifeguard additionally tell the requester we tried (nack)
        def expire_relay():
            if sub_seq in self._relays:
                requester, rseq, _ = self._relays.pop(sub_seq)
                if self.cfg.lifeguard:
                    self._send_to_addr(requester, self._with_gossip(Message(
                        kind=MsgKind.NACK, sender=self.id, probe_seq=rseq)))

        self.clock.call_later(self._probe_timeout(), expire_relay)

    def _on_ack(self, msg: Message, src: Address) -> None:
        relay = self._relays.pop(msg.probe_seq, None)
        if relay is not None:
            requester, rseq, _ = relay
            self._send_to_addr(requester, self._with_gossip(Message(
                kind=MsgKind.ACK, sender=self.id, probe_seq=rseq,
                on_behalf=msg.sender)))
            return
        probe = self._probes.get(msg.probe_seq)
        if probe is not None:
            if not probe.acked:
                self.registry.observe("probe_rtt_seconds",
                                      self.clock.now() - probe.started)
                if probe.span is not None:
                    probe.span.event(self.clock.now(), "ack")
            probe.acked = True

    def _on_nack(self, msg: Message, src: Address) -> None:
        # Lifeguard: feedback arrived though the probe failed — our network
        # path works, so this round must not raise local health's fail score.
        probe = self._probes.get(msg.probe_seq)
        if probe is not None:
            probe.nacked = True
            if probe.span is not None:
                probe.span.event(self.clock.now(), "nack")

    def _on_join(self, msg: Message, src: Address) -> None:
        self._note_and_gossip(msg.sender, src)
        snapshot = [
            WireUpdate(m.id, m.opinion.status, m.opinion.incarnation, m.addr,
                       origin=self.id)
            for m in self.members.members()]
        # the codec caps one gossip section at 255 updates: chunk large
        # snapshots across several JOIN_REPLY datagrams
        for i in range(0, len(snapshot), 200):
            self._send_to_addr(src, Message(
                kind=MsgKind.JOIN_REPLY, sender=self.id,
                gossip=tuple(snapshot[i:i + 200])))

    # -------------------------------------------------------------- gossip

    def _merge_gossip(self, msg: Message, src: Address) -> None:
        for u in msg.gossip:
            if u.member == self.id:
                self._handle_self_update(u)
                continue
            changed = self.members.apply(u.member, u.addr,
                                         Opinion(u.status, u.incarnation))
            if u.status == Status.SUSPECT:
                self._confirm_suspicion(u.member, u.origin, u.incarnation)
            if not changed:
                continue
            self.gossip.enqueue(u)
            if u.status == Status.SUSPECT:
                self._start_suspicion_timer(u.member, u.incarnation,
                                            origin=u.origin)
            else:
                self._cancel_suspicion(u.member)

    def _handle_self_update(self, u: WireUpdate) -> None:
        """Someone claims we are suspect/dead → refute if we can."""
        if u.status == Status.ALIVE:
            return
        if u.incarnation < self.members.incarnation and \
                u.status == Status.SUSPECT:
            return  # stale suspicion, already refuted
        if u.status == Status.DEAD:
            # sticky death cannot be refuted (docs/PROTOCOL.md §2); a real
            # deployment would rejoin with a fresh id. Keep running.
            return
        self.stats["refutations"] += 1
        new = self.members.refute()
        if self.cfg.lifeguard:
            self.lha = min(self.lha + 1, self.cfg.lha_max)
        self.gossip.enqueue(WireUpdate(self.id, new.status, new.incarnation,
                                       self.transport.local_address,
                                       origin=self.id))

    def _apply_and_gossip(self, member: int, op: Opinion) -> None:
        addr = self.members.addr(member) or ("", 0)
        if self.members.apply(member, addr, op):
            self.gossip.enqueue(WireUpdate(member, op.status, op.incarnation,
                                           addr, origin=self.id))
            if op.status == Status.SUSPECT:
                self._start_suspicion_timer(member, op.incarnation,
                                            origin=self.id)
            else:
                self._cancel_suspicion(member)

    # ---------------------------------------------------------------- wire

    def _buddy(self, target: int) -> WireUpdate | None:
        """Lifeguard buddy: when pinging a suspect, tell it so.

        Asserted from the membership table (with ourselves as origin — we do
        hold that belief), NOT from the piggyback queue: the queued entry's
        retransmit budget may be exhausted and gc'd long before the suspect
        is ever probed, and the buddy signal must survive that.
        """
        if not (self.cfg.lifeguard and self.cfg.buddy):
            return None
        op = self.members.opinion(target)
        if op is None or op.status != Status.SUSPECT:
            return None
        return WireUpdate(target, op.status, op.incarnation,
                          self.members.addr(target) or ("", 0),
                          origin=self.id)

    def _retransmit_limit(self) -> int:
        n = max(self.members.alive_count(), 2)
        return max(1, math.ceil(self.cfg.retransmit_mult * log_n_of(n)))

    def _with_gossip(self, msg: Message,
                     forced: WireUpdate | None = None) -> Message:
        sel = self.gossip.select(self._retransmit_limit())
        if forced is not None and all(u.member != forced.member
                                      for u in sel):
            kept = sel[:self.cfg.max_piggyback - 1]
            for displaced in sel[self.cfg.max_piggyback - 1:]:
                self.gossip.refund(displaced)  # charged but never sent
            sel = [forced] + kept
        return dataclasses.replace(msg, gossip=tuple(sel))

    def _send(self, member: int, msg: Message,
              forced: WireUpdate | None = None) -> None:
        addr = self.members.addr(member)
        if addr is None:
            return
        self._send_to_addr(addr, self._with_gossip(msg, forced))

    def _send_to_addr(self, addr: Address, msg: Message) -> None:
        self.stats["messages_out"] += 1
        self.transport.send(addr, encode(msg))
