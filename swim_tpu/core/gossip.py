"""Gossip piggyback queue: bounded infection-style dissemination.

SURVEY.md §2 "Gossip piggyback buffer": recent membership updates ride on
every outgoing ping/ack. Each update is retransmitted a bounded number of
times (λ·log N sends reaches everyone w.h.p.); selection prefers the
least-retransmitted (freshest) updates, ties by member id — the same rule
the simulators implement (docs/PROTOCOL.md §3).
"""

from __future__ import annotations

import dataclasses

from swim_tpu.core.codec import WireUpdate

Address = tuple[str, int]


@dataclasses.dataclass
class _Entry:
    update: WireUpdate
    transmits: int = 0


class PiggybackQueue:
    def __init__(self, max_piggyback: int):
        self.max_piggyback = max_piggyback
        self._entries: dict[int, _Entry] = {}   # member → freshest update

    def enqueue(self, update: WireUpdate) -> None:
        """Queue new information about a member (replaces any older entry,
        resetting its retransmit budget)."""
        self._entries[update.member] = _Entry(update)

    def select(self, limit: int) -> list[WireUpdate]:
        """Pick ≤ max_piggyback updates still under the retransmit `limit`,
        fewest-transmits-first (ties by member id); counts the sends.

        Lifeguard's buddy priority is NOT handled here: buddy updates are
        asserted from the membership table by the Node (they must survive
        this queue's budget exhaustion and gc).
        """
        live = [e for e in self._entries.values() if e.transmits < limit]
        live.sort(key=lambda e: (e.transmits, e.update.member))
        sel = live[:self.max_piggyback]
        for e in sel:
            e.transmits += 1
        return [e.update for e in sel]

    def refund(self, update: WireUpdate) -> None:
        """Un-count one send of an update that was selected but then
        displaced from the outgoing message (Lifeguard buddy force)."""
        e = self._entries.get(update.member)
        if e is not None and e.update == update and e.transmits > 0:
            e.transmits -= 1

    def gc(self, limit: int) -> None:
        """Drop entries whose retransmit budget is exhausted."""
        self._entries = {m: e for m, e in self._entries.items()
                         if e.transmits < limit}

    def __len__(self) -> int:
        return len(self._entries)
