"""Transport abstraction — the extension seam of the framework.

The reference abstracts its wire behind the `Swim.Transport` typeclass
(SURVEY.md §1: send/receive of protocol messages; instances for real
sockets and the in-process 32-node demo). swim_tpu mirrors that seam as an
ABC with three implementations:

  * `InProcessTransport` — deterministic in-memory network for multi-node
    runs in one process (the demo/test fixture), with injectable loss,
    partitions, and per-link latency driven by a `SimClock`.
  * `UDPTransport` — asyncio datagram transport for real clusters.
  * `TPUSimTransport` (swim_tpu/bridge) — the north-star backend: messages
    delivered into the vectorized TPU simulation.

Addresses are opaque `(host, port)` tuples; the in-process network uses
("sim", node_id).
"""

from __future__ import annotations

import abc
import random
from typing import Callable

from swim_tpu.core.clock import Clock, SimClock

Address = tuple[str, int]
Receiver = Callable[[Address, bytes], None]


class Transport(abc.ABC):
    """Datagram-style message transport (unreliable, unordered is allowed)."""

    @abc.abstractmethod
    def send(self, to: Address, payload: bytes) -> None:
        """Fire-and-forget send; loss is legal and expected."""

    @abc.abstractmethod
    def set_receiver(self, receiver: Receiver) -> None:
        """Register the inbound-message callback (sender address, payload)."""

    @property
    @abc.abstractmethod
    def local_address(self) -> Address:
        ...

    def close(self) -> None:  # pragma: no cover - trivial default
        pass


class SimNetwork:
    """Shared medium for InProcessTransport endpoints.

    Delivery is scheduled on the SimClock (default latency 1 ms), so message
    interleavings are deterministic given the seed — the reference's
    in-process cluster pattern made reproducible.
    """

    def __init__(self, clock: SimClock, seed: int = 0, loss: float = 0.0,
                 latency: float = 0.001, duplicate: float = 0.0,
                 replay: float = 0.0, replay_buffer: int = 256):
        self.clock = clock
        self.rng = random.Random(seed)
        self.loss = loss
        self.latency = latency
        # adversarial delivery (sim/scenario.py replay-storm): `duplicate`
        # is the probability a delivered datagram arrives twice; `replay`
        # the probability each transmit additionally re-delivers a random
        # STALE datagram (same src/dst as when first sent — old
        # incarnations included), from a bounded history.  Both ride the
        # same seeded rng/clock, so runs stay deterministic.  The decode
        # path must be idempotent under both (core/node.py merges are
        # monotone lattice joins; tests/test_scenario.py pins it).
        self.duplicate = duplicate
        self.replay = replay
        self._replay_buffer = replay_buffer
        self._history: list[tuple[Address, Address, bytes]] = []
        self._link_latency: dict[frozenset[Address], float] = {}
        self._endpoints: dict[Address, "InProcessTransport"] = {}
        self._cut: set[frozenset[Address]] = set()
        self._down: set[Address] = set()
        self.sent = 0
        self.delivered = 0
        self.duplicated = 0
        self.replayed = 0

    def attach(self, ep: "InProcessTransport") -> None:
        self._endpoints[ep.local_address] = ep

    def detach(self, addr: Address) -> None:
        """Remove an endpoint; traffic to it is dropped from now on."""
        self._endpoints.pop(addr, None)

    # -- fault injection ----------------------------------------------------

    def set_loss(self, loss: float) -> None:
        self.loss = loss

    def set_link_latency(self, a: Address, b: Address,
                         seconds: float) -> None:
        """Override the default latency for one (undirected) link — e.g. a
        slow WAN pair in an otherwise-LAN cluster."""
        self._link_latency[frozenset((a, b))] = seconds

    def cut(self, a: Address, b: Address) -> None:
        self._cut.add(frozenset((a, b)))

    def heal(self, a: Address, b: Address) -> None:
        self._cut.discard(frozenset((a, b)))

    def partition(self, group_a: list[Address], group_b: list[Address]):
        for a in group_a:
            for b in group_b:
                self.cut(a, b)

    def heal_all(self) -> None:
        self._cut.clear()

    def kill(self, addr: Address) -> None:
        """Crash-stop a node: its endpoint neither sends nor receives."""
        self._down.add(addr)

    # -- delivery -----------------------------------------------------------

    def transmit(self, src: Address, dst: Address, payload: bytes) -> None:
        self.sent += 1
        if self.replay and self._history:
            # stale replay rides on traffic: each transmit may re-deliver
            # one random datagram from the bounded history (possibly
            # carrying an out-of-date incarnation)
            if self.rng.random() < self.replay:
                rsrc, rdst, rpayload = self._history[
                    self.rng.randrange(len(self._history))]
                self.replayed += 1
                self._schedule(rsrc, rdst, rpayload)
        if src in self._down or dst in self._down:
            return
        if frozenset((src, dst)) in self._cut:
            return
        if self.loss and self.rng.random() < self.loss:
            return
        if self.duplicate or self.replay:
            self._history.append((src, dst, payload))
            if len(self._history) > self._replay_buffer:
                del self._history[:len(self._history) - self._replay_buffer]
        self._schedule(src, dst, payload)
        if self.duplicate and self.rng.random() < self.duplicate:
            self.duplicated += 1
            self._schedule(src, dst, payload)

    def _schedule(self, src: Address, dst: Address,
                  payload: bytes) -> None:
        if dst in self._down or frozenset((src, dst)) in self._cut:
            return
        ep = self._endpoints.get(dst)
        if ep is None:
            return

        def deliver():
            if dst in self._down:
                return
            self.delivered += 1
            if ep._receiver is not None:
                ep._receiver(src, payload)

        lat = self._link_latency.get(frozenset((src, dst)), self.latency)
        self.clock.call_later(lat, deliver)


class InProcessTransport(Transport):
    """Loopback transport instance backing multi-node single-process runs."""

    def __init__(self, network: SimNetwork, node_id: int):
        self._network = network
        self._addr: Address = ("sim", node_id)
        self._receiver: Receiver | None = None
        network.attach(self)

    def send(self, to: Address, payload: bytes) -> None:
        self._network.transmit(self._addr, to, payload)

    def set_receiver(self, receiver: Receiver) -> None:
        self._receiver = receiver

    @property
    def local_address(self) -> Address:
        return self._addr


class UDPTransport(Transport):
    """Real-network instance over asyncio UDP datagrams.

    Create with `await UDPTransport.create(host, port)` inside a running
    loop; pairs with core.clock.AsyncioClock.
    """

    def __init__(self, transport, local: Address):
        self._transport = transport
        self._local = local
        self._receiver: Receiver | None = None

    @classmethod
    async def create(cls, host: str = "127.0.0.1", port: int = 0):
        import asyncio

        loop = asyncio.get_running_loop()
        self_holder: dict = {}

        class _Proto(asyncio.DatagramProtocol):
            def datagram_received(_, data: bytes, addr):
                t = self_holder.get("t")
                if t is not None and t._receiver is not None:
                    t._receiver((addr[0], addr[1]), data)

        transport, _ = await loop.create_datagram_endpoint(
            _Proto, local_addr=(host, port))
        sock = transport.get_extra_info("sockname")
        t = cls(transport, (sock[0], sock[1]))
        self_holder["t"] = t
        return t

    def send(self, to: Address, payload: bytes) -> None:
        self._transport.sendto(payload, to)

    def set_receiver(self, receiver: Receiver) -> None:
        self._receiver = receiver

    @property
    def local_address(self) -> Address:
        return self._local

    def close(self) -> None:
        self._transport.close()
