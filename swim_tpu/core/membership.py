"""Membership table: statuses, incarnations, probe ordering.

The per-node view of the cluster (SURVEY.md §2 "Membership table"): a map
member → (Opinion, address), merged under the swim_tpu.types lattice, plus
SWIM §4.3's randomized round-robin probe order — shuffle the member list,
walk it, re-shuffle when exhausted; newly learned members insert at a random
position of the remaining walk so they cannot be starved.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Callable, Iterable

from swim_tpu.types import Opinion, Status, merge, supersedes

Address = tuple[str, int]


@dataclasses.dataclass
class Member:
    id: int
    addr: Address
    opinion: Opinion


class MembershipTable:
    def __init__(self, self_id: int, self_addr: Address,
                 rng: random.Random | None = None):
        self.self_id = self_id
        self.incarnation = 0          # own incarnation (grows by refutation)
        self._members: dict[int, Member] = {
            self_id: Member(self_id, self_addr, Opinion(Status.ALIVE, 0))}
        self._rng = rng or random.Random()
        self._probe_order: list[int] = []
        # hooks: fired on effective status changes (the reference's event
        # callbacks); signature (member_id, old Opinion|None, new Opinion)
        self.listeners: list[Callable[[int, Opinion | None, Opinion], None]] \
            = []

    # -- queries ------------------------------------------------------------

    def get(self, member: int) -> Member | None:
        return self._members.get(member)

    def opinion(self, member: int) -> Opinion | None:
        m = self._members.get(member)
        return m.opinion if m else None

    def addr(self, member: int) -> Address | None:
        m = self._members.get(member)
        return m.addr if m else None

    def members(self) -> list[Member]:
        return list(self._members.values())

    def ids(self, statuses: Iterable[Status] | None = None) -> list[int]:
        if statuses is None:
            return list(self._members)
        allowed = set(statuses)
        return [m.id for m in self._members.values()
                if m.opinion.status in allowed]

    def alive_count(self) -> int:
        return sum(1 for m in self._members.values()
                   if m.opinion.status != Status.DEAD)

    def __len__(self) -> int:
        return len(self._members)

    # -- mutation -----------------------------------------------------------

    def note_member(self, member: int, addr: Address) -> bool:
        """Learn a member exists (e.g. from a join) without an opinion yet.

        Returns True iff the member was new — callers gossip the discovery
        so joins disseminate infection-style in O(log N) periods rather
        than by O(N) direct contact."""
        if member not in self._members:
            self._apply_new(member, addr, Opinion(Status.ALIVE, 0))
            return True
        return False

    def apply(self, member: int, addr: Address, op: Opinion) -> bool:
        """Lattice-merge a received update. True iff it was new information.

        Self-updates are special: a SUSPECT/DEAD claim about *us* at our
        incarnation (or higher) triggers refutation handling in the Node —
        here it merges like any update so callers can inspect it.
        """
        cur = self._members.get(member)
        if cur is None:
            self._apply_new(member, addr, op)
            return True
        if not supersedes(op, cur.opinion):
            return False
        old = cur.opinion
        cur.opinion = merge(cur.opinion, op)
        if cur.addr[0] == "" and addr[0] != "":
            cur.addr = addr
        self._notify(member, old, cur.opinion)
        return True

    def refute(self) -> Opinion:
        """Bump own incarnation above any suspicion of us; returns new self
        opinion (to be gossiped)."""
        me = self._members[self.self_id]
        contested = me.opinion.incarnation
        self.incarnation = max(self.incarnation, contested) + 1
        old = me.opinion
        me.opinion = Opinion(Status.ALIVE, self.incarnation)
        self._notify(self.self_id, old, me.opinion)
        return me.opinion

    def _apply_new(self, member: int, addr: Address, op: Opinion) -> None:
        self._members[member] = Member(member, addr, op)
        # insert into the remaining probe walk at a random position so new
        # members are probed within one round (SWIM §4.3)
        if member != self.self_id:
            pos = self._rng.randint(0, len(self._probe_order))
            self._probe_order.insert(pos, member)
        self._notify(member, None, op)

    def _notify(self, member: int, old: Opinion | None, new: Opinion):
        for fn in self.listeners:
            fn(member, old, new)

    # -- probe ordering (randomized round-robin, SWIM §4.3) -----------------

    def next_probe_target(self) -> int | None:
        """Next member to probe: walk a shuffled list, skip the dead,
        re-shuffle when exhausted. None if nobody is probeable."""
        for _ in range(2):
            while self._probe_order:
                m = self._probe_order.pop()
                mem = self._members.get(m)
                if mem is not None and mem.opinion.status != Status.DEAD:
                    return m
            fresh = [m.id for m in self._members.values()
                     if m.id != self.self_id
                     and m.opinion.status != Status.DEAD]
            self._rng.shuffle(fresh)
            self._probe_order = fresh
            if not fresh:
                return None
        return None

    def random_members(self, k: int, exclude: set[int]) -> list[int]:
        """k distinct members for indirect probing, excluding the given ids
        and the dead."""
        pool = [m.id for m in self._members.values()
                if m.id not in exclude and m.id != self.self_id
                and m.opinion.status != Status.DEAD]
        self._rng.shuffle(pool)
        return pool[:k]
