"""In-process cluster harness — the reference's stock demo as a library.

Builds N Nodes over one SimNetwork/SimClock (BASELINE.json configs[0]: the
32-node in-process cluster, k=3, 1 s period) with deterministic virtual
time, fault injection hooks, and convergence queries. This doubles as the
multi-node-without-a-cluster test fixture (SURVEY.md §4).
"""

from __future__ import annotations

from swim_tpu.config import SwimConfig
from swim_tpu.core.clock import SimClock
from swim_tpu.core.node import Node
from swim_tpu.core.transport import InProcessTransport, SimNetwork
from swim_tpu.types import Status


class SimCluster:
    def __init__(self, cfg: SwimConfig, seed: int = 0, loss: float = 0.0,
                 latency: float = 0.001, trace=None,
                 duplicate: float = 0.0, replay: float = 0.0):
        # `trace`: optional swim_tpu.obs.trace.TraceSink shared by every
        # node — probe/suspicion lifecycle spans from the whole cluster.
        # `duplicate`/`replay`: adversarial delivery (SimNetwork), the
        # replay-storm scenario's idempotence workload.
        self.cfg = cfg
        self.clock = SimClock()
        self.network = SimNetwork(self.clock, seed=seed, loss=loss,
                                  latency=latency, duplicate=duplicate,
                                  replay=replay)
        self.nodes: list[Node] = []
        roster = []
        for i in range(cfg.n_nodes):
            t = InProcessTransport(self.network, i)
            self.nodes.append(Node(cfg, i, t, self.clock,
                                   seed=seed * 7919 + i, trace=trace))
            roster.append((i, t.local_address))
        for node in self.nodes:
            node.bootstrap(roster)

    def start(self) -> None:
        for n in self.nodes:
            n.start()

    def run(self, seconds: float) -> None:
        self.clock.advance(seconds)

    # -- fault injection ----------------------------------------------------

    def kill(self, node_id: int) -> None:
        """Crash-stop: the node's messages stop flowing; its timers die."""
        self.network.kill(("sim", node_id))
        self.nodes[node_id].stop()

    def partition_halves(self) -> None:
        n = self.cfg.n_nodes
        a = [("sim", i) for i in range(n // 2)]
        b = [("sim", i) for i in range(n // 2, n)]
        self.network.partition(a, b)

    def heal(self) -> None:
        self.network.heal_all()

    # -- queries ------------------------------------------------------------

    def views_of(self, member: int) -> list[Status]:
        return [n.members.opinion(member).status
                if n.members.opinion(member) else None
                for n in self.nodes]

    def all_consider(self, member: int, status: Status,
                     among: list[int] | None = None) -> bool:
        among = among if among is not None else range(self.cfg.n_nodes)
        return all(
            (op := self.nodes[i].members.opinion(member)) is not None
            and op.status == status
            for i in among)

    def converged_all_alive(self) -> bool:
        return all(
            self.all_consider(m, Status.ALIVE)
            for m in range(self.cfg.n_nodes))

    def detection_time(self, victim: int, timeout_s: float,
                       tick: float = 0.1) -> float | None:
        """Advance time until some live node stops believing `victim` ALIVE;
        returns elapsed seconds (None on timeout)."""
        start = self.clock.now()
        live = [i for i in range(self.cfg.n_nodes) if i != victim]
        while self.clock.now() - start < timeout_s:
            self.clock.advance(tick)
            for i in live:
                op = self.nodes[i].members.opinion(victim)
                if op is not None and op.status != Status.ALIVE:
                    return self.clock.now() - start
        return None
