"""Binary wire codec for SWIM protocol messages.

Compact datagram format (network byte order) mirroring the reference's
message set — ping / ping-req / ack plus piggybacked membership updates
(SURVEY.md §1 Transport row) — extended with Lifeguard's nack and the
join/snapshot pair:

    header:  magic 'W' | version u8 | kind u8 | sender_id u32
    body:    per-kind fields (below)
    gossip:  count u8, then count × update
    update:  member u32 | status u8 | incarnation u32 | origin u32 | address
    address: host_len u8 | host utf-8 | port u32 (u32: in-process
             transports use node ids as ports, which exceed u16)

Every message carries a gossip section (possibly empty) — dissemination is
piggybacked on the failure-detector traffic, never separate packets, exactly
the SWIM dissemination component. Updates carry the member's address so
joiners learn how to reach gossiped members (the join snapshot is just a
JOIN_REPLY with a large gossip section).
"""

from __future__ import annotations

import dataclasses
import struct

from swim_tpu.types import MsgKind, Status, Update

MAGIC = 0x57  # 'W'
VERSION = 1
_HDR = struct.Struct("!BBBI")
_UPD = struct.Struct("!IBII")

Address = tuple[str, int]


@dataclasses.dataclass(frozen=True)
class WireUpdate:
    """A membership update plus the member's address."""

    member: int
    status: Status
    incarnation: int
    addr: Address
    # Originator of the claim (SUSPECT: the suspecting node; DEAD: the
    # declarer). Lifeguard's dynamic suspicion counts *distinct origins* as
    # independent confirmations; relaying preserves the origin.
    origin: int = 0

    @property
    def update(self) -> Update:
        return Update(self.member, self.status, self.incarnation)


@dataclasses.dataclass(frozen=True)
class Message:
    kind: MsgKind
    sender: int
    probe_seq: int = 0
    target: int = 0           # PING_REQ / proxy PING: the probed member
    target_addr: Address = ("", 0)  # PING_REQ: where the proxy finds it
    on_behalf: int = 0        # proxy PING/ACK relay bookkeeping
    gossip: tuple[WireUpdate, ...] = ()


def _pack_addr(addr: Address) -> bytes:
    host = addr[0].encode()
    if len(host) > 255:
        raise ValueError("host too long")
    return bytes([len(host)]) + host + struct.pack("!I", addr[1])


def _unpack_addr(buf: bytes, off: int) -> tuple[Address, int]:
    ln = buf[off]
    off += 1
    host = buf[off:off + ln].decode()
    off += ln
    (port,) = struct.unpack_from("!I", buf, off)
    return (host, port), off + 4


def encode(msg: Message) -> bytes:
    out = [_HDR.pack(MAGIC, VERSION, int(msg.kind), msg.sender)]
    k = msg.kind
    if k in (MsgKind.PING, MsgKind.ACK, MsgKind.NACK):
        out.append(struct.pack("!II", msg.probe_seq, msg.on_behalf))
    elif k == MsgKind.PING_REQ:
        out.append(struct.pack("!II", msg.probe_seq, msg.target))
        out.append(_pack_addr(msg.target_addr))
    elif k in (MsgKind.JOIN, MsgKind.JOIN_REPLY):
        pass
    else:  # pragma: no cover - enum is closed
        raise ValueError(f"unknown kind {k}")
    if len(msg.gossip) > 255:
        raise ValueError("gossip section too large")
    out.append(bytes([len(msg.gossip)]))
    for u in msg.gossip:
        out.append(_UPD.pack(u.member, int(u.status), u.incarnation,
                               u.origin))
        out.append(_pack_addr(u.addr))
    return b"".join(out)


class DecodeError(ValueError):
    pass


def peek_kind(buf: bytes) -> MsgKind:
    """Header-only kind extraction (magic/version validated, body not
    parsed) — for hot paths that route on kind without needing the
    gossip payload (e.g. the bridge hub's ACK liveness credit)."""
    try:
        magic, version, kind, _ = _HDR.unpack_from(buf, 0)
    except struct.error as e:
        raise DecodeError(str(e)) from e
    if magic != MAGIC:
        raise DecodeError("bad magic")
    if version != VERSION:
        raise DecodeError(f"unsupported version {version}")
    try:
        return MsgKind(kind)
    except ValueError as e:
        raise DecodeError(str(e)) from e


def decode(buf: bytes) -> Message:
    try:
        magic, version, kind, sender = _HDR.unpack_from(buf, 0)
        if magic != MAGIC:
            raise DecodeError("bad magic")
        if version != VERSION:
            raise DecodeError(f"unsupported version {version}")
        kind = MsgKind(kind)
        off = _HDR.size
        probe_seq = target = on_behalf = 0
        target_addr: Address = ("", 0)
        if kind in (MsgKind.PING, MsgKind.ACK, MsgKind.NACK):
            probe_seq, on_behalf = struct.unpack_from("!II", buf, off)
            off += 8
        elif kind == MsgKind.PING_REQ:
            probe_seq, target = struct.unpack_from("!II", buf, off)
            off += 8
            target_addr, off = _unpack_addr(buf, off)
        count = buf[off]
        off += 1
        gossip = []
        for _ in range(count):
            member, status, inc, origin = _UPD.unpack_from(buf, off)
            off += _UPD.size
            addr, off = _unpack_addr(buf, off)
            gossip.append(WireUpdate(member, Status(status), inc, addr,
                                     origin))
        return Message(kind=kind, sender=sender, probe_seq=probe_seq,
                       target=target, target_addr=target_addr,
                       on_behalf=on_behalf, gossip=tuple(gossip))
    except DecodeError:
        raise
    except (struct.error, IndexError, UnicodeDecodeError, ValueError) as e:
        raise DecodeError(f"malformed datagram: {e}") from e
