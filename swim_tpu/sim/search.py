"""Coverage-guided adversarial search over fault-program parameters.

The scenario library's calibration points were found by hand (the 30%
rack-loss boundary in bench_results/scenario_rack_outage.json, the
0.43 gray level, the 50% flap storm).  This module automates that
boundary mapping — Lifeguard's evaluation methodology (sweep the fault
severity until the detector breaks, report the frontier) driven by the
batched scenario pipeline: every generation compiles P mutated
candidates at one shared segment capacity and advances them all in ONE
vmapped device run (`sim/experiments._run_study_batch`), so the search
pays one compile and then P scenarios per step forever after.

Two phases:

  * `explore` — novelty-guided mutation over (kind, level, window,
    duty cycle, domain, crash co-injection).  Each lane reduces to a
    coarse behavior signature (log-bucketed false-dead peak/final,
    suspect volume, undetected-crash count, incarnation ceiling); the
    archive keeps the first candidate per signature and parents are
    drawn from it, so the population is pushed toward behaviors not
    yet seen rather than re-sampling the basin it started in.
    Violation detectors run per lane: a sticky false death under the
    full Lifeguard config (the detector killed a healthy node), a
    false-dead storm (cascade), and an undetected crash (a node that
    crash-stopped mid-run and never reached a DEAD view).
  * `refine_boundary` — batched bisection along one parameter: each
    generation evaluates a P-point grid spanning the current bracket
    and tightens it to [max clean, min violating], so the frontier
    narrows by ~P× per device step instead of 2×.

Everything is deterministic given `seed` (np.random.default_rng for
mutation, fixed engine keys), and the report is a byte-stable JSON
artifact (sorted keys, no timestamps) in the verdict family — the
machine-found boundary lands in the scenario library as an ordinary
spec with a committed passing verdict (`flap_boundary`).

CLI: ``swim-tpu scenario search [--generations G] [--pop P] [--out F]``.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Mapping

import numpy as np

from swim_tpu.config import SwimConfig
from swim_tpu.sim import faults, scenario

NEVER = 2**31 - 1

# The searched geometry: the library's flap/gray anchor (n=256, 8 racks,
# full Lifeguard stack on the packed rotor wire).  Small enough that a
# 16-lane generation steps in ~a second on the CPU host, and identical
# to the committed library scenarios so a found boundary transplants
# into the library verbatim.
SEARCH_N = 256
SEARCH_PERIODS = 48
SEARCH_DOMAINS = "blocks:8"
SEARCH_CONFIG: Mapping[str, Any] = {
    "ring_probe": "rotor", "ring_scalar_wire": "packed",
    "ring_sel_scope": "period", "lifeguard": True, "buddy": True,
}
# one lane-event slot + one optional crash co-injection (crashes fold
# into the base plan, so capacity 1 covers every candidate) — fixed so
# the whole search shares a single compiled step
SEARCH_CAPACITY = 1


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One point in the fault-parameter space (JSON-able)."""

    kind: str = "link_loss"     # link_loss | gray | send_loss | recv_loss
    level: float = 0.2          # loss probability of the lane event
    start: int = 8              # window first period (inclusive)
    end: int = 40               # window last period (exclusive)
    period: int = 0             # flap cycle (0 = always on in window)
    on: int = 0                 # on-duty periods per cycle
    domain: int = 3             # target rack
    crash_domain: int = -1      # -1 = none; else that rack crash-stops
    crash_start: int = 12

    def events(self) -> tuple:
        ev: list[dict] = [{
            "kind": self.kind, "start": self.start, "end": self.end,
            "level": round(float(self.level), 6),
            "domain": self.domain,
            "period": self.period, "on": self.on,
        }]
        if self.crash_domain >= 0:
            ev.append({"kind": "crash", "domain": self.crash_domain,
                       "start": self.crash_start})
        return tuple(ev)

    def to_scenario(self, name: str, seed: int = 0,
                    **overrides) -> scenario.Scenario:
        return scenario.Scenario(
            name=name, n=SEARCH_N, periods=SEARCH_PERIODS, engine="ring",
            seed=seed, config=dict(SEARCH_CONFIG),
            domains=SEARCH_DOMAINS, capacity=SEARCH_CAPACITY,
            events=self.events(), **overrides)

    def spec_dict(self) -> dict:
        return dataclasses.asdict(self)


def _compile(cand: Candidate, seed: int) -> faults.FaultProgram:
    return scenario.compile_program(cand.to_scenario("search", seed=seed))


def run_generation(cands: list[Candidate], seed: int = 0):
    """One vmapped device step over a candidate population.

    Returns the batched StudyResult; all candidates share the search
    geometry/config, so the whole population is one batch group."""
    import jax

    from swim_tpu.sim import experiments

    cfg = SwimConfig(n_nodes=SEARCH_N, telemetry=True, **SEARCH_CONFIG)
    progs = [_compile(c, seed) for c in cands]
    keys = [jax.random.key(seed) for _ in cands]
    return experiments._run_study_batch(
        cfg, progs, keys, SEARCH_PERIODS, "ring",
        capacity=SEARCH_CAPACITY)


def lane_signature(res, cand: Candidate) -> dict:
    """Coarse behavior signature + raw observables for one lane."""
    fd = np.asarray(res.series.false_dead_views)
    susp = np.asarray(res.series.suspect_views)
    inc = np.asarray(res.series.max_incarnation)
    first_dead = np.asarray(res.track.first_dead_view)
    # undetected crashes: crashed early enough that detection is due
    # (>= 8 periods of margin), yet no DEAD view ever formed
    undetected = 0
    crashed_due = 0
    if cand.crash_domain >= 0:
        dom = scenario.domain_labels(SEARCH_N, SEARCH_DOMAINS)
        members = np.nonzero(dom == cand.crash_domain)[0]
        if cand.crash_start <= SEARCH_PERIODS - 8:
            crashed_due = int(members.size)
            undetected = int((first_dead[members] == NEVER).sum())

    def bucket(v: int) -> int:
        return 0 if v <= 0 else int(math.log10(v)) + 1

    obs = {
        "false_dead_peak": int(fd.max()),
        "false_dead_final": int(fd[-1]),
        "suspect_peak": int(susp.max()),
        "max_incarnation": int(inc.max()),
        "crashed_due": crashed_due,
        "undetected_crashes": undetected,
    }
    sig = (bucket(obs["false_dead_peak"]), bucket(obs["false_dead_final"]),
           bucket(obs["suspect_peak"]), bucket(obs["max_incarnation"]),
           1 if undetected else 0)
    return {"signature": sig, **obs}


def violations_of(sig: dict, cand: Candidate) -> list[str]:
    """Which detector-breaking behaviors this lane exhibits.

    All candidates run the FULL Lifeguard stack, so a false death here
    is the detector failing, not an ablation arm failing on purpose."""
    out = []
    if sig["false_dead_final"] > 0:
        out.append("sticky_false_dead")
    if sig["false_dead_peak"] >= 100:
        out.append("false_dead_storm")
    if sig["undetected_crashes"] > 0:
        out.append("undetected_crash")
    return out


def _mutate(cand: Candidate, rng: np.random.Generator) -> Candidate:
    """Perturb one or two parameters (bounded to the valid spec box)."""
    d = dataclasses.asdict(cand)
    for _ in range(int(rng.integers(1, 3))):
        which = rng.choice(["level", "window", "duty", "domain", "kind",
                            "crash"])
        if which == "level":
            d["level"] = float(np.clip(
                d["level"] + rng.normal(0, 0.12), 0.02, 0.98))
        elif which == "window":
            d["start"] = int(rng.integers(2, 20))
            d["end"] = int(d["start"]
                           + rng.integers(6, SEARCH_PERIODS - d["start"]))
        elif which == "duty":
            if rng.random() < 0.3:
                d["period"], d["on"] = 0, 0
            else:
                d["period"] = int(rng.integers(2, 9))
                d["on"] = int(rng.integers(1, d["period"] + 1))
        elif which == "domain":
            d["domain"] = int(rng.integers(0, 8))
        elif which == "kind":
            d["kind"] = str(rng.choice(
                ["link_loss", "gray", "send_loss", "recv_loss"]))
        elif which == "crash":
            if rng.random() < 0.5:
                d["crash_domain"] = -1
            else:
                d["crash_domain"] = int(rng.integers(0, 8))
                d["crash_start"] = int(rng.integers(4, 30))
        if d["crash_domain"] == d["domain"]:
            d["crash_domain"] = -1   # crashing the faulted rack masks it
    d["end"] = int(min(d["end"], SEARCH_PERIODS))
    return Candidate(**d)


def explore(generations: int = 4, pop: int = 16, seed: int = 0) -> dict:
    """Novelty-guided exploration: returns the archive + violations."""
    rng = np.random.default_rng(seed)
    from swim_tpu.sim import runner

    seedling = Candidate()
    archive: dict[tuple, dict] = {}
    violations: list[dict] = []
    parents = [seedling]
    evaluated = 0
    for gen in range(generations):
        cands = []
        for i in range(pop):
            if i < 2 or not parents:
                base = seedling
            else:
                base = parents[int(rng.integers(0, len(parents)))]
            cands.append(_mutate(base, rng))
        res_b = run_generation(cands, seed=seed)
        fresh = []
        for lane, cand in enumerate(cands):
            sig = lane_signature(runner.lane_result(res_b, lane), cand)
            evaluated += 1
            key = sig["signature"]
            if key not in archive:
                archive[key] = {"candidate": cand.spec_dict(),
                                "generation": gen, **sig,
                                "signature": list(key)}
                fresh.append(cand)
            for v in violations_of(sig, cand):
                violations.append({"violation": v, "generation": gen,
                                   "candidate": cand.spec_dict(), **sig,
                                   "signature": list(key)})
        # novelty guidance: parents are the candidates that just opened
        # new signature cells (fall back to the whole archive when a
        # generation goes dry)
        parents = fresh or [Candidate(**a["candidate"])
                            for a in archive.values()]
    return {
        "generations": generations, "pop": pop, "seed": seed,
        "evaluated": evaluated,
        "archive": sorted(archive.values(),
                          key=lambda a: a["signature"]),
        "violations": violations,
    }


def refine_boundary(template: Candidate,
                    predicate: Callable[[dict], bool] | None = None,
                    lo: float = 0.02, hi: float = 0.98,
                    pop: int = 16, tol: float = 0.005,
                    max_generations: int = 6, seed: int = 0) -> dict:
    """Batched bisection of the `level` frontier for one candidate
    shape: per generation, evaluate a `pop`-point grid spanning the
    bracket and tighten it to [max clean level, min violating level].
    ~pop× narrowing per device step vs 2× for scalar bisection."""
    from swim_tpu.sim import runner

    if predicate is None:
        predicate = lambda sig: sig["false_dead_final"] > 0  # noqa: E731
    history = []
    for gen in range(max_generations):
        levels = list(np.linspace(lo, hi, pop))
        cands = [dataclasses.replace(template, level=float(lv))
                 for lv in levels]
        res_b = run_generation(cands, seed=seed)
        sigs = [lane_signature(runner.lane_result(res_b, lane), c)
                for lane, c in enumerate(cands)]
        viol = [bool(predicate(s)) for s in sigs]
        new_lo, new_hi = lo, hi
        for lv, v in zip(levels, viol):
            if not v and lv > new_lo:
                # highest clean level BELOW the first violation only —
                # a non-monotone pocket must not fold the bracket past
                # a violating level
                if not any(vv and lx < lv for lx, vv in zip(levels, viol)):
                    new_lo = lv
        for lv, v in zip(levels, viol):
            if v:
                new_hi = min(new_hi, lv)
                break
        history.append({"generation": gen, "lo": lo, "hi": hi,
                        "grid": [round(float(lv), 6) for lv in levels],
                        "violating": viol})
        if not any(viol):
            return {"found": False, "lo": lo, "hi": hi,
                    "history": history,
                    "note": "no violation in bracket"}
        lo, hi = new_lo, new_hi
        if hi - lo <= tol:
            break
    return {
        "found": True,
        "clean_level": round(float(lo), 6),
        "violation_level": round(float(hi), 6),
        "width": round(float(hi - lo), 6),
        "template": template.spec_dict(),
        "history": history,
    }


def search(generations: int = 4, pop: int = 16, seed: int = 0,
           out: str | None = None) -> dict:
    """The full driver: explore, then refine the flap false-dead
    frontier (the library's `flap_boundary` scenario is this report's
    committed form).  Deterministic given `seed`; the report is a
    byte-stable JSON artifact when `out` is given."""
    report: dict[str, Any] = {"kind": "scenario_search", "version": 1,
                              "n": SEARCH_N, "periods": SEARCH_PERIODS,
                              "config": dict(SEARCH_CONFIG),
                              "domains": SEARCH_DOMAINS}
    report["explore"] = explore(generations=generations, pop=pop,
                                seed=seed)
    flap = Candidate(kind="link_loss", start=8, end=40, period=6, on=3,
                     domain=3)
    report["boundary"] = refine_boundary(flap, pop=pop, seed=seed)
    if out:
        scenario.write_verdict(report, out)
        report["artifact"] = out
    return report
