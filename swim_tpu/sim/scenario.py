"""Scenario compiler: adversarial fault programs as first-class tensors.

SWIM (DSN 2002) is evaluated under crash-stop and uniform loss;
Lifeguard (HashiCorp 2017) exists because real deployments die of gray
failures — slow-but-alive nodes, flapping and asymmetric links,
correlated rack outages.  This module turns a small declarative spec
(`Scenario`) into a validated, compiled `sim/faults.py` FaultProgram:

  * correlated domain failures — `domains` labels every node with a u8
    failure-domain (rack) id; a `(start, end, domain, kind)` event
    crashes or degrades the whole rack at once (crash events fold into
    `base.crash_step` at compile time: zero runtime residue),
  * asymmetric / flapping links — per-node send/recv loss factors as
    u16 thresholds composing with the engines' integer loss legs
    (`bits >= ceil(loss * 65536)`), with piecewise windows and a
    (period, on) duty cycle so links flap without retracing,
  * gray failures — per-node reply-loss (`kind="gray"`) so a node stays
    alive, keeps gossiping, but misses ack deadlines — the ablation
    separating Lifeguard's LHA/buddy path from vanilla SWIM,
  * message duplication and stale-incarnation replay — real-node-side
    injection (core/transport.py SimNetwork `duplicate`/`replay`); the
    decode path must be idempotent, and the replay-storm scenario
    asserts it.

The compiled program is a traced argument: sweeping levels, windows, or
domains with the same segment CAPACITY reuses one compiled step, exactly
like FaultPlan.  The empty scenario (no events) is bitwise-identical to
`faults.none(n)` on every engine (tests/test_scenario.py pins it).

Every scenario run ends in the observatory: telemetry rows (plus
fault-schedule gauges `gray_nodes` / `flap_active` recomputed from the
compiled program) feed obs/health.py — including the `gray_undetected`
and `flap_false_dead` rules — and a flight-record dump replayed through
`swim-tpu observe --check` semantics (obs/analyze.py error findings).
The result is a diffable verdict artifact under bench_results/
(`swim-tpu scenario run <name>`); docs/SCENARIOS.md documents the spec
grammar, the library table, and the artifact format.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import tempfile
from typing import Any, Mapping, Sequence

import numpy as np

from swim_tpu.config import SwimConfig
from swim_tpu.sim import faults

VERDICT_KIND = "scenario_verdict"
VERDICT_VERSION = 1

ENGINES = ("auto", "dense", "rumor", "ring", "ringshard", "real")

# event keys: required / optional-with-default (validation table)
_EVENT_KEYS = {"kind", "start", "end", "level", "domain", "nodes",
               "period", "on"}

# arm-spec keys: "config" overrides SwimConfig knobs; "gate" opts the
# arm out of the observatory error gate (ablation contrast arms); the
# rest override the scenario's own fault fields for that arm (a loss
# sweep is arms differing only in `loss`)
_ARM_KEYS = {"config", "gate", "loss", "events", "partition", "crashes",
             "seed"}


@dataclasses.dataclass(frozen=True)
class Scenario:
    """Declarative fault-scenario spec (compile() → FaultProgram).

    `events` entries are mappings with keys:
      kind    — "crash" | "send_loss" | "recv_loss" | "link_loss" | "gray"
      start   — first period (inclusive); crash events need only this
      end     — last period (exclusive); required for non-crash kinds
      level   — probability in [0, 1] (non-crash kinds)
      domain  — failure-domain id to target (-1 / absent = every node)
      nodes   — explicit node-id list (crash events, alternative to
                domain)
      period, on — flap duty cycle: active when (t−start) mod period
                < on; period 0 (default) = always active in the window

    `arms` maps arm name → {"config": {SwimConfig overrides},
    "gate": bool, plus optional scenario-field overrides (loss, events,
    partition, crashes, seed)} — a loss sweep is arms differing only in
    `loss`; an ablation's contrast arm sets gate=False to opt out of
    the observatory error gate (its failures are the point).  With
    arms=None a single gated "main" arm runs.

    `study` delegates to sim/experiments.STUDIES[study](**study_kw)
    instead of the engine arms — the existing study machinery under the
    same verdict/observatory wrapper (BASELINE sweeps as scenarios).

    engine="real" runs a core/cluster.py SimCluster with the `real`
    knobs ({seconds, loss, duplicate, replay}) and gates on the
    real-node registry rules.
    """

    name: str
    n: int = 256
    periods: int = 48
    engine: str = "ring"
    seed: int = 0
    config: Mapping[str, Any] = dataclasses.field(default_factory=dict)
    loss: float = 0.0
    domains: Any = None          # None | "blocks:K" | "stripe:K" | seq
    crashes: Mapping[str, Any] | None = None
    partition: Mapping[str, Any] | None = None
    events: Sequence[Mapping[str, Any]] = ()
    capacity: int | None = None
    arms: Mapping[str, Mapping[str, Any]] | None = None
    study: str | None = None
    study_kw: Mapping[str, Any] = dataclasses.field(default_factory=dict)
    real: Mapping[str, Any] | None = None
    expect: Sequence[Mapping[str, Any]] = ()
    allow_rules: Sequence[str] = ()
    artifact: str | None = None
    description: str = ""

    def spec_dict(self) -> dict:
        """JSON-able echo of the spec (embedded in verdict artifacts)."""
        d = dataclasses.asdict(self)
        if d["domains"] is not None and not isinstance(d["domains"], str):
            d["domains"] = np.asarray(d["domains"]).tolist()
        return d


def validate(sc: Scenario) -> None:
    """Reject malformed specs with actionable errors (compile calls
    this; the CLI calls it on `scenario show` too)."""
    if sc.engine not in ENGINES:
        raise ValueError(f"unknown engine {sc.engine!r}; one of {ENGINES}")
    if sc.n < 2:
        raise ValueError("scenario needs n >= 2")
    if sc.periods < 1:
        raise ValueError("scenario needs periods >= 1")
    if sc.study is not None and sc.study not in _study_names():
        raise ValueError(
            f"unknown study {sc.study!r}; one of {sorted(_study_names())}")
    dom = domain_labels(sc.n, sc.domains)
    n_domains = int(dom.max()) + 1
    for i, ev in enumerate(sc.events):
        unknown = set(ev) - _EVENT_KEYS
        if unknown:
            raise ValueError(
                f"events[{i}]: unknown key(s) {sorted(unknown)}")
        kind = ev.get("kind")
        if kind != "crash" and kind not in faults.SEG_KINDS:
            raise ValueError(
                f"events[{i}]: unknown kind {kind!r}; one of "
                f"{['crash'] + sorted(faults.SEG_KINDS)}")
        if "start" not in ev:
            raise ValueError(f"events[{i}]: missing 'start'")
        if kind != "crash":
            if "end" not in ev or ev["end"] <= ev["start"]:
                raise ValueError(
                    f"events[{i}]: needs end > start (half-open window)")
            level = ev.get("level")
            if level is None or not 0.0 <= level <= 1.0:
                raise ValueError(
                    f"events[{i}]: needs level in [0, 1], got {level!r}")
            period = ev.get("period", 0)
            on = ev.get("on", 0)
            if period > 0 and not 0 < on <= period:
                raise ValueError(
                    f"events[{i}]: flap duty needs 0 < on <= period "
                    f"({on}/{period})")
        d = ev.get("domain", -1)
        if d >= 0 and d >= n_domains:
            raise ValueError(
                f"events[{i}]: domain {d} out of range (the spec labels "
                f"{n_domains} domain(s))")
        if kind == "crash" and "nodes" in ev and "domain" in ev:
            raise ValueError(
                f"events[{i}]: crash targets either 'domain' or 'nodes'")
    if sc.arms is not None and sc.study is None and sc.engine != "real":
        for arm, spec in sc.arms.items():
            unknown = set(spec) - _ARM_KEYS
            if unknown:
                raise ValueError(
                    f"arm {arm!r}: unknown key(s) {sorted(unknown)}; "
                    f"one of {sorted(_ARM_KEYS)}")


def _study_names() -> set:
    from swim_tpu.sim import experiments

    return set(experiments.STUDIES)


def domain_labels(n: int, spec) -> np.ndarray:
    """u8[n] failure-domain labels from the spec's `domains` field.

    "blocks:K" — K contiguous racks (node i in rack i // ceil(n/K));
    "stripe:K" — round-robin striping (node i in rack i % K);
    a sequence — explicit labels (validated to [0, 255]).
    """
    if spec is None:
        return np.zeros((n,), np.uint8)
    if isinstance(spec, str):
        form, _, arg = spec.partition(":")
        try:
            k = int(arg)
        except ValueError:
            raise ValueError(f"bad domain spec {spec!r}") from None
        if not 1 <= k <= 256:
            raise ValueError(
                f"domain count must be in [1, 256] (u8 labels): {k}")
        ids = np.arange(n)
        if form == "blocks":
            labels = ids // -(-n // k)          # ceil-div block size
        elif form == "stripe":
            labels = ids % k
        else:
            raise ValueError(
                f"unknown domain form {form!r}; 'blocks:K' or 'stripe:K'")
        return labels.astype(np.uint8)
    arr = np.asarray(spec)
    if arr.shape != (n,):
        raise ValueError(
            f"explicit domain labels must have shape ({n},): {arr.shape}")
    if arr.size and (arr.min() < 0 or arr.max() > 255):
        raise ValueError("domain labels must fit u8 ([0, 255])")
    return arr.astype(np.uint8)


def compile_program(sc: Scenario) -> faults.FaultProgram:
    """Spec → validated, compiled tensor fault program.

    Crash events (including whole-domain crashes) fold into
    base.crash_step here — no runtime residue; everything else becomes
    padded segment slots (pad capacity with `capacity` so a library of
    specs with different event counts shares one trace)."""
    import jax

    validate(sc)
    n = sc.n
    dom = domain_labels(n, sc.domains)
    plan = faults.none(n)
    if sc.loss:
        plan = faults.with_loss(plan, float(sc.loss))
    if sc.crashes:
        c = dict(sc.crashes)
        plan = faults.with_random_crashes(
            plan, jax.random.key(sc.seed + 1), float(c["fraction"]),
            int(c.get("start", 2)),
            int(c.get("end", max(3, sc.periods // 2))))
    if sc.partition:
        p = dict(sc.partition)
        groups = p.get("groups")
        groups = faults.halves(n) if groups is None else groups
        plan = faults.with_partition(plan, groups, int(p["start"]),
                                     int(p["end"]))
    lane_events = []
    for ev in sc.events:
        if ev["kind"] == "crash":
            if "nodes" in ev:
                ids = np.asarray(ev["nodes"], np.int32)
            elif ev.get("domain", -1) >= 0:
                ids = np.nonzero(dom == ev["domain"])[0].astype(np.int32)
            else:
                ids = np.arange(n, dtype=np.int32)
            plan = faults.with_crashes(plan, ids, int(ev["start"]))
        else:
            lane_events.append(ev)
    cap = len(lane_events) if sc.capacity is None else int(sc.capacity)
    if len(lane_events) > cap:
        raise ValueError(
            f"{len(lane_events)} lane events exceed capacity {cap}")
    prog = faults.as_program(plan, domain_id=dom, capacity=cap)
    for i, ev in enumerate(lane_events):
        prog = faults.with_segment(
            prog, i, start=int(ev["start"]), end=int(ev["end"]),
            kind=ev["kind"], level=float(ev["level"]),
            domain=int(ev.get("domain", -1)),
            period=int(ev.get("period", 0)), on=int(ev.get("on", 0)))
    return prog


def fault_gauges(sc: Scenario) -> dict[str, np.ndarray]:
    """Host-side per-period fault-schedule gauges recomputed from the
    spec: `gray_nodes` (nodes with an active gray lane) and
    `flap_active` (nodes covered by a flapping segment's window) — the
    aux telemetry rows feeding obs/health.py's `gray_undetected` /
    `flap_false_dead` rules."""
    n, t_max = sc.n, sc.periods
    dom = domain_labels(n, sc.domains).astype(np.int32)
    gray = np.zeros((t_max,), np.int64)
    flap = np.zeros((t_max,), np.int64)
    for ev in sc.events:
        kind = ev.get("kind")
        if kind not in faults.SEG_KINDS:
            continue
        d = ev.get("domain", -1)
        cnt = int(n if d < 0 else (dom == d).sum())
        period = int(ev.get("period", 0))
        on = int(ev.get("on", 0))
        for t in range(max(0, int(ev["start"])),
                       min(t_max, int(ev["end"]))):
            duty = (period == 0
                    or ((t - int(ev["start"])) % period) < on)
            if kind == "gray" and duty:
                gray[t] += cnt
            if period > 0:
                flap[t] += cnt
    return {"gray_nodes": gray, "flap_active": flap}


# --------------------------------------------------------------- execution


def _arm_defs(sc: Scenario) -> list[tuple[str, dict, bool]]:
    if sc.arms is None:
        return [("main", {}, True)]
    return [(name, dict(spec), bool(spec.get("gate", True)))
            for name, spec in sc.arms.items()]


def _arm_scenario(sc: Scenario, spec: dict) -> Scenario:
    """Apply an arm's scenario-field overrides (loss / events /
    partition / crashes / seed) — the arm keys beyond config/gate."""
    repl = {k: spec[k] for k in
            ("loss", "events", "partition", "crashes", "seed")
            if k in spec}
    return dataclasses.replace(sc, **repl) if repl else sc


def _arm_prepare(sc: Scenario, spec: dict) -> tuple:
    """Arm spec → (arm-overridden scenario, SwimConfig, compiled
    program) — the static half of an arm run, shared by the serial and
    batched paths."""
    sc = _arm_scenario(sc, spec)
    cfg_kw = {**dict(sc.config), **dict(spec.get("config", {}))}
    cfg_kw.setdefault("telemetry", True)
    cfg = SwimConfig(n_nodes=sc.n, **cfg_kw)
    return sc, cfg, compile_program(sc)


def _run_engine_arm(sc: Scenario, arm: str, spec: dict,
                    out_dir: str) -> dict:
    """One engine arm: compile, run the study scan with telemetry,
    feed the health monitor + flight recorder (with the fault-schedule
    gauges), dump, and replay the dump through the offline analyzer —
    the same path `swim-tpu observe --check` takes."""
    import jax

    from swim_tpu.sim import experiments

    sc, cfg, prog = _arm_prepare(sc, spec)
    engine = experiments.pick_engine(sc.n, sc.engine)
    res = experiments._run_study(cfg, prog, jax.random.key(sc.seed),
                                 sc.periods, engine)
    return _arm_digest(sc, arm, engine, cfg, prog, res, out_dir)


def _run_engine_arms_batched(sc: Scenario, out_dir: str) -> dict:
    """All engine arms of one scenario as vmapped fleets: arms sharing
    a SwimConfig (config overrides are the only static divergence —
    loss/events/partition/crashes/seed are data) group into ONE
    batched device run (`experiments._run_study_batch`), each arm's
    program padded to the group capacity; lanes de-interleave through
    the SAME `_arm_digest` the serial path uses, so per-arm dicts,
    dumps, and verdicts are bitwise-identical to serial runs.

    Pricing note: the ICI bill is traced from the arm's OWN compiled
    program (pre-padding), never the padded batch copy — a padded lane
    must not sprout keys its serial twin lacks."""
    import jax

    from swim_tpu.sim import experiments, runner

    engine = experiments.pick_engine(sc.n, sc.engine)
    prepared = [(arm, *_arm_prepare(sc, spec))
                for arm, spec, _gate in _arm_defs(sc)]
    groups: dict[Any, list[int]] = {}
    for i, (_arm, _sc_a, cfg, _prog) in enumerate(prepared):
        groups.setdefault(cfg, []).append(i)
    arms_out: dict[str, dict] = {}
    for cfg, idxs in groups.items():
        progs = [prepared[i][3] for i in idxs]
        keys = [jax.random.key(prepared[i][1].seed) for i in idxs]
        res_b = experiments._run_study_batch(cfg, progs, keys,
                                             sc.periods, engine)
        for lane, i in enumerate(idxs):
            arm, sc_a, cfg_i, prog = prepared[i]
            res = runner.lane_result(res_b, lane)
            arms_out[arm] = _arm_digest(sc_a, arm, engine, cfg_i, prog,
                                        res, out_dir)
    return {arm: arms_out[arm] for arm, _, _ in _arm_defs(sc)}


def _arm_digest(sc: Scenario, arm: str, engine: str, cfg: SwimConfig,
                prog: faults.FaultProgram, res, out_dir: str) -> dict:
    """Post-run half of an arm: metric digests, ICI pricing, health
    monitor + flight-record dump, offline-analyzer replay.  `res` is
    either a serial StudyResult or one de-interleaved lane of a batch —
    identical inputs produce identical (byte-stable) outputs."""
    from swim_tpu.obs import analyze
    from swim_tpu.obs.health import HealthMonitor
    from swim_tpu.obs.recorder import FlightRecorder
    from swim_tpu.sim import runner
    from swim_tpu.utils import metrics

    series = res.series
    out: dict[str, Any] = {"engine": engine}
    out.update(runner.detection_summary(res, prog, sc.periods))
    out.update(metrics.series_digest(series))
    out["false_dead_views_final"] = int(
        np.asarray(series.false_dead_views)[-1])
    out["false_dead_views_peak"] = int(
        np.asarray(series.false_dead_views).max())
    out["max_incarnation"] = int(np.asarray(series.max_incarnation).max())
    if engine in ("rumor", "shard", "ring", "ringshard"):
        out["overflow"] = int(res.state.overflow)

    if (cfg.ring_scalar_wire == "packed"
            and int(prog.seg_kind.shape[0]) > 0):
        # price the lane on the packed scalar wire: the named
        # roll_link_thr term in the per-chip ICI tally (obs/ici.py) —
        # trace-only (eval_shape), costs nothing to embed
        from swim_tpu.obs import ici

        bill = ici.trace_ici_bytes(cfg, d=8, plan=prog)
        out["ici"] = {
            "per_chip_bytes_per_period":
                bill["per_chip_bytes_per_period"],
            "roll_link_thr_bytes":
                bill["breakdown"].get("roll_link_thr", 0),
        }

    monitor = HealthMonitor(window=min(16, max(2, sc.periods)),
                            n_nodes=sc.n)
    rec = FlightRecorder(cfg=cfg, capacity=sc.periods, monitor=monitor)
    aux = {"false_dead_views": np.asarray(series.false_dead_views)}
    aux.update(fault_gauges(sc))
    rec.record_stacked(res.telemetry, aux=aux)
    dump = os.path.join(out_dir, f"scenario_{sc.name}_{arm}.jsonl")
    rec.dump(dump, reason="scenario",
             extra={"scenario": sc.name, "arm": arm})
    report = analyze.analyze(dump)
    errors = analyze.error_findings(report)
    out["observatory"] = {
        "dump": dump,
        "health": monitor.summary(),
        "error_findings": errors,
        "waived_rules": sorted(set(sc.allow_rules)),
    }
    return out


def _run_real_arm(sc: Scenario, out_dir: str) -> dict:
    """Real-node arm: a core/cluster.py SimCluster under the scenario's
    adversarial delivery (loss / duplication / stale replay), gated on
    the real-node registry health rules."""
    from swim_tpu.core.cluster import SimCluster
    from swim_tpu.obs.health import HealthMonitor
    from swim_tpu.types import Status

    rk = dict(sc.real or {})
    cfg = SwimConfig(n_nodes=sc.n, **dict(sc.config))
    cluster = SimCluster(
        cfg, seed=sc.seed, loss=float(rk.get("loss", 0.0)),
        duplicate=float(rk.get("duplicate", 0.0)),
        replay=float(rk.get("replay", 0.0)))
    cluster.start()
    cluster.run(float(rk.get("seconds", 12.0)))
    net = cluster.network
    totals: dict[str, int] = {}
    for node in cluster.nodes:
        for name, counter in node.registry.counters.items():
            totals[name] = totals.get(name, 0) + int(counter.value)
    # every node is alive for the whole run: any DEAD view of a peer at
    # the end is a false-dead view
    false_dead = sum(
        1 for i, node in enumerate(cluster.nodes)
        for peer in range(sc.n)
        if peer != i
        and (op := node.members.opinion(peer)) is not None
        and op.status is Status.DEAD)
    monitor = HealthMonitor(n_nodes=sc.n)
    findings = monitor.check_registries(
        [node.registry for node in cluster.nodes])
    errors = [f.to_dict() for f in findings if f.severity == "error"]
    return {
        "engine": "real",
        "seconds": float(rk.get("seconds", 12.0)),
        "network": {"sent": net.sent, "delivered": net.delivered,
                    "duplicated": net.duplicated,
                    "replayed": net.replayed},
        "counters": totals,
        "false_dead_views_final": false_dead,
        "observatory": {
            "health": monitor.summary(),
            "error_findings": errors,
            "waived_rules": sorted(set(sc.allow_rules)),
        },
    }


def _run_study_mode(sc: Scenario, out_dir: str) -> dict:
    """Delegate to sim/experiments.STUDIES under the verdict wrapper.
    When the study kwargs name a flight_record path the dump is
    replayed through the offline analyzer for the observatory gate."""
    from swim_tpu.obs import analyze
    from swim_tpu.sim import experiments

    kw = dict(sc.study_kw)
    if "flight_record" in kw and kw["flight_record"] is not None:
        kw["flight_record"] = os.path.join(out_dir, kw["flight_record"])
    result = experiments.STUDIES[sc.study](**kw)
    out: dict[str, Any] = {"engine": result.get("engine", "study"),
                           "result": result}
    dump = result.get("flight_record")
    if dump and os.path.exists(dump):
        report = analyze.analyze(dump)
        out["observatory"] = {
            "dump": dump,
            "error_findings": analyze.error_findings(report),
            "waived_rules": sorted(set(sc.allow_rules)),
        }
    else:
        out["observatory"] = None
    return out


# ------------------------------------------------------------------ checks


def _geometric_law(result: dict, dump: str | None) -> dict | None:
    """First-detection-law statistics from a detection study's dump
    header (the full per-crash milestone lists live there — the
    analyzer's CDF is subsampled, unusable for KS)."""
    if not dump or not os.path.exists(dump):
        return None
    from swim_tpu.obs import analyze as _a

    header = _a.read_jsonl(dump)[0]
    study = header.get("study") or {}
    crash = np.asarray(study.get("crash_step", []), np.int64)
    first = np.asarray(study.get("first_suspect", []), np.int64)
    nn = study.get("n") or result.get("n")
    if crash.size == 0 or first.size == 0 or not nn:
        return None
    ok = first != np.int64(2**31 - 1)
    lat = (first[ok] + 1 - crash[ok]).astype(np.float64)
    m = int(lat.size)
    if m == 0:
        return None
    p = 1.0 - (1.0 - 1.0 / (nn - 1)) ** (nn - 1)
    mean_exp = 1.0 / p
    var = (1.0 - p) / (p * p)
    mean_obs = float(lat.mean())
    z_obs = (mean_obs - mean_exp) / math.sqrt(var / m)
    # KS against Geometric(p) on support {1, 2, ...}: both CDFs are
    # step functions jumping at the same integers, so the sup is over
    # post-jump values at support points (tests/test_fidelity.py
    # ks_distance_geometric uses the identical convention)
    lat_sorted = np.sort(lat)
    ks = 0.0
    for l in np.unique(lat_sorted):
        f_emp = float((lat_sorted <= l).mean())
        f_geo = 1.0 - (1.0 - p) ** l
        ks = max(ks, abs(f_emp - f_geo))
    return {"samples": m, "p": p, "expected_mean": mean_exp,
            "observed_mean": mean_obs, "z": z_obs,
            "ks_stat": ks, "ks_scaled": ks * math.sqrt(m)}


def _eval_checks(sc: Scenario, arms: dict[str, dict]) -> list[dict]:
    checks: list[dict] = []

    def add(name, ok, **detail):
        checks.append({"check": name,
                       "ok": bool(ok), **detail})

    # mandatory observatory gate: gated arms must be free of
    # error-severity findings outside the spec's waived rules
    waived = set(sc.allow_rules)
    gate_arms = [a for a, _, g in _arm_defs(sc) if g] \
        if (sc.study is None and sc.engine != "real") else list(arms)
    for arm in gate_arms:
        obs = (arms.get(arm) or {}).get("observatory")
        if obs is None:
            add("observe_clean", True, arm=arm, note="no dump to replay")
            continue
        hard = [f for f in obs["error_findings"]
                if f.get("rule") not in waived]
        soft = [f["rule"] for f in obs["error_findings"]
                if f.get("rule") in waived]
        add("observe_clean", not hard, arm=arm,
            errors=[f.get("rule") for f in hard], waived=sorted(set(soft)))

    for spec in sc.expect:
        spec = dict(spec)
        kind = spec.pop("check")
        if kind == "metric_zero":
            arm = spec.get("arm", "main")
            metric = spec.get("metric", "false_dead_views_final")
            v = arms[arm].get(metric)
            add(kind, v == 0, arm=arm, metric=metric, value=v)
        elif kind == "metric_max":
            arm = spec.get("arm", "main")
            metric = spec["metric"]
            v = arms[arm].get(metric)
            add(kind, v is not None and v <= spec["limit"], arm=arm,
                metric=metric, value=v, limit=spec["limit"])
        elif kind == "metric_nonzero":
            arm = spec.get("arm", "main")
            metric = spec["metric"]
            v = arms[arm].get(metric)
            add(kind, bool(v), arm=arm, metric=metric, value=v)
        elif kind == "fewer":
            metric = spec.get("metric", "false_dead_views_peak")
            lo = arms[spec["less"]].get(metric)
            hi = arms[spec["than"]].get(metric)
            add(kind, lo is not None and hi is not None and lo < hi,
                metric=metric, less=spec["less"], than=spec["than"],
                less_value=lo, than_value=hi)
        elif kind == "require_points":
            result = arms.get("study", {}).get("result", {})
            pts = result.get("points", [])
            add(kind, len(pts) >= spec.get("min", 1),
                points=len(pts), min=spec.get("min", 1))
        elif kind == "rule_fired":
            arm = spec.get("arm", "main")
            rule = spec["rule"]
            obs = (arms.get(arm) or {}).get("observatory") or {}
            fired = [f["rule"] for f in
                     obs.get("health", {}).get("findings", [])]
            add(kind, rule in fired, arm=arm, rule=rule, fired=fired)
        elif kind == "lane_charged":
            arm = spec.get("arm", "main")
            bill = arms[arm].get("ici") or {}
            v = bill.get("roll_link_thr_bytes", 0)
            add(kind, v > 0, arm=arm, roll_link_thr_bytes=v)
        elif kind == "detection_law":
            st = arms.get("study", {})
            law = _geometric_law(st.get("result", {}),
                                 (st.get("observatory") or {}).get("dump"))
            if law is None:
                add(kind, False, note="no law samples in dump header")
            else:
                z_lim = float(spec.get("z", 3.0))
                ks_lim = float(spec.get("ks", 1.358))
                band_ok = abs(law["z"]) <= z_lim
                ks_ok = law["ks_scaled"] <= ks_lim
                strict = bool(spec.get("strict", True))
                add(kind, (band_ok and ks_ok) or not strict,
                    band_ok=band_ok, ks_ok=ks_ok, z_limit=z_lim,
                    ks_limit=ks_lim, **law)
        elif kind == "counter_zero":
            arm = spec.get("arm", "real")
            name = spec["counter"]
            v = arms[arm].get("counters", {}).get(name, 0)
            add(kind, v == 0, arm=arm, counter=name, value=v)
        elif kind == "counter_nonzero":
            arm = spec.get("arm", "real")
            name = spec["counter"]
            v = arms[arm].get("counters", {}).get(name, 0)
            add(kind, v > 0, arm=arm, counter=name, value=v)
        elif kind == "network_nonzero":
            arm = spec.get("arm", "real")
            name = spec["field"]
            v = arms[arm].get("network", {}).get(name, 0)
            add(kind, v > 0, arm=arm, field=name, value=v)
        else:
            add(kind, False, note=f"unknown check kind {kind!r}")
    return checks


# --------------------------------------------------------------- verdicts


def write_verdict(verdict: dict, path: str) -> str:
    """Atomic, diffable JSON: sorted keys, indent 1, trailing newline,
    no timestamps — reruns of an unchanged scenario produce an
    identical artifact."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                               prefix=".verdict_")
    try:
        with os.fdopen(fd, "w") as fh:
            # numpy scalars/arrays ride in from study results; make them
            # plain JSON rather than forcing every producer to cast
            json.dump(verdict, fh, sort_keys=True, indent=1,
                      default=lambda o: (o.item() if np.isscalar(o)
                                         or getattr(o, "ndim", 1) == 0
                                         else np.asarray(o).tolist()))
            fh.write("\n")
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    return path


def run(sc: Scenario, out_dir: str = "bench_results",
        batch: bool = False) -> tuple[dict, str]:
    """Execute a scenario end to end and write its verdict artifact.

    Returns (verdict dict, artifact path).  verdict["verdict"] is
    "pass" iff every check (the mandatory observatory gate plus the
    spec's `expect` list) holds.

    `batch=True` runs the engine arms as vmapped fleets (one device
    run per shared SwimConfig) instead of serially — the verdict is
    bitwise-identical either way (study/real modes have no arm fleet
    and ignore the flag)."""
    validate(sc)
    os.makedirs(out_dir, exist_ok=True)
    arms: dict[str, dict] = {}
    if sc.study is not None:
        arms["study"] = _run_study_mode(sc, out_dir)
    elif sc.engine == "real":
        arms["real"] = _run_real_arm(sc, out_dir)
    elif batch:
        arms = _run_engine_arms_batched(sc, out_dir)
    else:
        for arm, spec, _gate in _arm_defs(sc):
            arms[arm] = _run_engine_arm(sc, arm, spec, out_dir)
    checks = _eval_checks(sc, arms)
    verdict = {
        "kind": VERDICT_KIND,
        "version": VERDICT_VERSION,
        "scenario": sc.spec_dict(),
        "arms": arms,
        "checks": checks,
        "verdict": "pass" if all(c["ok"] for c in checks) else "fail",
    }
    path = os.path.join(out_dir,
                        sc.artifact or f"scenario_{sc.name}.json")
    write_verdict(verdict, path)
    return verdict, path


# ---------------------------------------------------------------- library


def _lib() -> dict[str, Scenario]:
    ring_cfg = {"ring_probe": "rotor", "ring_scalar_wire": "packed",
                "ring_sel_scope": "period", "lifeguard": True,
                "buddy": True}
    lean_cfg = {"ring_sel_scope": "period", "suspicion_mult": 2.0,
                "retransmit_mult": 2.0, "k_indirect": 1,
                "ring_window_periods": 3, "ring_view_c": 2}
    return {
        "rack_outage": Scenario(
            name="rack_outage", n=256, periods=40, engine="ring",
            config=ring_cfg, domains="blocks:8",
            events=(
                {"kind": "crash", "domain": 2, "start": 12},
                {"kind": "link_loss", "domain": 5, "start": 8,
                 "end": 24, "level": 0.15},
            ),
            expect=(
                {"check": "metric_zero", "arm": "main",
                 "metric": "false_dead_views_final"},
                {"check": "metric_nonzero", "arm": "main",
                 "metric": "crashed"},
                {"check": "lane_charged", "arm": "main"},
            ),
            description="One rack (32/256 nodes) crash-stops at once "
                        "while another rack degrades to 15% link loss "
                        "— correlated domain failure.  (At 30% "
                        "sustained rack loss Lifeguard starts losing "
                        "nodes: measured, not assumed.)"),
        "flap": Scenario(
            name="flap", n=256, periods=48, engine="ring",
            config=ring_cfg, domains="blocks:8",
            events=(
                {"kind": "link_loss", "domain": 3, "start": 8,
                 "end": 40, "level": 0.2, "period": 6, "on": 3},
            ),
            arms={
                "mild": {},
                "storm": {"gate": False, "events": (
                    {"kind": "link_loss", "domain": 3, "start": 8,
                     "end": 40, "level": 0.5, "period": 6, "on": 3},
                )},
            },
            expect=(
                {"check": "metric_zero", "arm": "mild",
                 "metric": "false_dead_views_final"},
                {"check": "lane_charged", "arm": "mild"},
                {"check": "metric_nonzero", "arm": "storm",
                 "metric": "false_dead_views_peak"},
                {"check": "rule_fired", "arm": "storm",
                 "rule": "flap_false_dead"},
            ),
            description="One rack's links flap on a 3-on/3-off duty "
                        "cycle.  At 20% burst loss Lifeguard rides it "
                        "out clean (gated arm); at 50% the suspicion "
                        "volume saturates the piggyback budget, "
                        "refutations drop, and sticky DEAD cascades — "
                        "the ungated storm arm pins that regime and "
                        "proves the flap_false_dead health rule "
                        "fires."),
        "flap_boundary": Scenario(
            name="flap_boundary", n=256, periods=48, engine="ring",
            config=ring_cfg, domains="blocks:8",
            events=(
                {"kind": "link_loss", "domain": 3, "start": 8,
                 "end": 40, "level": 0.261209, "period": 6, "on": 3},
            ),
            arms={
                "edge_clean": {},
                "edge_storm": {"gate": False, "events": (
                    {"kind": "link_loss", "domain": 3, "start": 8,
                     "end": 40, "level": 0.261493, "period": 6,
                     "on": 3},
                )},
            },
            expect=(
                {"check": "metric_zero", "arm": "edge_clean",
                 "metric": "false_dead_views_final"},
                {"check": "lane_charged", "arm": "edge_clean"},
                {"check": "metric_nonzero", "arm": "edge_storm",
                 "metric": "false_dead_views_final"},
            ),
            description="Machine-found sticky-false-dead frontier of "
                        "the flap duty cycle (coverage-guided search, "
                        "seed 0: sim/search.py refine_boundary over "
                        "the 3-on/3-off link-loss template).  At burst "
                        "loss 0.261209 Lifeguard still converges to "
                        "zero false-dead views; 0.000284 higher, at "
                        "0.261493, refutations stop landing inside "
                        "the flap window and DEAD views stick past "
                        "recovery.  Pins the measured cliff between "
                        "the hand-picked flap anchors (0.2 clean / "
                        "0.5 storm)."),
        "gray_10pct": Scenario(
            name="gray_10pct", n=256, periods=48, engine="ring",
            config=ring_cfg, domains="blocks:10",
            events=(
                {"kind": "gray", "domain": 1, "start": 6, "end": 42,
                 "level": 0.43},
            ),
            arms={
                "lha": {"config": {}, "gate": True},
                "vanilla": {"config": {"lifeguard": False,
                                       "buddy": False},
                            "gate": False},
            },
            expect=(
                {"check": "fewer", "less": "lha", "than": "vanilla",
                 "metric": "false_dead_views_peak"},
                {"check": "metric_nonzero", "arm": "vanilla",
                 "metric": "false_dead_views_peak"},
                {"check": "metric_zero", "arm": "lha",
                 "metric": "false_dead_views_final"},
            ),
            description="~10% of nodes go gray (alive, gossiping, 43% "
                        "of their acks lost).  The LHA/buddy arm must "
                        "show strictly fewer false-dead views than "
                        "vanilla SWIM — Lifeguard's headline claim.  "
                        "(Calibrated across both threefry streams: at "
                        "this severity LHA holds zero false deaths "
                        "while vanilla false-kills 500-1000 views; by "
                        "~0.5 both degrade, at <=0.4 vanilla largely "
                        "survives too and the contrast shrinks.)"),
        "replay_storm": Scenario(
            name="replay_storm", n=16, engine="real",
            config={"k_indirect": 2},
            real={"seconds": 12.0, "loss": 0.05, "duplicate": 0.3,
                  "replay": 0.3},
            expect=(
                {"check": "counter_zero", "arm": "real",
                 "counter": "decode_errors"},
                {"check": "metric_zero", "arm": "real",
                 "metric": "false_dead_views_final"},
                {"check": "network_nonzero", "arm": "real",
                 "field": "duplicated"},
                {"check": "network_nonzero", "arm": "real",
                 "field": "replayed"},
            ),
            description="Real-node cluster under 30% duplication and "
                        "30% stale-datagram replay: the decode path "
                        "must be idempotent (no decode errors, no "
                        "false deaths)."),
        "baseline_config3": Scenario(
            name="baseline_config3", n=100_000, periods=100,
            engine="rumor",
            partition={"start": 33, "end": 66},
            arms={
                "loss_000": {"loss": 0.0},
                "loss_010": {"loss": 0.1},
                "loss_020": {"loss": 0.2},
                "loss_030": {"loss": 0.3},
            },
            allow_rules=("false_dead_views", "probe_failure_burst",
                         "stalled_dissemination", "overflow_growth",
                         "saturation_spike"),
            expect=(
                {"check": "metric_nonzero", "arm": "loss_030",
                 "metric": "suspect_views_peak"},
                {"check": "metric_nonzero", "arm": "loss_000",
                 "metric": "false_dead_views_peak"},
            ),
            artifact="study_fp_100k_scenario.json",
            description="BASELINE config 3 at spec (VERDICT r6 #3): "
                        "n=100,000, losses through 0.30, mid-run 2-way "
                        "partition — fp_sweep as four scenario arms "
                        "under full telemetry + health gating.  The "
                        "partition makes false-dead views and probe "
                        "bursts EXPECTED (DEAD is sticky; re-join is "
                        "the recovery path), so those rules are "
                        "explicitly waived, not silently ignored."),
        "lean_fidelity": Scenario(
            name="lean_fidelity", n=4096, periods=24, engine="ring",
            study="detection",
            study_kw={"n": 4096, "crash_fraction": 0.02,
                      "periods": 24, "engine": "ring",
                      "telemetry": True,
                      "flight_record": "scenario_lean_fidelity.jsonl",
                      **lean_cfg},
            expect=(
                {"check": "detection_law", "z": 3.0, "ks": 1.358,
                 "strict": True},
            ),
            allow_rules=("overflow_growth",),
            artifact="scenario_lean_fidelity.json",
            description="Lean-geometry fidelity certificate (VERDICT "
                        "r6 #4): the WW=6/RW=56/C=2/k=1/lambda=2 "
                        "anchor must satisfy the first-detection "
                        "geometric law (CLT band + KS) on the "
                        "law-preserving pull probe.  Calibrated "
                        "SUBCRITICAL (crash density 2% over 24 "
                        "periods): at 4% over 100 periods the piggyback "
                        "queue saturates and BOTH lean and default "
                        "geometry deviate from the law (measured mean "
                        "3.6 resp. 2.4 vs 1.58) — the law's "
                        "precondition, not the lean geometry, is what "
                        "breaks.  Residual overflow (~2 updates) is "
                        "waived, measured, and embedded in the "
                        "artifact."),
    }


LIBRARY: dict[str, Scenario] = _lib()


def get(name: str) -> Scenario:
    """Library lookup; accepts hyphenated aliases (rack-outage)."""
    key = name.replace("-", "_")
    if key not in LIBRARY:
        raise KeyError(
            f"unknown scenario {name!r}; one of {sorted(LIBRARY)}")
    return LIBRARY[key]
