"""The BASELINE.md studies (configs 2–5) as callable experiments.

Each function returns a JSON-able dict and scales from laptop CPU sizes to
the full TPU-mesh targets purely by its `n` argument:

  * `detection_study`     — config 2: N-node sim, random crash-stop
    injection → first-detection-time distribution (the SWIM paper's
    e/(e−1)-periods curve).
  * `fp_sweep`            — config 3: packet loss (+ optional 2-way
    partition) sweep → false-positive rates.
  * `suspicion_sweep`     — config 4: suspicion-multiplier λ sweep →
    detection latency vs false positives trade-off.
  * `lifeguard_ablation`  — config 5: Lifeguard on/off under loss+crash.

Engine selection: the exact dense engine up to `DENSE_MAX` nodes, the
O(R·N) rumor engine above (BASELINE's 100k/1M configs). All on-device work
runs under one jitted lax.scan per (config, periods); only O(periods)
scalars and O(N) milestone vectors reach the host.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import numpy as np

from swim_tpu.config import SwimConfig
from swim_tpu.models import dense, rumor
from swim_tpu.parallel import mesh as pmesh
from swim_tpu.sim import faults, runner
from swim_tpu.utils import metrics

DENSE_MAX = 8192

# detection_study(stream="auto") switches the ring engines to the
# streaming O(crashes) study driver at and above this N: below it the
# stacked [periods, N] track is cheap and keeps the exact historical
# code path; above it the stacked track is what broke the one-chip
# memory wall (bench_results/study_detection_16m_oom.json).  Both paths
# are bitwise-identical on milestones and series (tests/test_memwall.py).
STREAM_AUTO_NODES = 2_000_000


def pick_engine(n: int, engine: str = "auto") -> str:
    if engine != "auto":
        return engine
    return "dense" if n <= DENSE_MAX else "rumor"


@functools.lru_cache(maxsize=16)
def _mapped_step(cfg: SwimConfig, mesh, program: bool = False):
    """Identity-stable sharded step per (cfg, mesh, program-plan flag).

    `run_study_ring` is jitted with `step_fn` as a STATIC argument, so
    its compile cache is keyed on the function object's identity — a
    fresh `ring_shard.mapped_step` closure per study point forced a
    full recompile per point (at 1M nodes, minutes of XLA each) even
    when cfg was unchanged. Memoizing the closure lets loss-only grid
    points (same cfg, different fault plan — plan is a traced arg)
    share one compile.
    """
    from swim_tpu.parallel import ring_shard

    return ring_shard.mapped_step(cfg, mesh, program)


def _run_study(cfg: SwimConfig, plan: faults.FaultPlan, key: jax.Array,
               periods: int, engine: str, stream: bool = False,
               ckpt=None, chunk: int = 0):
    if stream and engine not in ("ring", "ringshard"):
        raise ValueError(
            f"streaming studies cover the ring engines only, not "
            f"'{engine}'")
    mesh = pmesh.make_mesh()
    n = cfg.n_nodes
    if engine == "shard":
        from swim_tpu.parallel import shard_engine

        state, plan = shard_engine.place(cfg, mesh, rumor.init_state(cfg),
                                         plan)
        step_fn = shard_engine.build_step(cfg, mesh)
        return runner.run_study_rumor(cfg, state, plan, key, periods,
                                      step_fn)
    if engine == "ringshard":
        from swim_tpu.models import ring
        from swim_tpu.parallel import ring_shard

        state, plan = ring_shard.place(cfg, mesh, ring.init_state(cfg),
                                       plan)
        step_fn = _mapped_step(cfg, mesh,
                               isinstance(plan, faults.FaultProgram))
        if stream:
            return runner.run_study_ring_stream(
                cfg, state, plan, key, periods, step_fn, chunk=chunk,
                ckpt=ckpt)
        return runner.run_study_ring(cfg, state, plan, key, periods,
                                     step_fn)
    plan = pmesh.shard_state(plan, mesh, n=n)
    if engine == "dense":
        state = pmesh.shard_state(dense.init_state(cfg), mesh, n=n)
        return runner.run_study(cfg, state, plan, key, periods)
    if engine == "ring":
        from swim_tpu.models import ring

        state = pmesh.shard_state(ring.init_state(cfg), mesh, n=n)
        if stream:
            return runner.run_study_ring_stream(
                cfg, state, plan, key, periods, chunk=chunk, ckpt=ckpt)
        return runner.run_study_ring(cfg, state, plan, key, periods)
    state = pmesh.shard_state(rumor.init_state(cfg), mesh, n=n)
    return runner.run_study_rumor(cfg, state, plan, key, periods)


def _run_study_batch(cfg: SwimConfig, progs, keys, periods: int,
                     engine: str, capacity: int | None = None):
    """`len(progs)` same-config studies as ONE vmapped device run.

    `progs` are FaultPrograms sharing one N; they are padded to a
    common segment capacity (the library max, or `capacity` if larger)
    so the batch traces a single step, then stacked along a leading P
    axis and driven through `runner.run_study_batch`.  `keys` is one
    root key per lane.  Every leaf of the returned StudyResult carries
    the [P] axis; de-interleave with `runner.lane_result` — each lane
    is bitwise-identical to its serial run (inert padding slots add
    zero to every lane threshold).

    The exchange-sharded engine has no program path (it rejects
    FaultPrograms serially too); dense/rumor/ring vmap the raw study
    bodies, and ringshard vmaps over the shard_map'd step closure —
    same memoized `_mapped_step`, so batched and serial studies share
    the sharded step cache."""
    import jax.numpy as jnp

    if engine == "shard":
        raise ValueError("batched studies: the exchange-sharded engine "
                         "has no fault-program path; use rumor, ring, "
                         "or ringshard")
    mesh = pmesh.make_mesh()
    n = cfg.n_nodes
    progs = list(progs)
    cap = max(int(p.seg_kind.shape[0]) for p in progs)
    if capacity is not None:
        cap = max(cap, int(capacity))
    padded = [faults.pad_program(p, cap) for p in progs]
    root_keys = jnp.stack(list(keys))
    if len(root_keys.shape) != 1 or root_keys.shape[0] != len(progs):
        raise ValueError(
            f"batched studies: {len(progs)} lanes need {len(progs)} root "
            f"keys, got shape {root_keys.shape}")
    if engine == "ringshard":
        from swim_tpu.models import ring
        from swim_tpu.parallel import ring_shard

        placed = [ring_shard.place(cfg, mesh, ring.init_state(cfg), pr)
                  for pr in padded]
        states = runner.batch_states([s for s, _ in placed])
        plans = runner.batch_states([pl for _, pl in placed])
        return runner.run_study_batch(
            cfg, states, plans, root_keys, periods, "ring",
            _mapped_step(cfg, mesh, True))
    plans = runner.batch_states(
        [pmesh.shard_state(pr, mesh, n=n) for pr in padded])
    if engine == "dense":
        init = dense.init_state
        kind = "dense"
    elif engine == "ring":
        from swim_tpu.models import ring

        init = ring.init_state
        kind = "ring"
    else:
        init = rumor.init_state
        kind = "rumor"
    states = runner.batch_states(
        [pmesh.shard_state(init(cfg), mesh, n=n) for _ in padded])
    return runner.run_study_batch(cfg, states, plans, root_keys, periods,
                                  kind)


def detection_study(n: int = 1000, crash_fraction: float = 0.01,
                    periods: int = 100, seed: int = 0,
                    engine: str = "auto",
                    flight_record: str | None = None,
                    stream: bool | str = "auto",
                    checkpoint_dir: str | None = None,
                    checkpoint_every: int = 0,
                    chunk: int = 0,
                    **cfg_kw) -> dict[str, Any]:
    """Config 2: crash-stop injection → detection-time distribution.

    With `telemetry=True` (a SwimConfig knob riding in via cfg_kw) the
    result gains a `telemetry` digest of the per-period EngineFrame
    series plus a `health` summary from the sliding-window rules
    engine (obs/health.py), and the flight recorder dumps the last
    periods to JSONL when any error-severity finding fires (reason
    `"health:<rule>"` — false_dead_views > 0 remains one such rule) or
    unconditionally when `flight_record` names a path.  The dump
    header embeds the crashed-subject detection milestones, so
    `swim-tpu observe DUMP` reproduces this study's detection summary
    offline (obs/analyze.py)."""
    engine = pick_engine(n, engine)
    if engine in ("ring", "ringshard"):
        # Fidelity by default (round 4; VERDICT r3 item 8): this study
        # exists to measure the paper's e/(e-1) first-detection law,
        # and the flagship rotor probe is by construction in the
        # deterministic-bound regime instead (detects in <= ~2 periods
        # — deviation R1).  Both ring layouts therefore default to the
        # law-preserving pull-uniform probe HERE (the sharded layout
        # routes pull's random-peer reads through nodewise ring-pass
        # exchanges — correct, deliberately not the throughput path);
        # rotor stays the explicit throughput opt-in
        # (ring_probe="rotor") and remains the default everywhere else.
        cfg_kw.setdefault("ring_probe", "pull")
    cfg = SwimConfig(n_nodes=n, **cfg_kw)
    # stream="auto": milestones are bitwise-identical either way, so the
    # study switches to the O(crashes) streaming driver exactly where
    # the stacked [periods, N] track starts to matter for HBM — or
    # whenever checkpointing is requested (only the streaming driver
    # checkpoints).  stream=True/False forces the path (tests pin both).
    if isinstance(stream, bool):
        do_stream = stream
    else:
        do_stream = (engine in ("ring", "ringshard")
                     and (n >= STREAM_AUTO_NODES
                          or checkpoint_dir is not None))
    ckpt = None
    if checkpoint_dir is not None:
        if not do_stream:
            raise ValueError("checkpointing needs the streaming study "
                             "driver; pass stream='auto' or stream=True")
        ckpt = runner.StudyCheckpointer(checkpoint_dir,
                                        every=checkpoint_every)
    plan = faults.with_random_crashes(
        faults.none(n), jax.random.key(seed + 1), crash_fraction,
        2, max(3, periods // 2))
    res = _run_study(cfg, plan, jax.random.key(seed), periods, engine,
                     stream=do_stream, ckpt=ckpt, chunk=chunk)
    out = {"study": "detection", "n": n, "periods": periods,
           "engine": engine, "crash_fraction": crash_fraction,
           "suspicion_periods": cfg.suspicion_periods}
    if engine in ("ring", "ringshard"):
        # self-describing: which probe regime produced these latencies
        # and which study driver (stream: O(crashes) milestone track)
        out["ring_probe"] = cfg.ring_probe
        out["stream"] = bool(do_stream)
    out.update(runner.detection_summary(res, plan, periods))
    out.update(metrics.series_digest(res.series))
    if engine in ("rumor", "shard", "ring", "ringshard"):
        out["overflow"] = int(res.state.overflow)
    if res.telemetry is not None:
        from swim_tpu.obs.health import HealthMonitor
        from swim_tpu.obs.recorder import FlightRecorder

        out["telemetry"] = metrics.series_digest(res.telemetry)
        monitor = HealthMonitor(window=min(16, max(2, periods)),
                                n_nodes=n)
        rec = FlightRecorder(cfg=cfg, capacity=min(64, periods),
                             monitor=monitor)
        rec.record_stacked(res.telemetry, aux={
            "false_dead_views": np.asarray(res.series.false_dead_views)})
        out["health"] = {"worst": monitor.worst() or "ok",
                         "findings": len(monitor.findings())}
        reason = rec.auto_dump_reason()
        if flight_record or reason:
            crash, milestones = runner.study_milestones(res, plan,
                                                        periods)
            # effective probe regime for the law check: only ring
            # engines can deviate (rotor, R1); dense/rumor probe
            # uniformly, their cfg.ring_probe default is inert
            study = {"n": n, "periods": periods, "engine": engine,
                     "probe": (cfg.ring_probe
                               if engine in ("ring", "ringshard")
                               else "pull"),
                     "crash_step": crash.tolist(),
                     "false_dead_views_final": int(np.asarray(
                         res.series.false_dead_views)[-1])}
            for name, arr in milestones.items():
                study[f"first_{name}" if name != "disseminated"
                      else name] = arr.tolist()
            path = flight_record or "flight_record.jsonl"
            rec.dump(path, reason=reason or "on_demand",
                     extra={"study": study})
            out["flight_record"] = path
    return out


def fp_sweep(n: int = 100_000, losses: tuple = (0.0, 0.1, 0.2, 0.3),
             partition: bool = True, periods: int = 100, seed: int = 0,
             engine: str = "auto", **cfg_kw) -> dict[str, Any]:
    """Config 3: loss (+ optional mid-run 2-way partition) → FP rates.

    A false positive is a live node holding a DEAD view of a live node at
    the end of the run. With the partition enabled, each half is *expected*
    to declare the other dead mid-run (that is SWIM working as specified);
    the interesting number is `false_dead_views_final` measured after the
    heal — whether refutation cleans the cluster up again is the paper's
    suspicion-mechanism claim. (It cannot: DEAD is sticky — the reference
    protocol needs re-join, which the sweep demonstrates quantitatively.)
    """
    engine = pick_engine(n, engine)
    points = []
    for loss in losses:
        cfg = SwimConfig(n_nodes=n, **cfg_kw)
        plan = faults.with_loss(faults.none(n), loss)
        if partition:
            plan = faults.with_partition(plan, faults.halves(n),
                                         periods // 3, 2 * periods // 3)
        res = _run_study(cfg, plan, jax.random.key(seed), periods, engine)
        series = res.series
        pt = {
            "loss": loss,
            "suspect_views_peak": int(np.asarray(
                series.suspect_views).max()),
            "false_dead_views_final": int(np.asarray(
                series.false_dead_views)[-1]),
            "false_dead_views_peak": int(np.asarray(
                series.false_dead_views).max()),
            "max_incarnation": int(np.asarray(
                series.max_incarnation).max()),
        }
        if engine in ("rumor", "shard", "ring", "ringshard"):
            pt["overflow"] = int(res.state.overflow)
        points.append(pt)
    return {"study": "fp_sweep", "n": n, "periods": periods,
            "engine": engine, "partition": partition, "points": points}


def suspicion_sweep(n: int = 1_000_000,
                    mults: tuple = (2.0, 3.0, 5.0, 8.0),
                    crash_fraction: float = 0.001, loss: float = 0.05,
                    losses: tuple | None = None,
                    periods: int = 100, seed: int = 0,
                    engine: str = "auto", **cfg_kw) -> dict[str, Any]:
    """Config 4: suspicion-timeout λ sweep — latency vs FP trade-off.

    When `losses` is given the sweep is the full `mults × losses` grid
    (BASELINE config 4 wants the trade-off curve at more than one packet
    loss rate); otherwise the single `loss` rate is used.
    """
    engine = pick_engine(n, engine)
    grid = tuple(losses) if losses else (loss,)
    points = []
    for lv in grid:
        for mult in mults:
            cfg = SwimConfig(n_nodes=n, suspicion_mult=mult, **cfg_kw)
            plan = faults.with_loss(
                faults.with_random_crashes(
                    faults.none(n), jax.random.key(seed + 1), crash_fraction,
                    2, max(3, periods // 2)),
                lv)
            res = _run_study(cfg, plan, jax.random.key(seed), periods,
                             engine)
            pt = {"suspicion_mult": mult, "loss": lv,
                  "suspicion_periods": cfg.suspicion_periods}
            pt.update(runner.detection_summary(res, plan, periods))
            pt["false_dead_views_peak"] = int(np.asarray(
                res.series.false_dead_views).max())
            points.append(pt)
    return {"study": "suspicion_sweep", "n": n, "periods": periods,
            "engine": engine, "losses": list(grid), "points": points}


def lifeguard_ablation(n: int = 1_000_000, crash_fraction: float = 0.001,
                       loss: float = 0.2, periods: int = 100, seed: int = 0,
                       engine: str = "auto", budget_arms: bool = False,
                       **cfg_kw) -> dict[str, Any]:
    """Config 5: Lifeguard extensions vs vanilla SWIM under lossy churn.

    `budget_arms=True` adds big-origination-budget twins of both arms
    (ring engines only: ring_orig_words 2→8, i.e. OB 64→256).  This
    separates the two candidate causes of the 1M-scale Lifeguard
    detection-latency regression (docs/RESULTS.md §5: suspect latency
    24.1 vs vanilla's 2.4 periods): LHA probe-thinning (intrinsic to
    Lifeguard) vs origination-budget throttling (an engine capacity
    knob).  If `lifeguard_ob8` recovers vanilla-like latency while
    keeping ~0 false-dead views, the regression is buyable-off with
    budget alone.
    """
    engine = pick_engine(n, engine)
    arm_defs = [("vanilla", False, {}), ("lifeguard", True, {})]
    if budget_arms:
        if engine not in ("ring", "ringshard"):
            raise ValueError("budget_arms sweeps ring_orig_words — ring "
                             "engines only")
        arm_defs += [("vanilla_ob8", False, {"ring_orig_words": 8}),
                     ("lifeguard_ob8", True, {"ring_orig_words": 8})]
    arms = {}
    for name, lg, extra in arm_defs:
        cfg = SwimConfig(n_nodes=n, lifeguard=lg, **{**cfg_kw, **extra})
        plan = faults.with_loss(
            faults.with_random_crashes(
                faults.none(n), jax.random.key(seed + 1), crash_fraction,
                2, max(3, periods // 2)),
            loss)
        res = _run_study(cfg, plan, jax.random.key(seed), periods, engine)
        arm = runner.detection_summary(res, plan, periods)
        arm["false_dead_views_peak"] = int(np.asarray(
            res.series.false_dead_views).max())
        arm["ring_orig_words"] = cfg.ring_orig_words
        arms[name] = arm
    return {"study": "lifeguard_ablation", "n": n, "periods": periods,
            "engine": engine, "loss": loss, "arms": arms}


STUDIES: dict[str, Callable[..., dict]] = {
    "detection": detection_study,
    "fp_sweep": fp_sweep,
    "suspicion_sweep": suspicion_sweep,
    "lifeguard": lifeguard_ablation,
}
