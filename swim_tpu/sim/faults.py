"""Fault injection as tensors (BASELINE.md configs 2–5).

The reference injects failures by killing live demo nodes; a vectorized
simulator instead expresses the whole fault schedule as data:

  * crash-stop: `crash_step[N]` — the period at which a node halts forever
    (INT32_MAX = never). Crashed nodes neither send nor receive.
  * packet loss: global Bernoulli `loss` probability, applied independently
    per directed message (every message wave draws its own uniforms).
  * partition: `partition_id[N]` group labels; between `partition_start` and
    `partition_end` (half-open, in periods) messages between different
    groups are dropped.  Labels are uint8 (up to 256 groups): every
    consumer compares them for EQUALITY only, and the ring engine rolls
    the label vector once per message wave — at 1M nodes over 8 chips
    the historical int32 labels were the single largest scalar ICI term
    (6 MB/period/chip at the lean geometry), paying 4x for width no
    comparison ever used.

Everything here is a *runtime* value — sweeps over loss rates, crash
schedules, or partition windows reuse a single compiled step (the engines
take FaultPlan as a traced argument).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

NEVER = np.int32(2**31 - 1)


class FaultPlan(NamedTuple):
    crash_step: jax.Array       # i32[N], NEVER = no crash
    loss: jax.Array             # f32 scalar in [0, 1)
    partition_id: jax.Array     # u8[N] group labels (equality-only; 256
    #                              groups max — with_partition validates)
    partition_start: jax.Array  # i32 scalar (period, inclusive)
    partition_end: jax.Array    # i32 scalar (period, exclusive)
    join_step: jax.Array        # i32[N], period a node becomes a member
    #                              (<= 0 = founding member). The dense,
    #                              rumor, and ring engines model join as
    #                              activation: a not-yet-joined node
    #                              neither acts nor receives and is in
    #                              nobody's membership list (no probes of
    #                              it); the sharded exchange engine raises
    #                              on join schedules. SWIM's snapshot
    #                              handshake lives in the real-node runtime
    #                              (core/node.py JOIN). Rejoin after DEAD
    #                              is a join under a fresh id, per the
    #                              protocol's rejoin-as-new-member rule.


def none(n: int) -> FaultPlan:
    """A perfect network: no crashes, no loss, no partition."""
    return FaultPlan(
        crash_step=jnp.full((n,), NEVER, jnp.int32),
        loss=jnp.float32(0.0),
        partition_id=jnp.zeros((n,), jnp.uint8),
        partition_start=jnp.int32(0),
        partition_end=jnp.int32(0),
        join_step=jnp.zeros((n,), jnp.int32),
    )


def with_joins(plan: FaultPlan, node_ids, at_step) -> FaultPlan:
    """Nodes that join (or rejoin under a fresh id) at the given period."""
    node_ids = jnp.asarray(node_ids, jnp.int32)
    at = jnp.broadcast_to(jnp.asarray(at_step, jnp.int32), node_ids.shape)
    return plan._replace(
        join_step=plan.join_step.at[node_ids].max(at))


def with_loss(plan: FaultPlan, loss: float) -> FaultPlan:
    return plan._replace(loss=jnp.float32(loss))


def with_crashes(plan: FaultPlan, node_ids, at_step) -> FaultPlan:
    """Crash the given nodes at the given period(s)."""
    node_ids = jnp.asarray(node_ids, jnp.int32)
    at = jnp.broadcast_to(jnp.asarray(at_step, jnp.int32), node_ids.shape)
    return plan._replace(
        crash_step=plan.crash_step.at[node_ids].min(at))


def with_random_crashes(plan: FaultPlan, key: jax.Array, fraction: float,
                        start: int, end: int) -> FaultPlan:
    """Crash ~`fraction` of nodes, each at a uniform period in [start, end).

    The spread-out (rather than burst) schedule is the default for the
    1k-node detection-time study (BASELINE.md config 2, "1% random
    crash-stop injection"); pass start == end - 1 for a burst.
    """
    n = plan.crash_step.shape[0]
    k_pick, k_when = jax.random.split(key)
    hit = jax.random.uniform(k_pick, (n,)) < fraction
    when = jax.random.randint(k_when, (n,), start, max(end, start + 1))
    return plan._replace(
        crash_step=jnp.where(hit, jnp.minimum(plan.crash_step, when),
                             plan.crash_step).astype(jnp.int32))


def with_partition(plan: FaultPlan, group_of, start: int,
                   end: int) -> FaultPlan:
    """Two-or-more-way partition over [start, end) periods.

    `group_of` is a label array (e.g. halves for the 2-way split of
    BASELINE.md config 3); labels must fit uint8 (up to 256 groups —
    the wire dtype the engines roll per message wave).
    """
    group = np.asarray(group_of)
    if group.size and (group.min() < 0 or group.max() > 255):
        raise ValueError(
            f"partition labels must be in [0, 255] (uint8 wire dtype): "
            f"got range [{group.min()}, {group.max()}]")
    return plan._replace(
        partition_id=jnp.asarray(group, jnp.uint8),
        partition_start=jnp.int32(start),
        partition_end=jnp.int32(end),
    )


def halves(n: int) -> np.ndarray:
    """Label array for a 2-way even split."""
    g = np.zeros((n,), np.uint8)
    g[n // 2:] = 1
    return g


def crashed_mask(plan: FaultPlan, step) -> jax.Array:
    """bool[N]: which nodes have crash-stopped by period `step`."""
    return jnp.asarray(step, jnp.int32) >= plan.crash_step


def partition_active(plan: FaultPlan, step) -> jax.Array:
    s = jnp.asarray(step, jnp.int32)
    return (s >= plan.partition_start) & (s < plan.partition_end)


def to_numpy(plan: FaultPlan) -> FaultPlan:
    return FaultPlan(*(np.asarray(x) for x in plan))
