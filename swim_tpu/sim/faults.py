"""Fault injection as tensors (BASELINE.md configs 2–5).

The reference injects failures by killing live demo nodes; a vectorized
simulator instead expresses the whole fault schedule as data:

  * crash-stop: `crash_step[N]` — the period at which a node halts forever
    (INT32_MAX = never). Crashed nodes neither send nor receive.
  * packet loss: global Bernoulli `loss` probability, applied independently
    per directed message (every message wave draws its own uniforms).
  * partition: `partition_id[N]` group labels; between `partition_start` and
    `partition_end` (half-open, in periods) messages between different
    groups are dropped.  Labels are uint8 (up to 256 groups): every
    consumer compares them for EQUALITY only, and the ring engine rolls
    the label vector once per message wave — at 1M nodes over 8 chips
    the historical int32 labels were the single largest scalar ICI term
    (6 MB/period/chip at the lean geometry), paying 4x for width no
    comparison ever used.

Everything here is a *runtime* value — sweeps over loss rates, crash
schedules, or partition windows reuse a single compiled step (the engines
take FaultPlan as a traced argument).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

NEVER = np.int32(2**31 - 1)


class FaultPlan(NamedTuple):
    crash_step: jax.Array       # i32[N], NEVER = no crash
    loss: jax.Array             # f32 scalar in [0, 1)
    partition_id: jax.Array     # u8[N] group labels (equality-only; 256
    #                              groups max — with_partition validates)
    partition_start: jax.Array  # i32 scalar (period, inclusive)
    partition_end: jax.Array    # i32 scalar (period, exclusive)
    join_step: jax.Array        # i32[N], period a node becomes a member
    #                              (<= 0 = founding member). The dense,
    #                              rumor, and ring engines model join as
    #                              activation: a not-yet-joined node
    #                              neither acts nor receives and is in
    #                              nobody's membership list (no probes of
    #                              it); the sharded exchange engine raises
    #                              on join schedules. SWIM's snapshot
    #                              handshake lives in the real-node runtime
    #                              (core/node.py JOIN). Rejoin after DEAD
    #                              is a join under a fresh id, per the
    #                              protocol's rejoin-as-new-member rule.


def none(n: int) -> FaultPlan:
    """A perfect network: no crashes, no loss, no partition."""
    return FaultPlan(
        crash_step=jnp.full((n,), NEVER, jnp.int32),
        loss=jnp.float32(0.0),
        partition_id=jnp.zeros((n,), jnp.uint8),
        partition_start=jnp.int32(0),
        partition_end=jnp.int32(0),
        join_step=jnp.zeros((n,), jnp.int32),
    )


def with_joins(plan: FaultPlan, node_ids, at_step) -> FaultPlan:
    """Nodes that join (or rejoin under a fresh id) at the given period."""
    node_ids = jnp.asarray(node_ids, jnp.int32)
    at = jnp.broadcast_to(jnp.asarray(at_step, jnp.int32), node_ids.shape)
    return plan._replace(
        join_step=plan.join_step.at[node_ids].max(at))


def with_loss(plan: FaultPlan, loss: float) -> FaultPlan:
    return plan._replace(loss=jnp.float32(loss))


def with_crashes(plan: FaultPlan, node_ids, at_step) -> FaultPlan:
    """Crash the given nodes at the given period(s)."""
    node_ids = jnp.asarray(node_ids, jnp.int32)
    at = jnp.broadcast_to(jnp.asarray(at_step, jnp.int32), node_ids.shape)
    return plan._replace(
        crash_step=plan.crash_step.at[node_ids].min(at))


def with_random_crashes(plan: FaultPlan, key: jax.Array, fraction: float,
                        start: int, end: int) -> FaultPlan:
    """Crash ~`fraction` of nodes, each at a uniform period in [start, end).

    The spread-out (rather than burst) schedule is the default for the
    1k-node detection-time study (BASELINE.md config 2, "1% random
    crash-stop injection"); pass start == end - 1 for a burst.
    """
    n = plan.crash_step.shape[0]
    k_pick, k_when = jax.random.split(key)
    hit = jax.random.uniform(k_pick, (n,)) < fraction
    when = jax.random.randint(k_when, (n,), start, max(end, start + 1))
    return plan._replace(
        crash_step=jnp.where(hit, jnp.minimum(plan.crash_step, when),
                             plan.crash_step).astype(jnp.int32))


def with_partition(plan: FaultPlan, group_of, start: int,
                   end: int) -> FaultPlan:
    """Two-or-more-way partition over [start, end) periods.

    `group_of` is a label array (e.g. halves for the 2-way split of
    BASELINE.md config 3); labels must fit uint8 (up to 256 groups —
    the wire dtype the engines roll per message wave).
    """
    group = np.asarray(group_of)
    if group.size and (group.min() < 0 or group.max() > 255):
        raise ValueError(
            f"partition labels must be in [0, 255] (uint8 wire dtype): "
            f"got range [{group.min()}, {group.max()}]")
    return plan._replace(
        partition_id=jnp.asarray(group, jnp.uint8),
        partition_start=jnp.int32(start),
        partition_end=jnp.int32(end),
    )


def halves(n: int) -> np.ndarray:
    """Label array for a 2-way even split."""
    g = np.zeros((n,), np.uint8)
    g[n // 2:] = 1
    return g


# ---------------------------------------------------------------------------
# FaultProgram: piecewise per-node link/gray fault schedules (sim/scenario.py
# compiles declarative specs into these; the engines consume them directly)
# ---------------------------------------------------------------------------

# Segment kinds.  "crash" segments never reach the engines: the scenario
# compiler folds them into base.crash_step at compile time, so a crash
# schedule leaves zero runtime residue.
KIND_NONE = 0        # inert slot (padding)
KIND_SEND_LOSS = 1   # add to the sender-side loss threshold (all legs)
KIND_RECV_LOSS = 2   # add to the receiver-side loss threshold (all legs)
KIND_LINK_LOSS = 3   # symmetric: both send and receive legs
KIND_GRAY = 4        # reply legs only: the node receives and gossips
#                      normally but its acks get lost — Lifeguard's
#                      gray-failure ablation workload

SEG_KINDS = {
    "send_loss": KIND_SEND_LOSS,
    "recv_loss": KIND_RECV_LOSS,
    "link_loss": KIND_LINK_LOSS,
    "gray": KIND_GRAY,
}

LANE_MAX = 65535  # u16 wire ceiling for one lane (see level_to_threshold)


class FaultProgram(NamedTuple):
    """FaultPlan plus a compiled piecewise fault schedule.

    Everything is a runtime tensor: sweeps over scenarios with the same
    segment COUNT reuse one compiled step, exactly like FaultPlan.  The
    segment arrays have static length S (the trace axis); scenario
    compilation pads to a fixed capacity so a library of specs shares
    one trace.  S == 0 means "no program": `split_program` strips the
    wrapper and the engines run the plain-FaultPlan code path, which is
    what makes the empty scenario bitwise-identical to `none(n)`.

    Per-node lanes derived from the segments are u16 thresholds in the
    same integer geometry as the engines' loss legs (`bits >= thr` with
    thr = ceil(p * 65536)): they compose with the global loss threshold
    by saturating addition.  A single u16 lane saturates at 65535 —
    probability 65535/65536, not quite 1.0; "never deliver" needs the
    composed threshold (loss + lane) to reach 65536, or a crash/
    partition segment.
    """

    base: FaultPlan
    domain_id: jax.Array   # u8[N] failure-domain labels (racks)
    seg_start: jax.Array   # i32[S] first period (inclusive)
    seg_end: jax.Array     # i32[S] last period (exclusive)
    seg_period: jax.Array  # i32[S] flap cycle length, 0 = always active
    seg_on: jax.Array      # i32[S] on-duty periods per cycle
    seg_domain: jax.Array  # i32[S] target domain, -1 = every node
    seg_kind: jax.Array    # i32[S] KIND_* selector
    seg_level: jax.Array   # u32[S] u16 threshold = level_to_threshold(p)


def level_to_threshold(p: float) -> int:
    """Probability -> u16 lane threshold, matching the engines' integer
    loss geometry (ceil(p * 65536), clamped to the u16 wire)."""
    if not 0.0 <= p <= 1.0:
        raise ValueError(f"fault level must be in [0, 1]: got {p}")
    return min(int(np.ceil(p * 65536.0)), LANE_MAX)


def empty_program(n: int) -> FaultProgram:
    """A FaultProgram with zero segments wrapping a perfect network."""
    return as_program(none(n))


def as_program(plan: FaultPlan, domain_id=None,
               capacity: int = 0) -> FaultProgram:
    """Wrap a FaultPlan with `capacity` inert segment slots."""
    n = plan.crash_step.shape[0]
    if domain_id is None:
        dom = jnp.zeros((n,), jnp.uint8)
    else:
        dom = jnp.asarray(domain_id, jnp.uint8)
    s = int(capacity)
    zi = jnp.zeros((s,), jnp.int32)
    return FaultProgram(
        base=plan, domain_id=dom,
        seg_start=zi, seg_end=zi, seg_period=zi, seg_on=zi,
        seg_domain=jnp.full((s,), -1, jnp.int32), seg_kind=zi,
        seg_level=jnp.zeros((s,), jnp.uint32))


def with_segment(prog: FaultProgram, slot: int, *, start: int, end: int,
                 kind: str, level: float, domain: int = -1,
                 period: int = 0, on: int = 0) -> FaultProgram:
    """Fill one segment slot (host-side builder; scenario.py compiles
    whole specs, this is the single-slot primitive under it)."""
    if kind not in SEG_KINDS:
        raise ValueError(
            f"unknown segment kind {kind!r}; one of {sorted(SEG_KINDS)}")
    if period > 0 and not 0 < on <= period:
        raise ValueError(
            f"flap duty must satisfy 0 < on <= period: {on}/{period}")
    return prog._replace(
        seg_start=prog.seg_start.at[slot].set(jnp.int32(start)),
        seg_end=prog.seg_end.at[slot].set(jnp.int32(end)),
        seg_period=prog.seg_period.at[slot].set(jnp.int32(period)),
        seg_on=prog.seg_on.at[slot].set(jnp.int32(on)),
        seg_domain=prog.seg_domain.at[slot].set(jnp.int32(domain)),
        seg_kind=prog.seg_kind.at[slot].set(
            jnp.int32(SEG_KINDS[kind])),
        seg_level=prog.seg_level.at[slot].set(
            jnp.uint32(level_to_threshold(level))))


def split_program(plan) -> tuple[FaultPlan, FaultProgram | None]:
    """(base plan, program-or-None).  None when the plan is a plain
    FaultPlan or a FaultProgram with zero segments — the engines gate
    every lane computation on this, so an empty program traces to the
    exact graph a plain FaultPlan does (the bitwise-parity contract)."""
    if isinstance(plan, FaultProgram):
        if plan.seg_kind.shape[0] == 0:
            return plan.base, None
        return plan.base, plan
    return plan, None


def base_of(plan) -> FaultPlan:
    return plan.base if isinstance(plan, FaultProgram) else plan


def link_lanes(prog: FaultProgram, step):
    """Per-node (send_thr, recv_thr, reply_thr) u32[N] lanes at period
    `step`: a static unroll over the S segments (S is tiny — the trace
    cost is a few fused selects), each segment contributing its level
    to the nodes in its domain while its time window and flap duty are
    active.  Values saturate at the u16 wire ceiling so the lanes can
    ride the packed scalar wire losslessly."""
    n = prog.domain_id.shape[0]
    t = jnp.asarray(step, jnp.int32)
    dom = prog.domain_id.astype(jnp.int32)
    send = jnp.zeros((n,), jnp.uint32)
    recv = jnp.zeros((n,), jnp.uint32)
    reply = jnp.zeros((n,), jnp.uint32)
    for i in range(int(prog.seg_kind.shape[0])):
        kind = prog.seg_kind[i]
        in_window = (t >= prog.seg_start[i]) & (t < prog.seg_end[i])
        phase = (t - prog.seg_start[i]) % jnp.maximum(prog.seg_period[i], 1)
        duty = (prog.seg_period[i] == 0) | (phase < prog.seg_on[i])
        hit = (prog.seg_domain[i] < 0) | (dom == prog.seg_domain[i])
        amt = jnp.where(in_window & duty & hit,
                        prog.seg_level[i], jnp.uint32(0))
        send = send + jnp.where(
            (kind == KIND_SEND_LOSS) | (kind == KIND_LINK_LOSS),
            amt, jnp.uint32(0))
        recv = recv + jnp.where(
            (kind == KIND_RECV_LOSS) | (kind == KIND_LINK_LOSS),
            amt, jnp.uint32(0))
        reply = reply + jnp.where(kind == KIND_GRAY, amt, jnp.uint32(0))
    cap = jnp.uint32(LANE_MAX)
    return (jnp.minimum(send, cap), jnp.minimum(recv, cap),
            jnp.minimum(reply, cap))


# ---------------------------------------------------------------------------
# ProgramBatch: a library of fault programs stacked along a leading P axis
# (sim/runner.py vmaps the study runners over it; sim/scenario.py batches a
# whole arm library into one device run)
# ---------------------------------------------------------------------------


class ProgramBatch(NamedTuple):
    """`size` FaultPrograms stacked leaf-wise along a new leading P axis.

    Host-side container: pass `batch.program` (whose every leaf carries
    the extra [P] dim) into vmapped runners so each lane sees an
    ordinary FaultProgram pytree — isinstance checks, `split_program`,
    and `link_lanes` all work unchanged per lane.  All members share one
    N and one capacity S (`stack_programs` pads to the max), so the
    whole batch traces ONE step; inert padding slots contribute exactly
    zero to every lane threshold, which is what makes a padded lane
    bitwise-identical to its serial run at its own capacity.
    """

    program: FaultProgram  # leaves stacked: base [P,N]/[P], segs [P,S]
    size: int              # P (static)


def pad_program(prog: FaultProgram, capacity: int) -> FaultProgram:
    """Grow a program's segment axis to `capacity` with inert slots.

    Padding slots are KIND_NONE at level 0 targeting domain -1 — they
    add 0 to every lane, so the padded program is behaviorally (and,
    because lanes are a pure sum over S, bitwise) identical.
    """
    s = int(prog.seg_kind.shape[0])
    pad = int(capacity) - s
    if pad < 0:
        raise ValueError(
            f"pad_program: capacity {capacity} < current {s} segments")
    if pad == 0:
        return prog
    zi = jnp.zeros((pad,), jnp.int32)
    return prog._replace(
        seg_start=jnp.concatenate([prog.seg_start, zi]),
        seg_end=jnp.concatenate([prog.seg_end, zi]),
        seg_period=jnp.concatenate([prog.seg_period, zi]),
        seg_on=jnp.concatenate([prog.seg_on, zi]),
        seg_domain=jnp.concatenate(
            [prog.seg_domain, jnp.full((pad,), -1, jnp.int32)]),
        seg_kind=jnp.concatenate([prog.seg_kind, zi]),
        seg_level=jnp.concatenate(
            [prog.seg_level, jnp.zeros((pad,), jnp.uint32)]))


def stack_programs(progs: list[FaultProgram] | tuple[FaultProgram, ...],
                   capacity: int | None = None) -> ProgramBatch:
    """Stack a program library into one ProgramBatch.

    All members must share one node count N; segment capacities are
    padded up to `capacity` (default: the library max) so the batch has
    a single S trace axis."""
    progs = list(progs)
    if not progs:
        raise ValueError("stack_programs: empty program list")
    ns = {int(p.domain_id.shape[0]) for p in progs}
    if len(ns) != 1:
        raise ValueError(
            f"stack_programs: mixed node counts {sorted(ns)}; a batch "
            f"shares one N")
    cap = max(int(p.seg_kind.shape[0]) for p in progs)
    if capacity is not None:
        if int(capacity) < cap:
            raise ValueError(
                f"stack_programs: capacity {capacity} < library max {cap}")
        cap = int(capacity)
    padded = [pad_program(p, cap) for p in progs]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *padded)
    return ProgramBatch(program=stacked, size=len(progs))


def lane_program(batch: ProgramBatch, p: int) -> FaultProgram:
    """Lane `p`'s FaultProgram (indexes every stacked leaf)."""
    if not 0 <= p < batch.size:
        raise IndexError(f"lane {p} out of range for batch of {batch.size}")
    return jax.tree.map(lambda x: x[p], batch.program)


def crashed_mask(plan: FaultPlan, step) -> jax.Array:
    """bool[N]: which nodes have crash-stopped by period `step`."""
    return jnp.asarray(step, jnp.int32) >= plan.crash_step


def partition_active(plan: FaultPlan, step) -> jax.Array:
    s = jnp.asarray(step, jnp.int32)
    return (s >= plan.partition_start) & (s < plan.partition_end)


def to_numpy(plan: FaultPlan) -> FaultPlan:
    return FaultPlan(*(np.asarray(x) for x in plan))
