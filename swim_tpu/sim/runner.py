"""Study runner: simulation with on-device metric collection.

Collects, inside the same lax.scan that advances the protocol, the
quantities BASELINE.md's studies need (configs 2–5):

  * first-detection step per crashed node (suspicion reaching any live node)
    and first-death-view step → detection-time distributions (the SWIM
    paper's e/(e−1) curve),
  * dissemination-completion step per crashed node (all live nodes hold the
    DEAD view),
  * per-period global counters (suspect views, dead views, refutations seen
    as incarnation bumps, false-death views) — psum-style full reductions
    that stay on device; only O(periods) scalars ever reach the host.

`run_study` works on the dense engine state; `run_study_rumor` collects the
same milestones from the rumor engine's event-shaped state in O(R·N) — a
rumor's live-knower count is one masked reduction, and per-subject
milestones are one scatter over the (tiny) rumor table.
"""

from __future__ import annotations

import functools
import os
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from swim_tpu.config import SwimConfig
from swim_tpu.models import dense
from swim_tpu.obs.engine import frame_from_tap
from swim_tpu.ops import lattice
from swim_tpu.sim import faults
from swim_tpu.sim.faults import FaultPlan
from swim_tpu.utils import checkpoint
from swim_tpu.utils.prng import draw_period

NEVER = jnp.int32(2**31 - 1)


class StudyTrack(NamedTuple):
    """Per-crashed-node detection milestones (i32[N], NEVER = not yet)."""

    first_suspect: jax.Array   # some live node stops believing ALIVE
    first_dead_view: jax.Array  # some live node holds DEAD
    disseminated: jax.Array    # all live nodes hold DEAD


class PeriodSeries(NamedTuple):
    """Per-period global counters (i32[periods])."""

    suspect_views: jax.Array
    dead_views: jax.Array
    false_dead_views: jax.Array
    max_incarnation: jax.Array


class StudyResult(NamedTuple):
    state: dense.DenseState
    track: StudyTrack
    series: PeriodSeries
    # [periods]-stacked obs.engine.EngineFrame when cfg.telemetry, else None
    telemetry: Any = None


def _update_track(track: StudyTrack, state: dense.DenseState,
                  crashed: jax.Array, t: jax.Array,
                  live: jax.Array) -> StudyTrack:
    """`crashed` selects which subjects accrue detection milestones;
    `live` (crash- AND join-aware) selects who counts as an observer."""
    key = state.key
    not_alive_view = lattice.is_suspect(key) | lattice.is_dead(key)
    dead_view = lattice.is_dead(key)
    live_col = live[:, None]
    any_suspect = jnp.any(not_alive_view & live_col, axis=0)
    any_dead = jnp.any(dead_view & live_col, axis=0)
    all_dead = jnp.all(dead_view | ~live_col, axis=0)

    def first(cur, cond):
        hit = cond & crashed & (cur == NEVER)
        return jnp.where(hit, t, cur)

    return StudyTrack(
        first_suspect=first(track.first_suspect, any_suspect),
        first_dead_view=first(track.first_dead_view, any_dead),
        disseminated=first(track.disseminated, all_dead),
    )


@functools.partial(jax.jit, static_argnums=(0, 4), donate_argnums=(1,))
def run_study(cfg: SwimConfig, state: dense.DenseState, plan: FaultPlan,
              root_key: jax.Array, periods: int) -> StudyResult:
    n = cfg.n_nodes
    track0 = StudyTrack(*(jnp.full((n,), NEVER, jnp.int32)
                          for _ in range(3)))

    def body(carry, _):
        st, track = carry
        rnd = draw_period(root_key, st.step, cfg)
        if cfg.telemetry:
            tap: dict = {}
            st = dense.step(cfg, st, plan, rnd, tap=tap)
            frame = frame_from_tap(tap)
        else:
            st = dense.step(cfg, st, plan, rnd)
            frame = None
        # metrics observe the post-step state at time st.step - 1 = the
        # period just executed
        t = st.step - 1
        base_plan = faults.base_of(plan)
        crashed = t >= base_plan.crash_step
        live = ~crashed & (t >= base_plan.join_step)
        track = _update_track(track, st, crashed, t, live=live)
        live_col = live[:, None]
        live_row = live[None, :]
        susp = lattice.is_suspect(st.key)
        dead = lattice.is_dead(st.key)
        series = (
            jnp.sum(susp & live_col).astype(jnp.int32),
            jnp.sum(dead & live_col).astype(jnp.int32),
            jnp.sum(dead & live_col & live_row).astype(jnp.int32),
            jnp.max(lattice.incarnation_of(st.key)).astype(jnp.int32),
        )
        return (st, track), (series, frame)

    (state, track), (series, frames) = jax.lax.scan(
        body, (state, track0), None, length=periods)
    return StudyResult(state, track, PeriodSeries(*series), frames)


class RumorStudyResult(NamedTuple):
    state: "rumor.RumorState"
    track: StudyTrack
    series: PeriodSeries
    # [periods]-stacked obs.engine.EngineFrame when cfg.telemetry, else None
    telemetry: Any = None


def _view_counts(subject, rkey, knowers, up, gone_dead):
    """Knower-weighted (suspect, dead) view counts over the rumor table
    plus the dissemination floor — shared by the full and streaming
    study bodies."""
    used = subject >= 0
    live_total = jnp.sum(up).astype(jnp.int32)
    is_s = lattice.is_suspect(rkey)
    is_d = lattice.is_dead(rkey)
    return (
        jnp.sum(jnp.where(used & is_s, knowers, 0)).astype(jnp.int32),
        jnp.sum(jnp.where(used & is_d, knowers, 0)).astype(jnp.int32)
        + jnp.sum(gone_dead) * live_total,
    )


def _subject_flags(n: int, subject, rkey, knowers, up,
                   gone_not_alive, gone_dead):
    """Per-subject (not-alive-seen, dead-seen, dead-disseminated) bool[N]
    plus knower-weighted (suspect, dead) view counts — shared by the
    rumor- and ring-engine study runners.

    A subject's milestone fires when a matching rumor is known by ≥1 live
    node (all live nodes, for dissemination) or has retired into the
    dissemination floor. `gone_not_alive`/`gone_dead` split because the
    ring engine's floor can hold ALIVE/SUSPECT keys (any disseminated
    retired key) while the rumor engine's holds only death tombstones.

    The three flags ride ONE u8 verdict lane (bit0 = not-alive seen,
    bit1 = dead seen, bit2 = disseminated) written by a single
    scatter-max, instead of three parallel bool[N] scatters — the same
    narrow-at-source move as ops/wavepack.py, and the fix for the
    duplicated pred[N] fusions the 16M study OOM HLO showed
    (study_detection_16m_oom.json). Scatter-max equals the per-bit
    scatter-OR because within one period the slot codes form a chain:
    with live observers, bit2 ⇒ knowers ≥ live_total > 0 ⇒ known, and
    dead ⇒ not-alive, so codes ∈ {0, 1, 3, 7}; with live_total == 0,
    `known` is all-False and codes ∈ {0, 4}.
    """
    used = subject >= 0
    live_total = jnp.sum(up).astype(jnp.int32)
    is_s = lattice.is_suspect(rkey)
    is_d = lattice.is_dead(rkey)
    known = used & (knowers > 0)
    sub = jnp.where(used, subject, n)
    code = ((known & (is_s | is_d)).astype(jnp.uint8)
            | (known & is_d).astype(jnp.uint8) << 1
            | (used & is_d & (knowers >= live_total)).astype(jnp.uint8) << 2)
    verdict = jnp.zeros((n,), jnp.uint8).at[sub].max(code, mode="drop")
    # the floor ORs in elementwise: a dead floor key marks all three
    # milestones (dead ⊂ not-alive), any floor key marks not-alive
    verdict = (verdict
               | jnp.where(gone_not_alive, jnp.uint8(1), jnp.uint8(0))
               | jnp.where(gone_dead, jnp.uint8(6), jnp.uint8(0)))
    not_alive = (verdict & 1) > 0
    dead_seen = (verdict & 2) > 0
    dead_all = (verdict & 4) > 0
    counts = _view_counts(subject, rkey, knowers, up, gone_dead)
    return not_alive, dead_seen, dead_all, counts


def _false_dead_views(subject, rkey, knowers, up, gone_dead):
    """Knower-weighted DEAD views whose subject is actually alive."""
    used = subject >= 0
    live_total = jnp.sum(up).astype(jnp.int32)
    live_subj = up[jnp.maximum(subject, 0)]
    return (jnp.sum(jnp.where(used & lattice.is_dead(rkey) & live_subj,
                              knowers, 0))
            + jnp.sum(gone_dead & up) * live_total).astype(jnp.int32)


def _rumor_subject_flags(cfg: SwimConfig, st, up: jax.Array):
    """Rumor-engine adapter over _subject_flags (knowers from the bool
    heard-matrix; the tombstone floor only ever holds DEAD keys)."""
    knowers = jnp.sum(st.knows & up[:, None], axis=0).astype(jnp.int32)
    gone_dead = lattice.is_dead(st.gone_key)
    return _subject_flags(cfg.n_nodes, st.subject, st.rkey, knowers, up,
                          gone_dead, gone_dead)


@functools.partial(jax.jit, static_argnums=(0, 4, 5), donate_argnums=(1,))
def run_study_rumor(cfg: SwimConfig, state, plan: FaultPlan,
                    root_key: jax.Array, periods: int,
                    step_fn=None) -> RumorStudyResult:
    """Rumor-engine study. `step_fn(state, plan, rnd)` overrides the step
    (static arg) — used to run the explicitly-sharded engine
    (swim_tpu/parallel/shard_engine.build_step) under the same metrics.

    With cfg.telemetry an override step_fn must return (state,
    EngineFrame) — the contract ring_shard.mapped_step follows."""
    from swim_tpu.models import rumor as rumor_mod

    n = cfg.n_nodes
    track0 = StudyTrack(*(jnp.full((n,), NEVER, jnp.int32)
                          for _ in range(3)))

    def body(carry, _):
        st, track = carry
        rnd = rumor_mod.draw_period_rumor(root_key, st.step, cfg)
        frame = None
        if step_fn is None:
            if cfg.telemetry:
                tap: dict = {}
                st = rumor_mod.step(cfg, st, plan, rnd, tap=tap)
                frame = frame_from_tap(tap)
            else:
                st = rumor_mod.step(cfg, st, plan, rnd)
        elif cfg.telemetry:
            st, frame = step_fn(st, plan, rnd)
        else:
            st = step_fn(st, plan, rnd)
        t = st.step - 1
        base_plan = faults.base_of(plan)
        crashed = t >= base_plan.crash_step
        up = ~crashed & (t >= base_plan.join_step)
        not_alive, dead_seen, dead_all, counts = _rumor_subject_flags(
            cfg, st, up)

        def first(cur, cond):
            hit = cond & crashed & (cur == NEVER)
            return jnp.where(hit, t, cur)

        track = StudyTrack(
            first_suspect=first(track.first_suspect, not_alive),
            first_dead_view=first(track.first_dead_view, dead_seen),
            disseminated=first(track.disseminated, dead_all),
        )
        knowers = jnp.sum(st.knows & up[:, None], axis=0).astype(jnp.int32)
        false_dead = _false_dead_views(st.subject, st.rkey, knowers, up,
                                       lattice.is_dead(st.gone_key))
        series = (counts[0], counts[1], false_dead,
                  jnp.maximum(
                      jnp.max(lattice.incarnation_of(st.rkey)),
                      jnp.max(st.inc_self)).astype(jnp.int32))
        return (st, track), (series, frame)

    (state, track), (series, frames) = jax.lax.scan(
        body, (state, track0), None, length=periods)
    return RumorStudyResult(state, track, PeriodSeries(*series), frames)


class RingStudyResult(NamedTuple):
    state: "ring.RingState"
    track: StudyTrack
    series: PeriodSeries
    # [periods]-stacked obs.engine.EngineFrame when cfg.telemetry, else None
    telemetry: Any = None


# `state` is donated in all three study runners: every caller builds it
# fresh for the call, and a non-donated 10M-node ring state (~6.4 GB)
# held next to the scan carry exceeded the 16 GB HBM (the same
# double-residency the bench harness hit at 10M, fixed there by
# init-inside-jit; donation is the API-preserving form here).
@functools.partial(jax.jit, static_argnums=(0, 4, 5), donate_argnums=(1,))
def run_study_ring(cfg: SwimConfig, state, plan: FaultPlan,
                   root_key: jax.Array, periods: int,
                   step_fn=None) -> RingStudyResult:
    """Ring-engine study: the same StudyTrack/PeriodSeries as the other
    engines, computed from the packed heard-bit words.

    `step_fn(state, plan, rnd)` overrides the stepper — the explicitly-
    sharded engine passes `ring_shard.mapped_step(cfg, mesh)` so studies
    run on the collective-permute path; metrics stay GSPMD-partitioned.
    With cfg.telemetry an override step_fn must return (state,
    EngineFrame) — which ring_shard.mapped_step does automatically.

    Per-slot knower COUNTS require unpacking the bit-planes ([N, R] work
    per period), which is fine at study sizes; the throughput bench path
    never runs this. The `disseminated` milestone uses the engine's
    dissemination floor (gone_key), which a death reaches when its word
    retires after full dissemination — i.e. the milestone can lag true
    dissemination by up to the window length (ring.py deviation R2);
    first_suspect / first_dead_view are exact (any-live-knower word ORs).
    """
    from swim_tpu.models import ring as ring_mod

    n = cfg.n_nodes
    track0 = StudyTrack(*(jnp.full((n,), NEVER, jnp.int32)
                          for _ in range(3)))

    def body(carry, _):
        st, track = carry
        rnd = ring_mod.draw_period_ring(root_key, st.step, cfg)
        frame = None
        if step_fn is None:
            if cfg.telemetry:
                tap: dict = {}
                st = ring_mod.step(cfg, st, plan, rnd, tap=tap)
                frame = frame_from_tap(tap)
            else:
                st = ring_mod.step(cfg, st, plan, rnd)
        elif cfg.telemetry:
            st, frame = step_fn(st, plan, rnd)
        else:
            st = step_fn(st, plan, rnd)
        t = st.step - 1
        base_plan = faults.base_of(plan)
        crashed = t >= base_plan.crash_step
        up = ~crashed & (t >= base_plan.join_step)

        # per-slot live-knower counts (layout resolution owned by
        # ring.live_knower_counts — chunked so the bit-plane expansion
        # stays bounded at any N; see its docstring for the 4M-node
        # CPU RESOURCE_EXHAUSTED this replaces)
        knowers = ring_mod.live_knower_counts(cfg, st, up)

        gone = st.gone_key
        gone_not_alive = lattice.is_suspect(gone) | lattice.is_dead(gone)
        gone_dead = lattice.is_dead(gone)
        not_alive, dead_seen, dead_all, counts = _subject_flags(
            n, st.subject, st.rkey, knowers, up, gone_not_alive, gone_dead)

        def first(cur, cond):
            hit = cond & crashed & (cur == NEVER)
            return jnp.where(hit, t, cur)

        track = StudyTrack(
            first_suspect=first(track.first_suspect, not_alive),
            first_dead_view=first(track.first_dead_view, dead_seen),
            disseminated=first(track.disseminated, dead_all),
        )
        false_dead = _false_dead_views(st.subject, st.rkey, knowers, up,
                                       gone_dead)
        series = (
            counts[0], counts[1], false_dead,
            jnp.maximum(jnp.max(lattice.incarnation_of(st.rkey)),
                        jnp.max(st.inc_self)).astype(jnp.int32),
        )
        return (st, track), (series, frame)

    (state, track), (series, frames) = jax.lax.scan(
        body, (state, track0), None, length=periods)
    return RingStudyResult(state, track, PeriodSeries(*series), frames)


# ---------------------------------------------------------------------------
# Streaming studies: O(crashes) milestone extraction folded into the scan
# carry.  The full-track path above carries 3× i32[N] milestone lanes and
# scatters bool[N] flags every period — 192 MB of carry plus scatter
# buffers at 16M nodes, a big slice of the 622M the 16M study OOM'd by
# (study_detection_16m_oom.json).  A detection study only ever *reads*
# milestones of crashed subjects (study_milestones restricts to
# crash < periods), and the crash schedule is host-known before the scan,
# so the streaming path precomputes the crashed-subject list once and
# carries [C]-sized lanes instead (C = crashes, ~160 at 16M with
# crash_fraction 1e-5).  Per period, subject matching is a [C, R] compare
# against the (tiny) rumor table plus [C] gathers from the dissemination
# floor — no N-sized scatter at all.  Bitwise parity with the stacked
# path is pinned by tests/test_memwall.py.
#
# The driver is chunked: the jitted chunk donates BOTH the engine state
# and the track carry, and the host loop between chunks is where
# mid-study checkpoints happen (per-period randomness is
# fold_in(root_key, st.step), and st.step rides in the state, so a
# chunked scan is bitwise-identical to one scan and a resumed run is
# bitwise-identical to an uninterrupted one).
# ---------------------------------------------------------------------------


class CompactTrack(NamedTuple):
    """Detection milestones restricted to crashed subjects (i32[C])."""

    subjects: jax.Array      # node ids with crash_step < periods, ascending
    crash_step: jax.Array    # their crash periods
    first_suspect: jax.Array
    first_dead_view: jax.Array
    disseminated: jax.Array


def compact_track_init(plan: FaultPlan, periods: int) -> CompactTrack:
    """Host-side: enumerate the subjects that can crash within the study
    window. np.where order (ascending node id) matches the restriction
    order of the full path's study_milestones, so summaries agree."""
    base = faults.base_of(plan)
    crash = np.asarray(jax.device_get(base.crash_step))
    subjects = np.flatnonzero(crash < periods).astype(np.int32)
    c = subjects.size
    # three DISTINCT buffers: the chunk donates each milestone lane, and
    # donating one shared buffer three times is an XLA error
    return CompactTrack(
        subjects=jnp.asarray(subjects),
        crash_step=jnp.asarray(crash[subjects].astype(np.int32)),
        first_suspect=jnp.full((c,), NEVER, jnp.int32),
        first_dead_view=jnp.full((c,), NEVER, jnp.int32),
        disseminated=jnp.full((c,), NEVER, jnp.int32),
    )


def _compact_subject_flags(subjects, subject, rkey, knowers, up,
                           gone_not_alive, gone_dead):
    """_subject_flags restricted to the crashed-subject list: a [C, R]
    compare against the rumor table plus [C] floor gathers, instead of
    bool[N] scatters. Value-identical to gathering the full flags at
    `subjects` (the parity the streaming tests pin)."""
    used = subject >= 0
    live_total = jnp.sum(up).astype(jnp.int32)
    is_s = lattice.is_suspect(rkey)
    is_d = lattice.is_dead(rkey)
    known = used & (knowers > 0)
    eq = subject[None, :] == subjects[:, None]  # [C, R]

    def hit(pred):
        return jnp.any(eq & pred[None, :], axis=1)

    not_alive = hit(known & (is_s | is_d)) | gone_not_alive[subjects]
    dead_seen = hit(known & is_d) | gone_dead[subjects]
    dead_all = (hit(used & is_d & (knowers >= live_total))
                | gone_dead[subjects])
    return not_alive, dead_seen, dead_all


@functools.partial(jax.jit, static_argnums=(0, 5, 6),
                   donate_argnums=(1, 2))
def _run_study_ring_chunk(cfg: SwimConfig, state, track: CompactTrack,
                          plan: FaultPlan, root_key: jax.Array,
                          periods: int, step_fn=None):
    """Advance `periods` periods of a streaming ring study. Donates the
    engine state AND the milestone carry — between chunks exactly one
    copy of each lives in HBM. The period clock is state.step, so
    chaining chunks reproduces one long scan bitwise."""
    from swim_tpu.models import ring as ring_mod

    def body(carry, _):
        st, tr = carry
        rnd = ring_mod.draw_period_ring(root_key, st.step, cfg)
        frame = None
        if step_fn is None:
            if cfg.telemetry:
                tap: dict = {}
                st = ring_mod.step(cfg, st, plan, rnd, tap=tap)
                frame = frame_from_tap(tap)
            else:
                st = ring_mod.step(cfg, st, plan, rnd)
        elif cfg.telemetry:
            st, frame = step_fn(st, plan, rnd)
        else:
            st = step_fn(st, plan, rnd)
        t = st.step - 1
        base_plan = faults.base_of(plan)
        up = ~(t >= base_plan.crash_step) & (t >= base_plan.join_step)
        knowers = ring_mod.live_knower_counts(cfg, st, up)
        gone = st.gone_key
        gone_not_alive = lattice.is_suspect(gone) | lattice.is_dead(gone)
        gone_dead = lattice.is_dead(gone)
        not_alive, dead_seen, dead_all = _compact_subject_flags(
            tr.subjects, st.subject, st.rkey, knowers, up,
            gone_not_alive, gone_dead)
        crashed = t >= tr.crash_step

        def first(cur, cond):
            hit = cond & crashed & (cur == NEVER)
            return jnp.where(hit, t, cur)

        tr = tr._replace(
            first_suspect=first(tr.first_suspect, not_alive),
            first_dead_view=first(tr.first_dead_view, dead_seen),
            disseminated=first(tr.disseminated, dead_all),
        )
        counts = _view_counts(st.subject, st.rkey, knowers, up, gone_dead)
        false_dead = _false_dead_views(st.subject, st.rkey, knowers, up,
                                       gone_dead)
        series = (
            counts[0], counts[1], false_dead,
            jnp.maximum(jnp.max(lattice.incarnation_of(st.rkey)),
                        jnp.max(st.inc_self)).astype(jnp.int32),
        )
        return (st, tr), (series, frame)

    (state, track), (series, frames) = jax.lax.scan(
        body, (state, track), None, length=periods)
    return state, track, PeriodSeries(*series), frames


class StudyCheckpointer:
    """Mid-study checkpoint/resume for the streaming driver.

    A study checkpoint is {engine state, CompactTrack, series prefix,
    root key, step}, written per-shard (utils/checkpoint.save_placed) so
    a sharded 64M flagship never gathers its state to one host. Restore
    re-places the engine state onto whatever sharding `state_like`
    carries; the track and series prefix come back as host arrays (the
    next chunk's jit re-places them)."""

    def __init__(self, directory: str, every: int = 0, keep: int = 3):
        self.directory = directory
        self.every = every
        self.keep = keep
        os.makedirs(directory, exist_ok=True)

    def _snaps(self) -> list[str]:
        return sorted(f for f in os.listdir(self.directory)
                      if f.startswith("study_") and f.endswith(".npz"))

    def save(self, state, track: CompactTrack, series: "PeriodSeries",
             root_key: jax.Array, step: int) -> str:
        path = os.path.join(self.directory, f"study_{step:012d}.npz")
        checkpoint.save_placed(path, (state, track, series), root_key, step)
        for f in self._snaps()[:-self.keep]:
            os.remove(os.path.join(self.directory, f))
        return path

    def latest(self) -> str | None:
        snaps = self._snaps()
        return os.path.join(self.directory, snaps[-1]) if snaps else None

    def restore(self, state_like):
        """None when no snapshot exists; else (state, track, series
        prefix, root_key, step). `state_like` supplies engine-state
        structure and placement (e.g. a placed init_state)."""
        path = self.latest()
        if path is None:
            return None
        track_like = CompactTrack(None, None, None, None, None)
        series_like = PeriodSeries(None, None, None, None)
        (state, track, series), root_key, step = checkpoint.restore_placed(
            path, (state_like, track_like, series_like))
        return state, CompactTrack(*track), PeriodSeries(*series), \
            root_key, step


def run_study_ring_stream(cfg: SwimConfig, state, plan: FaultPlan,
                          root_key: jax.Array, periods: int, step_fn=None,
                          chunk: int = 0,
                          ckpt: StudyCheckpointer | None = None
                          ) -> RingStudyResult:
    """Streaming ring study: O(crashes) milestone track, donated chunked
    scan, optional mid-study checkpointing. Returns a RingStudyResult
    whose `track` is a CompactTrack — detection_summary/study_milestones
    understand both shapes, and milestones/series are bitwise-identical
    to run_study_ring's (restricted to crashed subjects).

    `chunk` periods per jitted call (0 = one chunk, or ckpt.every when
    checkpointing). When `ckpt` holds a snapshot the study resumes from
    it — callers pass the same (cfg, plan, root_key, periods) and the
    resumed trajectory is bitwise-identical to an uninterrupted run."""
    if ckpt is not None and cfg.telemetry:
        raise ValueError("streaming study checkpointing does not cover "
                         "telemetry frames; disable one of them")
    track = None
    done = 0
    series_parts: list = []
    frame_parts: list = []
    if ckpt is not None:
        restored = ckpt.restore(state)
        if restored is not None:
            state, track, series_prefix, root_key, done = restored
            if done > periods:
                raise ValueError(
                    f"checkpoint at step {done} is beyond the requested "
                    f"{periods}-period study")
            series_parts.append(series_prefix)
    if track is None:
        track = compact_track_init(plan, periods)
    else:
        # a snapshot's subject list is a function of (plan, periods) at
        # save time — resuming under a different pair would silently
        # drop (or invent) crashed subjects, so refuse loudly
        want = compact_track_init(plan, periods)
        if not np.array_equal(np.asarray(want.subjects),
                              np.asarray(track.subjects)):
            raise ValueError(
                "checkpointed subject list does not match this "
                "(plan, periods); resume a study with its original "
                "arguments")
    if chunk <= 0:
        chunk = (ckpt.every if ckpt is not None and ckpt.every > 0
                 else periods)
    while done < periods:
        csize = min(chunk, periods - done)
        state, track, series_c, frames_c = _run_study_ring_chunk(
            cfg, state, track, plan, root_key, csize, step_fn)
        done += csize
        series_parts.append(jax.tree.map(np.asarray, series_c))
        if frames_c is not None:
            frame_parts.append(frames_c)
        if ckpt is not None and done < periods:
            series_so_far = PeriodSeries(*(np.concatenate(xs) for xs in
                                           zip(*series_parts)))
            ckpt.save(state, track, series_so_far, root_key, done)
    series = PeriodSeries(*(jnp.asarray(np.concatenate(xs))
                            for xs in zip(*series_parts)))
    frames = None
    if frame_parts:
        frames = jax.tree.map(lambda *xs: jnp.concatenate(xs), *frame_parts)
    return RingStudyResult(state, track, series, frames)


# ---------------------------------------------------------------------------
# Batched studies: one device step advances P scenarios (sim/faults.py
# ProgramBatch).  jax.vmap over the raw study bodies gives every output a
# leading [P] axis — states [P, ...], track [P, N], series [P, T], telemetry
# frames [P, T, ...] — and each lane is bitwise-identical to its serial run
# (the parity contract tests/test_scenario_batch.py pins per engine,
# including the sharded ring, where vmap composes over the shard_map'd
# step closure).
# ---------------------------------------------------------------------------

# The un-jitted study bodies (jit-of-jit would discard the inner donation
# and the vmap must wrap the raw traceable).
_SERIAL_BODIES = {
    "dense": run_study.__wrapped__,
    "rumor": run_study_rumor.__wrapped__,
    "ring": run_study_ring.__wrapped__,
}


@functools.partial(jax.jit, static_argnums=(0, 4, 5, 6),
                   donate_argnums=(1,))
def run_study_batch(cfg: SwimConfig, states, plans, root_keys,
                    periods: int, kind: str, step_fn=None):
    """Vmapped study: `states`/`plans`/`root_keys` are pytrees whose
    leaves carry a leading P axis (build with `batch_states` /
    faults.stack_programs); ONE compiled step advances all P lanes.

    `kind` selects the engine body ("dense" | "rumor" | "ring");
    `step_fn` (rumor/ring only) is the same static stepper override the
    serial runners take — the sharded ring passes its mapped_step
    closure and vmap composes over the shard_map.  Returns the engine's
    StudyResult with every leaf batched; de-interleave lanes with
    `lane_result`."""
    body = _SERIAL_BODIES[kind]
    if kind == "dense":
        fn = lambda s, p, k: body(cfg, s, p, k, periods)  # noqa: E731
    else:
        fn = lambda s, p, k: body(cfg, s, p, k, periods,  # noqa: E731
                                  step_fn)
    return jax.vmap(fn)(states, plans, root_keys)


def batch_states(states) -> Any:
    """Stack per-lane engine states leaf-wise along a new leading P axis."""
    states = list(states)
    if not states:
        raise ValueError("batch_states: empty state list")
    return jax.tree.map(lambda *xs: jnp.stack(xs), *states)


def lane_result(result, p: int):
    """Lane `p` of a batched StudyResult (indexes every stacked leaf;
    a None telemetry slot stays None — it is tree structure, not a
    leaf)."""
    return jax.tree.map(lambda x: x[p], result)


def study_milestones(result: StudyResult, plan: FaultPlan,
                     periods: int) -> tuple[np.ndarray, dict]:
    """(crash steps, milestone arrays) restricted to CRASHED subjects —
    the detection-summary inputs, in the shape the flight-recorder dump
    header embeds (obs/analyze.py recomputes the summary from these
    offline; milestone keys name the summary's output prefixes).

    A streaming study's CompactTrack already IS this restriction (same
    ascending-subject order), so it passes through without a gather."""
    if isinstance(result.track, CompactTrack):
        milestones = {
            name: np.asarray(arr).astype(np.int64)
            for name, arr in (("suspect", result.track.first_suspect),
                              ("dead_view", result.track.first_dead_view),
                              ("disseminated", result.track.disseminated))}
        return np.asarray(result.track.crash_step).astype(np.int64), \
            milestones
    crash = np.asarray(faults.base_of(plan).crash_step)
    crashed = crash < periods
    milestones = {
        name: np.asarray(arr)[crashed].astype(np.int64)
        for name, arr in (("suspect", result.track.first_suspect),
                          ("dead_view", result.track.first_dead_view),
                          ("disseminated", result.track.disseminated))}
    return crash[crashed].astype(np.int64), milestones


def detection_summary(result: StudyResult, plan: FaultPlan,
                      periods: int) -> dict:
    """Host-side digest: detection-latency distribution in periods.

    Delegates the latency arithmetic to obs/analyze.py's
    `summarize_detection` — the same function the offline analyzers
    run over a recorder dump, so live and replayed summaries are
    identical by construction."""
    from swim_tpu.obs import analyze

    crash, milestones = study_milestones(result, plan, periods)
    if not crash.size:
        return {"crashed": 0}
    return analyze.summarize_detection(
        crash, milestones,
        int(np.asarray(result.series.false_dead_views)[-1]))
