"""Study runner: simulation with on-device metric collection.

Collects, inside the same lax.scan that advances the protocol, the
quantities BASELINE.md's studies need (configs 2–5):

  * first-detection step per crashed node (suspicion reaching any live node)
    and first-death-view step → detection-time distributions (the SWIM
    paper's e/(e−1) curve),
  * dissemination-completion step per crashed node (all live nodes hold the
    DEAD view),
  * per-period global counters (suspect views, dead views, refutations seen
    as incarnation bumps, false-death views) — psum-style full reductions
    that stay on device; only O(periods) scalars ever reach the host.

`run_study` works on the dense engine state; `run_study_rumor` collects the
same milestones from the rumor engine's event-shaped state in O(R·N) — a
rumor's live-knower count is one masked reduction, and per-subject
milestones are one scatter over the (tiny) rumor table.
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from swim_tpu.config import SwimConfig
from swim_tpu.models import dense
from swim_tpu.obs.engine import frame_from_tap
from swim_tpu.ops import lattice
from swim_tpu.sim import faults
from swim_tpu.sim.faults import FaultPlan
from swim_tpu.utils.prng import draw_period

NEVER = jnp.int32(2**31 - 1)


class StudyTrack(NamedTuple):
    """Per-crashed-node detection milestones (i32[N], NEVER = not yet)."""

    first_suspect: jax.Array   # some live node stops believing ALIVE
    first_dead_view: jax.Array  # some live node holds DEAD
    disseminated: jax.Array    # all live nodes hold DEAD


class PeriodSeries(NamedTuple):
    """Per-period global counters (i32[periods])."""

    suspect_views: jax.Array
    dead_views: jax.Array
    false_dead_views: jax.Array
    max_incarnation: jax.Array


class StudyResult(NamedTuple):
    state: dense.DenseState
    track: StudyTrack
    series: PeriodSeries
    # [periods]-stacked obs.engine.EngineFrame when cfg.telemetry, else None
    telemetry: Any = None


def _update_track(track: StudyTrack, state: dense.DenseState,
                  crashed: jax.Array, t: jax.Array,
                  live: jax.Array) -> StudyTrack:
    """`crashed` selects which subjects accrue detection milestones;
    `live` (crash- AND join-aware) selects who counts as an observer."""
    key = state.key
    not_alive_view = lattice.is_suspect(key) | lattice.is_dead(key)
    dead_view = lattice.is_dead(key)
    live_col = live[:, None]
    any_suspect = jnp.any(not_alive_view & live_col, axis=0)
    any_dead = jnp.any(dead_view & live_col, axis=0)
    all_dead = jnp.all(dead_view | ~live_col, axis=0)

    def first(cur, cond):
        hit = cond & crashed & (cur == NEVER)
        return jnp.where(hit, t, cur)

    return StudyTrack(
        first_suspect=first(track.first_suspect, any_suspect),
        first_dead_view=first(track.first_dead_view, any_dead),
        disseminated=first(track.disseminated, all_dead),
    )


@functools.partial(jax.jit, static_argnums=(0, 4), donate_argnums=(1,))
def run_study(cfg: SwimConfig, state: dense.DenseState, plan: FaultPlan,
              root_key: jax.Array, periods: int) -> StudyResult:
    n = cfg.n_nodes
    track0 = StudyTrack(*(jnp.full((n,), NEVER, jnp.int32)
                          for _ in range(3)))

    def body(carry, _):
        st, track = carry
        rnd = draw_period(root_key, st.step, cfg)
        if cfg.telemetry:
            tap: dict = {}
            st = dense.step(cfg, st, plan, rnd, tap=tap)
            frame = frame_from_tap(tap)
        else:
            st = dense.step(cfg, st, plan, rnd)
            frame = None
        # metrics observe the post-step state at time st.step - 1 = the
        # period just executed
        t = st.step - 1
        base_plan = faults.base_of(plan)
        crashed = t >= base_plan.crash_step
        live = ~crashed & (t >= base_plan.join_step)
        track = _update_track(track, st, crashed, t, live=live)
        live_col = live[:, None]
        live_row = live[None, :]
        susp = lattice.is_suspect(st.key)
        dead = lattice.is_dead(st.key)
        series = (
            jnp.sum(susp & live_col).astype(jnp.int32),
            jnp.sum(dead & live_col).astype(jnp.int32),
            jnp.sum(dead & live_col & live_row).astype(jnp.int32),
            jnp.max(lattice.incarnation_of(st.key)).astype(jnp.int32),
        )
        return (st, track), (series, frame)

    (state, track), (series, frames) = jax.lax.scan(
        body, (state, track0), None, length=periods)
    return StudyResult(state, track, PeriodSeries(*series), frames)


class RumorStudyResult(NamedTuple):
    state: "rumor.RumorState"
    track: StudyTrack
    series: PeriodSeries
    # [periods]-stacked obs.engine.EngineFrame when cfg.telemetry, else None
    telemetry: Any = None


def _subject_flags(n: int, subject, rkey, knowers, up,
                   gone_not_alive, gone_dead):
    """Per-subject (not-alive-seen, dead-seen, dead-disseminated) bool[N]
    plus knower-weighted (suspect, dead) view counts — shared by the
    rumor- and ring-engine study runners.

    A subject's milestone fires when a matching rumor is known by ≥1 live
    node (all live nodes, for dissemination) or has retired into the
    dissemination floor. `gone_not_alive`/`gone_dead` split because the
    ring engine's floor can hold ALIVE/SUSPECT keys (any disseminated
    retired key) while the rumor engine's holds only death tombstones.
    """
    used = subject >= 0
    live_total = jnp.sum(up).astype(jnp.int32)
    is_s = lattice.is_suspect(rkey)
    is_d = lattice.is_dead(rkey)
    known = used & (knowers > 0)
    sub = jnp.where(used, subject, n)
    zeros = jnp.zeros((n,), jnp.bool_)
    not_alive = (zeros.at[sub].max(known & (is_s | is_d), mode="drop")
                 | gone_not_alive)
    dead_seen = zeros.at[sub].max(known & is_d, mode="drop") | gone_dead
    dead_all = (zeros.at[sub].max(used & is_d & (knowers >= live_total),
                                  mode="drop") | gone_dead)
    counts = (
        jnp.sum(jnp.where(used & is_s, knowers, 0)).astype(jnp.int32),
        jnp.sum(jnp.where(used & is_d, knowers, 0)).astype(jnp.int32)
        + jnp.sum(gone_dead) * live_total,
    )
    return not_alive, dead_seen, dead_all, counts


def _false_dead_views(subject, rkey, knowers, up, gone_dead):
    """Knower-weighted DEAD views whose subject is actually alive."""
    used = subject >= 0
    live_total = jnp.sum(up).astype(jnp.int32)
    live_subj = up[jnp.maximum(subject, 0)]
    return (jnp.sum(jnp.where(used & lattice.is_dead(rkey) & live_subj,
                              knowers, 0))
            + jnp.sum(gone_dead & up) * live_total).astype(jnp.int32)


def _rumor_subject_flags(cfg: SwimConfig, st, up: jax.Array):
    """Rumor-engine adapter over _subject_flags (knowers from the bool
    heard-matrix; the tombstone floor only ever holds DEAD keys)."""
    knowers = jnp.sum(st.knows & up[:, None], axis=0).astype(jnp.int32)
    gone_dead = lattice.is_dead(st.gone_key)
    return _subject_flags(cfg.n_nodes, st.subject, st.rkey, knowers, up,
                          gone_dead, gone_dead)


@functools.partial(jax.jit, static_argnums=(0, 4, 5), donate_argnums=(1,))
def run_study_rumor(cfg: SwimConfig, state, plan: FaultPlan,
                    root_key: jax.Array, periods: int,
                    step_fn=None) -> RumorStudyResult:
    """Rumor-engine study. `step_fn(state, plan, rnd)` overrides the step
    (static arg) — used to run the explicitly-sharded engine
    (swim_tpu/parallel/shard_engine.build_step) under the same metrics.

    With cfg.telemetry an override step_fn must return (state,
    EngineFrame) — the contract ring_shard.mapped_step follows."""
    from swim_tpu.models import rumor as rumor_mod

    n = cfg.n_nodes
    track0 = StudyTrack(*(jnp.full((n,), NEVER, jnp.int32)
                          for _ in range(3)))

    def body(carry, _):
        st, track = carry
        rnd = rumor_mod.draw_period_rumor(root_key, st.step, cfg)
        frame = None
        if step_fn is None:
            if cfg.telemetry:
                tap: dict = {}
                st = rumor_mod.step(cfg, st, plan, rnd, tap=tap)
                frame = frame_from_tap(tap)
            else:
                st = rumor_mod.step(cfg, st, plan, rnd)
        elif cfg.telemetry:
            st, frame = step_fn(st, plan, rnd)
        else:
            st = step_fn(st, plan, rnd)
        t = st.step - 1
        base_plan = faults.base_of(plan)
        crashed = t >= base_plan.crash_step
        up = ~crashed & (t >= base_plan.join_step)
        not_alive, dead_seen, dead_all, counts = _rumor_subject_flags(
            cfg, st, up)

        def first(cur, cond):
            hit = cond & crashed & (cur == NEVER)
            return jnp.where(hit, t, cur)

        track = StudyTrack(
            first_suspect=first(track.first_suspect, not_alive),
            first_dead_view=first(track.first_dead_view, dead_seen),
            disseminated=first(track.disseminated, dead_all),
        )
        knowers = jnp.sum(st.knows & up[:, None], axis=0).astype(jnp.int32)
        false_dead = _false_dead_views(st.subject, st.rkey, knowers, up,
                                       lattice.is_dead(st.gone_key))
        series = (counts[0], counts[1], false_dead,
                  jnp.maximum(
                      jnp.max(lattice.incarnation_of(st.rkey)),
                      jnp.max(st.inc_self)).astype(jnp.int32))
        return (st, track), (series, frame)

    (state, track), (series, frames) = jax.lax.scan(
        body, (state, track0), None, length=periods)
    return RumorStudyResult(state, track, PeriodSeries(*series), frames)


class RingStudyResult(NamedTuple):
    state: "ring.RingState"
    track: StudyTrack
    series: PeriodSeries
    # [periods]-stacked obs.engine.EngineFrame when cfg.telemetry, else None
    telemetry: Any = None


# `state` is donated in all three study runners: every caller builds it
# fresh for the call, and a non-donated 10M-node ring state (~6.4 GB)
# held next to the scan carry exceeded the 16 GB HBM (the same
# double-residency the bench harness hit at 10M, fixed there by
# init-inside-jit; donation is the API-preserving form here).
@functools.partial(jax.jit, static_argnums=(0, 4, 5), donate_argnums=(1,))
def run_study_ring(cfg: SwimConfig, state, plan: FaultPlan,
                   root_key: jax.Array, periods: int,
                   step_fn=None) -> RingStudyResult:
    """Ring-engine study: the same StudyTrack/PeriodSeries as the other
    engines, computed from the packed heard-bit words.

    `step_fn(state, plan, rnd)` overrides the stepper — the explicitly-
    sharded engine passes `ring_shard.mapped_step(cfg, mesh)` so studies
    run on the collective-permute path; metrics stay GSPMD-partitioned.
    With cfg.telemetry an override step_fn must return (state,
    EngineFrame) — which ring_shard.mapped_step does automatically.

    Per-slot knower COUNTS require unpacking the bit-planes ([N, R] work
    per period), which is fine at study sizes; the throughput bench path
    never runs this. The `disseminated` milestone uses the engine's
    dissemination floor (gone_key), which a death reaches when its word
    retires after full dissemination — i.e. the milestone can lag true
    dissemination by up to the window length (ring.py deviation R2);
    first_suspect / first_dead_view are exact (any-live-knower word ORs).
    """
    from swim_tpu.models import ring as ring_mod

    n = cfg.n_nodes
    track0 = StudyTrack(*(jnp.full((n,), NEVER, jnp.int32)
                          for _ in range(3)))

    def body(carry, _):
        st, track = carry
        rnd = ring_mod.draw_period_ring(root_key, st.step, cfg)
        frame = None
        if step_fn is None:
            if cfg.telemetry:
                tap: dict = {}
                st = ring_mod.step(cfg, st, plan, rnd, tap=tap)
                frame = frame_from_tap(tap)
            else:
                st = ring_mod.step(cfg, st, plan, rnd)
        elif cfg.telemetry:
            st, frame = step_fn(st, plan, rnd)
        else:
            st = step_fn(st, plan, rnd)
        t = st.step - 1
        base_plan = faults.base_of(plan)
        crashed = t >= base_plan.crash_step
        up = ~crashed & (t >= base_plan.join_step)

        # per-slot live-knower counts (layout resolution owned by
        # ring.live_knower_counts — chunked so the bit-plane expansion
        # stays bounded at any N; see its docstring for the 4M-node
        # CPU RESOURCE_EXHAUSTED this replaces)
        knowers = ring_mod.live_knower_counts(cfg, st, up)

        gone = st.gone_key
        gone_not_alive = lattice.is_suspect(gone) | lattice.is_dead(gone)
        gone_dead = lattice.is_dead(gone)
        not_alive, dead_seen, dead_all, counts = _subject_flags(
            n, st.subject, st.rkey, knowers, up, gone_not_alive, gone_dead)

        def first(cur, cond):
            hit = cond & crashed & (cur == NEVER)
            return jnp.where(hit, t, cur)

        track = StudyTrack(
            first_suspect=first(track.first_suspect, not_alive),
            first_dead_view=first(track.first_dead_view, dead_seen),
            disseminated=first(track.disseminated, dead_all),
        )
        false_dead = _false_dead_views(st.subject, st.rkey, knowers, up,
                                       gone_dead)
        series = (
            counts[0], counts[1], false_dead,
            jnp.maximum(jnp.max(lattice.incarnation_of(st.rkey)),
                        jnp.max(st.inc_self)).astype(jnp.int32),
        )
        return (st, track), (series, frame)

    (state, track), (series, frames) = jax.lax.scan(
        body, (state, track0), None, length=periods)
    return RingStudyResult(state, track, PeriodSeries(*series), frames)


# ---------------------------------------------------------------------------
# Batched studies: one device step advances P scenarios (sim/faults.py
# ProgramBatch).  jax.vmap over the raw study bodies gives every output a
# leading [P] axis — states [P, ...], track [P, N], series [P, T], telemetry
# frames [P, T, ...] — and each lane is bitwise-identical to its serial run
# (the parity contract tests/test_scenario_batch.py pins per engine,
# including the sharded ring, where vmap composes over the shard_map'd
# step closure).
# ---------------------------------------------------------------------------

# The un-jitted study bodies (jit-of-jit would discard the inner donation
# and the vmap must wrap the raw traceable).
_SERIAL_BODIES = {
    "dense": run_study.__wrapped__,
    "rumor": run_study_rumor.__wrapped__,
    "ring": run_study_ring.__wrapped__,
}


@functools.partial(jax.jit, static_argnums=(0, 4, 5, 6),
                   donate_argnums=(1,))
def run_study_batch(cfg: SwimConfig, states, plans, root_keys,
                    periods: int, kind: str, step_fn=None):
    """Vmapped study: `states`/`plans`/`root_keys` are pytrees whose
    leaves carry a leading P axis (build with `batch_states` /
    faults.stack_programs); ONE compiled step advances all P lanes.

    `kind` selects the engine body ("dense" | "rumor" | "ring");
    `step_fn` (rumor/ring only) is the same static stepper override the
    serial runners take — the sharded ring passes its mapped_step
    closure and vmap composes over the shard_map.  Returns the engine's
    StudyResult with every leaf batched; de-interleave lanes with
    `lane_result`."""
    body = _SERIAL_BODIES[kind]
    if kind == "dense":
        fn = lambda s, p, k: body(cfg, s, p, k, periods)  # noqa: E731
    else:
        fn = lambda s, p, k: body(cfg, s, p, k, periods,  # noqa: E731
                                  step_fn)
    return jax.vmap(fn)(states, plans, root_keys)


def batch_states(states) -> Any:
    """Stack per-lane engine states leaf-wise along a new leading P axis."""
    states = list(states)
    if not states:
        raise ValueError("batch_states: empty state list")
    return jax.tree.map(lambda *xs: jnp.stack(xs), *states)


def lane_result(result, p: int):
    """Lane `p` of a batched StudyResult (indexes every stacked leaf;
    a None telemetry slot stays None — it is tree structure, not a
    leaf)."""
    return jax.tree.map(lambda x: x[p], result)


def study_milestones(result: StudyResult, plan: FaultPlan,
                     periods: int) -> tuple[np.ndarray, dict]:
    """(crash steps, milestone arrays) restricted to CRASHED subjects —
    the detection-summary inputs, in the shape the flight-recorder dump
    header embeds (obs/analyze.py recomputes the summary from these
    offline; milestone keys name the summary's output prefixes)."""
    crash = np.asarray(faults.base_of(plan).crash_step)
    crashed = crash < periods
    milestones = {
        name: np.asarray(arr)[crashed].astype(np.int64)
        for name, arr in (("suspect", result.track.first_suspect),
                          ("dead_view", result.track.first_dead_view),
                          ("disseminated", result.track.disseminated))}
    return crash[crashed].astype(np.int64), milestones


def detection_summary(result: StudyResult, plan: FaultPlan,
                      periods: int) -> dict:
    """Host-side digest: detection-latency distribution in periods.

    Delegates the latency arithmetic to obs/analyze.py's
    `summarize_detection` — the same function the offline analyzers
    run over a recorder dump, so live and replayed summaries are
    identical by construction."""
    from swim_tpu.obs import analyze

    crash, milestones = study_milestones(result, plan, periods)
    if not crash.size:
        return {"crashed": 0}
    return analyze.summarize_detection(
        crash, milestones,
        int(np.asarray(result.series.false_dead_views)[-1]))
