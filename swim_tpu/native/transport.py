"""Native UDP transport: the udppump.cpp datapath behind the Transport ABC.

Same seam as core.transport.UDPTransport (one-per-host deployment), but the
socket lives on a C++ epoll thread: sends enqueue into the pump's outbox,
and a drainer polls inbound BATCHES out of the pump — one GIL crossing per
batch. Pairs with any Clock; delivery callbacks run on the drainer thread
(the Node runtime is single-threaded per node, so callers running multiple
nodes drive each from its own transport exactly as with asyncio).
"""

from __future__ import annotations

import ctypes
import logging
import socket as _socket
import threading

from swim_tpu.core.transport import Address, Receiver, Transport
from swim_tpu.native import pump_lib

_META_CAP = 1024
_BUF_CAP = 1 << 20


def is_available() -> bool:
    return pump_lib() is not None


class NativeUDPTransport(Transport):
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 poll_interval: float = 0.002, loop=None):
        """`loop`: optional asyncio loop; when given, receiver callbacks
        are marshalled onto it with call_soon_threadsafe so a Node driven
        by AsyncioClock sees single-threaded delivery (same contract as
        core.transport.UDPTransport). Without it, callbacks run on the
        drainer thread and the caller owns serialization."""
        lib = pump_lib()
        if lib is None:
            raise RuntimeError("native udppump unavailable (no toolchain)")
        lib.pump_create.restype = ctypes.c_void_p
        lib.pump_create.argtypes = [ctypes.c_char_p, ctypes.c_uint16]
        lib.pump_port.restype = ctypes.c_uint16
        lib.pump_port.argtypes = [ctypes.c_void_p]
        lib.pump_send.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_uint16,
                                  ctypes.POINTER(ctypes.c_uint8),
                                  ctypes.c_int]
        lib.pump_recv.restype = ctypes.c_int
        lib.pump_recv.argtypes = [ctypes.c_void_p,
                                  ctypes.POINTER(ctypes.c_uint8),
                                  ctypes.c_int,
                                  ctypes.POINTER(ctypes.c_uint32),
                                  ctypes.c_int]
        lib.pump_stats.argtypes = [ctypes.c_void_p] + \
            [ctypes.POINTER(ctypes.c_uint64)] * 3
        lib.pump_destroy.argtypes = [ctypes.c_void_p]
        self._lib = lib
        self._h = lib.pump_create(host.encode(), port)
        if not self._h:
            raise OSError(f"could not bind UDP {host}:{port}")
        self._local: Address = (host, lib.pump_port(self._h))
        self._resolved: dict[str, str] = {}
        self._hlock = threading.Lock()  # orders send/stats against close
        self._loop = loop
        self._receiver: Receiver | None = None
        self._poll_interval = poll_interval
        self._stop = threading.Event()
        self._buf = (ctypes.c_uint8 * _BUF_CAP)()
        self._meta = (ctypes.c_uint32 * (4 * _META_CAP))()
        self._thread = threading.Thread(target=self._drain, daemon=True)
        self._thread.start()

    def _drain(self) -> None:
        import socket as pysock

        base = ctypes.addressof(self._buf)
        while not self._stop.wait(self._poll_interval):
            n = self._lib.pump_recv(self._h, self._buf, _BUF_CAP,
                                    self._meta, _META_CAP)
            if n <= 0 or self._receiver is None:
                continue
            off = 0
            for i in range(n):
                # meta carries ntohl()'d (host-order) values; big-endian
                # re-encode recovers network order on any platform
                ip = pysock.inet_ntoa(
                    int(self._meta[4 * i]).to_bytes(4, "big"))
                port = int(self._meta[4 * i + 1])
                ln = int(self._meta[4 * i + 2])
                # string_at: one memcpy, no per-byte boxing
                payload = ctypes.string_at(base + off, ln)
                off += ln
                try:
                    if self._loop is not None:
                        self._loop.call_soon_threadsafe(
                            self._receiver, (ip, port), payload)
                    else:
                        self._receiver((ip, port), payload)
                except Exception:  # noqa: BLE001 — a broken handler must
                    # not kill the drainer and deafen the transport (the
                    # asyncio path survives handler errors the same way)
                    logging.getLogger(__name__).exception(
                        "receiver callback failed; datagram dropped")

    # ------------------------------------------------------------ Transport

    def send(self, to: Address, payload: bytes) -> None:
        if not self._h:
            return  # closed transport: datagram loss is legal on this seam
        host = to[0]
        ip = self._resolved.get(host)
        if ip is None:
            # the pump takes IPv4 literals only; resolve (and cache) names
            # so ("localhost", p) seeds behave as with the asyncio path
            try:
                ip = _socket.gethostbyname(host)
            except OSError:
                return
            self._resolved[host] = ip
        arr = (ctypes.c_uint8 * len(payload)).from_buffer_copy(payload)
        with self._hlock:
            if not self._h:
                return
            self._lib.pump_send(self._h, ip.encode(), to[1], arr,
                                len(payload))

    def set_receiver(self, receiver: Receiver) -> None:
        self._receiver = receiver

    @property
    def local_address(self) -> Address:
        return self._local

    def stats(self) -> dict[str, int]:
        rx = ctypes.c_uint64()
        tx = ctypes.c_uint64()
        dr = ctypes.c_uint64()
        with self._hlock:
            if not self._h:
                raise RuntimeError("transport closed")
            self._lib.pump_stats(self._h, ctypes.byref(rx), ctypes.byref(tx),
                                 ctypes.byref(dr))
        return {"rx": rx.value, "tx": tx.value, "drops": dr.value}

    def close(self) -> None:
        if not self._h:
            return
        self._stop.set()
        self._thread.join(timeout=5.0)
        if self._thread.is_alive():
            # a wedged receiver callback is still inside pump_recv;
            # leak the pump rather than free memory under its feet
            return
        with self._hlock:
            h, self._h = self._h, None
        if h:
            self._lib.pump_destroy(h)
