"""Native (C++) runtime datapath: wire codec + UDP pump.

The reference is a compiled-native implementation; swim_tpu keeps its
per-datagram hot path native too. Python owns the protocol state machine,
C++ owns bytes-on-the-wire:

  * codec.cpp   — encode/decode twin of swim_tpu/core/codec.py,
  * udppump.cpp — epoll socket pump on a native thread (batch GIL
    crossings, socket serviced while the interpreter runs protocol logic).

Build-on-first-use via g++ (no pip, no pybind11 — plain C ABI + ctypes),
cached next to the sources; every consumer falls back to the pure-Python
path when a toolchain is unavailable, so the native layer is a strict
acceleration, never a dependency.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD = os.path.join(_DIR, "_build")
_LOCK = threading.Lock()
_cache: dict[str, ctypes.CDLL | None] = {}


def _load(name: str) -> ctypes.CDLL | None:
    """Compile (once) and dlopen `name`.cpp; None if no toolchain."""
    with _LOCK:
        if name in _cache:
            return _cache[name]
        src = os.path.join(_DIR, f"{name}.cpp")
        so = os.path.join(_BUILD, f"lib{name}.so")
        lib: ctypes.CDLL | None = None
        try:
            if (not os.path.exists(so)
                    or os.path.getmtime(so) < os.path.getmtime(src)):
                os.makedirs(_BUILD, exist_ok=True)
                subprocess.run(
                    ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                     "-o", so + ".tmp", src, "-pthread"],
                    check=True, capture_output=True, timeout=120)
                os.replace(so + ".tmp", so)
            lib = ctypes.CDLL(so)
        except (OSError, subprocess.SubprocessError):
            lib = None
        _cache[name] = lib
        return lib


def codec_lib() -> ctypes.CDLL | None:
    return _load("codec")


def pump_lib() -> ctypes.CDLL | None:
    return _load("udppump")


def available() -> dict[str, bool]:
    return {"codec": codec_lib() is not None,
            "udppump": pump_lib() is not None}
