"""ctypes binding for the native wire codec (codec.cpp).

`encode`/`decode` are drop-in twins of swim_tpu/core/codec.py operating on
the same Message/WireUpdate dataclasses — parity is fuzz-tested in
tests/test_native.py.

Honest scope note: through THIS binding the native codec is not faster
than the Python one — materializing Message/WireUpdate dataclasses
dominates (measured ≈0.9× on 200-update join snapshots). Its role is
(a) a second, independently-written implementation of the wire format
that cross-validates the Python codec byte-for-byte under fuzz, and
(b) the parsing layer for datapaths that stay in C structs end-to-end
(udppump-side filtering, a future fully-native node runner). Perf-
sensitive Python callers should keep using swim_tpu.core.codec.
"""

from __future__ import annotations

import ctypes

from swim_tpu.core.codec import DecodeError, Message, WireUpdate
from swim_tpu.native import codec_lib
from swim_tpu.types import MsgKind, Status

_MAX_HOST = 255
_MAX_GOSSIP = 255
# true wire maximum: 7 header + (8 + 260 addr) body + 1 count +
# 255 × (13 + 260) updates ≈ 69.9 KiB — round up to 128 KiB
_MAX_DGRAM = 1 << 17


class _WireAddr(ctypes.Structure):
    # host as c_uint8 (NOT c_char): ctypes NUL-truncates c_char-array
    # reads, which would silently diverge from the Python codec on hosts
    # containing 0x00 bytes
    _fields_ = [("host_len", ctypes.c_uint8),
                ("host", ctypes.c_uint8 * _MAX_HOST),
                ("port", ctypes.c_uint32)]


class _WireUpd(ctypes.Structure):
    _fields_ = [("member", ctypes.c_uint32),
                ("status", ctypes.c_uint8),
                ("incarnation", ctypes.c_uint32),
                ("origin", ctypes.c_uint32),
                ("addr", _WireAddr)]


class _WireMsg(ctypes.Structure):
    _fields_ = [("kind", ctypes.c_uint8),
                ("sender", ctypes.c_uint32),
                ("probe_seq", ctypes.c_uint32),
                ("target", ctypes.c_uint32),
                ("on_behalf", ctypes.c_uint32),
                ("target_addr", _WireAddr),
                ("n_gossip", ctypes.c_uint16),
                ("gossip", _WireUpd * _MAX_GOSSIP)]


_lib = None


def _get_lib():
    global _lib
    if _lib is None:
        lib = codec_lib()
        if lib is None:
            raise RuntimeError("native codec unavailable (no toolchain)")
        lib.swim_encode.restype = ctypes.c_int
        lib.swim_encode.argtypes = [ctypes.POINTER(_WireMsg),
                                    ctypes.POINTER(ctypes.c_uint8),
                                    ctypes.c_int]
        lib.swim_decode.restype = ctypes.c_int
        lib.swim_decode.argtypes = [ctypes.POINTER(ctypes.c_uint8),
                                    ctypes.c_int, ctypes.POINTER(_WireMsg)]
        _lib = lib
    return _lib


def is_available() -> bool:
    return codec_lib() is not None


def _set_addr(wa: _WireAddr, addr) -> None:
    host = addr[0].encode()
    if len(host) > _MAX_HOST:
        raise ValueError("host too long")
    wa.host_len = len(host)
    ctypes.memmove(wa.host, host, len(host))
    wa.port = addr[1]


def _get_addr(wa: _WireAddr):
    return (bytes(wa.host[:wa.host_len]).decode(), wa.port)


def _to_wire(msg: Message) -> _WireMsg:
    m = _WireMsg()
    m.kind = int(msg.kind)
    m.sender = msg.sender
    m.probe_seq = msg.probe_seq
    m.target = msg.target
    m.on_behalf = msg.on_behalf
    _set_addr(m.target_addr, msg.target_addr)
    if len(msg.gossip) > _MAX_GOSSIP:
        raise ValueError("gossip section too large")
    m.n_gossip = len(msg.gossip)
    for i, u in enumerate(msg.gossip):
        g = m.gossip[i]
        g.member = u.member
        g.status = int(u.status)
        g.incarnation = u.incarnation
        g.origin = u.origin
        _set_addr(g.addr, u.addr)
    return m


def _from_wire(m: _WireMsg) -> Message:
    gossip = tuple(
        WireUpdate(g.member, Status(g.status), g.incarnation,
                   _get_addr(g.addr), g.origin)
        for g in m.gossip[:m.n_gossip])
    return Message(kind=MsgKind(m.kind), sender=m.sender,
                   probe_seq=m.probe_seq, target=m.target,
                   target_addr=_get_addr(m.target_addr),
                   on_behalf=m.on_behalf, gossip=gossip)


def encode(msg: Message) -> bytes:
    lib = _get_lib()
    m = _to_wire(msg)
    out = (ctypes.c_uint8 * _MAX_DGRAM)()
    n = lib.swim_encode(ctypes.byref(m), out, _MAX_DGRAM)
    if n < 0:
        raise ValueError("encode failed")
    return bytes(out[:n])


def decode(buf: bytes) -> Message:
    lib = _get_lib()
    m = _WireMsg()
    arr = (ctypes.c_uint8 * len(buf)).from_buffer_copy(buf)
    rc = lib.swim_decode(arr, len(buf), ctypes.byref(m))
    if rc != 0:
        raise DecodeError(f"malformed datagram (native rc={rc})")
    try:
        return _from_wire(m)
    except (ValueError, UnicodeDecodeError) as e:
        raise DecodeError(f"malformed datagram: {e}") from e
