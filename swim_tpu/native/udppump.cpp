// Native UDP datapath: an epoll-driven socket pump on its own thread.
//
// The one-per-host deployment path (swim_tpu/core/transport.py
// UDPTransport) does every datagram's recv/send on the Python event loop.
// This pump moves the socket work off-interpreter: a native thread owns
// the socket and two lock-protected rings, Python drains inbound batches
// and enqueues outbound batches — one GIL crossing per BATCH, not per
// datagram, and the socket stays serviced while the interpreter is busy
// running protocol logic (the reference, being compiled Haskell, gets
// this for free; swim_tpu's runtime keeps its datapath native too).
//
// C ABI only — consumed via ctypes (no pybind11 in this environment).

#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <netinet/in.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <mutex>
#include <thread>
#include <vector>

namespace {

constexpr int kMaxDgram = 65536;

struct Dgram {
  uint32_t ip;     // network order
  uint16_t port;   // host order
  std::vector<uint8_t> data;
};

struct Pump {
  int fd = -1;
  int efd = -1;          // eventfd: wake the loop for sends/shutdown
  int epfd = -1;
  uint16_t bound_port = 0;
  uint32_t bound_ip = 0;
  std::thread thr;
  std::atomic<bool> stop{false};
  std::mutex in_mu, out_mu;
  std::vector<Dgram> inbox, outbox;
  std::atomic<uint64_t> rx{0}, tx{0}, drops{0};

  void loop() {
    std::vector<uint8_t> buf(kMaxDgram);
    epoll_event evs[4];
    while (!stop.load(std::memory_order_acquire)) {
      int n = epoll_wait(epfd, evs, 4, 100);
      if (n < 0 && errno != EINTR) break;
      for (int i = 0; i < n; ++i) {
        if (evs[i].data.fd == efd) {
          uint64_t junk;
          (void)!read(efd, &junk, sizeof junk);
        }
      }
      // drain socket
      for (;;) {
        sockaddr_in src{};
        socklen_t slen = sizeof src;
        ssize_t got = recvfrom(fd, buf.data(), buf.size(), MSG_DONTWAIT,
                               (sockaddr *)&src, &slen);
        if (got < 0) break;
        Dgram d;
        d.ip = src.sin_addr.s_addr;
        d.port = ntohs(src.sin_port);
        d.data.assign(buf.begin(), buf.begin() + got);
        std::lock_guard<std::mutex> lk(in_mu);
        if (inbox.size() < 65536) {
          inbox.push_back(std::move(d));
          rx.fetch_add(1, std::memory_order_relaxed);
        } else {
          drops.fetch_add(1, std::memory_order_relaxed);
        }
      }
      // flush outbox
      std::vector<Dgram> out;
      {
        std::lock_guard<std::mutex> lk(out_mu);
        out.swap(outbox);
      }
      for (auto &d : out) {
        sockaddr_in dst{};
        dst.sin_family = AF_INET;
        dst.sin_addr.s_addr = d.ip;
        dst.sin_port = htons(d.port);
        if (sendto(fd, d.data.data(), d.data.size(), 0, (sockaddr *)&dst,
                   sizeof dst) >= 0)
          tx.fetch_add(1, std::memory_order_relaxed);
        else  // e.g. EMSGSIZE: a >64K join snapshot exceeds one datagram
          drops.fetch_add(1, std::memory_order_relaxed);
      }
    }
  }
};

}  // namespace

extern "C" {

// Create and bind; returns an opaque handle or null. `ip` is dotted quad.
void *pump_create(const char *ip, uint16_t port) {
  auto *p = new Pump();
  p->fd = socket(AF_INET, SOCK_DGRAM | SOCK_NONBLOCK, 0);
  if (p->fd < 0) { delete p; return nullptr; }
  int one = 1;
  setsockopt(p->fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  if (inet_pton(AF_INET, ip, &addr.sin_addr) != 1 ||
      bind(p->fd, (sockaddr *)&addr, sizeof addr) < 0) {
    close(p->fd); delete p; return nullptr;
  }
  sockaddr_in got{};
  socklen_t glen = sizeof got;
  getsockname(p->fd, (sockaddr *)&got, &glen);
  p->bound_port = ntohs(got.sin_port);
  p->bound_ip = got.sin_addr.s_addr;
  p->efd = eventfd(0, EFD_NONBLOCK);
  p->epfd = epoll_create1(0);
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.fd = p->fd;
  bool ok = p->efd >= 0 && p->epfd >= 0 &&
            epoll_ctl(p->epfd, EPOLL_CTL_ADD, p->fd, &ev) == 0;
  ev.data.fd = p->efd;
  ok = ok && epoll_ctl(p->epfd, EPOLL_CTL_ADD, p->efd, &ev) == 0;
  if (!ok) {  // fd exhaustion etc: fail loudly, not with a deaf handle
    close(p->fd);
    if (p->efd >= 0) close(p->efd);
    if (p->epfd >= 0) close(p->epfd);
    delete p;
    return nullptr;
  }
  p->thr = std::thread([p] { p->loop(); });
  return p;
}

uint16_t pump_port(void *h) { return ((Pump *)h)->bound_port; }

void pump_send(void *h, const char *ip, uint16_t port, const uint8_t *buf,
               int len) {
  auto *p = (Pump *)h;
  if (p == nullptr) return;
  Dgram d;
  if (inet_pton(AF_INET, ip, &d.ip) != 1) {
    p->drops.fetch_add(1, std::memory_order_relaxed);
    return;
  }
  d.port = port;
  d.data.assign(buf, buf + len);
  {
    std::lock_guard<std::mutex> lk(p->out_mu);
    p->outbox.push_back(std::move(d));
  }
  uint64_t one = 1;
  (void)!write(p->efd, &one, sizeof one);
}

// Drain up to `cap` datagrams. For each: writes src ip (u32 HOST order),
// src port (u16), length (u16) into the meta array (4 fields of u32 per
// entry for ctypes simplicity) and the payload into `out` back to back.
// Returns the number of datagrams; lengths[i] gives payload boundaries.
int pump_recv(void *h, uint8_t *out, int out_cap, uint32_t *meta, int cap) {
  auto *p = (Pump *)h;
  std::vector<Dgram> batch;
  {
    std::lock_guard<std::mutex> lk(p->in_mu);
    batch.swap(p->inbox);
  }
  int n = 0, off = 0;
  for (auto &d : batch) {
    if (n >= cap || off + (int)d.data.size() > out_cap) {
      // put the rest back (front of inbox, preserving order)
      std::lock_guard<std::mutex> lk(p->in_mu);
      p->inbox.insert(p->inbox.begin(), batch.begin() + n, batch.end());
      break;
    }
    std::memcpy(out + off, d.data.data(), d.data.size());
    meta[4 * n + 0] = ntohl(d.ip);  // host order; Python re-encodes big-endian
    meta[4 * n + 1] = d.port;
    meta[4 * n + 2] = (uint32_t)d.data.size();
    meta[4 * n + 3] = 0;
    off += d.data.size();
    ++n;
  }
  return n;
}

void pump_stats(void *h, uint64_t *rx, uint64_t *tx, uint64_t *drops) {
  auto *p = (Pump *)h;
  *rx = p->rx.load(); *tx = p->tx.load(); *drops = p->drops.load();
}

void pump_destroy(void *h) {
  auto *p = (Pump *)h;
  p->stop.store(true, std::memory_order_release);
  uint64_t one = 1;
  (void)!write(p->efd, &one, sizeof one);
  if (p->thr.joinable()) p->thr.join();
  close(p->fd); close(p->efd); close(p->epfd);
  delete p;
}

}  // extern "C"
