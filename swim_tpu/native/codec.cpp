// Native SWIM wire codec — C ABI twin of swim_tpu/core/codec.py.
//
// The reference implementation is a compiled-native program (Haskell); the
// swim_tpu runtime keeps its datapath native too: this codec and the UDP
// pump (udppump.cpp) form the per-datagram hot path, leaving Python to the
// protocol state machine. Format (network byte order, see codec.py):
//
//   header:  magic 'W' | version u8 | kind u8 | sender_id u32
//   body:    kind-dependent (probe_seq/on_behalf | probe_seq/target/addr)
//   gossip:  count u8, then count x (member u32 | status u8 | inc u32 |
//            origin u32 | addr)
//   address: host_len u8 | host bytes | port u32
//
// Exact parity with the Python codec is enforced by round-trip fuzzing in
// tests/test_native.py. The C structs use fixed-capacity buffers so the
// ABI needs no allocator handshake with ctypes.

#include <cstdint>
#include <cstring>

namespace {

constexpr uint8_t kMagic = 0x57;
constexpr uint8_t kVersion = 1;
constexpr int kMaxHost = 255;
constexpr int kMaxGossip = 255;

// MsgKind values must match swim_tpu/types.py
constexpr uint8_t kPing = 0, kPingReq = 1, kAck = 2, kNack = 3, kJoin = 4,
                  kJoinReply = 5;

inline void put_u32(uint8_t *p, uint32_t v) {
  p[0] = v >> 24; p[1] = v >> 16; p[2] = v >> 8; p[3] = v;
}
inline uint32_t get_u32(const uint8_t *p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

}  // namespace

extern "C" {

struct WireAddr {
  uint8_t host_len;
  char host[kMaxHost];
  uint32_t port;
};

struct WireUpd {
  uint32_t member;
  uint8_t status;
  uint32_t incarnation;
  uint32_t origin;
  WireAddr addr;
};

struct WireMsg {
  uint8_t kind;
  uint32_t sender;
  uint32_t probe_seq;
  uint32_t target;
  uint32_t on_behalf;
  WireAddr target_addr;
  uint16_t n_gossip;
  WireUpd gossip[kMaxGossip];
};

// Returns bytes written, or -1 if `cap` is too small / msg malformed.
int swim_encode(const WireMsg *m, uint8_t *out, int cap) {
  if (m->n_gossip > kMaxGossip) return -1;
  int off = 0;
  auto need = [&](int n) { return off + n <= cap; };
  auto put_addr = [&](const WireAddr &a) -> bool {
    if (!need(1 + a.host_len + 4)) return false;
    out[off++] = a.host_len;
    std::memcpy(out + off, a.host, a.host_len);
    off += a.host_len;
    put_u32(out + off, a.port);
    off += 4;
    return true;
  };
  if (!need(7)) return -1;
  out[off++] = kMagic;
  out[off++] = kVersion;
  out[off++] = m->kind;
  put_u32(out + off, m->sender); off += 4;
  switch (m->kind) {
    case kPing: case kAck: case kNack:
      if (!need(8)) return -1;
      put_u32(out + off, m->probe_seq); off += 4;
      put_u32(out + off, m->on_behalf); off += 4;
      break;
    case kPingReq:
      if (!need(8)) return -1;
      put_u32(out + off, m->probe_seq); off += 4;
      put_u32(out + off, m->target); off += 4;
      if (!put_addr(m->target_addr)) return -1;
      break;
    case kJoin: case kJoinReply:
      break;
    default:
      return -1;
  }
  if (!need(1)) return -1;
  out[off++] = (uint8_t)m->n_gossip;
  for (int i = 0; i < m->n_gossip; ++i) {
    const WireUpd &u = m->gossip[i];
    if (!need(13)) return -1;
    put_u32(out + off, u.member); off += 4;
    out[off++] = u.status;
    put_u32(out + off, u.incarnation); off += 4;
    put_u32(out + off, u.origin); off += 4;
    if (!put_addr(u.addr)) return -1;
  }
  return off;
}

// Returns 0 on success, negative error code on malformed input.
int swim_decode(const uint8_t *buf, int len, WireMsg *m) {
  int off = 0;
  auto need = [&](int n) { return off + n <= len; };
  auto get_addr = [&](WireAddr *a) -> bool {
    if (!need(1)) return false;
    a->host_len = buf[off++];
    if (!need(a->host_len + 4)) return false;
    std::memcpy(a->host, buf + off, a->host_len);
    off += a->host_len;
    a->port = get_u32(buf + off);
    off += 4;
    return true;
  };
  std::memset(m, 0, sizeof(WireMsg));
  if (!need(7)) return -2;
  if (buf[off++] != kMagic) return -3;
  if (buf[off++] != kVersion) return -4;
  m->kind = buf[off++];
  if (m->kind > kJoinReply) return -5;
  m->sender = get_u32(buf + off); off += 4;
  switch (m->kind) {
    case kPing: case kAck: case kNack:
      if (!need(8)) return -2;
      m->probe_seq = get_u32(buf + off); off += 4;
      m->on_behalf = get_u32(buf + off); off += 4;
      break;
    case kPingReq:
      if (!need(8)) return -2;
      m->probe_seq = get_u32(buf + off); off += 4;
      m->target = get_u32(buf + off); off += 4;
      if (!get_addr(&m->target_addr)) return -2;
      break;
    default:
      break;
  }
  if (!need(1)) return -2;
  m->n_gossip = buf[off++];
  for (int i = 0; i < m->n_gossip; ++i) {
    WireUpd &u = m->gossip[i];
    if (!need(13)) return -2;
    u.member = get_u32(buf + off); off += 4;
    u.status = buf[off++];
    if (u.status > 2) return -6;
    u.incarnation = get_u32(buf + off); off += 4;
    u.origin = get_u32(buf + off); off += 4;
    if (!get_addr(&u.addr)) return -2;
  }
  return 0;
}

}  // extern "C"
