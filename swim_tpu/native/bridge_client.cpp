// Foreign-core bridge conformance client — a self-contained SWIM protocol
// core in C++ that joins a swim_tpu simulated cluster over the TCP
// lockstep bridge (swim_tpu/bridge/protocol.py) and participates fully:
// join via snapshot, piggybacked gossip, probe/ack/ping-req failure
// detection, suspicion timers, incarnation refutation.
//
// This is the proof for SURVEY.md §2 "Host bridge": the wire contract is
// implementable from scratch in a non-Python language (the reference's
// core is compiled-native Haskell), and a foreign implementation of the
// datagram codec (shared with codec.cpp) plus the SWIM state machine
// interoperates with in-process swim_tpu nodes — exercised end-to-end by
// tests/test_bridge_c.py, which runs this binary against a BridgeServer
// and requires mutual ALIVE views and cross-language failure detection.
//
// Scope: the vanilla protocol of docs/PROTOCOL.md §3-§5 under the stock
// demo config (1 s period, k=3, B=6; timeouts as core/node.py computes
// them). Lifeguard extensions are not implemented here — the conformance
// scenario runs them disabled.
//
// Usage:
//   bridge_client HOST PORT NODE_ID SEED_ID DURATION [QUANTUM]
//                 [KILL_ID KILL_AT]
// Drives the co-simulation DURATION virtual seconds in QUANTUM slices;
// optionally injects KILL(KILL_ID) at virtual time KILL_AT. On exit,
// prints one line per known member: "member <id> <status> <incarnation>"
// (status 0=alive 1=suspect 2=dead) and "self <id> <incarnation>".

#include "codec.cpp"

#include <algorithm>
#include <arpa/inet.h>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <map>
#include <netdb.h>
#include <string>
#include <sys/socket.h>
#include <unistd.h>
#include <vector>

namespace {

// ------------------------------------------------------------ frame layer
// Bridge frames: u32le length | u8 opcode | little-endian fields
// (swim_tpu/bridge/protocol.py).

enum Op : uint8_t {
  HELLO = 1, WELCOME = 2, SEND = 3, STEP = 4, DELIVER = 5, TIME = 6,
  KILL = 7, SET_LOSS = 8, BYE = 9, ERROR_OP = 10,
};

int g_sock = -1;

void die(const char *msg) {
  std::fprintf(stderr, "bridge_client: %s\n", msg);
  std::exit(1);
}

void send_all(const uint8_t *p, size_t n) {
  while (n) {
    ssize_t w = ::send(g_sock, p, n, 0);
    if (w <= 0) die("send failed");
    p += w;
    n -= (size_t)w;
  }
}

void recv_all(uint8_t *p, size_t n) {
  while (n) {
    ssize_t r = ::recv(g_sock, p, n, 0);
    if (r <= 0) die("connection closed");
    p += r;
    n -= (size_t)r;
  }
}

void put_u32le(uint8_t *p, uint32_t v) {
  p[0] = v; p[1] = v >> 8; p[2] = v >> 16; p[3] = v >> 24;
}
uint32_t get_u32le(const uint8_t *p) {
  return (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
         ((uint32_t)p[3] << 24);
}
void put_f64le(uint8_t *p, double v) { std::memcpy(p, &v, 8); }
double get_f64le(const uint8_t *p) {
  double v;
  std::memcpy(&v, p, 8);
  return v;
}

void frame_send(const std::vector<uint8_t> &body) {
  uint8_t hdr[4];
  put_u32le(hdr, (uint32_t)body.size());
  send_all(hdr, 4);
  send_all(body.data(), body.size());
}

std::vector<uint8_t> frame_recv() {
  uint8_t hdr[4];
  recv_all(hdr, 4);
  uint32_t len = get_u32le(hdr);
  if (len == 0 || len > (1u << 20)) die("bad frame length");
  std::vector<uint8_t> body(len);
  recv_all(body.data(), len);
  return body;
}

void send_hello(uint32_t id) {
  std::vector<uint8_t> b(5);
  b[0] = HELLO;
  put_u32le(&b[1], id);
  frame_send(b);
}

void send_step(double dt) {
  std::vector<uint8_t> b(9);
  b[0] = STEP;
  put_f64le(&b[1], dt);
  frame_send(b);
}

void send_kill(uint32_t id) {
  std::vector<uint8_t> b(5);
  b[0] = KILL;
  put_u32le(&b[1], id);
  frame_send(b);
}

void send_bye() {
  frame_send({BYE});
}

void send_datagram(uint32_t src, uint32_t dst, const uint8_t *payload,
                   int len) {
  std::vector<uint8_t> b(9 + len);
  b[0] = SEND;
  put_u32le(&b[1], src);
  put_u32le(&b[5], dst);
  std::memcpy(&b[9], payload, len);
  frame_send(b);
}

// --------------------------------------------------------------- SWIM core

enum Status : uint8_t { ALIVE = 0, SUSPECT = 1, DEAD = 2 };

struct Member {
  Status status = ALIVE;
  uint32_t incarnation = 0;
};

struct GossipEntry {
  Status status;
  uint32_t incarnation;
  uint32_t origin;
  int sends = 0;
};

struct Timer {
  double at;
  int kind;       // 0=tick 1=probe_timeout 2=period_end 3=susp_expire
  //                 4=relay_expire
  uint64_t a = 0;
  uint64_t b = 0;
  bool cancelled = false;
};

struct Probe {
  uint32_t target;
  bool acked = false;
};

struct Relay {
  uint32_t requester;
  uint32_t rseq;
};

struct Swim {
  uint32_t id;
  double period = 1.0;
  int k_indirect = 3;
  int max_piggyback = 6;
  double suspicion_mult = 5.0;
  double retransmit_mult = 4.0;

  double now = 0.0;
  uint32_t inc_self = 0;
  uint64_t seq_next = 1;
  uint64_t rng = 0x9E3779B97F4A7C15ull;

  std::map<uint32_t, Member> members;           // excludes self
  std::map<uint32_t, GossipEntry> gossip;       // member -> freshest claim
  std::map<uint64_t, Probe> probes;
  std::map<uint64_t, Relay> relays;
  std::map<uint32_t, double> susp_started;      // member -> start (info)
  std::vector<Timer> timers;
  std::vector<uint32_t> probe_order;
  size_t probe_pos = 0;

  uint64_t rand64() {
    rng ^= rng << 13; rng ^= rng >> 7; rng ^= rng << 17;
    return rng;
  }

  double log_n() {
    double n = std::max((double)(members.size() + 1), 10.0);
    return std::max(1.0, std::log10(n));
  }
  int retransmit_limit() {
    return std::max(1, (int)std::ceil(retransmit_mult * log_n()));
  }
  double suspicion_timeout() { return suspicion_mult * log_n() * period; }
  double probe_timeout() { return 0.3 * period; }

  void add_timer(double delay, int kind, uint64_t a = 0, uint64_t b = 0) {
    timers.push_back({now + delay, kind, a, b, false});
  }

  // ---- membership lattice (docs/PROTOCOL.md §2) ----
  bool apply(uint32_t m, Status st, uint32_t inc) {
    Member &e = members[m];                 // inserts ALIVE(0) if new
    // precedence: DEAD sticky, higher incarnation wins, then
    // DEAD > SUSPECT > ALIVE at equal incarnation
    bool better;
    if (e.status == DEAD) {
      better = false;
    } else if (st == DEAD) {
      better = true;
    } else if (inc != e.incarnation) {
      better = inc > e.incarnation;
    } else {
      better = st > e.status;
    }
    if (!better) return false;
    e.status = st;
    e.incarnation = inc;
    return true;
  }

  void enqueue(uint32_t m, Status st, uint32_t inc, uint32_t origin) {
    gossip[m] = GossipEntry{st, inc, origin, 0};
  }

  void note_member(uint32_t m) {
    if (m == id) return;
    if (!members.count(m)) {
      members[m] = Member{};
      probe_order.push_back(m);
      enqueue(m, ALIVE, 0, id);
    }
  }

  void apply_and_gossip(uint32_t m, Status st, uint32_t inc,
                        uint32_t origin) {
    if (m == id) {
      // claim about us: refute suspicion (death is sticky, keep running)
      if (st == SUSPECT && inc >= inc_self) {
        inc_self = inc + 1;
        enqueue(id, ALIVE, inc_self, id);
      }
      return;
    }
    note_member(m);
    if (!apply(m, st, inc)) return;
    enqueue(m, st, inc, origin);
    if (st == SUSPECT) {
      susp_started[m] = now;
      add_timer(suspicion_timeout(), 3, m, inc);
    } else {
      susp_started.erase(m);
    }
  }

  // ---- piggyback ----
  int fill_gossip(WireMsg *msg) {
    // fewest-sends-first selection of <= B live entries
    std::vector<std::pair<int, uint32_t>> order;
    int limit = retransmit_limit();
    for (auto &kv : gossip)
      if (kv.second.sends < limit)
        order.push_back({kv.second.sends, kv.first});
    std::sort(order.begin(), order.end());
    int nsel = std::min((int)order.size(), max_piggyback);
    msg->n_gossip = (uint16_t)nsel;
    for (int i = 0; i < nsel; ++i) {
      uint32_t m = order[i].second;
      GossipEntry &e = gossip[m];
      e.sends++;
      WireUpd &u = msg->gossip[i];
      u.member = m;
      u.status = (uint8_t)e.status;
      u.incarnation = e.incarnation;
      u.origin = e.origin;
      u.addr.host_len = 3;
      std::memcpy(u.addr.host, "sim", 3);
      u.addr.port = m;
    }
    return nsel;
  }

  void transmit(uint32_t dst, WireMsg *msg) {
    uint8_t buf[65536];
    int n = swim_encode(msg, buf, sizeof buf);
    if (n < 0) die("encode failed");
    send_datagram(id, dst, buf, n);
  }

  WireMsg make(uint8_t kind) {
    WireMsg m;
    std::memset(&m, 0, sizeof m);
    m.kind = kind;
    m.sender = id;
    return m;
  }

  // ---- protocol tick ----
  void tick() {
    add_timer(period, 0);
    if (probe_order.empty()) return;
    if (probe_pos >= probe_order.size()) {
      // reshuffle each epoch (SWIM §4.3 randomized round-robin)
      for (size_t i = probe_order.size(); i > 1; --i)
        std::swap(probe_order[i - 1], probe_order[rand64() % i]);
      probe_pos = 0;
    }
    uint32_t target = probe_order[probe_pos++];
    uint64_t seq = seq_next++;
    probes[seq] = Probe{target};
    WireMsg m = make(kPing);
    m.probe_seq = (uint32_t)seq;
    fill_gossip(&m);
    transmit(target, &m);
    add_timer(probe_timeout(), 1, seq);
    add_timer(0.95 * period, 2, seq);
  }

  void on_probe_timeout(uint64_t seq) {
    auto it = probes.find(seq);
    if (it == probes.end() || it->second.acked) return;
    uint32_t target = it->second.target;
    // k distinct live proxies (excluding self, the target, and anyone
    // not believed ALIVE — vanilla SWIM samples without replacement)
    std::vector<uint32_t> pool;
    for (auto &kv : members)
      if (kv.first != target && kv.second.status == ALIVE)
        pool.push_back(kv.first);
    for (int i = 0; i < k_indirect && !pool.empty(); ++i) {
      size_t pick = rand64() % pool.size();
      uint32_t p = pool[pick];
      pool.erase(pool.begin() + pick);
      WireMsg m = make(kPingReq);
      m.probe_seq = (uint32_t)seq;
      m.target = target;
      m.target_addr.host_len = 3;
      std::memcpy(m.target_addr.host, "sim", 3);
      m.target_addr.port = target;
      fill_gossip(&m);
      transmit(p, &m);
    }
  }

  void on_period_end(uint64_t seq) {
    auto it = probes.find(seq);
    if (it == probes.end()) return;
    Probe p = it->second;
    probes.erase(it);
    if (p.acked) return;
    auto &e = members[p.target];
    if (e.status == ALIVE)
      apply_and_gossip(p.target, SUSPECT, e.incarnation, id);
  }

  void on_susp_expired(uint32_t m, uint32_t inc) {
    auto it = members.find(m);
    if (it == members.end() || it->second.status != SUSPECT ||
        it->second.incarnation != inc)
      return;
    apply_and_gossip(m, DEAD, it->second.incarnation, id);
  }

  // ---- receive ----
  void on_datagram(uint32_t src, const uint8_t *buf, int len) {
    WireMsg m;
    if (swim_decode(buf, len, &m) != 0) return;
    note_member(m.sender);
    for (int i = 0; i < m.n_gossip; ++i) {
      const WireUpd &u = m.gossip[i];
      apply_and_gossip(u.member, (Status)u.status, u.incarnation, u.origin);
    }
    switch (m.kind) {
      case kPing: {
        WireMsg a = make(kAck);
        a.probe_seq = m.probe_seq;
        a.on_behalf = m.on_behalf;
        fill_gossip(&a);
        transmit(m.sender, &a);
        break;
      }
      case kPingReq: {
        uint64_t sub = seq_next++;
        relays[sub] = Relay{m.sender, m.probe_seq};
        WireMsg p = make(kPing);
        p.probe_seq = (uint32_t)sub;
        p.on_behalf = m.sender;
        fill_gossip(&p);
        transmit(m.target_addr.port, &p);
        add_timer(probe_timeout(), 4, sub);
        break;
      }
      case kAck: {
        auto rit = relays.find(m.probe_seq);
        if (rit != relays.end()) {
          Relay r = rit->second;
          relays.erase(rit);
          WireMsg a = make(kAck);
          a.probe_seq = r.rseq;
          a.on_behalf = m.sender;
          fill_gossip(&a);
          transmit(r.requester, &a);
          break;
        }
        auto pit = probes.find(m.probe_seq);
        if (pit != probes.end()) pit->second.acked = true;
        break;
      }
      case kJoin: {
        // snapshot reply (chunked; our table is small)
        WireMsg r = make(kJoinReply);
        int i = 0;
        for (auto &kv : members) {
          if (i >= 200) break;
          WireUpd &u = r.gossip[i++];
          u.member = kv.first;
          u.status = (uint8_t)kv.second.status;
          u.incarnation = kv.second.incarnation;
          u.origin = id;
          u.addr.host_len = 3;
          std::memcpy(u.addr.host, "sim", 3);
          u.addr.port = kv.first;
        }
        r.n_gossip = (uint16_t)i;
        transmit(m.sender, &r);
        break;
      }
      default:
        break;     // kJoinReply/kNack: gossip merge already did the work
    }
  }

  // ---- virtual-time advance: fire timers in order up to `to` ----
  void advance_to(double to) {
    for (;;) {
      int best = -1;
      for (size_t i = 0; i < timers.size(); ++i)
        if (!timers[i].cancelled && timers[i].at <= to + 1e-12 &&
            (best < 0 || timers[i].at < timers[best].at))
          best = (int)i;
      if (best < 0) break;
      Timer t = timers[best];
      timers.erase(timers.begin() + best);
      now = std::max(now, t.at);
      switch (t.kind) {
        case 0: tick(); break;
        case 1: on_probe_timeout(t.a); break;
        case 2: on_period_end(t.a); break;
        case 3: on_susp_expired((uint32_t)t.a, (uint32_t)t.b); break;
        case 4: relays.erase(t.a); break;
      }
    }
    now = std::max(now, to);
  }
};

}  // namespace

int main(int argc, char **argv) {
  if (argc < 6)
    die("usage: bridge_client HOST PORT NODE_ID SEED_ID DURATION "
        "[QUANTUM] [KILL_ID KILL_AT]");
  const char *host = argv[1];
  int port = std::atoi(argv[2]);
  uint32_t node_id = (uint32_t)std::atoll(argv[3]);
  uint32_t seed_id = (uint32_t)std::atoll(argv[4]);
  double duration = std::atof(argv[5]);
  double quantum = argc > 6 ? std::atof(argv[6]) : 0.25;
  long kill_id = argc > 8 ? std::atol(argv[7]) : -1;
  double kill_at = argc > 8 ? std::atof(argv[8]) : -1.0;

  struct addrinfo hints = {}, *res;
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  char portbuf[16];
  std::snprintf(portbuf, sizeof portbuf, "%d", port);
  if (getaddrinfo(host, portbuf, &hints, &res) != 0 || !res)
    die("resolve failed");
  g_sock = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
  if (g_sock < 0 || ::connect(g_sock, res->ai_addr, res->ai_addrlen) != 0)
    die("connect failed");
  freeaddrinfo(res);

  send_hello(node_id);
  auto wf = frame_recv();
  if (wf[0] == ERROR_OP) die("server rejected node id");
  if (wf[0] != WELCOME) die("expected WELCOME");

  Swim node;
  node.id = node_id;
  node.now = get_f64le(&wf[5]);

  // JOIN the cluster through the seed, then start ticking (randomized
  // first-tick offset, as core/node.py does)
  node.note_member(seed_id);
  {
    WireMsg j = node.make(kJoin);
    node.transmit(seed_id, &j);
  }
  node.add_timer(0.5 * node.period, 0);

  bool killed = false;
  double end = node.now + duration;
  while (node.now < end - 1e-9) {
    if (kill_id >= 0 && !killed && node.now >= kill_at) {
      send_kill((uint32_t)kill_id);
      killed = true;
    }
    double dt = std::min(quantum, end - node.now);
    send_step(dt);
    std::vector<std::pair<uint32_t, std::vector<uint8_t>>> deliveries;
    double server_now = node.now;
    for (;;) {
      auto f = frame_recv();
      if (f[0] == TIME) {
        server_now = get_f64le(&f[1]);
        break;
      }
      if (f[0] != DELIVER) die("unexpected frame mid-step");
      uint32_t src = get_u32le(&f[1]);
      deliveries.emplace_back(
          src, std::vector<uint8_t>(f.begin() + 9, f.end()));
    }
    for (auto &d : deliveries)
      node.on_datagram(d.first, d.second.data(), (int)d.second.size());
    node.advance_to(server_now);
  }
  send_bye();
  ::close(g_sock);

  for (auto &kv : node.members)
    std::printf("member %u %u %u\n", kv.first,
                (unsigned)kv.second.status, kv.second.incarnation);
  std::printf("self %u %u\n", node.id, node.inc_self);
  return 0;
}
