"""swim-tpu command-line interface.

Subcommands:
  info      — derived protocol constants for a given cluster size
  demo      — the reference's stock demo: an N-node in-process cluster
              (default 32, k=3, 1 s period) on deterministic virtual time,
              with optional kills, loss, and partition injection
  simulate  — the vectorized TPU engine: N up to millions, faults as
              tensors, metrics as JSON
  observe   — analyze telemetry artifacts offline (flight-recorder
              dumps, trace-span JSONL) or tail a live dump / a
              /metrics URL as a refreshing terminal view
  profile   — phase-level step attribution (obs/prof.py): per-phase
              device-synced timings, modeled vs achieved HBM/ICI
              bytes, floor-or-fixable verdicts, optional device-trace
              top-op table
  trend     — jax-free per-tier bench trajectories over BENCH_r*.json
              + bench_results/, with a --check regression gate
  audit     — static contract audit (analysis/audit.py): retrace
              budget, donation coverage, wire payloads, ICI tally
              completeness, barrier survival, hot-path hygiene —
              verified deviceless against the jaxpr and AOT HLO
  serve     — the serving hub (swim_tpu/serve): 'serve bench' runs the
              10^3-client load harness against a >=1M-node ring engine
              and defends admission rate + echo RTT p50/p99 under a
              replay/duplication storm (bitwise state parity); 'serve
              trace' attributes the echo-RTT p99 tail to the hub's
              five period phases (obs/servetrace.py) and writes the
              byte-stable bench_results/serve_trace.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys


ENGINES = ("auto", "dense", "rumor", "shard", "ring", "ringshard")


def _cmd_info(args: argparse.Namespace) -> int:
    import swim_tpu

    cfg = swim_tpu.SwimConfig(n_nodes=args.nodes)
    print(json.dumps({
        "version": swim_tpu.__version__,
        "n_nodes": cfg.n_nodes,
        "k_indirect": cfg.k_indirect,
        "protocol_period_s": cfg.protocol_period,
        "suspicion_periods": cfg.suspicion_periods,
        "retransmit_limit": cfg.retransmit_limit,
        "max_piggyback": cfg.max_piggyback,
        "rumor_slots": cfg.rumor_slots,
    }, indent=2))
    return 0


def _cmd_demo(args: argparse.Namespace) -> int:
    from swim_tpu import SwimConfig, Status
    from swim_tpu.core.cluster import SimCluster

    cfg = SwimConfig(n_nodes=args.nodes, lifeguard=args.lifeguard)
    cluster = SimCluster(cfg, seed=args.seed, loss=args.loss)

    events = []
    for node in cluster.nodes:
        def listener(member, old, new, _id=node.id):
            if old is not None and old.status != new.status:
                events.append((cluster.clock.now(), _id, member,
                               new.status.name, new.incarnation))
        node.members.listeners.append(listener)

    cluster.start()
    cluster.run(args.settle)
    print(f"# {args.nodes}-node in-process cluster converged "
          f"(k={cfg.k_indirect}, period={cfg.protocol_period}s, "
          f"seed={args.seed}, loss={args.loss})")

    for victim in args.kill:
        print(f"# t={cluster.clock.now():.1f}s: killing node {victim}")
        cluster.kill(victim)
    cluster.run(args.duration)

    if not args.quiet:
        for t, observer, member, status, inc in events[-args.tail:]:
            print(f"t={t:7.2f}s  node{observer:<4d} sees node{member:<4d} "
                  f"{status}@{inc}")
    live = [i for i in range(args.nodes) if i not in set(args.kill)]
    summary = {
        "sim_seconds": round(cluster.clock.now(), 2),
        "messages_sent": cluster.network.sent,
        "messages_delivered": cluster.network.delivered,
        "status_transitions": len(events),
        "killed": args.kill,
        "all_kills_detected_everywhere": all(
            cluster.all_consider(v, Status.DEAD, among=live)
            for v in args.kill),
        "false_deaths": sum(
            1 for m in live for i in live
            if cluster.nodes[i].members.opinion(m).status == Status.DEAD),
        "refutations": sum(n.stats["refutations"] for n in cluster.nodes),
    }
    print(json.dumps(summary))
    return 0 if (summary["all_kills_detected_everywhere"] or not args.kill) \
        else 1


def _reject_sel_scope(resolved_engine: str, sel_scope: str) -> bool:
    """True (after printing the error) iff a non-wave --sel-scope was
    passed for an engine that would silently ignore it.  The knob only
    exists on the ring engines — refuse to run (and then mislabel) a run
    whose resolved engine ignores it (ADVICE r3: `study` guarded,
    `simulate` didn't; one shared guard for both)."""
    if sel_scope != "wave" and not resolved_engine.startswith("ring"):
        print(f"error: --sel-scope {sel_scope} has no effect on the "
              f"'{resolved_engine}' engine; pass --engine ring or "
              "ringshard", file=sys.stderr)
        return True
    return False


def _cmd_simulate(args: argparse.Namespace) -> int:
    import time

    import jax
    import numpy as np

    from swim_tpu import SwimConfig
    from swim_tpu.models import dense, rumor
    from swim_tpu.ops import lattice
    from swim_tpu.parallel import mesh as pmesh
    from swim_tpu.sim import experiments, faults

    engine = experiments.pick_engine(args.nodes, args.engine)
    if _reject_sel_scope(engine, args.sel_scope):
        return 2
    cfg = SwimConfig(n_nodes=args.nodes, suspicion_mult=args.suspicion_mult,
                     lifeguard=args.lifeguard,
                     ring_sel_scope=args.sel_scope)
    plan = faults.none(args.nodes)
    if args.loss:
        plan = faults.with_loss(plan, args.loss)
    if args.crash_fraction:
        plan = faults.with_random_crashes(
            plan, jax.random.key(args.seed + 1), args.crash_fraction,
            0, max(1, args.periods // 2))
    mesh = pmesh.make_mesh()
    if engine in ("shard", "ringshard"):
        if engine == "shard":
            from swim_tpu.parallel import shard_engine as par_mod
            state0 = rumor.init_state(cfg)
        else:
            from swim_tpu.models import ring
            from swim_tpu.parallel import ring_shard as par_mod
            state0 = ring.init_state(cfg)

        state, plan = par_mod.place(cfg, mesh, state0, plan)
        run_fn = par_mod.build_run(cfg, mesh, args.periods)

        def do_run(st):
            return run_fn(st, plan, jax.random.key(args.seed))
    else:
        if engine == "dense":
            mod = dense
        elif engine == "ring":
            from swim_tpu.models import ring as mod
        else:
            mod = rumor
        state = pmesh.shard_state(mod.init_state(cfg), mesh, n=args.nodes)
        plan = pmesh.shard_state(plan, mesh, n=args.nodes)

        def do_run(st):
            return mod.run(cfg, st, plan, jax.random.key(args.seed),
                           args.periods)
    import contextlib

    from swim_tpu.utils import profiling

    prof = (profiling.trace(args.profile) if args.profile
            else contextlib.nullcontext())
    t0 = time.perf_counter()
    with prof:
        state = do_run(state)
        jax.block_until_ready(state)
    dt = time.perf_counter() - t0

    crashed = np.asarray(plan.crash_step) <= args.periods
    live = ~crashed
    if engine == "dense":
        dead_views = np.asarray(lattice.is_dead(state.key))
    elif engine in ("ring", "ringshard"):
        dead_views = None          # summarized via the dissemination floor
    else:
        dead_views = np.asarray(lattice.is_dead(
            rumor.view_matrix(cfg, state))) if args.nodes <= 8192 else None
    out = {
        "nodes": args.nodes,
        "engine": engine,
        "periods": args.periods,
        "seconds": round(dt, 3),
        "periods_per_sec": round(args.periods / dt, 2),
        "crashed": int(crashed.sum()),
        "devices": len(jax.devices()),
        # self-describing throughput numbers (same rationale as
        # bench.py): a period-scope (deviation R5) run must never be
        # quotable as an exact wave-scope one
        **({"ring_sel_scope": cfg.ring_sel_scope}
           if engine in ("ring", "ringshard") else {}),
    }
    if dead_views is not None:
        detected = (dead_views[np.ix_(live, crashed)].all(axis=0).sum()
                    if crashed.any() else 0)
        out["crashed_detected_by_all_live"] = int(detected)
        out["false_deaths"] = int(dead_views[np.ix_(live, live)].sum())
    else:
        gone = np.asarray(lattice.is_dead(state.gone_key))
        out["tombstoned"] = int(gone.sum())
        out["tombstoned_crashed"] = int((gone & crashed).sum())
        out["overflow"] = int(state.overflow)
    print(json.dumps(out))
    return 0


def _cmd_study(args: argparse.Namespace) -> int:
    from swim_tpu.sim import experiments

    if args.mem_report:
        if args.study != "detection":
            print("error: --mem-report is a detection-study option",
                  file=sys.stderr)
            return 2
        resolved = experiments.pick_engine(args.nodes, args.engine)
        if args.engine != "auto" and not resolved.startswith("ring"):
            print("error: --mem-report accounts the ring study "
                  "pipeline; pass --engine ring or ringshard",
                  file=sys.stderr)
            return 2
        from swim_tpu.obs import memwall

        cfg_kw = {}
        if args.sel_scope != "wave":
            cfg_kw["ring_sel_scope"] = args.sel_scope
        try:
            report = memwall.study_memory_analysis(
                args.nodes, periods=args.periods,
                crash_fraction=args.crash_fraction,
                variant="stacked" if args.stream == "off" else "stream",
                engine=("ringshard" if resolved == "ringshard"
                        else "ring"),
                platform=args.mem_report,
                probe=args.probe or "pull", **cfg_kw)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        print(json.dumps(report))
        return 0
    kw = dict(n=args.nodes, periods=args.periods, seed=args.seed,
              engine=args.engine)
    if args.sel_scope != "wave":
        resolved = experiments.pick_engine(args.nodes, args.engine)
        if _reject_sel_scope(resolved, args.sel_scope):
            return 2
        kw["ring_sel_scope"] = args.sel_scope   # flows into SwimConfig
    if args.probe:
        resolved = experiments.pick_engine(args.nodes, args.engine)
        if not resolved.startswith("ring"):
            print(f"error: --probe {args.probe} has no effect on the "
                  f"'{resolved}' engine; pass --engine ring or "
                  "ringshard", file=sys.stderr)
            return 2
        kw["ring_probe"] = args.probe   # flows into SwimConfig
    if args.telemetry:
        kw["telemetry"] = True          # flows into SwimConfig
    if args.flight_record:
        if args.study != "detection":
            print("error: --flight-record is a detection-study option",
                  file=sys.stderr)
            return 2
        kw["telemetry"] = True
        kw["flight_record"] = args.flight_record
    if args.study != "detection" and (args.stream != "auto"
                                      or args.checkpoint_dir):
        print("error: --stream/--checkpoint-dir are detection-study "
              "options", file=sys.stderr)
        return 2
    if args.study == "detection":
        kw["crash_fraction"] = args.crash_fraction
        if args.stream != "auto":
            kw["stream"] = args.stream == "on"
        if args.checkpoint_dir:
            kw["checkpoint_dir"] = args.checkpoint_dir
            kw["checkpoint_every"] = args.checkpoint_every
    elif args.study == "fp_sweep":
        if args.losses:
            kw["losses"] = tuple(args.losses)
        kw["partition"] = not args.no_partition
    elif args.study == "suspicion_sweep":
        kw["mults"] = tuple(args.mults)
        kw["crash_fraction"] = args.crash_fraction
        kw["loss"] = args.loss
        if args.losses:
            kw["losses"] = tuple(args.losses)
    elif args.study == "lifeguard":
        kw["crash_fraction"] = args.crash_fraction
        kw["loss"] = args.loss
        kw["budget_arms"] = args.budget_arms
    out = experiments.STUDIES[args.study](**kw)
    if kw.get("ring_sel_scope"):
        # self-describing results: a period-scope (deviation R5) study
        # must never be quotable as an exact wave-scope one
        out = {**out, "ring_sel_scope": kw["ring_sel_scope"]}
    print(json.dumps(out))
    return 0


def _scrape_metrics(url: str) -> dict:
    """One GET of a Prometheus /metrics endpoint, reduced to the
    swim_health_* gauge set and counter totals (summed across node
    labels) — the live-view payload for `observe --follow URL`."""
    import urllib.request

    with urllib.request.urlopen(url, timeout=10) as resp:
        text = resp.read().decode()
    health: dict[str, float] = {}
    counters: dict[str, float] = {}
    build = ""
    for line in text.splitlines():
        if line.startswith("#") or not line.strip():
            continue
        name_labels, _, val = line.rpartition(" ")
        name = name_labels.split("{", 1)[0]
        try:
            v = float(val)
        except ValueError:
            continue
        if name.startswith("swim_health_"):
            health[name[len("swim_health_"):]] = max(
                v, health.get(name[len("swim_health_"):], 0.0))
        elif name.endswith("_total"):
            counters[name] = counters.get(name, 0.0) + v
        elif name == "swim_build_info":
            build = name_labels[len(name):]
    report: dict = {"kind": "metrics_scrape", "url": url,
                    "health": health, "counters": counters}
    if build:
        report["build_info"] = build
    return report


def _render_scrape(report: dict) -> str:
    status = int(report["health"].get("status", 0))
    lines = [f"metrics scrape · {report['url']}",
             f"health: {('ok', 'warn', 'ERROR')[min(status, 2)]}"]
    firing = [r for r, v in report["health"].items()
              if r != "status" and v > 0]
    for rule in firing:
        lines.append(f"  firing: {rule}")
    for name, v in sorted(report["counters"].items()):
        lines.append(f"  {name} {int(v)}")
    if report.get("build_info"):
        lines.append(f"  build {report['build_info']}")
    return "\n".join(lines)


def _cmd_observe(args: argparse.Namespace) -> int:
    import time

    from swim_tpu.obs import analyze

    is_url = (len(args.paths) == 1
              and args.paths[0].startswith(("http://", "https://")))
    if is_url and not args.follow and not args.json:
        args.follow = True      # a bare URL is a live view by definition

    def once() -> tuple[str, dict | None]:
        if is_url:
            report = _scrape_metrics(args.paths[0])
            return ((json.dumps(report, indent=2) if args.json
                     else _render_scrape(report)), report)
        report = analyze.analyze_paths(args.paths, window=args.window)
        return ((json.dumps(report, indent=2) if args.json
                 else analyze.render_report(report)), report)

    if not args.follow:
        try:
            text, report = once()
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        print(text)
        if args.check and report is not None \
                and not is_url and analyze.error_findings(report):
            return 1
        return 0

    i = 0
    while True:
        try:
            text, _ = once()
        except (OSError, ValueError) as e:
            text = f"(waiting: {e})"
        # redraw-in-place: clear screen + home, like watch(1)
        sys.stdout.write("\x1b[2J\x1b[H" + text + "\n")
        sys.stdout.flush()
        i += 1
        if args.iterations and i >= args.iterations:
            return 0
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


def _cmd_profile(args: argparse.Namespace) -> int:
    from swim_tpu import SwimConfig
    from swim_tpu.obs import prof as prof_mod

    # defaults are the 65k lean anchor (bench.py LEAN_ANCHOR): the
    # geometry every overhead/coverage contract is quoted at
    cfg = SwimConfig(
        n_nodes=args.nodes, ring_probe=args.probe,
        ring_sel_scope=args.sel_scope,
        suspicion_mult=args.suspicion_mult,
        retransmit_mult=args.retransmit_mult,
        k_indirect=args.k_indirect,
        ring_window_periods=args.window_periods,
        ring_view_c=args.view_c)
    report = prof_mod.profile_ring(
        cfg, settle=args.settle, reps=args.reps, seed=args.seed,
        crash_fraction=args.crash_fraction,
        trace_dir=args.trace or None, top_k=args.top)
    if args.out:
        path = prof_mod.save_artifact(
            report, None if args.out == "auto" else args.out)
        print(f"# wrote {path}", file=sys.stderr)
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print(prof_mod.render_report(report))
    if args.check and report["coverage_pct"] < report.get(
            "contract_coverage_pct", 95.0):
        return 1
    return 0


def _cmd_trend(args: argparse.Namespace) -> int:
    from swim_tpu.obs import trend

    argv = []
    if args.repo:
        argv += ["--repo", args.repo]
    argv += ["--threshold", str(args.threshold)]
    if args.json:
        argv.append("--json")
    if args.check:
        argv.append("--check")
    return trend.main(argv)


def _cmd_bridge(args: argparse.Namespace) -> int:
    from swim_tpu import SwimConfig
    from swim_tpu.bridge import BridgeServer

    cfg = SwimConfig(n_nodes=max(args.internal + 1, 2),
                     lifeguard=args.lifeguard)
    server = BridgeServer(cfg, n_internal=args.internal, seed=args.seed,
                          loss=args.loss, host=args.host, port=args.port,
                          metrics_port=args.metrics_port)
    server.start()
    out = {"listening": list(server.address),
           "internal_nodes": args.internal}
    if server.metrics_address is not None:
        out["metrics"] = list(server.metrics_address)
    print(json.dumps(out))
    server.join(timeout=args.timeout)
    return 0


def _cmd_scenario(args: argparse.Namespace) -> int:
    from swim_tpu.sim import scenario

    if args.action == "list":
        rows = []
        for name in sorted(scenario.LIBRARY):
            sc = scenario.LIBRARY[name]
            mode = sc.study or sc.engine
            rows.append((name, mode, sc.n,
                         sc.description.split(".  ")[0].rstrip(".")))
        if args.json:
            print(json.dumps([{"name": n, "mode": m, "n": nn, "about": d}
                              for n, m, nn, d in rows], indent=1))
        else:
            w = max(len(r[0]) for r in rows)
            for n, m, nn, d in rows:
                print(f"{n:<{w}}  {m:<9} n={nn:<7} {d}")
        return 0
    if args.action == "search":
        from swim_tpu.sim import search as scenario_search

        out = os.path.join(args.out_dir, "scenario_search_boundary.json")
        report = scenario_search.search(
            generations=args.generations, pop=args.pop, seed=args.seed,
            out=out)
        if args.json:
            print(json.dumps(report, indent=1, sort_keys=True,
                             default=str))
        else:
            b = report["boundary"]
            viols = report["explore"]["violations"]
            print(f"search: evaluated "
                  f"{report['explore']['evaluated']} candidates, "
                  f"{len(report['explore']['archive'])} behavior cells, "
                  f"{len(viols)} violation hits -> {out}")
            if b.get("found"):
                print(f"  flap false-dead boundary: clean at level "
                      f"{b['clean_level']}, violating at "
                      f"{b['violation_level']} (width {b['width']})")
        if args.check and not report["boundary"].get("found"):
            return 1
        return 0
    if args.name is None:
        print("scenario show/run need a scenario name "
              f"(one of {sorted(scenario.LIBRARY)})", file=sys.stderr)
        return 2
    sc = scenario.get(args.name)
    if args.action == "show":
        scenario.validate(sc)
        print(json.dumps(sc.spec_dict(), indent=1, sort_keys=True))
        return 0
    verdict, path = scenario.run(sc, out_dir=args.out_dir,
                                 batch=args.batch)
    if args.json:
        print(json.dumps(verdict, indent=1, sort_keys=True,
                         default=str))
    else:
        print(f"{sc.name}: {verdict['verdict']}  -> {path}")
        for c in verdict["checks"]:
            mark = "ok " if c["ok"] else "FAIL"
            detail = {k: v for k, v in c.items()
                      if k not in ("check", "ok", "fired")}
            print(f"  [{mark}] {c['check']} {json.dumps(detail, default=str)}")
    if args.check and verdict["verdict"] != "pass":
        return 1
    return 0


def _cmd_audit(args: argparse.Namespace) -> int:
    # the wire arms need the 8-device mesh regardless of --platform;
    # force_cpu is first-writer-wins and safe before any device query
    from swim_tpu.utils.platform import force_cpu

    force_cpu(8)
    from swim_tpu.analysis import audit

    report = audit.run_audit(wire_n=args.wire_n, retrace_n=args.retrace_n)
    if args.out:
        audit.write_report(report, args.out)
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        for contract in sorted(report["contracts"]):
            blob = report["contracts"][contract]
            print(f"[{blob['status']:>6}] {contract}")
            for row in blob["checks"]:
                mark = {"pass": ".", "waived": "w"}.get(row["status"], "F")
                print(f"   {mark} {row['arm']}: {row['detail']}")
        totals = report["totals"]
        print(f"{totals['checks_total']} checks, "
              f"{totals['failures']} failed, {totals['waived']} waived")
    ok, failures = audit.check_report(report)
    if args.check and not ok:
        for line in failures:
            print(f"AUDIT FAIL {line}", file=sys.stderr)
        return 1
    return 0


def _cmd_serve(args: argparse.Namespace) -> int:
    if args.action not in ("bench", "trace"):
        print("serve: actions are 'bench' and 'trace' (the embeddable "
              "hub API is swim_tpu.serve.ServeHub)", file=sys.stderr)
        return 2
    from swim_tpu.serve import load as serve_load

    if args.action == "trace":
        from swim_tpu.obs import analyze

        res = serve_load.run_trace(
            n_nodes=args.nodes, sessions=args.sessions,
            periods=args.periods, seed=args.seed,
            n_sockets=args.sockets, echo_samples=args.echo_samples,
            frontend=args.frontend)
        if args.out:
            os.makedirs(os.path.dirname(os.path.abspath(args.out)),
                        exist_ok=True)
            # byte-stable on re-read: sorted keys, no timestamps
            with open(args.out, "w") as f:
                json.dump(res, f, indent=1, sort_keys=True)
                f.write("\n")
        if args.json:
            print(json.dumps(res, indent=2, sort_keys=True))
        else:
            print(analyze.render_report(res, title="serve trace"))
            print(f"digests_match: {res['digests_match']}")
        return 0 if res.get("ok_parity") else 1

    res = serve_load.run_load(
        n_nodes=args.nodes, sessions=args.sessions,
        periods=args.periods, seed=args.seed,
        n_sockets=args.sockets, echo_samples=args.echo_samples,
        frontend=args.frontend)
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)),
                    exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(res, f, indent=1)
    print(json.dumps({k: v for k, v in res.items()
                      if k not in ("clean", "storm")}
                     if not args.json else res, indent=2))
    return 0 if res.get("ok_parity") else 1


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="swim-tpu",
        description="TPU-native SWIM failure-detection framework & simulator",
    )
    p.add_argument("--platform", default="default",
                   choices=("default", "cpu", "cpu8"),
                   help="JAX platform: 'cpu' forces the host CPU backend "
                        "(survives a broken TPU tunnel), 'cpu8' adds an "
                        "8-device virtual mesh for sharding work")
    sub = p.add_subparsers(dest="command", required=True)

    info = sub.add_parser("info", help="show derived protocol constants")
    info.add_argument("--nodes", type=int, default=32)
    info.set_defaults(fn=_cmd_info)

    demo = sub.add_parser(
        "demo", help="N-node in-process cluster (the reference's stock demo)")
    demo.add_argument("--nodes", type=int, default=32)
    demo.add_argument("--seed", type=int, default=0)
    demo.add_argument("--loss", type=float, default=0.0)
    demo.add_argument("--kill", type=int, nargs="*", default=[],
                      help="node ids to crash after settling")
    demo.add_argument("--settle", type=float, default=10.0,
                      help="seconds of sim time before injecting kills")
    demo.add_argument("--duration", type=float, default=30.0,
                      help="seconds of sim time after kills")
    demo.add_argument("--lifeguard", action="store_true")
    demo.add_argument("--tail", type=int, default=20,
                      help="show the last K status transitions")
    demo.add_argument("--quiet", action="store_true")
    demo.set_defaults(fn=_cmd_demo)

    sim = sub.add_parser("simulate", help="vectorized TPU simulation")
    sim.add_argument("--nodes", type=int, default=1024)
    sim.add_argument("--periods", type=int, default=100)
    sim.add_argument("--seed", type=int, default=0)
    sim.add_argument("--loss", type=float, default=0.0)
    sim.add_argument("--crash-fraction", type=float, default=0.01)
    sim.add_argument("--suspicion-mult", type=float, default=5.0)
    sim.add_argument("--lifeguard", action="store_true")
    sim.add_argument("--engine", choices=ENGINES,
                     default="auto")
    sim.add_argument("--sel-scope", choices=("wave", "period"),
                     default="wave",
                     help="ring piggyback-selection freshness (deviation "
                          "R5: 'period' selects once per period from "
                          "start-of-period state — the throughput mode)")
    sim.add_argument("--profile", default="",
                     help="write a jax.profiler device trace to this dir")
    sim.set_defaults(fn=_cmd_simulate)

    st = sub.add_parser(
        "study", help="BASELINE.md studies (configs 2-5) → JSON")
    st.add_argument("study", choices=("detection", "fp_sweep",
                                      "suspicion_sweep", "lifeguard"))
    st.add_argument("--nodes", type=int, default=1000)
    st.add_argument("--periods", type=int, default=100)
    st.add_argument("--seed", type=int, default=0)
    st.add_argument("--engine", choices=ENGINES,
                    default="auto")
    st.add_argument("--crash-fraction", type=float, default=0.01)
    st.add_argument("--loss", type=float, default=0.05)
    st.add_argument("--losses", type=float, nargs="*", default=None,
                    help="loss-rate grid (fp_sweep; also turns "
                         "suspicion_sweep into a mults x losses grid)")
    st.add_argument("--mults", type=float, nargs="*",
                    default=[2.0, 3.0, 5.0, 8.0])
    st.add_argument("--no-partition", action="store_true")
    st.add_argument("--sel-scope", choices=("wave", "period"),
                    default="wave",
                    help="ring piggyback-selection freshness (deviation "
                         "R5; 'period' = the throughput mode)")
    st.add_argument("--budget-arms", action="store_true",
                    help="lifeguard study: add ring_orig_words=8 twin "
                         "arms (budget-vs-LHA attribution)")
    st.add_argument("--telemetry", action="store_true",
                    help="collect per-period engine telemetry "
                         "(swim_tpu/obs EngineFrame) inside the study "
                         "scan; adds a 'telemetry' digest to the JSON. "
                         "Protocol state is bitwise identical either way")
    st.add_argument("--flight-record", default=None, metavar="PATH",
                    help="detection study: always dump the flight "
                         "recorder's JSONL to PATH (implies --telemetry; "
                         "without this, a dump still fires on anomaly)")
    st.add_argument("--probe", choices=("rotor", "pull"), default=None,
                    help="ring probe pattern override. The detection "
                         "study defaults BOTH ring layouts (ring and "
                         "ringshard) to 'pull' (law-preserving uniform "
                         "probing — the paper's e/(e-1) regime); pass "
                         "'rotor' to opt into the bounded-detection "
                         "throughput mode (deviation R1). Other "
                         "studies default to rotor.")
    st.add_argument("--stream", choices=("auto", "on", "off"),
                    default="auto",
                    help="detection study: drive the ring engines "
                         "through the streaming O(crashes) milestone "
                         "scan instead of the stacked [periods, N] "
                         "track. 'auto' streams at >= 2M nodes (or "
                         "whenever checkpointing is on); milestones "
                         "and series are bitwise identical either way")
    st.add_argument("--checkpoint-dir", default=None, metavar="DIR",
                    help="detection study: per-shard mid-study "
                         "checkpoints in DIR; when DIR already holds a "
                         "snapshot the study RESUMES from it, bitwise "
                         "identical to an uninterrupted run")
    st.add_argument("--checkpoint-every", type=int, default=0,
                    metavar="PERIODS",
                    help="checkpoint cadence in periods (default: one "
                         "snapshot per streaming chunk boundary)")
    st.add_argument("--mem-report", choices=("cpu", "tpu"), default=None,
                    help="don't run the study: AOT-compile its jitted "
                         "step at this shape and print XLA's "
                         "memory_analysis verdict against the one-chip "
                         "HBM budget as JSON ('tpu' compiles against a "
                         "deviceless v5e topology — the honest verdict; "
                         "'cpu' works anywhere but double-counts the "
                         "donated state)")
    st.set_defaults(fn=_cmd_study)

    ob = sub.add_parser(
        "observe", help="analyze telemetry artifacts (flight-recorder "
                        "dump / trace-span JSONL) or tail a live dump "
                        "or /metrics URL")
    ob.add_argument("paths", nargs="+",
                    help="recorder dump and/or span JSONL paths, or ONE "
                         "http(s)://host:port/metrics URL")
    ob.add_argument("--json", action="store_true",
                    help="emit the raw analyzer report as JSON")
    ob.add_argument("--follow", action="store_true",
                    help="refreshing terminal view: re-analyze the "
                         "file(s) or re-scrape the URL every --interval")
    ob.add_argument("--interval", type=float, default=2.0)
    ob.add_argument("--iterations", type=int, default=0,
                    help="stop --follow after K refreshes (0 = until ^C)")
    ob.add_argument("--window", type=int, default=16,
                    help="health-rule sliding window, in periods")
    ob.add_argument("--check", action="store_true",
                    help="exit 1 if any error-severity health finding "
                         "(CI gate)")
    ob.set_defaults(fn=_cmd_observe)

    sc = sub.add_parser(
        "scenario", help="compile & run adversarial fault scenarios "
                         "(sim/scenario.py library) gated by the "
                         "observatory")
    sc.add_argument("action", choices=("list", "show", "run", "search"))
    sc.add_argument("name", nargs="?", default=None,
                    help="library scenario name (hyphens ok: "
                         "rack-outage, flap, flap-boundary, gray-10pct, "
                         "replay-storm, baseline-config3, lean-fidelity)")
    sc.add_argument("--out-dir", default="bench_results",
                    help="where verdict artifacts + telemetry dumps go")
    sc.add_argument("--json", action="store_true",
                    help="emit the full verdict JSON")
    sc.add_argument("--check", action="store_true",
                    help="exit 1 unless every scenario check passes "
                         "(CI gate)")
    sc.add_argument("--batch", action="store_true",
                    help="run the engine arms as one vmapped fleet per "
                         "shared config (sim/faults.py ProgramBatch) — "
                         "verdict is bitwise-identical to serial")
    sc.add_argument("--generations", type=int, default=4,
                    help="[search] mutation generations")
    sc.add_argument("--pop", type=int, default=16,
                    help="[search] candidates per vmapped generation")
    sc.add_argument("--seed", type=int, default=0,
                    help="[search] deterministic search seed")
    sc.set_defaults(fn=_cmd_scenario)

    pr = sub.add_parser(
        "profile", help="phase-level step attribution with roofline "
                        "byte accounting (obs/prof.py)")
    pr.add_argument("--nodes", type=int, default=65536)
    pr.add_argument("--settle", type=int, default=2,
                    help="periods to run before timing (steady state)")
    pr.add_argument("--reps", type=int, default=5,
                    help="timed dispatches per program (best-of)")
    pr.add_argument("--seed", type=int, default=0)
    pr.add_argument("--crash-fraction", type=float, default=0.001)
    pr.add_argument("--probe", choices=("rotor", "pull"), default="rotor")
    pr.add_argument("--sel-scope", choices=("wave", "period"),
                    default="period",
                    help="default 'period' — the lean-anchor/throughput "
                         "mode whose fused path exposes all six phases")
    pr.add_argument("--suspicion-mult", type=float, default=2.0)
    pr.add_argument("--retransmit-mult", type=float, default=2.0)
    pr.add_argument("--k-indirect", type=int, default=1)
    pr.add_argument("--window-periods", type=int, default=3)
    pr.add_argument("--view-c", type=int, default=2)
    pr.add_argument("--trace", default="",
                    help="also capture a jax.profiler device trace to "
                         "this dir and attach the top-op table")
    pr.add_argument("--top", type=int, default=5,
                    help="top-K ops from the device trace")
    pr.add_argument("--json", action="store_true",
                    help="emit the raw report as JSON")
    pr.add_argument("--out", default="",
                    help="write the report artifact ('auto' = "
                         "bench_results/profile_phases.json, the file "
                         "the bridge's swim_prof_* gauges serve)")
    pr.add_argument("--check", action="store_true",
                    help="exit 1 if attribution coverage misses the "
                         "≥95%% contract")
    pr.set_defaults(fn=_cmd_profile)

    tr = sub.add_parser(
        "trend", help="per-tier bench p/s trajectories + regression "
                      "gate (jax-free; obs/trend.py)")
    tr.add_argument("--repo", default=None,
                    help="repo root holding BENCH_r*.json + "
                         "bench_results/ (default: auto-detect)")
    tr.add_argument("--threshold", type=float, default=0.10)
    tr.add_argument("--json", action="store_true")
    tr.add_argument("--check", action="store_true",
                    help="exit 1 when any tier regresses >threshold "
                         "vs its last-good round")
    tr.set_defaults(fn=_cmd_trend)

    br = sub.add_parser(
        "bridge", help="serve a simulated cluster for an external core "
                       "(swim_tpu/bridge/protocol.py)")
    br.add_argument("--internal", type=int, default=8,
                    help="in-process nodes to pre-populate")
    br.add_argument("--host", default="127.0.0.1")
    br.add_argument("--port", type=int, default=0)
    br.add_argument("--seed", type=int, default=0)
    br.add_argument("--loss", type=float, default=0.0)
    br.add_argument("--lifeguard", action="store_true")
    br.add_argument("--timeout", type=float, default=3600.0)
    br.add_argument("--metrics-port", type=int, default=None,
                    help="serve Prometheus text exposition on GET "
                         "/metrics at this port (0 = ephemeral)")
    br.set_defaults(fn=_cmd_bridge)

    au = sub.add_parser(
        "audit", help="static contract audit: retrace/donation/wire/"
                      "tally/barrier/hygiene invariants verified against "
                      "the jaxpr and AOT HLO, deviceless "
                      "(swim_tpu/analysis/audit.py)")
    au.add_argument("--out", default="bench_results/audit_report.json",
                    help="report path ('' skips writing)")
    au.add_argument("--wire-n", type=int, default=512,
                    help="node count for the 2x2 sharded wire arms")
    au.add_argument("--retrace-n", type=int, default=256,
                    help="node count for retrace/donation/barrier arms")
    au.add_argument("--json", action="store_true",
                    help="print the full report JSON")
    au.add_argument("--check", action="store_true",
                    help="exit 1 on any unwaived contract failure")
    au.set_defaults(fn=_cmd_audit)

    sv = sub.add_parser(
        "serve", help="serving hub: async session admission over a "
                      "free-running ring engine (swim_tpu/serve)")
    sv.add_argument("action", choices=("bench", "trace"),
                    help="'bench': the 10^3-client load harness "
                         "(clean arm vs replay/duplication storm; "
                         "exit 1 unless the arms stay bitwise-parity); "
                         "'trace': tail-latency attribution — an "
                         "untraced parity arm then a traced arm whose "
                         "phase timeline decomposes the echo-RTT p99 "
                         "(exit 1 unless bitwise parity AND >=90% of "
                         "the tail is attributed)")
    sv.add_argument("--nodes", type=int, default=1_000_000)
    sv.add_argument("--sessions", type=int, default=1000)
    sv.add_argument("--periods", type=int, default=3)
    sv.add_argument("--seed", type=int, default=0)
    sv.add_argument("--sockets", type=int, default=16,
                    help="client UDP sockets the sessions multiplex "
                         "over (sessions never cost fds)")
    sv.add_argument("--echo-samples", type=int, default=2000,
                    help="OP_ECHO RTT probes behind the p50/p99")
    sv.add_argument("--frontend", choices=("auto", "udppump", "socket"),
                    default="auto",
                    help="hub datapath: the udppump epoll frontend "
                         "when the native toolchain is present")
    sv.add_argument("--out", default="",
                    help="write the full result JSON here "
                         "(bench.py --tier serve owns the committed "
                         "bench_results/serve_load.json; 'serve trace' "
                         "--out owns bench_results/serve_trace.json, "
                         "written byte-stable: sorted keys, no "
                         "timestamps)")
    sv.add_argument("--json", action="store_true",
                    help="print the full result (arms included)")
    sv.set_defaults(fn=_cmd_serve)
    return p


def main(argv: list[str] | None = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.platform != "default":
        from swim_tpu.utils.platform import force_cpu

        force_cpu(8 if args.platform == "cpu8" else None)
    try:
        return args.fn(args)
    except BrokenPipeError:
        # `swim-tpu observe ... | head` closing the pipe is not an error
        sys.stderr.close()
        return 0


if __name__ == "__main__":
    sys.exit(main())
