"""swim-tpu command-line interface.

Mirrors the reference's demo executable (stock config: 32-node in-process
cluster, k=3, 1 s period — BASELINE.json configs[0]) and fronts the
simulators. Subcommands grow with the framework; `info` is always available.
"""

from __future__ import annotations

import argparse
import json
import sys


def _cmd_info(args: argparse.Namespace) -> int:
    import swim_tpu

    cfg = swim_tpu.SwimConfig(n_nodes=args.nodes)
    print(json.dumps({
        "version": swim_tpu.__version__,
        "n_nodes": cfg.n_nodes,
        "k_indirect": cfg.k_indirect,
        "protocol_period_s": cfg.protocol_period,
        "suspicion_periods": cfg.suspicion_periods,
        "retransmit_limit": cfg.retransmit_limit,
        "max_piggyback": cfg.max_piggyback,
        "rumor_slots": cfg.rumor_slots,
    }, indent=2))
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="swim-tpu",
        description="TPU-native SWIM failure-detection framework & simulator",
    )
    sub = p.add_subparsers(dest="command", required=True)

    info = sub.add_parser("info", help="show derived protocol constants")
    info.add_argument("--nodes", type=int, default=32)
    info.set_defaults(fn=_cmd_info)
    return p


def main(argv: list[str] | None = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
