"""Fused multi-wave window OR-merge (TPU Pallas kernel).

Why this kernel exists (round-4 TPU trace, docs/RESULTS.md §1): in
period-selection scope every rotor period ORs the SAME start-of-period
piggyback selection into the window at 2+4k rolled offsets —

    win |= ok_w ? roll(sel, off_w) : 0        for each of V=14 waves

XLA fuses all fourteen terms into one kLoop fusion (good), but each
roll lowers to a pair of dynamic slices along the node axis, which is
the MINOR (lane) dimension of the `{0,1}`-laid-out [N, WW] window —
so every vector load is lane-misaligned and the fusion ran at ~2.5x
its streaming floor (measured 2.29 ms/period of the 8.27 ms 1M-node
period, the largest single op).

Here the rolls become plain contiguous DMAs: in the transposed
[WW, N] view each word row is contiguous along N, so a roll is just a
dynamic column offset.  Per output block the kernel issues one DMA
per wave from a wrap-padded selection buffer (8-32 KB contiguous runs
— ideal DMA shapes, no lane shuffles), overlaps all V transfers, and
ORs them under the receiver's delivery mask.  The buddy-forced bits
(at most one word per receiver, waves W1/W4a) ride along as compact
(col, val) vectors instead of materialized [N, WW] one-hots.

Semantics (bitwise twin pinned by tests/test_wavemerge.py):

    out[i] = win[i]
             | OR_w  (oks[w, i] ? sel[(i + offs[w]) mod N] : 0)
             | OR_q  onehot(bcol[q, i]) * bval[q, i]

Delivery masks are indexed by RECEIVER, so they ride the output block
(lane-local); only the selection reads are offset.  The last grid
block self-clamps its start to N-T and recomputes the overlap region
with identical inputs (idempotent bit-ORs), so the kernel performs
the SAME arithmetic on every backend — no reliance on ragged-block
padding/clamping semantics, which differ between Mosaic and interpret
mode.  Wraparound reads come from a T-column wrap pad, never a
data-dependent second DMA.

The reference tree is unavailable (see SURVEY.md §0); the protocol
semantics this implements are the W1-W6 gossip deliveries documented
at models/ring.py Phases A/B and docs/PROTOCOL.md §3.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


def _block_t(v: int, vb: int, ww: int, n: int) -> int:
    """Node-axis block width (lanes).

    VMEM is dominated by the V per-wave selection buffers ([V, WW, T]
    u32) plus the accumulator ([WW, T]), the ok bits ([1, T]) and the
    buddy col/val scratch ([VB, T] ×2) — (V+1)·WW + 1 + 2·VB words per
    lane; budget ~8 MB for them, keep T a 128-lane multiple, and cap
    at 8192 (123 blocks at the 1M flagship: DMA issue overhead
    amortizes, transfers overlap).  Returns 0 when no 128-wide block
    fits the budget or when n is too small to clamp against (the twin
    handles those)."""
    budget = (8 * 1024 * 1024) // (((v + 1) * ww + 1 + 2 * vb) * 4)
    t = min(8192, (budget // 128) * 128, (n // 128) * 128)
    return t if t >= 128 and n >= t else 0


def _make_kernel(n: int, t: int, v: int, vb: int, ww: int):
    def kernel(offs_ref, sel_ref, win_ref, ok_ref, bcol_ref, bval_ref,
               out_ref, accv, selv, okv, bcolv, bvalv, sems, sout):
        i = pl.program_id(0)
        start = jnp.minimum(i * t, n - t)

        # issue every read up front; transfers overlap
        cps = []
        cp = pltpu.make_async_copy(win_ref.at[:, pl.ds(start, t)],
                                   accv, sems.at[0])
        cp.start()
        cps.append(cp)
        cp = pltpu.make_async_copy(ok_ref.at[:, pl.ds(start, t)],
                                   okv, sems.at[1])
        cp.start()
        cps.append(cp)
        cp = pltpu.make_async_copy(bcol_ref.at[:, pl.ds(start, t)],
                                   bcolv, sems.at[2])
        cp.start()
        cps.append(cp)
        cp = pltpu.make_async_copy(bval_ref.at[:, pl.ds(start, t)],
                                   bvalv, sems.at[3])
        cp.start()
        cps.append(cp)
        sel_cps = []
        for w in range(v):
            src = start + offs_ref[w]
            src = jnp.where(src >= n, src - n, src)   # offs in [0, n)
            cp = pltpu.make_async_copy(sel_ref.at[:, pl.ds(src, t)],
                                       selv.at[w], sems.at[4 + w])
            cp.start()
            sel_cps.append(cp)

        cps[0].wait()                                  # win -> acc
        cps[1].wait()                                  # ok bits
        acc = accv[...]
        okb = okv[...]                                 # u32[1, T]
        zero = jnp.zeros((), jnp.uint32)
        for w in range(v):
            sel_cps[w].wait()
            hit = ((okb >> w) & jnp.uint32(1)) > 0     # [1, T]
            acc = acc | jnp.where(hit, selv[w], zero)
        cps[2].wait()
        cps[3].wait()
        riota = jax.lax.broadcasted_iota(jnp.int32, (ww, t), 0)
        for q in range(vb):
            acc = acc | jnp.where(riota == bcolv[q:q + 1, :],
                                  bvalv[q:q + 1, :], zero)
        accv[...] = acc
        cp = pltpu.make_async_copy(accv, out_ref.at[:, pl.ds(start, t)],
                                   sout)
        cp.start()
        cp.wait()
    return kernel


@functools.partial(jax.jit, static_argnames=("t", "interpret"))
def _call(offs, sel_pad_t, win_t, okbits, bcol, bval, *, t, interpret):
    ww, n = win_t.shape
    v = int(offs.shape[0])
    vb = int(bcol.shape[0])
    grid = (_cdiv(n, t),)
    return pl.pallas_call(
        _make_kernel(n, t, v, vb, ww),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 5,
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[
                pltpu.VMEM((ww, t), jnp.uint32),        # accumulator
                pltpu.VMEM((v, ww, t), jnp.uint32),     # per-wave sel
                pltpu.VMEM((1, t), jnp.uint32),         # ok bits
                pltpu.VMEM((vb, t), jnp.int32),         # buddy cols
                pltpu.VMEM((vb, t), jnp.uint32),        # buddy vals
                pltpu.SemaphoreType.DMA((4 + v,)),
                pltpu.SemaphoreType.DMA,
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((ww, n), jnp.uint32),
        # out reuses win's buffer: every block fully reads its window
        # region before its output DMA starts, and the clamped last
        # block rewrites the overlap with identical values
        input_output_aliases={2: 0},
        interpret=interpret,
    )(offs, sel_pad_t, win_t, okbits, bcol, bval)


def _lax_twin(win, sel, oks, offs, bcol, bval):
    """jnp lowering — the pre-kernel rolled-OR formulation, kept as
    the non-TPU path and the bitwise contract for the kernel tests."""
    ww = win.shape[1]
    zero = jnp.zeros((), jnp.uint32)
    out = win
    for w in range(oks.shape[0]):
        rolled = jnp.roll(sel, -offs[w], axis=0)
        out = out | jnp.where(oks[w][:, None], rolled, zero)
    wids = jnp.arange(ww, dtype=jnp.int32)[None, :]
    for q in range(bcol.shape[0]):
        out = out | jnp.where(bcol[q][:, None] == wids,
                              bval[q][:, None], zero)
    return out


def merge_waves(win, sel, oks, offs, bcol, bval, impl: str = "auto",
                block_t: int | None = None):
    """OR V rolled, delivery-masked selection payloads plus VB forced
    bits into the window.

    win:  u32[N, WW]  receiver windows (carry-in)
    sel:  u32[N, WW]  start-of-period selection payload (sender rows)
    oks:  bool[V, N]  per-wave delivery mask, indexed by RECEIVER
    offs: i32[V]      receiver i hears sel row (i + offs[v]) mod N
                      (traced scalars fine; any sign/magnitude)
    bcol: i32[VB, N]  receiver-aligned forced-bit window column
    bval: u32[VB, N]  forced bit value (0 = no contribution; the col
                      of a zero-val entry is ignored)
    impl: "auto" (pallas on the TPU backend, jnp elsewhere),
          "pallas" (interpret mode off-TPU), or "lax"

    Returns u32[N, WW].
    """
    if impl not in ("auto", "pallas", "lax"):
        raise ValueError(f"bad impl {impl!r}: want auto|pallas|lax")
    n, ww = win.shape
    v = oks.shape[0]
    if v > 32:
        raise ValueError(f"V={v} waves exceed the 32-bit ok pack")
    offs = jnp.asarray(offs, jnp.int32)
    offs = jnp.mod(jnp.mod(offs, n) + n, n)
    if impl == "lax" or (impl == "auto"
                         and jax.default_backend() != "tpu"):
        return _lax_twin(win, sel, oks, offs, bcol, bval)
    if bcol.shape[0] == 0:
        # A zero-row VMEM scratch is not a valid Mosaic allocation;
        # one inert row (val 0 contributes nothing) keeps the kernel
        # shape-uniform for buddy-less configs.
        bcol = jnp.zeros((1, n), jnp.int32)
        bval = jnp.zeros((1, n), jnp.uint32)
    vb = int(bcol.shape[0])
    t = block_t if block_t is not None else _block_t(v, vb, ww, n)
    if t == 0:
        # No viable block: tiny N (< one 128-lane tile) or a
        # VMEM-hostile geometry.  Block STARTS need no alignment —
        # DMAs are byte-addressed, and the wave source offsets are
        # arbitrary by construction — so any n >= t works.
        if impl == "pallas":
            raise ValueError(
                f"no viable merge block for N={n}, WW={ww}, V={v}; "
                "use impl='auto' or 'lax'")
        return _lax_twin(win, sel, oks, offs, bcol, bval)
    okbits = jnp.zeros((n,), jnp.uint32)
    for w in range(v):
        okbits = okbits | (oks[w].astype(jnp.uint32) << w)
    sel_t = sel.T
    sel_pad = jnp.concatenate([sel_t, sel_t[:, :t]], axis=1)
    interpret = jax.default_backend() != "tpu"
    out_t = _call(offs, sel_pad, win.T, okbits[None, :],
                  bcol.astype(jnp.int32), bval.astype(jnp.uint32),
                  t=t, interpret=interpret)
    return out_t.T
