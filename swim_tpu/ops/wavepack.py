"""Slot-index codec for the compact ICI wave wire (ring_ici_wire).

SWIM's dissemination is bounded piggyback (Das et al., DSN 2002 §4.1):
each message carries at most B membership updates.  The ring engine
honors that bound at selection time — `_select_first_b` leaves at most
B = min(max_piggyback, WW*32) set bits per sel row — but the sharded
wave exchange (parallel/ring_shard.py) then ships the whole dense
window u32[S, WW] over ICI, paying for WW*32 slot positions per row
when at most B are live.

This module packs a bounded-piggyback sel block into its information
content: the SLOT INDICES of the set bits, row-major first-to-last,

    pack_slots(sel u32[S, WW], b)  ->  idx[S, b]   (uint8 or uint16)

where slot = word_col * 32 + bit, empty entries hold the dtype's max
value as a sentinel (a real slot never reaches it — see slot_dtype),
and

    unpack_slots(idx, ww)  ->  u32[S, WW]

reconstructs the exact window block (the values are single bits, so
they need not travel: receiver-side `1 << (slot & 31)` rebuilds them).
`unpack_slots(pack_slots(sel, b), ww) == sel` bitwise whenever every
row of `sel` has at most b set bits — which first-B selection
guarantees by construction.  Both directions are scatter-free
(extract-lowest-bit loops and one-hot ORs, the same idiom as
ops/selb.py's lax twin), so they run on the shard-local block inside
shard_map with no collectives.

Wire math (the point): a dense wave payload is WW*4 bytes/row; the
packed payload is b * itemsize bytes/row — 24 -> 6 at the lean
geometry (WW=6, b=6, uint8) and 48 -> 12 at the default (WW=12, b=6,
uint16), per neighbor-block transfer.  scripts/shard_anchor.py tallies
the resulting per-chip ICI bytes for both wire formats.

Scalar wave wire (ring_scalar_wire="packed") — the same pack-once
discipline applied to the per-wave SCALAR payloads (PR after the sel
window):

  * `pack_bits` / `unpack_bits`: a bool node vector rides as 1 bit per
    node (u32 words, SWIM's delivery flags are single bits — Das et
    al., DSN 2002 §4.1), 32x narrower than the bool8 lanes XLA would
    ship and 128x narrower than the int32 lanes the flags historically
    widened to.
  * `code_dtype`: the narrowest unsigned dtype holding a bounded code
    (slot + 1 sentinel encodings, buddy window columns) — the same
    sizing rule as slot_dtype, keyed by the value bound instead of the
    window geometry.
  * `pack_bundle` / `unpack_bundle`: several same-offset node vectors
    (a wave's ok chain + partition ids + buddy col/val codes) fuse
    into ONE u8 payload per neighbor block, so the sharded twin pays a
    single ppermute pair per wave no matter how many arrays ride.
    Bools bit-pack first; narrow ints bitcast to bytes.  Round-trip is
    bitwise exact, so the packed wire inherits the parity contract.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

WORD = 32


def slot_dtype(ww: int):
    """Narrowest unsigned dtype that can index ww*32 slots AND spare its
    max value as the empty sentinel (hence <=, not <)."""
    nbits = ww * WORD
    if nbits < 255:
        return jnp.uint8
    if nbits < 65535:
        return jnp.uint16
    return jnp.uint32


def packed_itemsize(ww: int) -> int:
    """Bytes per packed slot entry — the anchor model's tally unit."""
    return jnp.dtype(slot_dtype(ww)).itemsize


def code_dtype(max_code: int):
    """Narrowest unsigned dtype that can hold values in [0, max_code]."""
    if max_code <= 255:
        return jnp.uint8
    if max_code <= 65535:
        return jnp.uint16
    return jnp.uint32


def packed_words(s: int) -> int:
    """u32 words a bit-packed bool[s] occupies."""
    return -(-s // WORD)


def pack_bits(flags: jax.Array) -> jax.Array:
    """bool[s] -> u32[ceil(s/32)], bit i of word w = flags[32*w + i]."""
    s = flags.shape[0]
    w = packed_words(s)
    padded = jnp.concatenate(
        [flags, jnp.zeros((w * WORD - s,), jnp.bool_)]).reshape(w, WORD)
    weights = jnp.uint32(1) << jnp.arange(WORD, dtype=jnp.uint32)
    return jnp.sum(jnp.where(padded, weights[None, :], jnp.uint32(0)),
                   axis=1)


def unpack_bits(words: jax.Array, s: int) -> jax.Array:
    """Inverse of pack_bits: u32[ceil(s/32)] -> bool[s]."""
    bit = jnp.arange(WORD, dtype=jnp.uint32)[None, :]
    bits = ((words[:, None] >> bit) & jnp.uint32(1)) > 0
    return bits.reshape(-1)[:s]


def _byte_view(x: jax.Array) -> jax.Array:
    """Flat u8 view of a 1-D array (bools bit-pack first)."""
    if x.dtype == jnp.bool_:
        x = pack_bits(x)
    if x.dtype == jnp.uint8:
        return x
    return jax.lax.bitcast_convert_type(x, jnp.uint8).reshape(-1)


def bundle_nbytes(x: jax.Array) -> int:
    """Bytes one part contributes to a packed bundle payload."""
    if x.dtype == jnp.bool_:
        return 4 * packed_words(x.shape[0])
    return x.shape[0] * jnp.dtype(x.dtype).itemsize


def pack_bundle(parts) -> jax.Array:
    """Fuse same-length 1-D node vectors into ONE u8 payload: bools
    bit-pack to u32 words, narrow ints bitcast — a single wire array
    per neighbor block for the whole wave."""
    return jnp.concatenate([_byte_view(x) for x in parts])


def unpack_bundle(payload: jax.Array, like) -> list[jax.Array]:
    """Split a pack_bundle payload back into parts shaped/typed like
    the reference arrays `like` (bitwise inverse of pack_bundle)."""
    outs, off = [], 0
    for x in like:
        nb = bundle_nbytes(x)
        seg = payload[off:off + nb]
        off += nb
        if x.dtype == jnp.bool_:
            words = jax.lax.bitcast_convert_type(
                seg.reshape(-1, 4), jnp.uint32)
            outs.append(unpack_bits(words, x.shape[0]))
        elif x.dtype == jnp.uint8:
            outs.append(seg)
        else:
            itemsize = jnp.dtype(x.dtype).itemsize
            outs.append(jax.lax.bitcast_convert_type(
                seg.reshape(-1, itemsize), x.dtype))
    return outs


def pack_slots(sel: jax.Array, b: int) -> jax.Array:
    """u32[S, WW] with <= b set bits per row -> slot indices [S, b].

    Extracts set bits in ascending slot order: per pass, the first
    nonzero word (argmax over a !=0 mask) and its lowest set bit
    (isolate with x & -x, index by popcount(low - 1)), then clears that
    bit and repeats.  Rows with fewer than b bits pad with the dtype-max
    sentinel.  Bits beyond the b-th are silently dropped — callers must
    only pack first-B-selected blocks (the engine invariant)."""
    _, ww = sel.shape
    dt = slot_dtype(ww)
    wids = jnp.arange(ww, dtype=jnp.int32)[None, :]
    one = jnp.uint32(1)
    m = sel
    cols = []
    for _ in range(b):
        nz = m != 0
        has = jnp.any(nz, axis=1)
        w = jnp.argmax(nz, axis=1).astype(jnp.int32)
        hit = w[:, None] == wids
        word = jnp.max(jnp.where(hit, m, jnp.uint32(0)), axis=1)
        low = word & (jnp.uint32(0) - word)
        bit = jax.lax.population_count(
            jax.lax.bitcast_convert_type(low - one, jnp.int32))
        slot = w * WORD + jnp.where(has, bit, 0)
        cols.append(jnp.where(has, slot, jnp.iinfo(dt).max).astype(dt))
        m = m ^ jnp.where(hit, low[:, None], jnp.uint32(0))
    return jnp.stack(cols, axis=1)


def unpack_slots(idx: jax.Array, ww: int) -> jax.Array:
    """Slot indices [S, b] -> u32[S, ww] window block (inverse of
    pack_slots on first-B-bounded input).  One one-hot OR pass per
    packed column; sentinel entries (>= ww*32) contribute nothing."""
    s, b = idx.shape
    ii = idx.astype(jnp.int32)
    valid = ii < ww * WORD
    col = jnp.where(valid, ii // WORD, ww)         # ww: off every word
    bit = jnp.where(valid, ii & (WORD - 1), 0).astype(jnp.uint32)
    wids = jnp.arange(ww, dtype=jnp.int32)[None, :]
    zero = jnp.uint32(0)
    out = jnp.zeros((s, ww), jnp.uint32)
    for j in range(b):
        val = jnp.where(valid[:, j], jnp.uint32(1) << bit[:, j], zero)
        out = out | jnp.where(col[:, j:j + 1] == wids, val[:, None], zero)
    return out
