"""Slot-index codec for the compact ICI wave wire (ring_ici_wire).

SWIM's dissemination is bounded piggyback (Das et al., DSN 2002 §4.1):
each message carries at most B membership updates.  The ring engine
honors that bound at selection time — `_select_first_b` leaves at most
B = min(max_piggyback, WW*32) set bits per sel row — but the sharded
wave exchange (parallel/ring_shard.py) then ships the whole dense
window u32[S, WW] over ICI, paying for WW*32 slot positions per row
when at most B are live.

This module packs a bounded-piggyback sel block into its information
content: the SLOT INDICES of the set bits, row-major first-to-last,

    pack_slots(sel u32[S, WW], b)  ->  idx[S, b]   (uint8 or uint16)

where slot = word_col * 32 + bit, empty entries hold the dtype's max
value as a sentinel (a real slot never reaches it — see slot_dtype),
and

    unpack_slots(idx, ww)  ->  u32[S, WW]

reconstructs the exact window block (the values are single bits, so
they need not travel: receiver-side `1 << (slot & 31)` rebuilds them).
`unpack_slots(pack_slots(sel, b), ww) == sel` bitwise whenever every
row of `sel` has at most b set bits — which first-B selection
guarantees by construction.  Both directions are scatter-free
(extract-lowest-bit loops and one-hot ORs, the same idiom as
ops/selb.py's lax twin), so they run on the shard-local block inside
shard_map with no collectives.

Wire math (the point): a dense wave payload is WW*4 bytes/row; the
packed payload is b * itemsize bytes/row — 24 -> 6 at the lean
geometry (WW=6, b=6, uint8) and 48 -> 12 at the default (WW=12, b=6,
uint16), per neighbor-block transfer.  scripts/shard_anchor.py tallies
the resulting per-chip ICI bytes for both wire formats.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

WORD = 32


def slot_dtype(ww: int):
    """Narrowest unsigned dtype that can index ww*32 slots AND spare its
    max value as the empty sentinel (hence <=, not <)."""
    nbits = ww * WORD
    if nbits < 255:
        return jnp.uint8
    if nbits < 65535:
        return jnp.uint16
    return jnp.uint32


def packed_itemsize(ww: int) -> int:
    """Bytes per packed slot entry — the anchor model's tally unit."""
    return jnp.dtype(slot_dtype(ww)).itemsize


def pack_slots(sel: jax.Array, b: int) -> jax.Array:
    """u32[S, WW] with <= b set bits per row -> slot indices [S, b].

    Extracts set bits in ascending slot order: per pass, the first
    nonzero word (argmax over a !=0 mask) and its lowest set bit
    (isolate with x & -x, index by popcount(low - 1)), then clears that
    bit and repeats.  Rows with fewer than b bits pad with the dtype-max
    sentinel.  Bits beyond the b-th are silently dropped — callers must
    only pack first-B-selected blocks (the engine invariant)."""
    _, ww = sel.shape
    dt = slot_dtype(ww)
    wids = jnp.arange(ww, dtype=jnp.int32)[None, :]
    one = jnp.uint32(1)
    m = sel
    cols = []
    for _ in range(b):
        nz = m != 0
        has = jnp.any(nz, axis=1)
        w = jnp.argmax(nz, axis=1).astype(jnp.int32)
        hit = w[:, None] == wids
        word = jnp.max(jnp.where(hit, m, jnp.uint32(0)), axis=1)
        low = word & (jnp.uint32(0) - word)
        bit = jax.lax.population_count(
            jax.lax.bitcast_convert_type(low - one, jnp.int32))
        slot = w * WORD + jnp.where(has, bit, 0)
        cols.append(jnp.where(has, slot, jnp.iinfo(dt).max).astype(dt))
        m = m ^ jnp.where(hit, low[:, None], jnp.uint32(0))
    return jnp.stack(cols, axis=1)


def unpack_slots(idx: jax.Array, ww: int) -> jax.Array:
    """Slot indices [S, b] -> u32[S, ww] window block (inverse of
    pack_slots on first-B-bounded input).  One one-hot OR pass per
    packed column; sentinel entries (>= ww*32) contribute nothing."""
    s, b = idx.shape
    ii = idx.astype(jnp.int32)
    valid = ii < ww * WORD
    col = jnp.where(valid, ii // WORD, ww)         # ww: off every word
    bit = jnp.where(valid, ii & (WORD - 1), 0).astype(jnp.uint32)
    wids = jnp.arange(ww, dtype=jnp.int32)[None, :]
    zero = jnp.uint32(0)
    out = jnp.zeros((s, ww), jnp.uint32)
    for j in range(b):
        val = jnp.where(valid[:, j], jnp.uint32(1) << bit[:, j], zero)
        out = out | jnp.where(col[:, j:j + 1] == wids, val[:, None], zero)
    return out
