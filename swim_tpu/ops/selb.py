"""Fused first-B piggyback selection (TPU Pallas kernel).

`_select_first_b` — the mask of the first `b` set bits of each node's
window, newest word first, LSB-first within a word — is the piggyback
payload selection at the top of every rotor period (and of every WAVE
in exact wave-scope mode).  Its natural formulation is a budgeted
lowest-set-bit extract loop, which carries the per-node budget
SERIALLY through WW x min(b, 32) iterations; XLA lowers that ~72-deep
dependency chain into ~10 separate [N]-vector fusions (measured
1.31 ms/period at the 1M flagship geometry, the third-largest term in
the round-4 TPU profile).  A jnp popcount/prefix rewrite was tried
first and measured SLOWER in the full program (the [:, ::-1] suffix
flips materialized as two full-matrix `rev` copies and the cumsum as a
reduce-window: 81.7 -> 67 periods/sec end-to-end) — the closed form
only pays off when the whole computation stays in registers, i.e. in a
kernel.

This kernel computes the same mask in ONE streamed pass over the
window (read [WW, N] once, write [WW, N] once):

  * popcount each word, exclusive suffix-sum across words (newest
    first) in VMEM registers -> per-word remaining budget;
  * "lowest budget set bits of m" == m & lowmask(t) for the largest
    t in [0, 32] with popcount(m & lowmask(t)) <= budget, found by a
    6-step branch-free binary ascent (32, 16, .., 1), independent per
    word (the budget math above removed the cross-word serialization).

Everything is lane-local (node columns are independent), so the kernel
is safe under the sharded engine and value-identical in interpret
mode.  Bitwise contract: tests/test_core_units.py::TestSelectFirstB
pins kernel and twin element-for-element against an independent numpy
reference of the extract loop.

The reference tree is unavailable (see SURVEY.md §0); protocol
semantics follow the bounded piggyback selection documented at
models/ring.py and docs/PROTOCOL.md (fewest-transmits-first analog).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

WORD = 32


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


def _block_n(ww: int, n: int) -> int:
    """Lane-block width: in+out double-buffered [WW, BN] u32 blocks,
    ~10 MB budget, 128-lane tiles.  0 => no tile fits (fall back)."""
    bn = min(2048, ((10 * 1024 * 1024) // (16 * ww) // 128) * 128)
    if bn == 0:
        return 0
    # round small n UP to the 128-lane tile (grid padding masks the
    # overhang); min(bn, n) could otherwise emit an unaligned block
    return min(bn, max(128, _cdiv(n, 128) * 128))


def _lowmask(t):
    """u32 mask of bit positions [0, t) for t in [0, 32] (branch-free;
    the t==32 shift is discarded by the where)."""
    full = jnp.uint32(0xFFFFFFFF)
    return jnp.where(t >= WORD, full,
                     (jnp.uint32(1) << t.astype(jnp.uint32))
                     - jnp.uint32(1))


def _first_b_math(m, b: int):
    """The popcount/suffix/binary-ascent form on a [WW, BN] block
    (axis 0 = words, newest LAST — same order as the window layout).
    Shared verbatim by the kernel body and nothing else: the jnp twin
    deliberately keeps the extract-loop form (see module docstring)."""
    ww = m.shape[0]
    pc = jax.lax.population_count(
        jax.lax.bitcast_convert_type(m, jnp.int32))
    # exclusive suffix sums, newest word (last row) first
    excl_rows = []
    acc = jnp.zeros_like(pc[0:1])
    for w in range(ww - 1, -1, -1):
        excl_rows.append(acc)
        acc = acc + pc[w:w + 1]
    excl = jnp.concatenate(excl_rows[::-1], axis=0)
    budget = jnp.clip(b - excl, 0, WORD)
    t = jnp.zeros(m.shape, jnp.int32)
    for step in (32, 16, 8, 4, 2, 1):
        t2 = t + step
        cnt = jax.lax.population_count(
            jax.lax.bitcast_convert_type(m & _lowmask(t2), jnp.int32))
        t = jnp.where((t2 <= WORD) & (cnt <= budget), t2, t)
    return m & _lowmask(t)


def _make_kernel(b: int):
    def kernel(win_ref, out_ref):
        out_ref[...] = _first_b_math(win_ref[...], b)
    return kernel


@functools.partial(jax.jit, static_argnames=("b", "interpret"))
def _call(win_t, *, b, interpret):
    ww, n = win_t.shape
    bn = _block_n(ww, n)
    grid = (_cdiv(n, bn),)
    return pl.pallas_call(
        _make_kernel(b),
        grid=grid,
        in_specs=[pl.BlockSpec((ww, bn), lambda i: (0, i))],
        out_specs=pl.BlockSpec((ww, bn), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((ww, n), jnp.uint32),
        interpret=interpret,
    )(win_t)


def _lax_twin(win_masked, b: int):
    """jnp lowering: the budgeted lowest-set-bit extract loop — the
    original (and XLA-fastest) formulation, kept as the semantic home
    and the non-TPU path."""
    ww = win_masked.shape[-1]
    taken = [None] * ww
    budget = jnp.full(win_masked.shape[:1], b, jnp.int32)
    for w in range(ww - 1, -1, -1):         # newest word first
        m = win_masked[:, w]
        acc = jnp.zeros_like(m)
        for _ in range(min(b, WORD)):
            low = m & (jnp.uint32(0) - m)   # lowest set bit (0 if none)
            bitm = jnp.where(budget > 0, low, jnp.uint32(0))
            acc = acc | bitm
            m = m ^ bitm
            budget = budget - (bitm != 0).astype(jnp.int32)
        taken[w] = acc
    return jnp.stack(taken, axis=-1)


def select_first_b(win_masked, b: int, impl: str = "auto"):
    """Mask of the first `b` set bits of each row's window (u32[N, WW],
    newest word = last column, LSB-first within a word).

    impl: "auto" (pallas on the TPU backend, jnp elsewhere),
          "pallas" (interpret mode off-TPU), or "lax".
    """
    if impl not in ("auto", "pallas", "lax"):
        raise ValueError(f"bad impl {impl!r}: want auto|pallas|lax")
    if impl == "lax" or (impl == "auto"
                         and jax.default_backend() != "tpu"):
        return _lax_twin(win_masked, b)
    if _block_n(win_masked.shape[1], win_masked.shape[0]) == 0:
        if impl == "pallas":
            raise ValueError(
                f"window width WW={win_masked.shape[1]} exceeds the "
                "first-B kernel's scoped-vmem budget; use 'auto' or "
                "'lax'")
        return _lax_twin(win_masked, b)
    interpret = jax.default_backend() != "tpu"
    return _call(win_masked.T, b=b, interpret=interpret).T
