"""Fused cold-ring update + multi-query select (TPU Pallas kernel).

Why this kernel exists (round-4 TPU attribution, docs/RESULTS.md §1):
the XLA-lowered hot path paid ~3.5 GB/period of avoidable HBM traffic
on the 512 MB cold matrix at the 1M-node flagship geometry —

  * two full-matrix layout copies per period: XLA's layout assignment
    gave the loop-carried `cold` buffer a `{0,1}` (node-major) layout
    to suit the eq-iota one-hot selects, while the Phase-0b row slices
    and the flush want `{1,0}` (node-minor), so every period round-
    tripped 512 MB through `copy` instructions in both directions;
  * the Q-query one-hot `lax.reduce` decomposed into Q separate
    full-matrix fusions on the TPU backend (measured as three extra
    512 MB `gather` fusions), although the CPU backend fuses them
    into one pass — the round-4 CPU cost proxy halved while the TPU
    wall time stayed flat.

This kernel replaces the Phase-0d flush (OW row overwrites) and the
Phase-C view-query selects (Q per-node row lookups) with ONE blocked
pass: cold is read once and written once per period, all Q selects are
computed from the in-VMEM block, and — because Mosaic kernels use the
default `{1,0}` layout — every remaining XLA consumer (the contiguous
Phase-0b row slices) agrees with the carry layout, so the copies
disappear.

Semantics (bitwise-exact twin of the jnp path, pinned by
tests/test_coldsel.py):

    new_cold = cold with row flush_rows[w] := flush_vals[w]  (w < OW)
    sel[q][i] = new_cold[q_rows[q, i], i]  if 0 <= q_rows[q, i] < RW
                else 0

Everything is lane-local (each node column i depends only on column i
of the inputs plus the shared scalars), which makes the kernel safe
under the sharded engine (per-shard local columns) and value-identical
under interpret mode's clamped ragged-edge re-execution.

The reference tree is unavailable (see SURVEY.md §0); the protocol
semantics this implements are the window→cold-ring flush and heard-bit
view queries documented at models/ring.py Phase 0d / Phase C.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


def _block_n(rw: int, n: int) -> int:
    """Node-axis block width (lanes), sized to the ring depth.

    The double-buffered [RW, BN] in + out blocks dominate VMEM:
    roughly 2 (in+out) * 2 (double buffer) * RW * BN * 4 bytes.  A
    fixed BN=2048 fits the RW=128 flagship geometry with room to spare
    but overflowed the 16 MB scoped-vmem limit at the Lifeguard
    geometry's RW=512 (observed: 16.06M > 16.00M).  Budget ~10 MB for
    the big blocks and round down to the 128-lane tile.

    Returns 0 when even ONE 128-lane tile would overflow the budget
    (rw > 5120): flooring at 128 regardless would reintroduce exactly
    the scoped-vmem compile failure this sizing exists to prevent, so
    callers must fall back to the jnp lowering instead."""
    bn = min(2048, ((10 * 1024 * 1024) // (16 * rw) // 128) * 128)
    if bn == 0:
        return 0
    # round small n UP to the 128-lane tile (grid padding masks the
    # overhang); min(bn, n) could otherwise emit an unaligned block
    return min(bn, max(128, _cdiv(n, 128) * 128))


def _kernel(fr_ref, cold_ref, fv_ref, qr_ref, new_ref, sel_ref):
    """One node-axis block: flush OW rows, then Q one-hot row selects.

    fr_ref:  SMEM i32[OW]   ring rows to overwrite (scalar prefetch)
    cold_ref: VMEM u32[RW, BN]
    fv_ref:  VMEM u32[OW, BN]  replacement row contents
    qr_ref:  VMEM i32[Q, BN]   per-lane query rows
    new_ref: VMEM u32[RW, BN]  flushed block out
    sel_ref: VMEM u32[Q, BN]   selected words out
    """
    ow = fv_ref.shape[0]
    q_n = qr_ref.shape[0]
    blk = cold_ref[...]
    riota = jax.lax.broadcasted_iota(jnp.int32, blk.shape, 0)
    for w in range(ow):
        blk = jnp.where(riota == fr_ref[w], fv_ref[w:w + 1, :], blk)
    new_ref[...] = blk
    # Mosaic has no unsigned reductions; the select is ONE-HOT (riota
    # matches at most one row per lane), so a bitcast-i32 SUM of the
    # masked block is bit-exact: zero addends plus at most one payload.
    blk_i = jax.lax.bitcast_convert_type(blk, jnp.int32)
    zero = jnp.zeros((), jnp.int32)
    for q in range(q_n):
        hit = riota == qr_ref[q:q + 1, :]
        picked = jnp.sum(jnp.where(hit, blk_i, zero), axis=0,
                         keepdims=True)
        sel_ref[q:q + 1, :] = jax.lax.bitcast_convert_type(
            picked, jnp.uint32)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _call(flush_rows, cold, flush_vals, q_rows, *, interpret):
    rw, n = cold.shape
    ow = flush_vals.shape[0]
    q_n = q_rows.shape[0]
    bn = _block_n(rw, n)
    grid = (_cdiv(n, bn),)
    new_cold, sel = pl.pallas_call(
        _kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((rw, bn), lambda i, fr: (0, i)),
                pl.BlockSpec((ow, bn), lambda i, fr: (0, i)),
                pl.BlockSpec((q_n, bn), lambda i, fr: (0, i)),
            ],
            out_specs=[
                pl.BlockSpec((rw, bn), lambda i, fr: (0, i)),
                pl.BlockSpec((q_n, bn), lambda i, fr: (0, i)),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((rw, n), jnp.uint32),
            jax.ShapeDtypeStruct((q_n, n), jnp.uint32),
        ],
        # new_cold reuses cold's buffer: each grid block is fully DMA'd
        # to VMEM before its output DMA starts, so in-place is safe, and
        # the alias lets XLA update the loop-carried buffer without the
        # defensive 512 MB copy it otherwise inserts per period.
        input_output_aliases={1: 0},
        interpret=interpret,
    )(flush_rows, cold, flush_vals, q_rows)
    return new_cold, sel


def _lax_twin(flush_rows, cold, flush_vals, q_rows):
    """jnp reference implementation — the pre-kernel lowering, kept as
    the non-TPU path and the bitwise contract for the kernel tests."""
    rw = cold.shape[0]
    row_ids = jnp.arange(rw, dtype=jnp.int32)[:, None]
    new = cold
    for w in range(flush_vals.shape[0]):
        new = jnp.where(row_ids == flush_rows[w], flush_vals[w][None, :],
                        new)
    zero = jnp.zeros((), cold.dtype)
    ops_in = [jnp.where(row_ids == q_rows[q][None, :], new, zero)
              for q in range(q_rows.shape[0])]
    outs = jax.lax.reduce(ops_in, [zero] * len(ops_in),
                          lambda a, b: tuple(
                              jnp.maximum(x, y) for x, y in zip(a, b)),
                          (0,))
    return new, jnp.stack(list(outs))


def cold_update_select(cold, flush_rows, flush_vals, q_rows,
                       impl: str = "auto"):
    """Flush OW rows into the cold ring and answer Q row queries.

    cold:       u32[RW, N]
    flush_rows: i32[OW]     ring rows to overwrite (traced scalars ok)
    flush_vals: u32[OW, N]  replacement contents (the outgoing window
                            columns, word-major)
    q_rows:     i32[Q, N]   per-node query rows; out-of-[0, RW) -> 0
    impl:       "auto" (pallas on the TPU backend, jnp elsewhere),
                "pallas" (interpret mode off-TPU), or "lax"

    Returns (new_cold u32[RW, N], sel u32[Q, N]).
    """
    if impl not in ("auto", "pallas", "lax"):
        raise ValueError(f"bad impl {impl!r}: want auto|pallas|lax")
    if impl == "lax" or (impl == "auto"
                         and jax.default_backend() != "tpu"):
        return _lax_twin(flush_rows, cold, flush_vals, q_rows)
    if _block_n(cold.shape[0], cold.shape[1]) == 0:
        # Ring deeper than the kernel's VMEM budget can block (RW >
        # 5120, e.g. a very large ring_orig_words * suspicion life).
        if impl == "pallas":
            raise ValueError(
                f"ring depth RW={cold.shape[0]} exceeds the Pallas "
                "cold kernel's scoped-vmem budget (max 5120 words); "
                "use ring_cold_kernel='auto' or 'lax'")
        return _lax_twin(flush_rows, cold, flush_vals, q_rows)
    interpret = jax.default_backend() != "tpu"
    return _call(flush_rows.astype(jnp.int32), cold, flush_vals,
                 q_rows.astype(jnp.int32), interpret=interpret)
