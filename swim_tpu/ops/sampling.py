"""Randomized round-robin probe-target sampling (SWIM paper §4.3).

The paper's failure detector probes targets in shuffled round-robin order:
every node visits every other member exactly once per epoch of N−1
periods, which bounds worst-case detection time at N−1 periods (uniform
sampling only bounds it in expectation). Materializing a shuffled list per
node is O(N²) state at simulator scale, so the shuffle is computed, not
stored: a keyed **format-preserving permutation** of [0, m) built from a
4-round balanced Feistel network with cycle-walking. Each (node, epoch)
pair keys its own permutation; evaluating position `t mod m` walks that
node's shuffled probe list with O(1) state — docs/PROTOCOL.md §4.

Two implementations, bit-identical by construction and by test
(tests/test_sampling.py): `feistel` on uint32 jnp arrays for the engines,
`py_feistel` on Python ints for the scalar oracle.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

ROUNDS = 4
_GOLD = 0x9E3779B9


def _half_bits(m: int) -> int:
    """b such that the 2b-bit Feistel domain covers [0, m)."""
    if m < 2:
        return 1
    return max(1, ((m - 1).bit_length() + 1) // 2)


# ---------------------------------------------------------------- jnp path

def _mix32(x: jax.Array) -> jax.Array:
    """lowbias32 — a well-mixed 32-bit integer hash."""
    x = jnp.asarray(x, jnp.uint32)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    return x ^ (x >> 16)


def _perm2b(x: jax.Array, b: int, ka: jax.Array, kb: jax.Array) -> jax.Array:
    mask = jnp.uint32((1 << b) - 1)
    left = jnp.asarray(x, jnp.uint32) >> b
    right = jnp.asarray(x, jnp.uint32) & mask
    for r in range(ROUNDS):
        rk = _mix32(ka + jnp.uint32((r * _GOLD) & 0xFFFFFFFF)) ^ kb
        f = _mix32(right + rk) & mask
        left, right = right, left ^ f
    return (left << b) | right


def feistel(x: jax.Array, m: int, ka: jax.Array, kb: jax.Array) -> jax.Array:
    """Keyed permutation of [0, m) evaluated at x (elementwise).

    `m` is static; `x`, `ka`, `kb` broadcast. Cycle-walks values that land
    outside [0, m) (the Feistel domain is the next power of four)."""
    b = _half_bits(m)
    mm = jnp.uint32(m)
    y = _perm2b(x, b, ka, kb)

    def cond(y):
        return jnp.any(y >= mm)

    def body(y):
        return jnp.where(y >= mm, _perm2b(y, b, ka, kb), y)

    return jax.lax.while_loop(cond, body, y).astype(jnp.int32)


def round_robin_target(node: jax.Array, epoch: jax.Array, pos: jax.Array,
                       n: int) -> jax.Array:
    """Probe target of `node` at position `pos` of `epoch` (all [N] arrays).

    Permutes [0, n−1) with a (node, epoch)-derived key, then the skip-self
    map yields a permutation of the other n−1 members."""
    node = jnp.asarray(node, jnp.uint32)
    ka = _mix32(node * jnp.uint32(_GOLD)
                + jnp.asarray(epoch, jnp.uint32) * jnp.uint32(0x85EBCA6B))
    kb = _mix32(node ^ (jnp.asarray(epoch, jnp.uint32) + jnp.uint32(1)))
    p = feistel(jnp.asarray(pos, jnp.uint32), n - 1, ka, kb)
    return p + (p >= jnp.asarray(node, jnp.int32)).astype(jnp.int32)


# ------------------------------------------------------------- python twin

def _py_mix32(x: int) -> int:
    x &= 0xFFFFFFFF
    x ^= x >> 16
    x = (x * 0x7FEB352D) & 0xFFFFFFFF
    x ^= x >> 15
    x = (x * 0x846CA68B) & 0xFFFFFFFF
    return x ^ (x >> 16)


def _py_perm2b(x: int, b: int, ka: int, kb: int) -> int:
    mask = (1 << b) - 1
    left, right = x >> b, x & mask
    for r in range(ROUNDS):
        rk = _py_mix32((ka + r * _GOLD) & 0xFFFFFFFF) ^ kb
        f = _py_mix32((right + rk) & 0xFFFFFFFF) & mask
        left, right = right, left ^ f
    return (left << b) | right


def py_feistel(x: int, m: int, ka: int, kb: int) -> int:
    b = _half_bits(m)
    y = _py_perm2b(x, b, ka, kb)
    while y >= m:
        y = _py_perm2b(y, b, ka, kb)
    return y


def py_round_robin_target(node: int, epoch: int, pos: int, n: int) -> int:
    ka = _py_mix32((node * _GOLD + epoch * 0x85EBCA6B) & 0xFFFFFFFF)
    kb = _py_mix32((node ^ ((epoch + 1) & 0xFFFFFFFF)) & 0xFFFFFFFF)
    p = py_feistel(pos, n - 1, ka, kb)
    return p + (1 if p >= node else 0)
