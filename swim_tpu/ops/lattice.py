"""Vectorized membership-state lattice (JAX mirror of swim_tpu/types.py).

Opinions are packed into a single uint32 key so that the SWIM merge rule —
DEAD sticky, then higher incarnation, then SUSPECT > ALIVE — is exactly
`jnp.maximum`/scatter-max. Associativity/commutativity of `max` is what lets
a whole message wave merge in one scatter regardless of delivery order
(docs/PROTOCOL.md §3).

Layout (must match types.opinion_key):  key = dead<<31 | inc<<1 | suspect
"""

from __future__ import annotations

import jax.numpy as jnp

from swim_tpu.types import INC_MAX, Status

def pack(status, incarnation):
    """status u8/int [any shape], incarnation u32 → key u32."""
    status = jnp.asarray(status, jnp.uint32)
    inc = jnp.minimum(jnp.asarray(incarnation, jnp.uint32),
                      jnp.uint32(INC_MAX))
    dead = (status == Status.DEAD).astype(jnp.uint32) << 31
    suspect = (status == Status.SUSPECT).astype(jnp.uint32)
    return dead | (inc << 1) | suspect


def status_of(key):
    key = jnp.asarray(key, jnp.uint32)
    dead = (key >> 31) == 1
    suspect = (key & 1) == 1
    return jnp.where(dead, jnp.uint8(Status.DEAD),
                     jnp.where(suspect, jnp.uint8(Status.SUSPECT),
                               jnp.uint8(Status.ALIVE)))


def incarnation_of(key):
    return (jnp.asarray(key, jnp.uint32) >> 1) & jnp.uint32(INC_MAX)


def merge(a, b):
    """Lattice join == max over packed keys."""
    return jnp.maximum(a, b)


def is_dead(key):
    return (jnp.asarray(key, jnp.uint32) >> 31) == 1


def is_suspect(key):
    return (~is_dead(key)) & ((key & 1) == 1)


def alive_key(incarnation):
    return pack(jnp.uint8(Status.ALIVE), incarnation)


def suspect_key(incarnation):
    return pack(jnp.uint8(Status.SUSPECT), incarnation)


def dead_key(incarnation):
    return pack(jnp.uint8(Status.DEAD), incarnation)
