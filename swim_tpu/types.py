"""Core protocol types and the membership-state lattice.

SWIM's correctness hinges on one algebraic fact: merging two opinions about a
member — (status, incarnation) pairs — is an associative, commutative,
idempotent join on a lattice.  That is exactly what makes the whole protocol
vectorizable on TPU: every gossip merge in a message wave can be applied in
any order (a scatter-max), so one `jit`-compiled step can process all N nodes'
messages simultaneously without replaying per-message ordering.

Precedence (SWIM paper, Das et al. DSN 2002, §4.2):
  * DEAD is sticky: a confirm overrides ALIVE/SUSPECT of any incarnation.
  * Otherwise higher incarnation wins.
  * At equal incarnation, SUSPECT > ALIVE.

We encode an opinion as a single uint32 priority key so the join is `max`:

    key = (is_dead << 31) | (incarnation << 1) | is_suspect

(incarnation saturates at 2**30 - 1; it only grows via refutations, one per
suspicion of that node, so saturation is unreachable in practice — keys
compare equal at the clamp, making ties possible there but nowhere else.)

This module is pure Python + ints — shared by the scalar oracle
(`swim_tpu.models.oracle`), the real-node framework (`swim_tpu.core`), and
the wire codec. The JAX mirror of these ops lives in `swim_tpu.ops.lattice`.
"""

from __future__ import annotations

import dataclasses
import enum

INC_MAX = (1 << 30) - 1


class Status(enum.IntEnum):
    ALIVE = 0
    SUSPECT = 1
    DEAD = 2


@dataclasses.dataclass(frozen=True)
class Opinion:
    """One node's belief about one member: (status, incarnation).

    Deliberately NOT orderable: SWIM precedence is `merge`/`key()`, and a
    lexicographic dataclass order would silently disagree with it.
    """

    status: Status
    incarnation: int

    def key(self) -> int:
        return opinion_key(int(self.status), self.incarnation)


def opinion_key(status: int, incarnation: int) -> int:
    """Total-order key; lattice join == max over keys."""
    inc = min(incarnation, INC_MAX)
    if status == Status.DEAD:
        return (1 << 31) | (inc << 1)
    return (inc << 1) | (1 if status == Status.SUSPECT else 0)


def key_status(key: int) -> int:
    if key >> 31:
        return int(Status.DEAD)
    return int(Status.SUSPECT) if (key & 1) else int(Status.ALIVE)


def key_incarnation(key: int) -> int:
    return (key >> 1) & INC_MAX


def merge(a: Opinion, b: Opinion) -> Opinion:
    """Lattice join of two opinions (associative, commutative, idempotent)."""
    return a if a.key() >= b.key() else b


def supersedes(a: Opinion, b: Opinion) -> bool:
    """True iff learning `a` changes a view currently holding `b`.

    "New information" in SWIM terms — the trigger for re-gossiping an update
    (reset of its retransmit counter).
    """
    return a.key() > b.key()


@dataclasses.dataclass(frozen=True)
class Update:
    """A membership update as disseminated by gossip: member + opinion."""

    member: int
    status: Status
    incarnation: int

    @property
    def opinion(self) -> Opinion:
        return Opinion(self.status, self.incarnation)


class MsgKind(enum.IntEnum):
    """Wire message kinds (mirrors the reference's ping/ping-req/ack set)."""

    PING = 0
    PING_REQ = 1
    ACK = 2
    NACK = 3      # Lifeguard: explicit negative ack from a probe relay
    JOIN = 4      # join request to a seed
    JOIN_REPLY = 5  # membership snapshot
