"""Static analysis of the compiled engines (deviceless, no hardware).

`audit` verifies the compiled-program contracts the performance claims
rest on — retrace budget, donation coverage, wire payloads, ICI tally
completeness, barrier-chain survival, hot-path hygiene — against the
jaxpr and AOT-compiled HLO.  Import-time jax-free, like the obs stack.
"""

from swim_tpu.analysis import audit  # noqa: F401
