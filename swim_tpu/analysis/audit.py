"""Contract auditor: deviceless static verification of compiled-program laws.

The repo's performance story rests on invariants of the COMPILED program,
not just the protocol semantics the dynamic gates check: the S-axis
zero-retrace promise of the scenario compiler, buffer donation in every
study runner, the packed wire's u8-only collective-permute payloads, the
named ICI byte tally of `obs/ici.py`, and the `optimization_barrier`
ordering chains that break the one-chip memory wall.  Each of those was
enforced (if at all) by a scattered ad-hoc pin.  This module gives them
one machine-checked table.

Methodology — no hardware in the loop, matching `obs/memwall.py`:

* **jaxpr level** (trace only): collective byte accounting, barrier-chain
  presence, retrace counting, dtype/callback hygiene.  Collective bytes
  are counted with `lax.cond`/`lax.switch` branches contributing the MAX
  over branches (exactly one executes) and `lax.scan` contributing
  length x body.  This matters: a global roll by a traced shard distance
  lowers to a switch whose D branches each hold a collective-permute, so
  naive HLO text summation over-counts mutually-exclusive branches by D.
* **HLO level** (AOT compile, CPU mesh or deviceless XLA:TPU): payload
  dtype/shape pins via `scan_hlo_collectives` — per-line checks that are
  robust to the branch duplication above — plus the no-replication-scale-
  all-gather guarantee.
* **artifact level**: the committed `bench_results/memwall_report.json`
  carries the 64M sharded AOT row; the barrier-survival contract reads it
  so the known GSPMD chain drop (ROADMAP item 2) is a named, waived check
  instead of folklore.

Everything here is import-time jax-free (the metrics-registry lint and
`obs/expo.py` import this module without a backend); jax is imported
inside `run_audit` only.
"""

from __future__ import annotations

import json
import os
import re
import tempfile
from typing import Any

# ---------------------------------------------------------------------------
# The contract table.  Names are load-bearing: tests assert failures fire
# by name, the registry lint cross-checks gauges against this table, and
# waivers reference (contract, arm) pairs.
# ---------------------------------------------------------------------------

CONTRACTS = {
    "retrace_budget":
        "one compile per (engine, static-config) arm across a fault-program "
        "value sweep — S is the only trace axis",
    "donation_coverage":
        "every donate_argnums leaf is aliased in the compiled executable: "
        "alias bytes == donated bytes, exactly, for every study runner",
    "wire_contracts":
        "packed arms ship u8 collective-permute payloads and no [S]-shaped "
        "s32/pred lanes; no replication-scale all-gather; compact wire moves "
        "strictly fewer ppermute bytes than the window wire",
    "ici_tally_completeness":
        "every traced collective byte is attributed to a named obs/ici.py "
        "tally term — unattributed bytes fail",
    "barrier_survival":
        "the census-chunk and pull-gather optimization_barrier chains are "
        "present as ordering edges in the traced program, and the sharded "
        "GSPMD lowering keeps the census chain alive (64M AOT row)",
    "hot_path_hygiene":
        "no f64 values and no host callbacks inside traced engine steps "
        "and study bodies",
}

# Expected-fail entries: a failing check whose (contract, arm) appears here
# is reported as "waived" instead of failing the audit.  Each entry names
# the tracking pointer so the waiver is a debt, not a hole.
WAIVERS = (
    {
        "contract": "barrier_survival",
        "arm": "sharded_gspmd_64m",
        "reason":
            "The census-chunk optimization_barrier chain does not survive "
            "the GSPMD sharded lowering: the committed 64M ringshard AOT row "
            "OOMs at ~733G HLO temp (dozens of ~921M cold-plane slices at "
            "models/ring.py:595 held live, plus a 5G shmap-body window "
            "select).  Fix: re-pin the chain under GSPMD or move the census "
            "inside the shard body.",
        "pointer": "ROADMAP.md item 2; models/ring.py:595",
    },
)

# ---------------------------------------------------------------------------
# ICI tally vocabulary: which obs/ici.py breakdown terms attribute which
# collective family.  The registry lint verifies every term below appears
# in obs/ici.py; the completeness contract verifies the reverse direction
# (no breakdown key outside this vocabulary, no traced byte outside the
# terms' budget).
# ---------------------------------------------------------------------------

ICI_TERM_FAMILIES = {
    "ppermute": (
        "roll_probe_gate", "roll_ok_waves", "roll_pid_waves",
        "roll_link_thr", "roll_buddy_slots", "roll_buddy_cols",
        "roll_buddy_vals", "roll_view_slots", "roll_view_known",
        "roll_view_verdict", "roll_sel_waves", "sel_wire_boundary",
    ),
    "psum": ("psum_scalar", "gather_psum", "knows_psum"),
    "all_gather": ("candidates_all_gather",),
    # Host->device placed updates (not traced collectives, priced at a
    # fixed rate in obs/ici.py): the serving hub's batched row mirror —
    # one coalesced ExtOriginations placement per device step
    # (swim_tpu/serve/hub.py).
    "placed": ("ext_mirror_rows",),
}

ICI_TERMS = tuple(sorted(
    t for fam in ICI_TERM_FAMILIES.values() for t in fam))

# ---------------------------------------------------------------------------
# HLO collective scanner (shared with tests/test_ring_shard.py).
# ---------------------------------------------------------------------------

DTYPE_BYTES = {
    "pred": 1, "u8": 1, "s8": 1,
    "u16": 2, "s16": 2, "f16": 2, "bf16": 2,
    "u32": 4, "s32": 4, "f32": 4,
    "u64": 8, "s64": 8, "f64": 8,
}

HLO_COLLECTIVE_OPS = ("collective-permute", "all-gather", "all-reduce",
                      "all-to-all", "collective-broadcast")

_HLO_COLL_RE = re.compile(
    r"\b(" + "|".join(HLO_COLLECTIVE_OPS) + r")(-start|-done)?\(")
_HLO_SHAPE_RE = re.compile(r"\b(\w+)\[([\d,]*)\]")


def scan_hlo_collectives(hlo_text: str) -> list[dict]:
    """Inventory of collective instructions in an HLO module text.

    One record per instruction line: ``{"op", "payloads", "payload_bytes",
    "line"}`` where ``payloads`` lists every typed shape on the line as
    ``{"dtype", "elems", "bytes"}`` and ``payload_bytes`` is the largest
    (a win-sized operand can't hide inside an async-start tuple).  ``-done``
    halves of async pairs are skipped so each transfer counts once.

    NOTE: counts are STATIC instruction counts — collectives inside the
    branches of a `conditional` all appear even though one executes.  Use
    per-line dtype/shape checks on these records (branch-duplication-proof)
    and `jaxpr_collective_bytes` for executed-byte accounting.
    """
    records = []
    for line in hlo_text.splitlines():
        if "=" not in line:
            continue
        m = _HLO_COLL_RE.search(line)
        if not m or m.group(2) == "-done":
            continue
        payloads = []
        for sm in _HLO_SHAPE_RE.finditer(line):
            dtype, dims = sm.group(1), sm.group(2)
            if dtype not in DTYPE_BYTES:
                continue
            elems = 1
            for part in dims.split(","):
                if part:
                    elems *= int(part)
            payloads.append({"dtype": dtype, "elems": elems,
                             "bytes": elems * DTYPE_BYTES[dtype]})
        records.append({
            "op": m.group(1),
            "payloads": payloads,
            "payload_bytes": max((p["bytes"] for p in payloads), default=0),
            "line": line.strip()[:160],
        })
    return records


def max_payload_elems(records: list[dict], op: str) -> int:
    """Largest element count on any `op` instruction line (1 if none)."""
    worst = 1
    for r in records:
        if r["op"] != op:
            continue
        for p in r["payloads"]:
            worst = max(worst, p["elems"])
    return worst


def cperm_payloads(records: list[dict]) -> list[dict]:
    """Flat payload list across all collective-permute instructions."""
    return [p for r in records if r["op"] == "collective-permute"
            for p in r["payloads"]]


# ---------------------------------------------------------------------------
# jaxpr walkers.  These take jaxpr objects (so the caller has already
# imported jax); the walkers themselves only touch .eqns/.params/.aval.
# ---------------------------------------------------------------------------

_JAXPR_COLLECTIVES = {
    "ppermute": "ppermute",
    "psum": "psum",
    "psum_invariant": "psum",
    "all_gather": "all_gather",
    "all_to_all": "all_to_all",
}

_FORBIDDEN_PRIMS = ("pure_callback", "io_callback", "debug_callback",
                    "callback", "host_callback_call")


def _param_jaxprs(eqn):
    for v in eqn.params.values():
        for s in (v if isinstance(v, (list, tuple)) else (v,)):
            inner = getattr(s, "jaxpr", None)
            if inner is not None and hasattr(inner, "eqns"):
                yield inner
            elif hasattr(s, "eqns"):
                yield s


def _aval_bytes(avals) -> int:
    total = 0
    for a in avals:
        shape = getattr(a, "shape", None)
        dtype = getattr(a, "dtype", None)
        if shape is None or dtype is None:
            continue
        elems = 1
        for dim in shape:
            elems *= int(dim)
        total += elems * dtype.itemsize
    return total


def jaxpr_collective_bytes(jaxpr) -> dict[str, int]:
    """Executed collective payload bytes per family, from the trace.

    `cond`/`switch` contributes the max over branches (exactly one runs);
    `scan` contributes length x body; a `while` whose body holds
    collectives is unbounded statically and is surfaced under the
    ``"while_unbounded"`` key so the contract fails loud instead of
    under-counting.  all_gather counts output bytes (what lands per
    chip); everything else counts input payload bytes.
    """
    out: dict[str, int] = {}

    def merge(dst, src, mult=1):
        for k, v in src.items():
            dst[k] = dst.get(k, 0) + mult * v

    def walk(j, acc):
        for eqn in j.eqns:
            name = eqn.primitive.name
            if name in _JAXPR_COLLECTIVES:
                family = _JAXPR_COLLECTIVES[name]
                avals = [v.aval for v in
                         (eqn.outvars if name == "all_gather"
                          else eqn.invars)]
                acc[family] = acc.get(family, 0) + _aval_bytes(avals)
            elif name == "cond":
                best: dict[str, int] = {}
                for branch in eqn.params["branches"]:
                    sub: dict[str, int] = {}
                    walk(branch.jaxpr, sub)
                    if sum(sub.values()) > sum(best.values()):
                        best = sub
                merge(acc, best)
            elif name == "scan":
                sub = {}
                walk(eqn.params["jaxpr"].jaxpr, sub)
                merge(acc, sub, mult=int(eqn.params.get("length", 1)))
            elif name == "while":
                sub = {}
                walk(eqn.params["body_jaxpr"].jaxpr, sub)
                if sub:
                    acc["while_unbounded"] = (
                        acc.get("while_unbounded", 0) + sum(sub.values()))
            else:
                for inner in _param_jaxprs(eqn):
                    walk(inner, acc)

    walk(jaxpr, out)
    return out


def jaxpr_count_primitive(jaxpr, prim_name: str) -> int:
    """Static count of `prim_name` equations, all sub-jaxprs included."""
    count = 0
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == prim_name:
            count += 1
        for inner in _param_jaxprs(eqn):
            count += jaxpr_count_primitive(inner, prim_name)
    return count


def jaxpr_hygiene_violations(jaxpr) -> list[str]:
    """Sorted, deduplicated f64/callback violations in a traced program."""
    found: set[str] = set()

    def walk(j):
        for eqn in j.eqns:
            name = eqn.primitive.name
            if name in _FORBIDDEN_PRIMS:
                found.add(f"callback:{name}")
            for var in (*eqn.invars, *eqn.outvars):
                dtype = getattr(getattr(var, "aval", None), "dtype", None)
                if dtype is not None and str(dtype) == "float64":
                    found.add(f"f64:{name}")
            for inner in _param_jaxprs(eqn):
                walk(inner)

    walk(jaxpr)
    return sorted(found)


def tally_unattributed(family_bytes: dict[str, int],
                       breakdown: dict[str, int]) -> dict[str, int]:
    """Per-family bytes the trace moves but no named tally term claims.

    Returns ``{family: max(0, traced - attributed)}`` plus an
    ``"unknown_term:<key>"`` entry for any breakdown key outside
    ICI_TERM_FAMILIES (vocabulary drift fails too) and the pass-through
    of any ``while_unbounded`` traced bytes.
    """
    out: dict[str, int] = {}
    known = set(ICI_TERMS)
    for key in breakdown:
        if key not in known:
            out[f"unknown_term:{key}"] = int(breakdown[key])
    for family, traced in sorted(family_bytes.items()):
        if family == "while_unbounded":
            out[family] = int(traced)
            continue
        terms = ICI_TERM_FAMILIES.get(family, ())
        attributed = sum(int(breakdown.get(t, 0)) for t in terms)
        out[family] = max(0, int(traced) - attributed)
    return out


# ---------------------------------------------------------------------------
# Audit arms.  Geometry mirrors tests/test_ring_shard.py's SMALL_GEOM —
# parity there is pinned against the global engine at the same geometry,
# so the wire shapes audited here are the shapes the parity pin covers.
# ---------------------------------------------------------------------------

SMALL_GEOM = dict(suspicion_mult=1.0, k_indirect=1, max_piggyback=2,
                  ring_window_periods=2, ring_view_c=2)

WIRE_ARMS = (
    ("window+wide", {}),
    ("window+packed", {"ring_sel_scope": "period",
                       "ring_scalar_wire": "packed"}),
    ("compact+wide", {"ring_sel_scope": "period",
                      "ring_ici_wire": "compact"}),
    ("compact+packed", {"ring_sel_scope": "period",
                        "ring_ici_wire": "compact",
                        "ring_scalar_wire": "packed"}),
)

# Bookkeeping ceiling for all-gather payloads (elements): OB*D candidate
# keys — far below one shard's node rows.  Same constant the historical
# test pin used.
ALLGATHER_MAX_ELEMS = 2048

MEMWALL_ARTIFACT = os.path.join("bench_results", "memwall_report.json")


def _tree_bytes(tree) -> int:
    import jax
    total = 0
    for leaf in jax.tree.leaves(tree):
        shape = getattr(leaf, "shape", ())
        dtype = getattr(leaf, "dtype", None)
        if dtype is None:
            continue
        elems = 1
        for dim in shape:
            elems *= int(dim)
        total += elems * dtype.itemsize
    return total


def _program_sweep(n: int, capacity: int = 4):
    """Three FaultProgram VALUES at one capacity — the retrace sweep."""
    from swim_tpu.sim import faults

    base = faults.as_program(faults.none(n), capacity=capacity)
    gray = faults.with_segment(base, 0, start=1, end=6, kind="gray",
                               level=0.5)
    lossy = faults.with_segment(
        faults.as_program(faults.none(n), capacity=capacity),
        0, start=2, end=5, kind="link_loss", level=0.3)
    return (base, gray, lossy)


def run_audit(wire_n: int = 512, retrace_n: int = 256, d: int = 8,
              periods: int = 4, repo_root: str | None = None) -> dict:
    """Run every contract arm and return the (byte-stable) report dict.

    Deviceless: traces and AOT-compiles on the host mesh, never executes
    on hardware beyond tiny retrace-probe runs.  Needs `d` devices
    (tests/CLI force the 8-device virtual CPU mesh).
    """
    import jax
    import jax.numpy as jnp

    from swim_tpu import SwimConfig
    from swim_tpu.models import dense, ring, rumor
    from swim_tpu.parallel import mesh as pmesh, ring_shard
    from swim_tpu.obs import ici
    from swim_tpu.sim import faults, runner

    if len(jax.devices()) < d:
        raise RuntimeError(
            f"audit needs {d} devices, have {len(jax.devices())} — run via "
            "'swim-tpu audit' (which forces the virtual CPU mesh) or set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8")
    root = repo_root or os.getcwd()
    mesh = pmesh.make_mesh(d)
    key = jax.random.key(0)

    checks: dict[str, list[dict]] = {name: [] for name in CONTRACTS}
    totals = {"retraces_extra": 0, "unattributed_collective_bytes": 0,
              "undonated_bytes": 0, "barrier_chains_missing": 0}

    def add(contract: str, arm: str, ok: bool, detail: str) -> None:
        checks[contract].append(
            {"arm": arm, "ok": bool(ok), "detail": str(detail)})

    # -- retrace budget: one compile per arm across a program-value sweep --
    progs = _program_sweep(retrace_n)
    retrace_arms = (
        ("dense", runner.run_study, (0, 4), (1,), dense.init_state, ()),
        ("rumor", runner.run_study_rumor, (0, 4, 5), (1,),
         rumor.init_state, (None,)),
        ("ring", runner.run_study_ring, (0, 4, 5), (1,),
         ring.init_state, (None,)),
    )
    cfg_r = SwimConfig(n_nodes=retrace_n, **SMALL_GEOM)
    for name, jitted, static, donate, init, extra in retrace_arms:
        traces = []
        body = jitted.__wrapped__

        def counted(*a, _body=body, _traces=traces):
            _traces.append(1)
            return _body(*a)

        probe = jax.jit(counted, static_argnums=static,
                        donate_argnums=donate)
        for prog in progs:
            probe(cfg_r, init(cfg_r), prog, key, periods, *extra)
        extra_traces = max(0, len(traces) - 1)
        totals["retraces_extra"] += extra_traces
        add("retrace_budget", name, len(traces) == 1,
            f"{len(traces)} trace(s) over {len(progs)} program values")

    # streaming chunk: two plan values through one jitted chunk
    chunk_traces = []
    chunk_body = runner._run_study_ring_chunk.__wrapped__

    def counted_chunk(*a):
        chunk_traces.append(1)
        return chunk_body(*a)

    chunk_probe = jax.jit(counted_chunk, static_argnums=(0, 5, 6),
                          donate_argnums=(1, 2))
    for crash_at in (2, 3):
        plan_v = faults.with_crashes(faults.none(retrace_n), [5], [crash_at])
        state_v = ring.init_state(cfg_r)
        track_v = runner.compact_track_init(plan_v, periods)
        chunk_probe(cfg_r, state_v, track_v, plan_v, key, 0, None)
    totals["retraces_extra"] += max(0, len(chunk_traces) - 1)
    add("retrace_budget", "ring_stream_chunk", len(chunk_traces) == 1,
        f"{len(chunk_traces)} trace(s) over 2 plan values")

    # sharded step: jit cache must hold ONE entry across program values
    cfg_s = SwimConfig(n_nodes=retrace_n, ring_sel_scope="period",
                       ring_ici_wire="compact", ring_scalar_wire="packed",
                       **SMALL_GEOM)
    step_s = jax.jit(ring_shard.mapped_step(cfg_s, mesh, program=True))
    rnd_s = ring.draw_period_ring(key, 0, cfg_s)
    for prog in progs[:2]:
        st_p, pl_p = ring_shard.place(cfg_s, mesh,
                                      ring.init_state(cfg_s), prog)
        step_s(st_p, pl_p, rnd_s)
    cache = step_s._cache_size()
    totals["retraces_extra"] += max(0, cache - 1)
    add("retrace_budget", "ringshard", cache == 1,
        f"{cache} compiled entrie(s) over 2 program values")

    # -- donation coverage: AOT alias bytes == donated bytes, exactly --
    plan_d = faults.with_crashes(faults.none(retrace_n), [5], [2])
    state_ring = ring.init_state(cfg_r)
    track_d = runner.compact_track_init(plan_d, periods)
    states_b = runner.batch_states([dense.init_state(cfg_r)] * 2)
    plans_b = runner.batch_states(list(_program_sweep(retrace_n)[:2]))
    keys_b = jax.random.split(key, 2)
    donation_arms = (
        ("dense", runner.run_study,
         (cfg_r, dense.init_state(cfg_r), plan_d, key, periods),
         lambda a: (a[1],)),
        ("rumor", runner.run_study_rumor,
         (cfg_r, rumor.init_state(cfg_r), plan_d, key, periods, None),
         lambda a: (a[1],)),
        ("ring", runner.run_study_ring,
         (cfg_r, state_ring, plan_d, key, periods, None),
         lambda a: (a[1],)),
        ("ring_stream_chunk", runner._run_study_ring_chunk,
         (cfg_r, ring.init_state(cfg_r), track_d, plan_d, key, 0, None),
         lambda a: (a[1], a[2])),
        ("batch", runner.run_study_batch,
         (cfg_r, states_b, plans_b, keys_b, periods, "dense", None),
         lambda a: (a[1],)),
    )
    for name, jitted, args, donated_of in donation_arms:
        analysis = jitted.lower(*args).compile().memory_analysis()
        alias = int(analysis.alias_size_in_bytes)
        donated = sum(_tree_bytes(t) for t in donated_of(args))
        totals["undonated_bytes"] += max(0, donated - alias)
        add("donation_coverage", name, alias == donated,
            f"alias_bytes={alias} donated_bytes={donated}")

    # -- wire, tally, hygiene over the 2x2 sharded wire matrix --
    shard_rows = wire_n // d
    ppermute_bytes_by_arm: dict[str, int] = {}
    family_bytes_by_arm: dict[str, dict] = {}
    for arm_name, overrides in WIRE_ARMS:
        cfg_w = SwimConfig(n_nodes=wire_n, **SMALL_GEOM, **overrides)
        plan_w = faults.with_crashes(faults.none(wire_n), [5], [2])
        st_w, pl_w = ring_shard.place(cfg_w, mesh,
                                      ring.init_state(cfg_w), plan_w)
        rnd_w = ring.draw_period_ring(key, 0, cfg_w)
        mapped = ring_shard.mapped_step(cfg_w, mesh)
        jpr = jax.make_jaxpr(mapped)(st_w, pl_w, rnd_w)
        hlo = jax.jit(mapped).lower(st_w, pl_w, rnd_w).compile().as_text()
        records = scan_hlo_collectives(hlo)

        cperms = [r for r in records if r["op"] == "collective-permute"]
        problems = []
        if not cperms:
            problems.append("no collective-permute wave rolls")
        packed = cfg_w.ring_scalar_wire == "packed"
        if packed:
            if not any(p["dtype"] == "u8" for p in cperm_payloads(records)):
                problems.append("no u8 cperm payload on the packed wire")
            wide_lanes = sorted({
                f"{p['dtype']}[{p['elems']}]"
                for p in cperm_payloads(records)
                if p["dtype"] in ("s32", "pred")
                and p["elems"] == shard_rows})
            if wide_lanes:
                problems.append(
                    f"[S]-shaped scalar lanes on the packed wire: "
                    f"{wide_lanes}")
        ag_worst = max_payload_elems(records, "all-gather")
        if ag_worst > ALLGATHER_MAX_ELEMS:
            problems.append(
                f"all-gather payload {ag_worst} elems > bookkeeping "
                f"ceiling {ALLGATHER_MAX_ELEMS}")
        add("wire_contracts", arm_name, not problems,
            "; ".join(problems) if problems
            else f"{len(cperms)} cperm instruction(s), "
                 f"all-gather max {ag_worst} elems")

        family_bytes = jaxpr_collective_bytes(jpr.jaxpr)
        family_bytes_by_arm[arm_name] = family_bytes
        ppermute_bytes_by_arm[arm_name] = int(
            family_bytes.get("ppermute", 0))
        tally = ici.trace_ici_bytes(cfg_w, d)
        unattributed = tally_unattributed(family_bytes,
                                          tally["breakdown"])
        loose = {k: v for k, v in unattributed.items() if v}
        totals["unattributed_collective_bytes"] += sum(loose.values())
        add("ici_tally_completeness", arm_name, not loose,
            f"unattributed={loose}" if loose
            else f"traced={ {k: int(v) for k, v in sorted(family_bytes.items())} } "
                 "fully attributed")

        violations = jaxpr_hygiene_violations(jpr.jaxpr)
        add("hot_path_hygiene", f"ringshard/{arm_name}", not violations,
            "; ".join(violations) if violations else "clean")

    # Serving-hub mirroring bytes (swim_tpu/serve): pricing the coalesced
    # ExtOriginations placement must (a) stay inside the tally vocabulary
    # (no unknown_term drift), (b) charge exactly 16 bytes per reserved
    # slot (4 x 4-byte lanes), and (c) leave every traced collective byte
    # of the dense-wire arm attributed — the completeness contract
    # extended over the hub's ext seam.
    from swim_tpu.serve.hub import EXT_CAPACITY as serve_cap

    cfg_s = SwimConfig(n_nodes=wire_n, **SMALL_GEOM)
    tally_s = ici.trace_ici_bytes(cfg_s, d, ext_capacity=serve_cap)
    mirror_b = int(tally_s["breakdown"].get("ext_mirror_rows", 0))
    loose_s = {k: v for k, v in tally_unattributed(
        family_bytes_by_arm["window+wide"],
        tally_s["breakdown"]).items() if v}
    ok_s = mirror_b == 16 * serve_cap and not loose_s
    totals["unattributed_collective_bytes"] += sum(loose_s.values())
    add("ici_tally_completeness", "serve_ext_mirror", ok_s,
        f"ext_mirror_rows={mirror_b} (capacity {serve_cap}), "
        + (f"unattributed={loose_s}" if loose_s else "fully attributed"))

    compact_b = ppermute_bytes_by_arm["compact+packed"]
    wide_b = ppermute_bytes_by_arm["window+wide"]
    add("wire_contracts", "compact_vs_window", 0 < compact_b < wide_b,
        f"ppermute bytes/period/chip: compact+packed={compact_b} "
        f"window+wide={wide_b}")

    # -- hygiene over the study bodies (whole traced study, per engine) --
    prog_h = progs[0]
    hygiene_arms = (
        ("dense", lambda: jax.make_jaxpr(
            lambda s, p, k: runner.run_study.__wrapped__(
                cfg_r, s, p, k, periods))(
            dense.init_state(cfg_r), prog_h, key)),
        ("rumor", lambda: jax.make_jaxpr(
            lambda s, p, k: runner.run_study_rumor.__wrapped__(
                cfg_r, s, p, k, periods, None))(
            rumor.init_state(cfg_r), prog_h, key)),
        ("ring", lambda: jax.make_jaxpr(
            lambda s, p, k: runner.run_study_ring.__wrapped__(
                cfg_r, s, p, k, periods, None))(
            ring.init_state(cfg_r), prog_h, key)),
    )
    for name, trace in hygiene_arms:
        violations = jaxpr_hygiene_violations(trace().jaxpr)
        add("hot_path_hygiene", f"study/{name}", not violations,
            "; ".join(violations) if violations else "clean")

    # -- barrier survival --
    up = jnp.ones((retrace_n,), jnp.bool_)
    census_forced = jax.make_jaxpr(
        lambda s, u: ring.live_knower_counts(cfg_r, s, u,
                                             pair_budget=4 * retrace_n))(
        ring.init_state(cfg_r), up)
    n_forced = jaxpr_count_primitive(census_forced.jaxpr,
                                     "optimization_barrier")
    if n_forced < 2:
        totals["barrier_chains_missing"] += 1
    add("barrier_survival", "census_chunked", n_forced >= 2,
        f"{n_forced} optimization_barrier eqn(s) in the chunked census "
        "chain (floor 2)")

    cfg_pull = SwimConfig(n_nodes=retrace_n, ring_probe="pull",
                          **SMALL_GEOM)
    plan_p = faults.none(retrace_n)
    rnd_p = ring.draw_period_ring(key, 0, cfg_pull)
    pull_jpr = jax.make_jaxpr(
        lambda s, r: ring.step(cfg_pull, s, plan_p, r))(
        ring.init_state(cfg_pull), rnd_p)
    n_pull = jaxpr_count_primitive(pull_jpr.jaxpr, "optimization_barrier")
    if n_pull < 1:
        totals["barrier_chains_missing"] += 1
    add("barrier_survival", "pull_gather_step", n_pull >= 1,
        f"{n_pull} optimization_barrier eqn(s) in the pull-probe step "
        "(floor 1)")

    # sharded GSPMD survival: read the committed 64M AOT row.  A
    # compile-OOM there IS the chain dying under the sharded lowering —
    # waived (ROADMAP item 2) until re-pinned.
    memwall_path = os.path.join(root, MEMWALL_ARTIFACT)
    if os.path.exists(memwall_path):
        with open(memwall_path) as fh:
            rows = json.load(fh).get("rows", [])
        shard_rows_64m = [r for r in rows
                          if r.get("engine") == "ringshard"
                          and int(r.get("n", 0)) >= 64_000_000]
        if shard_rows_64m:
            oomed = any(r.get("compile_oom") for r in shard_rows_64m)
            add("barrier_survival", "sharded_gspmd_64m", not oomed,
                "64M ringshard AOT row compile-OOMs (census chain dropped "
                "under GSPMD)" if oomed
                else "64M ringshard AOT row compiles within accounting")
        else:
            add("barrier_survival", "sharded_gspmd_64m", True,
                "no >=64M ringshard row in memwall artifact (nothing to "
                "check)")
    else:
        add("barrier_survival", "sharded_gspmd_64m", True,
            "memwall artifact absent (nothing to check)")

    # -- assemble, apply waivers --
    waived_keys = {(w["contract"], w["arm"]): w for w in WAIVERS}
    contracts_out = {}
    n_checks = n_failed = n_waived = 0
    for contract in sorted(CONTRACTS):
        arm_rows = []
        worst = "pass"
        for row in checks[contract]:
            n_checks += 1
            status = "pass"
            if not row["ok"]:
                waiver = waived_keys.get((contract, row["arm"]))
                if waiver is not None:
                    status = "waived"
                    n_waived += 1
                    row = dict(row, waived_by=waiver["pointer"])
                else:
                    status = "fail"
                    n_failed += 1
            arm_rows.append(dict(row, status=status))
            if status == "fail":
                worst = "fail"
            elif status == "waived" and worst != "fail":
                worst = "waived"
        contracts_out[contract] = {
            "description": CONTRACTS[contract],
            "status": worst,
            "checks": arm_rows,
        }

    totals.update(checks_total=n_checks, failures=n_failed,
                  waived=n_waived)
    return {
        "schema": 1,
        "platform": jax.devices()[0].platform,
        "devices": d,
        "wire_n": wire_n,
        "retrace_n": retrace_n,
        "periods": periods,
        "contracts": contracts_out,
        "waivers": list(WAIVERS),
        "totals": totals,
    }


# ---------------------------------------------------------------------------
# Report plumbing: checking, byte-stable writing, gauges.
# ---------------------------------------------------------------------------

def check_report(report: dict) -> tuple[bool, list[str]]:
    """(ok, failures) — failures list unwaived failing checks by name."""
    failures = []
    for contract in sorted(report["contracts"]):
        for row in report["contracts"][contract]["checks"]:
            if row["status"] == "fail":
                failures.append(
                    f"{contract}/{row['arm']}: {row['detail']}")
    return (not failures), failures


def write_report(report: dict, path: str) -> None:
    """Atomic, byte-stable write: sorted keys, no timestamps, trailing
    newline — reruns of the same tree produce the identical file."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    text = json.dumps(report, indent=2, sort_keys=True) + "\n"
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                               prefix=".audit_")
    try:
        with os.fdopen(fd, "w") as fh:
            fh.write(text)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


AUDIT_GAUGES = {
    "swim_audit_checks_total":
        "contract checks evaluated in the last audit run",
    "swim_audit_failures_total":
        "unwaived failing contract checks (CI-red)",
    "swim_audit_waived_total":
        "failing checks covered by an expected-fail waiver",
    "swim_audit_retraces_extra_total":
        "retraces beyond the one-compile-per-arm budget",
    "swim_audit_unattributed_collective_bytes":
        "traced collective bytes not attributed to a named obs/ici.py "
        "tally term",
    "swim_audit_undonated_bytes":
        "donated-argument bytes not aliased in the compiled executable",
    "swim_audit_barrier_chains_missing":
        "barrier arms whose ordering chain fell below the contract floor",
}


def gauge_values(report: dict) -> dict[str, int | float]:
    """Metric name -> value for obs/expo.py (one per AUDIT_GAUGES key)."""
    totals = report["totals"]
    return {
        "swim_audit_checks_total": totals["checks_total"],
        "swim_audit_failures_total": totals["failures"],
        "swim_audit_waived_total": totals["waived"],
        "swim_audit_retraces_extra_total": totals["retraces_extra"],
        "swim_audit_unattributed_collective_bytes":
            totals["unattributed_collective_bytes"],
        "swim_audit_undonated_bytes": totals["undonated_bytes"],
        "swim_audit_barrier_chains_missing":
            totals["barrier_chains_missing"],
    }
