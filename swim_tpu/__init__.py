"""swim_tpu — a TPU-native SWIM gossip / failure-detection framework.

Built from scratch against the capabilities of the Haskell reference
`jpfuentes2/swim` (see SURVEY.md): the per-node protocol tick — randomized
ping-target selection, k-indirect probing, piggybacked gossip dissemination,
suspicion/incarnation state transitions — plus transports, codec, and a node
runtime; and, as the north star, a vectorized simulator that runs the
protocol for millions of virtual nodes as one jit-compiled JAX step over a
sharded TPU mesh.

Layering:
  swim_tpu.types / config   — protocol lattice & constants (pure Python)
  swim_tpu.core             — real-node framework: membership, suspicion,
                              gossip buffer, codec, Transport ABC
                              (in-process + UDP), Node runtime, demo CLI
  swim_tpu.models           — simulators: scalar oracle, dense O(N²) engine,
                              scalable O(R·N) rumor engine
  swim_tpu.ops              — vectorized building blocks (lattice, sampling,
                              mailbox delivery, Pallas kernels)
  swim_tpu.parallel         — mesh construction, sharded step, collectives
  swim_tpu.sim              — fault injection, runners, metrics collection
  swim_tpu.bridge           — gRPC contract for driving the simulator from an
                              external (e.g. Haskell) SWIM core
"""

__version__ = "0.1.0"

from swim_tpu.config import STOCK_DEMO, SwimConfig
from swim_tpu.types import MsgKind, Opinion, Status, Update, merge

__all__ = [
    "STOCK_DEMO",
    "SwimConfig",
    "MsgKind",
    "Opinion",
    "Status",
    "Update",
    "merge",
    "__version__",
]
