"""Explicitly-sharded ring engine: shard_map + collective-permute rolls.

Why this exists: jitting ring.step under GSPMD shardings is *correct* on
a mesh (the driver dry-runs it), but a `jnp.roll` by a TRACED shift is
opaque to the partitioner — compiling the sharded step at N=4096/D=8
inserts 56 all-gathers, 14 of them replicating the full win heard-bit
matrix every period (~590 MB/period/device of ICI traffic at the 1M-node
target).  The rotor protocol only needs to MOVE each wave's payload by
one shared offset.  This module runs the SAME `ring.step` body inside
`shard_map` with a `ShardOps` object that supplies the TPU-native data
movement (SURVEY.md §5 "Distributed comm backend"):

  * **Rolls → two collective-permutes.**  A global roll by traced d
    splits as d = k·S + r (S = rows per shard): every shard's rolled
    block is a window into shard (me+k) and (me+k+1)'s rows, fetched
    with static-permutation `ppermute`s selected by a D-way
    `lax.switch` on k, then stitched with one dynamic slice.  Per roll:
    2 neighbor-block transfers on ICI — no all-gather, no replication.
  * **Wave payloads → SWIM's bounded piggyback (optional).**  With
    `cfg.ring_ici_wire == "compact"` the per-wave sel-window rolls do
    not ship the dense u32[S, WW] block at all: the first-B-selected
    rows (<= B set bits each — the protocol's own piggyback bound)
    pack once per period into B slot indices (ops/wavepack.py), one
    boundary block is prefetched, and each wave then moves ONE packed
    [S, B] narrow-int block — ~WW*32/B fewer ICI bytes per wave,
    bitwise-equal after receiver-side unpack (see merge_waves).
  * **Scalar wave payloads → one bit-packed bundle (optional).**  With
    `cfg.ring_scalar_wire == "packed"` each wave's SCALAR vectors — ok
    chain (bool), partition ids (u8), buddy col/val codes — fuse into
    ONE u8 ppermute payload per neighbor block (ops/wavepack.py
    pack_bundle: bools ride as 1 bit/node), and lone bool rolls
    bit-pack too (see roll_bundle / roll_from).  Bitwise-equal after
    receiver-side unpack.
  * **Global reductions → psum** of per-shard partials (all integer —
    bitwise-exact, no float reassociation concerns).
  * **Node-axis scatter/gather by global id → masked local ops.**  Each
    shard applies exactly the updates addressed to its rows (indices
    outside its range drop); gathers contribute the owned value and
    psum-merge (single owner per id, so sum == value).
  * **First-k-true candidate compaction → local top_k + one small
    all_gather** ([D, OB] keys) + replicated merge, instead of a global
    scatter over the 2M-entry candidate vector.

The rumor table, fault-plan scalars, and all Phase D allocation logic
are REPLICATED: every shard computes them from replicated inputs and
psum/all_gather-merged values, so the copies stay identical by
construction.  Results are bitwise-equal to the single-program engine —
tests/test_ring_shard.py runs the full crash lifecycle on the 8-device
CPU mesh and asserts equality against `ring.step` period by period, and
pins the compiled HLO's collective set (collective-permutes present, no
win-sized all-gathers).

Reference parity note: jpfuentes2/swim's transport is process-to-process
sockets (SURVEY.md §1, tree unavailable — §0); this module is the
TPU-native analog of its network fan-out, with XLA collectives over
ICI/DCN in place of UDP datagrams.

Pull-uniform probing (`cfg.ring_probe == "pull"`) is supported (round
4): its random-peer reads route through nodewise ring-pass exchanges —
each shard's query bundle collective-permutes around the device ring,
answered from the holding shard — bitwise-equal to the single-program
pull engine (tests/test_ring_shard.py).  Deliberately not the
throughput path; the rotor flagship remains the fast mode.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

try:
    from jax import shard_map as _shard_map      # jax >= 0.8

    def shard_map(f, *, mesh, in_specs, out_specs, check_rep=False):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=check_rep)
except ImportError:                              # pragma: no cover
    from jax.experimental.shard_map import shard_map

from swim_tpu.config import SwimConfig
from swim_tpu.models import ring
from swim_tpu.obs.engine import EngineFrame, frame_from_tap
from swim_tpu.ops import wavepack
from swim_tpu.parallel import mesh as pmesh
from swim_tpu.sim.faults import FaultPlan, FaultProgram

AXIS = pmesh.NODE_AXIS

# Audit mode for the roll_from replicated-shift invariant (see its
# docstring): when True, every roll prints the cross-shard spread of its
# shift, which must be 0.  Costs a pmax+pmin+host-callback per roll —
# debug only.
DEBUG_REPLICATED = False


class ShardOps:
    """ring.GlobalOps twin for one node-axis shard inside shard_map.

    Every method returns the same VALUES as GlobalOps computing on the
    full node axis, restricted to (node-axis results) this shard's rows
    or (reductions / gathers) replicated across shards.  Must be
    constructed INSIDE the shard_map-traced function (uses axis_index).
    """

    supports_random_gather = True   # via the nodewise ring-pass
    #                                 exchanges below (round 4) — the
    #                                 fidelity pull mode, not the
    #                                 throughput path

    def __init__(self, cfg: SwimConfig, n_shards: int):
        self.n = cfg.n_nodes
        self.d = n_shards
        self.s = self.n // n_shards
        self.lo = jax.lax.axis_index(AXIS).astype(jnp.int32) * self.s
        self.wire = cfg.ring_ici_wire
        self.scalar_wire = cfg.ring_scalar_wire
        g = ring.geometry(cfg)
        self.ww = g.ww
        self.b_pig = min(cfg.max_piggyback, g.ww * ring.WORD)

    # -- node identity ----------------------------------------------------
    def ids(self):
        return self.lo + jnp.arange(self.s, dtype=jnp.int32)

    def zeros_nodes(self, dtype, cols: int | None = None):
        shape = (self.s,) if cols is None else (self.s, cols)
        return jnp.zeros(shape, dtype)

    def full_nodes(self, val, dtype):
        return jnp.full((self.s,), val, dtype)

    # -- reductions -------------------------------------------------------
    def gsum(self, partial):
        return jax.lax.psum(partial, AXIS)

    def gmax(self, partial):
        return jax.lax.pmax(partial, AXIS)

    # -- communication ----------------------------------------------------
    def _rot(self, x, k_static: int):
        """The block held by shard (me + k) mod D, for every shard."""
        if k_static % self.d == 0:
            return x
        perm = [(p, (p - k_static) % self.d) for p in range(self.d)]
        return jax.lax.ppermute(x, AXIS, perm)

    def roll_from(self, x, d, label=None):
        """x at global node (i + d) mod n for my rows i: d = k·S + r, so
        the answer is rows [r, S) of shard me+k plus rows [0, r) of
        shard me+k+1 — two ppermutes (switch-selected static k) and one
        dynamic slice.  `label` names the roll for the ICI byte tally
        (obs/ici.py CountingOps); inert on the real wire.

        With cfg.ring_scalar_wire == "packed", a lone bool node vector
        still ships bit-packed: it delegates to roll_bundle, whose
        payload for one bool part is u32[ceil(S/32)] bitcast to bytes —
        32x narrower than the bool lanes the wide wire moves.

        INVARIANT: `d` must be REPLICATED across shards (identical traced
        value on every shard). The lax.switch selects which ppermute
        branch runs, and collectives must be entered by all shards in the
        same order — a per-shard-divergent `d` would desynchronize them
        (hang or silent corruption), and shard_map's check_rep=False means
        nothing verifies this at trace time. All current callers derive
        `d` from `rnd.*` fields, which place() replicates by construction.
        Set DEBUG_REPLICATED=True to audit the invariant at runtime (the
        printed spread must be 0 on every call)."""
        del label
        if (self.scalar_wire == "packed" and x.ndim == 1
                and x.dtype == jnp.bool_):
            return self.roll_bundle((x,), d)[0]
        dd = jnp.mod(jnp.asarray(d, jnp.int32), self.n)
        if DEBUG_REPLICATED:
            spread = (jax.lax.pmax(dd, AXIS) - jax.lax.pmin(dd, AXIS))
            jax.debug.print("roll_from shift spread (must be 0): {s}",
                            s=spread)
        k = dd // self.s
        r = jnp.mod(dd, self.s)
        a = jax.lax.switch(
            k, [functools.partial(self._rot, k_static=kk)
                for kk in range(self.d)], x)
        b = self._rot(a, 1)
        ab = jnp.concatenate([a, b], axis=0)
        return jax.lax.dynamic_slice_in_dim(ab, r, self.s, axis=0)

    def roll_bundle(self, parts, d, labels=None):
        """roll_from over several same-offset node vectors at once —
        the packed scalar wire's fusion seam (cfg.ring_scalar_wire).

        "wide": each part rolls on its own (two dtype-wide neighbor
        blocks per part, exactly the historical wire).

        "packed": the parts fuse into ONE u8 payload per neighbor block
        (ops/wavepack.py pack_bundle — bools bit-pack to u32 words,
        narrow ints bitcast to bytes), so the whole wave costs one
        ppermute pair of sum-of-packed-bytes lanes no matter how many
        vectors ride.  Packing wraps only the ppermute leg: both
        neighbor blocks unpack back to typed [S] vectors BEFORE the
        r-offset stitch, so the dynamic slice works at row granularity
        and never splits a bit-packed word across the block boundary.
        Bitwise-exact round-trip (tests/test_wavepack.py), so the parity
        contract is inherited unchanged.

        The same replicated-shift invariant as roll_from applies."""
        del labels
        if not parts:
            return ()
        if self.scalar_wire != "packed":
            return tuple(self.roll_from(x, d) for x in parts)
        dd = jnp.mod(jnp.asarray(d, jnp.int32), self.n)
        if DEBUG_REPLICATED:
            spread = (jax.lax.pmax(dd, AXIS) - jax.lax.pmin(dd, AXIS))
            jax.debug.print("roll_bundle shift spread (must be 0): {s}",
                            s=spread)
        k = dd // self.s
        r = jnp.mod(dd, self.s)
        payload = wavepack.pack_bundle(parts)
        a = jax.lax.switch(
            k, [functools.partial(self._rot, k_static=kk)
                for kk in range(self.d)], payload)
        b = self._rot(a, 1)
        pa = wavepack.unpack_bundle(a, parts)
        pb = wavepack.unpack_bundle(b, parts)
        return tuple(
            jax.lax.dynamic_slice_in_dim(
                jnp.concatenate([xa, xb], axis=0), r, self.s, axis=0)
            for xa, xb in zip(pa, pb))

    # -- node-axis scatter/gather by GLOBAL node id -----------------------
    def _local(self, idx):
        """Global index -> local row; anything not owned -> S (drops)."""
        owned = (idx >= self.lo) & (idx < self.lo + self.s)
        return jnp.where(owned, idx - self.lo, self.s), owned

    def scatter_max(self, dst, idx, val):
        li, _ = self._local(idx)
        return dst.at[li].max(val, mode="drop")

    def scatter_add(self, dst, idx, val):
        li, _ = self._local(idx)
        return dst.at[li].add(val, mode="drop")

    def scatter_or_word(self, win, rows, cols, bits):
        li, _ = self._local(rows)
        return win.at[li, cols].add(bits, mode="drop")

    def gather(self, arr, idx):
        li, owned = self._local(idx)
        v = arr[jnp.clip(li, 0, self.s - 1)]
        if v.dtype == jnp.bool_:
            hit = jax.lax.psum(
                jnp.where(owned, v, False).astype(jnp.int32), AXIS)
            return hit > 0
        return jax.lax.psum(
            jnp.where(owned, v, jnp.zeros((), v.dtype)), AXIS)

    # -- nodewise exchanges (sharded pull mode; round 4) ------------------
    #
    # The psum-style gather/knows_words above require REPLICATED query
    # arrays (each shard must pose the same queries, or the elementwise
    # psum would mix different shards' questions).  The pull branch's
    # queries are NODE-AXIS — each shard asks about ITS rows' randomly
    # sampled peers — so they route through a D-step ppermute ring
    # pass instead: the query bundle visits every shard once, each
    # shard answers the entries it owns (local gathers), and after D
    # hops the bundle is home with exact answers.  This IS the
    # all-to-all the scatter-free rotor path avoids (RESULTS.md §2):
    # D ppermute rounds of [S]-sized payloads plus O(N) local gather
    # rows per exchange per period — correct and bitwise-equal to the
    # single-program engine, deliberately not the throughput path.

    def _shift1(self, x):
        if self.d == 1:
            return x
        perm = [(p, (p + 1) % self.d) for p in range(self.d)]
        return jax.lax.ppermute(x, AXIS, perm)

    def gather_nodewise(self, arr, idx):
        """arr[idx] for node-axis arr and node-axis GLOBAL ids [S]."""
        qids, acc = idx, jnp.zeros((self.s,) + arr.shape[1:], arr.dtype)
        for _ in range(self.d):
            owned = (qids >= self.lo) & (qids < self.lo + self.s)
            lr = jnp.clip(qids - self.lo, 0, self.s - 1)
            v = arr[lr]
            ow = owned.reshape((-1,) + (1,) * (arr.ndim - 1))
            acc = jnp.where(ow, v, acc)
            qids, acc = self._shift1(qids), self._shift1(acc)
        return acc

    def gather_rows(self, mat, idx):
        return self.gather_nodewise(mat, idx)

    def knows_nodewise(self, win, cold, slot_pos, rows, slot):
        """Heard-bit of global node ids `rows` [S] for ring slots
        `slot` [S] — the nodewise twin of knows_words.  The queried
        WORD travels the ring; the bit index stays home (slot_pos is
        pure replicated geometry, so computing it query-side is exact)."""
        ok, wcol, word_r, bit = slot_pos(slot)
        q, f, c, r = rows, ok, wcol, word_r
        acc = jnp.zeros((self.s,), win.dtype)
        for _ in range(self.d):
            owned = (q >= self.lo) & (q < self.lo + self.s)
            lr = jnp.clip(q - self.lo, 0, self.s - 1)
            word = jnp.where(f, win[lr, c], cold[r, lr])
            acc = jnp.where(owned, word, acc)
            q, f, c, r, acc = (self._shift1(q), self._shift1(f),
                               self._shift1(c), self._shift1(r),
                               self._shift1(acc))
        return (slot >= 0) & (((acc >> bit) & 1) > 0)

    def knows_self(self, win, cold, slot_pos, slot):
        """Heard-bit of each LOCAL row for ring slots `slot` [S] — no
        exchange (every query is owned here)."""
        ok, wcol, word_r, bit = slot_pos(slot)
        lr = jnp.arange(self.s, dtype=jnp.int32)
        word = jnp.where(ok, win[lr, wcol], cold[word_r, lr])
        return (slot >= 0) & (((word >> bit) & 1) > 0)

    def knows_words(self, win, cold, slot_pos, rows, slot):
        # cold is word-major: [RW, local N]
        ok, wcol, word_r, bit = slot_pos(slot)
        lr, owned = self._local(rows)
        lrc = jnp.clip(lr, 0, self.s - 1)
        word = jnp.where(ok, win[lrc, wcol], cold[word_r, lrc])
        kn = (slot >= 0) & (((word >> bit) & 1) > 0)
        return jax.lax.psum(
            jnp.where(owned, kn, False).astype(jnp.int32), AXIS) > 0

    def merge_waves(self, win, sel, oks, offs, bcols, bvals, impl):
        """GlobalOps.merge_waves twin: same values for this shard's
        rows.  The fused Pallas kernel needs the whole node axis in one
        address space; here every wave's roll is a ppermute neighbor
        exchange, so the merge stays per-wave, and `impl` is a
        single-program concern.  What DOES change per cfg is the wire
        format of the exchange (cfg.ring_ici_wire):

          * "window": each wave roll_from's the dense sel window —
            two u32[S, WW] neighbor blocks per wave on ICI.
          * "compact": sel is first-B-selected (<= b_pig set bits per
            row — SWIM's bounded piggyback), so it is packed ONCE into
            slot indices idx[S, B] (ops/wavepack.py) and each wave
            ships one packed block.  A global roll by d = k*S + r
            factors as z = roll(idx, r) then take shard me+k of z; z is
            REPLICATED-buildable locally from idx plus ONE boundary
            fetch of the next shard's packed block (shared by all
            waves, r < S), so each wave costs ONE switch-selected
            ppermute of [S, B] narrow ints instead of two [S, WW] u32
            blocks — ~WW*32/B fewer wave bytes, bitwise-equal after
            receiver-side unpack (the values are single bits; only the
            slot indices need to travel).

        The same replicated-shift invariant as roll_from applies: wave
        offsets derive from rnd.* fields, replicated by place()."""
        del impl
        zero = jnp.zeros((), jnp.uint32)
        out = win
        if self.wire == "compact":
            idx = wavepack.pack_slots(sel, self.b_pig)
            both = jnp.concatenate([idx, self._rot(idx, 1)], axis=0)
            for ok, d in zip(oks, offs):
                dd = jnp.mod(jnp.asarray(d, jnp.int32), self.n)
                k = dd // self.s
                r = jnp.mod(dd, self.s)
                z = jax.lax.dynamic_slice_in_dim(both, r, self.s, axis=0)
                y = jax.lax.switch(
                    k, [functools.partial(self._rot, k_static=kk)
                        for kk in range(self.d)], z)
                rolled = wavepack.unpack_slots(y, self.ww)
                out = out | jnp.where(ok[:, None], rolled, zero)
        else:
            for ok, d in zip(oks, offs):
                out = out | jnp.where(ok[:, None], self.roll_from(sel, d),
                                      zero)
        wids = jnp.arange(win.shape[1], dtype=jnp.int32)[None, :]
        for col, val in zip(bcols, bvals):
            out = out | jnp.where(col[:, None] == wids, val[:, None],
                                  zero)
        return out

    def first_true_nodes(self, valid, k):
        # per-shard sort-free compaction (ring._first_true_idx), then a
        # small all-gather + merge of the D candidate lists — the merge
        # keys are n - id so one descending top_k yields ascending ids
        kl = min(k, self.s)
        lidx = ring._first_true_idx(valid, kl)              # local rows
        gidx = jnp.where(lidx < self.s, lidx + self.lo, self.n)
        gk = jnp.where(gidx < self.n, self.n - gidx, 0)
        merged = jax.lax.all_gather(gk, AXIS).reshape(-1)   # [D * kl]
        kk2, _ = jax.lax.top_k(merged, min(k, self.d * kl))
        idx = jnp.where(kk2 > 0, self.n - kk2, self.n)
        if k > idx.shape[0]:
            idx = jnp.concatenate(
                [idx, jnp.full((k - idx.shape[0],), self.n, jnp.int32)])
        return idx


# ---------------------------------------------------------------------------
# Spec pytrees and the public build/place API
# ---------------------------------------------------------------------------


def _state_specs(cfg: SwimConfig) -> ring.RingState:
    return ring.RingState(
        win=P(AXIS, None), cold=P(None, AXIS), inc_self=P(AXIS),
        lha=P(AXIS), gone_key=P(AXIS),
        subject=P(), rkey=P(), birth0=P(), sent_node=P(), sent_time=P(),
        confirmed=P(), overflow=P(), index_overflow=P(), step=P())


def _plan_specs(program: bool = False):
    base = FaultPlan(crash_step=P(AXIS), loss=P(), partition_id=P(AXIS),
                     partition_start=P(), partition_end=P(),
                     join_step=P(AXIS))
    if not program:
        return base
    # FaultProgram: node-axis lanes shard with the nodes; the segment
    # table is a handful of scalars per segment — replicated
    return FaultProgram(
        base=base, domain_id=P(AXIS),
        seg_start=P(), seg_end=P(), seg_period=P(), seg_on=P(),
        seg_domain=P(), seg_kind=P(), seg_level=P())


def _rnd_specs(cfg: SwimConfig) -> ring.RingRandomness:
    if cfg.ring_probe == "pull":
        # pull mode: the loss_w*/lha_u fields are empty (0,) arrays —
        # replicated; every pull uniform is per-node — sharded
        return ring.RingRandomness(
            s_off=P(), q_off=P(), loss_w1=P(), loss_w2=P(),
            loss_w3=P(), loss_w4=P(), loss_w5=P(), loss_w6=P(),
            lha_u=P(),
            pull=ring.PullRandomness(
                m_u=P(AXIS), src_u=P(AXIS, None), d_fwd=P(AXIS),
                d_back=P(AXIS), px_u=P(AXIS, None),
                px_fwd=P(AXIS, None), px_back=P(AXIS, None),
                ack_u=P(AXIS), ack_leg=P(AXIS)))
    return ring.RingRandomness(
        s_off=P(), q_off=P(), loss_w1=P(AXIS), loss_w2=P(AXIS),
        loss_w3=P(AXIS, None), loss_w4=P(AXIS, None),
        loss_w5=P(AXIS, None), loss_w6=P(AXIS, None), lha_u=P(AXIS),
        pull=None)


def _check(cfg: SwimConfig, mesh) -> int:
    d = int(mesh.devices.size)
    if cfg.n_nodes % d != 0:
        raise ValueError(
            f"n_nodes={cfg.n_nodes} must divide over {d} devices")
    return d


def place(cfg: SwimConfig, mesh, state: ring.RingState, plan):
    """Device_put state + plan onto the mesh per this engine's specs.
    `plan` may be a FaultPlan or a FaultProgram — pass the matching
    `program=` flag to build_step/build_run/mapped_step."""
    _check(cfg, mesh)
    st = jax.tree.map(
        lambda x, sp: jax.device_put(x, NamedSharding(mesh, sp)),
        state, _state_specs(cfg))
    pl = jax.tree.map(
        lambda x, sp: jax.device_put(x, NamedSharding(mesh, sp)),
        plan, _plan_specs(program=isinstance(plan, FaultProgram)))
    return st, pl


@functools.lru_cache(maxsize=64)
def mapped_step(cfg: SwimConfig, mesh, program: bool = False):
    """The shard_mapped (unjitted) step(state, plan, rnd) — the single
    source of the engine's specs; nestable inside callers' scans (the
    study runner passes it to run_study_ring).  Memoized per
    (cfg, mesh): callers pass it as a STATIC jit argument, and a fresh
    closure per call would defeat the jit cache (one full study-scan
    recompile per sweep point).

    With cfg.telemetry the mapped step returns (state, EngineFrame):
    the tap values are psum/pmax-reduced inside ring.step, so every
    frame field is replicated — out_specs P() — and identical to the
    single-program engine's frame for the same period.

    With cfg.profiling the step additionally returns the obs/prof.py
    phase-marker vector (i32[len(PHASES)]); each marker is an
    ops.gsum-reduced fold, so it too is replicated (out_spec P()).
    Extras compose: (state, frame?, markers?) in that order."""
    d = _check(cfg, mesh)

    if cfg.telemetry or cfg.profiling:
        def _step(state, plan, rnd):
            from swim_tpu.obs.prof import PhaseProbe

            tap: dict | None = {} if cfg.telemetry else None
            pr = PhaseProbe() if cfg.profiling else None
            st = ring.step(cfg, state, plan, rnd, ops=ShardOps(cfg, d),
                           tap=tap, prof=pr)
            extras = []
            if cfg.telemetry:
                extras.append(frame_from_tap(tap))
            if cfg.profiling:
                extras.append(pr.marker_vector())
            return (st, *extras)

        extra_specs = []
        if cfg.telemetry:
            extra_specs.append(
                EngineFrame(*(P() for _ in EngineFrame._fields)))
        if cfg.profiling:
            extra_specs.append(P())
        out_specs = (_state_specs(cfg), *extra_specs)
    else:
        def _step(state, plan, rnd):
            return ring.step(cfg, state, plan, rnd, ops=ShardOps(cfg, d))

        out_specs = _state_specs(cfg)

    return shard_map(
        _step, mesh=mesh,
        in_specs=(_state_specs(cfg), _plan_specs(program), _rnd_specs(cfg)),
        out_specs=out_specs, check_rep=False)


def build_step(cfg: SwimConfig, mesh, program: bool = False):
    """jitted step(state, plan, rnd) with explicit collectives.
    `program=True` expects a FaultProgram plan pytree (sim/scenario.py)."""
    return jax.jit(mapped_step(cfg, mesh, program))


def build_run(cfg: SwimConfig, mesh, periods: int, program: bool = False):
    """jitted run(state, plan, root_key): `periods` under one lax.scan,
    randomness drawn inside the scan exactly as ring.run does.

    With cfg.telemetry returns (state, EngineFrame) where every frame
    field is a [periods]-stacked i32 series (the flight-recorder feed);
    with cfg.profiling the [periods, len(PHASES)] marker matrix is
    appended; otherwise just the final state."""
    sm = mapped_step(cfg, mesh, program)
    extras = cfg.telemetry or cfg.profiling

    def run(state, plan, root_key):
        def body(stt, _):
            rnd = ring.draw_period_ring(root_key, stt.step, cfg)
            out = sm(stt, plan, rnd)
            if extras:
                return out[0], out[1:]
            return out, None

        out, ys = jax.lax.scan(body, state, None, length=periods)
        return (out, *ys) if extras else out

    return jax.jit(run)
