"""Explicitly-sharded rumor engine: shard_map + compact message exchange.

Why this exists: jitting rumor.step with GSPMD shardings is *correct* on a
mesh (the driver dry-runs it), but the partitioner cannot see that message
delivery is sparse — compiling the sharded step at N=1024/D=8 inserts
~222 all-gathers, several of them effectively replicating the [N, R]
heard-bit matrix every period (256 MB/step/device at the 1M-node target —
unusable on real ICI). The protocol itself only needs to move MESSAGES:
O(N·k·B) small integers per period. This module restructures the period
as a per-shard computation + six compact `all_gather` exchanges, the
TPU-native analog of the reference's socket fan-out (SURVEY.md §5
"Distributed comm backend").

Design (device d owns node rows [d·n/D, (d+1)·n/D)):

  * knows / inc_self / lha and all PeriodRandomness tensors shard on the
    node axis; the rumor table, fault plan, and `gone_key` are REPLICATED
    (all-shard-identical updates, enforced by construction: every
    replicated update is a deterministic function of replicated inputs
    and `psum`/`all_gather` reductions).
  * Each wave: senders build fixed-size tuple arrays (dst, rumor ids,
    validity, carried loss draws for the response chain), `all_gather`
    moves them, every shard applies the slice addressed to its rows and
    emits the response wave locally. Response waves are compacted to
    `slack·expected` slots before gathering (overflow is counted in
    state.overflow, never silent; `exchange_slack=D` makes the exchange
    lossless and the engine bitwise-identical to models/rumor.py — the
    equality test in tests/test_shard_engine.py runs exactly that).
  * Suspicion expiry: each shard evaluates refutation for the sentinel
    nodes it owns; a boolean psum assembles the global verdict.
  * Originations: per-shard candidates compact locally, `all_gather`
    concatenates them in shard order (= global id order, matching the
    single-device engine's priority), and the allocation logic runs
    replicated on every shard.

Loss draws for response waves ride INSIDE the request tuples (an ack's
Bernoulli draw is indexed by the original pinger, whose randomness lives
on the pinger's shard), so no cross-shard randomness lookups exist.

Design lineage note: this engine's founding move — put SWIM's O(N·k·B)
bounded MESSAGES on the wire, never a dense O(N·R)/O(N·WW) state matrix
— is the same confrontation the ring twin later adopted as
`cfg.ring_ici_wire="compact"` (parallel/ring_shard.py merge_waves +
ops/wavepack.py): there the bounded piggyback packs into B slot indices
per row and each wave ships one packed block over ICI instead of the
dense sel window.  One principle, two engines.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map as _shard_map      # jax >= 0.8

    def shard_map(f, *, mesh, in_specs, out_specs, check_rep=False):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=check_rep)
except ImportError:                              # pragma: no cover
    from jax.experimental.shard_map import shard_map

from swim_tpu.config import SwimConfig
from swim_tpu.models import rumor
from swim_tpu.models.rumor import RumorRandomness, RumorState
from swim_tpu.ops import lattice, sampling
from swim_tpu.parallel.mesh import NODE_AXIS
from swim_tpu.sim import faults
from swim_tpu.sim.faults import FaultPlan

AX = NODE_AXIS


def _psum_bool(x, axis_name=AX):
    return jax.lax.psum(x.astype(jnp.int32), axis_name) > 0


def _gather_flat(tree, axis_name=AX):
    """all_gather each array and flatten the shard axis into the rows."""
    def g(x):
        y = jax.lax.all_gather(x, axis_name)          # [D, local, ...]
        # explicit row count: -1 inference divides by the trailing sizes,
        # which crashes (ZeroDivisionError) on zero-width carry arrays
        return y.reshape((y.shape[0] * y.shape[1],) + y.shape[2:])
    return jax.tree.map(g, tree)


class _Msgs(NamedTuple):
    """One wave's exchanged messages (all arrays share leading dim M)."""

    src: jax.Array      # i32[M] global sender id
    dst: jax.Array      # i32[M] global receiver id
    ok: jax.Array       # bool[M] delivered (faults already applied)
    sel: jax.Array      # i32[M, B] piggybacked rumor ids
    val: jax.Array      # bool[M, B]
    forced: jax.Array   # i32[M] buddy-forced rumor id (-1 none)
    carry: jax.Array    # f32[M, C] loss draws for the response chain
    meta: jax.Array     # i32[M] response routing (target / pinger id)


@functools.lru_cache(maxsize=32)
def build_step(cfg: SwimConfig, mesh, exchange_slack: int | None = None):
    """Compile-time builder: returns step(state, plan, rnd) under shard_map.

    Memoized on (cfg, mesh, exchange_slack) — both are hashable — so sweep
    loops (sim/experiments.py) reuse one jitted step per configuration
    instead of retracing per sweep point.

    `exchange_slack` bounds response-wave compaction at slack×(expected
    per-shard load); None defaults to the mesh size D, which is lossless
    (a shard can be the target of every probe) and bitwise-equal to the
    single-device engine. Use a small constant (e.g. 4) at large N to
    keep exchanges O(N·k·B/D) under adversarial target skew — overflow
    is counted, never silent.
    """
    n, k, r_cap = cfg.n_nodes, cfg.k_indirect, cfg.rumor_slots
    d_mesh = mesh.devices.size
    if n % d_mesh:
        raise ValueError(f"n_nodes {n} must divide the mesh size {d_mesh}")
    n_loc = n // d_mesh
    slack = d_mesh if exchange_slack is None else exchange_slack
    b_pig = min(cfg.max_piggyback, r_cap)
    w_pig = rumor._pig_window(cfg)
    cb = rumor._budget(cfg)
    cb_loc = max(1, min(n_loc, cb))
    s_cap = cfg.sentinels
    ack_cap = min(n, slack * n_loc)
    rly_cap = min(n * k, slack * n_loc * k)
    NO = jnp.int32(n)  # out-of-range row → dropped scatter

    node_specs = RumorState(
        knows=P(AX), inc_self=P(AX), lha=P(AX),
        gone_key=P(),   # subject-indexed: replicated (arbitrary lookups)
        subject=P(), rkey=P(), birth=P(), sent_node=P(), sent_time=P(),
        confirmed=P(), overflow=P(), step=P())
    plan_specs = FaultPlan(crash_step=P(), loss=P(), partition_id=P(),
                           partition_start=P(), partition_end=P(),
                           join_step=P())
    rnd_specs = RumorRandomness(
        base=jax.tree.map(lambda _: P(AX), rumor.draw_period_rumor(
            jax.random.key(0), 0, cfg).base),
        resample_u=P(AX))

    def shard_body(state: RumorState, plan: FaultPlan,
                   rnd: RumorRandomness) -> RumorState:
        d_idx = jax.lax.axis_index(AX)
        off = d_idx.astype(jnp.int32) * n_loc
        ids_l = off + jnp.arange(n_loc, dtype=jnp.int32)
        t = state.step
        base = rnd.base
        crashed_all = t >= plan.crash_step                  # bool[N] repl
        up_l = ~crashed_all[ids_l]
        part_on = ((t >= plan.partition_start) & (t < plan.partition_end))

        # ---- Phase 0: retirement (replicated; knower counts via psum) ----
        used = state.subject >= 0
        age = t - state.birth
        window = jnp.int32(cfg.gossip_window)
        pend_horizon = jnp.int32(
            (cfg.suspicion_max_periods
             if cfg.lifeguard and cfg.dynamic_suspicion
             else cfg.suspicion_periods) + 2)
        is_susp_r = lattice.is_suspect(state.rkey)
        is_dead_r = lattice.is_dead(state.rkey)
        gone_at_subj = state.gone_key[jnp.maximum(state.subject, 0)]
        same_subj = (state.subject[:, None] == state.subject[None, :])
        glob_refuted = (jnp.any(
            same_subj & used[None, :]
            & (state.rkey[None, :] > state.rkey[:, None]), axis=-1)
            | (gone_at_subj > state.rkey))
        pending = (is_susp_r & ~state.confirmed & ~glob_refuted
                   & (age < pend_horizon))
        live_total = jax.lax.psum(jnp.sum(up_l).astype(jnp.int32), AX)
        knowers = jax.lax.psum(
            jnp.sum(state.knows & up_l[:, None], axis=0).astype(jnp.int32),
            AX)
        disseminated = knowers >= live_total
        retire_dead = used & is_dead_r & disseminated
        gone_key = state.gone_key.at[
            jnp.where(retire_dead, state.subject, n)].max(
            state.rkey, mode="drop")
        keep = used & jnp.where(is_dead_r, ~disseminated,
                                (age < window) | pending)
        subject = jnp.where(keep, state.subject, -1)
        used = subject >= 0

        knows = state.knows                                  # [n_loc, R]
        rkey, birth = state.rkey, state.birth
        rr = jnp.arange(r_cap, dtype=jnp.int32)

        def opinion_l(kn, subj):
            mk = (used[None, :] & (subject[None, :] == subj[:, None]) & kn)
            vals = jnp.where(mk, rkey, jnp.uint32(0))
            best = jnp.max(vals, axis=-1)
            arg = jnp.argmax(vals, axis=-1).astype(jnp.int32)
            floor = jnp.maximum(lattice.alive_key(jnp.uint32(0)),
                                gone_key[subj])
            return (jnp.maximum(best, floor),
                    jnp.where(best > floor, arg, -1))

        def believes_dead_l(kn, subj):
            mk = (used[None, :] & (subject[None, :] == subj[:, None]) & kn)
            return (jnp.any(mk & is_dead_r[None, :], axis=-1)
                    | lattice.is_dead(gone_key[subj]))

        # ---- Phase A: targets & proxies (local) --------------------------
        if cfg.target_selection == "round_robin":
            epoch = jnp.broadcast_to(t // jnp.int32(n - 1), (n_loc,))
            pos = jnp.broadcast_to(t % jnp.int32(n - 1), (n_loc,))
            target = sampling.round_robin_target(ids_l, epoch, pos, n)
            prober = up_l
        else:
            def draw_tgt(u):
                idx = (u * jnp.float32(n - 1)).astype(jnp.int32)
                idx = jnp.minimum(idx, n - 2)
                return idx + (idx >= ids_l).astype(jnp.int32)

            target = draw_tgt(base.target_u)
            bad = believes_dead_l(knows, target)
            for a in range(rumor.RESAMPLE_ATTEMPTS):
                nxt = draw_tgt(rnd.resample_u[:, a])
                target = jnp.where(bad, nxt, target)
                bad = bad & believes_dead_l(knows, target)
            prober = up_l & ~bad
        lo = jnp.minimum(ids_l, target)
        hi = jnp.maximum(ids_l, target)
        idx2 = (base.proxy_u * jnp.float32(max(n - 2, 1))).astype(jnp.int32)
        idx2 = jnp.minimum(idx2, max(n - 3, 0))
        prox = idx2 + (idx2 >= lo[:, None]).astype(jnp.int32)
        prox = prox + (prox >= hi[:, None]).astype(jnp.int32)
        has_proxy = n > 2

        def delivered(src, dst, u):
            cut = part_on & (plan.partition_id[src] != plan.partition_id[dst])
            return (~crashed_all[src] & ~crashed_all[dst] & ~cut
                    & (u >= plan.loss.astype(jnp.float32)))

        # ---- piggyback selection (local rows; replicated candidates) -----
        eligible = used & (age >= 0) & (age < window)
        score = jnp.where(eligible, age * jnp.int32(r_cap) + rr,
                          jnp.int32(2**30))
        _, cand_idx = jax.lax.top_k(-score, w_pig)
        cand_idx = cand_idx.astype(jnp.int32)
        cand_valid = eligible[cand_idx]

        def select_rows(kn):
            """First-B eligible rumors per local row → (sel ids, valid)."""
            knc = kn[:, cand_idx] & cand_valid[None, :]
            if b_pig <= 16:
                packed = jnp.packbits(knc, axis=-1, bitorder="little")
                words = [packed[:, w] for w in range(packed.shape[-1])]
                one = jnp.uint8(1)
                ws, oks = [], []
                for _ in range(b_pig):
                    idx = jnp.zeros(knc.shape[:1], jnp.int32)
                    found = jnp.zeros(knc.shape[:1], jnp.bool_)
                    nxt = []
                    for w, m in enumerate(words):
                        nz = m != 0
                        low = m & (jnp.uint8(0) - m)
                        bit = jax.lax.population_count(low - one)
                        take = nz & ~found
                        idx = jnp.where(take,
                                        8 * w + bit.astype(jnp.int32), idx)
                        nxt.append(jnp.where(take, m & (m - one), m))
                        found = found | nz
                    words = nxt
                    ws.append(idx)
                    oks.append(found)
                wpos = jnp.stack(ws, axis=-1)
                val = jnp.stack(oks, axis=-1)
            else:
                pos = jnp.cumsum(knc.astype(jnp.int32), axis=-1)
                prio = jnp.where(
                    knc & (pos <= b_pig),
                    jnp.int32(w_pig) - jnp.arange(w_pig, dtype=jnp.int32),
                    0)
                vals, wpos = jax.lax.top_k(prio, b_pig)
                val = vals > 0
            return jnp.take(cand_idx, wpos), val

        def buddy_rows(kn, rows_subj):
            if not (cfg.lifeguard and cfg.buddy):
                return jnp.full(rows_subj.shape, -1, jnp.int32)
            mk = (used[None, :] & (subject[None, :] == rows_subj[:, None])
                  & kn)
            vals = jnp.where(mk, rkey, jnp.uint32(0))
            best = jnp.max(vals, axis=-1)
            arg = jnp.argmax(vals, axis=-1).astype(jnp.int32)
            return jnp.where(lattice.is_suspect(best), arg, -1)

        def apply_msgs(kn, m: _Msgs):
            """Merge the gathered wave into this shard's rows."""
            mine = m.ok & (m.dst >= off) & (m.dst < off + n_loc)
            row = jnp.where(mine, m.dst - off, NO)
            kn = kn.at[row[:, None], m.sel].max(
                m.val & mine[:, None], mode="drop")
            kn = kn.at[row, jnp.maximum(m.forced, 0)].max(
                mine & (m.forced >= 0), mode="drop")
            return kn, mine

        def compact_msgs(m: _Msgs, valid, cap):
            """Deterministic compaction of valid messages into cap slots;
            returns (msgs, dropped_count)."""
            total = jnp.sum(valid).astype(jnp.int32)
            mlen = valid.shape[0]
            (ci,) = jnp.nonzero(valid, size=cap, fill_value=mlen)
            got = ci < mlen
            cic = jnp.minimum(ci, mlen - 1)
            take = lambda x, fill: jnp.where(  # noqa: E731
                got if x.ndim == 1 else got[:, None], x[cic], fill)
            out = _Msgs(
                src=take(m.src, 0), dst=take(m.dst, 0),
                ok=take(m.ok, False) & got,
                sel=take(m.sel, 0), val=take(m.val, False),
                forced=take(m.forced, -1), carry=take(m.carry, 0.0),
                meta=take(m.meta, 0))
            return out, jnp.maximum(total - cap, 0)

        overflow = state.overflow

        # ---- W1 PING i→T(i): all local probers --------------------------
        sel1, val1 = select_rows(knows)
        ok1 = prober & delivered(ids_l, target, base.loss_w1)
        w1 = _Msgs(src=ids_l, dst=target, ok=ok1,
                   sel=sel1, val=val1 & prober[:, None],
                   forced=buddy_rows(knows, target),
                   carry=base.loss_w2[:, None], meta=ids_l)
        g1 = _gather_flat(w1)
        knows, mine1 = apply_msgs(knows, g1)

        # ---- W2 ACK T(i)→i: one per ping delivered to my rows -----------
        src2 = jnp.where(mine1, g1.dst, 0)
        sel2_all, val2_all = select_rows(knows)
        row2 = jnp.clip(src2 - off, 0, n_loc - 1)
        ok2 = mine1 & delivered(src2, g1.src, g1.carry[:, 0])
        w2_full = _Msgs(src=src2, dst=g1.src, ok=ok2,
                        sel=sel2_all[row2], val=val2_all[row2]
                        & mine1[:, None],
                        forced=jnp.full_like(src2, -1),
                        carry=jnp.zeros((src2.shape[0], 0), jnp.float32),
                        meta=src2)
        w2c, drop2 = compact_msgs(w2_full, mine1, ack_cap)
        overflow = overflow + jax.lax.psum(drop2, AX)
        g2 = _gather_flat(w2c)
        knows, mine2 = apply_msgs(knows, g2)
        acked = jnp.zeros((n_loc,), jnp.bool_).at[
            jnp.where(mine2, g2.dst - off, NO)].max(mine2, mode="drop")

        # ---- W3 PING-REQ i→p (k fan-out from unacked probers) ------------
        need = prober & ~acked & has_proxy
        src3 = jnp.repeat(ids_l, k)
        dst3 = prox.reshape(-1)
        sent3 = jnp.repeat(need, k)
        sel3, val3 = select_rows(knows)
        sel3 = jnp.repeat(sel3, k, axis=0)
        val3 = jnp.repeat(val3, k, axis=0)
        ok3 = sent3 & delivered(src3, dst3, base.loss_w3.reshape(-1))
        carry3 = jnp.stack([base.loss_w4.reshape(-1),
                            base.loss_w5.reshape(-1),
                            base.loss_w6.reshape(-1)], axis=-1)
        w3 = _Msgs(src=src3, dst=dst3, ok=ok3, sel=sel3,
                   val=val3 & sent3[:, None],
                   forced=jnp.full_like(src3, -1), carry=carry3,
                   meta=jnp.repeat(target, k))
        g3 = _gather_flat(w3)
        knows, mine3 = apply_msgs(knows, g3)

        # ---- W4 proxy PING p→T(i) ---------------------------------------
        src4 = jnp.where(mine3, g3.dst, 0)
        row4 = jnp.clip(src4 - off, 0, n_loc - 1)
        sel4_all, val4_all = select_rows(knows)
        tgt4 = g3.meta
        ok4 = mine3 & delivered(src4, tgt4, g3.carry[:, 0])
        w4_full = _Msgs(src=src4, dst=tgt4, ok=ok4,
                        sel=sel4_all[row4],
                        val=val4_all[row4] & mine3[:, None],
                        forced=jnp.where(
                            mine3, buddy_rows(knows[row4], tgt4), -1),
                        carry=g3.carry[:, 1:], meta=g3.src)
        w4c, drop4 = compact_msgs(w4_full, mine3, rly_cap)
        overflow = overflow + jax.lax.psum(drop4, AX)
        g4 = _gather_flat(w4c)
        knows, mine4 = apply_msgs(knows, g4)

        # ---- W5 target ACK T(i)→p ---------------------------------------
        src5 = jnp.where(mine4, g4.dst, 0)
        row5 = jnp.clip(src5 - off, 0, n_loc - 1)
        sel5_all, val5_all = select_rows(knows)
        ok5 = mine4 & delivered(src5, g4.src, g4.carry[:, 0])
        w5_full = _Msgs(src=src5, dst=g4.src, ok=ok5,
                        sel=sel5_all[row5],
                        val=val5_all[row5] & mine4[:, None],
                        forced=jnp.full_like(src5, -1),
                        carry=g4.carry[:, 1:], meta=g4.meta)
        w5c, drop5 = compact_msgs(w5_full, mine4, rly_cap)
        overflow = overflow + jax.lax.psum(drop5, AX)
        g5 = _gather_flat(w5c)
        knows, mine5 = apply_msgs(knows, g5)

        # ---- W6 relay ACK p→i -------------------------------------------
        src6 = jnp.where(mine5, g5.dst, 0)
        row6 = jnp.clip(src6 - off, 0, n_loc - 1)
        sel6_all, val6_all = select_rows(knows)
        ok6 = mine5 & delivered(src6, g5.meta, g5.carry[:, 0])
        w6_full = _Msgs(src=src6, dst=g5.meta, ok=ok6,
                        sel=sel6_all[row6],
                        val=val6_all[row6] & mine5[:, None],
                        forced=jnp.full_like(src6, -1),
                        carry=jnp.zeros((src6.shape[0], 0), jnp.float32),
                        meta=src6)
        w6c, drop6 = compact_msgs(w6_full, mine5, rly_cap)
        overflow = overflow + jax.lax.psum(drop6, AX)
        g6 = _gather_flat(w6c)
        knows, mine6 = apply_msgs(knows, g6)
        relayed = jnp.zeros((n_loc,), jnp.bool_).at[
            jnp.where(mine6, g6.dst - off, NO)].max(mine6, mode="drop")

        # ---- Phase C: verdicts / refutation / expiry ---------------------
        probe_ok = acked | relayed
        failed = prober & ~probe_ok
        lha = state.lha
        s_probe = lha
        if cfg.lifeguard:
            lha = jnp.where(prober,
                            jnp.clip(lha + jnp.where(failed, 1, -1), 0,
                                     cfg.lha_max), lha)
            thin = base.lha_u < (jnp.float32(1.0)
                                 / (1 + s_probe).astype(jnp.float32))
            failed = failed & thin
        viewed_tk, _ = opinion_l(knows, target)
        v_status = lattice.status_of(viewed_tk)
        mk_suspect = failed & (v_status == 0)
        re_suspect = failed & (v_status == 1)
        susp_key = lattice.suspect_key(lattice.incarnation_of(viewed_tk))

        self_mk = (used[None, :] & (subject[None, :] == ids_l[:, None])
                   & knows)
        self_vals = jnp.where(self_mk, rkey, jnp.uint32(0))
        self_best = jnp.maximum(jnp.max(self_vals, axis=-1),
                                lattice.alive_key(state.inc_self))
        refute = up_l & lattice.is_suspect(self_best)
        new_inc = jnp.where(refute, lattice.incarnation_of(self_best) + 1,
                            state.inc_self.astype(jnp.uint32)
                            ).astype(jnp.uint32)
        inc_self = jnp.where(refute, new_inc, state.inc_self)
        if cfg.lifeguard:
            lha = jnp.where(refute, jnp.clip(lha + 1, 0, cfg.lha_max), lha)

        # expiry: refutation checked by whichever shard owns each sentinel
        filled = jnp.sum(state.sent_node >= 0, axis=-1).astype(jnp.int32)
        if cfg.lifeguard and cfg.dynamic_suspicion:
            timeout = rumor.dynamic_timeout_table(cfg)[
                jnp.clip(filled, 0, s_cap)]
        else:
            timeout = jnp.full((r_cap,), cfg.suspicion_periods, jnp.int32)
        snode = state.sent_node
        sact = (snode >= 0) & (plan.crash_step[jnp.maximum(snode, 0)] > t)
        deadline_hit = sact & (t >= state.sent_time + timeout[:, None])
        higher = (same_subj & used[None, :]
                  & (rkey[None, :] > rkey[:, None]))
        local_sent = (snode >= off) & (snode < off + n_loc)
        ref_parts = []
        for s_i in range(s_cap):
            rows = jnp.where(local_sent[:, s_i], snode[:, s_i] - off, NO)
            kn_s = jnp.where(
                (rows < n_loc)[:, None],
                knows[jnp.clip(rows, 0, n_loc - 1)], False)
            ref_parts.append(jnp.any(higher & kn_s, axis=-1)
                             & local_sent[:, s_i])
        refuted_local = jnp.stack(ref_parts, axis=-1)      # [R, S]
        refuted = _psum_bool(refuted_local)
        can_confirm = deadline_hit & ~refuted
        dead_key_r = lattice.dead_key(lattice.incarnation_of(rkey))
        confirm = (used & is_susp_r & ~state.confirmed
                   & (dead_key_r > gone_key[jnp.maximum(subject, 0)])
                   & jnp.any(can_confirm, axis=-1))
        conf_s = jnp.argmax(can_confirm, axis=-1)
        conf_node = jnp.take_along_axis(snode, conf_s[:, None],
                                        axis=-1)[:, 0]

        # ---- Phase D: originations (gathered, replicated allocation) -----
        def compact_local(valid, subj_a, key_a):
            totalv = jnp.sum(valid).astype(jnp.int32)
            (ci,) = jnp.nonzero(valid, size=cb_loc, fill_value=n_loc)
            got = ci < n_loc
            cic = jnp.minimum(ci, n_loc - 1)
            return (got, jnp.where(got, subj_a[cic], -1),
                    jnp.where(got, key_a[cic], 0),
                    jnp.where(got, ids_l[cic], 0),
                    jnp.maximum(totalv - cb_loc, 0))

        rg, rsubj, rkey_c, rorig, rdrop = compact_local(
            refute, ids_l, lattice.alive_key(new_inc))
        sg, ssubj, skey_c, sorig, sdrop = compact_local(
            mk_suspect | re_suspect, target, susp_key)
        overflow = overflow + jax.lax.psum(rdrop + sdrop, AX)

        def gcat(x):
            y = jax.lax.all_gather(x, AX)
            return y.reshape((-1,) + y.shape[2:])

        c_subj = jnp.concatenate([subject, gcat(rsubj), gcat(ssubj)])
        c_key = jnp.concatenate([dead_key_r, gcat(rkey_c), gcat(skey_c)])
        c_orig = jnp.concatenate([jnp.maximum(conf_node, 0), gcat(rorig),
                                  gcat(sorig)])
        c_valid = jnp.concatenate([confirm, gcat(rg), gcat(sg)])
        gl = d_mesh * cb_loc
        c_src = jnp.concatenate([rr, jnp.full((2 * gl,), -1, jnp.int32)])
        c_susp = jnp.concatenate([jnp.zeros((r_cap + gl,), jnp.bool_),
                                  jnp.ones((gl,), jnp.bool_)])
        total = jnp.sum(c_valid).astype(jnp.int32)
        m = c_valid.shape[0]
        (ci,) = jnp.nonzero(c_valid, size=cb, fill_value=m)
        got = ci < m
        ci = jnp.minimum(ci, m - 1)
        subj_c = jnp.where(got, c_subj[ci], -1)
        key_c = jnp.where(got, c_key[ci], 0)
        orig_c = jnp.where(got, c_orig[ci], 0)
        src_c = jnp.where(got, c_src[ci], -1)
        susp_c = got & c_susp[ci]
        overflow = overflow + jnp.maximum(total - cb, 0)

        eq = ((subj_c[:, None] == subj_c[None, :])
              & (key_c[:, None] == key_c[None, :]))
        earlier = jnp.tril(jnp.ones((cb, cb), jnp.bool_), k=-1)
        dup_mask = eq & earlier & got[None, :] & got[:, None]
        dup_prev = jnp.any(dup_mask, axis=-1)
        win_idx = jnp.argmax(dup_mask, axis=-1)
        ex = (used[None, :] & (subj_c[:, None] == subject[None, :])
              & (key_c[:, None] == rkey[None, :]))
        ex_match = jnp.any(ex, axis=-1)
        ex_slot = jnp.argmax(ex, axis=-1).astype(jnp.int32)
        needs_slot = got & ~dup_prev & ~ex_match
        (free_slots,) = jnp.nonzero(~used, size=cb, fill_value=r_cap)
        n_free = jnp.sum(~used).astype(jnp.int32)
        apos = jnp.cumsum(needs_slot.astype(jnp.int32)) - 1
        alloc_ok = needs_slot & (apos < jnp.minimum(n_free, cb))
        slot_new = jnp.where(alloc_ok,
                             free_slots[jnp.clip(apos, 0, cb - 1)], -1)
        overflow = overflow + jnp.sum(needs_slot & ~alloc_ok)
        slot_f0 = jnp.where(ex_match, ex_slot, slot_new)
        slot_f = jnp.where(dup_prev, slot_f0[win_idx],
                           slot_f0).astype(jnp.int32)
        placed = got & (slot_f >= 0)

        wslot = jnp.where(alloc_ok, slot_f, r_cap)
        subject = subject.at[wslot].set(subj_c, mode="drop")
        rkey = rkey.at[wslot].set(key_c, mode="drop")
        birth = birth.at[wslot].set(t, mode="drop")
        confirmed = state.confirmed.at[wslot].set(False, mode="drop")
        snode = snode.at[wslot].set(-1, mode="drop")
        stime = state.sent_time.at[wslot].set(0, mode="drop")
        newly = jnp.zeros((r_cap,), jnp.bool_).at[wslot].set(
            True, mode="drop")
        knows = jnp.where(newly[None, :], False, knows)
        orig_row = jnp.where(placed & (orig_c >= off)
                             & (orig_c < off + n_loc), orig_c - off, NO)
        knows = knows.at[orig_row, jnp.maximum(slot_f, 0)].max(
            placed, mode="drop")

        joiner = placed & susp_c
        tgt_r = jnp.where(joiner, slot_f, r_cap)
        already = jnp.any(snode[jnp.clip(tgt_r, 0, r_cap - 1)]
                          == orig_c[:, None], axis=-1) & joiner
        joiner = joiner & ~already
        tgt_r = jnp.where(joiner, slot_f, r_cap)
        same_r = (tgt_r[:, None] == tgt_r[None, :])
        grp_rank = jnp.sum(same_r & earlier & joiner[None, :],
                           axis=-1).astype(jnp.int32)
        fill_now = jnp.sum(snode[jnp.clip(tgt_r, 0, r_cap - 1)] >= 0,
                           axis=-1).astype(jnp.int32)
        spos = fill_now + grp_rank
        j_ok = joiner & (spos < s_cap)
        wr = jnp.where(j_ok, tgt_r, r_cap)
        ws = jnp.clip(spos, 0, s_cap - 1)
        snode = snode.at[wr, ws].set(orig_c, mode="drop")
        stime = stime.at[wr, ws].set(t, mode="drop")
        conf_ok_slot = jnp.where(placed & (src_c >= 0), src_c, r_cap)
        confirmed = confirmed.at[conf_ok_slot].set(True, mode="drop")

        inc_self = jnp.where(crashed_all[ids_l], state.inc_self, inc_self)
        lha = jnp.where(crashed_all[ids_l], state.lha, lha)

        return RumorState(
            knows=knows, inc_self=inc_self, lha=lha, gone_key=gone_key,
            subject=subject, rkey=rkey, birth=birth,
            sent_node=snode, sent_time=stime, confirmed=confirmed,
            overflow=overflow, step=t + 1)

    smapped = shard_map(
        shard_body, mesh=mesh,
        in_specs=(node_specs, plan_specs, rnd_specs),
        out_specs=node_specs, check_rep=False)
    jitted = jax.jit(smapped)

    def stepper(state: RumorState, plan: FaultPlan, rnd):
        plan = _accept_plan(plan)
        _reject_join_plans(plan)
        return jitted(state, plan, rnd)

    return stepper


@functools.lru_cache(maxsize=32)
def build_run(cfg: SwimConfig, mesh, periods: int,
              exchange_slack: int | None = None):
    """Compile-time builder: run(state, plan, root_key) scanning `periods`
    protocol periods of the explicitly-sharded step under one jit."""
    step_fn = build_step(cfg, mesh, exchange_slack)

    def runner(state: RumorState, plan: FaultPlan, root_key):
        def body(stt, _):
            rnd = rumor.draw_period_rumor(root_key, stt.step, cfg)
            return step_fn(stt, plan, rnd), None

        out, _ = jax.lax.scan(body, state, None, length=periods)
        return out

    jitted = jax.jit(runner)

    def guarded(state: RumorState, plan: FaultPlan, root_key):
        plan = _accept_plan(plan)
        _reject_join_plans(plan)
        return jitted(state, plan, root_key)

    return guarded


def _accept_plan(plan) -> FaultPlan:
    """This engine's shard_map specs model a plain FaultPlan: unwrap
    zero-segment FaultPrograms (identical by the parity contract) and
    refuse real lane programs — the sharded RING exchange carries
    those (parallel/ring_shard.py program=True)."""
    base, prog = faults.split_program(plan)
    if prog is not None:
        raise NotImplementedError(
            "the sharded rumor exchange does not carry FaultProgram "
            "lane segments — use the sharded ring engine")
    return base


def _reject_join_plans(plan: FaultPlan) -> None:
    """This engine does not model join churn (FaultPlan docstring
    contract): refuse concrete plans with a join schedule. Traced values
    (inside an outer jit, already guarded at its concrete boundary) pass
    through."""
    import numpy as np

    js = plan.join_step
    if isinstance(js, jax.core.Tracer):
        return
    try:
        concrete = np.asarray(js)
    except Exception:
        return
    if np.any(concrete > 0):
        raise NotImplementedError(
            "the sharded exchange engine does not model join churn yet — "
            "use the ring, rumor, or dense engine for join schedules")


def place(cfg: SwimConfig, mesh, state: RumorState, plan: FaultPlan):
    """Device-put state/plan with this engine's placement (plan and
    gone_key replicated, node-axis tensors sharded)."""
    plan = _accept_plan(plan)
    _reject_join_plans(plan)
    from jax.sharding import NamedSharding

    node_sh = NamedSharding(mesh, P(AX))
    repl = NamedSharding(mesh, P())

    def put(x, spec):
        return jax.device_put(x, node_sh if spec == P(AX) else repl)

    specs = RumorState(
        knows=P(AX), inc_self=P(AX), lha=P(AX), gone_key=P(),
        subject=P(), rkey=P(), birth=P(), sent_node=P(), sent_time=P(),
        confirmed=P(), overflow=P(), step=P())
    state = jax.tree.map(put, state, specs)
    plan = jax.tree.map(lambda x: jax.device_put(x, repl), plan)
    return state, plan
