"""Device mesh construction and shardings for the node axis.

The simulator's parallelism is 1-D data parallelism over *virtual nodes*
(SURVEY.md §2): every per-node tensor shards its leading N axis across the
mesh; [N, N] view tensors shard rows (each chip owns its nodes' views, the
column axis stays logical). Message delivery then becomes gather (read
sender rows, local) + scatter (write receiver rows, cross-shard) — XLA's
GSPMD partitioner lowers the cross-shard scatters onto ICI collectives
(all-to-all / collective-permute) without any hand-written NCCL-style code,
which is the TPU-native analog of the reference's socket transport fan-out.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NODE_AXIS = "nodes"


def make_mesh(n_devices: int | None = None,
              devices: list | None = None) -> Mesh:
    """1-D mesh over the node axis. Default: all available devices."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (NODE_AXIS,))


def node_sharding(mesh: Mesh, ndim: int, axis: int = 0) -> NamedSharding:
    """Shard the node axis (at position `axis`); replicate the rest."""
    spec = [None] * ndim
    spec[axis] = NODE_AXIS
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def _node_dim(state, n: int | None) -> int | None:
    """The node-axis length: explicit `n`, else the largest leading dim.

    Pass `n` explicitly for states whose replicated tables can be longer
    than the node axis (e.g. a RumorState with rumor_slots > n_nodes).
    States with non-leading node axes (SHARD_AXES) *require* it: their
    replicated tables ([R]) or word-major matrices ([RW, N]) can exceed N
    at small N, and the largest-leading-dim inference would silently
    mis-shard them.
    """
    if n is not None:
        return n
    if getattr(type(state), "SHARD_AXES", None):
        raise ValueError(
            f"shard_state/state_shardings: pass n= explicitly for "
            f"{type(state).__name__} (it declares SHARD_AXES; inferring "
            f"the node axis from the largest leading dim can mis-shard)")
    return max((x.shape[0] for x in jax.tree.leaves(state)
                if getattr(x, "ndim", 0) >= 1), default=None)


def _spec_fn(state, mesh: Mesh, n: int | None):
    """Name-aware spec chooser shared by shard_state/state_shardings.

    Node axis is the leading axis by default; a state NamedTuple class
    may carry a plain SHARD_AXES class attribute (field name -> axis)
    for tensors whose node axis is not leading (e.g. the ring engine's
    word-major `cold`)."""
    nn = _node_dim(state, n)
    overrides = getattr(type(state), "SHARD_AXES", {})
    fields = getattr(state, "_fields", ())

    def spec_of(name, x):
        if hasattr(x, "_fields"):
            # nested NamedTuple (e.g. FaultProgram.base): recurse so the
            # spec pytree mirrors the state structure leaf-for-leaf
            return type(x)(*(spec_of(nm, y)
                             for nm, y in zip(x._fields, x)))
        axis = overrides.get(name, 0)
        if (getattr(x, "ndim", 0) > axis and x.shape[axis] == nn):
            return node_sharding(mesh, x.ndim, axis)
        return replicated(mesh)

    if fields:
        return type(state)(*(spec_of(nm, x)
                             for nm, x in zip(fields, state)))
    return jax.tree.map(lambda x: spec_of("", x), state)


def shard_state(state, mesh: Mesh, n: int | None = None):
    """Place a per-node-axis state pytree onto the mesh.

    Arrays whose node axis (leading by default; per-field overrides via
    the state type's SHARD_AXES) equals the node count shard on it;
    everything else replicates. Works for DenseState, RumorState,
    RingState, and FaultPlan.
    """
    specs = _spec_fn(state, mesh, n)
    return jax.tree.map(jax.device_put, state, specs)


def state_shardings(state, mesh: Mesh, n: int | None = None):
    """The NamedSharding pytree matching `shard_state` (for jit donation)."""
    return _spec_fn(state, mesh, n)
