"""Device mesh construction and shardings for the node axis.

The simulator's parallelism is 1-D data parallelism over *virtual nodes*
(SURVEY.md §2): every per-node tensor shards its leading N axis across the
mesh; [N, N] view tensors shard rows (each chip owns its nodes' views, the
column axis stays logical). Message delivery then becomes gather (read
sender rows, local) + scatter (write receiver rows, cross-shard) — XLA's
GSPMD partitioner lowers the cross-shard scatters onto ICI collectives
(all-to-all / collective-permute) without any hand-written NCCL-style code,
which is the TPU-native analog of the reference's socket transport fan-out.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NODE_AXIS = "nodes"


def make_mesh(n_devices: int | None = None,
              devices: list | None = None) -> Mesh:
    """1-D mesh over the node axis. Default: all available devices."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (NODE_AXIS,))


def node_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Shard the leading (node) axis; replicate everything else."""
    return NamedSharding(mesh, P(NODE_AXIS, *([None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def _node_dim(state, n: int | None) -> int | None:
    """The node-axis length: explicit `n`, else the largest leading dim.

    Pass `n` explicitly for states whose replicated tables can be longer
    than the node axis (e.g. a RumorState with rumor_slots > n_nodes).
    """
    if n is not None:
        return n
    return max((x.shape[0] for x in jax.tree.leaves(state)
                if getattr(x, "ndim", 0) >= 1), default=None)


def shard_state(state, mesh: Mesh, n: int | None = None):
    """Place a per-node-leading-axis state pytree onto the mesh.

    Arrays whose leading dim equals the node count shard on it; everything
    else replicates. Works for DenseState, RumorState, and FaultPlan.
    """
    nn = _node_dim(state, n)

    def place(x):
        if getattr(x, "ndim", 0) >= 1 and x.shape[0] == nn:
            return jax.device_put(x, node_sharding(mesh, x.ndim))
        return jax.device_put(x, replicated(mesh))

    return jax.tree.map(place, state)


def state_shardings(state, mesh: Mesh, n: int | None = None):
    """The NamedSharding pytree matching `shard_state` (for jit donation)."""
    nn = _node_dim(state, n)

    def spec(x):
        if getattr(x, "ndim", 0) >= 1 and x.shape[0] == nn:
            return node_sharding(mesh, x.ndim)
        return replicated(mesh)

    return jax.tree.map(spec, state)
