"""Bridge client: run protocol cores in THIS process against a remote
simulated cluster — the Python mock of the Haskell co-process.

SURVEY.md §7 step 6: until a populated reference tree and a GHC toolchain
exist, a Python mock of the external driver defines the bridge contract.
`ExternalNodeHost` is that mock — and also a real proof that the seam
works, because the nodes it hosts are complete swim_tpu `Node` protocol
engines that know nothing about the bridge: they see only a `Clock` and a
`Transport`, exactly the two seams the reference's typeclass abstracts.

With multiple clients on one server, every client's STEP advances the
SHARED clock, so a node's worst-case receive lag is ~(n_clients × quantum);
choose quantum ≪ probe timeout / n_clients when co-simulating several
processes.

Lockstep loop (per `run(duration)` call, in `quantum`-sized slices):
  1. STEP(dt) → server advances shared virtual time, returns DELIVER
     frames for our nodes and the new TIME,
  2. deliveries are handed to the local nodes' receivers,
  3. the local SimClock advances to the server's time, firing node timers,
     whose sends become SEND frames (applied server-side next quantum —
     the ≤ quantum skew is the bridge's one timing approximation).
"""

from __future__ import annotations

import socket

from swim_tpu.bridge import protocol as bp
from swim_tpu.config import SwimConfig
from swim_tpu.core.clock import SimClock
from swim_tpu.core.node import Node
from swim_tpu.core.transport import Address, Transport


class BridgeTransport(Transport):
    """Transport instance whose wire is the bridge connection."""

    def __init__(self, host: "ExternalNodeHost", node_id: int):
        self._host = host
        self._addr: Address = ("sim", node_id)
        self._receiver = None

    def send(self, to: Address, payload: bytes) -> None:
        self._host._send(self._addr[1], to[1], payload)

    def set_receiver(self, receiver) -> None:
        self._receiver = receiver

    @property
    def local_address(self) -> Address:
        return self._addr


class ExternalNodeHost:
    """Hosts protocol cores client-side, lockstepped to a BridgeServer."""

    def __init__(self, address: Address, quantum: float = 0.1):
        self.quantum = quantum
        self.clock = SimClock()
        self.nodes: dict[int, Node] = {}
        self._transports: dict[int, BridgeTransport] = {}
        self._sock = socket.create_connection(address)

    # ------------------------------------------------------------- lifecycle

    def add_node(self, cfg: SwimConfig, node_id: int,
                 seeds: list[int] = (), seed: int | None = None) -> Node:
        bp.write_frame(self._sock, bp.Frame(bp.HELLO, a=node_id))
        f = bp.read_frame(self._sock)
        if f is None or f.op == bp.ERROR:
            raise ValueError(f"bridge rejected node id {node_id}: {f}")
        if f.op != bp.WELCOME:
            raise ConnectionError(f"expected WELCOME, got {f}")
        self.clock.advance_to(f.t)
        transport = BridgeTransport(self, node_id)
        node = Node(cfg, node_id, transport, self.clock, seed=seed)
        self.nodes[node_id] = node
        self._transports[node_id] = transport
        node.start(seeds=[("sim", s) for s in seeds])
        return node

    def close(self) -> None:
        try:
            bp.write_frame(self._sock, bp.Frame(bp.BYE))
        except OSError:
            pass
        self._sock.close()

    # ------------------------------------------------------------- controls

    def kill(self, node_id: int) -> None:
        """Fault injection on the server's network (any node, either side)."""
        bp.write_frame(self._sock, bp.Frame(bp.KILL, a=node_id))
        node = self.nodes.get(node_id)
        if node is not None:
            node.stop()

    def set_loss(self, loss: float) -> None:
        bp.write_frame(self._sock, bp.Frame(bp.SET_LOSS, t=loss))

    # --------------------------------------------------------------- driving

    def run(self, duration: float) -> None:
        """Advance the co-simulation `duration` virtual seconds."""
        end = self.clock.now() + duration
        while self.clock.now() < end - 1e-9:
            dt = min(self.quantum, end - self.clock.now())
            bp.write_frame(self._sock, bp.Frame(bp.STEP, t=dt))
            deliveries: list[bp.Frame] = []
            while True:
                f = bp.read_frame(self._sock)
                if f is None:
                    raise ConnectionError("bridge closed mid-step")
                if f.op == bp.TIME:
                    now = f.t
                    break
                if f.op != bp.DELIVER:
                    raise ConnectionError(f"unexpected frame mid-step: {f}")
                deliveries.append(f)
            for d in deliveries:
                # through the Transport seam — the node registered its
                # receiver via set_receiver and knows nothing of the bridge
                t = self._transports.get(d.b)
                if t is not None and t._receiver is not None:
                    t._receiver(("sim", d.a), d.payload)
            self.clock.advance_to(now)

    # --------------------------------------------------------------- internal

    def _send(self, src: int, dst: int, payload: bytes) -> None:
        bp.write_frame(self._sock, bp.Frame(bp.SEND, a=src, b=dst,
                                            payload=payload))
