"""Host bridge: external protocol cores ↔ swim_tpu simulated clusters.

The reference's `Swim.Transport` typeclass is the seam an external
(Haskell) core plugs through; this package is the swim_tpu side of that
seam — a lockstep TCP protocol (protocol.py), a cluster-hosting server
(server.py), and the Python mock driver that defines the contract until
the Haskell co-process exists (client.py). SURVEY.md §2 "Host bridge",
§7 step 6.
"""

from swim_tpu.bridge.client import BridgeTransport, ExternalNodeHost
from swim_tpu.bridge.engine_server import EngineBridgeServer
from swim_tpu.bridge.server import BridgeServer

__all__ = ["BridgeServer", "BridgeTransport", "EngineBridgeServer",
           "ExternalNodeHost"]
