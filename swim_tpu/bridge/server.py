"""Bridge server: host a simulated cluster that external cores can join.

`BridgeServer` owns a SimClock + SimNetwork (optionally pre-populated with
in-process swim_tpu Nodes) and speaks the lockstep protocol of
swim_tpu/bridge/protocol.py with one external co-process. Every bridged
node is a first-class SimNetwork endpoint: loss, partitions, kills, and
latency apply to its traffic exactly as to in-process nodes' — which makes
the server a conformance harness for ANY external SWIM implementation (the
reference's Haskell core behind a socket-writing `Swim.Transport` instance
would plug in here unchanged; SURVEY.md §2 "Host bridge").

Determinism: virtual time advances only inside STEP handling, on the
server's single service thread, so a (server seed, client script) pair
replays identically.
"""

from __future__ import annotations

import socket
import threading

from swim_tpu.bridge import protocol as bp
from swim_tpu.config import SwimConfig
from swim_tpu.core.clock import SimClock
from swim_tpu.core.node import Node
from swim_tpu.core.transport import Address, InProcessTransport, SimNetwork


def _make_metrics_server(host: str, port: int, nodes: list[Node]):
    """Stdlib HTTP server exposing GET /metrics (Prometheus text 0.0.4):
    per-node typed registries, a `swim_build_info` gauge, the current
    `swim_health_*` gauges (obs/health.py real-node rules evaluated per
    scrape — `swim-tpu observe URL --follow` tails this), and — when a
    profile artifact exists (bench_results/profile_phases.json, written
    by `swim-tpu profile --out`) — the latest `swim_prof_*`
    phase-attribution gauges (obs/prof.py)."""
    import http.server

    from swim_tpu.obs.expo import (render_health, render_profile,
                                   render_prometheus)
    from swim_tpu.obs.health import evaluate_registries
    from swim_tpu.obs.prof import load_artifact

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):                                  # noqa: N802
            if self.path.split("?")[0] != "/metrics":
                self.send_error(404)
                return
            body = render_prometheus(
                (({"node": str(n.id)}, n.registry) for n in nodes),
                build_labels={"nodes": str(len(nodes))})
            body += render_health(
                evaluate_registries(n.registry for n in nodes))
            profile = load_artifact()      # best-effort; None when absent
            if profile is not None:
                body += render_profile(profile)
            data = body.encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def log_message(self, *a):                         # quiet
            pass

    return http.server.ThreadingHTTPServer((host, port), Handler)


class BridgeServer:
    """`metrics_port` (optional) additionally serves Prometheus text
    exposition (swim_tpu/obs/expo.py) over plain HTTP: GET /metrics
    renders every in-process node's typed counter/histogram registry
    with a `node` label.  0 binds an ephemeral port (tests); None (the
    default) serves no metrics endpoint."""

    def __init__(self, cfg: SwimConfig, n_internal: int, seed: int = 0,
                 loss: float = 0.0, host: str = "127.0.0.1", port: int = 0,
                 metrics_port: int | None = None):
        self.cfg = cfg
        self.clock = SimClock()
        self.network = SimNetwork(self.clock, seed=seed, loss=loss)
        self.nodes: list[Node] = []
        for i in range(n_internal):
            t = InProcessTransport(self.network, i)
            self.nodes.append(Node(cfg, i, t, self.clock, seed=seed * 7919 + i))
        self._bridged: dict[int, InProcessTransport] = {}
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(4)
        self.address: Address = self._sock.getsockname()
        self._thread: threading.Thread | None = None
        self._metrics_httpd = None
        self.metrics_address: Address | None = None
        if metrics_port is not None:
            self._metrics_httpd = _make_metrics_server(
                host, metrics_port, self.nodes)
            self.metrics_address = self._metrics_httpd.server_address[:2]
        self._started = False
        self._closing = False
        self._lock = threading.Lock()   # serializes command handling:
        # virtual time and the network mutate under exactly one client
        # command at a time, so multi-client co-simulation stays
        # deterministic given the interleaving of their STEPs

    # ---------------------------------------------------------------- server

    def start(self) -> None:
        """Start internal nodes (bootstrapped full-mesh) + service thread."""
        members = [(n.id, n.transport.local_address) for n in self.nodes]
        for n in self.nodes:
            n.bootstrap(members)
            n.start()
        self._started = True
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        if self._metrics_httpd is not None:
            threading.Thread(target=self._metrics_httpd.serve_forever,
                             daemon=True).start()

    def _serve(self) -> None:
        """Accept co-process clients until every connected client has hung
        up (at least one must connect first) or close() fires. Each
        connection gets a reader thread; command handling serializes on
        self._lock, so virtual time and the network mutate under exactly
        one client command at a time — multi-client co-simulation stays
        deterministic given the interleaving of the clients' STEPs."""
        self._sock.settimeout(0.2)
        workers: list[threading.Thread] = []
        try:
            while not self._closing:
                try:
                    conn, _ = self._sock.accept()
                except socket.timeout:
                    if workers and not any(w.is_alive() for w in workers):
                        break
                    continue
                except OSError:
                    break
                w = threading.Thread(target=self._serve_conn, args=(conn,),
                                     daemon=True)
                w.start()
                workers.append(w)
        finally:
            self._sock.close()

    def _serve_conn(self, conn: socket.socket) -> None:
        outbox: list[tuple[int, int, bytes]] = []
        owned: set[int] = set()
        try:
            while True:
                try:
                    f = bp.read_frame(conn)
                except (ValueError, OSError):
                    return  # torn frame / dead peer: drop this client only
                if f is None or f.op == bp.BYE:
                    return
                with self._lock:
                    # only wire writes are recoverable here; a protocol-
                    # engine error inside _handle must propagate loudly,
                    # not masquerade as a client disconnect
                    try:
                        self._handle(conn, f, outbox, owned)
                    except OSError:
                        return
        finally:
            with self._lock:
                # a vanished client's nodes must not black-hole traffic or
                # squat their ids: detach so a reconnect can re-claim
                for node_id in owned:
                    ep = self._bridged.pop(node_id, None)
                    if ep is not None:
                        self.network.detach(ep.local_address)
            conn.close()

    def _handle(self, conn: socket.socket, f: bp.Frame,
                outbox: list[tuple[int, int, bytes]],
                owned: set[int]) -> None:
        if f.op == bp.HELLO:
            if self._attach(f.a, outbox):
                owned.add(f.a)
                bp.write_frame(conn, bp.Frame(bp.WELCOME, a=f.a,
                                              t=self.clock.now()))
            else:
                bp.write_frame(conn, bp.Frame(bp.ERROR, a=bp.ERR_ID_TAKEN))
        elif f.op == bp.SEND:
            # only a connection's own nodes may transmit through it —
            # multi-client conformance runs must not let one client
            # attribute traffic to another's implementation. (KILL stays
            # global on purpose: it is harness fault injection, not node
            # behavior.) Faults then apply to the send like anyone's.
            ep = self._bridged.get(f.a) if f.a in owned else None
            if ep is not None:
                ep.send(("sim", f.b), f.payload)
        elif f.op == bp.STEP:
            self.clock.advance(f.t)
            out = list(outbox)
            outbox.clear()
            for src, dst, payload in out:
                bp.write_frame(conn, bp.Frame(bp.DELIVER, a=src, b=dst,
                                              payload=payload))
            bp.write_frame(conn, bp.Frame(bp.TIME, t=self.clock.now()))
        elif f.op == bp.KILL:
            self.kill(f.a)
        elif f.op == bp.SET_LOSS:
            self.network.set_loss(f.t)

    def _attach(self, node_id: int,
                outbox: list[tuple[int, int, bytes]]) -> bool:
        """Claim an endpoint for an external node, delivering into its
        owning connection's outbox; False if the id is taken (claiming an
        internal node's id would silently hijack its endpoint — the
        harness must reject that, not swallow it)."""
        if node_id in self._bridged or any(n.id == node_id
                                           for n in self.nodes):
            return False
        ep = InProcessTransport(self.network, node_id)

        def receiver(src: Address, payload: bytes, _id=node_id):
            outbox.append((src[1], _id, payload))

        ep.set_receiver(receiver)
        self._bridged[node_id] = ep
        return True

    # ------------------------------------------------------------- controls

    def kill(self, node_id: int) -> None:
        self.network.kill(("sim", node_id))
        for n in self.nodes:
            if n.id == node_id:
                n.stop()

    def close(self) -> None:
        """Stop accepting new clients; existing connections finish."""
        self._closing = True
        if self._metrics_httpd is not None:
            self._metrics_httpd.shutdown()
            self._metrics_httpd.server_close()
            self._metrics_httpd = None

    def join(self, timeout: float = 10.0) -> None:
        if self._thread is not None:
            self._thread.join(timeout)
