"""Engine-backed bridge: a foreign core's peers ARE the tensor simulation.

`EngineBridgeServer` completes the TPUSimTransport seam (SURVEY.md §2
"Host bridge"; VERDICT r2 "Missing #3"): where `bridge/server.py` hosts
an event-driven cluster of real `core/node.py` nodes, this server hosts
an N-node RING-ENGINE simulation (swim_tpu/models/ring.py) and couples
ONE externally-driven node id to it over the existing lockstep TCP
protocol (bridge/protocol.py) — so an untouched foreign SWIM core (e.g.
swim_tpu/native/bridge_client.cpp) probes, gossips with, and detects
failures among tens of thousands of tensor-simulated peers.

The seam, per protocol period (one `STEP` accumulation of cfg.protocol_period):

  outbound (engine → core): the reserved row X is the core's SHADOW in
    tensor state.  After each period the server diffs X's resolved
    heard-bits, decodes the newly-heard ring slots through the rumor
    table, and DELIVERs them as the piggyback of the ping that the
    rotor prober (X − s_t) actually sent X inside the engine — the wire
    traffic mirrors the tensor wave that carried the bits.
  inbound (core → engine): every datagram the core SENDs is decoded
    (swim_tpu/core/codec.py); its gossip updates become Phase-D
    external originations (`ring.ExtOriginations`) with the datagram's
    receiving engine node as the hearer, so the core's claims — its
    suspicions, its refutations — radiate through tensor state from
    the true delivery point.  Pings/ping-reqs are answered immediately
    from engine state (alive target → synthesized ack carrying the
    target's actual transmissible window selection).
  liveness: the engine's view of X is gated on the core really
    answering the mirrored probes: no ack for `ack_grace` periods →
    crash_step[X] = now, and the engine detects the silent core
    organically (suspicion → confirm → dissemination).

Deviations (documented; the seam is a transport, not a re-simulation):
  D1. Row X keeps its mechanical engine behavior (rotor probing, window
      recycling); the core's own agency enters as ADDITIONAL forced
      originations. A fully externally-computed X would need per-wave
      extraction, which the lockstep protocol's datagram granularity
      cannot express.
  D2. An injected update whose rumor already exists in the table dedups
      onto the existing slot without setting the hearer's bit (it hears
      through normal waves); stale updates (key ≤ the table's best for
      that subject, or ≤ the tombstone floor) are dropped host-side.
  D3. Client-facing replies (acks, join snapshot) are synthesized from
      engine state at datagram time, not queued to period boundaries —
      the core's sub-period probe timers (e.g. 0.3·period) would
      otherwise time out by construction.
  D4. Wire loss: every core→engine datagram leg (and each synthesized
      reply leg) draws Bernoulli(loss) from a seeded host RNG, so the
      core experiences the configured loss rate like any engine wave.
      Mirrored pings deliver losslessly: their piggyback content
      already passed the engine's in-wave loss draws, and a second
      draw would double-count; a lost mirrored-ACK (core→engine) is
      how the core gets organically suspected under loss.

Reference parity: jpfuentes2/swim's transport seam is its socket layer
(SURVEY.md §1, tree unavailable — §0); this is the TPU-native analog,
with the simulated side an XLA program instead of a process pool.
"""

from __future__ import annotations

import functools
import socket
import threading

import numpy as np

from swim_tpu.bridge import protocol as bp
from swim_tpu.config import SwimConfig
from swim_tpu.core import codec
from swim_tpu.types import (MsgKind, Status, key_incarnation, key_status,
                            opinion_key)

WORD = 32


def _status_of(key: int) -> Status:
    return Status(key_status(key))


def _inc_of(key: int) -> int:
    return key_incarnation(key)


def _pack_key(status: Status, inc: int) -> int:
    # types.opinion_key clamps inc to INC_MAX — essential here: a hostile
    # or corrupt wire incarnation >= 2^30 would otherwise shift into the
    # sticky DEAD bit and falsely tombstone an arbitrary member
    return opinion_key(int(status), inc)


class EngineBridgeServer:
    """Single-client lockstep server over a ring-engine simulation."""

    def __init__(self, cfg: SwimConfig, external_id: int, seed: int = 0,
                 host: str = "127.0.0.1", port: int = 0,
                 ext_capacity: int = 16, ack_grace: int = 3,
                 join_sample: int = 128):
        import jax

        from swim_tpu.models import ring

        if cfg.ring_probe != "rotor":
            raise ValueError("EngineBridgeServer requires the rotor probe "
                             "(the mirrored-ping seam is rotor-shaped)")
        self.cfg = cfg
        self.n = cfg.n_nodes
        if not 0 <= external_id < self.n:
            raise ValueError("external_id must be one of the N node ids")
        self.x = external_id
        self.ext_capacity = ext_capacity
        self.ack_grace = ack_grace
        self.join_sample = join_sample
        self._jax = jax
        self._ring = ring
        self._key = jax.random.key(seed)
        self.state = ring.init_state(cfg)
        self.t = 0                       # completed protocol periods
        self._frac = 0.0                 # virtual time into the period
        # host-side fault mirrors (device plan rebuilt on change)
        self._crash = np.full((self.n,), np.iinfo(np.int32).max // 2,
                              np.int32)
        self._join = np.zeros((self.n,), np.int32)
        self._loss = 0.0
        self._plan = None
        self._plan_dirty = True
        self._step = jax.jit(functools.partial(ring.step, cfg))
        # injections queued for the next period boundary
        self._inject: list[tuple[int, int, int, int]] = []  # subj,key,org,hear
        self._rng = np.random.default_rng(seed * 7919 + 17)  # D4 wire loss
        # host mirrors of the rumor table (refreshed after every period)
        self._subject = np.asarray(self.state.subject)
        self._rkey = np.asarray(self.state.rkey)
        self._gone = np.asarray(self.state.gone_key)
        self._prev_row = self._resolved_row(self.x)
        self._last_ack = -1              # newest mirrored-ping period acked
        self._joined = False
        self._x_crashed = False
        self._outq: list[bp.Frame] = []
        self._lock = threading.Lock()    # guards _outq/_inject/_crash
        #                                  (test hooks run off-thread)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(1)
        self.address = self._sock.getsockname()
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def join(self, timeout: float = 300.0) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    def _serve(self) -> None:
        try:
            conn, _ = self._sock.accept()
        except OSError:
            return
        try:
            while True:
                f = bp.read_frame(conn)
                if f is None or f.op == bp.BYE:
                    return
                self._handle(conn, f)
        except (ValueError, OSError):
            return
        finally:
            conn.close()
            self._sock.close()

    # ------------------------------------------------------------- protocol

    def _now(self) -> float:
        return self.t * self.cfg.protocol_period + self._frac

    def _handle(self, conn: socket.socket, f: bp.Frame) -> None:
        if f.op == bp.HELLO:
            if f.a != self.x or self._joined:
                bp.write_frame(conn, bp.Frame(bp.ERROR, a=bp.ERR_ID_TAKEN))
                return
            self._joined = True
            self._last_ack = self.t  # grace starts at join
            bp.write_frame(conn, bp.Frame(bp.WELCOME, a=f.a, t=self._now()))
        elif f.op == bp.SEND:
            self._on_datagram(f.a, f.b, f.payload)
        elif f.op == bp.STEP:
            self._frac += f.t
            while self._frac >= self.cfg.protocol_period - 1e-9:
                self._frac -= self.cfg.protocol_period
                self._run_period()
            with self._lock:
                flush, self._outq = self._outq, []
            for fr in flush:
                bp.write_frame(conn, fr)
            bp.write_frame(conn, bp.Frame(bp.TIME, t=self._now()))
        elif f.op == bp.KILL:
            self.kill(f.a)
        elif f.op == bp.SET_LOSS:
            self._loss = float(f.t)
            self._plan_dirty = True

    # --------------------------------------------------------- fault wiring

    def kill(self, node_id: int) -> None:
        with self._lock:
            if 0 <= node_id < self.n and self._crash[node_id] > self.t:
                self._crash[node_id] = self.t
                self._plan_dirty = True

    def _alive(self, node_id: int) -> bool:
        return (0 <= node_id < self.n and self._crash[node_id] > self.t
                and self._join[node_id] <= self.t)

    def _device_plan(self):
        if self._plan_dirty or self._plan is None:
            import jax.numpy as jnp

            from swim_tpu.sim.faults import FaultPlan

            self._plan = FaultPlan(
                crash_step=jnp.asarray(self._crash),
                loss=jnp.float32(self._loss),
                partition_id=jnp.zeros((self.n,), jnp.int32),
                partition_start=jnp.int32(1 << 30),
                partition_end=jnp.int32(1 << 30),
                join_step=jnp.asarray(self._join))
            self._plan_dirty = False
        return self._plan

    # -------------------------------------------------------- inbound seam

    def _queue_injections(self, hearer: int,
                          gossip: tuple[codec.WireUpdate, ...]) -> None:
        for u in gossip:
            if not 0 <= u.member < self.n:
                continue
            key = _pack_key(u.status, u.incarnation)
            if key <= self._best_key(u.member):
                continue                 # stale vs table/tombstone (D2)
            org = u.origin if 0 <= u.origin < self.n else hearer
            with self._lock:
                self._inject.append((u.member, key, org, hearer))

    def _lost(self) -> bool:
        """Bernoulli loss draw for one bridge datagram leg (D4): the
        core's wire traffic experiences the configured loss rate like
        any engine wave (seeded host RNG — reproducible given the same
        datagram order)."""
        return self._loss > 0.0 and self._rng.random() < self._loss

    def _on_datagram(self, src: int, dst: int, payload: bytes) -> None:
        if src != self.x:
            return
        try:
            msg = codec.decode(payload)
        except codec.DecodeError:
            return
        if not self._alive(dst) or self._lost():
            return     # datagram to a dead node, or lost on the wire:
            #            nothing is heard and nothing replies (D4)
        self._queue_injections(dst, msg.gossip)
        if msg.kind == MsgKind.PING:
            if self._lost():             # ack leg draws its own loss
                return
            ack = codec.Message(kind=MsgKind.ACK, sender=dst,
                                probe_seq=msg.probe_seq,
                                on_behalf=msg.on_behalf,
                                gossip=self._transmissible(dst))
            self._deliver(dst, ack)
        elif msg.kind == MsgKind.PING_REQ:
            tgt = msg.target
            # proxy round-trip: two more legs (proxy->tgt, tgt->proxy)
            # plus the relay ack leg, each drawing loss
            if (self._alive(tgt) and not self._lost()
                    and not self._lost() and not self._lost()):
                ack = codec.Message(kind=MsgKind.ACK, sender=dst,
                                    probe_seq=msg.probe_seq,
                                    on_behalf=tgt,
                                    gossip=self._transmissible(tgt))
                self._deliver(dst, ack)
        elif msg.kind == MsgKind.ACK:
            self._last_ack = self.t      # the core answered a mirrored ping
        elif msg.kind == MsgKind.JOIN:
            if self._lost():             # reply leg draws loss too (D4)
                return
            self._deliver(dst, codec.Message(
                kind=MsgKind.JOIN_REPLY, sender=dst,
                gossip=self._join_snapshot()))

    def _deliver(self, sender: int, msg: codec.Message) -> None:
        with self._lock:
            self._outq.append(bp.Frame(bp.DELIVER, a=sender, b=self.x,
                                       payload=codec.encode(msg)))

    # -------------------------------------------------------- outbound seam

    def _run_period(self) -> None:
        import jax

        from swim_tpu.models import ring

        # liveness gate: a silent core is a crashed member
        if (self._joined and not self._x_crashed
                and self.t - self._last_ack > self.ack_grace):
            self.kill(self.x)
            self._x_crashed = True
        ext = ring.ext_none(self.ext_capacity)
        with self._lock:
            batch, self._inject = (self._inject[:self.ext_capacity],
                                   self._inject[self.ext_capacity:])
        if batch:
            import jax.numpy as jnp

            ext = ring.ExtOriginations(
                subject=jnp.asarray(
                    [b[0] for b in batch]
                    + [-1] * (self.ext_capacity - len(batch)), jnp.int32),
                key=jnp.asarray(
                    [b[1] for b in batch]
                    + [0] * (self.ext_capacity - len(batch)), jnp.uint32),
                origin=jnp.asarray(
                    [b[2] for b in batch]
                    + [0] * (self.ext_capacity - len(batch)), jnp.int32),
                hearer=jnp.asarray(
                    [b[3] for b in batch]
                    + [0] * (self.ext_capacity - len(batch)), jnp.int32))
        rnd = self._ring.draw_period_ring(self._key, self.t, self.cfg)
        self.state = self._step(self.state, self._device_plan(), rnd,
                                ext=ext)
        s_off = int(jax.device_get(rnd.s_off))
        self.t += 1
        # refresh table mirrors, then mirror the rotor probe of X
        self._subject = np.asarray(self.state.subject)
        self._rkey = np.asarray(self.state.rkey)
        self._gone = np.asarray(self.state.gone_key)
        row = self._resolved_row(self.x)
        fresh = row & ~self._prev_row
        self._prev_row = row
        if not self._joined:
            return
        prober = (self.x - s_off) % self.n
        if not self._alive(prober):
            return                       # no probe of X this period
        updates = self._slots_to_updates(np.nonzero(fresh)[0], prober)
        for chunk in range(0, max(len(updates), 1), 255):
            ping = codec.Message(kind=MsgKind.PING, sender=prober,
                                 probe_seq=self.t,
                                 gossip=tuple(updates[chunk:chunk + 255]))
            self._deliver(prober, ping)

    # ------------------------------------------------------- state decoding

    def _geom(self):
        return self._ring.geometry(self.cfg)

    def _resolved_row(self, x: int) -> np.ndarray:
        """bool[R]: node x's current heard-bits (host mirror of
        ring.resolved_words for a single node)."""
        g = self._geom()
        win_x = np.asarray(self.state.win[x])          # u32[WW]
        cold_x = np.asarray(self.state.cold[:, x])     # u32[RW]
        t = int(self.state.step)
        first_gw = t * g.ow - g.ww
        win_ring0 = first_gw % g.rw
        words = cold_x.copy()
        for w in range(g.ww):
            words[(win_ring0 + w) % g.rw] = win_x[w]
        bits = np.unpackbits(
            words.astype("<u4").view(np.uint8), bitorder="little")
        return bits.astype(bool)

    def _best_key(self, member: int) -> int:
        """The strongest table/tombstone key currently held for member
        (numpy mirrors only — this runs per gossip update on the
        datagram hot path; a device gather here would cost hundreds of
        host round-trips per datagram)."""
        mask = self._subject == member
        best = int(self._rkey[mask].max()) if mask.any() else 0
        return max(best, int(self._gone[member]))

    def _slots_to_updates(self, slots: np.ndarray,
                          origin: int) -> list[codec.WireUpdate]:
        out = []
        for sl in slots.tolist():
            subj = int(self._subject[sl])
            if subj < 0:
                continue
            key = int(self._rkey[sl])
            out.append(codec.WireUpdate(
                member=subj, status=_status_of(key), incarnation=_inc_of(key),
                addr=("sim", subj), origin=origin))
        return out

    def _transmissible(self, j: int) -> tuple[codec.WireUpdate, ...]:
        """Node j's current piggyback: up to B used slots of its window
        (host mirror of the engine's first-B window selection)."""
        g = self._geom()
        win_j = np.asarray(self.state.win[j])          # u32[WW]
        t = int(self.state.step)
        first_gw = t * g.ow - g.ww
        r_tot = g.rw * WORD
        out = []
        b = min(self.cfg.max_piggyback, g.ww * WORD)
        for w in range(g.ww - 1, -1, -1):              # newest word first
            word = int(win_j[w])
            while word and len(out) < b:
                bit = (word & -word).bit_length() - 1
                word &= word - 1
                sl = (((first_gw + w) % g.rw) * WORD + bit) % r_tot
                subj = int(self._subject[sl])
                if subj < 0:
                    continue
                key = int(self._rkey[sl])
                out.append(codec.WireUpdate(
                    member=subj, status=_status_of(key),
                    incarnation=_inc_of(key), addr=("sim", subj), origin=j))
            if len(out) >= b:
                break
        return tuple(out)

    def _join_snapshot(self) -> tuple[codec.WireUpdate, ...]:
        """Up to `join_sample` alive members, spread across the id space
        (the wire gossip count is u8 — a 64k snapshot cannot fit, and
        SWIM only needs a partial view to bootstrap probing)."""
        stride = max(1, self.n // self.join_sample)
        out = []
        for m in range(0, self.n, stride):
            if m != self.x and self._alive(m):
                out.append(codec.WireUpdate(
                    member=m, status=Status.ALIVE, incarnation=0,
                    addr=("sim", m), origin=m))
            if len(out) >= min(self.join_sample, 255):
                break
        return tuple(out)

    # ------------------------------------------------------------ test hooks

    def inject_update(self, subject: int, status: Status, inc: int,
                      origin: int, hearer: int) -> None:
        """Queue a rumor injection directly (bypasses the wire)."""
        with self._lock:
            self._inject.append(
                (subject, _pack_key(status, inc), origin, hearer))

    def deliver_forged(self, sender: int,
                       updates: list[codec.WireUpdate]) -> None:
        """DELIVER a forged gossip-bearing ping to the core WITHOUT
        touching tensor state.  Test use: forge suspect(X) on the wire
        only — the engine's shadow row never sees a suspicion, so any
        alive(X, inc≥1) that later appears in tensor state can ONLY be
        the foreign core's refutation arriving through the injection
        seam (the engine-side proof is inc_self[X] staying 0)."""
        self._deliver(sender, codec.Message(
            kind=MsgKind.PING, sender=sender, probe_seq=0,
            gossip=tuple(updates)))

    def table_keys(self, subject: int) -> list[int]:
        """All live table keys about `subject` (host mirror)."""
        return [int(k) for k in self._rkey[self._subject == subject]]
