"""Engine-backed bridge: a foreign core's peers ARE the tensor simulation.

`EngineBridgeServer` completes the TPUSimTransport seam (SURVEY.md §2
"Host bridge"; VERDICT r2 "Missing #3"): where `bridge/server.py` hosts
an event-driven cluster of real `core/node.py` nodes, this server hosts
an N-node RING-ENGINE simulation (swim_tpu/models/ring.py) and couples
K externally-driven node ids to it over the existing lockstep TCP
protocol (bridge/protocol.py) — so untouched foreign SWIM cores (e.g.
swim_tpu/native/bridge_client.cpp) probe, gossip with, and detect
failures among tens of thousands of tensor-simulated peers AND each
other (multi-session lockstep barrier + hub-routed core↔core
datagrams — see the class docstring; round 4, VERDICT r3 item 5).

The seam, per protocol period (one `STEP` accumulation of cfg.protocol_period):

  outbound (engine → core): the reserved row X is the core's SHADOW in
    tensor state.  After each period the server diffs X's resolved
    heard-bits, decodes the newly-heard ring slots through the rumor
    table, and DELIVERs them as the piggyback of the ping that the
    rotor prober (X − s_t) actually sent X inside the engine — the wire
    traffic mirrors the tensor wave that carried the bits.
  inbound (core → engine): every datagram the core SENDs is decoded
    (swim_tpu/core/codec.py); its gossip updates become Phase-D
    external originations (`ring.ExtOriginations`) with the datagram's
    receiving engine node as the hearer, so the core's claims — its
    suspicions, its refutations — radiate through tensor state from
    the true delivery point.  Pings/ping-reqs are answered immediately
    from engine state (alive target → synthesized ack carrying the
    target's actual transmissible window selection).
  liveness: the engine's view of X is gated on the core really
    answering the mirrored probes: no ack for `ack_grace` periods →
    crash_step[X] = now, and the engine detects the silent core
    organically (suspicion → confirm → dissemination).

Deviations (documented; the seam is a transport, not a re-simulation):
  D1. Row X keeps its mechanical engine behavior (rotor probing, window
      recycling); the core's own agency enters as ADDITIONAL forced
      originations. A fully externally-computed X would need per-wave
      extraction, which the lockstep protocol's datagram granularity
      cannot express.
  D2. An injected update whose rumor already exists in the table dedups
      onto the existing slot without setting the hearer's bit (it hears
      through normal waves); stale updates (key ≤ the table's best for
      that subject, or ≤ the tombstone floor) are dropped host-side.
  D3. Client-facing replies (acks, join snapshot) are synthesized from
      engine state at datagram time, not queued to period boundaries —
      the core's sub-period probe timers (e.g. 0.3·period) would
      otherwise time out by construction.
  D4. Wire loss: every core→engine datagram leg (and each synthesized
      reply leg) draws Bernoulli(loss) from a seeded host RNG, so the
      core experiences the configured loss rate like any engine wave.
      Mirrored pings deliver losslessly: their piggyback content
      already passed the engine's in-wave loss draws, and a second
      draw would double-count; a lost mirrored-ACK (core→engine) is
      how the core gets organically suspected under loss.

Reference parity: jpfuentes2/swim's transport seam is its socket layer
(SURVEY.md §1, tree unavailable — §0); this is the TPU-native analog,
with the simulated side an XLA program instead of a process pool.
"""

from __future__ import annotations

import functools
import socket
import threading

import numpy as np

from swim_tpu.bridge import protocol as bp
from swim_tpu.config import SwimConfig
from swim_tpu.core import codec
from swim_tpu.obs.health import Finding
from swim_tpu.types import (MsgKind, Status, key_incarnation, key_status,
                            opinion_key)

WORD = 32


def _status_of(key: int) -> Status:
    return Status(key_status(key))


def _inc_of(key: int) -> int:
    return key_incarnation(key)


def _pack_key(status: Status, inc: int) -> int:
    # types.opinion_key clamps inc to INC_MAX — essential here: a hostile
    # or corrupt wire incarnation >= 2^30 would otherwise shift into the
    # sticky DEAD bit and falsely tombstone an arbitrary member
    return opinion_key(int(status), inc)


class _Session:
    """One TCP connection hosting one or more external node ids."""

    def __init__(self, sock: socket.socket):
        import time

        self.sock = sock
        self.ids: list[int] = []
        self.clock = 0.0                 # this session's virtual time
        self.outq: list[bp.Frame] = []
        self.live = True
        self.last_step_wall = time.monotonic()
        self.step_pending = False        # STEP read, waiting on _engine


class EngineBridgeServer:
    """Multi-client lockstep server over a ring-engine simulation.

    K external cores (each its own TCP session; a session may HELLO
    several ids, like ExternalNodeHost) co-simulate against one tensor
    cluster.  Time is conservative lockstep across sessions: each STEP
    advances only that session's virtual clock, and an engine period
    runs when EVERY live joined session has reached the period boundary
    (the barrier is min over session clocks — with one session this
    degenerates to the original single-client behavior exactly).  A
    session that disconnects leaves the barrier; its rows then miss
    their mirrored-probe acks and are crash-gated after `ack_grace`
    periods, so the remaining cores detect the departure organically.

    Datagrams between two external ids short-circuit over the wire
    (one D4 loss draw, no tensor involvement): the server is the hub,
    and two foreign cores can probe and gossip with EACH OTHER while
    both remain coupled to the tensor cluster.
    """

    def __init__(self, cfg: SwimConfig, external_id: int | None = None,
                 seed: int = 0, host: str = "127.0.0.1", port: int = 0,
                 ext_capacity: int = 16, ack_grace: int = 3,
                 join_sample: int = 128,
                 external_ids: list[int] | None = None,
                 stall_timeout: float = 60.0):
        import jax

        from swim_tpu.models import ring

        if cfg.ring_probe != "rotor":
            raise ValueError("EngineBridgeServer requires the rotor probe "
                             "(the mirrored-ping seam is rotor-shaped)")
        if external_ids is None:
            if external_id is None:
                raise ValueError("pass external_id or external_ids")
            external_ids = [external_id]
        elif external_id is not None:
            raise ValueError("pass external_id OR external_ids, not both")
        self.cfg = cfg
        self.n = cfg.n_nodes
        for x in external_ids:
            if not 0 <= x < self.n:
                raise ValueError("external ids must be N node ids")
        if len(set(external_ids)) != len(external_ids):
            raise ValueError("duplicate external ids")
        self.xs = list(external_ids)
        self.x = self.xs[0]              # back-compat accessor
        self.ext_capacity = ext_capacity
        self.ack_grace = ack_grace
        self.join_sample = join_sample
        self.stall_timeout = stall_timeout   # wall s without a STEP
        #                                      before a session stops
        #                                      gating the barrier
        self._jax = jax
        self._ring = ring
        self._key = jax.random.key(seed)
        self.state = ring.init_state(cfg)
        self.t = 0                       # completed protocol periods
        # host-side fault mirrors (device plan rebuilt on change)
        self._crash = np.full((self.n,), np.iinfo(np.int32).max // 2,
                              np.int32)
        self._join = np.zeros((self.n,), np.int32)
        self._loss = 0.0
        self._plan = None
        self._plan_dirty = True
        self._plan_gen = 0               # bumped on every fault mutation
        self._step = jax.jit(functools.partial(ring.step, cfg))
        # injections queued for the next period boundary
        self._inject: list[tuple[int, int, int, int]] = []  # subj,key,org,hear
        self._rng = np.random.default_rng(seed * 7919 + 17)  # D4 wire loss
        # host mirrors of the rumor table (refreshed after every period)
        self._subject = np.asarray(self.state.subject)
        self._rkey = np.asarray(self.state.rkey)
        self._gone = np.asarray(self.state.gone_key)
        # per-external-id seam state
        self._prev_rows: dict[int, np.ndarray] = {}
        self._last_acks: dict[int, int] = {}
        # ack-opportunity accounting for the liveness gate: a "ping
        # flush" is one outq flush that carried >=1 mirrored ping for
        # the id — the only events the core can possibly ack.  After an
        # id joins, all three dicts are mutated under self._engine
        # (STEP/SEND handlers and _run_period all hold it); the HELLO
        # handler initializes the id's keys under self._lock alone,
        # which is safe only because the id is not yet in _prev_rows
        # (the gate's iteration set) at that point.
        self._ping_pending: dict[int, bool] = {}   # queued, not flushed
        self._ping_flushes: dict[int, int] = {}    # flushes with pings
        self._ack_flush: dict[int, int] = {}       # _ping_flushes @ ack
        self._ext_crashed: dict[int, bool] = {x: False for x in self.xs}
        self.findings: list[Finding] = []   # session_evicted health trail
        self._owner: dict[int, _Session] = {}    # joined id -> session
        self._claimed: set[int] = set()          # ids ever HELLO'd
        self._sessions: list[_Session] = []
        self._lock = threading.Lock()    # guards queues/_inject/_crash
        #                                  (test hooks run off-thread)
        self._engine = threading.Lock()  # serializes period execution
        self._closing = False
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(max(len(self.xs), 1))
        self.address = self._sock.getsockname()
        self._thread: threading.Thread | None = None

    # -------------------------------------------------- back-compat views

    @property
    def _joined(self) -> bool:
        return bool(self._owner)

    @property
    def _x_crashed(self) -> bool:
        return self._ext_crashed[self.x]

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def join(self, timeout: float = 300.0) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    def close(self) -> None:
        self._closing = True
        try:
            self._sock.close()
        except OSError:
            pass
        # unblock handler threads parked in read_frame: close every
        # session socket too (a reader then sees EOF/OSError and exits)
        with self._lock:
            sessions = list(self._sessions)
        for sess in sessions:
            try:
                sess.sock.close()
            except OSError:
                pass

    def _serve(self) -> None:
        """Accept loop: one handler thread per session.  Exits (closing
        the listen socket) once every external id has been claimed and
        all sessions have disconnected — or on close()."""
        self._sock.settimeout(0.25)
        handlers: list[threading.Thread] = []
        try:
            while not self._closing:
                with self._lock:
                    done = (len(self._claimed) == len(self.xs)
                            and not any(s.live for s in self._sessions)
                            and self._claimed)
                if done:
                    return
                try:
                    conn, _ = self._sock.accept()
                except socket.timeout:
                    continue
                except OSError:
                    return
                sess = _Session(conn)
                with self._lock:
                    self._sessions.append(sess)
                th = threading.Thread(target=self._serve_session,
                                      args=(sess,), daemon=True)
                th.start()
                handlers.append(th)
        finally:
            for th in handlers:
                th.join(timeout=10)
            try:
                self._sock.close()
            except OSError:
                pass

    def _serve_session(self, sess: _Session) -> None:
        try:
            while True:
                f = bp.read_frame(sess.sock)
                if f is None or f.op == bp.BYE:
                    return
                self._handle(sess, f)
        except (ValueError, OSError):
            return
        finally:
            with self._lock:
                sess.live = False
            sess.sock.close()

    # ------------------------------------------------------------- protocol

    def _session_gates(self, s: _Session, now: float) -> bool:
        """Whether session s gates the barrier: live, joined, and not
        wall-clock-stalled.  Shared by _gating_clocks and _run_period's
        crash-gate — the two MUST agree, or a session could keep gating
        (and flushing) while the crash-gate judges it non-gating and
        applies the engine-time lag to its healthy ids.  Only STEP
        frames refresh the wall stamp (a wedged client spamming SENDs
        must still stall out), and a session whose STEP is read but
        queued behind the engine lock (step_pending) always gates — it
        is provably alive with a clock advance in flight, however long
        the current period run holds the lock.  Caller holds
        self._lock."""
        return bool(s.live and s.ids
                    and (s.step_pending
                         or now - s.last_step_wall <= self.stall_timeout))

    def _gating_clocks(self) -> list[float]:
        """Virtual clocks of the sessions that gate the barrier (see
        _session_gates).  A session that keeps its socket open but
        stops STEPping (hung process) would otherwise freeze engine
        time forever AND dodge the ack_grace crash-gate (which only
        runs inside _run_period) — after `stall_timeout` wall seconds
        without a STEP it stops gating; its rows then miss their
        mirrored-probe acks and die organically.  Caller holds
        self._lock."""
        import time

        now = time.monotonic()
        return [s.clock for s in self._sessions
                if self._session_gates(s, now)]

    def _handle(self, sess: _Session, f: bp.Frame) -> None:
        if f.op == bp.HELLO:
            with self._lock:
                ok = f.a in self.xs and f.a not in self._claimed
                if ok:
                    self._claimed.add(f.a)
                    self._owner[f.a] = sess
                    sess.ids.append(f.a)
                    self._last_acks[f.a] = self.t
                    self._ping_pending[f.a] = False
                    self._ping_flushes[f.a] = 0
                    self._ack_flush[f.a] = 0
                    # join pins this session's clock at engine time
                    sess.clock = max(
                        sess.clock, self.t * self.cfg.protocol_period)
            if not ok:
                bp.write_frame(sess.sock,
                               bp.Frame(bp.ERROR, a=bp.ERR_ID_TAKEN))
                return
            with self._engine:
                # serialized vs _run_period: the row extraction reads
                # self.state, which a concurrent STEP would be replacing
                self._prev_rows[f.a] = self._resolved_row(f.a)
            bp.write_frame(sess.sock,
                           bp.Frame(bp.WELCOME, a=f.a, t=sess.clock))
        elif f.op == bp.SEND:
            if f.a in sess.ids:
                with self._engine:
                    # serialized vs _run_period: the seam reads self.t,
                    # self.state, and the table mirrors, which a period
                    # running on another session's thread updates
                    # non-atomically
                    self._on_datagram(f.a, f.b, f.payload)
        elif f.op == bp.STEP:
            import time

            # stamp at STEP READ time, before the engine lock: a session
            # queued behind a slow period run (e.g. the first-period XLA
            # compile) must not be charged the server's own lock hold.
            # step_pending additionally marks it as provably alive WITH
            # an un-processed clock advance, so even a hold longer than
            # stall_timeout cannot wall-stall it out of the barrier and
            # into the engine-time crash-gate.
            with self._lock:
                sess.last_step_wall = time.monotonic()
                sess.step_pending = True
            with self._engine:
                with self._lock:
                    sess.clock += f.t
                    sess.step_pending = False
                    sess.last_step_wall = time.monotonic()
                # conservative barrier: run whole periods while EVERY
                # gating session has crossed the next boundary
                while True:
                    boundary = (self.t + 1) * self.cfg.protocol_period
                    with self._lock:
                        gating = self._gating_clocks()
                    if not gating or min(gating) < boundary - 1e-9:
                        break
                    self._run_period()
                with self._lock:
                    flush, sess.outq = sess.outq, []
                    # the ack-grace clock for this session's ids ticks
                    # on DELIVERED pings, not engine time: mirrored
                    # pings still queued here cannot have been acked
                    # (see _run_period's liveness gate)
                    for x in sess.ids:
                        if self._ping_pending.get(x):
                            self._ping_pending[x] = False
                            self._ping_flushes[x] += 1
            for fr in flush:
                bp.write_frame(sess.sock, fr)
            bp.write_frame(sess.sock, bp.Frame(bp.TIME, t=sess.clock))
        elif f.op == bp.KILL:
            self.kill(f.a)
        elif f.op == bp.SET_LOSS:
            with self._lock:
                self._loss = float(f.t)
                self._plan_dirty = True
                self._plan_gen += 1

    # --------------------------------------------------------- fault wiring

    def kill(self, node_id: int) -> None:
        with self._lock:
            if 0 <= node_id < self.n and self._crash[node_id] > self.t:
                self._crash[node_id] = self.t
                self._plan_dirty = True
                self._plan_gen += 1

    def _alive(self, node_id: int) -> bool:
        return (0 <= node_id < self.n and self._crash[node_id] > self.t
                and self._join[node_id] <= self.t)

    def _device_plan(self):
        # generation-checked rebuild: a concurrent kill()/SET_LOSS on
        # another session's thread landing after the snapshot must not
        # have its dirty mark erased (lost update), and an exception
        # during the build must leave the flag set so the next period
        # retries instead of silently running on a stale plan
        with self._lock:
            rebuild = self._plan_dirty or self._plan is None
            gen = self._plan_gen
            if rebuild:
                crash = self._crash.copy()
                join = self._join.copy()
                loss = self._loss
        if rebuild:
            import jax.numpy as jnp

            from swim_tpu.sim.faults import FaultPlan

            self._plan = FaultPlan(
                crash_step=jnp.asarray(crash),
                loss=jnp.float32(loss),
                partition_id=jnp.zeros((self.n,), jnp.uint8),
                partition_start=jnp.int32(1 << 30),
                partition_end=jnp.int32(1 << 30),
                join_step=jnp.asarray(join))
            with self._lock:
                if self._plan_gen == gen:
                    self._plan_dirty = False
        return self._plan

    # -------------------------------------------------------- inbound seam

    def _queue_injections(self, hearer: int,
                          gossip: tuple[codec.WireUpdate, ...]) -> None:
        for u in gossip:
            if not 0 <= u.member < self.n:
                continue
            key = _pack_key(u.status, u.incarnation)
            if key <= self._best_key(u.member):
                continue                 # stale vs table/tombstone (D2)
            org = u.origin if 0 <= u.origin < self.n else hearer
            with self._lock:
                self._inject.append((u.member, key, org, hearer))

    def _credit_ack(self, x: int) -> None:
        """Liveness credit for external id x (caller holds _engine)."""
        self._last_acks[x] = self.t
        self._ack_flush[x] = self._ping_flushes.get(x, 0)

    def _lost(self) -> bool:
        """Bernoulli loss draw for one bridge datagram leg (D4): the
        core's wire traffic experiences the configured loss rate like
        any engine wave (seeded host RNG — reproducible given the same
        datagram order)."""
        return self._loss > 0.0 and self._rng.random() < self._loss

    def _on_datagram(self, src: int, dst: int, payload: bytes) -> None:
        """One datagram from external node `src` (session-verified by
        the caller).  A dst owned by another LIVE session short-circuits
        over the wire — the hub path that lets two foreign cores talk to
        each other directly — after one D4 loss draw; everything else is
        the engine seam."""
        with self._lock:
            owner = self._owner.get(dst)
            owner_live = owner is not None and owner.live
        if owner_live and dst != src:
            if self._lost():
                return
            # the mirrored rotor prober of an external id can itself be
            # another external id; the probed core's ACK then rides this
            # hub path instead of the engine seam below, and must earn
            # the same liveness credit (the recipient core ignores an
            # ACK with a probe_seq it never issued).  Header-only peek:
            # the hub must not pay a full gossip parse per datagram.
            try:
                if codec.peek_kind(payload) == MsgKind.ACK:
                    self._credit_ack(src)
            except codec.DecodeError:
                pass
            with self._lock:
                owner.outq.append(bp.Frame(bp.DELIVER, a=src, b=dst,
                                           payload=payload))
            return
        try:
            msg = codec.decode(payload)
        except codec.DecodeError:
            return
        if not self._alive(dst) or self._lost():
            return     # datagram to a dead node, or lost on the wire:
            #            nothing is heard and nothing replies (D4)
        self._queue_injections(dst, msg.gossip)
        if msg.kind == MsgKind.ACK:
            # the core answered a mirrored ping: liveness credit for
            # the sending external id
            self._credit_ack(src)
        elif msg.kind == MsgKind.PING:
            if self._lost():             # ack leg draws its own loss
                return
            ack = codec.Message(kind=MsgKind.ACK, sender=dst,
                                probe_seq=msg.probe_seq,
                                on_behalf=msg.on_behalf,
                                gossip=self._transmissible(dst))
            self._deliver(src, dst, ack)
        elif msg.kind == MsgKind.PING_REQ:
            tgt = msg.target
            # proxy round-trip: two more legs (proxy->tgt, tgt->proxy)
            # plus the relay ack leg, each drawing loss
            if (self._alive(tgt) and not self._lost()
                    and not self._lost() and not self._lost()):
                ack = codec.Message(kind=MsgKind.ACK, sender=dst,
                                    probe_seq=msg.probe_seq,
                                    on_behalf=tgt,
                                    gossip=self._transmissible(tgt))
                self._deliver(src, dst, ack)
        elif msg.kind == MsgKind.JOIN:
            if self._lost():             # reply leg draws loss too (D4)
                return
            self._deliver(src, dst, codec.Message(
                kind=MsgKind.JOIN_REPLY, sender=dst,
                gossip=self._join_snapshot(exclude=src)))

    def _deliver(self, x: int, sender: int, msg: codec.Message) -> None:
        """Queue a DELIVER to external id x's owning session."""
        with self._lock:
            owner = self._owner.get(x)
            if owner is None or not owner.live:
                return
            owner.outq.append(bp.Frame(bp.DELIVER, a=sender, b=x,
                                       payload=codec.encode(msg)))

    # -------------------------------------------------------- outbound seam

    def _run_period(self) -> None:
        import time

        import jax

        from swim_tpu.models import ring

        # liveness gate: a silent core is a crashed member (per id).
        # For a gating session the grace clock ticks on ACK
        # OPPORTUNITIES — outq flushes that actually carried mirrored
        # pings — not on engine time: a healthy session cannot ack
        # pings still queued in its outq (they flush only at its own
        # STEP), so a multi-period catch-up burst by a lagging session
        # queues many pings but is exactly ONE opportunity, and cannot
        # crash-gate anyone.  A session that stopped gating
        # (disconnected, or wall-stalled per _gating_clocks) never
        # flushes again, so for it the clock falls back to engine
        # periods since its last ack — the documented organic-death
        # path for hung/departed cores.
        now = time.monotonic()
        for x in list(self._prev_rows):
            if self._ext_crashed[x]:
                continue
            with self._lock:
                owner = self._owner.get(x)
                gating = (owner is not None
                          and self._session_gates(owner, now))
            if gating:
                lag = (self._ping_flushes.get(x, 0)
                       - self._ack_flush.get(x, 0))
            else:
                lag = self.t - self._last_acks[x]
            if lag > self.ack_grace:
                self.kill(x)
                self._ext_crashed[x] = True
                # the old semantics evicted silently ("leaves the
                # barrier; its rows then miss") — surface it on the
                # health trail so /metrics and dump headers carry it
                cause = "ack-grace" if gating else "stall/disconnect"
                self.findings.append(Finding(
                    rule="session_evicted", severity="warn",
                    period=self.t, value=float(lag),
                    threshold=float(self.ack_grace),
                    message=f"external id {x} evicted ({cause}): "
                            f"{lag} periods without an ack; row "
                            "crash-gated"))
        ext = ring.ext_none(self.ext_capacity)
        with self._lock:
            batch, self._inject = (self._inject[:self.ext_capacity],
                                   self._inject[self.ext_capacity:])
        if batch:
            import jax.numpy as jnp

            ext = ring.ExtOriginations(
                subject=jnp.asarray(
                    [b[0] for b in batch]
                    + [-1] * (self.ext_capacity - len(batch)), jnp.int32),
                key=jnp.asarray(
                    [b[1] for b in batch]
                    + [0] * (self.ext_capacity - len(batch)), jnp.uint32),
                origin=jnp.asarray(
                    [b[2] for b in batch]
                    + [0] * (self.ext_capacity - len(batch)), jnp.int32),
                hearer=jnp.asarray(
                    [b[3] for b in batch]
                    + [0] * (self.ext_capacity - len(batch)), jnp.int32))
        rnd = self._ring.draw_period_ring(self._key, self.t, self.cfg)
        self.state = self._step(self.state, self._device_plan(), rnd,
                                ext=ext)
        s_off = int(jax.device_get(rnd.s_off))
        self.t += 1
        # refresh table mirrors, then mirror the rotor probe of every
        # joined external id
        self._subject = np.asarray(self.state.subject)
        self._rkey = np.asarray(self.state.rkey)
        self._gone = np.asarray(self.state.gone_key)
        for x in list(self._prev_rows):
            row = self._resolved_row(x)
            fresh = row & ~self._prev_rows[x]
            self._prev_rows[x] = row
            prober = (x - s_off) % self.n
            if not self._alive(prober):
                continue                 # no probe of x this period
            updates = self._slots_to_updates(np.nonzero(fresh)[0], prober)
            self._ping_pending[x] = True     # ack opportunity at next flush
            for chunk in range(0, max(len(updates), 1), 255):
                ping = codec.Message(
                    kind=MsgKind.PING, sender=prober, probe_seq=self.t,
                    gossip=tuple(updates[chunk:chunk + 255]))
                self._deliver(x, prober, ping)

    # ------------------------------------------------------- state decoding

    def _geom(self):
        return self._ring.geometry(self.cfg)

    def _resolved_row(self, x: int) -> np.ndarray:
        """bool[R]: node x's current heard-bits (host mirror of
        ring.resolved_words for a single node)."""
        g = self._geom()
        win_x = np.asarray(self.state.win[x])          # u32[WW]
        cold_x = np.asarray(self.state.cold[:, x])     # u32[RW]
        t = int(self.state.step)
        first_gw = t * g.ow - g.ww
        win_ring0 = first_gw % g.rw
        words = cold_x.copy()
        for w in range(g.ww):
            words[(win_ring0 + w) % g.rw] = win_x[w]
        bits = np.unpackbits(
            words.astype("<u4").view(np.uint8), bitorder="little")
        return bits.astype(bool)

    def _best_key(self, member: int) -> int:
        """The strongest table/tombstone key currently held for member
        (numpy mirrors only — this runs per gossip update on the
        datagram hot path; a device gather here would cost hundreds of
        host round-trips per datagram)."""
        mask = self._subject == member
        best = int(self._rkey[mask].max()) if mask.any() else 0
        return max(best, int(self._gone[member]))

    def _slots_to_updates(self, slots: np.ndarray,
                          origin: int) -> list[codec.WireUpdate]:
        out = []
        for sl in slots.tolist():
            subj = int(self._subject[sl])
            if subj < 0:
                continue
            key = int(self._rkey[sl])
            out.append(codec.WireUpdate(
                member=subj, status=_status_of(key), incarnation=_inc_of(key),
                addr=("sim", subj), origin=origin))
        return out

    def _transmissible(self, j: int) -> tuple[codec.WireUpdate, ...]:
        """Node j's current piggyback: up to B used slots of its window
        (host mirror of the engine's first-B window selection)."""
        g = self._geom()
        win_j = np.asarray(self.state.win[j])          # u32[WW]
        t = int(self.state.step)
        first_gw = t * g.ow - g.ww
        r_tot = g.rw * WORD
        out = []
        b = min(self.cfg.max_piggyback, g.ww * WORD)
        for w in range(g.ww - 1, -1, -1):              # newest word first
            word = int(win_j[w])
            while word and len(out) < b:
                bit = (word & -word).bit_length() - 1
                word &= word - 1
                sl = (((first_gw + w) % g.rw) * WORD + bit) % r_tot
                subj = int(self._subject[sl])
                if subj < 0:
                    continue
                key = int(self._rkey[sl])
                out.append(codec.WireUpdate(
                    member=subj, status=_status_of(key),
                    incarnation=_inc_of(key), addr=("sim", subj), origin=j))
            if len(out) >= b:
                break
        return tuple(out)

    def _join_snapshot(self, exclude: int) -> tuple[codec.WireUpdate, ...]:
        """Up to `join_sample` alive members, spread across the id space
        (the wire gossip count is u8 — a 64k snapshot cannot fit, and
        SWIM only needs a partial view to bootstrap probing).  `exclude`
        is the REQUESTING joiner (a node must not bootstrap itself);
        other external ids stay includable — they are legitimate,
        probeable members."""
        stride = max(1, self.n // self.join_sample)
        out = []
        for m in range(0, self.n, stride):
            if m != exclude and self._alive(m):
                out.append(codec.WireUpdate(
                    member=m, status=Status.ALIVE, incarnation=0,
                    addr=("sim", m), origin=m))
            if len(out) >= min(self.join_sample, 255):
                break
        return tuple(out)

    # ------------------------------------------------------------ test hooks

    def inject_update(self, subject: int, status: Status, inc: int,
                      origin: int, hearer: int) -> None:
        """Queue a rumor injection directly (bypasses the wire)."""
        with self._lock:
            self._inject.append(
                (subject, _pack_key(status, inc), origin, hearer))

    def deliver_forged(self, sender: int,
                       updates: list[codec.WireUpdate],
                       to: int | None = None) -> None:
        """DELIVER a forged gossip-bearing ping to a core WITHOUT
        touching tensor state (default target: the first external id).
        Test use: forge suspect(X) on the wire only — the engine's
        shadow row never sees a suspicion, so any alive(X, inc≥1) that
        later appears in tensor state can ONLY be the foreign core's
        refutation arriving through the injection seam (the engine-side
        proof is inc_self[X] staying 0)."""
        self._deliver(self.x if to is None else to, sender, codec.Message(
            kind=MsgKind.PING, sender=sender, probe_seq=0,
            gossip=tuple(updates)))

    def table_keys(self, subject: int) -> list[int]:
        """All live table keys about `subject` (host mirror)."""
        return [int(k) for k in self._rkey[self._subject == subject]]
