"""Bridge wire protocol: length-prefixed binary frames over TCP.

Normative spec + conformance checklist: docs/BRIDGE.md (this module is
the executable form of its §1 frame table).

This is the contract for an EXTERNAL protocol core (the reference's Haskell
`Swim.Protocol` behind a `Swim.Transport` instance — SURVEY.md §2 "Host
bridge") to participate in a swim_tpu simulated cluster. The format is
deliberately codegen-free — length-prefixed structs any language writes in
a dozen lines — because the co-process side cannot be assumed to have
protobuf/gRPC tooling (this environment has no GHC and no grpcio-tools;
SURVEY.md §7 step 6 calls for the contract to be defined by a Python mock
until the Haskell side exists).

Frame:  u32le body_length | body;   body: u8 opcode | fields (little-endian)

  opcode  dir  fields
  HELLO    c→s  u32 node_id          claim an external node id
  WELCOME  s→c  u32 node_id, f64 now
  SEND     c→s  u32 src, u32 dst, rest=payload   (opaque datagram bytes)
  STEP     c→s  f64 dt               advance virtual time (lockstep)
  DELIVER  s→c  u32 src, u32 dst, rest=payload   datagrams for bridged nodes
  TIME     s→c  f64 now              end-of-STEP marker
  KILL     c→s  u32 node_id          crash-stop any node (fault injection)
  SET_LOSS c→s  f64 loss             global Bernoulli loss
  BYE      c→s  —                    clean shutdown
  ERROR    s→c  u32 code             protocol error (ERR_*); HELLO with an
                                     already-claimed id → ERR_ID_TAKEN

Time only moves on STEP — the co-simulation is deterministic lockstep: the
server runs its in-process nodes' timers up to the new time, collects every
datagram addressed to bridged nodes, streams DELIVER frames, and finishes
the batch with TIME. Payloads are opaque bytes end-to-end (the transport
seam carries datagrams, not protocol structures); an external core that
wants to interoperate with in-process swim_tpu nodes must speak the
datagram codec in swim_tpu/core/codec.py.
"""

from __future__ import annotations

import socket
import struct
from typing import NamedTuple

(HELLO, WELCOME, SEND, STEP, DELIVER, TIME, KILL, SET_LOSS, BYE,
 ERROR) = range(1, 11)

ERR_ID_TAKEN = 1   # HELLO claimed an id that already has an endpoint

_U32 = struct.Struct("<I")
_OP_U32 = struct.Struct("<BI")
_OP_F64 = struct.Struct("<Bd")
_OP_U32_F64 = struct.Struct("<BId")
_OP_2U32 = struct.Struct("<BII")

MAX_FRAME = 1 << 20


class Frame(NamedTuple):
    op: int
    a: int = 0        # node id / src
    b: int = 0        # dst
    t: float = 0.0    # time / dt / loss
    payload: bytes = b""


def pack(f: Frame) -> bytes:
    if f.op in (HELLO, KILL, ERROR):
        body = _OP_U32.pack(f.op, f.a)
    elif f.op == WELCOME:
        body = _OP_U32_F64.pack(f.op, f.a, f.t)
    elif f.op in (SEND, DELIVER):
        body = _OP_2U32.pack(f.op, f.a, f.b) + f.payload
    elif f.op in (STEP, TIME, SET_LOSS):
        body = _OP_F64.pack(f.op, f.t)
    elif f.op == BYE:
        body = bytes([f.op])
    else:
        raise ValueError(f"unknown opcode {f.op}")
    return _U32.pack(len(body)) + body


def unpack(body: bytes) -> Frame:
    op = body[0]
    if op in (HELLO, KILL, ERROR):
        return Frame(op, a=_OP_U32.unpack(body)[1])
    if op == WELCOME:
        _, a, t = _OP_U32_F64.unpack(body)
        return Frame(op, a=a, t=t)
    if op in (SEND, DELIVER):
        _, a, b = _OP_2U32.unpack(body[:_OP_2U32.size])
        return Frame(op, a=a, b=b, payload=body[_OP_2U32.size:])
    if op in (STEP, TIME, SET_LOSS):
        return Frame(op, t=_OP_F64.unpack(body)[1])
    if op == BYE:
        return Frame(op)
    raise ValueError(f"unknown opcode {op}")


def read_frame(sock: socket.socket) -> Frame | None:
    """Blocking read of one frame; None on clean EOF."""
    hdr = _read_exact(sock, 4)
    if hdr is None:
        return None
    (length,) = _U32.unpack(hdr)
    if not 1 <= length <= MAX_FRAME:
        raise ValueError(f"bad frame length {length}")
    body = _read_exact(sock, length)
    if body is None:
        raise ValueError("truncated frame")
    return unpack(body)


def write_frame(sock: socket.socket, f: Frame) -> None:
    sock.sendall(pack(f))


def _read_exact(sock: socket.socket, n: int) -> bytes | None:
    """n bytes, or None on clean EOF; raises if the peer dies mid-read."""
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            if buf:
                raise ValueError("connection closed mid-frame")
            return None
        buf.extend(chunk)
    return bytes(buf)
