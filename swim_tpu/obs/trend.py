"""Bench trend engine: per-tier periods/sec trajectories + regression gate.

Jax-free (importable on any host, CI included).  Two artifact sources:

* ``BENCH_r*.json`` at the repo root — one per bench round, written by
  the external driver; ``parsed`` carries every ``<tier>_periods_per_sec``
  / ``<tier>_nodes`` pair plus the resolved ``platform``.  The round
  number in the filename gives a total order, so these are the canonical
  trajectory and the ONLY samples the regression gate judges.
* ``bench_results/bench_all*.json`` — tpu_watch captures whose
  ``result`` is bench.py's final JSON.  Ordered by ``captured_at``;
  they enrich the rendered trajectory but are advisory (no round
  number, so their position relative to rounds is ambiguous).

A series is keyed ``(tier, nodes, platform)`` — a CPU proxy number and
a TPU capture never compare, and neither do different N (the honesty
rule all RESULTS tables follow).  The ``--check`` gate fails a series
when the latest round's value drops more than ``threshold`` (default
10%) below the immediately previous (last-good) round: periods/sec
must not silently decay while feature PRs land.  run_suite.py runs the gate after artifact
capture and tpu_watch.py records its verdict next to the captures.

CLI: ``python -m swim_tpu.obs.trend [--repo DIR] [--json] [--check]``
(also surfaced as ``swim-tpu trend``).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")
_PPS_SUFFIX = "_periods_per_sec"
# Second metric family: peak memory bytes (bench.py --tier memwall).
# Same auto-registration (`<tier>_peak_bytes` + `<tier>_nodes`), but the
# gate direction INVERTS — bytes regress by RISING, p/s by dropping.
_BYTES_SUFFIX = "_peak_bytes"
# Serving-hub families (bench.py --tier serve): concurrent sessions
# sustained (regresses by dropping, like p/s) and p99 round-trip
# latency in ms (regresses by RISING, inverted like peak_bytes).
_SESSIONS_SUFFIX = "_sessions"
_P99_SUFFIX = "_p99_ms"
# Serve-path tracing (bench.py --tier servetrace): mean echo-tail ms
# NOT explained by a named _period phase.  Inverted — unexplained tail
# time regressing upward means the attribution layer is losing its
# grip on the p99, which is exactly what the gate must catch.
_UNATTR_SUFFIX = "_unattributed_ms"

DEFAULT_THRESHOLD = 0.10


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def _samples_from_parsed(parsed: dict, *, source: str, rnd: int | None,
                         captured_at: str | None) -> list[dict]:
    if not isinstance(parsed, dict):
        return []
    platform = parsed.get("platform") or parsed.get("accelerator") \
        or "unknown"
    out = []
    for key, val in parsed.items():
        if not isinstance(val, (int, float)):
            continue
        if key.endswith(_PPS_SUFFIX):
            tier, metric = key[:-len(_PPS_SUFFIX)], "pps"
        elif key.endswith(_BYTES_SUFFIX):
            tier, metric = key[:-len(_BYTES_SUFFIX)], "peak_bytes"
        elif key.endswith(_UNATTR_SUFFIX):
            tier, metric = key[:-len(_UNATTR_SUFFIX)], "unattributed_ms"
        elif key.endswith(_P99_SUFFIX):
            tier, metric = key[:-len(_P99_SUFFIX)], "p99_ms"
        elif key.endswith(_SESSIONS_SUFFIX):
            tier, metric = key[:-len(_SESSIONS_SUFFIX)], "sessions"
        else:
            continue
        nodes = parsed.get(f"{tier}_nodes")
        out.append({
            "tier": tier,
            "nodes": int(nodes) if isinstance(nodes, (int, float)) else None,
            "platform": str(platform),
            "metric": metric,
            "pps": float(val),
            "round": rnd,
            "captured_at": captured_at,
            "source": source,
        })
    return out


def collect(repo: str | None = None) -> list[dict]:
    """All trend samples from BENCH_r*.json + bench_results/bench_all*.

    Unreadable or shape-mismatched files are skipped (artifacts written
    by older rounds must never crash the gate)."""
    repo = repo or _repo_root()
    samples: list[dict] = []
    for path in sorted(glob.glob(os.path.join(repo, "BENCH_r*.json"))):
        m = _ROUND_RE.search(path)
        if not m:
            continue
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        samples.extend(_samples_from_parsed(
            doc.get("parsed", {}), source=os.path.basename(path),
            rnd=int(m.group(1)), captured_at=None))
    for path in sorted(glob.glob(
            os.path.join(repo, "bench_results", "bench_all*.json"))):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        samples.extend(_samples_from_parsed(
            doc.get("result", {}), source=os.path.basename(path),
            rnd=None, captured_at=doc.get("captured_at")))
    return samples


def series(samples: list[dict]) -> dict[tuple, list[dict]]:
    """Group by (tier, nodes, platform, metric); each series ordered
    with rounds first (numeric) then round-less captures by
    captured_at."""
    out: dict[tuple, list[dict]] = {}
    for s in samples:
        out.setdefault((s["tier"], s["nodes"], s["platform"],
                        s.get("metric", "pps")), []).append(s)
    for key in out:
        out[key].sort(key=lambda s: (
            0 if s["round"] is not None else 1,
            s["round"] if s["round"] is not None else 0,
            s["captured_at"] or ""))
    return out


def check(ser: dict[tuple, list[dict]],
          threshold: float = DEFAULT_THRESHOLD) -> list[dict]:
    """Regression gate over the round-ordered samples of each series.

    Last-good semantics (bench.py's last_good_tpu vocabulary): the
    latest round is judged against the IMMEDIATELY PREVIOUS round, and
    fails (ok=False) when it regresses more than `threshold` past it —
    a DROP for pps series, a RISE for peak_bytes series (memory
    regresses upward).  CPU proxy numbers are noisy round to round, so
    judging against the all-time best would permanently fail a series
    after one lucky round; the full trajectory stays visible in
    render() either way.  Series with fewer than two round samples pass
    vacuously."""
    findings = []
    for (tier, nodes, platform, metric), samp in sorted(
            ser.items(), key=lambda kv: str(kv[0])):
        rounds = [s for s in samp if s["round"] is not None]
        if len(rounds) < 2:
            continue
        latest, last_good = rounds[-1], rounds[-2]
        drop = 1.0 - latest["pps"] / last_good["pps"] \
            if last_good["pps"] > 0 else 0.0
        regression = -drop if metric in ("peak_bytes", "p99_ms",
                                         "unattributed_ms") else drop
        findings.append({
            "tier": tier, "nodes": nodes, "platform": platform,
            "metric": metric,
            "latest_round": latest["round"], "latest_pps": latest["pps"],
            "last_good_round": last_good["round"],
            "last_good_pps": last_good["pps"],
            "drop_pct": round(drop * 100.0, 2),
            "threshold_pct": round(threshold * 100.0, 2),
            "ok": regression <= threshold,
        })
    return findings


def summarize(repo: str | None = None,
              threshold: float = DEFAULT_THRESHOLD) -> dict:
    ser = series(collect(repo))
    findings = check(ser, threshold)
    return {
        "series": {
            f"{tier}@{nodes}/{platform}"
            + ("" if metric == "pps" else f" [{metric}]"): [
                {"round": s["round"], "captured_at": s["captured_at"],
                 "pps": s["pps"], "source": s["source"]}
                for s in samp]
            for (tier, nodes, platform, metric), samp in sorted(
                ser.items(), key=lambda kv: str(kv[0]))
        },
        "checks": findings,
        "ok": all(f["ok"] for f in findings),
    }


def render(summary: dict) -> str:
    lines = ["bench trend (periods/sec by tier@nodes/platform)", ""]
    for name, samp in summary["series"].items():
        traj = " -> ".join(
            f"{s['pps']:g}" + (f" (r{s['round']})" if s["round"] is not None
                               else " (capture)")
            for s in samp)
        lines.append(f"  {name}: {traj}")
    lines.append("")
    if not summary["checks"]:
        lines.append("gate: no series with >= 2 rounds; nothing to check")
    for f in summary["checks"]:
        tag = "ok  " if f["ok"] else "FAIL"
        metric = f.get("metric", "pps")
        name = f"{f['tier']}@{f['nodes']}/{f['platform']}" \
            + ("" if metric == "pps" else f" [{metric}]")
        lines.append(
            f"  [{tag}] {name}: "
            f"r{f['latest_round']} {f['latest_pps']:g} vs last-good "
            f"r{f['last_good_round']} {f['last_good_pps']:g} "
            f"(drop {f['drop_pct']}%, limit {f['threshold_pct']}%)")
    lines.append("")
    lines.append("gate: " + ("PASS" if summary["ok"] else "FAIL"))
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="swim-tpu trend",
        description="per-tier bench trajectories + regression gate")
    ap.add_argument("--repo", default=None,
                    help="repo root (default: auto-detect)")
    ap.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                    help="max allowed fractional drop vs the last-good "
                         "round (default 0.10)")
    ap.add_argument("--json", action="store_true",
                    help="emit the summary as JSON")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 when any series regresses past the "
                         "threshold")
    args = ap.parse_args(argv)
    summary = summarize(args.repo, args.threshold)
    if args.json:
        print(json.dumps(summary, indent=1))
    else:
        print(render(summary))
    if args.check and not summary["ok"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
