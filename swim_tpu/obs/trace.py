"""Structured protocol/serve-path tracing: spans + pluggable sinks.

A `Span` is one traced episode: a probe round (direct ping → indirect
ping-req fan-out → ack/nack → verdict), a suspicion (start →
independent confirmations → refute/confirm), or — the serving hub's
datagram lifecycle (obs/servetrace.py) — one datagram from frontend
receipt through the work queue or the device-mirror flush to its
reply.  Emitters push spans through a pluggable `TraceSink`; the
default is no sink at all (a `None` check on the hot path — zero
allocation when tracing is off).

Span schema (the JSONL shape written by `JsonlSink`):

  {"kind": "probe" | "suspicion" | "serve",
   "node": <observer id>, "subject": <member id>,
   "start": <clock seconds>, "end": <clock seconds>,
   "outcome": probe: "ack" | "fail";
              suspicion: "confirmed" | "refuted" | "superseded";
              serve: "echo_reply" | "gossip_flushed" | "deliver" |
                     "ack" | "admit" | "leave" | "rejected_queue",
   "events": [[<clock seconds>, <name>], ...]}

Event names: probe spans use "ping", "ping-req", "ack", "nack";
suspicion spans use "confirm" (one per independent suspector beyond
the originator).  Serve spans (node = session row, -1 pre-admission;
subject = wire opcode) use "queued" (bounded work-queue put), "handled"
(worker dequeue — queue wait is handled minus queued), "flush" (the
device-mirror period that carried a gossip update — coalesce-batching
delay), and "send" (DELIVER/ECHO reply handed to the frontend).
"""

from __future__ import annotations

import dataclasses
import json
from typing import IO, Protocol


@dataclasses.dataclass
class Span:
    kind: str                 # "probe" | "suspicion" | "serve"
    node: int
    subject: int
    start: float
    end: float | None = None
    outcome: str | None = None
    events: list[tuple[float, str]] = dataclasses.field(default_factory=list)

    def event(self, t: float, name: str) -> None:
        self.events.append((t, name))

    def finish(self, t: float, outcome: str) -> "Span":
        self.end = t
        self.outcome = outcome
        return self

    def to_dict(self) -> dict:
        return {"kind": self.kind, "node": self.node,
                "subject": self.subject, "start": self.start,
                "end": self.end, "outcome": self.outcome,
                "events": [[t, name] for t, name in self.events]}


class TraceSink(Protocol):
    def emit(self, span: Span) -> None: ...


class NullSink:
    """Swallows spans (explicit off; nodes also accept trace=None)."""

    def emit(self, span: Span) -> None:
        pass


class ListSink:
    """Collects spans in memory — tests and notebook inspection."""

    def __init__(self):
        self.spans: list[Span] = []

    def emit(self, span: Span) -> None:
        self.spans.append(span)


class JsonlSink:
    """Writes one JSON object per finished span to a file or stream."""

    def __init__(self, target: str | IO[str]):
        if isinstance(target, str):
            self._file: IO[str] = open(target, "a")
            self._owns = True
        else:
            self._file = target
            self._owns = False

    def emit(self, span: Span) -> None:
        self._file.write(json.dumps(span.to_dict()) + "\n")

    def close(self) -> None:
        self._file.flush()
        if self._owns:
            self._file.close()
