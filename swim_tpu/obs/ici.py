"""Per-collective ICI byte accounting for the sharded ring engine.

Promoted from scripts/shard_anchor.py (which now imports this) into the
runtime telemetry layer: `trace_ici_bytes(cfg, d)` tallies, during one
abstract (`jax.eval_shape`) trace of the real `ring.step` body, exactly
the bytes the ShardOps layout would move per chip per period for
`cfg.ring_ici_wire` — the dense "window" wire (2 u32[S, WW] neighbor
blocks per wave roll) or the "compact" wire (the first-B piggyback
packed as slot indices, ops/wavepack.py: one [S, B] narrow-int block per
wave plus one shared boundary fetch per period) — plus psum payloads for
reductions/replicated gathers and the [D, kl] candidate all_gather.

Scalar rolls tally under STABLE NAMED TERMS — the engine labels every
node-vector roll at the call site (roll_probe_gate, roll_ok_waves,
roll_pid_waves, roll_link_thr, roll_buddy_slots, roll_buddy_cols,
roll_buddy_vals, roll_view_slots, roll_view_known, roll_view_verdict)
— roll_link_thr is the FaultProgram per-wave u16 link-lane
(sim/faults.py link_lanes; absent for plain FaultPlan runs, so the
baseline ICI bill is unchanged by construction) — so artifacts
compare across wire formats and dtype changes instead of keying on
shapes.  The shape/dtype-derived `roll[...]` key survives only as the
fallback for unlabeled rolls.  With `cfg.ring_scalar_wire == "packed"`
the model charges bool vectors 1 bit/node (u32 word granularity) and
narrow codes their byte width, matching ShardOps.roll_bundle's fused
u8 payload byte-for-byte.

The tally is static per (cfg, d): the wave schedule, payload shapes and
collective set are compile-time constants, so the per-period byte cost
does not vary at runtime.  The flight recorder embeds it in the dump
header so every telemetry artifact is self-describing about its wire.

Time model (kept from the anchor script, documented there in full): the
per-chip RECEIVED bytes divided by ONE link's per-direction bandwidth —
a deliberate serial-link lower bound on the ICI ceiling.
"""

from __future__ import annotations

V5E_ICI_GBPS = 45.0   # v5e ICI, per link per direction (public figure)


def trace_ici_bytes(cfg, d: int, ici_gbps: float = V5E_ICI_GBPS,
                    plan=None, ext_capacity: int | None = None) -> dict:
    """Per-chip ICI bytes/period the ShardOps layout moves for `cfg`
    sharded over `d` devices, keyed by collective (trace-derived).
    `plan` defaults to `faults.none` (the baseline bill, unchanged);
    pass a FaultProgram to price its per-wave u16 link lane — the
    `roll_link_thr` term (sim/scenario.py embeds this in verdict
    artifacts).  `ext_capacity` prices the serving hub's batched row
    mirror (swim_tpu/serve/hub.py): the coalesced ExtOriginations batch
    is ONE placed update per device step — capacity entries of
    subject/key/origin/hearer at 4 bytes each, replicated to every chip
    — tallied under the `ext_mirror_rows` term so the auditor's
    tally-completeness contract covers the hub's mirroring bytes too
    (with ext_capacity=None the bill is unchanged, like plan)."""
    import jax
    import jax.numpy as jnp

    from swim_tpu.models import ring
    from swim_tpu.ops import wavepack
    from swim_tpu.sim import faults

    tally: dict[str, int] = {}

    def add(key, nbytes):
        tally[key] = tally.get(key, 0) + int(nbytes)

    class CountingOps(ring.GlobalOps):
        def __init__(self, cfg, d):
            super().__init__(cfg)
            self.cfg = cfg
            self.d = d

        def _roll_part_bytes(self, x):
            """Bytes ONE neighbor-block transfer of x costs per chip:
            rows-per-shard lanes at the wire dtype — except a bool node
            vector on the packed scalar wire, which ships 1 bit/node
            (u32 words, ops/wavepack.py pack_bits)."""
            s = x.shape[0] // self.d
            if (self.cfg.ring_scalar_wire == "packed" and x.ndim == 1
                    and x.dtype == jnp.bool_):
                return 4 * wavepack.packed_words(s)
            return s * (x.size // x.shape[0]) * x.dtype.itemsize

        def _roll_key(self, x, label):
            return (label if label is not None else
                    f"roll[{'x'.join(map(str, x.shape))},{x.dtype}]")

        def roll_from(self, x, dd, label=None):
            add(self._roll_key(x, label), 2 * self._roll_part_bytes(x))
            return super().roll_from(x, dd)

        def roll_bundle(self, parts, dd, labels=None):
            # The packed wire fuses all parts into one ppermute pair,
            # but the per-part packed bytes sum exactly to the fused
            # payload (pack_bundle concatenates byte views), so the
            # tally stays per named term with no fusion residue.
            if labels is None:
                labels = [None] * len(parts)
            for x, lb in zip(parts, labels):
                add(self._roll_key(x, lb), 2 * self._roll_part_bytes(x))
            return super().roll_bundle(parts, dd, labels)

        def merge_waves(self, win, sel, oks, offs, bcols, bvals, impl):
            if self.cfg.ring_ici_wire == "compact":
                ww = sel.shape[1]
                row = (min(self.cfg.max_piggyback, ww * wavepack.WORD)
                       * wavepack.packed_itemsize(ww))
                add("sel_wire_boundary", sel.shape[0] * row // self.d)
                add("roll_sel_waves",
                    len(oks) * sel.shape[0] * row // self.d)
            else:
                add("roll_sel_waves",
                    len(oks) * 2 * sel.size * sel.dtype.itemsize
                    // self.d)
            return super().merge_waves(win, sel, oks, offs, bcols,
                                       bvals, impl="lax")

        def gsum(self, partial):
            add("psum_scalar",
                4 * getattr(partial, "size", 1))
            return super().gsum(partial)

        def gather(self, arr, idx):
            add("gather_psum", 4 * max(getattr(idx, "size", 1), 1))
            return super().gather(arr, idx)

        def knows_words(self, win, cold, slot_pos, rows, slot):
            add("knows_psum", 4 * max(getattr(slot, "size", 1), 1))
            return super().knows_words(win, cold, slot_pos, rows, slot)

        def first_true_nodes(self, valid, k):
            kl = min(k, self.n // self.d)
            add("candidates_all_gather", 4 * self.d * kl)
            return super().first_true_nodes(valid, k)

    ops_c = CountingOps(cfg, d)

    def one_period():
        st = ring.init_state(cfg)
        pl = plan if plan is not None else faults.none(cfg.n_nodes)
        rnd = ring.draw_period_ring(jax.random.key(0), jnp.int32(0), cfg)
        ext = (None if ext_capacity is None
               else ring.ext_none(ext_capacity))
        return ring.step(cfg, st, pl, rnd, ops=ops_c, ext=ext)

    jax.eval_shape(one_period)
    if ext_capacity is not None:
        # The hub's batched row mirror: one placed ExtOriginations per
        # device step (4 i32/u32 lanes x capacity), replicated to every
        # chip — a host->ICI placed update, not a traced collective, so
        # it is priced here rather than inside CountingOps.
        add("ext_mirror_rows", 4 * 4 * ext_capacity)
    total = sum(tally.values())
    t_ici_ms = total / (ici_gbps * 1e9) * 1e3
    return {"per_chip_bytes_per_period": total,
            "t_ici_ms": t_ici_ms,
            "ici_ceiling_pps": round(1e3 / t_ici_ms, 1),
            "breakdown": dict(sorted(tally.items(),
                                     key=lambda kv: -kv[1]))}
