"""Bounded flight recorder for engine telemetry frames.

Keeps the last K periods of `EngineFrame` counters in a host-side ring
buffer and serializes them as JSONL on anomaly or on demand.  The dump
is self-describing: line 1 is a header object (schema version, dump
reason, frame field names, config snapshot, optional per-collective ICI
byte tally from obs/ici.py), every following line is one period's frame.

`FlightRecorder.load` round-trips a dump back into a NamedTuple of
arrays shaped like the engines' stacked frames, so
`swim_tpu.utils.metrics.series_digest` works on re-read artifacts
exactly as it does on live ones (tests/test_telemetry.py pins the
round trip).
"""

from __future__ import annotations

import collections
import dataclasses
import json
from collections import namedtuple
from typing import Any

import numpy as np

from swim_tpu.obs.engine import EngineFrame

KIND = "swim_tpu_flight_recorder"
VERSION = 1


class FlightRecorder:
    """Host-side ring buffer of the last `capacity` telemetry frames."""

    def __init__(self, cfg: Any = None, capacity: int = 64,
                 ici_bytes: dict | None = None):
        if capacity < 1:
            raise ValueError("flight recorder needs capacity >= 1")
        self.capacity = capacity
        self.cfg = cfg
        self.ici_bytes = ici_bytes
        self._frames: collections.deque[dict] = collections.deque(
            maxlen=capacity)

    def __len__(self) -> int:
        return len(self._frames)

    def record(self, period: int, frame: Any) -> None:
        """Append one period.  `frame` is an EngineFrame of scalars or any
        mapping/NamedTuple with (a subset of) its fields."""
        if hasattr(frame, "_asdict"):
            frame = frame._asdict()
        row = {"period": int(period)}
        for name in EngineFrame._fields:
            row[name] = int(frame.get(name, 0))
        self._frames.append(row)

    def record_stacked(self, frames: Any, start_period: int = 0) -> None:
        """Feed a stacked EngineFrame (arrays of shape [T]) period by
        period — the shape the engines' scans emit."""
        cols = {name: np.asarray(getattr(frames, name))
                for name in EngineFrame._fields}
        t_len = len(next(iter(cols.values())))
        for t in range(t_len):
            self.record(start_period + t,
                        {name: cols[name][t] for name in cols})

    def dump(self, path: str, reason: str = "on_demand") -> str:
        """Write the buffer as JSONL (header line + one line/period)."""
        header = {
            "kind": KIND,
            "version": VERSION,
            "reason": reason,
            "fields": list(EngineFrame._fields),
            "capacity": self.capacity,
            "periods": len(self._frames),
        }
        if self.cfg is not None:
            header["cfg"] = dataclasses.asdict(self.cfg)
        if self.ici_bytes is not None:
            header["ici_bytes"] = self.ici_bytes
        with open(path, "w") as f:
            f.write(json.dumps(header) + "\n")
            for row in self._frames:
                f.write(json.dumps(row) + "\n")
        return path

    @staticmethod
    def load(path: str) -> tuple[dict, Any]:
        """Re-read a dump: (header, frames) where `frames` is a NamedTuple
        of i64 arrays ([T] per field, plus `period`) digestible by
        `metrics.series_digest`."""
        with open(path) as f:
            lines = [json.loads(line) for line in f if line.strip()]
        if not lines or lines[0].get("kind") != KIND:
            raise ValueError(f"{path} is not a {KIND} dump")
        header, rows = lines[0], lines[1:]
        fields = ["period"] + list(header["fields"])
        Frames = namedtuple("RecordedFrames", fields)
        return header, Frames(*(
            np.asarray([row.get(name, 0) for row in rows], np.int64)
            for name in fields))
