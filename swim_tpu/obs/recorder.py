"""Bounded flight recorder for engine telemetry frames.

Keeps the last K periods of `EngineFrame` counters in a host-side ring
buffer and serializes them as JSONL on anomaly or on demand.  The dump
is self-describing: line 1 is a header object (schema version, dump
reason, frame field names, config snapshot, optional per-collective ICI
byte tally from obs/ici.py, optional embedded study milestones and
health findings), every following line is one period's frame.

`FlightRecorder.load` round-trips a dump back into a NamedTuple of
arrays shaped like the engines' stacked frames, so
`swim_tpu.utils.metrics.series_digest` works on re-read artifacts
exactly as it does on live ones (tests/test_telemetry.py pins the
round trip), and `swim_tpu.obs.analyze` recomputes the paper metrics
from the dump alone.

Health wiring: construct with `monitor=HealthMonitor(...)` and every
recorded row streams through the rules engine; `auto_dump_reason()`
surfaces any error-severity finding as a `"health:<rule>"` dump reason
and `dump` embeds the findings in the header (previously only
`false_dead_views > 0` could trigger an auto-dump).
"""

from __future__ import annotations

import collections
import dataclasses
import json
from collections import namedtuple
from typing import Any

import numpy as np

from swim_tpu.obs.engine import EngineFrame
from swim_tpu.obs.health import HealthMonitor

KIND = "swim_tpu_flight_recorder"
VERSION = 1


def write_jsonl(path: str, header: dict, rows: Any) -> str:
    """The repo's self-describing JSONL dump convention: line 1 is a
    header object (kind/version/...), every following line one row.
    Shared by `FlightRecorder.dump` and the serve-path tracer's frame
    dump (obs/servetrace.py) so every dump sniffs the same way."""
    with open(path, "w") as f:
        f.write(json.dumps(header) + "\n")
        for row in rows:
            f.write(json.dumps(row) + "\n")
    return path

# Host-side per-period counters the study runners produce NEXT TO the
# engine tap (sim/runner.py PeriodSeries) that are worth recording in
# the same row — accepted by `record`, round-tripped through dumps, and
# visible to the health monitor's rules.  `gray_nodes` / `flap_active`
# are fault-schedule gauges the scenario runner (sim/scenario.py)
# recomputes from the compiled FaultProgram, feeding the
# gray_undetected / flap_false_dead health rules.
AUX_FIELDS = ("false_dead_views", "gray_nodes", "flap_active")


class FlightRecorder:
    """Host-side ring buffer of the last `capacity` telemetry frames."""

    def __init__(self, cfg: Any = None, capacity: int = 64,
                 ici_bytes: dict | None = None,
                 monitor: HealthMonitor | None = None):
        if capacity < 1:
            raise ValueError("flight recorder needs capacity >= 1")
        self.capacity = capacity
        self.cfg = cfg
        self.ici_bytes = ici_bytes
        self.monitor = monitor
        self._frames: collections.deque[dict] = collections.deque(
            maxlen=capacity)
        self._aux_seen: set[str] = set()

    def __len__(self) -> int:
        return len(self._frames)

    def record(self, period: int, frame: Any) -> None:
        """Append one period.  `frame` is an EngineFrame of scalars or any
        mapping/NamedTuple with (a subset of) its fields, plus optional
        AUX_FIELDS.  Missing fields zero-fill (documented: a partial tap
        is a valid frame); an UNKNOWN key raises KeyError — the same
        typo guard as the registry's undeclared-counter contract."""
        if hasattr(frame, "_asdict"):
            frame = frame._asdict()
        unknown = set(frame) - set(EngineFrame._fields) - set(AUX_FIELDS)
        if unknown:
            raise KeyError(
                f"unknown telemetry field(s) {sorted(unknown)} — frames "
                "carry EngineFrame fields "
                f"{list(EngineFrame._fields)} plus aux {list(AUX_FIELDS)} "
                "(swim_tpu/obs/engine.py; a typo here would otherwise "
                "silently record zeros)")
        row = {"period": int(period)}
        for name in EngineFrame._fields:
            row[name] = int(frame.get(name, 0))
        for name in AUX_FIELDS:
            if name in frame:
                row[name] = int(frame[name])
                self._aux_seen.add(name)
        self._frames.append(row)
        if self.monitor is not None:
            self.monitor.observe(int(period), row)

    def record_stacked(self, frames: Any, start_period: int = 0,
                       aux: dict[str, Any] | None = None) -> None:
        """Feed a stacked EngineFrame (arrays of shape [T]) period by
        period — the shape the engines' scans emit.  `aux` optionally
        carries [T] arrays of AUX_FIELDS (e.g. the study runners'
        false_dead_views series) merged into the same rows."""
        cols = {name: np.asarray(getattr(frames, name))
                for name in EngineFrame._fields}
        for name, arr in (aux or {}).items():
            cols[name] = np.asarray(arr)
        t_len = len(next(iter(cols.values())))
        for t in range(t_len):
            self.record(start_period + t,
                        {name: cols[name][t] for name in cols})

    def auto_dump_reason(self) -> str | None:
        """`"health:<rule>"` when the attached monitor holds an
        error-severity finding, else None."""
        if self.monitor is None:
            return None
        return self.monitor.auto_dump_reason()

    def dump(self, path: str, reason: str = "on_demand",
             extra: dict | None = None) -> str:
        """Write the buffer as JSONL (header line + one line/period).
        `extra` merges additional self-describing sections into the
        header (e.g. the detection study's milestone arrays); core keys
        win on collision."""
        header = dict(extra or {})
        header.update({
            "kind": KIND,
            "version": VERSION,
            "reason": reason,
            "fields": list(EngineFrame._fields) + sorted(self._aux_seen),
            "capacity": self.capacity,
            "periods": len(self._frames),
        })
        if self.cfg is not None:
            header["cfg"] = dataclasses.asdict(self.cfg)
        if self.ici_bytes is not None:
            header["ici_bytes"] = self.ici_bytes
        if self.monitor is not None:
            header["health"] = self.monitor.summary()
        return write_jsonl(path, header, self._frames)

    @staticmethod
    def load(path: str) -> tuple[dict, Any]:
        """Re-read a dump: (header, frames) where `frames` is a NamedTuple
        of i64 arrays ([T] per field, plus `period`) digestible by
        `metrics.series_digest`."""
        with open(path) as f:
            lines = [json.loads(line) for line in f if line.strip()]
        if not lines or lines[0].get("kind") != KIND:
            raise ValueError(f"{path} is not a {KIND} dump")
        header, rows = lines[0], lines[1:]
        fields = ["period"] + list(header["fields"])
        Frames = namedtuple("RecordedFrames", fields)
        return header, Frames(*(
            np.asarray([row.get(name, 0) for row in rows], np.int64)
            for name in fields))
