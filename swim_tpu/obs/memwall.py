"""Memory-wall accounting: AOT `memory_analysis` of the study pipeline.

The 16M detection study died on its *own temporaries*, 622M over the
15.75G one-chip HBM budget (bench_results/study_detection_16m_oom.json),
while the plain 16M bench row fit — the wall was the study runner, not
the engine. This module makes that budget a measured, regression-gated
number that needs no hardware: `jax.jit(...).lower(shapes).compile()
.memory_analysis()` returns XLA's buffer-assignment totals (argument /
output / temp / alias bytes) for the exact program the study would run,
against nothing but ShapeDtypeStructs.

Two compile targets:

  * platform="cpu" — the host backend. Always available, but XLA:CPU
    materializes a full second copy of the engine state inside the step
    (no in-place update of the big heard-bit planes), so its totals
    overstate the device peak by ~1× state.
  * platform="tpu" — DEVICELESS XLA:TPU via
    `jax.experimental.topologies.get_topology_desc` (libtpu compiles
    without hardware). This is the same compiler whose compile-time HBM
    check produced the committed OOM artifact, so its verdict — either
    buffer totals under budget or a compile-time OOM error — is the
    one-chip claim itself, reproducible on any CPU host. A program
    replicated over the topology reports per-device bytes, i.e. the
    single-chip footprint.

`engine="ringshard"` additionally lowers the study against the sharded
ring's placement specs (parallel/ring_shard._state_specs) over the
topology mesh — the per-chip accounting of the 64M+ flagship.

Exposed as `bench.py --tier memwall` (committed artifact + trend-gated
peak bytes) and `swim-tpu study --mem-report` (ad-hoc, any shape).
Import-time jax-free like the other obs modules; jax loads on use.
"""

from __future__ import annotations

import os
from typing import Any

# One v5e chip's usable HBM — the denominator the 16M OOM was measured
# against ("16.36G of 15.75G hbm", study_detection_16m_oom.json).
HBM_BUDGET_BYTES = int(15.75 * 2**30)

DEFAULT_TOPOLOGY = "v5e:2x4"

# Prometheus gauge registry for the exposition side (obs/expo.py
# render_memwall). scripts/check_metrics_registry.py lints the two
# against each other the same way it does swim_prof_*.
MEM_GAUGES = {
    "swim_mem_argument_bytes": "XLA argument buffer bytes (engine state + "
                               "plan + milestone carry) of the study step",
    "swim_mem_output_bytes": "XLA output buffer bytes of the study step",
    "swim_mem_temp_bytes": "XLA temporary buffer bytes of the study step",
    "swim_mem_alias_bytes": "bytes aliased by donation (input buffers "
                            "reused as outputs)",
    "swim_mem_total_bytes": "peak accounted bytes per device: argument + "
                            "output + temp - alias",
    "swim_mem_state_bytes": "engine-state bytes alone (the sharded term "
                            "of the flagship budget)",
    "swim_mem_hbm_budget_bytes": "one-chip HBM budget the verdict is "
                                 "measured against",
    "swim_mem_fits_budget": "1 when total fits the one-chip budget, "
                            "else 0",
}


def _tree_bytes(shapes: Any) -> int:
    import jax

    return sum(int(x.size) * x.dtype.itemsize
               for x in jax.tree.leaves(shapes))


def _tpu_topology_mesh(topology: str):
    """Deviceless TPU mesh over a topology descriptor. libtpu insists on
    probing GCP instance metadata unless told not to — pin the env so
    this works on any laptop/CI host (no-ops on a real TPU VM where the
    vars are already set)."""
    import numpy as np
    import jax
    from jax.experimental import topologies

    os.environ.setdefault("TPU_SKIP_MDS_QUERY", "true")
    os.environ.setdefault("TPU_ACCELERATOR_TYPE", "v5litepod-8")
    os.environ.setdefault("TPU_WORKER_ID", "0")
    os.environ.setdefault("TPU_WORKER_HOSTNAMES", "localhost")
    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name=topology)
    from swim_tpu.parallel import mesh as pmesh

    return jax.sharding.Mesh(np.array(topo.devices), (pmesh.NODE_AXIS,))


def _oom_details(err: str) -> dict:
    """Fold a compile-time HBM OOM into report fields (the TPU compiler
    rejects over-budget programs at compile time — that rejection IS the
    measurement, same shape as the committed 16M OOM artifact)."""
    return {
        "compile_oom": True,
        "fits_budget": False,
        "error": " ".join(err.split())[:600],
    }


def study_memory_analysis(n: int, periods: int = 12,
                          crash_fraction: float = 1e-5, *,
                          variant: str = "stream", engine: str = "ring",
                          platform: str = "cpu",
                          topology: str = DEFAULT_TOPOLOGY,
                          probe: str = "pull",
                          budget_bytes: int = HBM_BUDGET_BYTES,
                          **cfg_kw) -> dict:
    """AOT memory accounting of one detection-study program at `n`-node
    shapes. Nothing is allocated at size N: state/plan/track enter as
    ShapeDtypeStructs and only the compiled executable's buffer
    assignment is read back.

    `variant` picks the program: "stream" is the O(crashes) chunked
    study step (runner._run_study_ring_chunk, state AND track donated);
    "stacked" is the full-track run_study_ring — the pre-streaming
    baseline, kept lowerable so the before/after contrast stays
    measurable at any shape. `engine="ringshard"` (tpu only) lowers
    against the sharded placement specs, reporting per-chip bytes."""
    import jax

    from swim_tpu import SwimConfig
    from swim_tpu.models import ring
    from swim_tpu.sim import faults, runner

    if variant not in ("stream", "stacked"):
        raise ValueError(f"unknown memwall variant {variant!r}")
    if engine not in ("ring", "ringshard"):
        raise ValueError(f"unknown memwall engine {engine!r}")
    if platform not in ("cpu", "tpu"):
        raise ValueError(f"unknown memwall platform {platform!r}")
    if engine == "ringshard" and (platform != "tpu" or variant != "stream"):
        raise ValueError("ringshard memory analysis needs platform='tpu' "
                         "and variant='stream' (the flagship program)")
    cfg_kw.setdefault("ring_probe", probe)
    cfg = SwimConfig(n_nodes=n, **cfg_kw)
    state_sd = jax.eval_shape(lambda: ring.init_state(cfg))
    plan_sd = jax.eval_shape(lambda: faults.none(n))
    key_sd = jax.eval_shape(lambda: jax.random.key(0))
    crashes = max(1, round(n * crash_fraction))
    i32 = jax.ShapeDtypeStruct((crashes,), "int32")
    track_sd = runner.CompactTrack(i32, i32, i32, i32, i32)
    carry_sd = (state_sd, track_sd) if variant == "stream" else state_sd

    report = {
        "n": int(n),
        "periods": int(periods),
        "crashes": int(crashes),
        "variant": variant,
        "engine": engine,
        "platform": platform,
        "ring_probe": cfg.ring_probe,
        "state_bytes": _tree_bytes(state_sd),
        "carry_bytes": _tree_bytes(carry_sd),
        "hbm_budget_bytes": int(budget_bytes),
    }

    step_fn = None
    if platform == "tpu":
        mesh = _tpu_topology_mesh(topology)
        report["topology"] = topology
        report["devices"] = len(mesh.devices.flat)
        rep = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
        if engine == "ringshard":
            from swim_tpu.parallel import ring_shard

            ring_shard._check(cfg, mesh)
            spec_of = lambda tree: jax.tree.map(  # noqa: E731
                lambda sp: jax.sharding.NamedSharding(mesh, sp), tree)
            state_sh = spec_of(ring_shard._state_specs(cfg))
            plan_sh = spec_of(ring_shard._plan_specs())
            step_fn = ring_shard.mapped_step(cfg, mesh)
        else:
            state_sh = rep
            plan_sh = rep
        in_sh = ((state_sh, rep, plan_sh, rep) if variant == "stream"
                 else (state_sh, plan_sh, rep))
        if variant == "stream":
            fn = jax.jit(runner._run_study_ring_chunk.__wrapped__,
                         static_argnums=(0, 5, 6), donate_argnums=(1, 2),
                         in_shardings=in_sh)
            args = (cfg, state_sd, track_sd, plan_sd, key_sd, periods,
                    step_fn)
        else:
            fn = jax.jit(runner.run_study_ring.__wrapped__,
                         static_argnums=(0, 4, 5), donate_argnums=(1,),
                         in_shardings=in_sh)
            args = (cfg, state_sd, plan_sd, key_sd, periods, None)
    else:
        if variant == "stream":
            fn = runner._run_study_ring_chunk
            args = (cfg, state_sd, track_sd, plan_sd, key_sd, periods, None)
        else:
            fn = runner.run_study_ring
            args = (cfg, state_sd, plan_sd, key_sd, periods, None)

    try:
        ma = fn.lower(*args).compile().memory_analysis()
    except Exception as e:  # compile-time HBM OOM is a result, not a crash
        msg = str(e)
        if "hbm" in msg.lower() or "RESOURCE_EXHAUSTED" in msg:
            report.update(_oom_details(msg))
            return report
        raise
    arg = int(ma.argument_size_in_bytes)
    out = int(ma.output_size_in_bytes)
    temp = int(ma.temp_size_in_bytes)
    alias = int(ma.alias_size_in_bytes)
    total = arg + out + temp - alias
    report.update({
        "compile_oom": False,
        "argument_bytes": arg,
        "output_bytes": out,
        "temp_bytes": temp,
        "alias_bytes": alias,
        "generated_code_bytes": int(ma.generated_code_size_in_bytes),
        "total_bytes": total,
        "budget_fraction": total / budget_bytes,
        "fits_budget": bool(total <= budget_bytes),
    })
    return report


def gauge_values(report: dict) -> dict[str, float]:
    """MEM_GAUGES name → value for one report (exposition + lint glue)."""
    return {
        "swim_mem_argument_bytes": float(report.get("argument_bytes", 0)),
        "swim_mem_output_bytes": float(report.get("output_bytes", 0)),
        "swim_mem_temp_bytes": float(report.get("temp_bytes", 0)),
        "swim_mem_alias_bytes": float(report.get("alias_bytes", 0)),
        "swim_mem_total_bytes": float(report.get("total_bytes", 0)),
        "swim_mem_state_bytes": float(report["state_bytes"]),
        "swim_mem_hbm_budget_bytes": float(report["hbm_budget_bytes"]),
        "swim_mem_fits_budget": 1.0 if report.get("fits_budget") else 0.0,
    }
