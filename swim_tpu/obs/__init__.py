"""Unified telemetry layer (engines + real nodes).

Two stacks, one subsystem:

* **On-device engine telemetry** — `SwimConfig.telemetry` gates a
  per-period `EngineFrame` of counters collected *inside* the engines'
  scan (piggyback-slot saturation vs the B budget, sel-window
  occupancy, wave-merge deliveries, probe failures, overflow).  The tap
  is purely additive: protocol state with telemetry on is bitwise
  identical to telemetry off (tests/test_ring_shard.py pins it across
  the sharded tri-run), and the measured overhead contract lives in
  `bench.py --telemetry-overhead`.  A bounded `FlightRecorder` keeps the
  last K frames and dumps JSONL on anomaly or on demand; `trace_ici_bytes`
  promotes scripts/shard_anchor.py's per-collective ICI tally into the
  runtime.

* **Real-node structured tracing** — `TraceSink` receives
  probe-lifecycle `Span`s from core/node.py, `MetricsRegistry` is the
  typed counter/histogram registry behind the nodes' `stats` mapping,
  and `render_prometheus` is the text exposition served by the bridge
  server's `/metrics` endpoint.

See docs/OBSERVABILITY.md for knobs, schemas, and semantics.
"""

from swim_tpu.obs.engine import (EngineFrame, RecordedRun, empty_frame,
                                 frame_from_tap, recorded_ring_run)
from swim_tpu.obs.ici import trace_ici_bytes
from swim_tpu.obs.recorder import FlightRecorder
from swim_tpu.obs.registry import (NODE_COUNTERS, NODE_HISTOGRAMS, Counter,
                                   Histogram, MetricsRegistry)
from swim_tpu.obs.trace import JsonlSink, ListSink, NullSink, Span, TraceSink
from swim_tpu.obs.expo import render_prometheus

__all__ = [
    "EngineFrame", "RecordedRun", "empty_frame", "frame_from_tap",
    "recorded_ring_run", "trace_ici_bytes", "FlightRecorder",
    "NODE_COUNTERS", "NODE_HISTOGRAMS", "Counter", "Histogram",
    "MetricsRegistry", "Span", "TraceSink", "NullSink", "ListSink",
    "JsonlSink", "render_prometheus",
]
