"""Unified telemetry layer (engines + real nodes).

Two stacks, one subsystem:

* **On-device engine telemetry** — `SwimConfig.telemetry` gates a
  per-period `EngineFrame` of counters collected *inside* the engines'
  scan (piggyback-slot saturation vs the B budget, sel-window
  occupancy, wave-merge deliveries, probe failures, overflow).  The tap
  is purely additive: protocol state with telemetry on is bitwise
  identical to telemetry off (tests/test_ring_shard.py pins it across
  the sharded tri-run), and the measured overhead contract lives in
  `bench.py --telemetry-overhead`.  A bounded `FlightRecorder` keeps the
  last K frames and dumps JSONL on anomaly or on demand; `trace_ici_bytes`
  promotes scripts/shard_anchor.py's per-collective ICI tally into the
  runtime.

* **Real-node structured tracing** — `TraceSink` receives
  probe-lifecycle `Span`s from core/node.py, `MetricsRegistry` is the
  typed counter/histogram registry behind the nodes' `stats` mapping,
  and `render_prometheus` is the text exposition served by the bridge
  server's `/metrics` endpoint.

* **Performance observatory** — `prof` segments each engine step into
  named phases (select / pack / ppermute / merge / commit /
  telemetry_tap) with device-synced prefix-differenced timings and
  modeled-vs-achieved HBM/ICI bytes per phase (roofline ceilings shared
  with utils/roofline.py and obs/ici.py); `trend` is the jax-free bench
  trajectory engine + `--check` regression gate over `bench_results/`
  and `BENCH_r*.json`.  `swim-tpu profile` / `swim-tpu trend` are the
  CLI faces; `render_profile` exposes the latest profile artifact as
  `swim_prof_*` gauges on the bridge `/metrics`.

* **Analysis & health** — `analyze` computes the paper's protocol
  metrics offline from recorded artifacts (detection-latency CDF vs
  the e/(e−1) law, infection-curve progress, piggyback pressure, span
  breakdowns); `HealthMonitor` is a sliding-window rules engine whose
  severity-ranked `Finding`s drive flight-recorder auto-dumps and the
  `swim_health_*` gauges (`render_health`).  `swim-tpu observe` is the
  CLI face of both.

See docs/OBSERVABILITY.md for knobs, schemas, and semantics.
"""

import importlib

# Attribute -> submodule, resolved lazily (PEP 562).  The split matters
# operationally: analyze/health/expo/registry/trace are json+numpy only,
# so `from swim_tpu.obs import analyze` in host-side tooling
# (scripts/run_suite.py artifact gating, scripts/tpu_watch.py capture
# enrichment) must not drag in jax via the engine-tap modules.
_LAZY = {
    "EngineFrame": "engine", "RecordedRun": "engine",
    "empty_frame": "engine", "frame_from_tap": "engine",
    "recorded_ring_run": "engine",
    "trace_ici_bytes": "ici",
    "FlightRecorder": "recorder",
    # prof is import-time jax-free (jax deferred to call time); the
    # PhaseProbe/profile_ring entry points do run jax when called
    "PHASES": "prof", "PROF_GAUGES": "prof", "PhaseProbe": "prof",
    "ProfiledRun": "prof", "profiled_ring_run": "prof",
    "phases_for": "prof", "profile_ring": "prof",
    "render_profile": "expo",
    "NODE_COUNTERS": "registry", "NODE_HISTOGRAMS": "registry",
    "Counter": "registry", "Histogram": "registry",
    "MetricsRegistry": "registry",
    "Span": "trace", "TraceSink": "trace", "NullSink": "trace",
    "ListSink": "trace", "JsonlSink": "trace",
    "render_prometheus": "expo", "render_health": "expo",
    "HEALTH_RULES": "health", "Finding": "health",
    "HealthMonitor": "health", "evaluate_registries": "health",
}

__all__ = sorted(_LAZY) + ["analyze", "health", "trend"]


def __getattr__(name: str):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    value = getattr(importlib.import_module(f"{__name__}.{mod}"), name)
    globals()[name] = value          # cache: resolve each name once
    return value
