"""Typed counter/histogram registry for real nodes.

Supersedes the flat `stats` dict core/node.py used to hold: every
counter a node increments is DECLARED here (name + help text), so the
exposition handler can render HELP/TYPE metadata and
scripts/check_metrics_registry.py can fail the build when a new
`self.stats[...]` key is incremented without being registered.

Compatibility: `MetricsRegistry.stats_view()` returns a MutableMapping
backed by the typed counters, so existing call sites —
`node.stats["probes"] += 1`, `utils.metrics.aggregate_nodes`,
`sum(n.stats["refutations"] ...)` — keep working unchanged, but an
UNDECLARED key now raises KeyError instead of silently minting an
untyped counter.
"""

from __future__ import annotations

from collections.abc import MutableMapping

NODE_COUNTERS: dict[str, str] = {
    "probes": "Protocol probes initiated",
    "probe_failures": "Probe rounds that ended with no direct or relayed ack",
    "suspicions": "Suspicion timers started",
    "refutations": "Self-suspicions refuted with an incarnation bump",
    "deaths_declared": "Suspicions expired into a DEAD declaration",
    "messages_in": "Datagrams received",
    "messages_out": "Datagrams sent",
    "decode_errors": "Datagrams dropped by the wire codec",
}

# Bucket upper bounds in seconds (+Inf is implicit).  Sized for the
# stock 1 s protocol period: probe RTTs land in the sub-period buckets,
# suspicion lifetimes in the multi-period tail.
DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                   5.0, 10.0, 30.0)

NODE_HISTOGRAMS: dict[str, tuple[str, tuple[float, ...]]] = {
    "probe_rtt_seconds":
        ("Round-trip time of acked direct probes", DEFAULT_BUCKETS),
    "suspicion_duration_seconds":
        ("Suspicion-timer lifetime from start to refute/confirm",
         DEFAULT_BUCKETS),
}


class Counter:
    __slots__ = ("name", "help", "value")

    def __init__(self, name: str, help_text: str):
        self.name = name
        self.help = help_text
        self.value = 0

    def inc(self, amount: int = 1) -> None:
        self.value += amount


class Histogram:
    __slots__ = ("name", "help", "buckets", "counts", "sum", "count")

    def __init__(self, name: str, help_text: str,
                 buckets: tuple[float, ...] = DEFAULT_BUCKETS):
        if tuple(buckets) != tuple(sorted(buckets)):
            raise ValueError(f"histogram {name}: buckets must be sorted")
        self.name = name
        self.help = help_text
        self.buckets = tuple(buckets)
        self.counts = [0] * (len(self.buckets) + 1)   # last = +Inf
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        self.sum += value
        self.count += 1
        for i, ub in enumerate(self.buckets):
            if value <= ub:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def cumulative(self) -> list[int]:
        """Prometheus-style cumulative bucket counts (ending at +Inf)."""
        out, acc = [], 0
        for c in self.counts:
            acc += c
            out.append(acc)
        return out


class _StatsView(MutableMapping):
    """dict-compatible facade over a registry's counters."""

    __slots__ = ("_reg",)

    def __init__(self, registry: "MetricsRegistry"):
        self._reg = registry

    def __getitem__(self, name: str) -> int:
        return self._reg.counter(name).value

    def __setitem__(self, name: str, value: int) -> None:
        self._reg.counter(name).value = int(value)

    def __delitem__(self, name: str):
        raise TypeError("registry counters cannot be deleted")

    def __iter__(self):
        return iter(self._reg.counters)

    def __len__(self) -> int:
        return len(self._reg.counters)


class MetricsRegistry:
    """Holds one process-local set of typed counters and histograms."""

    def __init__(self, counters: dict[str, str] | None = None,
                 histograms: dict[str, tuple[str, tuple[float, ...]]]
                 | None = None):
        self.counters: dict[str, Counter] = {
            name: Counter(name, help_text)
            for name, help_text in (counters or {}).items()}
        self.histograms: dict[str, Histogram] = {
            name: Histogram(name, help_text, buckets)
            for name, (help_text, buckets) in (histograms or {}).items()}

    @classmethod
    def node_default(cls) -> "MetricsRegistry":
        return cls(NODE_COUNTERS, NODE_HISTOGRAMS)

    def counter(self, name: str) -> Counter:
        try:
            return self.counters[name]
        except KeyError:
            raise KeyError(
                f"counter {name!r} is not declared in the registry — add "
                "it to swim_tpu.obs.registry.NODE_COUNTERS (see "
                "scripts/check_metrics_registry.py)") from None

    def histogram(self, name: str) -> Histogram:
        return self.histograms[name]

    def observe(self, name: str, value: float) -> None:
        self.histograms[name].observe(value)

    def stats_view(self) -> _StatsView:
        return _StatsView(self)
