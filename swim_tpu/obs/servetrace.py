"""Serve-path tracing: phase timelines + datagram spans for the hub.

The serving hub's committed headline carries a 30x echo-RTT tail with
zero attribution — nothing times the five phases of `ServeHub._period`
and nothing follows a datagram through the bounded work queue.  This
module is the missing layer, config-gated exactly like the node
tracers (`ServeHub(trace=...)`, default off — a `None` check on the
hot path, zero allocation when tracing is off):

  PHASE TIMELINE.  `ServeTrace.begin/lap/end` bracket one `_period()`
    call into the five named PHASES (contiguous laps, so the phases
    tile the period wall by construction).  Each period lands as one
    frame — absolute `[name, t_begin, t_end]` intervals on the shared
    monotonic clock — in a bounded ring, plus running log-bucketed
    per-phase histograms that survive ring eviction.
  DATAGRAM SPANS.  `datagram_span` mints obs/trace.py Spans of the new
    `"serve"` kind (node = session row, subject = wire opcode).  The
    hub marks "queued" at work-queue put, "handled" at worker dequeue,
    "flush" at the device-mirror period that carries a gossip update,
    and "send" at DELIVER/ECHO reply — so work-queue wait and
    coalesce-batching delay are separated from device time.  Finished
    spans collect in a bounded ring and optionally forward to any
    `TraceSink` (JsonlSink dumps feed `swim-tpu observe`).
  ATTRIBUTION INPUT.  `frames()` + the load harness's client-side echo
    windows (same CLOCK — time.monotonic at both ends of the loopback)
    are what `obs/analyze.py:summarize_serve` overlaps to decompose
    the measured echo-RTT tail into per-phase milliseconds.

Everything here is jax-free and thread-compatible: the engine thread
owns begin/lap/end, frontend/worker threads append finished spans
(atomic deque ops).  Tracing only reads clocks and appends to
host-side buffers — it never touches the rng, the plan, or the
injection order, which is why traced-vs-untraced engine state stays
sha256-bitwise identical (tests/test_servetrace.py pins it) and why
the `bench.py --tier servetrace` overhead contract is <=5%.
"""

from __future__ import annotations

import bisect
import collections
import time
from typing import Any

from swim_tpu.obs.trace import Span, TraceSink

# The five phases of ServeHub._period, in execution order.  Laps are
# contiguous (each phase ends where the next begins), so per-frame
# coverage of the period wall is total by construction; analyze.py's
# >=90% contract guards the echo-RTT attribution, not this tiling.
PHASES = (
    "evict_scan",        # stale-session scan + evict enqueue
    "inject_coalesce",   # gossip batch slice + np build + device_put
    "engine_step",       # rnd draw + jitted step (device-synced edge)
    "s_off_get",         # rotor offset device_get
    "mirror_fanout",     # per-session mirrored pings + socket sends
)

# Log-bucketed duration histogram edges, ms: 1us .. ~134s doubling.
HIST_EDGES_MS = tuple(0.001 * 2 ** k for k in range(28))

SERVE_TRACE_GAUGES: dict[str, str] = {
    "swim_serve_phase_ms":
        "Mean per-period serve-path phase time, ms (phase label; the "
        "five ServeHub._period phases)",
    "swim_serve_phase_p99_ms":
        "p99 per-period phase time, ms (phase label; histogram-edge "
        "resolution from the running log-bucketed histogram)",
    "swim_serve_phase_fraction":
        "Phase share of total attributed period time",
    "swim_serve_period_ms":
        "Mean period wall time across traced periods, ms",
    "swim_serve_unattributed_ms":
        "Mean per-period wall time not covered by the five phases, ms "
        "(should be ~0: laps are contiguous)",
}


def coerce(trace: Any) -> "ServeTrace | None":
    """`ServeHub(trace=...)` coercion: None/False off, True -> a fresh
    ServeTrace, a ServeTrace instance passes through."""
    if trace is None or trace is False:
        return None
    if trace is True:
        return ServeTrace()
    if isinstance(trace, ServeTrace):
        return trace
    raise TypeError(f"trace must be None/bool/ServeTrace, got {trace!r}")


class ServeTrace:
    """Bounded period-frame ring + running phase histograms + span ring.

    One instance per hub.  The engine thread drives begin/lap/end; any
    thread may emit finished datagram spans (`emit` is a deque append —
    atomic under the GIL — plus an optional sink forward)."""

    def __init__(self, frame_capacity: int = 1024,
                 span_capacity: int = 8192,
                 sink: TraceSink | None = None):
        if frame_capacity < 1 or span_capacity < 1:
            raise ValueError("servetrace capacities must be >= 1")
        self.sink = sink
        self._frames: collections.deque[dict] = collections.deque(
            maxlen=frame_capacity)
        self._spans: collections.deque[Span] = collections.deque(
            maxlen=span_capacity)
        self._hist = {p: [0] * (len(HIST_EDGES_MS) + 1) for p in PHASES}
        self._sums = {p: 0.0 for p in PHASES}
        self._wall_sum = 0.0
        self._periods = 0
        self._cur: dict | None = None
        self._t_last = 0.0

    # ------------------------------------------------------------ clock

    @staticmethod
    def now() -> float:
        """The shared attribution clock.  time.monotonic, NOT
        perf_counter: the load harness stamps its client-side echo
        windows with time.monotonic, and overlap attribution needs
        both ends on one timebase."""
        return time.monotonic()

    # ---------------------------------------------------- phase timeline

    def begin(self, period: int) -> None:
        t = self.now()
        self._cur = {"period": int(period), "t0": t, "phases": []}
        self._t_last = t

    def lap(self, name: str) -> None:
        """Close the current phase at `name` (contiguous: the next lap
        starts where this one ends)."""
        t = self.now()
        self._cur["phases"].append([name, self._t_last, t])
        self._t_last = t

    def end(self) -> None:
        cur, self._cur = self._cur, None
        if cur is None:
            return
        cur["t1"] = self._t_last
        wall_ms = (cur["t1"] - cur["t0"]) * 1e3
        cur["wall_ms"] = round(wall_ms, 6)
        self._frames.append(cur)
        self._periods += 1
        self._wall_sum += wall_ms
        for name, b, e in cur["phases"]:
            dur_ms = (e - b) * 1e3
            self._sums[name] += dur_ms
            self._hist[name][bisect.bisect_right(HIST_EDGES_MS,
                                                 dur_ms)] += 1

    # ------------------------------------------------------------- spans

    def datagram_span(self, t_start: float, op: int,
                      row: int = -1) -> Span:
        """A `"serve"` span for one datagram: node = session row (-1
        pre-admission), subject = wire opcode, start = frontend receipt."""
        return Span(kind="serve", node=int(row), subject=int(op),
                    start=t_start)

    def emit(self, span: Span) -> None:
        self._spans.append(span)
        if self.sink is not None:
            self.sink.emit(span)

    # ----------------------------------------------------------- outputs

    def frames(self) -> list[dict]:
        """The retained period frames (JSON-ready), oldest first."""
        return [dict(f) for f in self._frames]

    def span_dicts(self) -> list[dict]:
        return [s.to_dict() for s in list(self._spans)]

    def _phase_p99_ms(self, name: str) -> float:
        counts = self._hist[name]
        total = sum(counts)
        if not total:
            return 0.0
        target = 0.99 * total
        seen = 0
        for i, c in enumerate(counts):
            seen += c
            if seen >= target:
                return float(HIST_EDGES_MS[min(i, len(HIST_EDGES_MS) - 1)])
        return float(HIST_EDGES_MS[-1])

    def summary(self) -> dict:
        """Running per-phase stats over every traced period (not just
        the retained ring) — the expo.render_serve_trace input."""
        n = self._periods
        attributed = sum(self._sums.values())
        phases = {}
        for name in PHASES:
            total = self._sums[name]
            phases[name] = {
                "total_ms": round(total, 3),
                "mean_ms": round(total / n, 4) if n else 0.0,
                "p99_ms": round(self._phase_p99_ms(name), 4),
                "fraction": round(total / attributed, 4) if attributed
                else 0.0,
            }
        mean_wall = self._wall_sum / n if n else 0.0
        return {
            "kind": "serve_phase_summary",
            "periods": n,
            "phase_names": list(PHASES),
            "phases": phases,
            "period_ms": {"mean": round(mean_wall, 4),
                          "total": round(self._wall_sum, 3)},
            "unattributed_ms": round(
                max(0.0, (self._wall_sum - attributed) / n) if n else 0.0,
                4),
            "hist_edges_ms": list(HIST_EDGES_MS),
            "hist": {name: list(self._hist[name]) for name in PHASES},
            "spans": len(self._spans),
        }

    def dump_frames(self, path: str, extra: dict | None = None) -> str:
        """Write the frame ring as self-describing JSONL (the
        obs/recorder.py header-line convention, via its shared
        `write_jsonl`)."""
        from swim_tpu.obs.recorder import write_jsonl

        header = dict(extra or {})
        header.update({"kind": "swim_tpu_serve_trace_frames",
                       "version": 1,
                       "phase_names": list(PHASES),
                       "periods": self._periods,
                       "retained": len(self._frames)})
        return write_jsonl(path, header, self.frames())


def gauge_values(summary: dict) -> dict[str, float]:
    """SERVE_TRACE_GAUGES scalar fallbacks from one `summary()` dict
    (per-phase series render with a `phase` label in expo; the scalar
    collapses to the slowest phase, mirroring render_sessions' worst-
    session fallback)."""
    phases = summary.get("phases") or {}
    worst_mean = max((float(p.get("mean_ms", 0.0))
                      for p in phases.values()), default=0.0)
    worst_p99 = max((float(p.get("p99_ms", 0.0))
                     for p in phases.values()), default=0.0)
    worst_frac = max((float(p.get("fraction", 0.0))
                      for p in phases.values()), default=0.0)
    return {
        "swim_serve_phase_ms": worst_mean,
        "swim_serve_phase_p99_ms": worst_p99,
        "swim_serve_phase_fraction": worst_frac,
        "swim_serve_period_ms":
            float((summary.get("period_ms") or {}).get("mean", 0.0)),
        "swim_serve_unattributed_ms":
            float(summary.get("unattributed_ms", 0.0)),
    }
