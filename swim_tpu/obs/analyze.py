"""Streaming analyzers over telemetry artifacts: the paper's metrics.

PR 3's layer records — flight-recorder JSONL (per-period `EngineFrame`
rows, obs/recorder.py) and trace-span JSONL (probe/suspicion episodes,
obs/trace.py) — but nothing interpreted the data.  This module closes
the loop: feed it a dump and it computes the SWIM paper's protocol
quantities offline, with no live run:

  * detection-latency distribution + CDF and the mean vs the paper's
    e/(e−1)-periods first-detection law (the dump header's embedded
    `study` section carries the crashed-subject milestones that
    sim/experiments.py:detection_study records),
  * dissemination (infection-curve) progress from `waves_delivered`,
  * piggyback-budget pressure trend from `sel_rows_saturated` /
    `sel_slots_max` vs the B budget in the header's config snapshot,
  * probe-outcome breakdown, RTT percentiles, and suspicion
    refute/false-positive rates from trace spans,
  * severity-ranked health findings (obs/health.py replayed over the
    recorded rows).

Everything here is host-side post-processing (json + numpy only — no
jax import, so scripts/tpu_watch.py can attach reports cheaply), and
every analyzer emits a small typed summary dict so results are
diffable artifacts.  `swim-tpu observe` renders these reports; the
detection summary is numerically identical to
`sim/runner.py:detection_summary` because both delegate to
`summarize_detection` below.
"""

from __future__ import annotations

import json
import math
from typing import Any, Iterable, Mapping

import numpy as np

from swim_tpu.obs import health as health_mod

NEVER = 2**31 - 1                     # sim/runner.py's not-yet sentinel
E_OVER_E_MINUS_1 = math.e / (math.e - 1)
RECORDER_KIND = "swim_tpu_flight_recorder"
SPAN_KINDS = ("probe", "suspicion", "serve")

# ServeHub._period phase order (obs/servetrace.py PHASES) — kept as a
# literal so this module stays import-light for tpu_watch attachment.
SERVE_PHASES = ("evict_scan", "inject_coalesce", "engine_step",
                "s_off_get", "mirror_fanout")
SERVE_COVERAGE_CONTRACT_PCT = 90.0


# --------------------------------------------------------------- detection

def summarize_detection(crash_step: np.ndarray,
                        milestones: Mapping[str, np.ndarray],
                        false_dead_final: int | None = None) -> dict:
    """Latency distribution per milestone for CRASHED subjects.

    `crash_step[i]` is subject i's crash period; each milestones array
    holds the period the milestone fired (NEVER = not yet).  This is
    the single source of truth for the latency arithmetic —
    sim/runner.py:detection_summary delegates here, so a recorder dump
    re-analyzed offline reproduces the live study summary exactly.
    """
    crash = np.asarray(crash_step, np.int64)
    out: dict[str, Any] = {"crashed": int(crash.size)}
    if not crash.size:
        return out
    for name, arr in milestones.items():
        arr = np.asarray(arr, np.int64)
        lat = arr - crash
        ok = arr != NEVER
        out[f"{name}_detected"] = int(ok.sum())
        if ok.any():
            lat_ok = lat[ok] + 1  # period t event ⇒ latency in (0, t+1]
            out[f"{name}_latency_mean"] = float(lat_ok.mean())
            out[f"{name}_latency_p50"] = float(np.percentile(lat_ok, 50))
            out[f"{name}_latency_p99"] = float(np.percentile(lat_ok, 99))
    if false_dead_final is not None:
        out["false_dead_views_final"] = int(false_dead_final)
    return out


def latency_cdf(crash_step, first_detect, max_points: int = 32) -> list:
    """Detection-latency CDF as `[latency, fraction_detected]` steps
    over crashed subjects (undetected subjects never reach 1.0)."""
    crash = np.asarray(crash_step, np.int64)
    arr = np.asarray(first_detect, np.int64)
    if not crash.size:
        return []
    ok = arr != NEVER
    lat = np.sort(arr[ok] + 1 - crash[ok])
    vals, counts = np.unique(lat, return_counts=True)
    frac = np.cumsum(counts) / crash.size
    pts = [[int(v), round(float(f), 4)] for v, f in zip(vals, frac)]
    if len(pts) > max_points:     # keep ends + even interior subsample
        idx = np.linspace(0, len(pts) - 1, max_points).astype(int)
        pts = [pts[i] for i in idx]
    return pts


def detection_law(crash_step, first_suspect, n_nodes: int | None,
                  probe: str | None = None) -> dict:
    """Mean first-detection latency vs the SWIM paper's geometric law.

    With uniform probing, a crashed member escapes every live prober
    with probability (1 − 1/(N−1))^(N−1) → 1/e, so first detection is
    Geometric(p) with mean → e/(e−1) ≈ 1.582 periods.  `law_applies`
    is False for the rotor probe (deterministic bounded-detection
    regime, deviation R1) — the ratio is still reported, labeled."""
    crash = np.asarray(crash_step, np.int64)
    arr = np.asarray(first_suspect, np.int64)
    ok = arr != NEVER
    out: dict[str, Any] = {
        "e_over_e_minus_1": E_OVER_E_MINUS_1,
        "law_applies": probe in (None, "pull"),
        "samples": int(ok.sum()),
    }
    if probe is not None:
        out["probe"] = probe
    if n_nodes and n_nodes > 2:
        p = 1.0 - (1.0 - 1.0 / (n_nodes - 1)) ** (n_nodes - 1)
        out["expected_mean"] = 1.0 / p
    else:
        out["expected_mean"] = E_OVER_E_MINUS_1
    if ok.any():
        mean = float((arr[ok] + 1 - crash[ok]).mean())
        out["latency_mean"] = mean
        out["mean_vs_law"] = mean / out["expected_mean"]
    return out


# ----------------------------------------------------- frame-dump analyzers

class DisseminationAnalyzer:
    """Infection-curve progress from `waves_delivered`."""

    def __init__(self):
        self.deliveries: list[int] = []

    def feed(self, row: Mapping[str, Any]) -> None:
        self.deliveries.append(int(row.get("waves_delivered", 0)))

    def summary(self) -> dict:
        d = np.asarray(self.deliveries, np.int64)
        out = {"periods": int(d.size), "delivered_total": int(d.sum())}
        if d.size and d.sum():
            cum = np.cumsum(d)
            frac = cum / cum[-1]
            out["delivered_mean"] = float(d.mean())
            out["delivered_peak"] = int(d.max())
            out["peak_period"] = int(d.argmax())
            for q in (0.5, 0.9):
                out[f"periods_to_{int(q * 100)}pct"] = int(
                    np.argmax(frac >= q))
            # a healthy infection curve front-loads: its last quarter
            # should carry little of the total traffic
            tail = d[3 * d.size // 4:]
            out["tail_quarter_share"] = round(
                float(tail.sum() / d.sum()), 4)
        return out


class PiggybackAnalyzer:
    """Budget-pressure trend from the selection statistics vs B."""

    def __init__(self, budget: int | None = None):
        self.budget = budget
        self.saturated: list[int] = []
        self.slots_max: list[int] = []
        self.selected: list[int] = []

    def feed(self, row: Mapping[str, Any]) -> None:
        self.saturated.append(int(row.get("sel_rows_saturated", 0)))
        self.slots_max.append(int(row.get("sel_slots_max", 0)))
        self.selected.append(int(row.get("sel_slots_selected", 0)))

    @staticmethod
    def _trend(arr: np.ndarray) -> str:
        if arr.size < 4:
            return "flat"
        half = arr.size // 2
        a, b = float(arr[:half].mean()), float(arr[half:].mean())
        ref = max(abs(a), 1.0)
        if b - a > 0.25 * ref:
            return "rising"
        if a - b > 0.25 * ref:
            return "falling"
        return "flat"

    def summary(self) -> dict:
        sat = np.asarray(self.saturated, np.int64)
        smax = np.asarray(self.slots_max, np.int64)
        sel = np.asarray(self.selected, np.int64)
        out: dict[str, Any] = {
            "saturated_peak": int(sat.max()) if sat.size else 0,
            "saturated_mean": float(sat.mean()) if sat.size else 0.0,
            "saturation_trend": self._trend(sat),
            "slots_max_peak": int(smax.max()) if smax.size else 0,
            "slots_selected_total": int(sel.sum()),
        }
        if self.budget:
            out["budget"] = int(self.budget)
            out["headroom_slots"] = int(self.budget) - out["slots_max_peak"]
        return out


class ProbeFrameAnalyzer:
    """Probe-failure series from the engine tap."""

    def __init__(self):
        self.failed: list[int] = []

    def feed(self, row: Mapping[str, Any]) -> None:
        self.failed.append(int(row.get("probes_failed", 0)))

    def summary(self) -> dict:
        f = np.asarray(self.failed, np.int64)
        return {
            "failed_total": int(f.sum()),
            "failed_peak": int(f.max()) if f.size else 0,
            "failing_periods": int((f > 0).sum()),
            "first_failure_period": (int(np.argmax(f > 0))
                                     if (f > 0).any() else None),
        }


# ------------------------------------------------------------ span analyzer

def analyze_spans(rows: Iterable[Mapping[str, Any]]) -> dict:
    """Per-probe outcome breakdown + suspicion analytics from trace
    spans (obs/trace.py JSONL schema)."""
    probe_outcomes: dict[str, int] = {}
    events: dict[str, int] = {}
    rtts: list[float] = []
    susp_outcomes: dict[str, int] = {}
    susp_durations: list[float] = []
    serve_outcomes: dict[str, int] = {}
    serve_queue_waits: list[float] = []
    serve_flush_delays: list[float] = []
    serve_echo_durs: list[float] = []
    indirect_rescues = 0
    n = 0
    for r in rows:
        n += 1
        dur = (r["end"] - r["start"]
               if r.get("end") is not None else None)
        for _, name in r.get("events", ()):
            events[name] = events.get(name, 0) + 1
        if r.get("kind") == "serve":
            out = r.get("outcome") or "open"
            serve_outcomes[out] = serve_outcomes.get(out, 0) + 1
            marks = {name: t for t, name in r.get("events", ())}
            if "queued" in marks and "handled" in marks:
                serve_queue_waits.append(marks["handled"]
                                         - marks["queued"])
            if "queued" in marks and "flush" in marks:
                serve_flush_delays.append(marks["flush"]
                                          - marks["queued"])
            if out == "echo_reply" and dur is not None:
                serve_echo_durs.append(float(dur))
            continue
        if r.get("kind") == "probe":
            out = r.get("outcome") or "open"
            probe_outcomes[out] = probe_outcomes.get(out, 0) + 1
            if out == "ack" and dur is not None:
                rtts.append(float(dur))
            if out == "ack" and any(name == "ping-req"
                                    for _, name in r.get("events", ())):
                indirect_rescues += 1
        elif r.get("kind") == "suspicion":
            out = r.get("outcome") or "open"
            susp_outcomes[out] = susp_outcomes.get(out, 0) + 1
            if dur is not None:
                susp_durations.append(float(dur))
    report: dict[str, Any] = {"spans": n}
    probes = sum(probe_outcomes.values())
    if probes:
        report["probes"] = {
            "total": probes,
            "outcomes": dict(sorted(probe_outcomes.items())),
            "failure_rate": round(
                probe_outcomes.get("fail", 0) / probes, 4),
            "indirect_rescues": indirect_rescues,
            "events": dict(sorted(events.items())),
        }
        if rtts:
            arr = np.asarray(rtts)
            report["probes"]["rtt_mean_s"] = float(arr.mean())
            report["probes"]["rtt_p99_s"] = float(np.percentile(arr, 99))
    susps = sum(susp_outcomes.values())
    if susps:
        refuted = susp_outcomes.get("refuted", 0)
        report["suspicions"] = {
            "total": susps,
            "outcomes": dict(sorted(susp_outcomes.items())),
            # every refuted suspicion was a false positive caught in
            # time — the paper's suspicion-mechanism claim, measured
            "false_positive_rate": round(refuted / susps, 4),
        }
        if susp_durations:
            arr = np.asarray(susp_durations)
            report["suspicions"]["duration_mean_s"] = float(arr.mean())
    serves = sum(serve_outcomes.values())
    if serves:
        serve: dict[str, Any] = {
            "total": serves,
            "outcomes": dict(sorted(serve_outcomes.items())),
        }
        # stage separations — the span schema's whole point: queue wait
        # (bounded work queue) and coalesce-batching delay (gossip
        # waiting for its ExtOriginations flush period) vs device time
        for key, vals in (("queue_wait", serve_queue_waits),
                          ("flush_delay", serve_flush_delays),
                          ("echo", serve_echo_durs)):
            if vals:
                arr = np.asarray(vals) * 1e3
                serve[f"{key}_mean_ms"] = round(float(arr.mean()), 4)
                serve[f"{key}_p99_ms"] = round(
                    float(np.percentile(arr, 99)), 4)
        report["serve"] = serve
    return report


# --------------------------------------------------------- serve attribution

def summarize_serve(frames: Iterable[Mapping[str, Any]],
                    echo_windows: Iterable[Iterable[float]],
                    phase_summary: Mapping[str, Any] | None = None,
                    contract_pct: float = SERVE_COVERAGE_CONTRACT_PCT,
                    ) -> dict:
    """Decompose the measured echo-RTT tail into serve-path phases.

    `frames` are obs/servetrace.py period frames — absolute
    `[name, t_begin, t_end]` phase intervals on the shared monotonic
    clock.  `echo_windows` are the load harness's CLIENT-side
    `[t_send, t_recv]` stamps per echo sample, same clock (loopback,
    one host).  A tail echo is slow because the frontend drain sat
    behind whatever the engine thread was doing, so the overlap of its
    wall window with the phase intervals IS the attribution — measured,
    not modeled.  The p99 tail (samples at/above the p99 RTT) must be
    >=`contract_pct` covered by named phases or the report says
    `attributed: false` and the residual stays `unattributed` —
    never silently re-binned.
    """
    frames = list(frames)
    windows = [(float(w[0]), float(w[1])) for w in echo_windows]
    rtts_ms = np.asarray([(e - b) * 1e3 for b, e in windows], np.float64)
    report: dict[str, Any] = {
        "kind": "serve_trace",
        "periods": len(frames),
        "phase_names": list(SERVE_PHASES),
        "contract_pct": float(contract_pct),
    }
    if phase_summary is not None:
        report["phases"] = dict(phase_summary.get("phases") or {})
        report["period_ms"] = dict(phase_summary.get("period_ms") or {})
    if not len(rtts_ms) or not frames:
        report.update({"echo": {"samples": int(len(rtts_ms))},
                       "coverage_pct": 0.0, "attributed": False,
                       "reason": "no echo windows or no traced frames"})
        return report
    p50, p99, p999 = (float(np.percentile(rtts_ms, q))
                      for q in (50.0, 99.0, 99.9))
    report["echo"] = {"samples": int(len(rtts_ms)),
                      "p50_ms": round(p50, 3), "p99_ms": round(p99, 3),
                      "p999_ms": round(p999, 3)}
    tail = [(b, e) for (b, e) in windows if (e - b) * 1e3 >= p99]
    intervals = [(name, float(pb), float(pe))
                 for f in frames for name, pb, pe in f.get("phases", ())]
    per_phase = {name: 0.0 for name in SERVE_PHASES}
    tail_wall = 0.0
    for b, e in tail:
        tail_wall += e - b
        for name, pb, pe in intervals:
            ov = min(e, pe) - max(b, pb)
            if ov > 0.0:
                per_phase[name] = per_phase.get(name, 0.0) + ov
    n_tail = len(tail)
    mean_tail_ms = tail_wall / n_tail * 1e3
    decomp = {name: round(per_phase[name] / n_tail * 1e3, 4)
              for name in per_phase}
    attributed_ms = sum(decomp.values())
    decomp["unattributed"] = round(
        max(0.0, mean_tail_ms - attributed_ms), 4)
    coverage = (100.0 * attributed_ms / mean_tail_ms
                if mean_tail_ms > 0 else 0.0)
    report.update({
        "tail": {"spans": n_tail, "threshold_ms": round(p99, 3),
                 "mean_ms": round(mean_tail_ms, 3)},
        "p99_attribution_ms": decomp,
        "unattributed_ms": decomp["unattributed"],
        "coverage_pct": round(min(coverage, 100.0), 2),
        "attributed": coverage >= contract_pct,
    })
    return report


# ------------------------------------------------------------- entry points

def read_jsonl(path: str) -> list[dict]:
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def sniff(path: str) -> str:
    """`"recorder"` | `"spans"` by the first JSONL line's shape."""
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            first = json.loads(line)
            if first.get("kind") == RECORDER_KIND:
                return "recorder"
            if first.get("kind") in SPAN_KINDS:
                return "spans"
            break
    raise ValueError(f"{path}: neither a flight-recorder dump nor a "
                     "trace-span JSONL")


def analyze_frames(header: Mapping[str, Any],
                   rows: Iterable[Mapping[str, Any]],
                   window: int = 16) -> dict:
    """Analyzer pass over recorder rows: paper metrics + replayed
    health findings.  `header` is the dump's self-describing first
    line (config snapshot, optional embedded study section)."""
    cfg = header.get("cfg") or {}
    n = cfg.get("n_nodes")
    monitor = health_mod.HealthMonitor(window=window, n_nodes=n)
    dis = DisseminationAnalyzer()
    pig = PiggybackAnalyzer(budget=cfg.get("max_piggyback"))
    prb = ProbeFrameAnalyzer()
    periods = 0
    for row in rows:
        periods += 1
        for a in (dis, pig, prb):
            a.feed(row)
        monitor.observe(int(row.get("period", periods - 1)), row)
    report: dict[str, Any] = {
        "kind": "flight_recorder",
        "reason": header.get("reason"),
        "periods": periods,
        "dissemination": dis.summary(),
        "piggyback": pig.summary(),
        "probes": prb.summary(),
        "health": monitor.summary(),
    }
    if n:
        report["n_nodes"] = n
    study = header.get("study")
    if study:
        crash = np.asarray(study["crash_step"], np.int64)
        # milestone key names match runner.detection_summary's output
        # keys (suspect_latency_mean, ...) — byte-identical summaries
        milestones = {name: np.asarray(study[src], np.int64)
                      for name, src in (("suspect", "first_suspect"),
                                        ("dead_view", "first_dead_view"),
                                        ("disseminated", "disseminated"))
                      if src in study}
        report["detection"] = summarize_detection(
            crash, milestones, study.get("false_dead_views_final"))
        if "suspect" in milestones:
            report["detection_law"] = detection_law(
                crash, milestones["suspect"], study.get("n", n),
                study.get("probe", cfg.get("ring_probe")))
            report["detection_cdf"] = latency_cdf(
                crash, milestones["suspect"])
    return report


def analyze(path: str, window: int = 16) -> dict:
    """Dispatch on file shape; returns one typed report dict."""
    kind = sniff(path)
    rows = read_jsonl(path)
    if kind == "recorder":
        return analyze_frames(rows[0], rows[1:], window=window)
    report = analyze_spans(rows)
    report["kind"] = "trace_spans"
    return report


def analyze_paths(paths: Iterable[str], window: int = 16) -> dict:
    """Merge reports for a dump + spans pair (or any mix): recorder
    reports land under `"engine"`, span reports under `"nodes"`."""
    merged: dict[str, Any] = {}
    for path in paths:
        report = analyze(path, window=window)
        key = ("engine" if report["kind"] == "flight_recorder"
               else "nodes")
        merged.setdefault(key, {})[path] = report
    # single-file calls stay flat for convenience
    flat: dict[str, Any] = {}
    for group in merged.values():
        if len(group) == 1 and len(merged) == 1:
            return next(iter(group.values()))
    return merged


def error_findings(report: Mapping[str, Any]) -> list[dict]:
    """Every error-severity finding in a (possibly merged) report —
    what scripts/run_suite.py gates CI on."""
    out: list[dict] = []

    def walk(node):
        if isinstance(node, Mapping):
            for f in (node.get("health") or {}).get("findings", ()):
                if f.get("severity") == "error":
                    out.append(f)
            for k, v in node.items():
                if k != "health":
                    walk(v)

    walk(report)
    return out


# ---------------------------------------------------------------- rendering

def _fmt_val(v: Any) -> str:
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def render_report(report: Mapping[str, Any], title: str = "") -> str:
    """Human-readable terminal view of an analyzer report."""
    lines: list[str] = []
    if title:
        lines.append(f"== {title} ==")

    def section(name, d, indent="  "):
        if not d:
            return
        lines.append(f"{name}:")
        for k, v in d.items():
            if isinstance(v, Mapping):
                lines.append(f"{indent}{k}: " + ", ".join(
                    f"{kk}={_fmt_val(vv)}" for kk, vv in v.items()))
            elif isinstance(v, list):
                lines.append(f"{indent}{k}: {v}")
            else:
                lines.append(f"{indent}{k}: {_fmt_val(v)}")

    if report.get("kind") == "flight_recorder":
        head = f"flight recorder · {report.get('periods', 0)} periods"
        if report.get("n_nodes"):
            head += f" · n={report['n_nodes']}"
        if report.get("reason"):
            head += f" · reason={report['reason']}"
        lines.append(head)
        for key in ("detection", "detection_law", "dissemination",
                    "piggyback", "probes"):
            section(key, report.get(key))
        if report.get("detection_cdf"):
            pts = report["detection_cdf"]
            lines.append("detection_cdf (latency→frac): " + " ".join(
                f"{p[0]}:{p[1]:.2f}" for p in pts[:12]))
        health = report.get("health") or {}
        lines.append(f"health: {health.get('worst', 'ok')}")
        for f in health.get("findings", ()):
            lines.append(f"  [{f['severity']}] {f['rule']}: "
                         f"{f['message']}")
    elif report.get("kind") == "trace_spans":
        lines.append(f"trace spans · {report.get('spans', 0)} spans")
        section("probes", report.get("probes"))
        section("suspicions", report.get("suspicions"))
        section("serve", report.get("serve"))
    elif report.get("kind") == "serve_trace":
        # two shapes share the kind: summarize_serve's flat report and
        # serve/load.run_trace's payload, which nests it under
        # "attribution" — render from whichever level carries it
        att = report.get("attribution") or report
        head = (f"serve trace · {report.get('periods', 0)} periods · "
                f"{(att.get('echo') or {}).get('samples', 0)} echo "
                f"samples")
        lines.append(head)
        section("echo", att.get("echo"))
        section("tail", att.get("tail"))
        decomp = att.get("p99_attribution_ms") or {}
        if decomp:
            lines.append("p99 attribution (ms):")
            for name, ms in decomp.items():
                lines.append(f"  {name}: {_fmt_val(ms)}")
        section("period_ms", att.get("period_ms"))
        for key in ("coverage_pct", "contract_pct"):
            if key in att:
                lines.append(f"{key}: {_fmt_val(att[key])}")
        ok = att.get("attributed")
        lines.append("attribution: "
                     + ("ok (>= contract)" if ok else "UNATTRIBUTED"))
        if att.get("reason"):
            lines.append(f"  reason: {att['reason']}")
    else:   # merged multi-file report
        for group, sub in report.items():
            for path, rep in sub.items():
                lines.append(render_report(rep, title=f"{group}: {path}"))
    return "\n".join(lines)
