"""On-device engine telemetry: the per-period EngineFrame tap.

The engines' `step` functions accept an optional `tap` dict.  When the
caller passes one (`cfg.telemetry` decides where the engines are driven
by a runner), the step writes replicated i32 scalars into it — computed
through the same `ops` seam as the protocol (`ops.gsum` / `ops.gmax`),
so the sharded twin produces the SAME frame values as the single-program
engine.  When `tap` is None (the default) the traced program is
unchanged, which is what makes the telemetry-on/off bitwise-parity pin
structural rather than lucky.

Frame fields (all i32, per period):

  sel_slots_selected  valid piggyback slots selected across all senders
                      this period (the B-budget spend)
  sel_rows_saturated  senders whose selection used the FULL B budget —
                      saturation here means the compact wire's bounded
                      [S, B] payload is the binding constraint
  sel_slots_max       max per-sender valid-slot count (headroom vs B,
                      and vs the u8/u16 slot-index packing of the
                      compact wire: indices stay < ww*32 by geometry)
  win_occupancy       transmissible candidates at selection time (ring:
                      set bits in the eligible sel window; rumor:
                      eligible rumors; dense: pending retransmit
                      entries)
  waves_delivered     messages delivered across every wave this period
  probes_failed       probes with neither direct nor relayed ack
  overflow            cumulative origination overflow (post-step state)
  index_overflow      cumulative view-index overflow (ring engines)
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp


class EngineFrame(NamedTuple):
    """One period's telemetry counters (i32 scalars; i32[T] when stacked)."""

    sel_slots_selected: jax.Array
    sel_rows_saturated: jax.Array
    sel_slots_max: jax.Array
    win_occupancy: jax.Array
    waves_delivered: jax.Array
    probes_failed: jax.Array
    overflow: jax.Array
    index_overflow: jax.Array


def empty_frame() -> EngineFrame:
    return EngineFrame(*(jnp.int32(0) for _ in EngineFrame._fields))


def frame_from_tap(tap: dict) -> EngineFrame:
    """Build a frame from whatever keys the engine filled; rest are 0."""
    return EngineFrame(*(jnp.asarray(tap.get(name, 0), jnp.int32)
                         for name in EngineFrame._fields))


class RecordedRun(NamedTuple):
    """A telemetry run's result: final state + stacked EngineFrame[T].

    `.step` proxies the state's period counter so bench.py's `_time_run`
    execution-proof (end_step - start_step == periods) applies unchanged
    to the telemetry arm.
    """

    state: Any
    frames: EngineFrame

    @property
    def step(self):
        return self.state.step


@functools.partial(jax.jit, static_argnums=(0, 4))
def recorded_ring_run(cfg, state, plan, root_key: jax.Array,
                      periods: int) -> RecordedRun:
    """ring.run with the telemetry tap: one fused scan, frames as ys.

    The frames are scan OUTPUTS — materialized whether or not the caller
    reads them, so the bench overhead arm measures the real collector
    cost instead of a dead-code-eliminated no-op.
    """
    from swim_tpu.models import ring

    def body(st, _):
        tap: dict = {}
        st = ring.step(cfg, st, plan,
                       ring.draw_period_ring(root_key, st.step, cfg),
                       tap=tap)
        return st, frame_from_tap(tap)

    state, frames = jax.lax.scan(body, state, None, length=periods)
    return RecordedRun(state, frames)
